"""Sharded checkpoint round-trip + elastic kill-and-resume (VERDICT item 10).

Reference capability: fleet sharded checkpoints +
distributed/fleet/elastic/manager.py auto-resume.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.elastic import ElasticManager, latest_checkpoint
from paddle_tpu.io.checkpoint import (
    CheckpointManager, abstract_state, load_checkpoint, save_checkpoint,
)


@pytest.fixture
def mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("dp", "tp"))


def _sharded_state(mesh):
    rng = np.random.RandomState(0)
    w = jax.device_put(jnp.asarray(rng.randn(8, 16).astype(np.float32)),
                       NamedSharding(mesh, P("dp", "tp")))
    b = jax.device_put(jnp.asarray(rng.randn(16).astype(np.float32)),
                       NamedSharding(mesh, P("tp")))
    return {"params": {"w": w, "b": b}, "step": jnp.int32(7)}


def test_sharded_roundtrip(tmp_path, mesh):
    state = _sharded_state(mesh)
    save_checkpoint(str(tmp_path / "ckpt"), 0, state)
    restored = load_checkpoint(str(tmp_path / "ckpt"), 0,
                               target=abstract_state(state))
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    # restored arrays carry the original NamedSharding: 4x2 shards of (2, 8)
    shard_shapes = {s.data.shape for s in restored["params"]["w"].addressable_shards}
    assert shard_shapes == {(2, 8)}
    assert restored["params"]["b"].sharding.is_equivalent_to(
        state["params"]["b"].sharding, 1)


def test_restore_with_different_sharding(tmp_path, mesh):
    """Resharding on restore: save dp-sharded, restore tp-sharded."""
    state = _sharded_state(mesh)
    save_checkpoint(str(tmp_path / "c"), 3, state)
    target = abstract_state(state)
    target["params"]["w"] = jax.ShapeDtypeStruct(
        (8, 16), jnp.float32, sharding=NamedSharding(mesh, P(None, "tp")))
    restored = load_checkpoint(str(tmp_path / "c"), target=target)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert {s.data.shape for s in
            restored["params"]["w"].addressable_shards} == {(8, 8)}


def test_manager_async_retention(tmp_path, mesh):
    state = _sharded_state(mesh)
    with CheckpointManager(str(tmp_path / "m"), max_to_keep=2,
                           async_save=True) as m:
        for step in range(5):
            state = {**state, "step": jnp.int32(step)}
            assert m.save(step, state, force=True)
        m.wait()
        assert m.latest_step() == 4
        assert m.all_steps() == [3, 4]  # max_to_keep pruned the rest
        restored = m.restore(target=abstract_state(state))
    assert int(restored["step"]) == 4


def test_elastic_kill_and_resume(tmp_path, mesh):
    """Train, 'die' mid-run, come back, resume from newest checkpoint."""
    ckpt_dir = str(tmp_path / "elastic")

    def run(until_step, resume=True):
        """One trainer lifetime; returns (last_step, final_w)."""
        state = _sharded_state(mesh)
        m = CheckpointManager(ckpt_dir, max_to_keep=3, async_save=False)
        em = ElasticManager(ckpt_dir, timeout=9999, save_interval=2,
                            save_fn=lambda s: m.save(s, state, force=True))
        holder = {}

        def restore(step):
            holder.update(m.restore(step, target=abstract_state(state)))

        start = em.resume(restore) if resume else 0
        if holder:
            state = {"params": holder["params"], "step": holder["step"]}
        w = state["params"]["w"]
        step = start
        for step in range(start, until_step):
            w = w + 1.0  # "training"
            state = {"params": {"w": w, "b": state["params"]["b"]},
                     "step": jnp.int32(step)}
            em.tick(step)
        m.wait()
        m.close()
        return step, state["params"]["w"]

    # first lifetime: reaches step 5, last complete checkpoint at step 4
    run(6, resume=False)
    assert latest_checkpoint(ckpt_dir) == 4
    # second lifetime resumes at 5 and continues to 9
    last, w = run(10)
    assert last == 9
    # w was checkpointed at step 4 (after 5 increments), then 5 more: 10
    np.testing.assert_allclose(np.asarray(w)[0, 0],
                               np.asarray(_sharded_state(mesh)["params"]["w"])[0, 0] + 10)


def test_elastic_watchdog_detects_stall(tmp_path):
    em = ElasticManager(str(tmp_path / "wd"), timeout=0.2)
    em.tick(0)
    stalls = []
    em.start_watchdog(on_stall=stalls.append, poll=0.1)
    import time

    time.sleep(0.8)
    em.stop()
    assert em.stalled and stalls and stalls[0]["step"] == 0


def test_tick_check_and_reserve_is_atomic(tmp_path):
    """Regression (threadlint CL007/CL001): the monotonicity check and
    the `_last_step` write are one atomic step under the manager's
    state lock, so overlapping increasing sequences from concurrent
    tickers can never leave the recorded progress below the global max
    (a stale tick racing a fresh one used to be able to re-publish the
    older step after its check passed)."""
    import threading
    import warnings as _warnings

    em = ElasticManager(str(tmp_path / "cc"), timeout=9999)
    n, offsets = 80, (0, 3, 7)

    def run(base):
        with _warnings.catch_warnings():
            # regressing ticks are EXPECTED here (overlapping
            # sequences); each returns False and warns by contract
            _warnings.simplefilter("ignore")
            for i in range(n):
                em.tick(base + i)

    threads = [threading.Thread(target=run, args=(k,)) for k in offsets]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert em._last_step == max(offsets) + n - 1
    # the PUBLISHED view must not regress either: a tick superseded
    # while waiting to publish drops its stale publication, so the
    # heartbeat file always ends at the global max
    import json as _json

    hb = _json.load(open(em._hb_path))
    assert hb["step"] == max(offsets) + n - 1, hb

    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")
        assert em.tick(0) is False          # stale: state untouched
    assert em._last_step == max(offsets) + n - 1


def test_tick_reserves_under_the_state_lock(tmp_path):
    """The tick fast path must consult the state lock (not a racy bare
    read) before publishing progress."""
    em = ElasticManager(str(tmp_path / "lk"), timeout=9999)
    acquired = []

    class _ProbeLock:
        def __enter__(self):
            acquired.append(True)

        def __exit__(self, *exc):
            return False

    em._state_lock = _ProbeLock()
    assert em.tick(1) is True
    assert acquired, "tick() must check-and-reserve under _state_lock"
    assert em._last_step == 1
