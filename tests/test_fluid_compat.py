"""paddle.fluid compat shim: reference-era scripts run unmodified
(round-3 verdict #7).

The two tests below are written in the idiom of the reference's own
book/tutorial MNIST scripts (fluid/__init__.py era): fluid.layers.* +
Executor for static, fluid.dygraph.guard/to_variable for eager.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def _mnist_batch(rng, n=32):
    img = rng.randn(n, 1, 28, 28).astype("float32")
    label = rng.randint(0, 10, (n, 1)).astype("int64")
    return img, label


def test_fluid_static_mnist_script():
    """The era's static MNIST: layers.data -> fc(softmax) ->
    cross_entropy -> SGD.minimize -> Executor.run feed/fetch loop."""
    paddle.enable_static()
    try:
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                    dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            hidden = fluid.layers.fc(img, size=64, activation="relu")
            prediction = fluid.layers.fc(hidden, size=10,
                                         activation="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(input=prediction, label=label))
            acc = fluid.layers.accuracy(input=prediction, label=label)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        x, y = _mnist_batch(rng)  # one batch: loss must drop on it
        losses = []
        for _ in range(8):
            lv, av = exe.run(main, feed={"img": x, "label": y},
                             fetch_list=[loss, acc])
            losses.append(float(lv))
        assert losses[-1] < losses[0], losses
        assert np.isfinite(losses).all()
    finally:
        paddle.disable_static()


def test_fluid_static_save_load_params(tmp_path):
    paddle.enable_static()
    try:
        main = fluid.Program()
        with fluid.program_guard(main):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.fc(x, size=4)
            loss = fluid.layers.reduce_mean(y * y)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        exe.run(main, feed={"x": rng.randn(4, 8).astype("float32")},
                fetch_list=[loss])
        fluid.io.save_params(exe, str(tmp_path), main_program=main)
        before = {p.name: np.asarray(p._value).copy()
                  for p in main.all_parameters()}
        for p in main.all_parameters():  # clobber
            p._value = p._value * 0.0
        fluid.io.load_params(exe, str(tmp_path), main_program=main)
        for p in main.all_parameters():
            np.testing.assert_array_equal(np.asarray(p._value),
                                          before[p.name])
    finally:
        paddle.disable_static()


def test_fluid_dygraph_mnist_script():
    """The era's dygraph MNIST: guard + to_variable + dygraph layer
    classes (explicit input dims) + AdamOptimizer(parameter_list=)."""
    with fluid.dygraph.guard():
        paddle.seed(0)

        class MNIST(fluid.dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.conv = fluid.dygraph.Conv2D(1, 8, 3, padding=1,
                                                 act="relu")
                self.pool = fluid.dygraph.Pool2D(2, "max", 2)
                self.fc = fluid.dygraph.Linear(8 * 14 * 14, 10,
                                               act="softmax")

            def forward(self, x):
                x = self.pool(self.conv(x))
                return self.fc(fluid.layers.reshape(x, [x.shape[0], -1]))

        model = MNIST()
        opt = fluid.optimizer.AdamOptimizer(
            learning_rate=1e-3, parameter_list=model.parameters())
        rng = np.random.RandomState(1)
        x, y = _mnist_batch(rng)  # one batch: loss must drop on it
        losses = []
        for _ in range(6):
            img = fluid.dygraph.to_variable(x)
            label = fluid.dygraph.to_variable(y)
            prediction = model(img)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(prediction, label))
            loss.backward()
            opt.minimize(loss)
            model.clear_gradients()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses


def test_fluid_dygraph_save_load(tmp_path):
    with fluid.dygraph.guard():
        paddle.seed(0)
        lin = fluid.dygraph.Linear(4, 4)
        path = str(tmp_path / "model")
        fluid.dygraph.save_dygraph(lin.state_dict(), path)
        params, opt_state = fluid.dygraph.load_dygraph(path)
        assert params is not None and opt_state is None
        lin2 = fluid.dygraph.Linear(4, 4)
        lin2.set_state_dict(params)
        np.testing.assert_array_equal(lin2.weight.numpy(),
                                      lin.weight.numpy())


def test_fluid_layers_misc_ops():
    with fluid.dygraph.guard():
        a = fluid.layers.ones([2, 3])
        b = fluid.layers.fill_constant([2, 3], "float32", 2.0)
        c = fluid.layers.elementwise_add(a, b, act="relu")
        np.testing.assert_array_equal(c.numpy(), np.full((2, 3), 3.0))
        m = fluid.layers.matmul(a, fluid.layers.transpose(b, [1, 0]))
        assert m.shape == [2, 2]
        s = fluid.layers.reduce_sum(m)
        assert float(s) == 2.0 * 3 * 2 * 2
        lo = fluid.layers.softmax_with_cross_entropy(
            fluid.layers.ones([4, 10]),
            fluid.dygraph.to_variable(np.zeros((4, 1), np.int64)))
        assert lo.shape[0] == 4
        v, idx = fluid.layers.topk(b, 2)
        assert v.shape == [2, 2]
        z = fluid.layers.cast(fluid.layers.zeros([2]), "int64")
        assert "int" in str(z.dtype)


def test_fluid_dygraph_guard_restores_static():
    paddle.enable_static()
    try:
        with fluid.dygraph.guard():
            assert paddle.in_dynamic_mode()
            t = fluid.dygraph.to_variable(np.ones(3, np.float32))
            assert float(fluid.layers.reduce_sum(t)) == 3.0
        assert not paddle.in_dynamic_mode()  # guard restored static
    finally:
        paddle.disable_static()


def test_fluid_core_and_places():
    assert not fluid.core.is_compiled_with_cuda()
    assert fluid.core.get_cuda_device_count() == 0
    assert fluid.CPUPlace is not None
    assert fluid.initializer.Xavier is not None
    assert fluid.regularizer.L2Decay(1e-4).coeff == pytest.approx(1e-4)
