"""threadlint: static concurrency analyzer on the shared staticlib core.

Locks the ISSUE-8 acceptance surface:
  * fixture detections for all 7 rules (CL001–CL007);
  * precision controls that must NOT fire (lock-held mutation, waived
    site, single-thread-only state, Condition.wait on the held lock,
    tmp + os.replace atomic writes);
  * the CLI exit-code contract: `python -m tools.threadlint paddle_tpu`
    exits 0 on the shipped tree and nonzero on a synthetic fixture
    mutating shared module state from a thread target without its lock;
  * the staticlib re-home regression: tracelint still analyzes the tree
    to a BYTE-IDENTICAL baseline;
  * the concurrency fixes this PR shipped stay clean under the analyzer.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.staticlib import baseline as slib_baseline  # noqa: E402
from tools.threadlint import analyzer  # noqa: E402


# ---------------------------------------------------------------------------
# fixture code exercising every rule

FIXTURE = textwrap.dedent('''
    import atexit
    import os
    import subprocess
    import threading
    import time

    _shared = {"n": 0}
    _flag = None
    _guarded = {"n": 0}
    _waived = {"n": 0}
    _thread_only = []
    _lock_a = threading.Lock()
    _lock_b = threading.Lock()
    _glock = threading.Lock()
    _cond = threading.Condition()


    def worker():
        _ = _flag
        _shared["n"] += 1          # CL001: thread-side write, no lock


    def reader_api():
        return _shared["n"]        # sync-side read: the state is shared


    def lazy_init():
        global _flag
        if _flag is None:          # CL007: check ...
            _flag = object()       # ... then act, no lock across
        return _flag


    def guarded_worker():
        with _glock:
            _guarded["n"] += 1     # control: mutation under the lock


    def guarded_reader():
        with _glock:
            return _guarded["n"]


    def waived_worker():
        _waived["n"] += 1  # threadlint: ok[CL001] reviewed fixture waiver


    def waived_reader():
        return _waived["n"]


    def lonely_worker():
        _thread_only.append(1)     # control: single-context state


    def launch_all():
        threading.Thread(target=worker).start()
        threading.Thread(target=guarded_worker).start()
        threading.Thread(target=waived_worker).start()
        threading.Thread(target=lonely_worker).start()


    def start_then_spawn():
        t = threading.Thread(target=worker)
        t.start()
        subprocess.run(["true"])   # CL004: spawn after a live thread


    def ab_path():
        with _lock_a:
            with _lock_b:          # order A -> B
                pass


    def ba_path():
        with _lock_b:
            with _lock_a:          # order B -> A: CL002 inversion
                pass


    def sleepy():
        with _lock_a:
            time.sleep(0.1)        # CL003: blocking under a lock


    def cond_waiter():
        with _cond:
            _cond.wait()           # control: wait() RELEASES the held cond


    def publish_status(root):
        with open(root + "/store/status.json", "w") as f:  # CL005
            f.write("{}")


    def publish_atomic(root):
        tmp = root + "/store/status.json.tmp"
        with open(tmp, "w") as f:  # control: tmp + os.replace is atomic
            f.write("{}")
        os.replace(tmp, root + "/store/status.json")


    def drainer():
        with open("/tmp/threadlint_fixture.log", "a") as f:
            f.write("bye")


    def spawn_drainer():
        threading.Thread(target=drainer, daemon=True).start()  # CL006


    def _at_exit():
        t = threading.Thread(target=drainer)
        t.start()
        t.join()                   # CL006: atexit joins with no timeout


    atexit.register(_at_exit)
''')


@pytest.fixture(scope="module")
def fixture_findings(tmp_path_factory):
    d = tmp_path_factory.mktemp("threadlint_fixture")
    p = d / "fixture_threads.py"
    p.write_text(FIXTURE)
    findings, errors = analyzer.analyze_paths([str(p)])
    assert not errors
    return findings


def _hits(findings, rule, where=""):
    return [f for f in findings
            if f.rule == rule and where in f.func and not f.suppressed]


# -- detections (all 7 rules) -------------------------------------------------

def test_all_seven_rules_detect_on_fixture(fixture_findings):
    rules = {f.rule for f in fixture_findings if not f.suppressed}
    assert {"unguarded-shared-mutation", "lock-order-inversion",
            "blocking-under-lock", "thread-before-fork",
            "non-atomic-shared-write", "shutdown-ordering",
            "check-then-act"} <= rules, rules


def test_cl001_unguarded_shared_mutation(fixture_findings):
    hits = _hits(fixture_findings, "unguarded-shared-mutation", "worker")
    assert hits and hits[0].symbol == "mut:_shared"
    assert hits[0].severity == "error"


def test_cl002_lock_order_inversion(fixture_findings):
    hits = _hits(fixture_findings, "lock-order-inversion")
    assert len(hits) == 1          # one finding per inverted pair
    assert "g:_lock_a" in hits[0].symbol and "g:_lock_b" in hits[0].symbol


def test_cl003_blocking_under_lock(fixture_findings):
    hits = _hits(fixture_findings, "blocking-under-lock", "sleepy")
    assert hits and hits[0].symbol == "block:time.sleep"
    assert hits[0].confidence == "definite"


def test_cl004_thread_before_fork(fixture_findings):
    hits = _hits(fixture_findings, "thread-before-fork", "start_then_spawn")
    assert hits and hits[0].symbol == "spawn:subprocess.run"


def test_cl005_non_atomic_shared_write(fixture_findings):
    hits = _hits(fixture_findings, "non-atomic-shared-write",
                 "publish_status")
    assert hits and hits[0].symbol == "open-w"


def test_cl006_shutdown_ordering(fixture_findings):
    daemon = [f for f in _hits(fixture_findings, "shutdown-ordering")
              if f.symbol.startswith("daemon-io:")]
    at_exit = [f for f in _hits(fixture_findings, "shutdown-ordering")
               if f.symbol.startswith("atexit:")]
    assert daemon and "drainer" in daemon[0].symbol
    assert at_exit and "_at_exit" in at_exit[0].symbol


def test_cl007_check_then_act(fixture_findings):
    hits = _hits(fixture_findings, "check-then-act", "lazy_init")
    assert hits and hits[0].symbol == "toctou:_flag"
    # the write inside the claimed check-then-act is NOT double-reported
    assert not _hits(fixture_findings, "unguarded-shared-mutation",
                     "lazy_init")


# -- precision controls -------------------------------------------------------

def test_lock_held_mutation_is_clean(fixture_findings):
    assert not _hits(fixture_findings, "unguarded-shared-mutation",
                     "guarded_worker")


def test_waived_site_is_suppressed_not_new(fixture_findings):
    waived = [f for f in fixture_findings
              if "waived_worker" in f.func
              and f.rule == "unguarded-shared-mutation"]
    assert waived and all(f.suppressed for f in waived)


def test_single_thread_only_state_is_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if "lonely_worker" in f.func and not f.suppressed]


def test_condition_wait_on_held_lock_is_clean(fixture_findings):
    assert not _hits(fixture_findings, "blocking-under-lock",
                     "cond_waiter")


def test_atomic_write_pattern_is_clean(fixture_findings):
    assert not _hits(fixture_findings, "non-atomic-shared-write",
                     "publish_atomic")


def test_fingerprints_are_line_number_free(tmp_path):
    src = FIXTURE
    (tmp_path / "a.py").write_text(src)
    (tmp_path / "b.py").write_text("# an unrelated leading comment\n" + src)
    fa, _ = analyzer.analyze_paths([str(tmp_path / "a.py")])
    fb, _ = analyzer.analyze_paths([str(tmp_path / "b.py")])
    fp_a = sorted(f.fingerprint().split("|", 2)[2] for f in fa)
    fp_b = sorted(f.fingerprint().split("|", 2)[2] for f in fb)
    assert fp_a == fp_b


# -- the shipped tree and this PR's fixes -------------------------------------

def test_fixed_runtime_sites_stay_clean():
    """Regression for the triage fixes: the sites this PR guarded
    (JitCache.reset_counters under the cache lock, the ElasticManager
    state lock) must analyze clean — a revert reintroduces findings."""
    dispatch = os.path.join(REPO_ROOT, "paddle_tpu", "core", "dispatch.py")
    findings, _ = analyzer.analyze_paths([dispatch])
    assert not [f for f in findings
                if not f.suppressed and "JitCache" in f.symbol]
    elastic = os.path.join(REPO_ROOT, "paddle_tpu", "distributed",
                           "elastic.py")
    findings, _ = analyzer.analyze_paths([elastic])
    assert not [f for f in findings if not f.suppressed], [
        (f.rule, f.symbol) for f in findings if not f.suppressed]


# -- CLI contract -------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.threadlint", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)


def test_cli_clean_tree_exits_zero():
    r = _run_cli("paddle_tpu", "--fail-stale")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_synthetic_violation_fails(tmp_path):
    pkg = tmp_path / "synthpkg"
    pkg.mkdir()
    (pkg / "racy.py").write_text(textwrap.dedent('''
        import threading

        _state = {"step": 0}


        def _worker():
            _state["step"] += 1


        def progress():
            return _state["step"]


        def launch():
            threading.Thread(target=_worker, daemon=True).start()
    '''))
    r = _run_cli(str(pkg))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "CL001" in r.stdout


def test_cli_write_baseline_then_clean(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "racy.py").write_text(textwrap.dedent('''
        import threading

        _x = {"n": 0}


        def _w():
            _x["n"] += 1


        def read():
            return _x["n"]


        def go():
            threading.Thread(target=_w).start()
    '''))
    bl = tmp_path / "baseline.json"
    assert _run_cli(str(pkg), "--baseline", str(bl)).returncode == 1
    assert _run_cli(str(pkg), "--baseline", str(bl),
                    "--write-baseline").returncode == 0
    r = _run_cli(str(pkg), "--baseline", str(bl))
    assert r.returncode == 0, r.stdout
    assert "1 baselined" in r.stdout

    # fixing the debt leaves a stale entry: --fail-stale gates on it
    (pkg / "racy.py").write_text("def read():\n    return 0\n")
    assert _run_cli(str(pkg), "--baseline", str(bl)).returncode == 0
    r = _run_cli(str(pkg), "--baseline", str(bl), "--fail-stale")
    assert r.returncode == 1
    assert "stale" in r.stdout


def test_cli_json_report(tmp_path):
    out = tmp_path / "report.json"
    r = _run_cli("paddle_tpu", "--json", str(out))
    assert r.returncode == 0
    doc = json.loads(out.read_text())
    assert set(doc["rules"]) == {
        "unguarded-shared-mutation", "lock-order-inversion",
        "blocking-under-lock", "thread-before-fork",
        "non-atomic-shared-write", "shutdown-ordering", "check-then-act"}
    assert doc["summary"]["new"] == 0


def test_shipped_baseline_is_fresh():
    """The checked-in baseline matches what the analyzer produces today
    (no stale entries, no unbaselined findings)."""
    findings, errors = analyzer.analyze_paths(
        [os.path.join(REPO_ROOT, "paddle_tpu")])
    assert not errors
    bl = slib_baseline.load_baseline(
        os.path.join(REPO_ROOT, "tools", "threadlint", "baseline.json"))
    new, baselined, _sup, _info, stale = slib_baseline.partition(
        findings, bl)
    assert not new, [(f.path, f.rule, f.symbol) for f in new]
    assert not stale, stale


# -- staticlib re-home regression ---------------------------------------------

def test_tracelint_baseline_byte_identical_on_staticlib_core(tmp_path):
    """The shared-core extraction must leave tracelint's behavior
    untouched: re-deriving its baseline from a fresh analysis of the
    tree reproduces the checked-in file BYTE FOR BYTE."""
    from tools.tracelint import analyzer as t_analyzer
    from tools.tracelint import baseline as t_baseline

    findings, errors = t_analyzer.analyze_paths(
        [os.path.join(REPO_ROOT, "paddle_tpu")])
    assert not errors
    out = tmp_path / "baseline.json"
    t_baseline.write_baseline(str(out), findings)
    checked = os.path.join(REPO_ROOT, "tools", "tracelint",
                           "baseline.json")
    with open(checked, "rb") as f:
        assert out.read_bytes() == f.read()


def test_both_tools_share_the_staticlib_finding_record():
    from tools.staticlib.findings import Finding as Base
    from tools.threadlint.analyzer import Finding as ClFinding
    from tools.tracelint.analyzer import Finding as TlFinding

    assert issubclass(TlFinding, Base) and issubclass(ClFinding, Base)
    assert TlFinding.RULES is not ClFinding.RULES
