"""Top-level namespace parity vs the reference paddle __init__ exports."""
import os
import re

import numpy as np
import pytest

import paddle_tpu as paddle

# parses the reference checkout's __init__ files; skip (don't fail 28x)
# on hosts without the read-only mount
pytestmark = pytest.mark.skipif(
    not os.path.isdir("/root/reference/python/paddle"),
    reason="reference source not mounted at /root/reference")

# names the reference exports that are intentionally absent here
_WAIVED = {
    "check_shape",  # static-graph debug helper tied to ProgramDesc
    "tolist",       # method on Tensor (paddle.tolist(t) unused in practice)
}


def _ref_exports(path):
    src = open(path).read()
    return set(re.findall(r"^\s+'([A-Za-z_0-9]+)',?\s*(?:#.*)?$", src, re.M))


def test_reference_top_level_exports_present():
    ref = _ref_exports("/root/reference/python/paddle/__init__.py")
    missing = sorted(n for n in ref
                     if n not in _WAIVED and not hasattr(paddle, n))
    assert not missing, f"top-level API gaps vs reference: {missing}"


@pytest.mark.parametrize("mod,path", [
    (paddle.nn, "/root/reference/python/paddle/nn/__init__.py"),
    (paddle.nn.functional,
     "/root/reference/python/paddle/nn/functional/__init__.py"),
    (paddle.tensor, "/root/reference/python/paddle/tensor/__init__.py"),
    (paddle.io, "/root/reference/python/paddle/io/__init__.py"),
    (paddle.vision.datasets,
     "/root/reference/python/paddle/vision/datasets/__init__.py"),
    (paddle.vision.transforms,
     "/root/reference/python/paddle/vision/transforms/__init__.py"),
    (paddle.metric, "/root/reference/python/paddle/metric/__init__.py"),
    (paddle.jit, "/root/reference/python/paddle/jit/__init__.py"),
    (paddle.optimizer,
     "/root/reference/python/paddle/optimizer/__init__.py"),
    (paddle.static, "/root/reference/python/paddle/static/__init__.py"),
    (paddle.linalg, "/root/reference/python/paddle/linalg.py"),
    (paddle.fft, "/root/reference/python/paddle/fft.py"),
    (paddle.distribution,
     "/root/reference/python/paddle/distribution/__init__.py"),
    (paddle.sparse, "/root/reference/python/paddle/sparse/__init__.py"),
    (paddle.incubate,
     "/root/reference/python/paddle/incubate/__init__.py"),
    (paddle.utils, "/root/reference/python/paddle/utils/__init__.py"),
    (paddle.distributed,
     "/root/reference/python/paddle/distributed/__init__.py"),
    (paddle.distributed.fleet,
     "/root/reference/python/paddle/distributed/fleet/__init__.py"),
    (paddle.amp, "/root/reference/python/paddle/amp/__init__.py"),
    (paddle.autograd,
     "/root/reference/python/paddle/autograd/__init__.py"),
    (paddle.device, "/root/reference/python/paddle/device/__init__.py"),
    (paddle.text, "/root/reference/python/paddle/text/__init__.py"),
    (paddle.vision.ops, "/root/reference/python/paddle/vision/ops.py"),
    (paddle.signal, "/root/reference/python/paddle/signal.py"),
    (paddle.profiler,
     "/root/reference/python/paddle/profiler/__init__.py"),
    (paddle.static.nn,
     "/root/reference/python/paddle/static/nn/__init__.py"),
], ids=["nn", "nn.functional", "tensor", "io", "vision.datasets",
        "vision.transforms", "metric", "jit", "optimizer", "static",
        "linalg", "fft", "distribution", "sparse", "incubate", "utils",
        "distributed", "fleet", "amp", "autograd", "device", "text",
        "vision.ops", "signal", "profiler", "static.nn"])
def test_submodule_exports_present(mod, path):
    ref = _ref_exports(path)
    missing = sorted(n for n in ref if not hasattr(mod, n))
    assert not missing, f"{mod.__name__} gaps vs reference: {missing}"


def test_new_ops():
    x = paddle.to_tensor(np.array([[1.0, 0.0], [1.0, 1.0]], np.float32))
    assert not bool(paddle.all(x)._value)
    assert bool(paddle.any(x)._value)
    np.testing.assert_allclose(paddle.trace(x).numpy(), 2.0)
    np.testing.assert_allclose(
        paddle.logit(paddle.to_tensor(np.float32(0.75))).numpy(),
        np.log(3.0), rtol=1e-6)
    z = paddle.to_tensor(np.array([1 + 2j], np.complex64))
    np.testing.assert_allclose(paddle.conj(z).numpy(), [1 - 2j])
    # renorm: rows with norm > max scaled down to max
    v = paddle.to_tensor(np.array([[3.0, 4.0], [0.3, 0.4]], np.float32))
    out = paddle.renorm(v, 2.0, 0, 1.0)
    np.testing.assert_allclose(np.linalg.norm(out.numpy()[0]), 1.0,
                               rtol=1e-4)
    np.testing.assert_allclose(out.numpy()[1], [0.3, 0.4], rtol=1e-6)


def test_batch_and_flags():
    r = paddle.batch(lambda: iter(range(7)), 3)
    assert [len(b) for b in r()] == [3, 3, 1]
    r2 = paddle.batch(lambda: iter(range(7)), 3, drop_last=True)
    assert [len(b) for b in r2()] == [3, 3]
    paddle.set_flags({"FLAGS_cudnn_deterministic": 1})
    assert paddle.get_flags("FLAGS_cudnn_deterministic") == {
        "FLAGS_cudnn_deterministic": 1}
    paddle.disable_signal_handler()
    assert isinstance(paddle.DataParallel, type)
    assert paddle.NPUPlace(0) is not None


def test_round3_namespace_exports():
    """Round-3 namespaces: quantization/auto_parallel/sparsity/text match
    the reference surfaces they mirror."""
    from paddle_tpu import text
    from paddle_tpu.distributed import auto_parallel

    # paddle.distributed re-exports shard_tensor/shard_op (reference
    # distributed/__init__.py:45)
    assert hasattr(paddle.distributed, "shard_tensor")
    assert hasattr(paddle.distributed, "shard_op")
    assert hasattr(auto_parallel, "ProcessMesh")
    assert hasattr(auto_parallel, "Engine")
    # paddle.static.sparsity (reference static/sparsity/__init__.py)
    for n in ("calculate_density", "decorate", "prune_model",
              "set_excluded_layers", "reset_excluded_layers"):
        assert hasattr(paddle.static.sparsity, n), n
    # slim quantization classes
    for n in ("PostTrainingQuantization", "ImperativeQuantAware",
              "QuantConfig"):
        assert hasattr(paddle.static.quantization, n), n
    # text datasets (reference text/__init__.py exports)
    for n in ("Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05st",
              "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"):
        assert hasattr(text, n), n


def test_tensor_method_list_parity():
    """Every name in the reference's tensor_method_func monkey-patch list
    must exist on our Tensor (the reference attaches all of them,
    including a few whose first parameter is not a tensor)."""
    import re

    import numpy as np

    import paddle_tpu as paddle

    src = open("/root/reference/python/paddle/tensor/__init__.py").read()
    m = re.search(r"tensor_method_func\s*=\s*\[", src)
    assert m, "tensor_method_func list not found in reference"
    body = src[m.end():].split("]", 1)[0]
    names = re.findall(r"['\"](\w+)['\"]", body)  # both quote styles
    assert len(names) > 200, len(names)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    missing = [n for n in names if not hasattr(t, n)]
    assert not missing, missing
