"""Serving engine (paddle_tpu/inference/): paged KV cache allocator,
continuous-batching scheduler, ragged/paged attention parity, and the
engine acceptance properties (token-exact batching, deadline eviction,
telemetry/span reconciliation, warm-start round trip)."""
import json
import os
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import (
    ContinuousBatchingScheduler,
    KVCacheConfig,
    PagedKVCache,
    RequestState,
    ServeConfig,
    ServeRequest,
    ServingEngine,
    TinyServeModel,
)
from paddle_tpu.runtime.resilience import (
    FaultInjector,
    fault_events,
    reset_fault_events,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cache(num_blocks=8, block_size=4, layers=1, heads=2, head_dim=4,
           max_blocks_per_seq=None):
    return PagedKVCache(KVCacheConfig(
        num_layers=layers, num_heads=heads, head_dim=head_dim,
        block_size=block_size, num_blocks=num_blocks,
        max_blocks_per_seq=max_blocks_per_seq))


# ---------------------------------------------------------------------------
# paged KV cache allocator


class TestKVCache:
    def test_alloc_grows_lazily_and_free_returns_all(self):
        c = _cache(num_blocks=8, block_size=4)
        assert c.ensure_capacity("a", 3)          # 1 block
        assert c.blocks_in_use() == 1
        assert c.ensure_capacity("a", 9)          # grows to 3
        assert c.block_table("a") == [0, 1, 2]
        assert c.ensure_capacity("a", 9)          # idempotent
        assert c.blocks_in_use() == 3
        assert c.release("a") == 3
        assert c.blocks_free() == 8
        assert c.release("a") == 0                # double release: no-op

    def test_oom_allocates_nothing(self):
        c = _cache(num_blocks=2, block_size=4)
        assert c.ensure_capacity("a", 8)
        before = c.block_table("b")
        assert not c.ensure_capacity("b", 5)      # needs 2, none free
        assert c.block_table("b") == before == []
        assert c.blocks_free() == 0

    def test_per_request_block_bound(self):
        c = _cache(num_blocks=8, block_size=4, max_blocks_per_seq=2)
        assert not c.ensure_capacity("a", 9)      # 3 blocks > bound
        assert c.ensure_capacity("a", 8)

    def test_fragmentation_interleaved_alloc_free_conserves_pool(self):
        """Interleaved alloc/free across many requests: every block is
        either in exactly one table or on the free list, and freed
        blocks are reused (paged = no external fragmentation)."""
        c = _cache(num_blocks=6, block_size=2)
        rng = np.random.RandomState(0)
        live = {}
        for i in range(200):
            rid = f"r{rng.randint(8)}"
            if rid in live and rng.rand() < 0.4:
                c.release(rid)
                live.pop(rid)
            else:
                want = live.get(rid, 0) + int(rng.randint(1, 4))
                if c.ensure_capacity(rid, want * 2):  # tokens = 2/block
                    live[rid] = want
            held = sum(len(c.block_table(r)) for r in live)
            assert held + c.blocks_free() == 6
            all_blocks = [b for r in live for b in c.block_table(r)]
            assert len(all_blocks) == len(set(all_blocks))  # no aliasing
        for r in list(live):
            c.release(r)
        assert c.blocks_free() == 6
        assert c.stats()["highwater"] <= 6

    def test_lowest_id_first_is_deterministic(self):
        a, b = _cache(), _cache()
        for c in (a, b):
            c.ensure_capacity("x", 8)
            c.ensure_capacity("y", 4)
            c.release("x")
            c.ensure_capacity("z", 8)
        assert a.block_table("z") == b.block_table("z")

    def test_padded_tables_and_utilization(self):
        c = _cache(num_blocks=8, block_size=4, max_blocks_per_seq=3)
        c.ensure_capacity("a", 8)
        t = c.padded_tables(["a", None, "missing"])
        assert t.shape == (3, 3) and t.dtype == np.int32
        assert list(t[0][:2]) == c.block_table("a")
        assert t[1].tolist() == [0, 0, 0]
        assert c.utilization() == pytest.approx(2 / 8)


# ---------------------------------------------------------------------------
# continuous-batching scheduler


def _sched(num_blocks=16, block_size=4, max_running=2, token_budget=8,
           **kw):
    cache = _cache(num_blocks=num_blocks, block_size=block_size,
                   max_blocks_per_seq=kw.pop("max_blocks_per_seq", None))
    return ContinuousBatchingScheduler(cache, max_running=max_running,
                                       token_budget=token_budget, **kw)


class TestScheduler:
    def test_admit_prefill_decode_lifecycle(self):
        s = _sched(max_running=2, token_budget=8)
        r = ServeRequest([5, 6, 7], max_new_tokens=2)
        s.submit(r)
        plan = s.plan()
        assert r.state == RequestState.RUNNING
        assert plan.prefill_rows == 3 and plan.decode_rows == 0
        assert plan.token_ids[:3].tolist() == [5, 6, 7]
        assert plan.row_pos[:3].tolist() == [0, 1, 2]
        assert plan.row_pos[3:].tolist() == [-1] * 5   # budget tail
        assert [row for row, _ in plan.emit] == [2]    # last prompt row
        s.complete_step(plan, {2: 9})
        assert r.generated == [9]
        plan2 = s.plan()                                # decode row
        assert plan2.decode_rows == 1 and plan2.prefill_rows == 0
        assert plan2.decode_only
        assert plan2.token_ids[0] == 9 and plan2.row_pos[0] == 3
        s.complete_step(plan2, {0: 4})
        assert r.state == RequestState.FINISHED
        assert s.cache.blocks_in_use() == 0            # freed on finish

    def test_prefill_chunks_across_steps_under_budget(self):
        s = _sched(token_budget=4)
        r = ServeRequest(list(range(1, 11)), max_new_tokens=1)
        s.submit(r)
        p1 = s.plan()
        assert p1.prefill_rows == 4 and not p1.emit
        p2 = s.plan()
        assert p2.prefill_rows == 4 and not p2.emit
        p3 = s.plan()
        assert p3.prefill_rows == 2
        assert [row for row, _ in p3.emit] == [1]
        assert p3.row_pos[1] == 9

    def test_decode_rows_scheduled_before_prefill(self):
        s = _sched(max_running=2, token_budget=4)
        a = ServeRequest([1, 2], max_new_tokens=4)
        s.submit(a)
        s.complete_step(s.plan(), {1: 7})              # a enters decode
        b = ServeRequest([3, 4, 5, 6, 7], max_new_tokens=1)
        s.submit(b)
        plan = s.plan()
        assert plan.decode_rows == 1 and plan.prefill_rows == 3
        assert plan.row_req[0] == a.slot               # decode row first
        assert not plan.decode_only

    def test_deadline_evicts_running_and_queued(self):
        reset_fault_events()
        s = _sched(max_running=1)
        slow = ServeRequest([1, 2], max_new_tokens=8, deadline_s=0.01)
        queued = ServeRequest([3], max_new_tokens=1, deadline_s=0.01)
        s.submit(slow)
        s.submit(queued)
        s.complete_step(s.plan(), {1: 5})
        time.sleep(0.03)
        plan = s.plan()
        assert slow.state == RequestState.EVICTED
        assert slow.evict_reason == "deadline"
        assert queued.state == RequestState.EVICTED
        assert s.cache.blocks_in_use() == 0
        assert fault_events().get("request_deadline", 0) >= 2
        assert plan.n_rows == 0

    def test_preempts_youngest_prefill_for_decode_blocks(self):
        reset_fault_events()
        s = _sched(num_blocks=4, block_size=2, max_running=2,
                   token_budget=6)
        a = ServeRequest([1, 2, 3], max_new_tokens=3)      # 2 blocks
        s.submit(a)
        s.complete_step(s.plan(), {2: 9})                  # a -> decode
        b = ServeRequest([5, 6, 7, 8, 9, 10], max_new_tokens=1)
        s.submit(b)
        p2 = s.plan()    # a decodes (no growth); b prefills 2 blocks
        assert b.state == RequestState.RUNNING and b.n_fed == 4
        s.complete_step(p2, {0: 9})
        p3 = s.plan()    # a's decode needs a 3rd block -> preempt b
        assert a.state == RequestState.RUNNING
        assert p3.decode_rows == 1
        assert b.preemptions == 1 and b.n_fed == 0
        assert fault_events().get("kv_preemptions", 0) >= 1
        s.complete_step(p3, {0: 4})                        # a finishes
        assert a.state == RequestState.FINISHED
        for _ in range(6):                                 # b restarts
            if b.state == RequestState.FINISHED:
                break
            plan = s.plan()
            s.complete_step(plan, {row: 3 for row, _ in plan.emit})
        assert b.state == RequestState.FINISHED
        assert s.cache.blocks_in_use() == 0

    def test_decode_past_max_context_evicts_without_preempting_peers(self):
        """A decode that hit the per-request block bound can never be
        satisfied by freeing peers' blocks — it must evict directly, not
        trigger a futile preemption cascade restarting every prefilling
        request (code-review finding)."""
        reset_fault_events()
        s = _sched(num_blocks=8, block_size=2, max_running=2,
                   token_budget=4, max_blocks_per_seq=2)
        a = ServeRequest([1, 2, 3], max_new_tokens=50)   # ctx cap = 4
        s.submit(a)
        s.complete_step(s.plan(), {2: 9})                # a -> decode
        b = ServeRequest([5, 6, 7], max_new_tokens=2)
        s.submit(b)
        p2 = s.plan()                                    # a decodes pos 3
        s.complete_step(p2, {row: 9 for row, _ in p2.emit})
        p3 = s.plan()   # a would need pos 4 > max_context -> evict a
        assert a.state == RequestState.EVICTED
        assert a.evict_reason == "context_exhausted"
        assert b.preemptions == 0                        # no cascade
        assert b.state == RequestState.RUNNING
        s.complete_step(p3, {row: 3 for row, _ in p3.emit})
        assert b.state == RequestState.FINISHED

    def test_prompt_longer_than_max_context_is_rejected(self):
        reset_fault_events()
        s = _sched(num_blocks=4, block_size=2, max_blocks_per_seq=2)
        r = ServeRequest([1] * 10, max_new_tokens=1)        # > 4 positions
        s.submit(r)
        assert s.plan().n_rows == 0
        assert r.state == RequestState.EVICTED
        assert r.evict_reason == "prompt_too_long"

    def test_eos_finishes_early(self):
        s = _sched()
        r = ServeRequest([1, 2], max_new_tokens=50, eos_id=7)
        s.submit(r)
        s.complete_step(s.plan(), {1: 7})
        assert r.state == RequestState.FINISHED
        assert r.generated == [7]


# ---------------------------------------------------------------------------
# ragged/paged attention: dense path vs naive reference, kernel parity


def _naive(q, ks, vs, scale):
    s = np.einsum("hd,lhd->hl", q, ks) * scale
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("hl,lhd->hd", p, vs)


@pytest.mark.parametrize("mix", [
    [(5, 0)],                     # one pure prefill
    [(1, 3), (1, 7)],             # two decode rows, ragged contexts
    [(6, 0), (1, 2), (3, 4)],     # prefill + decode + chunk continuation
])
def test_ragged_dense_matches_naive_reference(mix):
    """Each (n_tokens, start_pos) entry is one request's rows this step;
    contexts are pre-populated up to start_pos, then the step's rows are
    written+attended by the op. Every row must equal single-request
    full attention at its position."""
    from paddle_tpu.nn.functional.attention import _ragged_paged_dense

    rng = np.random.RandomState(0)
    H, D, BS, NB, BMAX = 2, 4, 4, 32, 4
    scale = 1.0 / float(np.sqrt(D))
    fn = _ragged_paged_dense(BS, scale)
    kp = np.zeros((NB, BS, H, D), np.float32)
    vp = np.zeros_like(kp)
    R = len(mix)
    tables = np.zeros((R, BMAX), np.int32)
    hist = {}   # request -> (ks, vs) full history
    nb_next = 1  # leave block 0 as the shared padding target
    for r, (n, start) in enumerate(mix):
        total = start + n
        nblocks = -(-total // BS)
        tables[r, :nblocks] = range(nb_next, nb_next + nblocks)
        nb_next += nblocks
        ks = rng.randn(total, H, D).astype(np.float32)
        vs = rng.randn(total, H, D).astype(np.float32)
        hist[r] = (ks, vs)
        for p in range(start):   # pre-populate context before the step
            blk = tables[r, p // BS]
            kp[blk, p % BS], vp[blk, p % BS] = ks[p], vs[p]
    T = sum(n for n, _ in mix) + 2          # +2 padding rows
    qs = rng.randn(T, H, D).astype(np.float32)
    tok_k = np.zeros((T, H, D), np.float32)
    tok_v = np.zeros((T, H, D), np.float32)
    row_req = np.zeros(T, np.int32)
    row_pos = np.full(T, -1, np.int32)
    i = 0
    for r, (n, start) in enumerate(mix):
        for j in range(n):
            tok_k[i], tok_v[i] = hist[r][0][start + j], hist[r][1][start + j]
            row_req[i], row_pos[i] = r, start + j
            i += 1
    out, kp2, vp2 = fn(jnp.asarray(qs.reshape(T, H * D)),
                       jnp.asarray(tok_k.reshape(T, H * D)),
                       jnp.asarray(tok_v.reshape(T, H * D)),
                       jnp.asarray(kp), jnp.asarray(vp),
                       jnp.asarray(tables), jnp.asarray(row_req),
                       jnp.asarray(row_pos))
    out = np.asarray(out).reshape(T, H, D)
    i = 0
    for r, (n, start) in enumerate(mix):
        ks, vs = hist[r]
        for j in range(n):
            pos = start + j
            ref = _naive(qs[i], ks[:pos + 1], vs[:pos + 1], scale)
            np.testing.assert_allclose(out[i], ref, rtol=1e-5, atol=1e-6,
                                       err_msg=f"req {r} pos {pos}")
            i += 1
    assert np.all(out[i:] == 0)             # padding rows: zeros


@pytest.mark.parametrize("block_size,bmax", [(2, 6), (4, 3), (8, 2)])
def test_paged_kernel_matches_dense_block_by_block(block_size, bmax):
    """Pallas decode kernel (interpret mode on CPU) vs the dense path,
    across block geometries and ragged context lengths — including a
    context that ends mid-block and a padding row."""
    from paddle_tpu.nn.functional.attention import _ragged_paged_dense
    from paddle_tpu.ops.pallas.ragged_paged_attention import (
        paged_attention_decode_raw,
    )

    rng = np.random.RandomState(1)
    H, D, NB = 2, 4, 16
    scale = 1.0 / float(np.sqrt(D))
    lens = [block_size * bmax, block_size + 1, 1, 0]   # 0 = padding row
    R = len(lens)
    tables = np.zeros((R, bmax), np.int32)
    kp = np.zeros((NB, block_size, H, D), np.float32)
    vp = np.zeros_like(kp)
    nxt = 1
    for r, ln in enumerate(lens):
        nblocks = -(-ln // block_size)
        tables[r, :nblocks] = range(nxt, nxt + nblocks)
        nxt += nblocks
        for p in range(ln):
            blk = tables[r, p // block_size]
            kp[blk, p % block_size] = rng.randn(H, D)
            vp[blk, p % block_size] = rng.randn(H, D)
    q = rng.randn(R, H, D).astype(np.float32)
    row_req = np.arange(R, dtype=np.int32)
    row_pos = np.asarray([ln - 1 for ln in lens], np.int32)  # -1 = pad
    # dense path: pass the last cached token as the "new" kv (rewriting
    # the same slot with the same value — a pure read reference)
    tok_k = np.zeros((R, H, D), np.float32)
    tok_v = np.zeros((R, H, D), np.float32)
    for r, ln in enumerate(lens):
        if ln:
            blk = tables[r, (ln - 1) // block_size]
            tok_k[r] = kp[blk, (ln - 1) % block_size]
            tok_v[r] = vp[blk, (ln - 1) % block_size]
    dense = _ragged_paged_dense(block_size, scale)
    d_out = np.asarray(dense(
        jnp.asarray(q.reshape(R, H * D)),
        jnp.asarray(tok_k.reshape(R, H * D)),
        jnp.asarray(tok_v.reshape(R, H * D)),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables),
        jnp.asarray(row_req), jnp.asarray(row_pos))[0]).reshape(R, H, D)
    k_out = np.asarray(paged_attention_decode_raw(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(np.asarray(lens, np.int32)),
        scale))
    np.testing.assert_allclose(k_out, d_out, rtol=1e-5, atol=1e-6)
    assert np.all(k_out[-1] == 0)


def test_engine_through_kernel_path_matches_dense(monkeypatch):
    """Route the whole engine through the Pallas dispatch path by
    forcing the GATE only (`_use_paged_kernel`) — the backend stays
    'cpu', so `_interpret()` stays True and the kernel GENUINELY
    executes in interpret mode (monkeypatching jax.default_backend
    would flip _interpret too, the kernel would fail to lower on CPU,
    and the degrade-to-dense guard would silently mask the whole test —
    review finding). Decode-only steps take the kernel; tokens match
    the dense engine exactly and NO fallback fires."""
    from paddle_tpu.nn.functional import attention as A

    reset_fault_events()
    dense_tokens = _engine().generate(PROMPTS, max_new_tokens=4)
    calls = []
    real_fn = A._paged_decode_fn

    def counting(*a, **kw):
        calls.append(1)
        return real_fn(*a, **kw)

    monkeypatch.setattr(A, "_paged_decode_fn", counting)
    monkeypatch.setattr(A, "_use_paged_kernel",
                        lambda head_dim, decode_only: decode_only)
    try:
        kernel_tokens = _engine().generate(PROMPTS, max_new_tokens=4)
    finally:
        monkeypatch.undo()
    assert calls, "kernel dispatch path was never taken"
    assert kernel_tokens == dense_tokens
    assert fault_events().get("paged_kernel_fallbacks", 0) == 0, \
        "the kernel did not actually run — the fallback served instead"


def test_kernel_failure_degrades_to_dense(monkeypatch):
    """A Mosaic lowering gap (simulated: the raw kernel raises) must
    degrade to the dense path with a paged_kernel_fallbacks fault event,
    not crash the serving loop."""
    from paddle_tpu.core.dispatch import reset_dispatch_stats
    from paddle_tpu.nn.functional import attention as A
    from paddle_tpu.ops.pallas import ragged_paged_attention as RPA

    reset_fault_events()
    dense_tokens = _engine().generate(PROMPTS, max_new_tokens=3)
    # drop compiled programs: a cached _paged_decode executable from an
    # earlier kernel-path test would serve without re-tracing and the
    # patched-in failure below would never fire
    reset_dispatch_stats(clear_caches=True)

    def boom(*a, **kw):
        raise RuntimeError("mosaic says no")

    monkeypatch.setattr(RPA, "paged_attention_decode_raw", boom)
    monkeypatch.setattr(A, "_use_paged_kernel",
                        lambda head_dim, decode_only: decode_only)
    try:
        tokens = _engine().generate(PROMPTS, max_new_tokens=3)
    finally:
        monkeypatch.undo()
    assert tokens == dense_tokens
    assert fault_events().get("paged_kernel_fallbacks", 0) >= 1


def test_paged_kernel_dispatch_gated_on_backend(monkeypatch):
    """The kernel routes only decode-only TPU steps; CPU and mixed
    batches stay dense (the flash-style capability probe)."""
    import jax

    from paddle_tpu.nn.functional import attention as A

    assert A._paged_decode_fn is not None     # registered at import
    assert not A._use_paged_kernel(64, decode_only=True)   # CPU backend
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert A._use_paged_kernel(64, decode_only=True)
    assert not A._use_paged_kernel(64, decode_only=False)  # mixed batch
    assert not A._use_paged_kernel(512, decode_only=True)  # huge head


# ---------------------------------------------------------------------------
# engine acceptance


def _engine(seed=0, **cfg):
    model = TinyServeModel(vocab=32, dim=8, layers=2, heads=2, ffn=16,
                           seed=seed)
    base = dict(max_running=3, token_budget=8, block_size=4,
                num_blocks=16, max_blocks_per_seq=4)
    base.update(cfg)
    return ServingEngine(model, ServeConfig(**base))


PROMPTS = [[1, 2, 3, 4, 5], [7, 8], [3, 1, 4, 1, 5, 9]]


class TestEngine:
    def test_batched_equals_sequential_token_exact(self):
        batched = _engine().generate(PROMPTS, max_new_tokens=4)
        sequential = [_engine().generate([p], max_new_tokens=4)[0]
                      for p in PROMPTS]
        assert batched == sequential
        assert all(len(t) == 4 for t in batched)

    def test_fusion_parity(self):
        """The decode loop under PADDLE_TPU_EAGER_FUSION=1 (one fused
        flush per step at the engine's token read) produces identical
        tokens."""
        from paddle_tpu.core import fusion

        baseline = _engine().generate(PROMPTS, max_new_tokens=3)
        fusion.set_fusion(True)
        try:
            fused = _engine().generate(PROMPTS, max_new_tokens=3)
        finally:
            fusion.set_fusion(False)
        assert fused == baseline

    def test_more_requests_than_slots_queue_and_finish(self):
        eng = _engine(max_running=2)
        prompts = [[i + 1, i + 2] for i in range(6)]
        outs = eng.generate(prompts, max_new_tokens=2)
        assert all(len(t) == 2 for t in outs)
        st = eng.stats()
        assert st["finished"] == 6 and st["running"] == 0
        assert st["kv"]["blocks_in_use"] == 0

    def test_request_histograms_and_spans_reconcile(self, tmp_path):
        from paddle_tpu.core.dispatch import reset_dispatch_stats
        from paddle_tpu.runtime import telemetry, tracing

        # clean global slate: earlier tests fed the process-wide serve
        # histograms (and sampled per-op run stats) with tracing OFF,
        # which would skew span<->metric counts (the PR-12 fit-reconcile
        # precedent)
        telemetry.reset_metrics()
        reset_dispatch_stats()
        tracing.configure(str(tmp_path / "trace"))
        tracing.reset_span_stats()
        try:
            eng = _engine()
            eng.generate(PROMPTS, max_new_tokens=3)
            ok, report = tracing.reconcile_with_metrics()
            assert report["serve_request"]["span_n"] >= len(PROMPTS)
            assert not report["serve_request"]["skipped"]
            assert not report["serve_ttft"]["skipped"]
            assert report["serve_request"]["ok"], report
            assert report["serve_ttft"]["ok"], report
            snap = telemetry.snapshot()
            fam = snap["paddle_tpu_serve_request_seconds"]["series"][0]
            st = tracing.span_stats()[("serve", "request")]
            assert st["count"] == fam["count"]
            assert abs(st["total_s"] - fam["sum"]) < 1e-9
        finally:
            tracing.set_enabled(False)

    def test_slow_request_evicted_at_deadline_not_wedging_loop(self):
        """FaultInjector wedges every step with an injected delay; the
        request with the tight deadline is evicted AT its deadline
        (request_deadline fault event) while the other request still
        runs to completion — the batch loop degrades per-request."""
        reset_fault_events()
        eng = _engine(max_running=2)
        slow_id = eng.submit([1, 2, 3], max_new_tokens=50,
                             deadline_s=0.12)
        ok_id = eng.submit([7, 8], max_new_tokens=3)
        with FaultInjector({"serve.step": ("delay", 0.05)}):
            out = eng.run(max_steps=60)
        assert ok_id in out and len(out[ok_id]) == 3
        assert slow_id not in out
        evicted = {r.request_id: r for r in eng.scheduler.evicted}
        assert slow_id in evicted
        assert evicted[slow_id].evict_reason == "deadline"
        assert fault_events().get("request_deadline", 0) >= 1
        # evicted at ~its deadline, not after the full 50-token run
        req = evicted[slow_id]
        assert len(req.generated) < 50

    def test_evicted_requests_counted_by_outcome(self):
        from paddle_tpu.runtime import telemetry

        reset_fault_events()
        eng = _engine()
        eng.submit([1, 2], max_new_tokens=1)
        eng.submit([3, 4], max_new_tokens=50, deadline_s=0.0)  # instant
        time.sleep(0.001)
        eng.run(max_steps=20)
        snap = telemetry.snapshot()
        series = snap["paddle_tpu_serve_requests_total"]["series"]
        by_outcome = {tuple(s["labels"].values())[0]: s["value"]
                      for s in series}
        assert by_outcome.get("completed", 0) >= 1
        assert by_outcome.get("evicted", 0) >= 1

    def test_kv_gauges_track_occupancy(self):
        from paddle_tpu.runtime import telemetry

        eng = _engine()
        eng.submit(PROMPTS[0], max_new_tokens=2)
        eng.step()
        snap = telemetry.snapshot()
        vals = {tuple(s["labels"].values())[0]: s["value"]
                for s in snap["paddle_tpu_serve_kv_blocks"]["series"]}
        assert vals["in_use"] == eng.cache.blocks_in_use() > 0
        eng.run(max_steps=20)
        assert eng.cache.blocks_in_use() == 0

    def test_watchdog_ticks_per_step(self, tmp_path):
        from paddle_tpu.distributed.elastic import ElasticManager

        em = ElasticManager(str(tmp_path), timeout=300.0,
                            save_interval=10**9)
        eng = _engine()
        eng.elastic = em
        eng.generate([PROMPTS[0]], max_new_tokens=2)
        assert em._last_step == eng.steps > 0


@pytest.mark.slow
def test_serve_warm_start_round_trip(tmp_path):
    """Two fresh processes (tests/_serve_child.py): the second
    precompiles the first's shape manifest and must serve with ZERO
    fresh XLA compiles and identical tokens. tools/serve_smoke.py runs
    the same proof (plus reconciliation) in ci_check."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               PADDLE_TPU_COMPILE_CACHE_DIR=str(tmp_path / "cache"),
               PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S="0",
               SERVE_MANIFEST=str(tmp_path / "manifest.json"))
    env.pop("PADDLE_TPU_SHAPE_MANIFEST", None)
    env.pop("SERVE_TRACE_DIR", None)

    def run(mode):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tests",
                                          "_serve_child.py"), mode],
            env=env, cwd=REPO, capture_output=True, timeout=300)
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        return json.loads(proc.stdout.decode().strip().splitlines()[-1])

    cold = run("record")
    assert cold["batched"] == cold["sequential"]
    warm = run("replay")
    assert warm["precompile"]["ops_precompiled"] >= 1
    assert warm["fresh_compiles"] == 0
    assert warm["disk_cache_hits"] > 0
    assert warm["batched"] == cold["batched"]


# ---------------------------------------------------------------------------
# robustness: admission control / cancel / drain / journal (ISSUE 18)


def _outcome_counts():
    from paddle_tpu.runtime import telemetry

    fam = telemetry.snapshot().get("paddle_tpu_serve_requests_total") or {}
    return {tuple(s["labels"].values())[0]: s["value"]
            for s in fam.get("series", [])}


class TestAdmissionControl:
    def test_queue_full_sheds_with_counter_and_fault(self):
        from paddle_tpu.inference import OverloadedError

        reset_fault_events()
        before = _outcome_counts().get("overloaded", 0)
        eng = _engine(max_running=1, max_queued=1)
        eng.submit([1, 2], max_new_tokens=2)          # fills the queue
        with pytest.raises(OverloadedError) as exc:
            eng.submit([3, 4], max_new_tokens=2)
        assert exc.value.reason == "queue_full"
        assert _outcome_counts().get("overloaded", 0) == before + 1
        assert fault_events().get("serve_sheds", 0) >= 1
        # the shed request was never queued: memory cannot grow
        assert eng.scheduler.stats()["queued"] == 1
        assert eng.scheduler.shed_by_reason == {"queue_full": 1}
        # accepted work is unharmed
        out = eng.run(max_steps=50)
        assert len(out) == 1

    def test_token_backlog_sheds(self):
        from paddle_tpu.inference import OverloadedError

        eng = _engine(max_queued_tokens=4)
        eng.submit([1, 2, 3], max_new_tokens=2)
        with pytest.raises(OverloadedError) as exc:
            eng.submit([4, 5, 6], max_new_tokens=2)   # 3+3 > 4
        assert exc.value.reason == "token_backlog"

    def test_kv_backlog_sheds_on_full_context_horizon(self):
        from paddle_tpu.inference import OverloadedError

        # block_size=4: a 5-token prompt + 4 new needs 3 blocks > bound
        eng = _engine(max_queued_blocks=2)
        with pytest.raises(OverloadedError) as exc:
            eng.submit([1, 2, 3, 4, 5], max_new_tokens=4)
        assert exc.value.reason == "kv_backlog"
        eng.submit([1, 2], max_new_tokens=2)          # 2 blocks: fits
        assert len(eng.run(max_steps=50)) == 1

    def test_queue_wait_shed_at_plan_time(self):
        reset_fault_events()
        s = _sched(max_running=1, token_budget=4, max_queue_wait_s=0.5)
        a = ServeRequest([1, 2, 3], max_new_tokens=8)
        b = ServeRequest([4, 5], max_new_tokens=2)
        s.submit(a)
        s.plan()                                      # a occupies the slot
        s.submit(b)
        s.plan(now=b.t_submit + 0.1)                  # within the wait cap
        assert b.state == RequestState.WAITING
        s.plan(now=b.t_submit + 1.0)                  # past it: shed
        assert b.state == RequestState.EVICTED
        assert b.evict_reason == "queue_timeout"
        assert s.shed_by_reason.get("queue_timeout") == 1
        assert fault_events().get("serve_sheds", 0) >= 1

    def test_queue_timeout_counts_as_overloaded_outcome(self):
        before = _outcome_counts().get("overloaded", 0)
        eng = _engine(max_running=1, max_queue_wait_s=0.0,
                      max_queued=8)
        eng.submit([1, 2, 3], max_new_tokens=6)       # takes the slot
        eng.submit([4, 5], max_new_tokens=2)          # will wait > 0.0s
        time.sleep(0.002)
        eng.run(max_steps=60)
        assert _outcome_counts().get("overloaded", 0) >= before + 1


class TestCancel:
    def test_cancel_queued_and_running_free_blocks_now(self):
        before = _outcome_counts().get("cancelled", 0)
        eng = _engine(max_running=1)
        run_id = eng.submit([1, 2, 3], max_new_tokens=8)
        q_id = eng.submit([4, 5], max_new_tokens=8)
        eng.step()
        assert eng.cache.blocks_in_use() > 0
        assert eng.cancel(run_id)                     # running
        assert eng.cache.blocks_in_use() == 0         # freed immediately
        assert eng.cancel(q_id)                       # still queued
        assert not eng.cancel("nope")                 # unknown id
        assert not eng.cancel(run_id)                 # already gone
        assert not eng.scheduler.has_work()
        assert _outcome_counts().get("cancelled", 0) == before + 2
        # cancellation is caller intent, not degradation: no shed count
        assert eng.scheduler.shed_total == 0

    def test_cancelled_request_not_in_results(self):
        eng = _engine()
        keep = eng.submit([1, 2], max_new_tokens=2)
        drop = eng.submit([3, 4], max_new_tokens=2)
        eng.cancel(drop)
        out = eng.run(max_steps=50)
        assert keep in out and drop not in out


class TestDrain:
    def test_drain_finishes_inflight_then_refuses_admission(self):
        from paddle_tpu.inference import OverloadedError

        eng = _engine()
        ids = [eng.submit(p, max_new_tokens=3) for p in PROMPTS]
        report = eng.drain(deadline_s=60.0)
        assert report["shed"] == 0
        assert sorted(report["results"]) == sorted(ids)
        assert all(len(t) == 3 for t in report["results"].values())
        with pytest.raises(OverloadedError) as exc:
            eng.submit([9, 9], max_new_tokens=1)
        assert exc.value.reason == "draining"
        assert eng.diagnostics_snapshot()["drain"]["state"] == "drained"

    def test_drain_deadline_sheds_stragglers(self):
        eng = _engine()
        eng.submit([1, 2, 3], max_new_tokens=50)
        with FaultInjector({"serve.step": ("delay", 0.05)}):
            report = eng.drain(deadline_s=0.12)
        assert report["shed"] >= 1
        assert not eng.scheduler.has_work()
        assert eng.cache.blocks_in_use() == 0
        ev = {r.request_id: r.evict_reason
              for r in eng.scheduler.evicted}
        assert "drain_deadline" in ev.values()


class TestJournal:
    def test_round_trip_completed_and_unfinished(self, tmp_path):
        from paddle_tpu.inference import RequestJournal, read_journal

        path = tmp_path / "j.jsonl"
        eng = _engine()
        eng.journal = RequestJournal(str(path))
        done_id = eng.submit([1, 2, 3], max_new_tokens=3)
        out = eng.run(max_steps=50)
        # leave one request mid-flight: submit + a single step only
        live_id = eng.submit([4, 5, 6], max_new_tokens=8)
        eng.step()
        state = read_journal(str(path))
        assert state["completed"] == {done_id: out[done_id]}
        assert state["outcomes"][done_id] == "completed"
        unfinished = {s["id"]: s for s in state["unfinished"]}
        assert set(unfinished) == {live_id}
        spec = unfinished[live_id]
        assert spec["prompt"] == [4, 5, 6]
        assert spec["max_new_tokens"] == 8
        assert len(spec["gen"]) >= 1                  # the stepped token

    def test_compaction_drops_finished_keeps_live_with_gen(self,
                                                           tmp_path):
        from paddle_tpu.inference import RequestJournal, read_journal

        path = tmp_path / "j.jsonl"
        j = RequestJournal(str(path), max_bytes=400)
        fin = ServeRequest([1, 2], max_new_tokens=2, request_id="fin")
        live = ServeRequest([3, 4], max_new_tokens=9, request_id="live")
        j.record_submit(fin)
        j.record_submit(live)
        j.record_finish("fin", "completed", tokens=[7, 8])
        for t in range(40):                           # overflow max_bytes
            j.record_step([("live", t)])
        assert j.stats()["compactions"] >= 1
        state = read_journal(str(path))
        unfinished = {s["id"]: s for s in state["unfinished"]}
        assert set(unfinished) == {"live"}
        assert unfinished["live"]["gen"] == list(range(40))
        # finished history was dropped by the rewrite, but the pre-
        # compaction fin is irrelevant to recovery: live set is right
        j.close()

    def test_write_failure_degrades_never_raises(self, tmp_path):
        from paddle_tpu.inference import RequestJournal

        reset_fault_events()
        eng = _engine()
        eng.journal = RequestJournal(str(tmp_path / "j.jsonl"))
        with FaultInjector({"serve.journal_write": ("raise", 0)}):
            eng.submit([1, 2, 3], max_new_tokens=3)
            out = eng.run(max_steps=50)               # must not raise
        assert len(out) == 1                          # serving unharmed
        assert eng.journal.errors > 0
        assert fault_events().get("journal_errors", 0) >= 1

    def test_torn_tail_and_garbage_lines_skipped(self, tmp_path):
        from paddle_tpu.inference import read_journal

        path = tmp_path / "j.jsonl"
        path.write_text(
            '{"k":"sub","id":"a","prompt":[1],"max_new_tokens":2,'
            '"eos_id":null,"deadline_s":null}\n'
            'not json at all\n'
            '{"k":"tok","toks":[["a",5]]}\n'
            '{"k":"sub","id":"b","pro')                # torn by SIGKILL
        state = read_journal(str(path))
        assert [s["id"] for s in state["unfinished"]] == ["a"]
        assert state["unfinished"][0]["gen"] == [5]

    def test_recover_reads_env_journal_and_resumes_token_exact(
            self, tmp_path):
        from paddle_tpu.inference import RequestJournal

        path = str(tmp_path / "j.jsonl")
        want = _engine().generate([PROMPTS[0]], max_new_tokens=5)[0]
        # simulate the crashed life: journal a submit + 2 emitted tokens
        crashed = ServeRequest(PROMPTS[0], max_new_tokens=5,
                               request_id="r0")
        j = RequestJournal(path)
        j.record_submit(crashed)
        j.record_step([("r0", want[0])])
        j.record_step([("r0", want[1])])
        j.close()
        eng = _engine(journal_max_bytes=4 << 20)
        eng.journal = RequestJournal(path)
        rec = eng.recover()
        assert rec["resumed"] == ["r0"]
        out = eng.run(max_steps=60)
        assert out["r0"] == want                      # token-exact


def test_deadline_eviction_races_concurrent_submit():
    """A second thread submits continuously while the decode thread
    evicts deadline-expired requests under an injected per-step delay:
    no exception may leak from either side, and the block pool must be
    fully conserved afterwards (the CL001/CL007 sites the scheduler
    lock closes)."""
    import threading

    eng = _engine(max_running=2, max_queued=4)
    errors = []

    def _submitter():
        from paddle_tpu.inference import OverloadedError

        for i in range(60):
            try:
                eng.submit([1 + i % 7, 2], max_new_tokens=2,
                           deadline_s=0.01 if i % 3 else 5.0)
            except OverloadedError:
                pass
            except Exception as e:  # noqa: BLE001 — the assertion
                errors.append(e)
            time.sleep(0.001)

    t = threading.Thread(target=_submitter)
    t.start()
    with FaultInjector({"serve.step": ("delay", 0.005)}):
        while t.is_alive():
            eng.run(max_steps=5)
    t.join()
    eng.run(max_steps=400)
    assert not errors
    assert not eng.scheduler.has_work()
    assert eng.cache.blocks_in_use() == 0
    assert eng.cache.blocks_free() == eng.cache.config.num_blocks


def test_run_returns_promptly_when_nothing_runnable():
    """Queued work that cannot be planned (every KV allocation failing)
    must make run() yield promptly — not spin to max_steps."""
    eng = _engine()
    eng.submit([1, 2, 3], max_new_tokens=3)
    with FaultInjector({"serve.kv_alloc": ("raise", 0)}):
        t0 = time.perf_counter()
        out = eng.run(max_steps=10_000)
        dt = time.perf_counter() - t0
    assert out == {}
    assert dt < 5.0                                   # yielded, no spin
    assert eng.scheduler.has_work()                   # work survives
    out = eng.run(max_steps=60)                       # injector lifted
    assert len(out) == 1
