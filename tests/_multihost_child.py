"""Child process for the two-process multi-host test.

Usage: python _multihost_child.py <process_id> <coordinator_port>

Each process owns 2 virtual CPU devices; jax.distributed rendezvous
makes a 4-device global world. The child drives the framework's own
multi-host surface: init_distributed_env -> world_mesh -> a jitted
data-parallel step whose gradient sync crosses the process boundary.
"""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

import functools

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import env


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    env.init_distributed_env(f"127.0.0.1:{port}", 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()
    assert env.rank() == pid

    mesh = env.world_mesh("dp")
    env.set_mesh(mesh)

    # global batch sharded over all 4 devices (2 per process): each
    # process supplies ITS addressable shards; grad sync = psum over dp
    # crossing the process boundary
    n, dim = 8, 4
    full_x = np.arange(n * dim, dtype=np.float32).reshape(n, dim) / 10.0
    full_y = np.linspace(0.0, 1.0, n, dtype=np.float32)
    sharding = NamedSharding(mesh, P("dp", None))
    x = jax.make_array_from_callback(
        (n, dim), sharding, lambda idx: full_x[idx])
    y = jax.make_array_from_callback(
        (n,), NamedSharding(mesh, P("dp")), lambda idx: full_y[idx])
    w = jnp.zeros((dim,), jnp.float32)  # replicated params

    @functools.partial(jax.jit,
                       out_shardings=NamedSharding(mesh, P(None)))
    def step(w, x, y):
        def loss(w):
            return jnp.mean((x @ w - y) ** 2)
        g = jax.grad(loss)(w)
        return w - 0.1 * g

    for _ in range(10):
        w = step(w, x, y)
    # every process must hold identical, globally-synced params
    w_local = np.asarray(jax.device_get(w))

    # reference: single-process full-batch gradient descent
    w_ref = np.zeros((dim,), np.float32)
    for _ in range(10):
        g = 2.0 / n * full_x.T @ (full_x @ w_ref - full_y)
        w_ref = w_ref - 0.1 * g
    np.testing.assert_allclose(w_local, w_ref, rtol=1e-5, atol=1e-6)

    # explicit collective over the process boundary: psum of rank+1
    from paddle_tpu.core.jax_compat import shard_map

    @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                       out_specs=P())  # replicated: fetchable everywhere
    def total(v):
        return jax.lax.psum(jnp.sum(v), "dp")

    contrib = jax.make_array_from_callback(
        (4,), NamedSharding(mesh, P("dp")),
        lambda idx: np.arange(4, dtype=np.float32)[idx] + 1.0)
    tot = float(jax.device_get(total(contrib)))
    assert tot == 10.0, tot

    _hybrid_dp_tp(pid)

    print(f"MULTIHOST_OK pid={pid} procs={jax.process_count()} "
          f"devices={jax.device_count()}", flush=True)


def _hybrid_dp_tp(pid):
    """dp=2 (one process per dp rank) x tp=2 (local devices): a
    megatron column+row parallel MLP under shard_map — the tp psum rides
    'local ICI', the dp gradient sum crosses the process boundary."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.jax_compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()).reshape(2, 2)  # [dp, tp]
    mesh = Mesh(devs, ("dp", "tp"))

    B, H, F = 8, 4, 8
    rng = np.random.RandomState(7)
    full_x = rng.randn(B, H).astype(np.float32)
    w1 = rng.randn(H, F).astype(np.float32)   # column-sharded over tp
    w2 = rng.randn(F, H).astype(np.float32)   # row-sharded over tp

    x = jax.make_array_from_callback(
        (B, H), NamedSharding(mesh, P("dp", None)), lambda i: full_x[i])
    w1s = jax.device_put(w1, NamedSharding(mesh, P(None, "tp")))
    w2s = jax.device_put(w2, NamedSharding(mesh, P("tp", None)))

    import functools

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("dp", None), P(None, "tp"),
                                 P("tp", None)),
                       out_specs=P())
    def grad_norm(xb, w1b, w2b):
        def loss(w1b, w2b):
            h = jnp.tanh(xb @ w1b)          # [b_local, F/tp]
            y = h @ w2b                      # partial sum over tp
            y = jax.lax.psum(y, "tp")        # row-parallel reduction
            return jnp.sum(y ** 2)

        l, (g1, g2) = jax.value_and_grad(loss, argnums=(0, 1))(w1b, w2b)
        # dp-mean of the loss and grads crosses the process boundary;
        # the per-tp-shard grads are reduced to a replicated scalar via
        # a tp psum so the output is provably replicated on both axes
        l = jax.lax.pmean(l, "dp")
        g_norm = jax.lax.psum(jnp.sum(jax.lax.pmean(g1, "dp") ** 2), "tp")
        return l + 0.0 * g_norm

    got = float(jax.device_get(grad_norm(x, w1s, w2s)))

    # single-process oracle
    h = np.tanh(full_x @ w1)
    y = h @ w2
    per_dp = np.array([np.sum(y[:4] ** 2), np.sum(y[4:] ** 2)])
    want = float(per_dp.mean())
    np.testing.assert_allclose(got, want, rtol=1e-5)
    print(f"HYBRID_OK pid={pid} loss={got:.4f}", flush=True)


if __name__ == "__main__":
    main()
