"""Child process for the two-process multi-host test.

Usage: python _multihost_child.py <process_id> <coordinator_port>

Each process owns 2 virtual CPU devices; jax.distributed rendezvous
makes a 4-device global world. The child drives the framework's own
multi-host surface: init_distributed_env -> world_mesh -> a jitted
data-parallel step whose gradient sync crosses the process boundary.
"""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

import functools

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import env


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    env.init_distributed_env(f"127.0.0.1:{port}", 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()
    assert env.rank() == pid

    mesh = env.world_mesh("dp")
    env.set_mesh(mesh)

    # global batch sharded over all 4 devices (2 per process): each
    # process supplies ITS addressable shards; grad sync = psum over dp
    # crossing the process boundary
    n, dim = 8, 4
    full_x = np.arange(n * dim, dtype=np.float32).reshape(n, dim) / 10.0
    full_y = np.linspace(0.0, 1.0, n, dtype=np.float32)
    sharding = NamedSharding(mesh, P("dp", None))
    x = jax.make_array_from_callback(
        (n, dim), sharding, lambda idx: full_x[idx])
    y = jax.make_array_from_callback(
        (n,), NamedSharding(mesh, P("dp")), lambda idx: full_y[idx])
    w = jnp.zeros((dim,), jnp.float32)  # replicated params

    @functools.partial(jax.jit,
                       out_shardings=NamedSharding(mesh, P(None)))
    def step(w, x, y):
        def loss(w):
            return jnp.mean((x @ w - y) ** 2)
        g = jax.grad(loss)(w)
        return w - 0.1 * g

    for _ in range(10):
        w = step(w, x, y)
    # every process must hold identical, globally-synced params
    w_local = np.asarray(jax.device_get(w))

    # reference: single-process full-batch gradient descent
    w_ref = np.zeros((dim,), np.float32)
    for _ in range(10):
        g = 2.0 / n * full_x.T @ (full_x @ w_ref - full_y)
        w_ref = w_ref - 0.1 * g
    np.testing.assert_allclose(w_local, w_ref, rtol=1e-5, atol=1e-6)

    # explicit collective over the process boundary: psum of rank+1
    from jax import shard_map

    @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                       out_specs=P())  # replicated: fetchable everywhere
    def total(v):
        return jax.lax.psum(jnp.sum(v), "dp")

    contrib = jax.make_array_from_callback(
        (4,), NamedSharding(mesh, P("dp")),
        lambda idx: np.arange(4, dtype=np.float32)[idx] + 1.0)
    tot = float(jax.device_get(total(contrib)))
    assert tot == 10.0, tot

    print(f"MULTIHOST_OK pid={pid} procs={jax.process_count()} "
          f"devices={jax.device_count()}", flush=True)


if __name__ == "__main__":
    main()
