"""Structured span tracing (runtime/tracing.py): span nesting and
threading, Chrome Trace Event Format validity, the kill-switch parity
contract (no trace dir => byte-identical dispatch stats), span<->counter
reconciliation, crash durability of the bounded buffer, and the
2-subprocess cluster trace merge. Plus the satellites that ride the
same PR: the OTLP exporter and the data-wait gauge."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import dispatch
from paddle_tpu.runtime import telemetry as T
from paddle_tpu.runtime import tracing
from paddle_tpu.runtime.resilience import fault_events

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _trace_hygiene():
    """Every test leaves the process with tracing OFF and span stats
    empty — other test files must keep seeing the untraced fast path."""
    yield
    tracing.set_enabled(False)
    tracing.reset_span_stats()


@pytest.fixture
def tdir(tmp_path):
    d = str(tmp_path / "trace")
    tracing.configure(d)
    tracing.reset_span_stats()
    return d


# ---------------------------------------------------------------------------
# span semantics

def test_span_nesting_and_self_time(tdir):
    with tracing.span("outer", "phase_a"):
        time.sleep(0.02)
        with tracing.span("inner", "phase_b"):
            time.sleep(0.01)
    st = tracing.span_stats()
    outer = st[("phase_a", "outer")]
    inner = st[("phase_b", "inner")]
    assert outer["count"] == 1 and inner["count"] == 1
    assert inner["total_s"] <= outer["total_s"]
    # the child's time is subtracted from the parent's SELF time
    assert outer["self_s"] == pytest.approx(
        outer["total_s"] - inner["total_s"], abs=1e-6)
    # phase totals aggregate self time per category (no double count)
    ph = tracing.phase_totals()
    assert ph["phase_a"] == pytest.approx(outer["self_s"])
    assert ph["phase_b"] == pytest.approx(inner["total_s"])


def test_threaded_spans_carry_distinct_tids(tdir):
    def work(name):
        with tracing.span(name, "threaded"):
            time.sleep(0.005)

    threads = [threading.Thread(target=work, args=(f"w{i}",), name=f"w{i}")
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with tracing.span("main_thread", "threaded"):
        pass
    tracing.flush()
    events = tracing.read_trace(tracing.trace_path())
    xs = [e for e in events if e.get("ph") == "X"
          and e.get("cat") == "threaded"]
    assert len(xs) == 3
    assert len({e["tid"] for e in xs}) == 3  # one lane per thread
    names = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert {"w0", "w1"} <= names


def test_chrome_trace_format_validity(tdir):
    with tracing.span("op", "cat", detail="x"):
        pass
    tracing.instant("marker", "cat")
    tracing.flush()
    # unterminated (crash-shaped) file: tolerant reader + validator
    events = tracing.validate_trace(tracing.trace_path())
    assert any(e["ph"] == "X" and e["name"] == "op" for e in events)
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in events)
    # clean close terminates the array: the file is then STRICT JSON
    tracing.close()
    with open(tracing.trace_path()) as f:
        parsed = json.loads(f.read())
    assert isinstance(parsed, list) and len(parsed) >= len(events)
    x = next(e for e in parsed if e.get("ph") == "X")
    assert isinstance(x["ts"], int) and isinstance(x["dur"], int)
    assert x["dur"] >= 0 and {"name", "cat", "pid", "tid"} <= set(x)


def test_trace_file_event_cap_drops_not_grows(tmp_path):
    d = str(tmp_path / "capped")
    tracing.configure(d, max_events=10)
    for i in range(50):
        tracing.emit_span(f"s{i}", "cap", time.time(), 0.001)
    tracing.flush()
    tr = tracing.tracer()
    # +1: the process metadata record is inserted at flush, outside the
    # buffered-event cap
    assert tr.emitted <= 11
    assert tr.dropped >= 40


def test_bounded_buffer_flushes_at_threshold(tmp_path):
    d = str(tmp_path / "buf")
    tracing.configure(d, flush_every=10)
    for i in range(3):
        tracing.emit_span(f"s{i}", "buf", time.time(), 0.001)
    # below the bound: nothing but the array opener on disk yet
    assert len(tracing.read_trace(tracing.trace_path())) == 0
    tracing.flush()
    assert len([e for e in tracing.read_trace(tracing.trace_path())
                if e.get("ph") == "X"]) == 3


# ---------------------------------------------------------------------------
# kill switch: no trace dir => byte-identical dispatch behavior

def _dispatch_workload():
    dispatch.reset_dispatch_stats(clear_caches=True)
    t = paddle.to_tensor(
        np.random.RandomState(0).randn(8, 8).astype(np.float32))
    for _ in range(4):
        paddle.tanh(paddle.matmul(t, t)).sum()
    ds = dispatch.dispatch_stats()
    return (
        {k: ds["forward"][k] for k in ("hits", "misses", "bypasses",
                                       "unkeyable", "warming", "fallbacks")},
        {k: (v["hits"], v["misses"], v["retraces"])
         for k, v in ds["per_op"].items()},
    )


def test_kill_switch_parity_dispatch_stats(tmp_path):
    assert not tracing.enabled()  # no trace dir configured => off
    baseline = _dispatch_workload()
    tracing.configure(str(tmp_path / "trace"))
    tracing.reset_span_stats()
    traced = _dispatch_workload()
    # the tracer observed real dispatch activity...
    assert any(c == "dispatch" for c, _ in tracing.span_stats())
    tracing.set_enabled(False)
    killed = _dispatch_workload()
    # ...and neither tracing nor the kill switch changed ONE counter
    assert baseline == traced == killed


def test_set_enabled_and_configure_rearm(tmp_path):
    from paddle_tpu.runtime import diagnostics

    tracing.configure(str(tmp_path / "t"))
    assert tracing.enabled()
    assert tracing.set_enabled(False) is True
    assert not tracing.enabled()
    # file tracing off, but the flight-recorder tap (diagnostics, on by
    # default) still consumes spans — only with BOTH layers off does
    # span() collapse to the shared null span (the one-falsy-check path)
    prev_diag = diagnostics.set_enabled(False)
    try:
        assert tracing.span("x", "y") is tracing._NULL
    finally:
        diagnostics.set_enabled(prev_diag)
    assert tracing.span("x", "y") is not tracing._NULL  # tap re-armed
    tracing.set_enabled(True)
    assert tracing.enabled()
    tracing.set_enabled(False)
    tracing.configure(str(tmp_path / "t"))  # explicit configure re-arms
    assert tracing.enabled()


# ---------------------------------------------------------------------------
# reconciliation: the timeline and the counters cannot disagree

def test_reconcile_exact_pair_and_mismatch_detection(tmp_path):
    T.reset_metrics()
    dispatch.reset_dispatch_stats()
    tracing.configure(str(tmp_path / "trace"))
    tracing.reset_span_stats()
    h = T.histogram("paddle_tpu_step_seconds", "train step wall time")
    for dt in (0.25, 0.125):
        h.observe(dt)
        tracing.emit_span("train_step", "step", time.time() - dt, dt)
    ok, rep = tracing.reconcile_with_metrics()
    assert rep["step"]["ok"] and not rep["step"]["skipped"]
    assert rep["step"]["span_s"] == pytest.approx(0.375)
    # an extra span the histogram never saw must be CAUGHT
    tracing.emit_span("train_step", "step", time.time(), 0.5)
    ok2, rep2 = tracing.reconcile_with_metrics()
    assert not rep2["step"]["ok"]
    T.reset_metrics()


def test_fit_reconciles_spans_with_metrics(tmp_path):
    """A real (tiny) fit: dispatch run spans, step spans and data-wait
    spans must all reconcile with dispatch_stats()/the histograms."""
    import paddle_tpu.nn as nn

    T.reset_metrics()
    # clear_caches so the warm-up ops re-enter through the miss path
    # (hit-path sampling only attributes ops with a stats entry)
    dispatch.reset_dispatch_stats(clear_caches=True)
    prev_sample = dispatch.set_op_sample_every(1)
    prev_warm = dispatch.set_warmup_count(1)
    tracing.configure(str(tmp_path / "trace"))
    tracing.reset_span_stats()
    try:
        rng = np.random.RandomState(0)
        t = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
        for _ in range(4):
            paddle.tanh(paddle.matmul(t, t)).sum()
        x = rng.rand(32, 4).astype(np.float32)
        y = (x @ rng.rand(4, 1).astype(np.float32)).astype(np.float32)
        net = nn.Linear(4, 1)
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.Adam(0.05, parameters=net.parameters()),
            nn.MSELoss())
        model.fit([x, y], epochs=1, batch_size=16, verbose=0,
                  callbacks=[paddle.callbacks.TelemetryCallback(
                      str(tmp_path / "tel"), export_every=100,
                      scalars=False)])
        ok, rep = tracing.reconcile_with_metrics()
        assert ok, rep
        for key in ("dispatch_run", "step", "data_wait"):
            assert not rep[key]["skipped"], rep
            assert rep[key]["span_n"] > 0
    finally:
        dispatch.set_op_sample_every(prev_sample)
        dispatch.set_warmup_count(prev_warm)
        T.reset_metrics()
        # drop this test's sampled per-op stats too: a later test (any
        # file order) asserting registry<->per_op agreement must not
        # inherit half of this test's traffic
        dispatch.reset_dispatch_stats()


def test_data_wait_gauge_and_span(tmp_path):
    import paddle_tpu.nn as nn

    T.reset_metrics()
    tracing.configure(str(tmp_path / "trace"))
    tracing.reset_span_stats()
    model = paddle.Model(nn.Linear(2, 2))
    model._note_data_wait(0.033, time.time() - 0.033)
    snap = T.snapshot()
    hist = snap["paddle_tpu_data_wait_seconds"]["series"][0]
    assert hist["count"] == 1 and hist["sum"] == pytest.approx(0.033)
    gauge = snap["paddle_tpu_data_wait_seconds_last"]["series"][0]
    assert gauge["value"] == pytest.approx(0.033)
    assert tracing.span_stats()[("data", "data_wait")]["count"] == 1
    T.reset_metrics()


# ---------------------------------------------------------------------------
# crash durability: kill -9 loses at most the unflushed tail

def test_kill9_child_loses_at_most_unflushed_tail(tmp_path):
    from paddle_tpu.testing.faults import faults_env

    child_dir = str(tmp_path / "crash")
    kill_after = 25
    env = faults_env({"tracing.child": ("kill", kill_after)})
    env.update({"TRACING_CHILD_DIR": child_dir, "JAX_PLATFORMS": "cpu"})
    p = subprocess.run(
        [sys.executable, os.path.join(HERE, "_tracing_child.py"), "kill"],
        env=env, capture_output=True, text=True, timeout=120)
    assert p.returncode == -9, (p.returncode, p.stderr)
    files = [f for f in os.listdir(child_dir)
             if f.startswith(tracing.TRACE_BASENAME_PREFIX)]
    assert len(files) == 1
    path = os.path.join(child_dir, files[0])
    # the unterminated file still parses (Perfetto's tolerance)
    events = tracing.read_trace(path)
    idx = sorted(e["args"]["i"] for e in events
                 if e.get("ph") == "X" and e.get("cat") == "test")
    # a contiguous prefix survived, missing at most the buffered tail
    # (flush_every=4 in the child, plus the metadata records sharing
    # the buffer)
    assert idx == list(range(1, len(idx) + 1))
    assert len(idx) >= kill_after - 8
    assert len(idx) <= kill_after


# ---------------------------------------------------------------------------
# cluster merge: two ranks, one timeline

@pytest.mark.filterwarnings("ignore::UserWarning")
def test_cluster_merge_carries_both_ranks_spans(tmp_path):
    store = str(tmp_path / "store")
    os.makedirs(store, exist_ok=True)
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "PADDLE_TPU_CLUSTER_DIR": store,
                    "PADDLE_TPU_CLUSTER_RANK": str(rank),
                    "PADDLE_TPU_CLUSTER_WORLD": "2"})
        env.pop("PADDLE_TPU_TRACE", None)  # the child configures itself
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(HERE, "_tracing_child.py"),
             "rank"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, (rank, out, err)
    from paddle_tpu.distributed.coordination import DirectoryStore

    out = T.merge_cluster(DirectoryStore(store))
    assert out["trace_path"] and os.path.exists(out["trace_path"])
    assert out["trace_events"] > 0
    events = tracing.read_trace(out["trace_path"], strict=True)
    by_rank = {}
    for e in events:
        if e.get("ph") == "X":
            by_rank.setdefault(e["pid"], set()).add(e.get("cat"))
    assert 0 in by_rank and 1 in by_rank
    for rank in (0, 1):
        assert {"compute", "checkpoint", "coord"} <= by_rank[rank], by_rank
    # the tail is byte-offset persisted: a second merge with no new
    # writes appends NOTHING (the PR-8 O(new bytes) contract)
    out2 = T.merge_cluster(DirectoryStore(store))
    assert out2["trace_events"] == 0
    state = json.load(open(os.path.join(store, "merged",
                                        "merge_state.json")))
    assert state["traces"]  # per-file offsets persisted


def test_configure_same_dir_updates_flush_bound(tmp_path):
    """Re-configuring the SAME dir must honor a newly requested buffer
    bound — a caller asking for flush_every=1 believes in per-span
    durability (review finding: the early return silently kept 64)."""
    d = str(tmp_path / "t")
    tracing.configure(d, flush_every=50)
    tracing.configure(d, flush_every=1)
    tracing.emit_span("s", "c", time.time(), 0.001)
    assert len([e for e in tracing.read_trace(tracing.trace_path())
                if e.get("ph") == "X"]) == 1  # on disk without flush()


def test_rank_assigned_before_flush_lanes_spans(tmp_path):
    """The pid lane is stamped at FLUSH time: spans emitted before the
    cluster rank was assigned but flushed after (the real-multihost
    bring-up order, where set_rank happens at fit start) must land on
    the rank lane, with the lane named by process metadata."""
    prev = T.set_rank(None)
    try:
        tracing.configure(str(tmp_path / "t"))
        tracing.emit_span("early", "c", time.time(), 0.001)  # buffered
        T.set_rank(5)
        tracing.flush()
        evs = tracing.read_trace(tracing.trace_path())
        assert next(e for e in evs if e.get("name") == "early")["pid"] == 5
        meta = [e for e in evs if e.get("name") == "process_name"]
        assert meta and meta[-1]["args"]["rank"] == 5
    finally:
        T.set_rank(prev)


def test_reopen_after_clean_close_stays_valid(tmp_path):
    """Re-opening a cleanly terminated trace file must strip the '{}]'
    terminator before appending — otherwise every later span lands
    past the ']' and the file fails validation forever (review
    finding)."""
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    tracing.configure(a)
    tracing.emit_span("one", "c", time.time(), 0.001)
    path = tracing.trace_path()
    tracing.configure(b)   # closes + terminates a's file
    tracing.configure(a)   # same host+pid => same path, reopened
    assert tracing.trace_path() == path
    tracing.emit_span("two", "c", time.time(), 0.001)
    tracing.flush()
    names = [e["name"] for e in tracing.validate_trace(path)
             if e.get("ph") == "X"]
    assert "one" in names and "two" in names
    tracing.close()
    with open(path) as f:
        json.loads(f.read())  # strict JSON again after the re-close


def test_trace_merge_detects_replaced_file(tmp_path):
    """Every trace file's first line is the identical '[' opener, so
    the incarnation signature must key on the SECOND line (process
    metadata): a recycled-pid relaunch that rewrites the same path
    LONGER than the old offset must re-tail from 0, not silently skip
    the new incarnation's earliest spans."""
    d = tmp_path / "traces"
    d.mkdir()
    p = str(d / "trace-h-1.json")

    def write(pid, n):
        with open(p, "w") as f:
            f.write("[\n")
            f.write(json.dumps({"ph": "M", "name": "process_name",
                                "pid": pid, "tid": 0, "ts": 0,
                                "args": {"os_pid": pid}}) + ",\n")
            for i in range(n):
                f.write(json.dumps({"ph": "X", "name": f"s{pid}-{i}",
                                    "cat": "t", "ts": i, "dur": 1,
                                    "pid": pid, "tid": 1}) + ",\n")

    write(100, 2)
    out = str(tmp_path / "merged.json")
    state = {}
    assert T._merge_trace_files([p], out, state) == 3
    write(200, 6)  # new incarnation, same path, GROWS past the offset
    T._merge_trace_files([p], out, state)
    evs = tracing.read_trace(out)
    assert any(e.get("name") == "s200-0" for e in evs), \
        "earliest span of the replaced incarnation was dropped"


# ---------------------------------------------------------------------------
# OTLP exporter (satellite)

def test_otlp_push_roundtrip():
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        body = None
        seen_path = None

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            type(self).body = self.rfile.read(n)
            type(self).seen_path = self.path
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):  # noqa: A002
            pass

    T.reset_metrics()
    T.counter("paddle_tpu_train_steps_total", "steps").inc(7)
    T.histogram("paddle_tpu_step_seconds", "steps").observe(0.05)
    T.gauge("paddle_tpu_loss", "loss").set(1.5)
    srv = HTTPServer(("127.0.0.1", 0), Handler)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    try:
        ok = T.push_otlp(f"http://127.0.0.1:{srv.server_port}")
    finally:
        srv.shutdown()
    assert ok
    assert Handler.seen_path == "/v1/metrics"
    payload = json.loads(Handler.body)
    metrics = {m["name"]: m for m in payload["resourceMetrics"][0]
               ["scopeMetrics"][0]["metrics"]}
    c = metrics["paddle_tpu_train_steps_total"]["sum"]
    assert c["isMonotonic"] and c["aggregationTemporality"] == 2
    assert c["dataPoints"][0]["asDouble"] == 7.0
    # cumulative series carry a start timestamp (collectors need it
    # for reset detection across process restarts)
    assert int(c["dataPoints"][0]["startTimeUnixNano"]) <= \
        int(c["dataPoints"][0]["timeUnixNano"])
    h = metrics["paddle_tpu_step_seconds"]["histogram"]["dataPoints"][0]
    assert h["count"] == "1" and float(h["sum"]) == pytest.approx(0.05)
    assert "startTimeUnixNano" in h
    assert len(h["bucketCounts"]) == len(h["explicitBounds"]) + 1
    g = metrics["paddle_tpu_loss"]["gauge"]["dataPoints"][0]
    assert g["asDouble"] == 1.5
    T.reset_metrics()


def test_otlp_failure_degrades_to_fault_event():
    before = fault_events().get("push_failures", 0)
    with pytest.warns(UserWarning, match="OTLP export"):
        ok = T.push_otlp("http://127.0.0.1:9")  # discard port: refused
    assert ok is False
    assert fault_events().get("push_failures", 0) == before + 1


def test_otlp_opt_in_only():
    assert T.otlp_endpoint() is None or "PADDLE_TPU_TELEMETRY_OTLP" in \
        os.environ
    assert T.push_otlp(None) in (False,) if T.otlp_endpoint() is None \
        else True


# ---------------------------------------------------------------------------
# schema: the new vocabulary is frozen

def test_new_names_in_schema():
    s = T.schema()
    assert "paddle_tpu_data_wait_seconds" in s["metrics"]
    assert "paddle_tpu_data_wait_seconds_last" in s["metrics"]
    assert "trace_merge" in s["events"]
