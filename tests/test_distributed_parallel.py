"""DataParallel grad-sync parity, TP layer math parity, ZeRO shard shapes
(SURVEY §4 test_distributed_*)."""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn

N = 8


@pytest.fixture(autouse=True)
def _clean_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _small_net():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _loss_and_grads(net, x, y):
    loss = nn.functional.mse_loss(net(x), y)
    loss.backward()
    grads = {k: np.asarray(p.grad._value) for k, p in net.named_parameters()}
    net.clear_gradients()
    return float(loss), grads


def test_data_parallel_grad_parity():
    """Same global batch: dp-sharded run must produce identical grads to the
    single-device run (XLA inserts the grad all-reduce)."""
    x_np = np.random.RandomState(0).randn(N * 4, 16).astype(np.float32)
    y_np = np.random.RandomState(1).randn(N * 4, 4).astype(np.float32)

    net = _small_net()
    ref_loss, ref_grads = _loss_and_grads(
        net, paddle.to_tensor(x_np), paddle.to_tensor(y_np))

    dist.init_parallel_env()
    net2 = _small_net()  # same seed -> same init
    dp = dist.DataParallel(net2)
    x = paddle.to_tensor(x_np)
    out = dp(x)
    # input really got dp-sharded
    shard = x._value.sharding
    assert isinstance(shard, NamedSharding) and shard.spec[0] == "dp"
    assert len(x._value.sharding.device_set) == N
    loss = nn.functional.mse_loss(out, paddle.to_tensor(y_np))
    loss.backward()
    assert abs(float(loss) - ref_loss) < 1e-5
    for k, p in net2.named_parameters():
        np.testing.assert_allclose(np.asarray(p.grad._value), ref_grads[k],
                                   rtol=1e-5, atol=1e-6)
    with dp.no_sync():
        pass  # API parity
    assert dp.scale_loss(loss) is loss


def test_column_row_parallel_match_dense():
    """Column->Row parallel pair == plain two-layer MLP, with weights
    actually tp-sharded on the mesh."""
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))
    dist.set_mesh(mesh)
    paddle.seed(11)
    col = dist.ColumnParallelLinear(16, 64, gather_output=False)
    row = dist.RowParallelLinear(64, 8, input_is_parallel=True)
    paddle.seed(11)
    d1 = nn.Linear(16, 64)
    d2 = nn.Linear(64, 8)
    np.testing.assert_allclose(np.asarray(col.weight._value),
                               np.asarray(d1.weight._value))

    # weights carry tp shardings: col out-dim, row in-dim
    assert col.weight._value.sharding.spec == P(None, "tp")
    assert row.weight._value.sharding.spec == P("tp", None)

    x = paddle.randn([4, 16])
    y_mp = row(col(x))
    y_dense = d2(d1(x))
    np.testing.assert_allclose(np.asarray(y_mp._value),
                               np.asarray(y_dense._value), rtol=1e-4,
                               atol=1e-5)

    # grads flow + match dense
    loss = (y_mp * y_mp).mean()
    loss.backward()
    loss_d = (y_dense * y_dense).mean()
    loss_d.backward()
    np.testing.assert_allclose(np.asarray(col.weight.grad._value),
                               np.asarray(d1.weight.grad._value), rtol=1e-4,
                               atol=1e-5)


def test_vocab_parallel_embedding_match_dense():
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    dist.set_mesh(mesh)
    paddle.seed(3)
    vp = dist.VocabParallelEmbedding(64, 16)
    paddle.seed(3)
    dense = nn.Embedding(64, 16)
    assert vp.weight._value.sharding.spec == P("tp", None)
    ids = paddle.to_tensor(np.array([[1, 5, 63], [0, 2, 7]], np.int64))
    np.testing.assert_allclose(np.asarray(vp(ids)._value),
                               np.asarray(dense(ids)._value), rtol=1e-6)


def test_parallel_cross_entropy_match_dense():
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    dist.set_mesh(mesh)
    logits = paddle.randn([4, 32])
    labels = paddle.to_tensor(np.array([1, 5, 8, 31], np.int64))
    pce = dist.ParallelCrossEntropy()
    ref = nn.functional.cross_entropy(logits, labels, reduction="none")
    np.testing.assert_allclose(np.asarray(pce(logits, labels)._value),
                               np.asarray(ref._value), rtol=1e-5)


def test_group_sharded_stage3_shard_shapes_and_parity():
    """ZeRO: params + opt states land dp-sharded (1/N per device); training
    still reaches the same loss as unsharded."""
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    x_np = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    y_np = np.random.RandomState(1).randn(32, 4).astype(np.float32)

    def run(sharded):
        dist.set_mesh(None)
        net = _small_net()
        opt = paddle.optimizer.Adam(parameters=net.parameters(),
                                    learning_rate=0.01)
        if sharded:
            dist.init_parallel_env()
            net, opt = group_sharded_parallel(net, opt, level="p_g_os")
            w = net[0].weight._value
            # parameter is REALLY sharded: one 1/N shard per device
            assert len(w.sharding.device_set) == N
            shard_shape = w.sharding.shard_shape(w.shape)
            assert np.prod(shard_shape) == np.prod(w.shape) // N
        losses = []
        for _ in range(5):
            loss = nn.functional.mse_loss(
                net(paddle.to_tensor(x_np)), paddle.to_tensor(y_np))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses

    ref = run(False)
    shd = run(True)
    np.testing.assert_allclose(shd, ref, rtol=1e-4, atol=1e-5)


def test_sync_batch_norm_forward():
    """Regression: SyncBatchNorm must work outside shard_map (plain path)
    and psum stats under a live dp axis."""
    sbn = nn.SyncBatchNorm(4)
    y = sbn(paddle.randn([2, 4, 8, 8]))
    assert tuple(y.shape) == (2, 4, 8, 8)


def test_hcg_groups_have_axis_and_correct_devices():
    from paddle_tpu.distributed import fleet

    st = fleet.DistributedStrategy()
    st.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    fleet.init(strategy=st)
    hcg = fleet.get_hybrid_communicate_group()
    gdp = hcg.get_data_parallel_group()
    gtp = hcg.get_model_parallel_group()
    assert gdp.axes == "dp" and gtp.axes == "tp"
    assert gdp.ranks == [0, 4]  # dp-slice of the (2,2,2) mesh, not [0,1]
    assert gtp.ranks == [0, 1]
    # cached: repeated getters return the same group (no recompiles)
    assert gdp is hcg.get_data_parallel_group()


def test_broadcast_rejects_nonmember_src():
    dist.init_parallel_env()
    g = dist.new_group([0, 1])
    with pytest.raises(ValueError, match="not a member"):
        dist.broadcast(paddle.to_tensor(np.ones((2, 1), np.float32)),
                       src=5, group=g)


def test_fleet_init_builds_hybrid_mesh():
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 2, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = dist.get_mesh()
    assert dict(mesh.shape) == {"dp": 2, "pp": 2, "sp": 1, "tp": 2}
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert fleet.worker_num() == N
    assert fleet.is_first_worker()

    # distributed_model wraps with DataParallel when dp > 1
    m = fleet.distributed_model(_small_net())
    assert isinstance(m, dist.DataParallel)
