"""Keyword-signature parity vs the reference source.

Namespace parity says a NAME exists; this goes one level deeper: for a
spread of everyday APIs, every parameter name a reference-era script
could pass BY KEYWORD must be accepted by our implementation (either a
real parameter or **kwargs). Parameter names are parsed from the
reference's def statements with ast — no reference import needed.
"""
import ast
import inspect
import os

import pytest

import paddle_tpu as paddle

REF = "/root/reference/python/paddle"

# this suite PARSES the reference checkout; on hosts without the
# read-only mount it must skip, not fail 39 times on open()
pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF),
    reason="reference source not mounted at /root/reference")

# (reference file, def name, our callable)
CASES = [
    ("tensor/math.py", "add", paddle.add),
    ("tensor/math.py", "multiply", paddle.multiply),
    ("tensor/math.py", "sum", paddle.sum),
    ("tensor/math.py", "cumsum", paddle.cumsum),
    ("tensor/math.py", "clip", paddle.clip),
    ("tensor/search.py", "argsort", paddle.argsort),
    ("tensor/search.py", "topk", paddle.topk),
    ("tensor/search.py", "nonzero", paddle.nonzero),
    ("tensor/manipulation.py", "concat", paddle.concat),
    ("tensor/manipulation.py", "split", paddle.split),
    ("tensor/manipulation.py", "squeeze", paddle.squeeze),
    ("tensor/manipulation.py", "gather", paddle.gather),
    ("tensor/manipulation.py", "scatter", paddle.scatter),
    ("tensor/creation.py", "arange", paddle.arange),
    ("tensor/creation.py", "full", paddle.full),
    ("fluid/layers/tensor.py", "linspace", paddle.linspace),
    ("tensor/linalg.py", "matmul", paddle.matmul),
    ("tensor/linalg.py", "norm", paddle.norm),
    ("tensor/random.py", "uniform", paddle.uniform),
    ("tensor/random.py", "randint", paddle.randint),
    ("nn/functional/loss.py", "cross_entropy",
     paddle.nn.functional.cross_entropy),
    ("nn/functional/loss.py", "mse_loss", paddle.nn.functional.mse_loss),
    ("nn/functional/common.py", "dropout", paddle.nn.functional.dropout),
    ("nn/functional/common.py", "linear", paddle.nn.functional.linear),
    ("nn/functional/common.py", "interpolate",
     paddle.nn.functional.interpolate),
    ("nn/functional/conv.py", "conv2d", paddle.nn.functional.conv2d),
    ("nn/functional/pooling.py", "max_pool2d",
     paddle.nn.functional.max_pool2d),
    ("nn/functional/pooling.py", "avg_pool2d",
     paddle.nn.functional.avg_pool2d),
    ("nn/functional/norm.py", "layer_norm", paddle.nn.functional.layer_norm),
    ("nn/functional/norm.py", "batch_norm", paddle.nn.functional.batch_norm),
    ("nn/functional/activation.py", "softmax",
     paddle.nn.functional.softmax),
    ("nn/functional/input.py", "embedding", paddle.nn.functional.embedding),
    ("tensor/stat.py", "mean", paddle.mean),
    ("tensor/stat.py", "std", paddle.std),
    ("tensor/stat.py", "quantile", paddle.quantile),
    ("tensor/logic.py", "equal", paddle.equal),
    ("tensor/logic.py", "allclose", paddle.allclose),
    ("fft.py", "fft", paddle.fft.fft),
    ("tensor/einsum.py", "einsum", paddle.einsum),
]


def _ref_params(path, fn_name):
    """Parameter names of the last `def fn_name` in the reference file."""
    with open(os.path.join(REF, path)) as f:
        tree = ast.parse(f.read())
    found = None
    for node in tree.body:  # module level only, source order (last wins)
        if isinstance(node, ast.FunctionDef) and node.name == fn_name:
            found = node
    if found is None:
        return None
    a = found.args
    names = ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
             + [p.arg for p in a.kwonlyargs])
    return [n for n in names if n != "name"]  # name= is universally dropped


@pytest.mark.parametrize("path,fn_name,ours", CASES,
                         ids=[c[1] for c in CASES])
def test_keywords_accepted(path, fn_name, ours):
    ref_names = _ref_params(path, fn_name)
    if ref_names is None:
        pytest.skip(f"{fn_name} not found in reference {path}")
    sig = inspect.signature(ours)
    has_var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                     for p in sig.parameters.values())
    ours_names = set(sig.parameters)
    missing = [n for n in ref_names if n not in ours_names]
    assert has_var_kw or not missing, (
        f"{fn_name}: reference keywords {missing} not accepted "
        f"(ours: {sorted(ours_names)})")
