"""Differential fuzz: the eager autograd tape vs jax.grad on random DAGs.

Each case builds a random op DAG over a pool of leaf tensors and runs
the SAME paddle ops twice: (a) eagerly with the tape and .backward(),
and (b) under jax.grad with the tape off (paddle.no_grad) — leaf
gradients must match. This exercises tape topology (fan-out, value
reuse, broadcast, reduction, transpose) far beyond the hand-written
autograd tests, and pins the two AD regimes to each other.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle

_BINARY = [
    ("add", lambda a, b: a + b),
    ("sub", lambda a, b: a - b),
    ("mul", lambda a, b: a * b),
    ("matmul", lambda a, b: paddle.matmul(a, b) * 0.3),
]
_UNARY = [
    ("tanh", lambda a: paddle.tanh(a)),
    ("sigmoid", lambda a: paddle.nn.functional.sigmoid(a)),
    ("exp_scaled", lambda a: paddle.exp(a * 0.1)),
    ("square", lambda a: a * a),
    ("neg", lambda a: -a),
    ("transpose", lambda a: paddle.transpose(a, [1, 0])),
    ("mean_bcast", lambda a: paddle.mean(a, -1, keepdim=True) + a * 0),
]
_OPS = dict(_BINARY + _UNARY)


def _build_case(seed):
    """(leaf_arrays, program): program entries (op_name, input_indices)
    append new values to the pool; later ops can reuse ANY value."""
    rng = np.random.RandomState(seed)
    n_leaves = rng.randint(2, 5)
    n = rng.randint(2, 5)
    shape = (n, n)  # square so transpose composes with elementwise ops
    leaves = [rng.randn(*shape).astype(np.float32) for _ in range(n_leaves)]
    program = []
    n_vals = n_leaves
    for _ in range(rng.randint(3, 10)):
        if rng.rand() < 0.5:
            name, _ = _BINARY[rng.randint(len(_BINARY))]
            ins = (rng.randint(n_vals), rng.randint(n_vals))
        else:
            name, _ = _UNARY[rng.randint(len(_UNARY))]
            ins = (rng.randint(n_vals),)
        program.append((name, ins))
        n_vals += 1
    return leaves, program


def _run(program, vals):
    vals = list(vals)
    for name, ins in program:
        vals.append(_OPS[name](*[vals[i] for i in ins]))
    out = None  # mix every value into the loss so no node is dead
    for v in vals:
        term = paddle.mean(v * v)
        out = term if out is None else out + term
    return out


@pytest.mark.parametrize("seed", range(25))
def test_tape_matches_jax_grad(seed):
    leaves, program = _build_case(seed)

    # (a) eager tape
    p_leaves = []
    for a in leaves:
        t = paddle.to_tensor(a)
        t.stop_gradient = False
        p_leaves.append(t)
    _run(program, p_leaves).backward()
    got = [np.asarray(t.grad.numpy()) for t in p_leaves]

    # (b) jax.grad over the same paddle ops, tape off
    from paddle_tpu.core.tensor import Tensor

    def pure_fn(arrs):
        with paddle.no_grad():
            return _run(program, [Tensor(a) for a in arrs])._value

    want = jax.grad(pure_fn)([jnp.asarray(a) for a in leaves])
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_allclose(g, np.asarray(w), rtol=1e-4, atol=1e-5,
                                   err_msg=f"seed={seed} leaf={i}")


def test_create_graph_nodes_do_not_collide_in_bwd_cache():
    """Two create_graph vjp nodes share vjp_call's code object and carry
    their per-node state in default args — the backward cache must key on
    defaults too, or the second node silently reuses the first node's
    compiled vjp (sin's second-order grad where exp's is required)."""
    x = paddle.to_tensor(np.float32(0.7))
    x.stop_gradient = False
    g1 = paddle.grad(paddle.sin(x), [x], create_graph=True)[0]
    g2 = paddle.grad(paddle.exp(x), [x], create_graph=True)[0]
    total = g1 + g2
    total.backward()
    # d/dx (cos x + e^x) = -sin x + e^x
    want = -np.sin(0.7) + np.exp(0.7)
    np.testing.assert_allclose(float(x.grad), want, rtol=1e-5)


@pytest.mark.parametrize("seed", range(8))
def test_static_matches_dygraph_on_random_dags(seed):
    """The same random op DAG must produce identical results eagerly and
    through the static Program/Executor (deferred trace -> one XLA
    program)."""
    leaves, program = _build_case(seed + 100)

    eager = _run(program, [paddle.to_tensor(a) for a in leaves])
    eager_val = float(eager)

    paddle.enable_static()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            feeds = []
            for i, a in enumerate(leaves):
                feeds.append(paddle.static.data(
                    f"x{i}", list(a.shape), "float32"))
            out = _run(program, feeds)
        exe = paddle.static.Executor()
        (got,) = exe.run(main,
                         feed={f"x{i}": a for i, a in enumerate(leaves)},
                         fetch_list=[out])
    finally:
        paddle.disable_static()
    np.testing.assert_allclose(float(np.asarray(got)), eager_val,
                               rtol=1e-4, atol=1e-5,
                               err_msg=f"seed={seed}")
