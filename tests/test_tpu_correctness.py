"""@pytest.mark.tpu — on-chip correctness for the perf-path kernels.

Auto-skips unless the live jax backend is a real TPU. To run on the
chip: `PADDLE_TPU_TEST_REAL_CHIP=1 python -m pytest tests/ -m tpu -q`
(the env flag stops conftest from forcing the CPU platform; never do
this while another TPU client — e.g. bench.py — is queued or running:
one client session at a time, per docs/PERF.md rules of engagement).

bench.py's `tpu_correctness` config executes the same checks in-process
while it holds the chip grant, so these assertions normally get their
hardware evidence from the bench JSON rather than from pytest.
"""
import jax
import pytest

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(jax.default_backend() != "tpu",
                       reason="needs a real TPU backend"),
]


@pytest.fixture(scope="module")
def checks():
    from paddle_tpu.testing.tpu_checks import run_tpu_checks

    return run_tpu_checks()


def _assert_group(checks, prefix):
    keys = [k for k in checks
            if k.startswith("tpu_check_" + prefix) and k.endswith("_ok")]
    errors = {k: checks.get(k.replace("_ok", "_err")) for k in keys
              if not checks[k]}
    hard = {k: v for k, v in checks.items()
            if k.startswith("tpu_check_" + prefix) and k.endswith("_error")}
    assert keys and not errors and not hard, (errors, hard)


def test_flash_attention_on_chip(checks):
    _assert_group(checks, "flash_f32")
    _assert_group(checks, "flash_bf16")
    _assert_group(checks, "flash_masked")
    _assert_group(checks, "flash_bwd")


def test_flash_tilings_on_chip(checks):
    _assert_group(checks, "flash_tiling")


def test_ring_attention_on_chip(checks):
    _assert_group(checks, "ring")


def test_blockwise_ce_on_chip(checks):
    _assert_group(checks, "blockwise_ce")


def test_int8_matmul_on_chip(checks):
    _assert_group(checks, "int8")
