"""Child process for the serving robustness acceptance
(tools/serve_chaos_smoke.py).

Modes (argv[1]):
  overload — warm the engine, measure its sustainable service rate,
             then offer 4x that rate through tools/loadgen.py with a
             tiny admission queue: the engine must SHED (OverloadedError
             + `overloaded` outcomes + serve_sheds faults), keep queue
             depth bounded, keep admitted-request TTFT bounded, and
             exit clean. ISSUE 20 additions: /requestz must parse
             under scrape WHILE the storm runs, the last-1m TTFT
             window must move, shed requests must carry full sampled
             traces (access records + `serve/request/*` detail
             spans), and access-log aggregates must reconcile exactly
             with the outcome counters and latency histograms
             (tracing.reconcile_with_metrics; the parent sets
             PADDLE_TPU_TRACE so span stats are live).
  chaos    — degradation contracts under injected faults: a
             serve.step delay must evict ONLY deadline-burdened
             requests; serve.kv_alloc failures must starve (not crash)
             the loop, and the engine must serve normally once the
             injector lifts.
  drain    — a serving loop with install_signal_drain(); prints READY,
             keeps serving until the parent SIGTERMs it. Expected exit:
             rc=-SIGTERM with a `sigterm_drain` postmortem bundle whose
             extra carries the drain report.
  baseline — fixed workload, uninterrupted; saves the shape manifest;
             prints outputs (the token-exactness reference).
  kill     — same workload + request journal; the parent's
             PADDLE_TPU_FAULT_INJECT=serve.step=kill:N SIGKILLs the
             process mid-decode (rc=-9; nothing printed).
  recover  — warm-starts from the manifest, recovers the kill pass's
             journal, finishes the workload; prints recovered/resumed
             outputs + compile metrics (parent asserts token-exact vs
             baseline with ZERO fresh compiles).

Env (set by the parent): JAX_PLATFORMS=cpu,
PADDLE_TPU_COMPILE_CACHE_DIR, PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S=0,
SERVE_MANIFEST, CHAOS_JOURNAL; drain mode also gets
PADDLE_TPU_DIAGNOSTICS_DIR; kill mode PADDLE_TPU_FAULT_INJECT.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from paddle_tpu.core import dispatch  # noqa: E402
from paddle_tpu.inference import (  # noqa: E402
    OverloadedError,
    ServeConfig,
    ServingEngine,
    TinyServeModel,
)
from paddle_tpu.runtime import telemetry, warmup  # noqa: E402
from paddle_tpu.runtime.resilience import (  # noqa: E402
    FaultInjector,
    fault_events,
)

mode = sys.argv[1]

PROMPTS = [[1, 2, 3, 4, 5], [7, 8], [3, 1, 4, 1, 5, 9], [11, 13],
           [2, 4, 6], [9, 9, 1]]
NEW_TOKENS = 6


def _mk(max_queued=64, max_queue_wait_s=None, journal=None):
    model = TinyServeModel(vocab=32, dim=8, layers=2, heads=2, ffn=16,
                           seed=0)
    cfg = ServeConfig(max_running=3, token_budget=8, block_size=4,
                      num_blocks=16, max_blocks_per_seq=4,
                      max_queued=max_queued,
                      max_queue_wait_s=max_queue_wait_s)
    return ServingEngine(model, cfg, journal=journal)


def _outcomes():
    fam = telemetry.snapshot().get("paddle_tpu_serve_requests_total") or {}
    return {tuple(s["labels"].values())[0]: s["value"]
            for s in fam.get("series", [])}


def _emit(out):
    print(json.dumps(out))


if mode == "overload":
    import threading
    import urllib.request

    from tools.loadgen import run_load
    from paddle_tpu.runtime import diagnostics, tracing

    dispatch.set_warmup_count(1)
    eng = _mk(max_queued=8, max_queue_wait_s=2.0)
    eng.generate(PROMPTS[:3], max_new_tokens=3)  # compile warmup
    t0 = time.perf_counter()
    eng.generate(PROMPTS, max_new_tokens=3)
    sustainable_rps = len(PROMPTS) / (time.perf_counter() - t0)
    rate = 4.0 * sustainable_rps
    # ISSUE 20: statusz live during the storm; a third thread scrapes
    # /requestz under fire, and the last-1m window is snapshotted
    # before/after so the rolling view provably MOVES
    diagnostics.start_statusz(0)
    addr = diagnostics.statusz_address()
    w1_before = eng.windows.snapshot()["1m"]
    requestz = {"scrapes": 0, "parsed": 0, "in_flight_max": 0}
    stop = threading.Event()

    def _scrape_requestz():
        url = f"http://{addr[0]}:{addr[1]}/requestz"
        while not stop.wait(0.1):
            requestz["scrapes"] += 1
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    doc = json.loads(resp.read().decode("utf-8"))
                requestz["parsed"] += 1
                for e in doc.get("engines") or []:
                    requestz["in_flight_max"] = max(
                        requestz["in_flight_max"],
                        len(e.get("in_flight") or []))
            except Exception:  # noqa: BLE001 — a missed scrape is data
                pass

    th = threading.Thread(target=_scrape_requestz, daemon=True)
    if addr is not None:
        th.start()
    report = run_load(eng, rate_rps=rate, duration_s=2.0,
                      prompt_lens=(2, 4), new_tokens=(2, 4), seed=1,
                      hard_wall_s=90.0)
    stop.set()
    if addr is not None:
        th.join(timeout=5.0)
    w1_after = eng.windows.snapshot()["1m"]
    rec_ok, rec_report = tracing.reconcile_with_metrics()
    shed_recs = [r for r in eng.access.recent(256)
                 if r.get("outcome") == "overloaded"]
    detail_spans = {k[1]: int(v["count"])
                    for k, v in tracing.span_stats().items()
                    if k[1].startswith("request/")}
    report.pop("records", None)  # bounded child JSON
    _emit({"report": report, "outcomes": _outcomes(),
           "serve_sheds": fault_events().get("serve_sheds", 0),
           "rate_rps": rate, "sustainable_rps": sustainable_rps,
           "max_queued": 8,
           "requestz": requestz,
           "w1_before": w1_before, "w1_after": w1_after,
           "reconcile_ok": rec_ok,
           "reconcile_bad": {k: v for k, v in rec_report.items()
                             if not v.get("ok", True)},
           "shed_records": len(shed_recs),
           "shed_records_sampled": sum(
               1 for r in shed_recs if r.get("sampled")),
           "detail_spans": detail_spans})

elif mode == "chaos":
    dispatch.set_warmup_count(1)
    # phase 1: a wedged-slow step must evict ONLY the deadline-burdened
    # requests; patient ones finish
    eng = _mk()
    eng.generate(PROMPTS[:2], max_new_tokens=2)  # compile warmup
    patient, impatient = [], []
    with FaultInjector({"serve.step": ("delay", 0.05)}):
        for i, p in enumerate(PROMPTS):
            rid = eng.submit(p, max_new_tokens=3,
                             deadline_s=0.02 if i % 2 else 30.0)
            (impatient if i % 2 else patient).append(rid)
        done1 = eng.run(max_steps=300)
    stats1 = eng.scheduler.stats()
    # phase 2: every KV allocation fails — the loop must starve
    # WITHOUT crashing or spinning, then serve normally post-injector
    eng2 = _mk(max_queued=4, max_queue_wait_s=None)
    eng2.generate(PROMPTS[:1], max_new_tokens=2)
    shed2 = 0
    with FaultInjector({"serve.kv_alloc": ("raise", 0)}):
        for p in PROMPTS:
            try:
                eng2.submit(p, max_new_tokens=3)
            except OverloadedError:
                shed2 += 1
        t0 = time.perf_counter()
        starved = eng2.run(max_steps=300)
        starve_wall = time.perf_counter() - t0
    done2 = eng2.run(max_steps=300)
    post = eng2.generate([[5, 6, 7]], max_new_tokens=3)[0]
    _emit({"phase1": {"completed": sorted(done1),
                      "patient": patient, "impatient": impatient,
                      "deadline_faults":
                          fault_events().get("request_deadline", 0),
                      "stats": stats1},
           "phase2": {"starved_completed": len(starved),
                      "starve_wall_s": starve_wall, "shed": shed2,
                      "completed": len(done2),
                      "stats": eng2.scheduler.stats()},
           "post_recovery_tokens": post})

elif mode == "drain":
    dispatch.set_warmup_count(1)
    eng = _mk(journal=os.environ.get("CHAOS_JOURNAL"))
    eng.install_signal_drain(deadline_s=30.0)
    eng.generate(PROMPTS[:2], max_new_tokens=2)  # compile warmup
    print("READY", flush=True)
    while True:  # a real server: keep work flowing until told to stop
        if not eng.scheduler.has_work():
            for p in PROMPTS:
                try:
                    eng.submit(p, max_new_tokens=8)
                except OverloadedError:
                    break
        eng.run(max_steps=50)  # drains + exits in here on SIGTERM
        time.sleep(0.005)

elif mode == "baseline":
    dispatch.set_warmup_count(1)
    eng = _mk()
    for p in PROMPTS:
        eng.submit(p, max_new_tokens=NEW_TOKENS)
    out = eng.run(max_steps=500)
    warmup.save_manifest(os.environ["SERVE_MANIFEST"])
    _emit({"outputs": out, "steps": eng.steps})

elif mode == "kill":
    # PADDLE_TPU_FAULT_INJECT=serve.step=kill:N (parent) SIGKILLs the
    # process mid-decode; everything after run() is unreachable
    dispatch.set_warmup_count(1)
    eng = _mk(journal=os.environ["CHAOS_JOURNAL"])
    for p in PROMPTS:
        eng.submit(p, max_new_tokens=NEW_TOKENS)
    out = eng.run(max_steps=500)
    _emit({"outputs": out, "survived": True})

elif mode == "recover":
    pre = warmup.precompile(os.environ["SERVE_MANIFEST"])
    dispatch.set_warmup_count(1)
    eng = _mk(journal=os.environ["CHAOS_JOURNAL"])
    rec = eng.recover()
    post = eng.run(max_steps=500)
    comp = dispatch.dispatch_stats()["compile"]
    _emit({"recovered_completed": rec["completed"],
           "resumed": rec["resumed"], "skipped": rec["skipped"],
           "post_outputs": post, "precompile": pre,
           "fresh_compiles": comp["fresh_compiles"],
           "disk_cache_hits": comp["disk_cache_hits"]})

else:
    raise SystemExit(f"unknown mode {mode!r}")
