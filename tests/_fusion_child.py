"""Child process for the trace-fusion warm-start round trip
(test_fusion.py / tools/fusion_smoke.py).

Modes (argv[1]):
  record — run the shared fused workload cold, save the shape manifest
           (which now carries fused-trace entries), print one JSON line
           of compile + fusion metrics.
  replay — precompile the manifest (installing the fused traces AOT),
           run the same workload, print metrics. With a warm shared
           compile-cache dir the parent asserts ZERO fresh XLA compiles
           and fused-cache misses == 0 — the first flush of every trace
           shape is a plain cache hit.

Env (set by the parent): JAX_PLATFORMS=cpu,
PADDLE_TPU_COMPILE_CACHE_DIR, PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S=0,
FUSION_MANIFEST.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
from paddle_tpu.core import dispatch, fusion  # noqa: E402
from paddle_tpu.runtime import warmup  # noqa: E402

mode = sys.argv[1]
manifest_path = os.environ["FUSION_MANIFEST"]


def workload():
    """A deterministic fused train loop: fwd + backward + cotangent
    accumulation + SGD step, identical in both processes."""
    dispatch.set_warmup_count(1)
    fusion.set_fusion(True)
    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    w = paddle.to_tensor(rng.randn(16, 4).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])
    losses = []
    for _ in range(3):
        h = F.relu(paddle.matmul(x, w) + b)
        loss = ((h - y) * (h - y)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._value)))
    return losses


pre = None
if mode == "replay":
    pre = warmup.precompile(manifest_path)
losses = workload()
if mode == "record":
    warmup.save_manifest(manifest_path)
ds = dispatch.dispatch_stats()
comp = ds["compile"]
fus = ds["fusion"]
out = {
    "losses": losses,
    "fresh_compiles": comp["fresh_compiles"],
    "disk_cache_hits": comp["disk_cache_hits"],
    "fused_hits": fus["fused"]["hits"],
    "fused_misses": fus["fused"]["misses"],
    "recorded_ops": fus["recorded_ops"],
    "flushes": fus["flushes"],
    "eager_replays": fus["eager_replays"],
}
if pre is not None:
    out["precompile"] = pre
print(json.dumps(out))
