"""Trace-fusion for eager dispatch (core/fusion.py): deferred op
recording, single fused-program flushes, fingerprint caching, flush
reasons, kill-switch equivalence, and the warm-start fused-trace round
trip."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core import dispatch, fusion
from paddle_tpu.core.autograd import apply
from paddle_tpu.core.fusion import LazyArray

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fusion_isolation():
    """Every test starts fusion-off with clean fusion caches/stats and
    leaves the process the same way (other test files must see today's
    per-op path untouched)."""
    fusion.set_fusion(False)
    prev_warm = dispatch.set_warmup_count(1)
    dispatch.reset_dispatch_stats(clear_caches=True)
    yield
    fusion.flush()
    fusion.set_fusion(False)
    dispatch.set_warmup_count(prev_warm)
    dispatch.reset_dispatch_stats(clear_caches=True)


def _mlp_step(x, y, params, opt):
    h = F.relu(paddle.matmul(x, params[0]) + params[1])
    p = paddle.matmul(h, params[2]) + params[3]
    loss = ((p - y) * (p - y)).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return loss


def _make_fixture():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    prng = np.random.RandomState(1)
    params = [
        paddle.to_tensor(prng.randn(16, 32).astype(np.float32) * 0.1,
                         stop_gradient=False),
        paddle.to_tensor(np.zeros(32, np.float32), stop_gradient=False),
        paddle.to_tensor(prng.randn(32, 4).astype(np.float32) * 0.1,
                         stop_gradient=False),
        paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False),
    ]
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=params)
    return x, y, params, opt


# ---------------------------------------------------------------------------
# numerical parity

def test_forward_parity_eager_vs_fused():
    rng = np.random.RandomState(0)
    xv = rng.randn(4, 8).astype(np.float32)

    def chain():
        t = paddle.to_tensor(xv)
        u = paddle.tanh(paddle.matmul(t, t.T))
        v = F.softmax(u + 0.5, axis=-1)
        return np.asarray((v * v).sum()._value)

    eager = chain()
    fusion.set_fusion(True)
    fused = chain()
    np.testing.assert_allclose(eager, fused, rtol=1e-6)


def test_grad_parity_paddle_grad():
    rng = np.random.RandomState(0)
    xv = rng.randn(4, 8).astype(np.float32)
    wv = rng.randn(8, 3).astype(np.float32)

    def run():
        xt = paddle.to_tensor(xv, stop_gradient=False)
        wt = paddle.to_tensor(wv, stop_gradient=False)
        h = paddle.tanh(paddle.matmul(xt, wt))
        loss = (h * h).mean()
        gs = paddle.grad(loss, [xt, wt])
        return [np.asarray(g._value) for g in gs] + [np.asarray(loss._value)]

    eager = run()
    fusion.set_fusion(True)
    fused = run()
    for a, b in zip(eager, fused):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_train_loop_parity_backward_and_optimizer():
    """Full fwd+bwd+SGD trajectory: fused numerics must match per-op to
    allclose tolerance over several steps (accumulated divergence would
    show here)."""

    def run(steps=5):
        x, y, params, opt = _make_fixture()
        losses = []
        for _ in range(steps):
            loss = _mlp_step(x, y, params, opt)
            losses.append(float(np.asarray(loss._value)))
        return losses, [np.asarray(p._value) for p in params]

    eager_losses, eager_params = run()
    fusion.set_fusion(True)
    fused_losses, fused_params = run()
    np.testing.assert_allclose(eager_losses, fused_losses, rtol=1e-5)
    for a, b in zip(eager_params, fused_params):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# laziness + materialization points

def test_ops_are_deferred_and_materialize_on_host_access():
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((3, 3), np.float32))
    u = paddle.tanh(t)
    assert type(u._value) is LazyArray
    assert u._value._concrete is None  # nothing executed yet
    # shape/dtype queries stay eager — no flush
    assert u.shape == [3, 3]
    assert u._value._concrete is None
    # host access flushes
    val = float(u.sum())
    assert abs(val - 9 * np.tanh(1.0)) < 1e-5
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["flushes"].get("materialize") == 1
    assert fs["recorded_ops"] >= 2


def test_lazy_raw_array_surface():
    """Library code touches `Tensor._value` with the raw jax.Array API
    (`.at[...]`, slicing, device_put, attribute probes) — every one of
    those must be a materialization point, not an AttributeError
    (review finding: __setitem__'s no-grad path crashed on `.at`)."""
    import jax

    fusion.set_fusion(True)
    t = paddle.to_tensor(np.zeros((3, 3), np.float32))
    u = paddle.tanh(t + 1.0)
    assert isinstance(u._value, LazyArray)
    # Tensor.__setitem__ (no-grad path) -> lazy.at[idx].set(v)
    u[0] = 7.0
    got = np.asarray(u._value)
    want = np.full((3, 3), np.tanh(1.0), np.float32)
    want[0] = 7.0
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # raw slicing of a pending value
    v = paddle.tanh(t + 2.0)
    np.testing.assert_allclose(np.asarray(v._value[1:, :2]),
                               np.full((2, 2), np.tanh(2.0)), rtol=1e-6)
    # device_put + raw operators on a pending value
    w = paddle.tanh(t + 3.0)
    moved = jax.device_put(w._value)
    np.testing.assert_allclose(np.asarray(moved),
                               np.full((3, 3), np.tanh(3.0)), rtol=1e-6)
    x = paddle.tanh(t + 1.0)
    np.testing.assert_allclose(np.asarray(x._value * 2 - 1.0),
                               want * 0 + 2 * np.tanh(1.0) - 1.0, rtol=1e-6)
    # the full numeric operator protocol materializes (floordiv, mod,
    # abs, bitwise on ints) — eager-valid expressions must not raise
    z = paddle.to_tensor(np.full((2, 2), 7, np.int32)) + 0
    assert isinstance(z._value, LazyArray)
    np.testing.assert_array_equal(np.asarray(z._value // 2), 3)
    np2 = paddle.to_tensor(np.full((2, 2), 7, np.int32)) + 0
    np.testing.assert_array_equal(np.asarray(np2._value % 4), 3)
    neg = paddle.to_tensor(np.full((2, 2), -3.0, np.float32)) + 0.0
    np.testing.assert_allclose(np.asarray(abs(neg._value)), 3.0)
    msk = paddle.to_tensor(np.full((2, 2), 6, np.int32)) + 0
    np.testing.assert_array_equal(np.asarray(msk._value & 4), 4)


def test_lazy_comparisons_are_elementwise():
    """Default identity __eq__ silently returned False for equal-valued
    pending arrays (paddle.equal_all goes through `x._value ==
    y._value`); comparisons must materialize like every other raw-array
    protocol."""
    fusion.set_fusion(True)
    a = paddle.to_tensor(np.ones((2, 3), np.float32))
    x = paddle.tanh(a)
    y = paddle.tanh(a + 0.0)
    assert bool(paddle.equal_all(x, y))
    assert isinstance(x._value, LazyArray) or x._value is not None
    lt = paddle.tanh(a)._value < paddle.tanh(a + 1.0)._value
    assert bool(np.asarray(lt).all())


def test_user_shape_error_does_not_demote_op():
    """An ordinary shape mismatch must raise to the caller WITHOUT
    permanently demoting a shared op (matmul) from fusion."""
    fusion.set_fusion(True)
    a = paddle.to_tensor(np.ones((2, 3), np.float32))
    b = paddle.to_tensor(np.ones((4, 5), np.float32))
    with pytest.raises(Exception):
        float(paddle.matmul(a, b).sum())
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["demotions"] == 0, fs
    # a well-shaped matmul afterwards still fuses
    c = paddle.matmul(a, paddle.to_tensor(np.ones((3, 2), np.float32)))
    assert isinstance(c._value, LazyArray)
    np.testing.assert_allclose(np.asarray(c._value), np.full((2, 2), 3.0))


def test_cross_thread_materialization():
    """A placeholder recorded on one thread and materialized on another
    must flush safely (flush_trace is the cross-thread entry point) —
    the reader sees the patched value, never a spurious RuntimeError."""
    import threading

    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((4, 4), np.float32))
    outs = [paddle.tanh(t + i) for i in range(8)]
    results = {}

    def reader(i):
        results[i] = float(np.asarray(outs[i]._value).sum())

    threads = [threading.Thread(target=reader, args=(i,)) for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    for i in range(8):
        assert abs(results[i] - 16 * np.tanh(1.0 + i)) < 1e-4, (i, results)


def test_lazy_array_protocols():
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    u = paddle.tanh(t)
    assert len(u) == 2
    assert u.ndim == 2 and u.size == 6
    np.testing.assert_allclose(np.asarray(u._value),
                               np.tanh(np.arange(6).reshape(2, 3)),
                               rtol=1e-6)
    s = (paddle.to_tensor(np.float32(2.0)) * 3).sum()
    assert int(s) == 6 and bool(s)


# ---------------------------------------------------------------------------
# flush-reason classes

def test_flush_reason_unjittable_forced():
    fusion.set_fusion(True)

    @dispatch.non_jittable
    def host_side(v):
        return v * 2  # raw python operator: needs concrete inputs

    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    u = paddle.tanh(t)
    r = apply(host_side, u)
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["flushes"].get("unjittable") == 1, fs["flushes"]
    np.testing.assert_allclose(np.asarray(r._value), np.tanh(1.0) * 2,
                               rtol=1e-6)


def test_flush_reason_suspend_both_layers():
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    u = paddle.tanh(t)
    with dispatch.suspend():  # the hapi whole-step path
        # BOTH layers are suspended: a backward inside the region must
        # not defer either (record_call checks fusion's counter only)
        w = paddle.to_tensor(np.ones((2, 2), np.float32),
                             stop_gradient=False)
        loss = (paddle.tanh(w) * paddle.tanh(w)).mean()
        loss.backward()
        assert not isinstance(w._grad._value, LazyArray)
        w.clear_grad()
    assert u._value._concrete is not None
    v = paddle.tanh(t)
    with fusion.suspend():
        w = paddle.tanh(t)  # recorded nowhere: per-op path
        assert not isinstance(w._value, LazyArray)
    assert v._value._concrete is not None
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["flushes"].get("suspend") == 2, fs["flushes"]


def test_flush_reason_max_len_safety_valve(monkeypatch):
    monkeypatch.setattr(fusion, "_max_ops", 4)
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    acc = paddle.tanh(t)
    for _ in range(9):
        acc = paddle.tanh(acc)
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["flushes"].get("max_len", 0) >= 2, fs["flushes"]
    assert fs["max_trace_len"] <= 4
    # values still correct through the splits
    expect = np.ones((2, 2))
    for _ in range(10):
        expect = np.tanh(expect)
    np.testing.assert_allclose(np.asarray(acc._value), expect, rtol=1e-6)


def test_runtime_demotion_learns_unsafe_op():
    """An op whose abstract evaluation fails (host materialization)
    is learned fusion-unsafe with a fault event, runs eagerly with
    correct values, and future sightings are flush points."""
    from paddle_tpu.runtime.resilience import fault_events

    fusion.set_fusion(True)

    def host_materializing(v):
        return v * int(v.sum())  # int() on a tracer: eval_shape raises

    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    before = fault_events().get("fusion_demotions", 0)
    r = apply(host_materializing, t)
    np.testing.assert_allclose(np.asarray(r._value), np.ones((2, 2)) * 4)
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["unsafe_ops"] >= 1
    assert fault_events().get("fusion_demotions", 0) == before + 1
    # second sighting: already-known unsafe -> forced flush, no re-probe
    u = paddle.tanh(t)
    apply(host_materializing, t)
    assert u._value._concrete is not None


# ---------------------------------------------------------------------------
# flush-site attribution (fuselint --verify-runtime's runtime half)

def test_flush_sites_attribute_and_reconcile():
    """Every flush is attributed to the file:line that forced it (the
    first frame outside the machinery), the per-reason site sums
    reconcile EXACTLY with the flush totals, and the steady MLP loop's
    one-per-step flush lands on the optimizer's concretize boundary."""
    fusion.set_fusion(True)
    x, y, params, opt = _make_fixture()
    for _ in range(10):
        _mlp_step(x, y, params, opt)
    fs = dispatch.dispatch_stats()["fusion"]
    sites = fs["flush_sites"]
    for reason, n in fs["flushes"].items():
        assert sum(sites.get(reason, {}).values()) == n, (reason, sites)
    mat = sites.get("materialize", {})
    assert any(s.startswith("paddle_tpu/optimizer/optimizer.py:")
               for s in mat), mat


def test_flush_site_attributes_to_user_code():
    """A host read in user code is attributed to THAT line, not to the
    Tensor/LazyArray protocol plumbing."""
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((3, 3), np.float32))
    u = paddle.tanh(t)
    float(u.sum())  # <- the forcing site
    sites = dispatch.dispatch_stats()["fusion"]["flush_sites"]
    mat = sites.get("materialize", {})
    assert len(mat) == 1
    site = next(iter(mat))
    assert site.startswith("tests/test_fusion.py:"), site


def test_flush_site_table_is_bounded():
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    src = "\n".join(f"float(paddle.tanh(t + {i}).sum())"
                    for i in range(fusion._SITE_CAP + 8))
    exec(compile(src, "synthetic_sites.py", "exec"),
         {"paddle": paddle, "t": t})
    sites = dispatch.dispatch_stats()["fusion"]["flush_sites"]
    mat = sites.get("materialize", {})
    assert len(mat) <= fusion._SITE_CAP + 1
    assert mat.get("<other>", 0) >= 8  # overflow folded, not dropped
    assert sum(mat.values()) == \
        dispatch.dispatch_stats()["fusion"]["flushes"]["materialize"]


# ---------------------------------------------------------------------------
# the lazy_* routes (ISSUE-11 triage fixes)

def test_lazy_mul_stays_in_trace():
    """`*` on a pending value records instead of flushing — gradient
    scaling (AMP unscale) would otherwise cut the fused program."""
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    u = paddle.tanh(t)
    v = u._value * 0.5            # __mul__ route
    w = 2.0 * v                   # __rmul__ route
    assert type(v) is LazyArray and type(w) is LazyArray
    assert not dispatch.dispatch_stats()["fusion"]["flushes"]
    np.testing.assert_allclose(np.asarray(w), np.tanh(1.0) * 1.0,
                               rtol=1e-6)


def test_lazy_apply_records_library_op():
    """fusion.lazy_apply: the escape hatch for raw jnp work below the
    dispatch layer — records under fusion, plain eager otherwise."""
    import jax.numpy as jnp

    def clamp01(v):
        return jnp.clip(v, 0.0, 1.0)

    # eager: no fusion, concrete in/out
    t = paddle.to_tensor(np.full((2, 2), 3.0, np.float32))
    out = fusion.lazy_apply(clamp01, t._value)
    assert not isinstance(out, LazyArray)
    np.testing.assert_allclose(np.asarray(out), 1.0)
    # fused: pending in, pending out, no flush
    fusion.set_fusion(True)
    u = paddle.tanh(t)
    out = fusion.lazy_apply(clamp01, u._value)
    assert type(out) is LazyArray
    assert not dispatch.dispatch_stats()["fusion"]["flushes"]
    np.testing.assert_allclose(np.asarray(out), np.tanh(3.0), rtol=1e-6)


def test_amp_unscale_defers_under_fusion():
    """GradScaler.unscale_ must not flush mid-step: the per-grad
    unscale ops record into the trace and the ONE sync is the
    found_inf read (regression for the raw `g * inv` + `jnp.isfinite`
    escapes fuselint FL006 flags)."""
    fusion.set_fusion(True)
    w = paddle.to_tensor(np.ones((4, 4), np.float32) * 0.1,
                         stop_gradient=False)
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w])
    loss = scaler.scale((paddle.matmul(x, w) ** 2).mean())
    loss.backward()
    scaler.step(opt)
    scaler.update()
    assert scaler._found_inf is False
    fs = dispatch.dispatch_stats()["fusion"]
    # exactly ONE flush reached the scaler path: the found_inf bool()
    # sync inside amp/__init__.py — not a per-grad site
    amp_sites = {s: n for s, n in
                 fs["flush_sites"].get("materialize", {}).items()
                 if "paddle_tpu/amp/" in s}
    assert len(amp_sites) == 1 and sum(amp_sites.values()) == 1, (
        fs["flush_sites"])
    # numerics: unscale really divided by the scale. loss =
    # mean((x @ w)^2) with x all-ones and w all-0.1: x@w entries are
    # 0.4, dL/dW = x^T (2 (x@w) / 16) = 0.2 everywhere — the UNSCALED
    # gradient, proving the recorded `g * inv` used the real inverse
    assert w._grad is not None
    g = np.asarray(fusion.concrete(w._grad._value))
    np.testing.assert_allclose(g, np.full((4, 4), 0.2, np.float32),
                               rtol=1e-5)
    opt.clear_grad()


def test_amp_unscale_parity_with_fusion_off():
    """The lazy routes must be numerically inert: same grads and same
    found_inf with fusion on and off."""

    def run():
        w = paddle.to_tensor(np.ones((4, 4), np.float32) * 0.1,
                             stop_gradient=False)
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
        loss = scaler.scale((paddle.matmul(x, w) ** 2).mean())
        loss.backward()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w])
        scaler.unscale_(opt)
        g = np.asarray(fusion.concrete(w._grad._value))
        return g, scaler._found_inf

    g_off, inf_off = run()
    fusion.set_fusion(True)
    g_on, inf_on = run()
    np.testing.assert_allclose(g_off, g_on, rtol=1e-6)
    assert inf_off == inf_on is False


def test_amp_unscale_detects_inf_under_fusion():
    fusion.set_fusion(True)
    w = paddle.to_tensor(np.ones((2, 2), np.float32),
                         stop_gradient=False)
    w._grad = paddle.to_tensor(
        np.array([[np.inf, 1.0], [1.0, 1.0]], np.float32))
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w])
    scaler.unscale_(opt)
    assert scaler._found_inf is True


# ---------------------------------------------------------------------------
# deferred optimizer update (PADDLE_TPU_FUSION_OPT_STEP)

@pytest.fixture
def _fused_opt_step():
    from paddle_tpu.optimizer import optimizer as opt_mod

    prev = opt_mod.set_fused_step_recording(True)
    yield
    opt_mod.set_fused_step_recording(prev)


def test_fused_opt_step_records_update_into_trace(_fused_opt_step):
    """With PADDLE_TPU_FUSION_OPT_STEP on, steps after the first defer
    the optimizer update: the flush moves from the optimizer boundary
    to the caller's host read, and the fused trace grows by the update
    node (ROADMAP item 2's one-flush-per-step shape)."""
    fusion.set_fusion(True)
    x, y, params, opt = _make_fixture()
    losses = []
    for _ in range(6):
        loss = _mlp_step(x, y, params, opt)
        losses.append(float(np.asarray(loss._value)))
    fs = dispatch.dispatch_stats()["fusion"]
    mat = fs["flush_sites"].get("materialize", {})
    opt_flushes = sum(n for s, n in mat.items()
                      if "optimizer/optimizer.py" in s)
    test_flushes = sum(n for s, n in mat.items()
                       if s.startswith("tests/test_fusion.py:"))
    # step 1 concretizes (warm-start signature on real arrays); every
    # later step flushes at THIS test's float() read instead
    assert opt_flushes == 1, mat
    assert test_flushes == 5, mat


def test_fused_opt_step_parity_stateful_optimizer(_fused_opt_step):
    """Momentum (stateful) trajectory parity: deferred update must
    match the concretizing path bit-for-tolerance over several steps,
    including the state dicts living as LazyArrays between steps."""

    def run(steps=5):
        x, y, params, _ = _make_fixture()
        opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                        parameters=params)
        losses = []
        for _ in range(steps):
            h = paddle.nn.functional.relu(
                paddle.matmul(x, params[0]) + params[1])
            p = paddle.matmul(h, params[2]) + params[3]
            loss = ((p - y) * (p - y)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._value)))
        return losses, [np.asarray(fusion.concrete(p._value))
                        for p in params]

    eager_l, eager_p = run()
    fusion.set_fusion(True)
    fused_l, fused_p = run()
    np.testing.assert_allclose(eager_l, fused_l, rtol=1e-5)
    for a, b in zip(eager_p, fused_p):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fused_opt_step_default_off():
    """The gate defaults off: without the env/runtime opt-in, step()
    concretizes at its boundary exactly as before."""
    from paddle_tpu.optimizer import optimizer as opt_mod

    assert opt_mod._fuse_step[0] is False or \
        os.environ.get("PADDLE_TPU_FUSION_OPT_STEP", "0").lower() not in (
            "0", "false", "no")
    fusion.set_fusion(True)
    x, y, params, opt = _make_fixture()
    for _ in range(3):
        _mlp_step(x, y, params, opt)
    mat = dispatch.dispatch_stats()["fusion"]["flush_sites"].get(
        "materialize", {})
    assert all("optimizer/optimizer.py" in s for s in mat), mat


# ---------------------------------------------------------------------------
# fingerprint cache

def test_steady_loop_fingerprint_hit_rate():
    """A steady training loop must replay cached fused executables:
    >= 99% fused-cache hit rate (the acceptance bar)."""
    fusion.set_fusion(True)
    x, y, params, opt = _make_fixture()
    for _ in range(150):
        _mlp_step(x, y, params, opt)
    fs = dispatch.dispatch_stats()["fusion"]
    fc = fs["fused"]
    assert fc["hits"] + fc["misses"] >= 150
    assert fc["hit_rate"] >= 0.99, fc
    # one flush per step, at the optimizer's materialization boundary
    assert fs["flushes"].get("materialize", 0) >= 150
    assert fs["avg_trace_len"] > 5


def test_eager_replay_below_warm_gate():
    """Below the warm-count gate a trace replays op-by-op eagerly —
    correct values, no fused compile."""
    dispatch.set_warmup_count(3)
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    v1 = float(paddle.tanh(t).sum())
    v2 = float(paddle.tanh(t).sum())
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["eager_replays"] == 2
    assert fs["fused"]["size"] == 0  # nothing compiled yet
    v3 = float(paddle.tanh(t).sum())  # third sighting compiles
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["fused"]["size"] == 1
    assert v1 == v2 == v3


def test_mid_replay_failure_preserves_computed_prefix(monkeypatch):
    """When the op-by-op fallback replay fails at node k, the real
    error raises at the materialization point AND values computed by
    nodes before k survive — eager mode would have produced them."""

    def broken_build(nodes, alive):
        def boom(*ext):
            raise RuntimeError("synthetic fused failure")
        return boom

    monkeypatch.setattr(fusion, "_build_fused", broken_build)
    fusion.set_fusion(True)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    ok = paddle.tanh(t)       # node 0: fine
    bad = paddle.tanh(ok)     # node 1: sabotaged below
    tr = bad._value._trace

    def sabotage(*ins):
        raise RuntimeError("synthetic node failure")

    tr.nodes[1].call = sabotage
    with pytest.raises(RuntimeError, match="synthetic node failure"):
        float(bad.sum())
    # node 0 executed before the failure: its value must be available
    np.testing.assert_allclose(np.asarray(ok._value),
                               np.tanh(np.ones((2, 2))), rtol=1e-6)
    # re-touching the never-computed tensor names the ORIGINAL cause,
    # not an opaque internal invariant
    with pytest.raises(RuntimeError, match="synthetic node failure"):
        float(bad.sum())


def test_fused_failure_falls_back_to_eager_replay(monkeypatch):
    """A fused program that fails at execution degrades to op-by-op
    replay with correct values and a fusion_fallbacks fault event."""
    from paddle_tpu.runtime.resilience import fault_events

    def broken_build(nodes, alive):
        def boom(*ext):
            raise RuntimeError("synthetic fused failure")
        return boom

    monkeypatch.setattr(fusion, "_build_fused", broken_build)
    fusion.set_fusion(True)
    before = fault_events().get("fusion_fallbacks", 0)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    val = float(paddle.tanh(t).sum())
    assert abs(val - 4 * np.tanh(1.0)) < 1e-5
    fs = dispatch.dispatch_stats()["fusion"]
    assert fs["fallbacks"] == 1
    assert fault_events().get("fusion_fallbacks", 0) == before + 1


# ---------------------------------------------------------------------------
# kill switch

def test_kill_switch_reproduces_per_op_path_exactly():
    """With fusion off (the PADDLE_TPU_EAGER_FUSION=0 default), the
    per-op path must be byte-identical to today's: same dispatch_stats
    traffic, zero fusion activity."""

    def run():
        dispatch.reset_dispatch_stats(clear_caches=True)
        x, y, params, opt = _make_fixture()
        for _ in range(3):
            _mlp_step(x, y, params, opt)
        ds = dispatch.dispatch_stats()
        fwd, bwd, fus = ds["forward"], ds["backward"], ds["fusion"]
        per_op = {k: (v["hits"], v["misses"], v["retraces"])
                  for k, v in ds["per_op"].items()}
        return ({k: fwd[k] for k in ("hits", "misses", "bypasses",
                                     "unkeyable", "warming", "fallbacks")},
                {k: bwd[k] for k in ("hits", "misses")}, per_op,
                fus["recorded_ops"], sum(fus["flushes"].values()))

    baseline = run()          # plain per-op path
    prev = fusion.set_fusion(False)  # kill switch explicitly off
    killed = run()
    fusion.set_fusion(prev)
    assert baseline == killed
    assert killed[3] == 0 and killed[4] == 0  # fusion never engaged


def test_fusion_defaults_off():
    # the env default ships fusion off: importing paddle_tpu must not
    # change eager behavior until someone opts in
    assert not fusion.fusion_enabled()


# ---------------------------------------------------------------------------
# warm start

def test_trace_manifest_round_trip_in_process():
    """A fresh fused build records a replayable trace entry; after a
    cache wipe, precompile() reinstalls it and the first flush is a
    pure cache hit."""
    from paddle_tpu.runtime import warmup

    warmup.reset_manifest_records()
    fusion.set_fusion(True)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    w = paddle.to_tensor(rng.randn(16, 4).astype(np.float32),
                         stop_gradient=False)

    def work():
        h = paddle.tanh(paddle.matmul(x, w))
        loss = (h * h).mean()
        loss.backward()
        g = np.asarray(w._grad._value)
        w.clear_grad()
        return float(np.asarray(loss._value)), g

    l0, g0 = work()
    doc = warmup.manifest()
    traces = [e for e in doc["entries"] if e.get("kind") == "trace"]
    assert traces and all(t["replayable"] for t in traces), traces

    dispatch.reset_dispatch_stats(clear_caches=True)
    stats = warmup.precompile(doc)
    assert stats["traces_precompiled"] >= 1, stats
    l1, g1 = work()
    fc = dispatch.dispatch_stats()["fusion"]["fused"]
    assert fc["hits"] >= 1 and fc["misses"] == 0, fc
    assert abs(l0 - l1) < 1e-6
    np.testing.assert_allclose(g0, g1, rtol=1e-6)
    warmup.reset_manifest_records()


def test_warm_start_round_trip_subprocess(tmp_path):
    """The acceptance proof: a SECOND PROCESS with the shared compile
    cache + shape manifest replays the recorded fused traces with zero
    fresh XLA compiles and zero fused-cache misses."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PADDLE_TPU_COMPILE_CACHE_DIR=str(tmp_path / "cache"),
        PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S="0",
        FUSION_MANIFEST=str(tmp_path / "manifest.json"),
    )
    env.pop("PADDLE_TPU_SHAPE_MANIFEST", None)

    def run(mode):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tests",
                                          "_fusion_child.py"), mode],
            env=env, cwd=REPO, capture_output=True, timeout=240)
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        return json.loads(proc.stdout.decode().strip().splitlines()[-1])

    cold = run("record")
    assert cold["recorded_ops"] > 0
    assert cold["fused_misses"] >= 1  # it did the compiles
    warm = run("replay")
    assert warm["precompile"]["traces_precompiled"] >= 1, warm
    assert warm["fused_misses"] == 0, warm
    assert warm["fused_hits"] >= 3, warm
    assert warm["fresh_compiles"] == 0, warm
    assert warm["disk_cache_hits"] > 0, warm
    np.testing.assert_allclose(cold["losses"], warm["losses"], rtol=1e-6)
