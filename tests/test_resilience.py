"""Fault-injection suite for the resilient training runtime (ISSUE 3).

Deterministic faults (runtime/resilience.FaultInjector) drive every
recovery path and the fault-event counters assert each path actually
fired: transient-IOError-then-succeed on save, kill -9 mid-async-save,
corrupted shard restore fallback, BadStepGuard rollback on injected
NaN, watchdog stall on a never-appearing heartbeat, heartbeat
monotonicity, and the hapi ResilienceCallback end-to-end.
"""
import json
import os
import signal
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.dispatch import dispatch_stats
from paddle_tpu.distributed.elastic import ElasticManager, latest_checkpoint
from paddle_tpu.io.checkpoint import (
    CheckpointManager, IntegrityError, complete_steps, latest_complete_step,
    leaf_checksums, load_checkpoint, save_checkpoint, verify_checksums,
    INTEGRITY_BASENAME,
)
from paddle_tpu.runtime.resilience import (
    BadStepGuard, EscalationError, FaultInjector, all_finite, corrupt_file,
    fault_events, fault_point, record_fault, reset_fault_events,
    retry_with_backoff,
)
from paddle_tpu.testing.faults import corrupt_shard, faults_env


@pytest.fixture(autouse=True)
def _fresh_fault_counters():
    reset_fault_events()
    yield
    reset_fault_events()


def _state(step=0, seed=0, n=8):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(n, n).astype(np.float32)),
            "step": jnp.int32(step)}


# ---------------------------------------------------------------------------
# retry / backoff / degradation

def test_retry_transient_then_succeed():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        fault_point("t.flaky")
        return "ok"

    with FaultInjector({"t.flaky": ("transient", 2)}):
        assert retry_with_backoff(flaky, base_delay=0.001,
                                  counter="save_retries") == "ok"
    assert calls["n"] == 3
    assert fault_events()["save_retries"] == 2
    assert fault_events()["injected_faults"] == 2


def test_retry_exhaustion_raises():
    def always():
        fault_point("t.always")

    with FaultInjector({"t.always": ("raise", 0)}):
        with pytest.raises(IOError):
            retry_with_backoff(always, attempts=3, base_delay=0.001,
                               counter="save_retries")
    assert fault_events()["save_retries"] == 2  # attempts-1 retries


def test_save_transient_io_error_retries_then_lands(tmp_path):
    d = str(tmp_path / "c")
    with CheckpointManager(d, async_save=False) as m:
        with FaultInjector({"checkpoint.save": ("transient", 2)}):
            assert m.save(0, _state(), force=True)
        m.wait()
        assert m.latest_step() == 0
    assert fault_events()["save_retries"] == 2
    # the landed checkpoint restores clean
    r = load_checkpoint(d)
    np.testing.assert_array_equal(np.asarray(r["w"]),
                                  np.asarray(_state()["w"]))


def test_save_hard_failure_degrades_never_raises(tmp_path):
    d = str(tmp_path / "c")
    with CheckpointManager(d, async_save=False, retry_attempts=2) as m:
        assert m.save(0, _state(0), force=True)
        with FaultInjector({"checkpoint.save": ("raise", 0)}):
            with pytest.warns(UserWarning, match="save of step 1 failed"):
                assert m.save(1, _state(1), force=False) is False
        # training survived; the previous checkpoint still stands
        assert m.latest_step() == 0
    assert fault_events()["save_failures"] == 1
    assert fault_events()["save_retries"] == 1


# ---------------------------------------------------------------------------
# integrity manifest

def test_manifest_written_at_commit_and_verifies(tmp_path):
    d = str(tmp_path / "c")
    state = _state(3)
    save_checkpoint(d, 3, state)
    mpath = os.path.join(d, "3", INTEGRITY_BASENAME)
    assert os.path.exists(mpath)
    with open(mpath) as f:
        manifest = json.load(f)["leaves"]
    assert verify_checksums(state, manifest) == []
    assert manifest == leaf_checksums(state)
    # a clean restore passes verification silently
    r = load_checkpoint(d)
    assert int(r["step"]) == 3
    assert fault_events()["restore_fallbacks"] == 0


def test_async_manifest_flushes_after_commit(tmp_path):
    d = str(tmp_path / "c")
    with CheckpointManager(d, async_save=True) as m:
        m.save(0, _state(0), force=True)
        m.wait()
        assert os.path.exists(os.path.join(d, "0", INTEGRITY_BASENAME))


def test_corrupt_shard_restore_falls_back(tmp_path):
    d = str(tmp_path / "c")
    with CheckpointManager(d, async_save=False, max_to_keep=None) as m:
        m.save(0, _state(0, seed=0), force=True)
        m.save(1, _state(1, seed=1), force=True)
        m.wait()
    corrupt_shard(d, 1)
    with CheckpointManager(d) as m:
        with pytest.warns(UserWarning, match="falling back"):
            r = m.restore()
        assert m.last_restored_step == 0
    np.testing.assert_array_equal(np.asarray(r["w"]),
                                  np.asarray(_state(0, seed=0)["w"]))
    assert fault_events()["restore_fallbacks"] >= 1


def test_checksum_mismatch_detected_by_manifest(tmp_path):
    """Tamper the MANIFEST: orbax reads the data fine, but our
    verification convicts the step and falls back — the path that
    catches silent bit rot tensorstore's codec checksums can't see."""
    d = str(tmp_path / "c")
    with CheckpointManager(d, async_save=False, max_to_keep=None) as m:
        m.save(0, _state(0, seed=0), force=True)
        m.save(1, _state(1, seed=1), force=True)
        m.wait()
    mpath = os.path.join(d, "1", INTEGRITY_BASENAME)
    with open(mpath) as f:
        manifest = json.load(f)
    first = next(iter(manifest["leaves"]))
    manifest["leaves"][first]["crc32"] ^= 0xFFFF
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    with CheckpointManager(d) as m:
        with pytest.warns(UserWarning, match="IntegrityError"):
            r = m.restore()
        assert m.last_restored_step == 0
    assert int(r["step"]) == 0
    assert fault_events()["restore_fallbacks"] >= 1
    # strict mode surfaces the corruption instead of falling back
    with CheckpointManager(d) as m:
        with pytest.raises(IntegrityError):
            m.restore(strict=True)


def test_all_steps_corrupt_raises(tmp_path):
    d = str(tmp_path / "c")
    save_checkpoint(d, 0, _state(0))
    corrupt_shard(d, 0)
    with pytest.warns(UserWarning, match="falling back"):
        with pytest.raises(FileNotFoundError, match="no restorable"):
            load_checkpoint(d)


# ---------------------------------------------------------------------------
# latest-step unification (elastic == checkpoint manager, tmp-dir aware)

def test_latest_step_tmp_dir_aware(tmp_path):
    d = str(tmp_path / "c")
    for name in ["3", "4", "5.orbax-checkpoint-tmp-123", "junk"]:
        os.makedirs(os.path.join(d, name))
    open(os.path.join(d, "9"), "w").close()  # a stray FILE, not a step
    # orbax commits by atomic rename: bare-digit DIRS are complete; the
    # in-flight tmp dir for step 5 and non-step entries are not
    assert complete_steps(d) == [3, 4]
    assert latest_complete_step(d) == 4
    assert latest_checkpoint(d) == 4  # elastic delegates: can't disagree


def test_elastic_resume_skips_in_flight_tmp_dir(tmp_path):
    d = str(tmp_path / "e")
    save_checkpoint(d, 2, _state(2))
    os.makedirs(os.path.join(d, "3.orbax-checkpoint-tmp-99"))
    em = ElasticManager(d, timeout=9999)
    seen = []
    assert em.resume(seen.append) == 3
    assert seen == [2]


# ---------------------------------------------------------------------------
# crash consistency: kill -9 mid-async-save

def test_kill9_mid_async_save_restores_prior_step(tmp_path):
    d = str(tmp_path / "crash")
    child = os.path.join(os.path.dirname(__file__), "_resilience_child.py")
    env = faults_env({"checkpoint.async_started": ("kill", 2)})
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    proc = subprocess.run([sys.executable, child, d], env=env,
                          capture_output=True, text=True, timeout=300)
    # SIGKILLed mid-write, after step 0 was durably committed
    assert proc.returncode == -signal.SIGKILL, (proc.returncode,
                                                proc.stdout, proc.stderr)
    assert "STEP0_COMMITTED" in proc.stdout
    assert "SURVIVED" not in proc.stdout
    # the torn step-1 write left only an orbax tmp dir (or nothing) —
    # every reader agrees the directory is at step 0
    assert latest_complete_step(d) == 0
    assert latest_checkpoint(d) == 0
    leftovers = [n for n in os.listdir(d) if n.startswith("1")]
    assert all("orbax-checkpoint-tmp" in n for n in leftovers), leftovers
    # and it RESTORES: the prior step comes back bit-exact
    r = load_checkpoint(d)
    rng = np.random.RandomState(7)
    np.testing.assert_array_equal(
        np.asarray(r["w"]), rng.randn(256, 256).astype(np.float32))
    assert int(r["step"]) == 0


# ---------------------------------------------------------------------------
# bad-step guard

def test_badstep_guard_rollback_and_continue():
    snapshots = {"good": 1.0}
    rolled = []

    guard = BadStepGuard(lambda step: rolled.append(step),
                         max_consecutive=3)
    assert guard.check(0, 0.5)
    assert not guard.check(1, float("nan"))
    assert rolled == [1]
    assert guard.check(2, 0.4)  # recovered: consecutive resets
    assert guard.consecutive == 0
    assert fault_events()["rollbacks"] == 1
    assert snapshots["good"] == 1.0


def test_badstep_guard_grads_and_arrays():
    guard = BadStepGuard(lambda step: None, max_consecutive=10)
    ok = {"a": jnp.ones(3), "b": [np.ones(2)]}
    bad = {"a": jnp.ones(3), "b": [np.array([1.0, np.inf])]}
    assert guard.check(0, 0.1, grads=ok)
    assert not guard.check(1, 0.1, grads=bad)
    assert all_finite(ok) and not all_finite(bad)


def test_badstep_guard_escalates():
    guard = BadStepGuard(lambda step: None, max_consecutive=2)
    assert not guard.check(0, float("inf"))
    with pytest.raises(EscalationError):
        guard.check(1, float("nan"))
    assert fault_events()["escalations"] == 1
    assert fault_events()["rollbacks"] == 2

    hits = []
    guard = BadStepGuard(lambda step: None, max_consecutive=2,
                         on_escalate=lambda step, n: hits.append((step, n)))
    guard.check(0, float("nan"))
    guard.check(1, float("nan"))
    assert hits == [(1, 2)]


def test_elastic_guard_rolls_back_to_checkpoint(tmp_path):
    """Manual loop: injected NaN rolls w back to the last complete
    checkpoint and training resumes to completion."""
    d = str(tmp_path / "e")
    m = CheckpointManager(d, async_save=False, max_to_keep=None)
    em = ElasticManager(d, timeout=9999, save_interval=2,
                        save_fn=lambda s: m.save(s, {"w": live["w"],
                                                     "step": jnp.int32(s)},
                                                 force=True))
    live = {"w": jnp.zeros((4,), jnp.float32)}

    def restore(step):
        r = m.restore(step)
        live["w"] = jnp.asarray(r["w"])
        return m.last_restored_step

    guard = em.guard(restore)
    for step in range(8):
        w = live["w"] + 1.0
        if step == 5:
            w = w * jnp.float32(np.nan)  # the injected bad step
        live["w"] = w
        if not guard.check(step, float(jnp.sum(w))):
            continue
        em.tick(step)
    m.wait()
    m.close()
    # steps 0..4 add 1 each (ckpts at 2,4), step 5 NaN -> rollback to
    # ckpt@4 (w=5), steps 6,7 add 1 each -> 7
    assert fault_events()["rollbacks"] == 1
    np.testing.assert_allclose(np.asarray(live["w"]), 7.0)
    assert bool(np.isfinite(np.asarray(live["w"])).all())


# ---------------------------------------------------------------------------
# watchdog + heartbeat hardening

def test_watchdog_detects_hang_before_first_heartbeat(tmp_path):
    em = ElasticManager(str(tmp_path / "wd"), timeout=0.3)
    stalls = []
    em.start_watchdog(on_stall=stalls.append, poll=0.05)
    deadline = time.time() + 5.0
    while not em.stalled and time.time() < deadline:
        time.sleep(0.05)
    em.stop()
    assert em.stalled
    assert stalls and stalls[0]["reason"] == "no_heartbeat"
    assert em.stall_reason == "no_heartbeat"
    assert fault_events()["stall_detections"] == 1


def test_watchdog_survives_bad_heartbeat_and_own_callback(tmp_path):
    d = str(tmp_path / "wd")
    em = ElasticManager(d, timeout=0.3)
    with open(em._hb_path, "w") as f:
        f.write("{not json")  # torn write: unreadable forever

    def exploding(info):
        raise RuntimeError("callback bug")

    em.start_watchdog(on_stall=exploding, poll=0.05)
    deadline = time.time() + 5.0
    while not em.stalled and time.time() < deadline:
        time.sleep(0.05)
    em.stop()
    assert em.stalled  # unreadable heartbeat still counts as a hang
    assert fault_events()["stall_detections"] == 1
    assert fault_events()["watchdog_errors"] >= 1  # callback survived


def test_watchdog_step_deadline_distinct_from_timeout(tmp_path):
    """Heartbeat stays FRESH (ticked continuously) but the step number
    never advances: only the per-step deadline can see this."""
    em = ElasticManager(str(tmp_path / "wd"), timeout=60.0,
                        step_deadline=0.3)
    stalls = []
    em.start_watchdog(on_stall=stalls.append, poll=0.05)
    deadline = time.time() + 5.0
    while not em.stalled and time.time() < deadline:
        em.tick(3)  # alive, but wedged at step 3
        time.sleep(0.05)
    em.stop()
    assert em.stalled and em.stall_reason == "step_deadline"
    assert stalls[0]["step"] == 3


def test_watchdog_run_deadline(tmp_path):
    em = ElasticManager(str(tmp_path / "wd"), timeout=60.0,
                        run_deadline=0.2)
    em.tick(0)
    em.start_watchdog(poll=0.05)
    deadline = time.time() + 5.0
    while not em.stalled and time.time() < deadline:
        time.sleep(0.05)
    em.stop()
    assert em.stalled and em.stall_reason == "run_deadline"


def test_watchdog_run_deadline_before_first_heartbeat(tmp_path):
    """run_deadline expiring with NO heartbeat file yet must still
    deliver on_stall with a dict payload (not crash the watchdog)."""
    em = ElasticManager(str(tmp_path / "wd"), timeout=60.0,
                        run_deadline=0.15)
    stalls = []
    em.start_watchdog(on_stall=stalls.append, poll=0.05)
    deadline = time.time() + 5.0
    while not stalls and time.time() < deadline:
        time.sleep(0.05)
    em.stop()
    assert em.stalled and em.stall_reason == "run_deadline"
    assert stalls and stalls[0]["reason"] == "run_deadline"
    assert stalls[0]["step"] is None
    assert fault_events()["watchdog_errors"] == 0


def test_tick_monotonicity_guard(tmp_path):
    em = ElasticManager(str(tmp_path / "hb"), timeout=9999)
    assert em.tick(5)
    with pytest.warns(UserWarning, match="backwards"):
        assert em.tick(3) is False  # stale step refused
    with open(em._hb_path) as f:
        assert json.load(f)["step"] == 5  # progress untouched
    assert fault_events()["heartbeat_regressions"] == 1
    assert em.tick(5)  # equal step is a legal re-tick
    assert em.tick(6)


# ---------------------------------------------------------------------------
# observability: dispatch_stats / profiler surface

def test_fault_events_in_dispatch_stats_and_summary(capsys):
    record_fault("restore_fallbacks", "test")
    ds = dispatch_stats()
    assert ds["fault_events"]["restore_fallbacks"] == 1
    assert set(ds["fault_events"]) >= {"save_retries", "rollbacks",
                                       "stall_detections",
                                       "eager_demotions"}
    from paddle_tpu.profiler import Profiler

    p = Profiler(timer_only=True)
    p.start()
    p.step()
    p.summary()
    out = capsys.readouterr().out
    assert "fault events" in out and "restore_fallbacks: 1" in out


def test_runtime_eager_demotion_records_fault_event():
    import jax

    from paddle_tpu.core import dispatch

    def shape_from_value(x):
        return x.reshape(int(x.sum()))  # int(traced) -> unjittable

    vals, treedef = jax.tree_util.tree_flatten(((jnp.ones(4),), {}))
    prev = dispatch.set_warmup_count(1)
    try:
        before = fault_events()["eager_demotions"]
        out = dispatch.run_op(shape_from_value, vals, treedef,
                              lambda: shape_from_value(jnp.ones(4)))
        assert np.asarray(out).shape == (4,)
        assert fault_events()["eager_demotions"] == before + 1
    finally:
        dispatch.set_warmup_count(prev)


# ---------------------------------------------------------------------------
# hapi integration: ResilienceCallback

def _nan_fit_setup(tmp_path, nan_batch=2, n=16, batch=4):
    paddle.seed(0)
    x = np.random.rand(n, 4).astype(np.float32)
    w = np.random.rand(4, 1).astype(np.float32)
    y = (x @ w).astype(np.float32)
    # poison exactly one batch: its loss (and the fused step's update)
    # goes NaN, which is what the guard must roll back
    x[nan_batch * batch] = np.nan
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    return model, net, x, y


def test_resilience_callback_nan_rollback_completes_training(tmp_path):
    from paddle_tpu.hapi.callbacks import ResilienceCallback

    model, net, x, y = _nan_fit_setup(tmp_path)
    cb = ResilienceCallback(str(tmp_path / "ck"), save_interval=1,
                            async_save=False, max_to_keep=None,
                            max_consecutive_rollbacks=3)
    with pytest.warns(UserWarning, match="rolling back"):
        model.fit([x, y], epochs=2, batch_size=4, verbose=0,
                  shuffle=False, callbacks=[cb])
    # the NaN batch recurs each epoch: one rollback per epoch, and the
    # run still completes with finite parameters
    assert fault_events()["rollbacks"] == 2
    for _, p in net.named_parameters():
        assert bool(np.isfinite(p.numpy()).all())
    # good steps kept checkpointing after the rollbacks
    assert latest_complete_step(str(tmp_path / "ck")) is not None


def test_resilience_callback_escalation_stops_training(tmp_path):
    from paddle_tpu.hapi.callbacks import ResilienceCallback

    paddle.seed(0)
    x = np.full((16, 4), np.nan, np.float32)  # EVERY batch is bad
    y = np.zeros((16, 1), np.float32)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    cb = ResilienceCallback(str(tmp_path / "ck"), save_interval=100,
                            async_save=False,
                            max_consecutive_rollbacks=2)
    with pytest.warns(UserWarning, match="rolling back"):
        model.fit([x, y], epochs=5, batch_size=4, verbose=0,
                  shuffle=False, callbacks=[cb])
    assert model.stop_training  # default escalation: stop, don't spin
    assert fault_events()["escalations"] >= 1
    # fit honors stop_training PER BATCH: exactly 2 bad steps ran
    # (escalation on the 2nd), not 4/epoch for 5 epochs
    assert fault_events()["rollbacks"] == 2


def test_resilience_callback_kill_and_resume(tmp_path):
    """Two fit() lifetimes over the same ckpt_dir: the second resumes
    from the first's final checkpoint instead of starting over."""
    from paddle_tpu.hapi.callbacks import ResilienceCallback

    ck = str(tmp_path / "ck")
    paddle.seed(0)
    x = np.random.rand(16, 4).astype(np.float32)
    w = np.random.rand(4, 1).astype(np.float32)
    y = (x @ w).astype(np.float32)

    def lifetime():
        net = nn.Linear(4, 1)
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.SGD(0.05,
                                           parameters=net.parameters()),
                      nn.MSELoss())
        cb = ResilienceCallback(ck, save_interval=2, async_save=False,
                                max_to_keep=None)
        model.fit([x, y], epochs=1, batch_size=4, verbose=0, shuffle=False,
                  callbacks=[cb])
        return cb, net

    cb1, net1 = lifetime()          # 4 steps: global steps 0..3
    first_end = cb1.global_step
    cb2, net2 = lifetime()          # resumes AFTER the first lifetime
    assert cb2.global_step > first_end
    # the resumed lifetime restored the first one's trained params
    # before continuing (they differ from a fresh init)
    sd1 = {k: p.numpy() for k, p in net1.named_parameters()}
    sd2 = {k: p.numpy() for k, p in net2.named_parameters()}
    assert set(sd1) == set(sd2)
    for k in sd1:
        assert bool(np.isfinite(sd2[k]).all())


# ---------------------------------------------------------------------------
# grad-norm guard: exploding-but-finite steps (PR-3 follow-up)

def test_badstep_guard_grad_norm_threshold():
    """A finite loss with a grad norm above the threshold is a bad step:
    rollback fires; below it, nothing does. Non-finite norms are bad
    regardless of threshold."""
    rolled = []
    guard = BadStepGuard(lambda step: rolled.append(step),
                         max_consecutive=10, grad_norm_threshold=100.0)
    assert guard.check(0, 0.5, grad_norm=3.0)
    assert not guard.check(1, 0.5, grad_norm=1e6)   # finite but exploding
    assert rolled == [1]
    assert guard.check(2, 0.5, grad_norm=np.float32(99.0))
    assert not guard.check(3, 0.5, grad_norm=float("nan"))
    assert fault_events()["rollbacks"] == 2

    # without a threshold only non-finite norms are bad
    guard2 = BadStepGuard(lambda step: None, max_consecutive=10)
    assert guard2.check(0, 0.5, grad_norm=1e30)
    assert not guard2.check(1, 0.5, grad_norm=float("inf"))


def test_fused_step_exposes_grad_norm():
    """With want_grad_norm set (ResilienceCallback does this), the hapi
    fused train step returns the per-step global L2 grad norm
    (engine.last_grad_norm) matching a hand computation; without it the
    norm is not computed (no extra reduction for guard-less users)."""
    paddle.seed(0)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.0, parameters=net.parameters()),
                  nn.MSELoss())
    rng = np.random.RandomState(0)
    x = rng.rand(8, 4).astype(np.float32)
    y = rng.rand(8, 1).astype(np.float32)
    model.train_batch([x], [y])
    assert model._engine.last_grad_norm is None  # opt-in only
    model._engine.want_grad_norm = True          # rebuilds the step fn
    model.train_batch([x], [y])
    gnorm = float(np.asarray(model._engine.last_grad_norm))
    assert np.isfinite(gnorm) and gnorm > 0
    # lr=0 froze the params: recompute the same grads by hand
    w = {k: p.numpy() for k, p in net.named_parameters()}
    wt = w["weight"]
    b = w["bias"]
    pred = x @ wt + b
    gw = 2.0 * x.T @ (pred - y) / len(x)
    gb = 2.0 * np.mean(pred - y, axis=0)
    ref = float(np.sqrt((gw ** 2).sum() + (gb ** 2).sum()))
    np.testing.assert_allclose(gnorm, ref, rtol=1e-4)


def test_resilience_callback_grad_norm_threshold_rollback(tmp_path):
    """End-to-end: a huge-magnitude (but finite) batch explodes the grad
    norm; ResilienceCallback(grad_norm_threshold=...) rolls back and
    training completes with finite params — the exploding step's update
    never sticks."""
    from paddle_tpu.hapi.callbacks import ResilienceCallback

    paddle.seed(0)
    x = np.random.rand(16, 4).astype(np.float32)
    w = np.random.rand(4, 1).astype(np.float32)
    y = (x @ w).astype(np.float32)
    x[8] = 1e4  # finite, but the MSE grads through it explode
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    cb = ResilienceCallback(str(tmp_path / "ck"), save_interval=1,
                            async_save=False, max_to_keep=None,
                            max_consecutive_rollbacks=5,
                            grad_norm_threshold=1e3)
    with pytest.warns(UserWarning, match="grad norm"):
        model.fit([x, y], epochs=1, batch_size=4, verbose=0, shuffle=False,
                  callbacks=[cb])
    assert fault_events()["rollbacks"] >= 1
    for _, p in net.named_parameters():
        pv = p.numpy()
        assert bool(np.isfinite(pv).all())
        assert float(np.abs(pv).max()) < 1e3  # the bad update was undone
