"""Custom C++ op end-to-end: cpp_extension -> ctypes -> pure_callback bridge.

Reference capability: paddle/extension.h custom op registration
(custom_relu example in the reference's custom-op tests).
"""
import ctypes

import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture(scope="module")
def scale_shift_lib(tmp_path_factory):
    src = tmp_path_factory.mktemp("csrc") / "scale_shift.cpp"
    src.write_text(r"""
extern "C" {
// y = a * x + b  (elementwise); grad_x = a * ct
void scale_shift(const float* x, float a, float b, float* y, long n) {
  for (long i = 0; i < n; ++i) y[i] = a * x[i] + b;
}
void scale_shift_grad(const float* ct, float a, float* gx, long n) {
  for (long i = 0; i < n; ++i) gx[i] = a * ct[i];
}
}
""")
    lib = paddle.utils.cpp_extension.load("scale_shift", [str(src)])
    lib.scale_shift.argtypes = [ctypes.POINTER(ctypes.c_float),
                                ctypes.c_float, ctypes.c_float,
                                ctypes.POINTER(ctypes.c_float),
                                ctypes.c_long]
    lib.scale_shift_grad.argtypes = [ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_float,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_long]
    return lib


@pytest.fixture(scope="module")
def scale_shift_op(scale_shift_lib):
    lib = scale_shift_lib
    A, B = 3.0, 1.0

    def fwd(x):
        x = np.ascontiguousarray(x, np.float32)
        y = np.empty_like(x)
        lib.scale_shift(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                        A, B,
                        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                        x.size)
        return y

    def bwd(ct, x):
        ct = np.ascontiguousarray(ct, np.float32)
        gx = np.empty_like(ct)
        lib.scale_shift_grad(
            ct.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), A,
            gx.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), ct.size)
        return gx

    return paddle.utils.register_custom_op(
        "scale_shift", fwd, infer_shape=lambda x: x, backward=bwd)


def test_eager_and_tape(scale_shift_op):
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.stop_gradient = False
    y = scale_shift_op(x)
    np.testing.assert_allclose(y.numpy(), 3.0 * x.numpy() + 1.0)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 3.0)  # C++ backward kernel


def test_inside_jit(scale_shift_op):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(v):
        return scale_shift_op.jax_fn(v) * 2.0

    v = jnp.arange(4, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(f(v)), (3.0 * np.arange(4) + 1) * 2)

    g = jax.grad(lambda v: scale_shift_op.jax_fn(v).sum())(v)
    np.testing.assert_allclose(np.asarray(g), 3.0)


def test_no_backward_op():
    op = paddle.utils.register_custom_op(
        "np_cumsum", lambda x: np.cumsum(x), infer_shape=lambda x: x)
    x = paddle.to_tensor(np.ones(4, np.float32))
    np.testing.assert_allclose(op(x).numpy(), [1, 2, 3, 4])
