"""Request-scoped serving observability (ISSUE 20): rolling-window
aggregation (concurrent rotation safety, quantile interpolation, SLO
burn-rate), deterministic tail-based trace sampling, access-log <->
counter EXACT reconciliation, and the access log's torn-tail/rotation
durability contract."""
import json
import os
import threading
import time

import pytest

from paddle_tpu.inference import (
    OverloadedError,
    ServeConfig,
    ServingEngine,
    TinyServeModel,
    read_access_log,
    tail_sampled,
)
from paddle_tpu.inference.access_log import AccessLog, aggregates
from paddle_tpu.runtime import telemetry, tracing
from paddle_tpu.runtime.resilience import (
    FaultInjector,
    fault_events,
    reset_fault_events,
)
from paddle_tpu.runtime.windows import (
    SLOMonitor,
    ServingWindows,
    WindowedCounter,
    WindowedHistogram,
    WindowedMax,
    quantile_from_buckets,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# windowed primitives


class TestWindowedCounter:
    def test_deterministic_expiry(self):
        c = WindowedCounter(window_s=10.0, subwindows=5)  # width 2s
        c.inc(3, now=0.0)
        c.inc(2, now=1.0)                    # same epoch
        assert c.total(now=1.0) == 5.0
        assert c.total(now=9.9) == 5.0       # still inside the window
        assert c.total(now=10.1) == 0.0      # epoch 0 aged out
        assert c.rate(now=5.0) == 0.5

    def test_slot_reuse_resets_stale_epoch(self):
        c = WindowedCounter(window_s=10.0, subwindows=5)
        c.inc(7, now=0.0)                    # epoch 0 -> slot 0
        c.inc(1, now=10.0)                   # epoch 5 -> slot 0 again
        # the stale epoch-0 value must have been wiped, not summed
        assert c.total(now=10.0) == 1.0

    def test_concurrent_rotation_no_lost_increments(self):
        """The tentpole race: producers hammering a counter across
        hundreds of live rotation boundaries must lose NOTHING — the
        stale-slot reset and the increment share one critical section,
        so an increment can never land in the void between them."""
        c = WindowedCounter(window_s=30.0, subwindows=30000)  # 1ms width
        n_threads, per_thread = 4, 20000
        start = threading.Barrier(n_threads)

        def worker():
            start.wait()
            for i in range(per_thread):
                c.inc()
                if i % 2000 == 1999:
                    time.sleep(0.001)  # stretch across more epochs

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # the run spans way less than the 30s window: every increment
        # must still be visible
        assert c.total() == float(n_threads * per_thread)

    def test_rejects_bad_geometry(self):
        with pytest.raises(ValueError):
            WindowedCounter(window_s=0)
        with pytest.raises(ValueError):
            WindowedCounter(subwindows=0)


class TestWindowedMax:
    def test_max_and_expiry(self):
        m = WindowedMax(window_s=10.0, subwindows=5)
        assert m.value(now=0.0) is None
        m.observe(3, now=0.0)
        m.observe(9, now=1.0)
        m.observe(5, now=4.0)
        assert m.value(now=4.0) == 9.0
        assert m.value(now=11.5) == 5.0      # the 9 aged out with epoch 0
        assert m.value(now=30.0) is None


class TestWindowedHistogram:
    def test_quantiles_track_observations(self):
        h = WindowedHistogram((0.1, 0.5, 1.0, 5.0), window_s=60.0,
                              subwindows=6)
        for v in (0.05, 0.05, 0.3, 0.3, 0.3, 0.7, 0.7, 0.9, 2.0, 4.0):
            h.observe(v, now=1.0)
        counts, total, n = h.merged(now=1.0)
        assert n == 10 and counts == [2, 3, 3, 2, 0]
        assert abs(total - 9.3) < 1e-9
        p50 = h.quantile(50, now=1.0)
        assert 0.1 < p50 <= 0.5              # rank 5 is in the 0.5 bucket
        p99 = h.quantile(99, now=1.0)
        assert 1.0 < p99 <= 5.0
        assert h.quantile(50, now=120.0) is None   # window rolled over

    def test_quantile_from_buckets_edges(self):
        assert quantile_from_buckets((1.0,), [0, 0], 0, 99) is None
        # everything in the +Inf tail clamps to the last finite bound
        assert quantile_from_buckets((1.0, 2.0), [0, 0, 5], 5, 99) == 2.0
        # single bucket interpolates from the lower edge
        got = quantile_from_buckets((1.0, 2.0), [4, 0, 0], 4, 50)
        assert 0.0 < got <= 1.0


class TestServingWindows:
    def test_snapshot_and_gauge_publish(self):
        telemetry.reset_metrics()
        w = ServingWindows(windows=(("1m", 60.0, 12),))
        w.observe_ttft(0.2, now=1.0)
        w.observe_ttft(0.4, now=2.0)
        w.count_submitted(now=1.0)
        w.count_submitted(now=2.0)
        w.count_shed(now=2.0)
        w.count_tokens(30, now=2.0)
        w.observe_queue_depth(7, now=2.0)
        snap = w.publish(now=3.0)
        panel = snap["1m"]
        assert panel["ttft_count"] == 2
        assert abs(panel["ttft_sum_s"] - 0.6) < 1e-9
        assert panel["submitted"] == 2.0 and panel["shed"] == 1.0
        assert panel["shed_ratio"] == 0.5
        assert panel["goodput_tokens_per_sec"] == 30 / 60.0
        assert panel["queue_depth_highwater"] == 7.0
        snap2 = telemetry.snapshot()
        by_label = {tuple(s["labels"].values())[0]: s["value"]
                    for s in snap2["paddle_tpu_serve_shed_ratio"]["series"]}
        assert by_label["1m"] == 0.5


class TestSLOMonitor:
    def _mon(self, **kw):
        base = dict(objective=0.9, fast=("1m", 60.0, 12),
                    slow=("5m", 300.0, 20), fast_burn=6.0, slow_burn=3.0,
                    cooldown_s=10.0, min_samples=5)
        base.update(kw)
        return SLOMonitor("test_slo", **base)

    def test_no_burn_without_min_samples(self):
        m = self._mon()
        for _ in range(4):
            m.observe(False, now=100.0)
        panel = m.evaluate(now=100.0)
        assert not panel["burning"] and m.burns_emitted == 0

    def test_burn_requires_both_windows(self):
        m = self._mon()
        # 10 bad at t=100: both windows see them -> burning
        for _ in range(10):
            m.observe(False, now=100.0)
        panel = m.evaluate(now=100.0)
        assert panel["burning"] and m.burns_emitted == 1
        assert panel["windows"]["1m"]["burn_rate"] >= 6.0
        # at t=200 the fast (1m) window has rolled clean but the slow
        # (5m) still carries the badness: NOT burning (the two-window
        # AND is the whole point)
        panel2 = m.evaluate(now=200.0)
        assert not panel2["burning"]
        assert panel2["windows"]["1m"]["samples"] == 0
        assert panel2["windows"]["5m"]["samples"] == 10

    def test_burn_event_cooldown(self):
        m = self._mon()
        for _ in range(10):
            m.observe(False, now=100.0)
        assert m.evaluate(now=100.0)["burning"]
        assert m.evaluate(now=105.0)["burning"]  # inside cooldown
        assert m.burns_emitted == 1
        assert m.evaluate(now=112.0)["burning"]  # cooldown passed
        assert m.burns_emitted == 2

    def test_good_traffic_never_burns(self):
        m = self._mon()
        for _ in range(100):
            m.observe(True, now=50.0)
        panel = m.evaluate(now=50.0)
        assert not panel["burning"]
        assert panel["windows"]["1m"]["bad_ratio"] == 0.0


# ---------------------------------------------------------------------------
# tail sampling


class TestTailSampling:
    def test_unhappy_outcomes_always_sample(self):
        for outcome in ("overloaded", "evicted", "cancelled", "error"):
            assert tail_sampled(outcome, None, 2.0)
            assert tail_sampled(outcome, 0.001, None)

    def test_completed_samples_only_past_threshold(self):
        assert not tail_sampled("completed", 0.5, 2.0)
        assert tail_sampled("completed", 2.0, 2.0)
        assert tail_sampled("completed", 9.9, 2.0)

    def test_completed_without_threshold_or_latency_not_sampled(self):
        assert not tail_sampled("completed", 5.0, None)
        assert not tail_sampled("completed", None, 2.0)

    def test_deterministic(self):
        args = ("completed", 1.999999, 2.0)
        assert all(tail_sampled(*args) == tail_sampled(*args)
                   for _ in range(100))


# ---------------------------------------------------------------------------
# access log durability (no engine needed)


class TestAccessLogDurability:
    def _rec(self, i, outcome="completed"):
        return {"kind": "serve_access", "request_id": f"r{i}",
                "outcome": outcome, "latency_s": 0.1 * i,
                "prompt_len": 4, "max_new_tokens": 2}

    def test_ring_and_file_and_aggregates(self, tmp_path):
        telemetry.reset_metrics()     # clears the aggregates too
        log = AccessLog(str(tmp_path / "access.jsonl"), ring=4)
        for i in range(6):
            log.record(self._rec(i), latency_s=0.1 * i, ttft_s=None)
        log.close()
        assert [r["request_id"] for r in log.recent()] == \
            ["r2", "r3", "r4", "r5"]          # ring bounded at 4
        recs = read_access_log(str(tmp_path / "access.jsonl"))
        assert [r["request_id"] for r in recs] == [f"r{i}" for i in range(6)]
        agg = aggregates()
        assert agg["outcomes"] == {"completed": 6}
        assert agg["latency_count"] == 6 and agg["ttft_count"] == 0

    def test_torn_tail_tolerated(self, tmp_path):
        path = str(tmp_path / "access.jsonl")
        log = AccessLog(path)
        for i in range(3):
            log.record(self._rec(i))
        log.close()
        with open(path, "a") as f:            # SIGKILL mid-write
            f.write('{"kind":"serve_access","request_id":"torn","outc')
        recs = read_access_log(path)
        assert [r["request_id"] for r in recs] == ["r0", "r1", "r2"]

    def test_rotation_generations_read_oldest_first(self, tmp_path):
        path = str(tmp_path / "access.jsonl")
        log = AccessLog(path, max_bytes=200, max_files=3)
        for i in range(20):
            log.record(self._rec(i))
        log.close()
        assert log.rotations >= 2
        assert os.path.exists(path + ".1")
        recs = read_access_log(path)
        got = [int(r["request_id"][1:]) for r in recs]
        assert got == sorted(got)             # oldest generation first
        assert got[-1] == 19                  # newest record survives
        # the rotation bound holds: at most max_files generations
        assert not os.path.exists(path + ".3")

    def test_write_failure_degrades_never_raises(self, tmp_path):
        reset_fault_events()
        telemetry.reset_metrics()
        log = AccessLog(str(tmp_path / "access.jsonl"))
        with FaultInjector({"serve.access_write": ("raise", 0)}):
            log.record(self._rec(0))          # must not raise
        assert log.errors == 1
        assert fault_events().get("access_log_errors", 0) >= 1
        # ring + aggregates still saw the record (only the file write
        # was dropped)
        assert len(log.ring) == 1
        assert aggregates()["outcomes"] == {"completed": 1}
        log.close()

    def test_no_path_means_ring_only(self):
        telemetry.reset_metrics()
        log = AccessLog(None)
        log.record(self._rec(0))
        assert log.stats()["ok"] and log.stats()["path"] is None
        assert len(log.recent()) == 1


# ---------------------------------------------------------------------------
# engine integration: exact reconciliation + requestz + TPOT


def _engine(**cfg):
    model = TinyServeModel(vocab=32, dim=8, layers=2, heads=2, ffn=16,
                           seed=0)
    base = dict(max_running=3, token_budget=8, block_size=4,
                num_blocks=16, max_blocks_per_seq=4)
    base.update(cfg)
    return ServingEngine(model, ServeConfig(**base))


PROMPTS = [[1, 2, 3, 4, 5], [7, 8], [3, 1, 4, 1, 5, 9]]


class TestEngineObservability:
    def test_access_aggregates_reconcile_exactly(self, tmp_path):
        from paddle_tpu.core.dispatch import reset_dispatch_stats

        telemetry.reset_metrics()
        reset_dispatch_stats()
        tracing.configure(str(tmp_path / "trace"))
        tracing.reset_span_stats()
        try:
            eng = _engine(max_queued=2,
                          access_log=str(tmp_path / "access.jsonl"))
            shed = 0
            for i in range(6):
                try:
                    eng.submit([1 + i, 2, 3], max_new_tokens=3)
                except OverloadedError:
                    shed += 1
            out = eng.run()
            assert shed > 0 and len(out) > 0
            ok, rep = tracing.reconcile_with_metrics()
            assert ok, rep
            acc = rep["serve_access_outcomes"]
            assert not acc["skipped"] and acc["ok"]
            assert acc["span_n"] == len(out) + shed  # one record per exit
            assert rep["serve_access_latency"]["ok"]
            assert not rep["serve_access_latency"]["skipped"]
            assert rep["serve_access_ttft"]["ok"]
            # the per-outcome counts agree with the counter series
            agg = aggregates()
            fam = telemetry.snapshot()["paddle_tpu_serve_requests_total"]
            counter = {tuple(s["labels"].values())[0]: int(s["value"])
                       for s in fam["series"]}
            assert agg["outcomes"] == counter
            # submit-time sheds never entered the latency histogram —
            # the aggregate must not claim them either
            assert agg["latency_count"] == len(out)
        finally:
            tracing.set_enabled(False)

    def test_access_records_written_for_every_exit(self, tmp_path):
        telemetry.reset_metrics()
        path = str(tmp_path / "access.jsonl")
        eng = _engine(max_queued=2, access_log=path)
        shed = 0
        for i in range(5):
            try:
                eng.submit([1 + i, 2], max_new_tokens=2)
            except OverloadedError:
                shed += 1
        out = eng.run()
        recs = read_access_log(path)
        assert len(recs) == len(out) + shed
        by_outcome = {}
        for r in recs:
            by_outcome[r["outcome"]] = by_outcome.get(r["outcome"], 0) + 1
        assert by_outcome.get("overloaded", 0) == shed
        assert by_outcome.get("completed", 0) == len(out)
        for r in recs:
            if r["outcome"] == "overloaded":
                assert r["sampled"] and r["latency_s"] is None
            else:
                assert r["latency_s"] is not None
                assert r["ttft_s"] is not None
                assert r["tokens_out"] == 2

    def test_tpot_aggregates_and_histogram(self, tmp_path):
        telemetry.reset_metrics()
        path = str(tmp_path / "access.jsonl")
        eng = _engine(access_log=path)
        out = eng.generate(PROMPTS, max_new_tokens=4)
        assert all(len(t) == 4 for t in out)
        recs = [r for r in read_access_log(path)
                if r["outcome"] == "completed"]
        assert len(recs) == len(PROMPTS)
        for r in recs:
            # 4 tokens -> 3 inter-token gaps, mean/max present
            assert r["tpot_count"] == 3
            assert r["tpot_mean_s"] is not None
            assert r["tpot_max_s"] >= r["tpot_mean_s"] - 1e-9
        fam = telemetry.snapshot()["paddle_tpu_serve_tpot_seconds"]
        assert fam["series"][0]["count"] == 3 * len(PROMPTS)

    def test_happy_path_not_sampled_above_threshold(self, tmp_path):
        telemetry.reset_metrics()
        path = str(tmp_path / "access.jsonl")
        eng = _engine(access_log=path, trace_slow_s=1e9)
        eng.generate(PROMPTS[:2], max_new_tokens=2)
        recs = read_access_log(path)
        assert recs and all(not r["sampled"] for r in recs)

    def test_requestz_snapshot_shape(self, tmp_path):
        telemetry.reset_metrics()
        eng = _engine(access_log=str(tmp_path / "access.jsonl"))
        eng.generate(PROMPTS[:2], max_new_tokens=2)
        snap = eng.requestz_snapshot()
        assert snap["in_flight"] == []        # drained
        assert len(snap["recent"]) == 2
        assert set(snap["windows"]) == {"1m", "5m"}
        assert snap["windows"]["1m"]["ttft_count"] == 2
        assert "burning" in snap["slo"]
        assert snap["oldest_queued_age_s"] == 0.0
        assert snap["access"]["records"] == 2
        # a queued request shows up with its age and phase
        eng.scheduler.begin_drain()           # block admission to plan
        json.dumps(snap, default=str)         # statusz-serializable

    def test_oldest_queued_age_in_stats_and_gauge(self, tmp_path):
        telemetry.reset_metrics()
        eng = _engine()
        assert eng.stats()["oldest_queued_age_s"] == 0.0
        eng.submit(PROMPTS[0], max_new_tokens=2)
        time.sleep(0.01)
        age = eng.scheduler.oldest_queued_age()
        assert age >= 0.01
        assert eng.stats()["oldest_queued_age_s"] >= 0.01
        eng.run()
        assert eng.scheduler.oldest_queued_age() == 0.0

    def test_windowed_gauges_move_while_lifetime_only_grows(
            self, tmp_path):
        """The windowed view's reason to exist: drive traffic at two
        deterministic 'times' through the engine's ServingWindows and
        watch the 1m panel ROLL (old samples leave), which the lifetime
        histogram cannot do."""
        telemetry.reset_metrics()
        eng = _engine()
        eng.windows.observe_ttft(5.0, now=10.0)     # slow sample at t=10
        p99_early = eng.windows.snapshot(now=11.0)["1m"]["ttft_p99_s"]
        assert p99_early is not None and p99_early > 2.0
        eng.windows.observe_ttft(0.01, now=100.0)   # fast sample at t=100
        panel_late = eng.windows.snapshot(now=101.0)["1m"]
        # the slow sample aged out of the 1m window: p99 moved DOWN
        assert panel_late["ttft_count"] == 1
        assert panel_late["ttft_p99_s"] < p99_early
