"""Collectives on the 8-device CPU mesh (SURVEY §4 test_distributed_*).

Eager regime: rank-stacked tensors (leading axis = rank). Traced regime:
rank-local blocks inside shard_map.
"""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.core.jax_compat import shard_map
import paddle_tpu.distributed as dist

N = 8


@pytest.fixture(autouse=True)
def _env():
    dist.init_parallel_env()
    yield
    dist.set_mesh(None)


def _stack(fn=float):
    return paddle.to_tensor(
        np.arange(N, dtype=np.float32).reshape(N, 1))


def test_all_reduce_sum():
    x = _stack()
    out = dist.all_reduce(x)
    np.testing.assert_allclose(np.asarray(x._value), np.full((N, 1), 28.0))
    assert out is x  # in-place


def test_all_reduce_ops():
    for op, expect in [(dist.ReduceOp.MAX, 7.0), (dist.ReduceOp.MIN, 0.0),
                       (dist.ReduceOp.AVG, 3.5)]:
        x = _stack()
        dist.all_reduce(x, op=op)
        np.testing.assert_allclose(np.asarray(x._value),
                                   np.full((N, 1), expect))
    x = paddle.to_tensor(np.full((N, 1), 2.0, np.float32))
    dist.all_reduce(x, op=dist.ReduceOp.PROD)
    np.testing.assert_allclose(np.asarray(x._value), np.full((N, 1), 256.0))


def test_all_reduce_replicated_fallback():
    """Arbitrary-shaped (non-rank-stacked) tensors are accepted as
    replicated — every rank holds the value — matching the reference's
    shape-agnostic eager semantics (round-4 verdict weak #4). Parity:
    the result equals the stacked path fed n identical copies."""
    xv = np.arange(3, dtype=np.float32) + 1.0
    x = paddle.to_tensor(xv)
    out = dist.all_reduce(x)
    np.testing.assert_allclose(np.asarray(out._value), N * xv)
    assert out is x  # in-place contract preserved
    # parity vs the rank-stacked path with n identical slices
    stacked = paddle.to_tensor(np.broadcast_to(xv, (N, 3)).copy())
    dist.all_reduce(stacked)
    np.testing.assert_allclose(np.asarray(stacked._value)[0],
                               np.asarray(out._value))
    for op, expect in [(dist.ReduceOp.MAX, xv), (dist.ReduceOp.MIN, xv),
                       (dist.ReduceOp.AVG, xv), (dist.ReduceOp.PROD,
                                                 xv ** N)]:
        y = paddle.to_tensor(xv.copy())
        dist.all_reduce(y, op=op)
        np.testing.assert_allclose(np.asarray(y._value), expect, rtol=1e-5)
    # scalars (no leading axis at all) work too
    s = paddle.to_tensor(np.float32(2.0))
    dist.all_reduce(s)
    assert float(s._value) == 2.0 * N


def test_all_gather_replicated_fallback():
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = []
    res = dist.all_gather(out, paddle.to_tensor(xv.copy()))
    assert len(out) == N
    for t in out:
        np.testing.assert_allclose(np.asarray(t._value), xv)
    assert tuple(res._value.shape) == (N, 2, 3)


def test_broadcast_and_reduce_replicated_fallback():
    xv = np.arange(4, dtype=np.float32)
    x = paddle.to_tensor(xv.copy())
    dist.broadcast(x, src=3)  # replicated: already src's value
    np.testing.assert_allclose(np.asarray(x._value), xv)
    y = paddle.to_tensor(xv.copy())
    dist.reduce(y, dst=2)
    np.testing.assert_allclose(np.asarray(y._value), N * xv)


def test_all_gather():
    out = []
    dist.all_gather(out, _stack())
    assert len(out) == N
    for i, t in enumerate(out):
        assert float(t._value[0]) == float(i)


def test_broadcast():
    x = _stack()
    dist.broadcast(x, src=5)
    np.testing.assert_allclose(np.asarray(x._value), np.full((N, 1), 5.0))


def test_reduce():
    x = _stack()
    dist.reduce(x, dst=2)
    expect = np.arange(N, dtype=np.float32).reshape(N, 1)
    expect[2] = 28.0
    np.testing.assert_allclose(np.asarray(x._value), expect)


def test_scatter():
    t = paddle.zeros([N, 2])
    dist.scatter(t, [paddle.to_tensor(np.full(2, float(i), np.float32))
                     for i in range(N)], src=0)
    np.testing.assert_allclose(np.asarray(t._value),
                               np.repeat(np.arange(float(N))[:, None], 2, 1))


def test_alltoall():
    inp = paddle.to_tensor(np.arange(N * N, dtype=np.float32)
                           .reshape(N, N, 1))
    res = dist.alltoall(inp)
    np.testing.assert_allclose(
        np.asarray(res._value)[:, :, 0],
        np.arange(N * N).reshape(N, N).T)


def test_alltoall_single():
    v = paddle.to_tensor(np.arange(N * N, dtype=np.float32).reshape(N, N))
    o = dist.alltoall_single(v)
    np.testing.assert_allclose(np.asarray(o._value),
                               np.arange(N * N).reshape(N, N).T)


def test_send_recv_mailbox():
    dist.send(paddle.to_tensor(np.ones(3, np.float32) * 5), dst=0)
    r = paddle.zeros([3])
    dist.recv(r, src=0)
    np.testing.assert_allclose(np.asarray(r._value), np.full(3, 5.0))
    with pytest.raises(RuntimeError, match="no message"):
        dist.recv(paddle.zeros([3]), src=3)


def test_barrier_and_wait():
    dist.barrier()
    dist.wait(paddle.ones([2]))


def test_new_group_subset():
    g = dist.new_group([0, 2, 4, 6])
    assert g.nranks == 4
    assert g.get_group_rank(4) == 2
    x = paddle.to_tensor(np.ones((4, 3), np.float32))
    dist.all_reduce(x, group=g)
    np.testing.assert_allclose(np.asarray(x._value), np.full((4, 3), 4.0))


def test_rank_world_size():
    assert dist.get_rank() == 0
    assert dist.get_world_size() == N
    assert dist.get_world_size(dist.new_group([0, 1])) == 2


def test_traced_collectives_in_shard_map():
    mesh = dist.get_mesh()

    def red(x):
        return dist.all_reduce(paddle.Tensor(x))._value

    y = shard_map(red, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                      check_vma=False)(np.arange(N, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(y), np.full(N, 28.0))

    def gather(x):
        return dist.all_gather(None, paddle.Tensor(x))._value

    y = shard_map(gather, mesh=mesh, in_specs=P("dp"), out_specs=P(None),
                      check_vma=False)(np.arange(N, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(y), np.arange(N))

    def a2a(x):
        return dist.alltoall(paddle.Tensor(x))._value

    y = shard_map(a2a, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                      check_vma=False)(
        np.arange(N * N, dtype=np.float32).reshape(N * N, 1))
    np.testing.assert_allclose(np.asarray(y).reshape(N, N),
                               np.arange(N * N).reshape(N, N).T)

    def perm(x):
        t = dist.p2p_permute(paddle.Tensor(x),
                             [(i, (i + 1) % N) for i in range(N)])
        return t._value

    y = shard_map(perm, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                      check_vma=False)(np.arange(N, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(y), np.roll(np.arange(N), 1))


def test_traced_all_reduce_differentiable():
    mesh = dist.get_mesh()

    def loss_fn(x):
        def body(v):
            s = dist.all_reduce(paddle.Tensor(v))._value
            return (s ** 2).sum()
        per = shard_map(body, mesh=mesh, in_specs=P("dp"),
                            out_specs=P(), check_vma=False)(x)
        return per

    x = np.arange(N, dtype=np.float32)
    g = jax.grad(loss_fn)(x)
    # out_specs=P() takes one replica: loss = (sum x)^2 -> grad = 2 sum(x)
    np.testing.assert_allclose(np.asarray(g), np.full(N, 2.0 * 28.0))
