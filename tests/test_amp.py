"""AMP tests (reference: unittests test_amp_* / test_imperative_auto_mixed_precision)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_autocast_o1_dtype_policy():
    a = paddle.randn([4, 4])
    b = paddle.randn([4, 4])
    with paddle.amp.auto_cast(level="O1"):
        mm = paddle.matmul(a, b)
        assert mm.dtype == paddle.bfloat16  # white list -> low precision
        sm = paddle.nn.functional.softmax(mm)
        assert sm.dtype == paddle.float32  # black list -> f32
        add = a + b
        assert add.dtype == paddle.float32  # neither list: left alone
    mm2 = paddle.matmul(a, b)
    assert mm2.dtype == paddle.float32  # outside context


def test_autocast_o2():
    a = paddle.randn([4, 4])
    with paddle.amp.auto_cast(level="O2"):
        out = a + a
        assert out.dtype == paddle.bfloat16


def test_autocast_custom_lists():
    a = paddle.randn([4, 4])
    with paddle.amp.auto_cast(custom_white_list=["add"]):
        out = paddle.add(a, a)
        assert out.dtype == paddle.bfloat16
    with paddle.amp.auto_cast(custom_black_list=["matmul"]):
        out = paddle.matmul(a, a)
        assert out.dtype == paddle.float32


def test_grad_scaler_scales_and_unscales():
    lin = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
    x = paddle.ones([2, 4])
    loss = lin(x).mean()
    scaled = scaler.scale(loss)
    assert float(scaled) == pytest.approx(float(loss) * 64.0, rel=1e-5)
    scaled.backward()
    w_before = lin.weight.numpy().copy()
    scaler.step(opt)
    scaler.update()
    # grads were unscaled before step: effective update independent of scale
    lin2 = nn.Linear(4, 2)
    lin2.weight.set_value(w_before)
    lin2.bias.set_value(np.zeros(2, np.float32))
    assert not np.allclose(lin.weight.numpy(), w_before)


def test_grad_scaler_skips_on_inf():
    lin = nn.Linear(2, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    loss = lin(paddle.ones([1, 2])).mean()
    scaler.scale(loss).backward()
    lin.weight.grad._value = lin.weight.grad._value.at[0, 0].set(np.inf)
    w0 = lin.weight.numpy().copy()
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(lin.weight.numpy(), w0)  # step skipped
    assert scaler._scale == pytest.approx(2.0)  # halved


def test_decorate_casts_model():
    m = nn.Linear(4, 4)
    paddle.amp.decorate(m, level="O2")
    assert m.weight.dtype == paddle.bfloat16


def test_o2_bf16_forward_tracks_f32():
    """The O2 (bf16 weights) forward must track the f32 forward within
    bf16 tolerance on a small BERT — the TPU hot-path numeric guard."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM

    paddle.seed(0)
    cfg = BertConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128, max_position=32,
                     dropout=0.0, attention_dropout=0.0)
    model = BertForMaskedLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (2, 16)))
    labels = paddle.to_tensor(rng.randint(0, 128, (2, 16)))

    with paddle.no_grad():
        out32 = model(ids, labels=labels)
        loss32 = float(out32[0] if isinstance(out32, (list, tuple))
                       else out32)

    paddle.amp.decorate(model, level="O2")
    with paddle.no_grad():
        out16 = model(ids, labels=labels)
        loss16 = float(out16[0] if isinstance(out16, (list, tuple))
                       else out16)

    # bf16 has ~3 significant decimal digits; losses are O(log vocab)
    assert abs(loss16 - loss32) / max(abs(loss32), 1e-6) < 0.02, \
        (loss32, loss16)


def test_amp_o2_norms_do_not_upcast_matmuls():
    """A f32-kept norm under AMP O2 must not promote the rest of the
    network: norms compute stats in f32 but return the INPUT dtype
    (reference kernel contract), so every downstream matmul stays bf16.
    Before the cast-back, all 222 dots of the BERT headline bench step
    ran f32 — half the MXU's bf16 throughput left on the table."""
    import re

    import jax

    model = nn.Sequential(
        nn.Linear(64, 64), nn.LayerNorm(64), nn.Linear(64, 64),
        nn.LayerNorm(64), nn.Linear(64, 10))
    paddle.amp.decorate(model, level="O2")
    model.eval()
    params = {k: p._value for k, p in model.named_parameters()}
    from paddle_tpu.core.tensor import Tensor

    def fwd(pv, x):
        out, _ = model.functional_call(
            {k: Tensor(v) for k, v in pv.items()}, Tensor(x))
        return out._value

    x = np.random.RandomState(0).randn(8, 64).astype(np.float32)
    import jax.numpy as jnp

    txt = jax.jit(fwd).lower(params, jnp.asarray(x, jnp.bfloat16)).as_text()
    dots = re.findall(r"stablehlo\.dot_general.*->\s*tensor<[^>]*x(\w+)>",
                      txt)
    assert dots and all(d == "bf16" for d in dots), dots
    # and the norm itself emits the input dtype
    ln = nn.LayerNorm(64)
    y = ln(paddle.to_tensor(x.astype(np.float32)).astype("bfloat16"))
    assert str(y._value.dtype) == "bfloat16"


def test_decorate_master_weight_routes_to_multi_precision():
    model = nn.Linear(4, 4)
    opt = paddle.optimizer.AdamW(parameters=model.parameters())
    assert opt._multi_precision is None  # AUTO
    paddle.amp.decorate(model, optimizers=opt, level="O2",
                        master_weight=False)
    assert opt._multi_precision is False
    states = opt.functional_init_states(
        {k: p._value for k, p in model.named_parameters()})
    assert all("master" not in s for s in states.values())
    opt2 = paddle.optimizer.AdamW(parameters=model.parameters())
    paddle.amp.decorate(model, optimizers=opt2, level="O2",
                        master_weight=True)
    assert opt2._multi_precision is True
