"""Child process for the crash-consistency suite (test_resilience.py).

Saves a complete checkpoint at step 0, then starts an ASYNC save of a
large (incompressible) state at step 1. The parent launches us with
PADDLE_TPU_FAULT_INJECT=checkpoint.async_started=kill:2 — the injector
SIGKILLs this process at the step-1 fault point, while orbax's
background thread is still writing the tmp dir. `kill -9` semantics: no
atexit, no finally, no orbax cleanup. The parent then asserts the
directory restores to step 0.

Run without injection env, it prints SURVIVED (used to validate the
harness itself).
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from paddle_tpu.io.checkpoint import CheckpointManager  # noqa: E402

ckpt_dir = sys.argv[1]
rng = np.random.RandomState(7)

m = CheckpointManager(ckpt_dir, max_to_keep=None, async_save=True)
state0 = {"w": jnp.asarray(rng.randn(256, 256).astype(np.float32)),
          "step": jnp.int32(0)}
m.save(0, state0, force=True)
m.wait()
print("STEP0_COMMITTED", flush=True)

# random f32 is incompressible: the background OCDBT write of ~64MB is
# still in flight when the injector kills us at the post-queue site
big = {"w": jnp.asarray(rng.randn(4096, 4096).astype(np.float32)),
       "step": jnp.int32(1)}
m.save(1, big, force=True)
# (unreachable under injection: fault_point("checkpoint.async_started")
# inside save() fires kill:2 — call #1 was the step-0 save)
m.wait()
print("SURVIVED", flush=True)
