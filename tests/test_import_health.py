"""Import-time smoke test: `import paddle_tpu` and every public
submodule must import cleanly under JAX_PLATFORMS=cpu with no TPU
present.

Regression guard for the shard_map incident: one bare
`from jax import shard_map` at module scope (moved across JAX versions)
broke collection of 48/72 test files — the suite ran almost entirely
dark while reporting only collection errors. Any future version-skewed
or TPU-only import must fail HERE, loudly and attributably, instead of
silently killing the rest of the suite.
"""
import importlib
import pkgutil

import pytest

import paddle_tpu

# modules that are entry points (argparse/sys.argv at import) — not part
# of the importable API surface
_ENTRY_POINTS = {"paddle_tpu.distributed.launch.__main__"}


def _walk_names():
    names = ["paddle_tpu"]
    for m in pkgutil.walk_packages(paddle_tpu.__path__, prefix="paddle_tpu."):
        if m.name in _ENTRY_POINTS:
            continue
        names.append(m.name)
    return names


def test_every_submodule_imports():
    failures = []
    for name in _walk_names():
        try:
            importlib.import_module(name)
        except Exception as e:  # noqa: BLE001 — collect all, report once
            failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, (
        f"{len(failures)} paddle_tpu module(s) fail to import on a "
        "CPU-only host:\n  " + "\n  ".join(failures))


def test_walk_saw_the_real_tree():
    """The walker itself must not silently degrade: the package has
    dozens of modules; a near-empty walk means __path__ broke."""
    assert len(_walk_names()) > 50


@pytest.mark.parametrize("symbol", ["shard_map"])
def test_jax_compat_exports(symbol):
    """The compat shim must resolve its symbols on the installed JAX."""
    compat = importlib.import_module("paddle_tpu.core.jax_compat")
    assert callable(getattr(compat, symbol))


@pytest.mark.parametrize("name", [
    "tools.staticlib",
    "tools.staticlib.astnav",
    "tools.staticlib.baseline",
    "tools.staticlib.callgraph",
    "tools.staticlib.findings",
    "tools.staticlib.report",
    "tools.staticlib.rules",
    "tools.staticlib.taint",
    "tools.staticlib.waivers",
    "tools.threadlint",
    "tools.threadlint.analyzer",
    "tools.threadlint.rules",
    "tools.tracelint",
    "tools.tracelint.analyzer",
    "tools.fuselint",
    "tools.fuselint.analyzer",
    "tools.fuselint.rules",
    "tools.fuselint.verify",
    "tools.distlint",
    "tools.distlint.analyzer",
    "tools.distlint.rules",
    "tools.distlint.verify",
    "tools.staticcheck",
])
def test_analysis_tooling_imports(name):
    """The static-analysis stack (shared staticlib core + all four
    analyzers + the unified staticcheck entry) must import cleanly —
    CI's lint gates run through these modules, so an import break here
    silently disables the gates."""
    importlib.import_module(name)
