"""Blockwise fused softmax-CE vs the naive logits path (fwd + grads)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.ops.blockwise_ce import blockwise_softmax_ce


def _naive(h, w, labels):
    logits = h.astype(jnp.float32) @ w.astype(jnp.float32).T
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.take_along_axis(logp, labels[:, None], 1)[:, 0].mean()


@pytest.mark.parametrize("n,h,v,block", [
    (16, 8, 128, 32),   # v % block == 0
    (16, 8, 100, 32),   # padding path
    (5, 16, 50, 64),    # single partial block
])
def test_blockwise_matches_naive(n, h, v, block):
    rng = np.random.RandomState(0)
    hid = jnp.asarray(rng.randn(n, h).astype(np.float32))
    w = jnp.asarray(rng.randn(v, h).astype(np.float32) * 0.1)
    labels = jnp.asarray(rng.randint(0, v, n))

    loss = blockwise_softmax_ce(hid, w, labels, block)
    ref = _naive(hid, w, labels)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-5)

    g = jax.grad(lambda a, b: blockwise_softmax_ce(a, b, labels, block),
                 argnums=(0, 1))(hid, w)
    gr = jax.grad(lambda a, b: _naive(a, b, labels), argnums=(0, 1))(hid, w)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(gr[0]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(gr[1]),
                               rtol=1e-4, atol=1e-6)


def test_blockwise_under_jit_bf16():
    rng = np.random.RandomState(1)
    hid = jnp.asarray(rng.randn(8, 16), jnp.bfloat16)
    w = jnp.asarray(rng.randn(96, 16) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 96, 8))
    loss = jax.jit(lambda a, b: blockwise_softmax_ce(a, b, labels, 32))(
        hid, w)
    ref = _naive(hid, w, labels)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-2)


def test_gpt_fused_loss_parity():
    """GPTForCausalLM with fused_loss on matches the naive loss path and
    trains (grads flow through the tape)."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    kw = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
              max_position=16, dropout=0.0, use_flash=False)
    m1 = GPTForCausalLM(GPTConfig(fused_loss=True, **kw))
    paddle.seed(0)
    m2 = GPTForCausalLM(GPTConfig(fused_loss=False, **kw))
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (4, 12)))
    labels = paddle.to_tensor(rng.randint(0, 128, (4, 12)))
    l1 = m1(ids, labels=labels)
    l2 = m2(ids, labels=labels)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    l1.backward()
    g = m1.gpt.wte.weight.grad
    assert g is not None
    assert float(np.abs(np.asarray(g.numpy())).sum()) > 0


def test_incubate_alias():
    from paddle_tpu import incubate

    rng = np.random.RandomState(2)
    h = paddle.to_tensor(rng.randn(6, 8).astype(np.float32))
    w = paddle.to_tensor(rng.randn(40, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 40, 6))
    h.stop_gradient = False
    loss = incubate.softmax_cross_entropy_blockwise(h, w, y, block=16)
    loss.backward()
    assert h.grad is not None


def test_ignore_index_parity():
    """labels == -100 are excluded from the mean and get zero grads (the
    cross_entropy contract the fused GPT path must keep)."""
    rng = np.random.RandomState(3)
    hid = jnp.asarray(rng.randn(8, 8).astype(np.float32))
    w = jnp.asarray(rng.randn(64, 8).astype(np.float32) * 0.1)
    labels = rng.randint(0, 64, 8)
    labels[[1, 4, 5]] = -100
    labels_j = jnp.asarray(labels)
    kept = labels != -100

    def ref(a, b):
        logits = a @ b.T
        logp = jax.nn.log_softmax(logits, -1)
        pick = -jnp.take_along_axis(
            logp, jnp.clip(labels_j, 0, 63)[:, None], 1)[:, 0]
        return jnp.where(jnp.asarray(kept), pick, 0.0).sum() / kept.sum()

    loss = blockwise_softmax_ce(hid, w, labels_j, 16)
    np.testing.assert_allclose(float(loss), float(ref(hid, w)), rtol=1e-5)
    g = jax.grad(lambda a: blockwise_softmax_ce(a, w, labels_j, 16))(hid)
    gr = jax.grad(lambda a: ref(a, w))(hid)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-4,
                               atol=1e-7)
    # ignored rows: exactly zero gradient
    np.testing.assert_array_equal(np.asarray(g)[~kept], 0.0)


def test_bert_fused_mlm_loss_parity():
    """BertForMaskedLM with fused_loss on matches the materialized-logit
    path — loss AND grads (the decoder bias rides the kernel's bias
    argument), including ignore_index=-100 rows."""
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM

    kw = dict(vocab_size=256, hidden_size=32, num_layers=1, num_heads=2,
              intermediate_size=64, max_position=32, dropout=0.0,
              attention_dropout=0.0)
    paddle.seed(7)
    m1 = BertForMaskedLM(BertConfig(fused_loss=True, **kw))
    paddle.seed(7)
    m2 = BertForMaskedLM(BertConfig(fused_loss=False, **kw))
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 256, (2, 16)))
    lab = rng.randint(0, 256, (2, 16))
    lab[0, :5] = -100  # ignored positions must not contribute
    labels = paddle.to_tensor(lab)
    l1 = m1(ids, labels=labels)
    l2 = m2(ids, labels=labels)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    l1.backward()
    l2.backward()
    for (k1, p1), (k2, p2) in zip(m1.named_parameters(),
                                  m2.named_parameters()):
        assert k1 == k2
        if "seq_relationship" in k1:  # NSP head: no labels given
            continue
        # both paths must agree on WHICH params got grads (a fused path
        # silently dropping e.g. the bias cotangent must fail here)
        assert (p1.grad is None) == (p2.grad is None), k1
        if p1.grad is None:
            continue
        np.testing.assert_allclose(
            np.asarray(p1.grad.numpy()), np.asarray(p2.grad.numpy()),
            rtol=2e-4, atol=1e-6, err_msg=k1)


def test_blockwise_bias_matches_naive():
    """Optional [V] bias: value and (dh, dw, db) grads vs the naive
    materialized logits+bias path."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(4)
    n, h, v, block = 24, 16, 70, 32  # v % block != 0: padded tail
    hid = jnp.asarray(rng.randn(n, h).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(v, h).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(v).astype(np.float32) * 0.5)
    labels = jnp.asarray(rng.randint(0, v, n))

    def naive(hh, ww, bb):
        logits = hh @ ww.T + bb
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
        return (lse - picked).mean()

    def fused(hh, ww, bb):
        return blockwise_softmax_ce(hh, ww, labels, block, bias=bb)

    np.testing.assert_allclose(float(fused(hid, w, b)),
                               float(naive(hid, w, b)), rtol=1e-5)
    gf = jax.grad(fused, argnums=(0, 1, 2))(hid, w, b)
    gn = jax.grad(naive, argnums=(0, 1, 2))(hid, w, b)
    for a, bb_, name in zip(gf, gn, "h w b".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb_),
                                   rtol=1e-4, atol=1e-6, err_msg=name)
