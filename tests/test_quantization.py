"""PTQ/QAT (reference: fluid/contrib/slim/quantization — see
paddle_tpu/quantization docstrings for per-class mapping)."""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import (
    ImperativePTQ, ImperativeQuantAware, PostTrainingQuantization,
    QuantConfig, QuantizedConv2D, QuantizedLinear, fake_quant,
    quantize_weight,
)


def _mlp():
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))


class TestPrimitives:
    def test_quantize_weight_per_channel(self):
        w = np.random.RandomState(0).randn(6, 4).astype(np.float32)
        q, s = quantize_weight(w, channel_axis=1)
        assert q.dtype == np.int8 and s.shape == (1, 4)
        np.testing.assert_allclose(q * s, w, atol=float(s.max()))

    def test_fake_quant_ste_grad(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 8, dtype=np.float32))
        x.stop_gradient = False
        y = fake_quant(x, 1.0)
        (y ** 2).sum().backward()
        # STE: dy/dx == 2*qdq(x) (identity through the rounding)
        np.testing.assert_allclose(np.asarray(x.grad.numpy()),
                                   2 * np.asarray(y.numpy()), rtol=1e-5)


class TestPTQ:
    def test_ptq_linear_accuracy(self):
        paddle.seed(0)
        model = _mlp()
        rng = np.random.RandomState(0)
        calib = [paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
                 for _ in range(4)]
        ref_out = np.asarray(model(calib[0]).numpy())

        ptq = ImperativePTQ(QuantConfig())
        ptq.quantize(model)
        for x in calib:
            model(x)
        ptq.convert(model)
        assert isinstance(model[0], QuantizedLinear)
        out = np.asarray(model(calib[0]).numpy())
        # int8 tolerance: ~1% of dynamic range
        err = np.abs(out - ref_out).max() / (np.abs(ref_out).max() + 1e-8)
        assert err < 0.05, err

    def test_int8_ops_in_hlo(self):
        paddle.seed(1)
        model = _mlp()
        ptq = ImperativePTQ()
        ptq.quantize(model)
        x = paddle.randn([4, 16])
        model(x)
        ptq.convert(model)

        def fwd(xv):
            from paddle_tpu.core.tensor import Tensor

            return model(Tensor(xv))._value

        hlo = jax.jit(fwd).lower(x._value).as_text()
        assert "i8" in hlo or "s8" in hlo, "no int8 types in lowered HLO"

    def test_ptq_conv_lenet_accuracy(self):
        from paddle_tpu.vision.models import LeNet

        paddle.seed(2)
        model = LeNet()
        model.eval()
        rng = np.random.RandomState(2)
        xs = [paddle.to_tensor(rng.randn(4, 1, 28, 28).astype(np.float32))
              for _ in range(3)]
        ref = np.asarray(model(xs[0]).numpy())
        ptq = ImperativePTQ(QuantConfig(activation_quantize_type="hist"))
        ptq.quantize(model)
        for x in xs:
            model(x)
        ptq.convert(model)
        quant_types = [type(l).__name__ for _, l in model.named_sublayers()]
        assert "QuantizedConv2D" in quant_types
        assert "QuantizedLinear" in quant_types
        out = np.asarray(model(xs[0]).numpy())
        # logits shift but argmax ranking should broadly hold on random net
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert err < 0.2, err

    def test_post_training_quantization_api(self):
        paddle.seed(3)
        model = _mlp()
        rng = np.random.RandomState(3)

        class DS(paddle.io.Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return rng.randn(16).astype(np.float32)

        loader = paddle.io.DataLoader(DS(), batch_size=4)
        ptq = PostTrainingQuantization(model=model, data_loader=loader,
                                       algo="KL", batch_nums=2)
        qmodel = ptq.quantize()
        assert isinstance(qmodel[0], QuantizedLinear)


class TestQAT:
    def test_qat_train_then_convert(self):
        paddle.seed(4)
        model = _mlp()
        qat = ImperativeQuantAware()
        qat.quantize(model)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        rng = np.random.RandomState(4)
        x = paddle.to_tensor(rng.randn(16, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        losses = []
        for _ in range(15):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        model.eval()
        ref = np.asarray(model(x).numpy())
        qat.convert(model)
        assert isinstance(model[0], QuantizedLinear)
        out = np.asarray(model(x).numpy())
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert err < 0.1, err


class TestReviewRegressions:
    def test_ptq_bare_linear_root(self):
        """A quantizable ROOT layer must be converted (returned), not
        silently left float."""
        paddle.seed(7)
        lin = nn.Linear(8, 4)
        ptq = ImperativePTQ()
        ptq.quantize(lin)
        lin(paddle.randn([4, 8]))
        out = ptq.convert(lin)
        assert isinstance(out, QuantizedLinear)

    def test_ptq_conv_nhwc(self):
        paddle.seed(8)
        conv = nn.Conv2D(3, 5, 3, data_format="NHWC")
        conv.eval()
        x = paddle.randn([2, 8, 8, 3])
        ref = np.asarray(conv(x).numpy())
        ptq = ImperativePTQ()
        ptq.quantize(conv)
        conv(x)
        q = ptq.convert(conv)
        assert isinstance(q, QuantizedConv2D)
        out = np.asarray(q(x).numpy())
        assert out.shape == ref.shape
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert err < 0.1, err


def test_asp_2d_pattern_and_density():
    """The 2-D greedy must satisfy the n:m cap on BOTH axes (reference
    guarantee) and keep density near n/m (the reference greedy fills most
    rows to exactly n; the global descending scan matches it)."""
    from paddle_tpu.incubate import asp

    rng = np.random.RandomState(3)
    dens = []
    for _ in range(10):
        w = rng.randn(8, 8)
        mask = asp.get_mask_2d_greedy(w, 2, 4)
        for r0 in range(0, 8, 4):
            for c0 in range(0, 8, 4):
                block = mask[r0:r0 + 4, c0:c0 + 4]
                assert (block.sum(1) <= 2).all()
                assert (block.sum(0) <= 2).all()
        dens.append(mask.mean())
    assert np.mean(dens) > 0.45, np.mean(dens)


class TestNNQuantNamespace:
    """paddle.nn.quant — the fake-quant layers the passes insert
    (reference nn/quant/quant_layers.py)."""

    def test_fake_quant_abs_max(self):
        import paddle_tpu.nn.quant as q

        x = paddle.to_tensor(np.linspace(-2, 2, 16, dtype=np.float32))
        y = q.FakeQuantAbsMax(quant_bits=8)(x)
        # qdq error bounded by one quantization step
        step = 2.0 / 127
        assert np.abs(y.numpy() - x.numpy()).max() <= step
        x.stop_gradient = False
        loss = (q.FakeQuantAbsMax()(x) ** 2).mean()
        loss.backward()
        assert x.grad is not None  # straight-through estimator

    def test_fake_quant_moving_average_tracks_and_freezes(self):
        import paddle_tpu.nn.quant as q

        fq = q.FakeQuantMovingAverageAbsMax(moving_rate=0.5)
        a = paddle.to_tensor(np.full(8, 4.0, np.float32))
        b = paddle.to_tensor(np.full(8, 2.0, np.float32))
        fq(a)
        s1 = float(fq.scale.numpy())
        assert s1 == 4.0  # first observation seeds the scale
        fq(b)
        assert float(fq.scale.numpy()) == 0.5 * 4.0 + 0.5 * 2.0
        fq.eval()
        frozen = float(fq.scale.numpy())
        fq(paddle.to_tensor(np.full(8, 100.0, np.float32)))
        assert float(fq.scale.numpy()) == frozen  # eval: no update

    def test_channel_wise_and_output_scale(self):
        import paddle_tpu.nn.quant as q

        w = paddle.to_tensor(
            np.stack([np.full(4, 0.1), np.full(4, 10.0)]).astype(
                np.float32))
        y = q.FakeQuantChannelWiseAbsMax(quant_axis=0)(w)
        # per-channel scales: the small channel keeps fine resolution
        assert np.abs(y.numpy()[0] - w.numpy()[0]).max() < 1e-3
        obs = q.MovingAverageAbsMaxScale()
        out = obs(w)
        np.testing.assert_array_equal(out.numpy(), w.numpy())
        assert float(obs.scale.numpy()) == 10.0


def test_fleet_utils_fs_and_hybrid_util():
    from paddle_tpu.distributed.fleet.utils import fs
    from paddle_tpu.distributed.fleet.utils import hybrid_parallel_util \
        as hpu
    from paddle_tpu import nn
    import pytest

    assert fs.LocalFS().is_exist("/")
    with pytest.raises(NotImplementedError, match="LocalFS"):
        fs.HDFSClient()
    lin = nn.Linear(4, 4)
    loss = (lin(paddle.randn([2, 4])) ** 2).mean()
    loss.backward()
    hpu.fused_allreduce_gradients(list(lin.parameters()), None)
    assert lin.weight.grad is not None
    assert hpu.broadcast_dp_parameters(lin, None) is None


def test_nn_quant_unseeded_scale_is_identity_and_traces():
    """Eval with an untrained scale passes through (quantizing by a
    floored zero scale would zero activations); the EMA update traces
    under to_static (buffer capture, the BN mechanism)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.quant as q

    fq = q.FakeQuantMovingAverageAbsMax()
    fq.eval()
    x = paddle.to_tensor(np.linspace(-2, 2, 8, dtype=np.float32))
    np.testing.assert_array_equal(fq(x).numpy(), x.numpy())  # identity

    fq2 = q.FakeQuantMovingAverageAbsMax(moving_rate=0.5)
    traced = paddle.jit.to_static(fq2)
    y = traced(x)  # must NOT TracerArrayConversionError
    assert float(fq2.scale.numpy()) == 2.0  # buffer update captured
    assert np.abs(y.numpy() - x.numpy()).max() <= 2.0 / 127
