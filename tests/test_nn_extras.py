"""New nn functionals/layers: affine_grid, grid_sample, diag_embed,
gather_tree, sparse_attention, hsigmoid_loss, margin_cross_entropy,
Silu, HSigmoidLoss, BeamSearchDecoder/dynamic_decode, inplace tensor ops.

Reference: python/paddle/nn/functional/{vision,extension,loss,
sparse_attention}.py, nn/decode.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

F = nn.functional


class TestAffineGridSample:
    def test_identity_affine_grid_sample(self):
        paddle.seed(0)
        x = paddle.randn([2, 3, 8, 8])
        theta = paddle.to_tensor(
            np.tile(np.array([[1.0, 0, 0], [0, 1.0, 0]], np.float32),
                    (2, 1, 1)))
        grid = F.affine_grid(theta, [2, 3, 8, 8], align_corners=True)
        assert grid.shape == [2, 8, 8, 2]
        out = F.grid_sample(x, grid, align_corners=True)
        np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_affine_grid_translation(self):
        # shift by one pixel in x (normalized 2/(W-1) with align_corners)
        W = 5
        theta = paddle.to_tensor(np.array(
            [[[1.0, 0, 2.0 / (W - 1)], [0, 1.0, 0]]], np.float32))
        x = paddle.to_tensor(
            np.arange(W * W, dtype=np.float32).reshape(1, 1, W, W))
        grid = F.affine_grid(theta, [1, 1, W, W], align_corners=True)
        out = F.grid_sample(x, grid, align_corners=True)
        np.testing.assert_allclose(out.numpy()[0, 0, :, :-1],
                                   x.numpy()[0, 0, :, 1:], atol=1e-4)

    def test_grid_sample_modes(self):
        x = paddle.to_tensor(np.array([[[[0.0, 10.0], [20.0, 30.0]]]],
                                      np.float32))
        # sample exactly at the center: bilinear avg of the 4 corners
        grid = paddle.to_tensor(np.zeros((1, 1, 1, 2), np.float32))
        out = F.grid_sample(x, grid, align_corners=True)
        np.testing.assert_allclose(out.numpy().ravel(), [15.0], atol=1e-5)
        # out of range with zeros padding -> 0, with border -> edge value
        far = paddle.to_tensor(np.full((1, 1, 1, 2), 5.0, np.float32))
        z = F.grid_sample(x, far, padding_mode="zeros")
        np.testing.assert_allclose(z.numpy().ravel(), [0.0], atol=1e-6)
        b = F.grid_sample(x, far, padding_mode="border")
        np.testing.assert_allclose(b.numpy().ravel(), [30.0], atol=1e-5)

    def test_grid_sample_grad(self):
        x = paddle.randn([1, 2, 4, 4])
        x.stop_gradient = False
        grid = paddle.to_tensor(
            np.random.RandomState(0).uniform(-1, 1, (1, 3, 3, 2))
            .astype(np.float32))
        grid.stop_gradient = False
        out = F.grid_sample(x, grid)
        out.sum().backward()
        assert x.grad is not None and grid.grad is not None


def test_diag_embed():
    v = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    out = F.diag_embed(v)
    assert out.shape == [2, 2, 2]
    np.testing.assert_allclose(out.numpy()[0], [[1, 0], [0, 2]])
    off = F.diag_embed(v, offset=1)
    assert off.shape == [2, 3, 3]
    np.testing.assert_allclose(off.numpy()[1],
                               [[0, 3, 0], [0, 0, 4], [0, 0, 0]])


def test_gather_tree():
    # example from the reference docstring
    ids = paddle.to_tensor(np.array(
        [[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]]))
    parents = paddle.to_tensor(np.array(
        [[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]]))
    out = F.gather_tree(ids, parents)
    np.testing.assert_array_equal(
        np.asarray(out._value),
        [[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]])


def test_sparse_attention_matches_masked_dense():
    rng = np.random.RandomState(0)
    N, H, S, D = 1, 2, 4, 8
    q, k, v = [rng.randn(N, H, S, D).astype(np.float32) for _ in range(3)]
    # full pattern -> must equal ordinary attention
    offset = np.tile(np.arange(0, (S + 1) * S, S, dtype=np.int32),
                     (N, H, 1))
    cols = np.tile(np.tile(np.arange(S, dtype=np.int32), S), (N, H, 1))
    out = F.sparse_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                             paddle.to_tensor(v), paddle.to_tensor(offset),
                             paddle.to_tensor(cols))
    att = np.einsum("nhqd,nhkd->nhqk", q, k) / np.sqrt(D)
    att = np.exp(att - att.max(-1, keepdims=True))
    att /= att.sum(-1, keepdims=True)
    expect = np.einsum("nhqk,nhkd->nhqd", att, v)
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)

    # banded pattern (diagonal only) -> each row returns its own value row
    offset = np.tile(np.arange(S + 1, dtype=np.int32), (N, H, 1))
    cols = np.tile(np.arange(S, dtype=np.int32), (N, H, 1))
    out = F.sparse_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                             paddle.to_tensor(v), paddle.to_tensor(offset),
                             paddle.to_tensor(cols))
    np.testing.assert_allclose(out.numpy(), v, rtol=1e-5)

    # attn_mask is a 0=masked indicator (reference semantics): full CSR
    # pattern + mask allowing only the diagonal == diagonal-only result
    full_off = np.tile(np.arange(0, (S + 1) * S, S, dtype=np.int32),
                       (N, H, 1))
    full_cols = np.tile(np.tile(np.arange(S, dtype=np.int32), S),
                        (N, H, 1))
    am = np.broadcast_to(np.eye(S, dtype=np.float32), (N, H, S, S)).copy()
    out_m = F.sparse_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(full_off), paddle.to_tensor(full_cols),
        attn_mask=paddle.to_tensor(am))
    np.testing.assert_allclose(out_m.numpy(), v, rtol=1e-5)


class TestHSigmoid:
    def test_loss_shape_and_grad(self):
        paddle.seed(3)
        x = paddle.randn([6, 16])
        x.stop_gradient = False
        label = paddle.to_tensor(np.array([0, 1, 2, 3, 4, 5]))
        layer = nn.HSigmoidLoss(16, 8)
        loss = layer(x, label)
        assert loss.shape == [6, 1]
        assert np.all(np.isfinite(loss.numpy())) and np.all(
            loss.numpy() > 0)
        loss.sum().backward()
        assert x.grad is not None and layer.weight.grad is not None

    def test_training_separates_classes(self):
        paddle.seed(4)
        rng = np.random.RandomState(0)
        centers = rng.randn(4, 8).astype(np.float32) * 3
        xs = np.concatenate([centers[i] + 0.1 * rng.randn(16, 8)
                             for i in range(4)]).astype(np.float32)
        ys = np.repeat(np.arange(4), 16)
        layer = nn.HSigmoidLoss(8, 4)
        opt = paddle.optimizer.Adam(learning_rate=0.1,
                                    parameters=layer.parameters())
        first = None
        for _ in range(30):
            loss = layer(paddle.to_tensor(xs),
                         paddle.to_tensor(ys)).mean()
            if first is None:
                first = float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < first * 0.3, (first, float(loss))

    def test_custom_path(self):
        x = paddle.randn([2, 8])
        label = paddle.to_tensor(np.array([0, 1]))
        table = paddle.to_tensor(np.array([[0, 1, -1], [0, 2, 3]]))
        code = paddle.to_tensor(np.array([[1, 0, 0], [0, 1, 1]]))
        layer = nn.HSigmoidLoss(8, 5, is_custom=True)
        loss = layer(x, label, path_table=table, path_code=code)
        assert loss.shape == [2, 1]


def test_margin_cross_entropy():
    paddle.seed(5)
    rng = np.random.RandomState(0)
    feats = rng.randn(8, 16).astype(np.float32)
    feats /= np.linalg.norm(feats, axis=1, keepdims=True)
    w = rng.randn(16, 10).astype(np.float32)
    w /= np.linalg.norm(w, axis=0, keepdims=True)
    cos = feats @ w
    label = rng.randint(0, 10, 8)
    loss, sm = F.margin_cross_entropy(
        paddle.to_tensor(cos), paddle.to_tensor(label),
        return_softmax=True, reduction="mean")
    assert np.isfinite(float(loss))
    np.testing.assert_allclose(sm.numpy().sum(-1), 1.0, rtol=1e-5)
    # margin increases the loss vs plain scaled CE
    plain = F.margin_cross_entropy(
        paddle.to_tensor(cos), paddle.to_tensor(label),
        margin1=1.0, margin2=0.0, margin3=0.0)
    assert float(loss) > float(plain)


def test_beam_search_decode():
    """Greedy-equivalent check: a cell whose logits always prefer one token
    chain; beam search must recover it and stop at end_token."""
    paddle.seed(6)
    V, B, beam = 7, 2, 3

    class FixedCell(nn.Layer):
        def forward(self, ids, states):
            # state counts steps; prefer token (step+1), then end at 4
            step = states
            logits = np.full((ids.shape[0], V), -5.0, np.float32)
            nxt = min(int(np.asarray(step._value)[0]) + 1, 4)
            logits[:, nxt] = 5.0
            return paddle.to_tensor(logits), paddle.to_tensor(
                step._value + 1)

    dec = paddle.nn.BeamSearchDecoder(FixedCell(), start_token=0,
                                      end_token=4, beam_size=beam)
    init = paddle.to_tensor(np.zeros(B, np.int64))  # per-batch; tiled inside
    ids, scores = paddle.nn.dynamic_decode(dec, init, max_step_num=8)
    seq = np.asarray(ids._value)[0, 0]
    # best beam decodes 1, 2, 3, 4(end)
    np.testing.assert_array_equal(seq[:4], [1, 2, 3, 4])
    assert scores.shape == [B, beam]


def test_inplace_tensor_ops():
    x = paddle.to_tensor(np.array([0.5, -0.2], np.float32))
    import scipy.special as sp

    expect = sp.erfinv(x.numpy())
    x.erfinv_()
    np.testing.assert_allclose(x.numpy(), expect, rtol=1e-5)

    a = paddle.to_tensor(np.zeros(3, np.float32))
    b = paddle.to_tensor(np.ones(3, np.float32))
    a.lerp_(b, 0.25)
    np.testing.assert_allclose(a.numpy(), 0.25)

    arr = paddle.to_tensor(np.zeros((2, 3), np.float32))
    idx = paddle.to_tensor(np.array([[0], [2]]))
    arr.put_along_axis_(idx, paddle.to_tensor(7.0), axis=1)
    np.testing.assert_allclose(arr.numpy(),
                               [[7, 0, 0], [0, 0, 7]])

    m = paddle.to_tensor(np.array([[4.0, 0.0], [0.0, 2.0]], np.float32))
    np.testing.assert_allclose(paddle.inverse(m).numpy(),
                               [[0.25, 0], [0, 0.5]], rtol=1e-6)


def test_data_dependent_ops_refuse_static_baking():
    """sequence_mask(maxlen=None) / class_center_sample read data off
    the build-time dummy feed under static mode — they must refuse
    instead of baking (the accuracy/auc bug class)."""
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [4], "int64")
            with pytest.raises(ValueError, match="maxlen"):
                nn.functional.sequence_mask(x)
            # explicit maxlen stays fine
            m = nn.functional.sequence_mask(x, maxlen=8)
            assert m.shape[-1] == 8
            with pytest.raises(ValueError, match="dygraph"):
                nn.functional.class_center_sample(x, 10, 4)
    finally:
        paddle.disable_static()
