"""Constructor -> forward -> state_dict round-trip sweep over the nn
layer zoo. Catches breakage in layer registration, parameter naming,
and (de)serialization that narrower per-layer tests can miss.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

RNG = np.random.RandomState(0)


def _x(*shape):
    return paddle.to_tensor(RNG.randn(*shape).astype(np.float32))


# (ctor, args, kwargs, input_builder)
SWEEP = [
    (nn.Linear, (8, 4), {}, lambda: _x(2, 8)),
    (nn.Embedding, (10, 6), {}, lambda: paddle.to_tensor(
        np.array([[1, 2], [3, 4]], np.int64))),
    (nn.Conv1D, (3, 5, 3), {}, lambda: _x(2, 3, 9)),
    (nn.Conv2D, (3, 5, 3), {}, lambda: _x(2, 3, 9, 9)),
    (nn.Conv3D, (2, 4, 3), {}, lambda: _x(1, 2, 5, 6, 6)),
    (nn.Conv1DTranspose, (3, 5, 3), {}, lambda: _x(2, 3, 9)),
    (nn.Conv2DTranspose, (3, 5, 3), {}, lambda: _x(2, 3, 9, 9)),
    (nn.BatchNorm1D, (4,), {}, lambda: _x(2, 4, 7)),
    (nn.BatchNorm2D, (4,), {}, lambda: _x(2, 4, 5, 5)),
    (nn.BatchNorm3D, (4,), {}, lambda: _x(2, 4, 3, 4, 4)),
    (nn.LayerNorm, (6,), {}, lambda: _x(2, 5, 6)),
    (nn.GroupNorm, (2, 4), {}, lambda: _x(2, 4, 5, 5)),
    (nn.InstanceNorm2D, (4,), {}, lambda: _x(2, 4, 5, 5)),
    (nn.SpectralNorm, ((5, 4), 0, 1), {}, lambda: _x(5, 4)),
    (nn.MaxPool2D, (2,), {}, lambda: _x(2, 3, 8, 8)),
    (nn.AvgPool2D, (2,), {}, lambda: _x(2, 3, 8, 8)),
    (nn.AdaptiveAvgPool2D, (3,), {}, lambda: _x(2, 3, 8, 8)),
    (nn.AdaptiveMaxPool2D, (3,), {}, lambda: _x(2, 3, 8, 8)),
    (nn.ReLU, (), {}, lambda: _x(4, 4)),
    (nn.GELU, (), {}, lambda: _x(4, 4)),
    (nn.PReLU, (), {}, lambda: _x(4, 4)),
    (nn.Softmax, (), {}, lambda: _x(4, 4)),
    (nn.Dropout, (0.5,), {}, lambda: _x(4, 4)),
    (nn.Dropout2D, (0.5,), {}, lambda: _x(2, 3, 4, 4)),
    (nn.AlphaDropout, (0.5,), {}, lambda: _x(4, 4)),
    (nn.Pad2D, (1,), {}, lambda: _x(2, 3, 4, 4)),
    (nn.ZeroPad2D, (1,), {}, lambda: _x(2, 3, 4, 4)),
    (nn.Upsample, (), {"scale_factor": 2}, lambda: _x(2, 3, 4, 4)),
    (nn.UpsamplingBilinear2D, (), {"scale_factor": 2},
     lambda: _x(2, 3, 4, 4)),
    (nn.PixelShuffle, (2,), {}, lambda: _x(2, 8, 4, 4)),
    (nn.PixelUnshuffle, (2,), {}, lambda: _x(2, 2, 8, 8)),
    (nn.ChannelShuffle, (2,), {}, lambda: _x(2, 4, 4, 4)),
    (nn.Flatten, (), {}, lambda: _x(2, 3, 4)),
    (nn.CosineSimilarity, (), {"axis": 1},
     lambda: (_x(3, 8), _x(3, 8))),
    (nn.PairwiseDistance, (), {}, lambda: (_x(3, 8), _x(3, 8))),
    (nn.Bilinear, (4, 5, 3), {}, lambda: (_x(2, 4), _x(2, 5))),
    (nn.SimpleRNN, (4, 6), {}, lambda: _x(2, 5, 4)),
    (nn.LSTM, (4, 6), {}, lambda: _x(2, 5, 4)),
    (nn.GRU, (4, 6), {}, lambda: _x(2, 5, 4)),
    (nn.MultiHeadAttention, (8, 2), {}, lambda: _x(2, 5, 8)),
    (nn.TransformerEncoderLayer, (8, 2, 16), {"dropout": 0.0},
     lambda: _x(2, 5, 8)),
    (nn.LocalResponseNorm, (5,), {}, lambda: _x(2, 7, 6, 6)),
    (nn.Identity, (), {}, lambda: _x(3, 3)),
    (nn.Unfold, (3,), {}, lambda: _x(2, 3, 8, 8)),
    (nn.Fold, ((6, 6), 3), {}, lambda: _x(2, 27, 16)),
]


@pytest.mark.parametrize(
    "ctor,args,kwargs,make_input", SWEEP,
    ids=[c[0].__name__ for c in SWEEP])
def test_layer_forward_and_state_roundtrip(ctor, args, kwargs, make_input):
    paddle.seed(7)
    layer = ctor(*args, **kwargs)
    layer.eval()
    inp = make_input()
    # SERIALIZED snapshot before the first forward: state_dict() values
    # are live references (reference/torch semantics), and stateful
    # layers (SpectralNorm's power iteration) mutate them in place on
    # every call — only serialization is a true snapshot
    import io

    buf = io.BytesIO()
    paddle.save(layer.state_dict(), buf)
    out = layer(*inp) if isinstance(inp, tuple) else layer(inp)
    first = out[0] if isinstance(out, (list, tuple)) else out
    assert np.all(np.isfinite(np.asarray(first.numpy()))), ctor.__name__

    buf.seek(0)
    fresh = ctor(*args, **kwargs)
    fresh.eval()
    fresh.set_state_dict(paddle.load(buf))
    out2 = fresh(*inp) if isinstance(inp, tuple) else fresh(inp)
    second = out2[0] if isinstance(out2, (list, tuple)) else out2
    np.testing.assert_allclose(np.asarray(first.numpy()),
                               np.asarray(second.numpy()),
                               rtol=1e-5, atol=1e-6)
