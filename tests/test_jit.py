"""paddle.jit tests (reference: unittests test_jit_save_load.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_to_static_layer_parity():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    x = paddle.randn([3, 4])
    eager = net(x).numpy()
    st = paddle.jit.to_static(net)
    np.testing.assert_allclose(st(x).numpy(), eager, rtol=1e-5, atol=1e-6)
    # second call hits the jit cache
    np.testing.assert_allclose(st(x).numpy(), eager, rtol=1e-5, atol=1e-6)


def test_to_static_function_decorator():
    @paddle.jit.to_static
    def f(a, b):
        return a * b + 1

    out = f(paddle.ones([2, 2]), paddle.full([2, 2], 3.0))
    np.testing.assert_allclose(out.numpy(), np.full((2, 2), 4.0))


def test_to_static_respects_training_mode():
    net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    st = paddle.jit.to_static(net)
    x = paddle.ones([8, 4])
    net.eval()
    o1 = st(x).numpy()
    o2 = st(x).numpy()
    np.testing.assert_allclose(o1, o2)
    net.train()
    o3 = st(x).numpy()
    assert (o3 == 0).any()  # dropout active


def test_jit_save_load_roundtrip(tmp_path):
    paddle.seed(5)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    path = str(tmp_path / "m")
    paddle.jit.save(net, path,
                    input_spec=[paddle.InputSpec([-1, 4], "float32")])
    loaded = paddle.jit.load(path)
    for bs in (1, 6):
        x = paddle.randn([bs, 4])
        np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                                   rtol=1e-5, atol=1e-6)
    with pytest.raises(RuntimeError):
        loaded.train()


def test_jit_save_load_bn_uses_eval_stats(tmp_path):
    net = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4))
    net.train()
    for _ in range(3):
        net(paddle.randn([16, 4]))  # accumulate running stats
    net.eval()
    x = paddle.randn([5, 4])
    path = str(tmp_path / "bn")
    paddle.jit.save(net, path,
                    input_spec=[paddle.InputSpec([-1, 4], "float32")])
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(), rtol=1e-4,
                               atol=1e-5)


def test_enable_to_static_toggle():
    net = nn.Linear(2, 2)
    st = paddle.jit.to_static(net)
    x = paddle.randn([1, 2])
    paddle.jit.enable_to_static(False)
    try:
        out = st(x)  # falls through to eager
        assert out.shape == [1, 2]
    finally:
        paddle.jit.enable_to_static(True)
