"""dygraph-to-static control-flow conversion (reference
jit/dy2static/program_translator.py + convert_operators.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import convert_to_static


class TestIfConversion:
    def test_tensor_predicate_if(self):
        @paddle.jit.to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        pos = f(paddle.to_tensor(np.ones(4, np.float32)))
        np.testing.assert_allclose(pos.numpy(), 2.0)
        neg = f(paddle.to_tensor(-np.ones(4, np.float32)))
        np.testing.assert_allclose(neg.numpy(), -2.0)

    def test_elif_chain(self):
        @paddle.jit.to_static
        def f(x):
            s = x.sum()
            if s > 10.0:
                out = x * 3.0
            elif s > 0.0:
                out = x * 2.0
            else:
                out = x * 0.0
            return out

        big = f(paddle.to_tensor(np.full(4, 5.0, np.float32)))
        np.testing.assert_allclose(big.numpy(), 15.0)
        small = f(paddle.to_tensor(np.full(4, 0.5, np.float32)))
        np.testing.assert_allclose(small.numpy(), 1.0)
        neg = f(paddle.to_tensor(np.full(4, -1.0, np.float32)))
        np.testing.assert_allclose(neg.numpy(), 0.0)

    def test_python_predicate_keeps_eager_semantics(self):
        calls = []

        def g(x, flag):
            if flag:  # plain python bool: no tracing of the dead branch
                calls.append("t")
                return x + 1.0
            calls.append("f")
            return x - 1.0

        conv = convert_to_static(g)
        out = conv(paddle.to_tensor(np.zeros(2, np.float32)), True)
        np.testing.assert_allclose(out.numpy(), 1.0)
        # converted only the outcome, not both branches
        assert calls == ["t"]

    def test_if_reads_outer_var(self):
        @paddle.jit.to_static
        def f(x):
            base = x + 10.0
            if x.sum() > 0:
                y = base * 1.0
            else:
                y = base * -1.0
            return y

        out = f(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(out.numpy(), 11.0)


class TestWhileConversion:
    def test_tensor_while(self):
        @paddle.jit.to_static
        def f(x):
            i = paddle.to_tensor(np.int32(0))
            while i < 5:
                x = x * 2.0
                i = i + 1
            return x

        out = f(paddle.to_tensor(np.ones(3, np.float32)))
        np.testing.assert_allclose(out.numpy(), 32.0)

    def test_python_while_untouched(self):
        def g(x, n):
            k = 0
            while k < n:
                x = x + 1.0
                k += 1
            return x

        conv = convert_to_static(g)
        out = conv(paddle.to_tensor(np.zeros(2, np.float32)), 3)
        np.testing.assert_allclose(out.numpy(), 3.0)


class TestReviewRegressions:
    def test_read_then_write_in_branch(self):
        @paddle.jit.to_static
        def f(x):
            y = x * 0.0
            if x.sum() > 0:
                y = y + 1.0
            else:
                y = y - 1.0
            return y

        np.testing.assert_allclose(
            f(paddle.to_tensor(np.ones(2, np.float32))).numpy(), 1.0)
        np.testing.assert_allclose(
            f(paddle.to_tensor(-np.ones(2, np.float32))).numpy(), -1.0)

    def test_read_then_write_python_pred(self):
        def g(x, flag):
            y = x + 1.0
            if flag:
                y = y * 10.0
            return y

        conv = convert_to_static(g)
        np.testing.assert_allclose(
            conv(paddle.to_tensor(np.ones(2, np.float32)), True).numpy(),
            20.0)

    def test_bound_method_not_broken(self):
        class M(paddle.nn.Layer):
            def forward(self, x):
                return x * 2.0

        m = M()
        out, traced = paddle.jit.TracedLayer.trace(
            m, [paddle.to_tensor(np.ones(2, np.float32))])
        np.testing.assert_allclose(out.numpy(), 2.0)
        np.testing.assert_allclose(
            traced(paddle.to_tensor(np.full(2, 3.0, np.float32))).numpy(),
            6.0)

    def test_while_with_body_local_temporary(self):
        @paddle.jit.to_static
        def w(x):
            while x.sum() < 20.0:
                tmp = x * 2.0
                x = tmp + 1.0
            return x

        out = w(paddle.to_tensor(np.ones(2, np.float32)))
        assert float(out.numpy().sum()) >= 20.0

    def test_return_after_nested_def_not_transformed(self):
        def g(x):
            if x is not None:  # python predicate, block has nested def
                def inner():
                    return 1

                return x + inner()
            return x

        conv = convert_to_static(g)
        np.testing.assert_allclose(
            conv(paddle.to_tensor(np.zeros(2, np.float32))).numpy(), 1.0)

    def test_live_global_rebinding(self):
        import tests._dy2s_helper as helper

        conv = convert_to_static(helper.scaled)
        helper.SCALE = 2.0
        np.testing.assert_allclose(
            conv(paddle.to_tensor(np.ones(2, np.float32))).numpy(), 2.0)
        helper.SCALE = 5.0  # converted fn must see the new binding
        np.testing.assert_allclose(
            conv(paddle.to_tensor(np.ones(2, np.float32))).numpy(), 5.0)


class TestFallbacks:
    def test_return_inside_branch_left_alone(self):
        def g(x):
            if True:  # static python predicate with early return
                return x + 1.0
            return x

        conv = convert_to_static(g)
        out = conv(paddle.to_tensor(np.zeros(2, np.float32)))
        np.testing.assert_allclose(out.numpy(), 1.0)

    def test_closure_function_converts(self):
        scale = paddle.to_tensor(np.float32(3.0))

        def g(x):
            if x.sum() > 0:
                y = x * scale
            else:
                y = x
            return y

        conv = paddle.jit.to_static(g)
        out = conv(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(out.numpy(), 3.0)


class TestForRangeConversion:
    def test_tensor_stop_for_range(self):
        """for i in range(n) with tensor n traces to a while_loop."""
        @paddle.jit.to_static
        def f(x, n):
            s = paddle.zeros([], x.dtype)
            for i in range(n):
                s = s + x * i.astype(x.dtype)
            return s

        x = paddle.to_tensor(np.float32(2.0))
        n = paddle.to_tensor(np.int32(4))
        assert float(f(x, n)) == 2.0 * (0 + 1 + 2 + 3)
        assert float(f(x, paddle.to_tensor(np.int32(0)))) == 0.0

    def test_python_range_same_result(self):
        @paddle.jit.to_static
        def f(x):
            s = x * 0
            for i in range(1, 6, 2):
                s = s + i
            return s

        assert float(f(paddle.to_tensor(np.float32(0.0)))) == 1 + 3 + 5

    def test_negative_step_tensor_bounds(self):
        @paddle.jit.to_static
        def f(x, start):
            s = paddle.zeros([], x.dtype)
            for i in range(start, paddle.to_tensor(np.int32(0)),
                           paddle.to_tensor(np.int32(-1))):
                s = s + i.astype(x.dtype)
            return s

        x = paddle.to_tensor(np.float32(0.0))
        assert float(f(x, paddle.to_tensor(np.int32(4)))) == 4 + 3 + 2 + 1

    def test_for_with_break_stays_python(self):
        """break keeps the native for (the desugared body would skip the
        index increment on continue/break paths)."""
        @paddle.jit.to_static
        def f(x):
            s = 0
            for i in range(10):
                if i >= 3:
                    break
                s = s + 1
            return x + s

        assert float(f(paddle.to_tensor(np.float32(0.0)))) == 3.0

    def test_for_over_list_stays_python(self):
        @paddle.jit.to_static
        def f(x):
            s = x * 0
            for v in [1.0, 2.0, 3.0]:
                s = s + v
            return s

        assert float(f(paddle.to_tensor(np.float32(0.0)))) == 6.0

    def test_loop_var_reassignment_keeps_python_semantics(self):
        @paddle.jit.to_static
        def f(x):
            s = 0
            for i in range(3):
                i = i + 10
                s = s + i
            return x + s

        assert float(f(paddle.to_tensor(np.float32(0.0)))) == 10 + 11 + 12

    def test_loop_var_read_after_loop(self):
        """After a non-empty loop the target holds the LAST YIELDED value
        (start+(n-1)*step), not one-past-the-end — the counter-driven
        desugar matches dygraph (round-4 advisor finding)."""
        @paddle.jit.to_static
        def f(x):
            for i in range(3):
                x = x + i
            return x + i  # python: i == 2 after the loop

        assert float(f(paddle.to_tensor(np.float32(0.0)))) == 5.0

        @paddle.jit.to_static
        def g(x):
            for i in range(1, 10, 3):  # 1, 4, 7
                x = x + i
            return x + i  # i == 7

        assert float(g(paddle.to_tensor(np.float32(0.0)))) == 19.0

        @paddle.jit.to_static
        def h(x):
            for i in range(3):
                i = i * 10  # reassignment: still 3 passes; i == 20 after
                x = x + i
            return x + i

        assert float(h(paddle.to_tensor(np.float32(0.0)))) == 50.0

    def test_range_argument_contract(self):
        @paddle.jit.to_static
        def zero_step(x):
            s = 0
            for i in range(5, 0, 0):
                s = s + i
            return x + s

        with pytest.raises(ValueError):
            zero_step(paddle.to_tensor(np.float32(0.0)))

        @paddle.jit.to_static
        def float_stop(x):
            s = 0
            for i in range(2.5):
                s = s + i
            return x + s

        with pytest.raises(TypeError):
            float_stop(paddle.to_tensor(np.float32(0.0)))
