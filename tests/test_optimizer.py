"""Optimizer + lr scheduler tests (reference: unittests test_sgd_op.py,
test_adam_op.py, test_lr_scheduler.py). Numerics vs torch.optim."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _problem():
    w0 = np.random.rand(5, 3).astype(np.float32)
    x0 = np.random.rand(10, 5).astype(np.float32)
    y0 = np.random.rand(10, 3).astype(np.float32)
    return w0, x0, y0


def _run_pair(p_opt_fn, t_opt_fn, steps=8, tol=1e-5):
    w0, x0, y0 = _problem()
    lin = nn.Linear(5, 3, bias_attr=False)
    lin.weight.set_value(w0)
    popt = p_opt_fn(lin.parameters())
    tw = torch.nn.Parameter(torch.tensor(w0))
    topt = t_opt_fn([tw])
    tx, ty = torch.tensor(x0), torch.tensor(y0)
    for _ in range(steps):
        loss = ((lin(paddle.to_tensor(x0)) - paddle.to_tensor(y0)) ** 2).mean()
        loss.backward()
        popt.step()
        popt.clear_grad()
        topt.zero_grad()
        tl = ((tx @ tw - ty) ** 2).mean()
        tl.backward()
        topt.step()
    np.testing.assert_allclose(lin.weight.numpy(), tw.detach().numpy(),
                               rtol=tol, atol=tol)


def test_sgd():
    _run_pair(lambda p: paddle.optimizer.SGD(0.1, parameters=p),
              lambda p: torch.optim.SGD(p, lr=0.1))


def test_momentum():
    _run_pair(lambda p: paddle.optimizer.Momentum(0.1, 0.9, parameters=p),
              lambda p: torch.optim.SGD(p, lr=0.1, momentum=0.9))


def test_momentum_nesterov():
    _run_pair(
        lambda p: paddle.optimizer.Momentum(0.05, 0.9, parameters=p,
                                            use_nesterov=True),
        lambda p: torch.optim.SGD(p, lr=0.05, momentum=0.9, nesterov=True))


def test_adam():
    _run_pair(lambda p: paddle.optimizer.Adam(0.01, parameters=p),
              lambda p: torch.optim.Adam(p, lr=0.01))


def test_adamw():
    _run_pair(lambda p: paddle.optimizer.AdamW(0.01, parameters=p,
                                               weight_decay=0.05),
              lambda p: torch.optim.AdamW(p, lr=0.01, weight_decay=0.05))


def test_adagrad():
    _run_pair(lambda p: paddle.optimizer.Adagrad(0.05, parameters=p),
              lambda p: torch.optim.Adagrad(p, lr=0.05, eps=1e-6), tol=1e-4)


def test_adamax():
    _run_pair(lambda p: paddle.optimizer.Adamax(0.01, parameters=p),
              lambda p: torch.optim.Adamax(p, lr=0.01), tol=1e-4)


def test_rmsprop():
    _run_pair(
        lambda p: paddle.optimizer.RMSProp(0.01, rho=0.9, epsilon=1e-8,
                                           parameters=p),
        lambda p: torch.optim.RMSprop(p, lr=0.01, alpha=0.9, eps=1e-8),
        tol=2e-3)  # eps placement differs (inside vs outside sqrt)


def test_adadelta_decreases_loss():
    w0, x0, y0 = _problem()
    lin = nn.Linear(5, 3, bias_attr=False)
    lin.weight.set_value(w0)
    opt = paddle.optimizer.Adadelta(1.0, parameters=lin.parameters())
    losses = []
    for _ in range(20):
        loss = ((lin(paddle.to_tensor(x0)) - paddle.to_tensor(y0)) ** 2).mean()
        losses.append(float(loss))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert losses[-1] < losses[0]


def test_lamb_decreases_loss():
    w0, x0, y0 = _problem()
    lin = nn.Linear(5, 3, bias_attr=False)
    lin.weight.set_value(w0)
    opt = paddle.optimizer.Lamb(0.01, parameters=lin.parameters())
    losses = []
    for _ in range(15):
        loss = ((lin(paddle.to_tensor(x0)) - paddle.to_tensor(y0)) ** 2).mean()
        losses.append(float(loss))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert losses[-1] < losses[0]


def test_weight_decay_and_clip():
    w0, x0, y0 = _problem()
    lin = nn.Linear(5, 3, bias_attr=False)
    lin.weight.set_value(w0)
    opt = paddle.optimizer.SGD(
        0.1, parameters=lin.parameters(),
        weight_decay=paddle.regularizer.L2Decay(0.1),
        grad_clip=nn.ClipGradByGlobalNorm(0.5))
    loss = ((lin(paddle.to_tensor(x0)) - paddle.to_tensor(y0)) ** 2).mean()
    loss.backward()
    g = lin.weight.grad.numpy()
    opt.step()
    # manual: clipped (g + 0.1 w), lr 0.1
    reg = g + 0.1 * w0
    n = np.sqrt((reg ** 2).sum())
    if n > 0.5:
        reg = reg * 0.5 / n
    np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * reg, rtol=1e-4,
                               atol=1e-6)


def test_optimizer_state_dict_roundtrip():
    w0, x0, y0 = _problem()
    lin = nn.Linear(5, 3, bias_attr=False)
    lin.weight.set_value(w0)
    opt = paddle.optimizer.Adam(0.01, parameters=lin.parameters())
    for _ in range(3):
        loss = ((lin(paddle.to_tensor(x0)) - paddle.to_tensor(y0)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    sd = opt.state_dict()
    opt2 = paddle.optimizer.Adam(0.01, parameters=lin.parameters())
    opt2.set_state_dict(sd)
    m1 = opt._accumulators[id(lin.weight)]["moment1"]
    m2 = opt2._accumulators[id(lin.weight)]["moment1"]
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2))


def test_set_lr_and_get_lr():
    lin = nn.Linear(2, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
    assert opt.get_lr() == pytest.approx(0.1)
    opt.set_lr(0.05)
    assert opt.get_lr() == pytest.approx(0.05)


LR_CASES = [
    ("StepDecay", lambda: paddle.optimizer.lr.StepDecay(0.1, 2, 0.5),
     [0.1, 0.1, 0.05, 0.05, 0.025]),
    ("MultiStepDecay",
     lambda: paddle.optimizer.lr.MultiStepDecay(0.1, [2, 4], 0.1),
     [0.1, 0.1, 0.01, 0.01, 0.001]),
    ("ExponentialDecay",
     lambda: paddle.optimizer.lr.ExponentialDecay(1.0, 0.5),
     [1.0, 0.5, 0.25, 0.125, 0.0625]),
    ("InverseTimeDecay",
     lambda: paddle.optimizer.lr.InverseTimeDecay(1.0, 1.0),
     [1.0, 0.5, 1 / 3, 0.25, 0.2]),
    ("PiecewiseDecay",
     lambda: paddle.optimizer.lr.PiecewiseDecay([2, 4], [1.0, 0.5, 0.1]),
     [1.0, 1.0, 0.5, 0.5, 0.1]),
]


@pytest.mark.parametrize("name,mk,expected", LR_CASES)
def test_lr_schedules(name, mk, expected):
    sch = mk()
    got = []
    for _ in expected:
        got.append(sch())
        sch.step()
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_cosine_and_poly_and_noam():
    import math

    sch = paddle.optimizer.lr.CosineAnnealingDecay(1.0, 10)
    vals = []
    for _ in range(11):
        vals.append(sch())
        sch.step()
    assert vals[0] == pytest.approx(1.0)
    assert vals[10] == pytest.approx(0.0, abs=1e-6)
    assert vals[5] == pytest.approx(0.5, abs=1e-6)

    p = paddle.optimizer.lr.PolynomialDecay(1.0, 10, end_lr=0.0, power=1.0)
    v = []
    for _ in range(11):
        v.append(p())
        p.step()
    np.testing.assert_allclose(v, [1 - i / 10 for i in range(11)], atol=1e-6)

    n = paddle.optimizer.lr.NoamDecay(d_model=512, warmup_steps=10,
                                      learning_rate=1.0)
    seq = []
    for _ in range(20):
        seq.append(n())
        n.step()
    peak = max(seq)
    assert seq.index(peak) in (9, 10)


def test_linear_warmup_wraps_scheduler():
    inner = paddle.optimizer.lr.StepDecay(0.1, 5, 0.5)
    sch = paddle.optimizer.lr.LinearWarmup(inner, warmup_steps=4,
                                           start_lr=0.0, end_lr=0.1)
    vals = [sch()]
    for _ in range(5):
        sch.step()
        vals.append(sch())
    np.testing.assert_allclose(vals[:4], [0.0, 0.025, 0.05, 0.075], atol=1e-6)
    assert vals[4] == pytest.approx(0.1)


def test_reduce_on_plateau():
    sch = paddle.optimizer.lr.ReduceOnPlateau(1.0, patience=1, factor=0.5)
    for loss in [1.0, 0.9, 0.9, 0.9, 0.9]:
        sch.step(loss)
    assert sch() < 1.0


def test_adam_clip_scheduler_integration_vs_numpy():
    """Adam + ClipGradByGlobalNorm + LinearWarmup(CosineAnnealing) driven
    through the public step()/scheduler.step() loop must match a
    hand-rolled numpy replica for 12 steps — the integration seam
    (clip -> lr resolve -> fused update) in one oracle."""
    import numpy as np

    import paddle_tpu as paddle

    rng = np.random.RandomState(3)
    w0 = rng.randn(4, 3).astype(np.float32)
    xs = rng.randn(12, 4).astype(np.float32)

    w = paddle.to_tensor(w0.copy())
    w.stop_gradient = False
    sched = paddle.optimizer.lr.LinearWarmup(
        paddle.optimizer.lr.CosineAnnealingDecay(0.1, T_max=8),
        warmup_steps=3, start_lr=0.0, end_lr=0.1)
    clip = paddle.nn.ClipGradByGlobalNorm(0.5)
    opt = paddle.optimizer.Adam(learning_rate=sched, parameters=[w],
                                grad_clip=clip)

    # numpy replica
    wn = w0.copy()
    m = np.zeros_like(wn)
    v = np.zeros_like(wn)
    b1, b2, eps = 0.9, 0.999, 1e-8

    # capture the schedule values once; the paddle side re-runs a fresh
    # scheduler so both sides consume lrs[i] at step i
    lrs = []
    for i in range(12):
        lrs.append(float(sched()))
        sched.step()

    # re-run paddle side with a FRESH scheduler so both sides see lrs[i]
    sched2 = paddle.optimizer.lr.LinearWarmup(
        paddle.optimizer.lr.CosineAnnealingDecay(0.1, T_max=8),
        warmup_steps=3, start_lr=0.0, end_lr=0.1)
    opt = paddle.optimizer.Adam(learning_rate=sched2, parameters=[w],
                                grad_clip=clip)
    for i in range(12):
        loss = ((paddle.to_tensor(xs[i]) @ w) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        sched2.step()

        g = 2.0 / 3.0 * np.outer(xs[i], xs[i] @ wn) / 1.0
        # numpy loss = mean((x @ w)^2) over 3 outputs -> d/dw = 2/3 x (x.w)^T
        gn = np.linalg.norm(g)
        if gn > 0.5:
            g = g * (0.5 / gn)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        t = i + 1
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        wn = wn - lrs[i] * mhat / (np.sqrt(vhat) + eps)

    np.testing.assert_allclose(np.asarray(w.numpy()), wn, rtol=1e-4,
                               atol=1e-5)


def test_parameter_groups_scale_lr_and_weight_decay():
    """Dict parameter groups (reference optimizer param_groups): per-group
    learning_rate multiplies the base lr; per-group weight_decay
    overrides the optimizer-level one."""
    import numpy as np

    import paddle_tpu as paddle

    m1 = paddle.nn.Linear(4, 4, bias_attr=False)
    m2 = paddle.nn.Linear(4, 4, bias_attr=False)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[
        {"params": list(m1.parameters()), "learning_rate": 1.0},
        {"params": list(m2.parameters()), "learning_rate": 0.1},
    ])
    w1b = np.asarray(m1.weight.numpy()).copy()
    w2b = np.asarray(m2.weight.numpy()).copy()
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    (m1(x).sum() + m2(x).sum()).backward()
    opt.step()
    d1 = np.abs(np.asarray(m1.weight.numpy()) - w1b).max()
    d2 = np.abs(np.asarray(m2.weight.numpy()) - w2b).max()
    np.testing.assert_allclose(d1 / d2, 10.0, rtol=1e-4)

    # per-group weight decay: group-2 weights shrink, group-1 don't
    m3 = paddle.nn.Linear(3, 3, bias_attr=False)
    m4 = paddle.nn.Linear(3, 3, bias_attr=False)
    opt2 = paddle.optimizer.SGD(learning_rate=0.5, parameters=[
        {"params": list(m3.parameters()), "weight_decay": 0.0},
        {"params": list(m4.parameters()), "weight_decay": 0.1},
    ])
    w3b = np.asarray(m3.weight.numpy()).copy()
    w4b = np.asarray(m4.weight.numpy()).copy()
    z = paddle.to_tensor(np.zeros((1, 3), np.float32))
    (m3(z).sum() + m4(z).sum()).backward()   # zero data grads
    opt2.step()
    np.testing.assert_allclose(np.asarray(m3.weight.numpy()), w3b,
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(m4.weight.numpy()),
                               w4b * (1 - 0.5 * 0.1), rtol=1e-5)


def test_half_params_get_f32_master_and_states():
    """bf16 params train through an f32 master copy with f32 moments
    (reference multi_precision semantics, always on for half params):
    tiny updates must not round away in bf16, and state dtypes must be
    stable from step one (donation/retrace contract)."""
    import jax.numpy as jnp

    import paddle_tpu.nn as nn

    paddle.seed(3)
    m16 = nn.Linear(16, 1, bias_attr=False)
    paddle.seed(3)
    m32 = nn.Linear(16, 1, bias_attr=False)
    paddle.amp.decorate(m16, level="O2")
    assert str(m16.weight._value.dtype) == "bfloat16"
    o16 = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=m16.parameters())
    o32 = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=m32.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randn(32, 1).astype(np.float32))
    for _ in range(50):
        for mm, oo in ((m16, o16), (m32, o32)):
            loss = ((mm(x.astype(mm.weight.dtype)) - y.astype(
                mm.weight.dtype)) ** 2).mean()
            loss.backward()
            oo.step()
            oo.clear_grad()
    st = o16._accumulators[id(m16.weight)]
    assert str(st["master"].dtype) == "float32"
    assert str(st["moment1"].dtype) == "float32"
    assert str(st["moment2"].dtype) == "float32"
    # functional path mirrors the same policy
    params = {"w": m16.weight._value}
    states = o16.functional_init_states(params)
    leaf = states[0]
    assert str(leaf["master"].dtype) == "float32"
    assert str(leaf["moment2"].dtype) == "float32"


def test_bf16_param_accumulates_tiny_updates_via_master():
    """The reason the master exists: an AdamW step is ~lr in magnitude
    (1e-4 here), far below bf16 resolution at 1.0 (2^-8) — without the
    f32 master every step rounds away and the param freezes at 1.0;
    with it the accumulated drift reaches the bf16 param."""
    import jax.numpy as jnp

    w = paddle.to_tensor(np.ones(4, np.float32)).astype("bfloat16")
    w.stop_gradient = False
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=[w],
                                 weight_decay=0.0)
    for _ in range(80):
        (w.astype("float32") * 0.1).sum().backward()
        opt.step()
        opt.clear_grad()
    # ~80 * 1e-4 = 0.008 accumulated: visible in bf16 (step 0.0078 at 1)
    val = float(np.asarray(w._value.astype(jnp.float32)).mean())
    assert val < 0.999, val
    master = opt._accumulators[id(w)]["master"]
    np.testing.assert_allclose(np.asarray(master), 1.0 - 80e-4, atol=1e-3)


def test_multi_precision_false_opts_out():
    """Explicit multi_precision=False keeps half-dtype accumulators and
    no master (reference default behavior; halves optimizer-state HBM)."""
    import jax.numpy as jnp

    w = paddle.to_tensor(np.ones(4, np.float32)).astype("bfloat16")
    w.stop_gradient = False
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=[w],
                                multi_precision=False)
    (w.astype("float32") * 0.1).sum().backward()
    opt.step()
    st = opt._accumulators[id(w)]
    assert "master" not in st
    assert str(st["moment1"].dtype) == "bfloat16"
    states = opt.functional_init_states({"w": w._value})
    assert "master" not in states[0]
