"""SURVEY §4: the driver's multichip dryrun must pass on the virtual mesh."""
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))


def test_dryrun_multichip_8(require_partial_auto_spmd):
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_entry_compiles(require_partial_auto_spmd):
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    loss = jax.jit(fn)(*args)
    assert float(loss) > 0
