"""Fake CONFIGS for bench-orchestrator tests (BENCH_CONFIGS_MODULE).

Behavior is driven by marker files in BENCH_FAKE_DIR so a config can
crash the whole runner process on its FIRST attempt only (testing the
orchestrator's respawn + crash-skip path) while staying deterministic.
"""
import os


def _fake_lenet():
    return {"lenet_imgs_per_sec": 111.0}


def _fake_bert():
    return {"bert_tokens_per_sec": 999.0, "bert_step_ms": 10.0}


def _fake_crasher():
    marker = os.path.join(os.environ["BENCH_FAKE_DIR"], "crashed_once")
    if not os.path.exists(marker):
        with open(marker, "w") as f:
            f.write("1")
        os._exit(3)  # hard-kill the runner process mid-config
    return {"crasher_ok": True}


def _fake_error():
    raise RuntimeError("deliberate in-process failure")


CONFIGS = {
    "lenet": (_fake_lenet, {}, 60),
    "crasher": (_fake_crasher, {}, 60),
    "bert": (_fake_bert, {}, 60),
    "error": (_fake_error, {}, 60),
}
