"""tracelint: AST jit-safety analyzer + static unjittable manifest.

Locks the ISSUE-2 acceptance surface:
  * `python -m tools.tracelint paddle_tpu` exits 0 on the baselined tree
    and non-zero once a synthetic violation is introduced;
  * >= 6 distinct rule detections on fixture code (plus the precision
    controls that must NOT fire);
  * the generated manifest is loaded by core/dispatch.py at import and
    dispatch_stats() splits manifest-preloaded from runtime-learned
    unjittable ops.
"""
import os
import shutil
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.tracelint import analyzer, baseline, manifest  # noqa: E402


# ---------------------------------------------------------------------------
# fixture code exercising every rule

FIXTURE = textwrap.dedent('''
    import time
    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.autograd import apply
    from paddle_tpu.core.dispatch import non_jittable


    def host_float_op(x):
        def f(v):
            return jnp.asarray(float(v.sum()))
        return apply(f, x)


    def host_numpy_method_op(x):
        return apply(lambda v: jnp.asarray(v.numpy() * 2), x)


    def impure_time_op(x):
        def f(v):
            return v * time.time()
        return apply(f, x)


    def impure_np_random_op(x):
        def f(v):
            return v + np.random.rand()
        return apply(f, x)


    def closure_capture_op(x):
        key = jax.random.PRNGKey(0)

        def f(v):
            return v * jax.random.uniform(key, v.shape)
        return apply(f, x)


    _BUF = []


    def mutation_op(x):
        def f(v):
            global _SEEN
            _SEEN = 1
            _BUF.append(1)
            return v
        return apply(f, x)


    def branchy_op(x):
        def f(v):
            s = jnp.sum(v)
            if s > 0:
                return v
            return -v
        return apply(f, x)


    @non_jittable
    def clean_marked_op(v):
        return v * 2


    def trace_site(fn, x):
        return jax.jit(fn)(x)


    def waived_trace_site(fn, x):
        return jax.jit(fn)(x)  # tracelint: ok[suspend-audit] fixture


    _MODULE_LEVEL_JIT = jax.jit(lambda v: v + 1)


    def id_waived_trace_site(fn, x):
        return jax.jit(fn)(x)  # tracelint: ok[TL007] id-form waiver


    def wrong_id_waiver_site(fn, x):
        return jax.jit(fn)(x)  # tracelint: ok[TL001] other rule only


    # ---- precision controls: none of these may produce findings ----

    def clean_none_branch(x, w=None):
        def f(v, wv=w):
            if wv is None:
                return v
            return v * wv
        return apply(f, x)


    def clean_shape_branch(x):
        def f(v):
            if v.shape[0] > 1 and v.ndim == 2:
                return v.sum()
            return v
        return apply(f, x)


    def clean_dtype_branch(x, y):
        def f(a, b):
            if jnp.issubdtype(jnp.result_type(a, b), jnp.integer):
                return a // b
            return a / b
        return apply(f, x, y)


    def clean_vararg_truthiness(x, *rest):
        def f(v, *more):
            if more:
                return v + more[0]
            return v
        return apply(f, x, *rest)


    def clean_static_capture(x):
        axes = (0, 1)

        def f(v):
            return jnp.sum(v, axis=axes)
        return apply(f, x)
''')


@pytest.fixture(scope="module")
def fixture_findings(tmp_path_factory):
    d = tmp_path_factory.mktemp("tracelint_fixture")
    p = d / "fixture_ops.py"
    p.write_text(FIXTURE)
    findings, errors = analyzer.analyze_paths([str(p)])
    assert not errors
    return findings


def _rules_in(findings, func_prefix):
    return {f.rule for f in findings
            if f.func.startswith(func_prefix) and not f.suppressed}


def test_at_least_six_distinct_rules(fixture_findings):
    rules = {f.rule for f in fixture_findings if not f.suppressed}
    assert len(rules) >= 6, rules
    assert {"host-materialize", "impure-call", "closure-capture",
            "state-mutation", "data-dependent-control-flow",
            "stale-non-jittable", "suspend-audit"} <= rules


def test_host_materialize_detections(fixture_findings):
    assert "host-materialize" in _rules_in(fixture_findings, "host_float_op")
    assert "host-materialize" in _rules_in(fixture_findings,
                                           "host_numpy_method_op")


def test_impure_call_detections(fixture_findings):
    assert _rules_in(fixture_findings, "impure_time_op") == {"impure-call"}
    assert _rules_in(fixture_findings,
                     "impure_np_random_op") == {"impure-call"}


def test_closure_capture_detection(fixture_findings):
    hits = [f for f in fixture_findings
            if f.rule == "closure-capture" and "closure_capture_op" in f.func]
    assert hits and "key" in hits[0].symbol


def test_closure_capture_prng_key_suggests_non_jittable(fixture_findings):
    # a PRNG-key capture is usually deliberate (dropout semantics), so
    # the report must carry the fix — record the intent @non_jittable —
    # not just the finding
    hits = [f for f in fixture_findings
            if f.rule == "closure-capture" and "closure_capture_op" in f.func]
    assert hits and "@non_jittable" in hits[0].message


def test_state_mutation_detections(fixture_findings):
    symbols = {f.symbol for f in fixture_findings
               if f.rule == "state-mutation" and "mutation_op" in f.func}
    assert any(s.startswith("global:") for s in symbols), symbols
    assert "_BUF.append" in symbols


def test_data_dependent_branch_detection(fixture_findings):
    hits = [f for f in fixture_findings
            if f.rule == "data-dependent-control-flow"
            and "branchy_op" in f.func]
    assert hits and hits[0].symbol == "if:s"


def test_stale_non_jittable_detection(fixture_findings):
    hits = [f for f in fixture_findings if f.rule == "stale-non-jittable"]
    assert hits and hits[0].func == "clean_marked_op"
    assert hits[0].severity == "info"


def test_suspend_audit_and_inline_waiver(fixture_findings):
    flagged = [f for f in fixture_findings if f.rule == "suspend-audit"]
    by_func = {f.func: f.suppressed for f in flagged}
    assert by_func["trace_site"] is False
    assert by_func["waived_trace_site"] is True
    # module-level trace entries must report as <module>, not crash the
    # analyzer (regression: qualname() on a non-scope node)
    assert by_func.get("<module>") is False
    # rule-ID waiver form is honored, and scoped: a waiver naming a
    # DIFFERENT rule must not suppress this one (regression: the old
    # regex rejected uppercase IDs and degraded to a blanket waiver)
    assert by_func["id_waived_trace_site"] is True
    assert by_func["wrong_id_waiver_site"] is False


def test_precision_controls_are_clean(fixture_findings):
    for prefix in ("clean_none_branch", "clean_shape_branch",
                   "clean_dtype_branch", "clean_vararg_truthiness",
                   "clean_static_capture"):
        assert _rules_in(fixture_findings, prefix) == set(), prefix


def test_fingerprints_are_line_free(tmp_path):
    src = ("import time\n"
           "from paddle_tpu.core.autograd import apply\n"
           "def op(x):\n"
           "    def f(v):\n"
           "        return v * time.time()\n"
           "    return apply(f, x)\n")
    a = tmp_path / "a.py"
    a.write_text(src)
    f1, _ = analyzer.analyze_paths([str(a)])
    a.write_text("# pushed down\n# two lines\n" + src)
    f2, _ = analyzer.analyze_paths([str(a)])
    assert [x.fingerprint() for x in f1] == [x.fingerprint() for x in f2]
    assert f1[0].line != f2[0].line


def test_baseline_partition_and_staleness(fixture_findings):
    base = {}
    new, baselined, suppressed, info, stale = baseline.partition(
        fixture_findings, base)
    assert baselined == [] and stale == []
    assert all(f.severity != "info" for f in new)
    # baseline everything -> nothing new; plus one stale entry
    counts = {}
    for f in new:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    counts["impure-call|gone.py|dead_op|time.time"] = 1
    new2, baselined2, _, _, stale2 = baseline.partition(fixture_findings,
                                                        counts)
    assert new2 == [] and len(baselined2) == len(new)
    assert stale2 == ["impure-call|gone.py|dead_op|time.time"]


def test_manifest_entries_definite_only(fixture_findings):
    entries = manifest.manifest_entries(fixture_findings)
    names = {k[1] for k in entries}
    # impure/time ops are manifest-grade; the suspend-audit trace site
    # and closure captures are not
    assert "f" in names
    for (path, name, line), reason in entries.items():
        assert path.endswith("fixture_ops.py")
        assert "TL00" in reason


# ---------------------------------------------------------------------------
# CLI acceptance: exit codes on the real tree

def _run_cli(args, cwd=REPO_ROOT):
    return subprocess.run([sys.executable, "-m", "tools.tracelint", *args],
                          cwd=cwd, capture_output=True, text=True,
                          timeout=300)


def test_cli_clean_tree_exits_zero():
    r = _run_cli(["paddle_tpu"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new" in r.stdout


def test_cli_checked_in_manifest_is_fresh():
    r = _run_cli(["paddle_tpu", "--check-manifest"])
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_synthetic_violation_fails(tmp_path):
    # copy the real tree, introduce one bad op, run with the SAME
    # checked-in baseline: the new finding must gate
    dst = tmp_path / "paddle_tpu"
    shutil.copytree(
        os.path.join(REPO_ROOT, "paddle_tpu"), dst,
        ignore=shutil.ignore_patterns("__pycache__", "libs", "include"))
    bad = dst / "tensor" / "_tl_synthetic.py"
    bad.write_text(textwrap.dedent('''
        import time
        from ..core.autograd import apply

        def leaky_op(x):
            def f(v):
                return v * time.time()
            return apply(f, x)
    '''))
    r = _run_cli([str(dst), "--baseline",
                  os.path.join(REPO_ROOT, "tools", "tracelint",
                               "baseline.json")])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "_tl_synthetic.py" in r.stdout
    assert "impure-call" in r.stdout
    # and the same copy WITHOUT the violation is clean
    bad.unlink()
    r2 = _run_cli([str(dst), "--baseline",
                   os.path.join(REPO_ROOT, "tools", "tracelint",
                                "baseline.json")])
    assert r2.returncode == 0, r2.stdout + r2.stderr


# ---------------------------------------------------------------------------
# dispatch integration: manifest preload vs runtime learning

def test_dispatch_loads_checked_in_manifest():
    from paddle_tpu.core import dispatch as D

    assert D._manifest, "manifest not loaded at import"
    gen = D._load_unjittable_manifest()
    assert set(gen) >= set(D._manifest) or gen == D._manifest


def test_manifest_preload_skips_compile_probe():
    import paddle_tpu as paddle
    from paddle_tpu.core import dispatch as D
    from paddle_tpu.core.autograd import apply

    def synthetic_bad(v):
        import time
        return v * time.time()

    key = D._manifest_key(synthetic_bad.__code__)
    prev_warm = D.set_warmup_count(1)
    D._manifest[key] = "TL004 impure-call: synthetic"
    D.reset_dispatch_stats()
    try:
        x = paddle.to_tensor(np.ones(4, np.float32))
        before = D.dispatch_stats()["unjittable"]
        for _ in range(3):
            apply(synthetic_bad, x)
        s = D.dispatch_stats()
        # demoted via the manifest on first sighting: no failed-compile
        # probe (fallbacks counter untouched), source attributed
        assert s["forward"]["manifest_preloads"] == 1
        assert s["forward"]["fallbacks"] == 0
        uj = s["unjittable"]
        assert uj["manifest_preloaded"] == before["manifest_preloaded"] + 1
        # later calls exit via the non_jittable fast path
        assert s["forward"]["bypasses"] >= 2
    finally:
        D._manifest.pop(key, None)
        D.set_warmup_count(prev_warm)


def test_runtime_learning_still_attributed():
    import paddle_tpu as paddle
    from paddle_tpu.core import dispatch as D
    from paddle_tpu.core.autograd import apply

    def runtime_bad(v):
        if float(v.sum()) > 0:  # concretization error under trace
            return v
        return -v

    prev_warm = D.set_warmup_count(1)
    D.reset_dispatch_stats()
    try:
        x = paddle.to_tensor(np.ones(4, np.float32))
        before = D.dispatch_stats()["unjittable"]["runtime_learned"]
        apply(runtime_bad, x)
        s = D.dispatch_stats()
        assert s["unjittable"]["runtime_learned"] == before + 1
        assert s["forward"]["fallbacks"] == 1  # the probe was paid
    finally:
        D.set_warmup_count(prev_warm)


def test_real_manifest_entry_blocks_moe_probe():
    """End-to-end: the checked-in manifest row for the moe assign-pos op
    matches the op's real code object at runtime."""
    import jax.numpy as jnp

    from paddle_tpu.core import dispatch as D
    from paddle_tpu.distributed.models.moe import _assign_pos
    import paddle_tpu as paddle

    prev_warm = D.set_warmup_count(1)
    D.reset_dispatch_stats()
    try:
        x = paddle.to_tensor(np.array([0, 1, 0, 1], np.int32))
        cum = paddle.to_tensor(np.array([2, 4], np.int32))
        out = _assign_pos(x, cum)
        s = D.dispatch_stats()
        # demoted via the manifest — either just now (cold path) or by an
        # earlier test in the session (demotions persist across stat
        # resets; this call then exits via the non_jittable bypass).
        # Either way the op never pays a failed-compile probe.
        assert s["unjittable"]["manifest_preloaded"] >= 1, s["unjittable"]
        assert s["forward"]["fallbacks"] == 0, s["forward"]
        assert s["forward"]["manifest_preloads"] \
            + s["forward"]["bypasses"] >= 1, s["forward"]
        assert np.asarray(out._value).shape == (4,)
    finally:
        D.set_warmup_count(prev_warm)


def test_per_op_cache_size_accounting():
    import paddle_tpu as paddle
    from paddle_tpu.core import dispatch as D

    D.reset_dispatch_stats()
    prev_warm = D.set_warmup_count(1)
    try:
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        y = paddle.to_tensor(np.ones((3, 3), np.float32))
        for t in (x, y, x, y):
            paddle.tanh(t)
        per = D.dispatch_stats()["per_op"]["tanh"]
        assert per["cache_entries"] >= 2  # one program per shape
        # profiler surfaces the same snapshot
        import paddle_tpu.profiler as prof

        assert prof.dispatch_stats()["per_op"]["tanh"]["cache_entries"] \
            == per["cache_entries"]
    finally:
        D.set_warmup_count(prev_warm)
