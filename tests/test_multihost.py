"""REAL multi-process multi-host bring-up (component #40).

Two OS processes, each owning 2 virtual CPU devices, rendezvous through
`init_distributed_env` (jax.distributed — the same DCN path a
multi-host TPU pod uses) into one 4-device world, then run a jitted
data-parallel step whose gradient all-reduce crosses the process
boundary, plus an explicit shard_map psum. This is the strongest
simulation of multi-host available without two physical hosts.
"""
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_world(require_multiprocess_cpu):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "_multihost_child.py"),
             str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode("utf-8", "replace"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid {pid} rc={p.returncode}:\n{out[-2000:]}"
        assert f"HYBRID_OK pid={pid}" in out, out[-2000:]
        assert f"MULTIHOST_OK pid={pid} procs=2 devices=4" in out, out[-2000:]
