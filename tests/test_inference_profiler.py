"""Inference Predictor over jit.save artifacts + profiler states/trace."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestInference:
    def _save_model(self, tmp_path):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        prefix = str(tmp_path / "infer_model")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.static.InputSpec([-1, 8],
                                                            "float32")])
        return net, prefix

    def test_predictor_matches_eager(self, tmp_path):
        net, prefix = self._save_model(tmp_path)
        cfg = paddle.inference.Config(prefix)
        pred = paddle.inference.create_predictor(cfg)
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        (out,) = pred.run([x])
        ref = net(paddle.to_tensor(x))
        np.testing.assert_allclose(out, np.asarray(ref._value), rtol=1e-5)

    def test_named_handles_zero_copy(self, tmp_path):
        net, prefix = self._save_model(tmp_path)
        pred = paddle.inference.create_predictor(
            paddle.inference.Config(prefix))
        names = pred.get_input_names()
        assert names
        h = pred.get_input_handle(names[0])
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        h.copy_from_cpu(x)
        assert pred.run()
        out_h = pred.get_output_handle(pred.get_output_names()[0])
        np.testing.assert_allclose(
            out_h.copy_to_cpu(),
            np.asarray(net(paddle.to_tensor(x))._value), rtol=1e-5)

    def test_dynamic_batch(self, tmp_path):
        _, prefix = self._save_model(tmp_path)
        pred = paddle.inference.create_predictor(
            paddle.inference.Config(prefix))
        for bs in (1, 3, 7):
            (out,) = pred.run([np.zeros((bs, 8), np.float32)])
            assert out.shape == (bs, 4)

    def test_missing_model_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            paddle.inference.create_predictor(
                paddle.inference.Config(str(tmp_path / "nope")))


class TestProfiler:
    def test_scheduler_states(self):
        from paddle_tpu.profiler import ProfilerState, make_scheduler

        sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                               skip_first=1)
        states = [sched(i) for i in range(1, 6)]
        assert states[0] == ProfilerState.CLOSED
        assert states[1] == ProfilerState.READY
        assert states[2] == ProfilerState.RECORD
        assert states[3] == ProfilerState.RECORD_AND_RETURN
        assert states[4] == ProfilerState.CLOSED

    def test_profiler_records_trace(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_PROFILER_DIR", str(tmp_path))
        ready = []
        prof = paddle.profiler.Profiler(
            targets=[paddle.profiler.ProfilerTarget.CPU],
            on_trace_ready=lambda p: ready.append(p.export()))
        prof.start()
        with paddle.profiler.RecordEvent("matmul-span"):
            x = paddle.randn([64, 64])
            (x @ x).numpy()
        prof.step()
        prof.stop()
        assert ready
        # jax wrote trace artifacts under the dir (plugins/ layout)
        found = []
        for root, _dirs, files in os.walk(str(tmp_path)):
            found.extend(files)
        assert found, "no trace artifacts written"

    def test_step_info(self):
        prof = paddle.profiler.Profiler(timer_only=True)
        prof.start()
        for _ in range(3):
            prof.step()
        prof.stop()
        assert "steps/s" in prof.step_info()


def test_predictor_shares_compile_across_instances(tmp_path):
    """The AOT knob that matters (round-2 verdict weak #8): a second
    Predictor on the same saved model must NOT trigger a new XLA
    compilation."""
    import logging

    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    prefix = str(tmp_path / "m")
    paddle.jit.save(net, prefix,
                    input_spec=[paddle.static.InputSpec([2, 8], "float32")])

    records = []

    class H(logging.Handler):
        def emit(self, rec):
            records.append(rec.getMessage())

    h = H()
    loggers = [logging.getLogger("jax._src.dispatch"),
               logging.getLogger("jax._src.interpreters.pxla")]
    old = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    for lg in loggers:
        lg.addHandler(h)
    try:
        from paddle_tpu import inference

        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        p1 = inference.create_predictor(inference.Config(prefix))
        (out1,) = p1.run([x])
        n_compiles_first = len([r for r in records if "Compiling" in r])
        records.clear()
        p2 = inference.create_predictor(inference.Config(prefix))
        (out2,) = p2.run([x])
        n_compiles_second = len([r for r in records if "Compiling" in r])
    finally:
        for lg in loggers:
            lg.removeHandler(h)
        jax.config.update("jax_log_compiles", old)
    np.testing.assert_allclose(out1, out2)
    assert n_compiles_second == 0, (
        f"second Predictor recompiled ({n_compiles_second} compiles; "
        f"first did {n_compiles_first})")


def test_profiler_statistic_path():
    from paddle_tpu.profiler import profiler_statistic as ps

    class E:
        def __init__(self, name, dur):
            self.name = name
            self.duration_ms = dur

    sd = ps.StatisticData([E("matmul", 1.5), E("matmul", 0.5),
                           E("conv", 2.0)])
    assert sd.totals()["matmul"][:2] == (2, 2.0)
    table = ps._build_table(sd)
    assert "matmul" in table and "conv" in table
    assert ps.SortedKeys is not None


def test_fleet_elastic_path(monkeypatch):
    import types

    import pytest

    from paddle_tpu.distributed.fleet import elastic as fe

    assert fe.ElasticManager is not None
    args = types.SimpleNamespace(elastic_server=None)
    monkeypatch.delenv("PADDLE_ELASTIC_SERVER", raising=False)
    monkeypatch.delenv("PADDLE_CHECKPOINT_DIR", raising=False)
    assert not fe.enable_elastic(args)
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", "/tmp/x")
    assert fe.enable_elastic(args)
    with pytest.raises(NotImplementedError, match="ElasticManager"):
        fe.launch_elastic(args)
