"""Child for the sharded-input-pipeline tests (tests/test_prefetch.py).

Modes (argv[1]):

* ``shard`` — one DP "host": loads ONLY its `DistributedBatchSampler`
  rows (env PF_RANK / PF_NRANKS) through a `DevicePrefetcher` and
  prints per-batch, per-row sha1 digests. The parent interleaves the
  ranks' rows back into the global batch stream and compares it,
  digest for digest, against single-host loading — the ISSUE-15
  2-process acceptance: per-host sharded loading + prefetch yields the
  SAME global batch stream (order and values).
* ``mesh`` — single process forced to 2 CPU devices
  (XLA_FLAGS=--xla_force_host_platform_device_count=2): a
  ``sharding="dp"`` prefetcher must yield GLOBAL arrays carrying the
  dp NamedSharding with values identical to the host batch
  (process-local data -> global array assembly).
"""
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.io import DataLoader, DevicePrefetcher  # noqa: E402
from paddle_tpu.io.sampler import DistributedBatchSampler  # noqa: E402

N = 16
LOCAL_BATCH = 4


class _Det(paddle.io.Dataset):
    """Deterministic rows: value is a pure function of the index."""

    def __len__(self):
        return N

    def __getitem__(self, i):
        x = np.asarray([i, 2.0 * i, i * i], np.float32)
        y = np.int64(i)
        return x, y


def row_digest(x_row, y_row):
    h = hashlib.sha1()
    h.update(np.ascontiguousarray(x_row, np.float32).tobytes())
    h.update(np.asarray(y_row, np.int64).tobytes())
    return h.hexdigest()


def run_shard():
    rank = int(os.environ["PF_RANK"])
    nranks = int(os.environ["PF_NRANKS"])
    sampler = DistributedBatchSampler(
        _Det(), batch_size=LOCAL_BATCH, num_replicas=nranks, rank=rank,
        shuffle=True)
    sampler.set_epoch(1)
    loader = DataLoader(_Det(), batch_sampler=sampler)
    out = []
    with DevicePrefetcher(iter(loader), depth=2) as pf:
        for x, y in pf:
            xv = np.asarray(x.numpy())
            yv = np.asarray(y.numpy())
            out.append([row_digest(xv[j], yv[j]) for j in range(len(yv))])
    print(json.dumps({"rank": rank, "batches": out}))


def run_mesh():
    import jax
    from jax.sharding import NamedSharding

    from paddle_tpu.distributed import env as _env

    assert jax.device_count() >= 2, jax.devices()
    _env.set_mesh(jax.sharding.Mesh(np.array(jax.devices()[:2]), ("dp",)))
    loader = DataLoader(_Det(), batch_size=4, shuffle=False)
    with DevicePrefetcher(iter(loader), depth=2, sharding="dp") as pf:
        got = list(pf)
    assert len(got) == 4, len(got)
    ref = list(DataLoader(_Det(), batch_size=4, shuffle=False))
    sharded_leaves = 0
    for (x, y), (rx, ry) in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(x.numpy()),
                                      np.asarray(rx.numpy()))
        np.testing.assert_array_equal(np.asarray(y.numpy()),
                                      np.asarray(ry.numpy()))
        for leaf in (x._value, y._value):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding) and "dp" in sh.spec:
                sharded_leaves += 1
    # every batch leaf has a leading dim divisible by 2 here, so ALL
    # of them must have taken the global-assembly path
    assert sharded_leaves == 8, sharded_leaves
    print(json.dumps({"ok": True, "sharded_leaves": sharded_leaves}))


if __name__ == "__main__":
    if sys.argv[1] == "shard":
        run_shard()
    elif sys.argv[1] == "mesh":
        run_mesh()
    else:
        raise SystemExit(f"unknown mode {sys.argv[1]}")
