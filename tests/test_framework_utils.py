"""Tests for round-2 framework utilities: ref-compatible save/load, AMP O2
norm-skip, conv_transpose output_size, tracked __setitem__, flops, debug."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_save_load_plain_ndarray(tmp_path):
    """paddle.save pickles plain np.ndarray payloads (reference format)."""
    import pickle

    lin = nn.Linear(4, 3)
    path = str(tmp_path / "m.pdparams")
    paddle.save(lin.state_dict(), path)
    with open(path, "rb") as f:
        raw = pickle.load(f)
    for v in raw.values():
        assert type(v) is np.ndarray
    # round trip back to Tensors
    sd = paddle.load(path)
    for v in sd.values():
        assert isinstance(v, paddle.Tensor)
    np.testing.assert_allclose(np.asarray(sd["weight"]._value),
                               np.asarray(lin.weight._value))
    # return_numpy path
    sd2 = paddle.load(path, return_numpy=True)
    assert type(sd2["weight"]) is np.ndarray


def test_amp_decorate_keeps_norm_fp32():
    net = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.LayerNorm(8))
    paddle.amp.decorate(net, level="O2")
    assert net[0].weight.dtype.name == "bfloat16"
    assert net[1].weight.dtype.name == "float32"
    assert net[1]._mean.dtype.name == "float32"
    assert net[2].weight.dtype.name == "float32"


def test_conv2d_transpose_output_size():
    x = paddle.randn([1, 4, 7, 7])
    w = paddle.randn([4, 6, 3, 3])
    # stride 2, default pad: base output is 15; output_size selects 15 or 16
    y15 = nn.functional.conv2d_transpose(x, w, stride=2, output_size=[15, 15])
    assert tuple(y15.shape) == (1, 6, 15, 15)
    y16 = nn.functional.conv2d_transpose(x, w, stride=2, output_size=[16, 16])
    with pytest.raises(ValueError):
        nn.functional.conv2d_transpose(x, w, stride=2, output_size=[17, 17])
    assert tuple(y16.shape) == (1, 6, 16, 16)
    # parity with explicit output_padding
    ypad = nn.functional.conv2d_transpose(x, w, stride=2, output_padding=1)
    np.testing.assert_allclose(np.asarray(y16._value),
                               np.asarray(ypad._value), rtol=1e-5)
    with pytest.raises(ValueError):
        nn.functional.conv2d_transpose(x, w, stride=2, output_size=[40, 40])
    with pytest.raises(ValueError):
        nn.functional.conv2d_transpose(x, w, stride=2, output_padding=1,
                                       output_size=[16, 16])


def test_setitem_tracked_in_autograd():
    x = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
    y = x * 3.0
    y[1] = paddle.to_tensor(np.float32(0.0))
    loss = y.sum()
    loss.backward()
    # grad wrt x: position 1 was overwritten -> d loss/dx[1] = 0, others 3
    np.testing.assert_allclose(np.asarray(x.grad._value), [3.0, 0.0, 3.0, 3.0])


def test_flops_lenet():
    from paddle_tpu.vision.models import LeNet

    n = paddle.flops(LeNet(), [1, 1, 28, 28])
    # reference dynamic_flops on its LeNet example: conv+linear dominated,
    # our LeNet matches the reference vision LeNet topology
    assert n > 100_000


def test_set_printoptions_and_check_numerics():
    paddle.set_printoptions(precision=3)
    t = paddle.to_tensor(np.array([1.234567], np.float32))
    assert "1.235" in repr(t) or "1.23" in repr(t)
    paddle.set_printoptions(precision=8)
    good = paddle.to_tensor(np.ones(3, np.float32))
    paddle.check_numerics(good)  # no raise
    bad = paddle.to_tensor(np.array([1.0, np.nan], np.float32))
    with pytest.raises(FloatingPointError):
        paddle.check_numerics(bad, "unit")


def test_linalg_namespace():
    x = paddle.to_tensor(np.eye(3, dtype=np.float32) * 2)
    assert float(paddle.linalg.det(x)._value) == pytest.approx(8.0)


def test_hub_local_source(tmp_path):
    """paddle.hub list/help/load over a local hubconf.py (reference
    python/paddle/hub.py)."""
    (tmp_path / "hubconf.py").write_text(
        "def tiny_model(width=4):\n"
        "    '''A tiny model entry.'''\n"
        "    import paddle_tpu as paddle\n"
        "    return paddle.nn.Linear(width, width)\n")
    import paddle_tpu as paddle

    names = paddle.hub.list(str(tmp_path))
    assert "tiny_model" in names
    assert "tiny model" in paddle.hub.help(str(tmp_path), "tiny_model")
    layer = paddle.hub.load(str(tmp_path), "tiny_model", width=6)
    assert layer.weight.shape == [6, 6]
    import pytest as _pytest

    with _pytest.raises(RuntimeError):
        paddle.hub.load(str(tmp_path), "tiny_model", source="github")


def test_bare_import_does_not_init_backend():
    """import paddle_tpu must not touch a device (a PRNGKey built at
    import time used to initialize the backend — hanging the import
    whenever the device was unreachable)."""
    import subprocess
    import sys

    code = (
        "import jax\n"
        "import paddle_tpu\n"
        "try:\n"
        "    from jax._src import xla_bridge as xb\n"
        "    assert not xb._backends, list(xb._backends)\n"
        "except ImportError:\n"
        "    pass  # private internals moved; timely import still proves it\n"
        "print('LAZY-OK')\n")
    out = subprocess.run([sys.executable, "-c", code], timeout=180,
                         capture_output=True, text=True)
    assert "LAZY-OK" in out.stdout, (out.stdout[-300:], out.stderr[-300:])


def test_distributed_launch_cli(tmp_path):
    """python -m paddle_tpu.distributed.launch script.py runs the script
    with the trainer env exported (reference launch contract)."""
    import subprocess
    import sys

    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import paddle_tpu as paddle\n"
        "assert os.environ['PADDLE_TRAINER_ID'] == '0'\n"
        "print('WORKER-OK', paddle.distributed.get_rank())\n")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         str(script)], timeout=240, capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert "WORKER-OK 0" in out.stdout, (out.stdout[-300:],
                                         out.stderr[-300:])
