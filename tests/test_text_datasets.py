"""Text dataset parsers against synthetic files in the reference formats
(reference: python/paddle/text/datasets/*.py)."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text import (Conll05st, Imdb, Imikolov, Movielens,
                             UCIHousing, WMT14, WMT16)


class TestImdb:
    def _make_tar(self, tmp_path):
        path = str(tmp_path / "imdb.tar.gz")
        docs = {
            ("train", "pos", 0): b"great great fun fun fun, movie!",
            ("train", "pos", 1): b"great movie fun",
            ("train", "neg", 0): b"bad bad awful movie",
            ("train", "neg", 1): b"awful movie bad fun",
            ("test", "pos", 0): b"great fun",
            ("test", "neg", 0): b"bad awful",
        }
        with tarfile.open(path, "w:gz") as tf:
            for (split, cls, i), text in docs.items():
                info = tarfile.TarInfo(f"aclImdb/{split}/{cls}/{i}.txt")
                info.size = len(text)
                tf.addfile(info, io.BytesIO(text))
        return path

    def test_parse_and_dict(self, tmp_path):
        ds = Imdb(data_file=self._make_tar(tmp_path), mode="train",
                  cutoff=1)
        # words with freq > 1 survive; sorted by (-freq, word)
        assert "movie" in ds.word_idx and "<unk>" in ds.word_idx
        # punctuation stripped, lowercased
        assert "movie!" not in ds.word_idx
        assert len(ds) == 4
        doc, label = ds[0]
        assert doc.dtype == np.int64 or doc.dtype == np.int32 or \
            doc.dtype.kind == "i"
        assert label.shape == (1,)
        labels = sorted(int(ds[i][1][0]) for i in range(len(ds)))
        assert labels == [0, 0, 1, 1]  # pos=0 neg=1

    def test_cutoff_respected(self, tmp_path):
        path = self._make_tar(tmp_path)
        small = Imdb(data_file=path, mode="train", cutoff=1)
        big = Imdb(data_file=path, mode="train", cutoff=100)
        assert len(big.word_idx) < len(small.word_idx)
        assert list(big.word_idx) == ["<unk>"]

    def test_synthetic_fallback(self):
        ds = Imdb(mode="test")
        assert len(ds) > 0 and len(ds.word_idx) > 1


class TestImikolov:
    def _make_tar(self, tmp_path):
        path = str(tmp_path / "ptb.tar.gz")
        corpus = {"train": "a b c a b\na b\n", "valid": "a c\n",
                  "test": "b c a\n"}
        with tarfile.open(path, "w:gz") as tf:
            for split, text in corpus.items():
                data = text.encode()
                info = tarfile.TarInfo(
                    f"./simple-examples/data/ptb.{split}.txt")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        return path

    def test_ngram(self, tmp_path):
        ds = Imikolov(data_file=self._make_tar(tmp_path), data_type="NGRAM",
                      window_size=2, mode="train", min_word_freq=0)
        # "a b c a b" + <s>/<e> -> 6 bigrams; "a b" -> 3 bigrams
        assert len(ds) == 9
        assert all(len(s) == 2 for s in (ds[i] for i in range(3)))

    def test_seq_mode_and_min_freq(self, tmp_path):
        path = self._make_tar(tmp_path)
        ds = Imikolov(data_file=path, data_type="SEQ", window_size=-1,
                      mode="test", min_word_freq=0)
        src, trg = ds[0]
        assert src[0] == ds.word_idx["<s>"]
        assert trg[-1] == ds.word_idx["<e>"]
        # min_word_freq prunes words into <unk>
        pruned = Imikolov(data_file=path, data_type="NGRAM", window_size=2,
                          mode="train", min_word_freq=3)
        assert "c" not in pruned.word_idx  # freq 2 <= 3 in train+valid
        assert "a" in pruned.word_idx      # freq 4 > 3


class TestMovielens:
    def _make_zip(self, tmp_path):
        path = str(tmp_path / "ml.zip")
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("ml-1m/movies.dat",
                       "1::Toy Story (1995)::Animation|Comedy\n"
                       "2::Heat (1995)::Action\n")
            z.writestr("ml-1m/users.dat",
                       "1::F::1::10::48067\n2::M::56::16::70072\n")
            z.writestr("ml-1m/ratings.dat",
                       "1::1::5::978300760\n2::2::1::978302109\n"
                       "1::2::4::978301968\n")
        return path

    def test_parse(self, tmp_path):
        ds = Movielens(data_file=self._make_zip(tmp_path), mode="train",
                       test_ratio=0.0)
        assert len(ds) == 3
        sample = ds[0]
        # usr(4) + movie(3) + rating = 8 fields
        assert len(sample) == 8
        uid, gender, age, job = sample[:4]
        assert gender[0] in (0, 1)
        rating = sample[-1]
        assert -5.0 <= float(rating[0]) <= 5.0
        # title word dict strips year suffix and lowercases
        assert "toy" in ds.movie_title_dict
        assert "(1995)" not in ds.movie_title_dict


class TestUCIHousing:
    def test_parse_normalize_split(self, tmp_path):
        rng = np.random.RandomState(0)
        data = rng.rand(10, 14)
        path = str(tmp_path / "housing.data")
        with open(path, "w") as f:
            for row in data:
                f.write(" ".join(map(str, row)) + "\n")
        tr = UCIHousing(data_file=path, mode="train")
        te = UCIHousing(data_file=path, mode="test")
        assert len(tr) == 8 and len(te) == 2
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        # feature normalization: (x - avg) / (max - min)
        avg = data[:, 0].mean()
        rngspan = data[:, 0].max() - data[:, 0].min()
        np.testing.assert_allclose(float(x[0]),
                                   (data[0, 0] - avg) / rngspan, rtol=1e-5)
        # label column is untouched
        np.testing.assert_allclose(float(y[0]), data[0, -1], rtol=1e-5)


class TestConll05:
    def test_parse_props_format(self):
        ds = Conll05st()
        assert len(ds) > 0
        sample = ds[0]
        assert len(sample) == 9
        words, c_n2, c_n1, c_0, c_p1, c_p2, pred, mark, labels = sample
        n = len(words)
        assert all(len(x) == n for x in sample)
        # the predicate mark column has the verb window flagged
        assert mark.sum() >= 1
        # labels include the verb tag
        word_d, verb_d, label_d = ds.get_dict()
        assert label_d["B-V"] in labels
        assert os.path.exists(ds.get_embedding())


class TestWMT:
    def test_wmt14(self):
        ds = WMT14(mode="train")
        src, trg, trg_next = ds[0]
        sd, td = ds.get_dict()
        assert src[0] == sd["<s>"] and src[-1] == sd["<e>"]
        assert trg[0] == td["<s>"]
        assert trg_next[-1] == td["<e>"]
        # shifted-by-one relation
        np.testing.assert_array_equal(trg[1:], trg_next[:-1])

    def test_wmt14_dict_size(self, tmp_path):
        # over-length sequences (>80 tokens) are dropped per the reference
        path = str(tmp_path / "wmt14.tar.gz")
        long_src = " ".join(["s0"] * 100)
        with tarfile.open(path, "w:gz") as tf:
            def add(name, text):
                data = text.encode()
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
            add("d/src.dict", "<s>\n<e>\n<unk>\ns0\n")
            add("d/trg.dict", "<s>\n<e>\n<unk>\nt0\n")
            add("train/train", f"{long_src}\tt0\ns0 s0\tt0 t0\n")
        ds = WMT14(data_file=path, mode="train", dict_size=4)
        assert len(ds) == 1  # the 100-token line was dropped

    def test_wmt16_builds_dict_from_train(self):
        ds = WMT16(mode="val", src_dict_size=10, trg_dict_size=10)
        assert ds.src_dict["<s>"] == 0 and ds.src_dict["<unk>"] == 2
        assert len(ds.src_dict) <= 10
        src, trg, trg_next = ds[0]
        np.testing.assert_array_equal(trg[1:], trg_next[:-1])


def test_bert_finetune_on_imdb_parser():
    """End-to-end: Imdb tar parser -> DataLoader (pad collate) -> BERT
    classifier -> hapi-style train loop; loss decreases."""
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, BertForSequenceClassification

    ds = Imdb(mode="train")
    maxlen = 32

    class Padded(paddle.io.Dataset):
        def __len__(self):
            return min(len(ds), 32)

        def __getitem__(self, i):
            doc, label = ds[i]
            doc = doc[:maxlen]
            ids = np.zeros(maxlen, np.int64)
            ids[:len(doc)] = doc % 1000
            return ids, label.reshape(-1)

    paddle.seed(0)
    cfg = BertConfig(vocab_size=1000, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64,
                     max_position=maxlen, dropout=0.0,
                     attention_dropout=0.0)
    model = BertForSequenceClassification(cfg, num_classes=2)
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 parameters=model.parameters())
    loader = paddle.io.DataLoader(Padded(), batch_size=8, shuffle=False)
    loss_fn = paddle.nn.CrossEntropyLoss()
    losses = []
    for epoch in range(4):
        for ids, labels in loader:
            out = model(ids)
            logits = out[0] if isinstance(out, (list, tuple)) else out
            loss = loss_fn(logits, labels.reshape([-1]))
            loss.backward()
            opt.step()
            opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
