"""io tests (reference: unittests test_dataloader_*, test_batch_sampler)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import (
    BatchSampler, ChainDataset, ConcatDataset, DataLoader, Dataset,
    DistributedBatchSampler, IterableDataset, RandomSampler, SequenceSampler,
    Subset, TensorDataset, WeightedRandomSampler, random_split,
)


class RangeDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i, i * 2], np.float32), np.asarray(i % 3, np.int64)


def test_dataloader_basic():
    dl = DataLoader(RangeDataset(10), batch_size=4)
    batches = list(dl)
    assert len(batches) == 3
    x, y = batches[0]
    assert x.shape == [4, 2] and y.shape == [4]
    assert batches[-1][0].shape == [2, 2]  # remainder kept
    dl2 = DataLoader(RangeDataset(10), batch_size=4, drop_last=True)
    assert len(list(dl2)) == 2


def test_dataloader_shuffle_and_workers():
    ds = RangeDataset(64)
    dl = DataLoader(ds, batch_size=8, shuffle=True, num_workers=2)
    seen = []
    for x, y in dl:
        seen.extend(x.numpy()[:, 0].astype(int).tolist())
    assert sorted(seen) == list(range(64))
    assert seen != list(range(64))  # shuffled


def test_dataloader_custom_collate():
    def collate(batch):
        xs = np.stack([b[0] for b in batch])
        return paddle.to_tensor(xs.sum())
    dl = DataLoader(RangeDataset(4), batch_size=4, collate_fn=collate)
    (out,) = list(dl)
    assert out.ndim == 0


def test_iterable_dataset():
    class It(IterableDataset):
        def __iter__(self):
            for i in range(7):
                yield np.asarray([i], np.float32)
    dl = DataLoader(It(), batch_size=3)
    shapes = [b.shape for b in dl]
    assert shapes == [[3, 1], [3, 1], [1, 1]]


def test_tensor_dataset_and_ops():
    x = paddle.randn([10, 3])
    y = paddle.arange(10)
    ds = TensorDataset([x, y])
    assert len(ds) == 10
    a, b = ds[3]
    assert a.shape == [3] and int(b) == 3
    sub = Subset(ds, [1, 3, 5])
    assert len(sub) == 3
    parts = random_split(ds, [7, 3])
    assert len(parts[0]) == 7 and len(parts[1]) == 3
    parts_f = random_split(ds, [0.5, 0.5])
    assert len(parts_f[0]) + len(parts_f[1]) == 10
    cat = ConcatDataset([RangeDataset(3), RangeDataset(4)])
    assert len(cat) == 7
    np.testing.assert_allclose(cat[5][0], [2, 4])


def test_samplers():
    ds = RangeDataset(10)
    assert list(SequenceSampler(ds)) == list(range(10))
    rs = list(RandomSampler(ds))
    assert sorted(rs) == list(range(10))
    ws = list(WeightedRandomSampler([0.1, 0.9], 100))
    assert 0 < sum(ws) < 100  # mostly index 1
    bs = BatchSampler(ds, batch_size=3)
    assert [len(b) for b in bs] == [3, 3, 3, 1]
    assert len(bs) == 4


def test_distributed_batch_sampler():
    ds = RangeDataset(16)
    seen = []
    for rank in range(4):
        s = DistributedBatchSampler(ds, batch_size=2, num_replicas=4,
                                    rank=rank)
        for b in s:
            seen.extend(b)
    assert sorted(seen) == list(range(16))
    s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=4, rank=0,
                                 shuffle=True)
    s0.set_epoch(1)
    assert len(list(s0)) == 2


def test_save_load_roundtrip(tmp_path):
    sd = {"w": paddle.randn([3, 3]), "nested": {"b": paddle.ones([2])},
          "step": 7}
    p = str(tmp_path / "ckpt.pdparams")
    paddle.save(sd, p)
    back = paddle.load(p)
    np.testing.assert_allclose(back["w"].numpy(), sd["w"].numpy())
    np.testing.assert_allclose(back["nested"]["b"].numpy(), [1, 1])
    assert back["step"] == 7
    back_np = paddle.load(p, return_numpy=True)
    assert isinstance(back_np["w"], np.ndarray)


def test_save_load_bytesio():
    """The reference supports BytesIO targets for paddle.save/load
    (framework/io.py _open_file_buffer)."""
    import io as _io

    import numpy as np

    import paddle_tpu as paddle

    obj = {"w": paddle.to_tensor(np.arange(6, dtype=np.float32)),
           "meta": {"epoch": 3, "name": "x"},
           "list": [paddle.to_tensor(np.ones((2, 2), np.float32)), 7]}
    buf = _io.BytesIO()
    paddle.save(obj, buf)
    buf.seek(0)
    back = paddle.load(buf)
    np.testing.assert_array_equal(np.asarray(back["w"].numpy()),
                                  np.arange(6, dtype=np.float32))
    assert back["meta"] == {"epoch": 3, "name": "x"}
    assert float(back["list"][0].numpy().sum()) == 4.0 and \
        back["list"][1] == 7
