"""Flagship BERT/GPT tests (reference: fleet GPT unit tests pattern).

Marked slow: ~240s of CPU compile-bound generate/training loops — the
single largest tier-1 time sink (PR 2 `--durations` profile, which
measured the suite 150s OVER the 870s budget). Run with `-m slow`.
"""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models import (
    BertConfig, BertForMaskedLM, BertForSequenceClassification, BertModel,
    GPTConfig, GPTForCausalLM, GPTModel,
)


def _tiny_gpt():
    return GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=4, max_position=64, dropout=0.0)


def _tiny_bert():
    return BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, max_position=64, intermediate_size=64,
                      dropout=0.0, attention_dropout=0.0)


def test_gpt_forward_loss_and_train_step():
    paddle.seed(0)
    m = GPTForCausalLM(_tiny_gpt())
    ids = paddle.randint(0, 128, [2, 16])
    logits = m(ids)
    assert logits.shape == [2, 16, 128]
    loss0 = m(ids, labels=ids)
    assert 4.0 < float(loss0) < 6.5  # ~ln(128)=4.85 at init
    opt = paddle.optimizer.Adam(1e-3, parameters=m.parameters())
    for _ in range(5):
        loss = m(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < float(loss0)


def test_gpt_causality():
    """Changing a future token must not affect earlier logits."""
    paddle.seed(0)
    m = GPTForCausalLM(_tiny_gpt())
    m.eval()
    ids = paddle.randint(0, 128, [1, 12])
    with paddle.no_grad():
        base = m(ids).numpy()
        ids2 = ids.numpy().copy()
        ids2[0, -1] = (ids2[0, -1] + 1) % 128
        out2 = m(paddle.to_tensor(ids2)).numpy()
    np.testing.assert_allclose(base[0, :-1], out2[0, :-1], rtol=1e-4,
                               atol=1e-5)
    assert not np.allclose(base[0, -1], out2[0, -1])


def test_gpt_generate_with_cache_matches_full_forward():
    paddle.seed(0)
    m = GPTForCausalLM(_tiny_gpt())
    m.eval()
    ids = paddle.randint(0, 128, [1, 8])
    gen = m.generate(ids, max_new_tokens=4)
    assert gen.shape == [1, 12]
    # greedy decode step-by-step without cache must agree
    cur = ids
    with paddle.no_grad():
        for _ in range(4):
            logits = m(cur)
            nxt = paddle.argmax(logits[:, -1], -1)
            cur = paddle.concat([cur, paddle.unsqueeze(nxt, -1)], axis=1)
    np.testing.assert_array_equal(gen.numpy(), cur.numpy())


def test_bert_masked_lm_and_classification():
    paddle.seed(0)
    m = BertForMaskedLM(_tiny_bert())
    ids = paddle.randint(0, 128, [2, 16])
    labels = paddle.randint(0, 128, [2, 16])
    loss = m(ids, labels=labels)
    assert 4.0 < float(loss) < 6.5
    loss.backward()
    # pooler/NSP head sit outside the MLM loss graph; everything else grads
    with_grad = sum(1 for p in m.parameters() if p.grad is not None)
    assert with_grad >= len(m.parameters()) - 4
    assert m.bert.embeddings.word_embeddings.weight.grad is not None

    clf = BertForSequenceClassification(_tiny_bert(), num_classes=3)
    logits = clf(ids)
    assert logits.shape == [2, 3]


def test_bert_attention_mask():
    paddle.seed(0)
    m = BertModel(_tiny_bert())
    m.eval()
    ids = paddle.randint(0, 128, [1, 10])
    mask = paddle.to_tensor(np.array([[1] * 6 + [0] * 4], np.int64))
    with paddle.no_grad():
        h1, _ = m(ids, attention_mask=mask)
        # padding content must not influence unmasked positions
        ids2 = ids.numpy().copy()
        ids2[0, 7] = (ids2[0, 7] + 1) % 128
        h2, _ = m(paddle.to_tensor(ids2), attention_mask=mask)
    np.testing.assert_allclose(h1.numpy()[0, :6], h2.numpy()[0, :6],
                               rtol=1e-4, atol=1e-5)


def test_gpt_mlm_loss_decreases_under_model_fit():
    paddle.seed(0)
    m = GPTForCausalLM(_tiny_gpt())

    class LMWrapper(nn.Layer):
        def __init__(self, gpt):
            super().__init__()
            self.gpt = gpt

        def forward(self, ids):
            return self.gpt(ids)

    ids = np.random.randint(0, 128, (32, 16))

    class ShiftCE(nn.Layer):
        def forward(self, logits, labels):
            from paddle_tpu import tensor as T

            return nn.functional.cross_entropy(
                T.reshape(logits, [-1, logits.shape[-1]]),
                T.reshape(labels, [-1]))

    model = paddle.Model(LMWrapper(m))
    model.prepare(paddle.optimizer.Adam(1e-3, parameters=m.parameters()),
                  ShiftCE())
    model.fit([ids, ids], epochs=3, batch_size=16, verbose=0)
    res = model.evaluate([ids, ids], batch_size=16, verbose=0)
    assert res["loss"] < 4.85  # below uniform-random entropy


@pytest.mark.tpu
@pytest.mark.skipif(
    __import__("jax").default_backend() != "tpu",
    reason="pallas flash attention runs on TPU only")
def test_flash_attention_matches_xla():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import flash_attention_raw

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 256, 64), jnp.float32)
    k = jnp.asarray(rng.randn(2, 256, 64), jnp.float32)
    v = jnp.asarray(rng.randn(2, 256, 64), jnp.float32)

    def ref(q, k, v, causal):
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(64)
        if causal:
            s = jnp.where(jnp.tril(jnp.ones((256, 256), bool)), s, -1e30)
        return jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, -1), v)

    for causal in (False, True):
        out = flash_attention_raw(q, k, v, causal)
        assert float(jnp.abs(out - ref(q, k, v, causal)).max()) < 2e-2


class TestJitGenerate:
    """Jitted static-shape decode vs the eager KV-cache path."""

    def _model(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        paddle.seed(21)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=3, num_heads=4,
            max_position=48, dropout=0.0, use_flash=False))
        m.eval()
        return m

    # Cross-implementation numeric tolerance for the tie-aware parity
    # check below. Measured drift between the two decode programs on
    # this fixture is ~3e-2 in the logits (see the test docstring).
    _XIMPL_TOL = 0.05

    def test_greedy_parity_with_eager(self):
        """Tie-aware greedy parity: the jit decode's token choices must
        be consistent with the eager path's logits up to documented
        cross-implementation float drift.

        Token-EXACT equality between the two decode implementations is
        not well-defined (the pre-PR-11 form of this test): the
        static-KV jitted decode and the eager growing-cache decode are
        mathematically equivalent but compile to DIFFERENT XLA
        programs (padded S=48 attention + lax.scan over stacked layers
        vs exact-length attention + a Python layer loop), so f32
        reduction orders differ; on this tiny random-init model the
        pre-LN normalizations divide near-zero-variance activations,
        amplifying that rounding noise to ~3e-2 in the logits, and
        greedy argmax turns any near-tie into full token divergence
        from that step on. Under the default env the per-op jit cache
        happens to round like the jit decode and exact equality held;
        under PADDLE_TPU_EAGER_JIT=0 plain eager rounds differently
        and it reproducibly failed (ROADMAP pre-existing cluster).

        So: teacher-force the jit path's output through ONE eager
        forward and assert, per generated token, that (a) the chosen
        token's eager logit is within tolerance of the eager argmax,
        and (b) wherever eager's top-2 gap is decisive (> 2x the
        tolerance) the tokens agree exactly."""
        from paddle_tpu import tensor as T

        m = self._model()
        rng = np.random.RandomState(0)
        n_new, t0 = 9, 7
        ids = paddle.to_tensor(rng.randint(0, 97, (2, t0)))
        out_jit = m.generate(ids, max_new_tokens=n_new, use_jit=True)
        toks = np.asarray(out_jit.numpy())
        assert toks.shape == (2, t0 + n_new)
        np.testing.assert_array_equal(toks[:, :t0],
                                      np.asarray(ids.numpy()))
        with paddle.no_grad():
            hidden = m.gpt(paddle.to_tensor(toks[:, :-1]))
            logits = T.matmul(hidden, m.gpt.wte.weight,
                              transpose_y=True)
        lg = np.asarray(logits._value)  # position p predicts token p+1
        tol = self._XIMPL_TOL
        for b in range(toks.shape[0]):
            for step in range(n_new):
                row = lg[b, t0 - 1 + step]
                tok = toks[b, t0 + step]
                top2 = np.sort(row)[-2:]
                assert row[tok] >= row.max() - tol, (
                    b, step, tok, float(row.max() - row[tok]))
                if top2[1] - top2[0] > 2 * tol:
                    assert tok == int(row.argmax()), (b, step)

    def test_decode_executable_reused(self):
        import jax

        m = self._model()
        rng = np.random.RandomState(1)
        ids = paddle.to_tensor(rng.randint(0, 97, (2, 5)))
        m.generate(ids, max_new_tokens=4, use_jit=True)
        decode_jits = [v for k, v in m._gen_jit_cache.items()
                       if k[0] == "decode"]
        assert len(decode_jits) == 1
        # RELATIVE assertion: jax may evict pjit trace caches in a
        # long-lived process (observed as _cache_size()==0 deep into the
        # full suite), so pin the invariant that matters — a longer
        # continuation adds NO new trace signatures to the same decode
        # executable (one signature serves every step and length)
        s1 = decode_jits[0]._cache_size()
        assert s1 <= 1
        m.generate(ids, max_new_tokens=8, use_jit=True)
        s2 = decode_jits[0]._cache_size()
        assert s2 <= max(s1, 1)

    def test_topk_sampling_shapes(self):
        m = self._model()
        rng = np.random.RandomState(2)
        ids = paddle.to_tensor(rng.randint(0, 97, (1, 4)))
        out = m.generate(ids, max_new_tokens=6, temperature=0.8, top_k=5,
                         use_jit=True)
        assert out.shape == [1, 10]
        arr = np.asarray(out.numpy())
        assert ((arr >= 0) & (arr < 97)).all()


def test_jit_generate_review_regressions():
    """max_new_tokens=0 returns the prompt; greedy decode leaves the
    global RNG stream untouched."""
    from paddle_tpu.framework import random as rnd
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(31)
    m = GPTForCausalLM(GPTConfig(vocab_size=50, hidden_size=16,
                                 num_layers=2, num_heads=2,
                                 max_position=32, dropout=0.0,
                                 use_flash=False))
    m.eval()
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 50, (1, 6)))
    out0 = m.generate(ids, max_new_tokens=0)
    assert out0.shape == [1, 6]

    paddle.seed(77)
    m.generate(ids, max_new_tokens=3)  # greedy: must not draw keys
    a = np.asarray(paddle.randn([4]).numpy())
    paddle.seed(77)
    b = np.asarray(paddle.randn([4]).numpy())
    np.testing.assert_array_equal(a, b)


def test_jit_generate_amp_bf16():
    """Jit decode under amp.decorate O2 (bf16 weights, f32 norms) — the
    scan carry must stay one dtype."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(41)
    m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                 num_layers=2, num_heads=4,
                                 max_position=32, dropout=0.0,
                                 use_flash=False))
    paddle.amp.decorate(m, level="O2")
    m.eval()
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 64, (2, 6)))
    out = m.generate(ids, max_new_tokens=5)
    assert out.shape == [2, 11]


class TestGenerateStrategies:
    """top-p sampling + jitted beam search (reference generation
    utilities' decode strategies on the static-KV substrate)."""

    def _model(self, max_pos=32):
        paddle.seed(0)
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                        num_heads=4, max_position=max_pos, dropout=0.0,
                        use_flash=False)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m

    def test_top_p_sampling_runs_and_differs_from_greedy(self):
        model = self._model()
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 96, (2, 6)))
        greedy = model.generate(ids, max_new_tokens=8)
        nucleus = model.generate(ids, max_new_tokens=8, top_p=0.9)
        assert greedy.shape == nucleus.shape == [2, 14]
        out = np.asarray(nucleus.numpy())
        assert ((0 <= out) & (out < 96)).all()
        # top_p=tiny keeps only the argmax token -> equals greedy
        strict = model.generate(ids, max_new_tokens=8, top_p=1e-9)
        np.testing.assert_array_equal(strict.numpy(), greedy.numpy())

    def test_beam_search_matches_greedy_at_k1_and_scores_at_k4(self):
        model = self._model()
        rng = np.random.RandomState(1)
        ids = paddle.to_tensor(rng.randint(0, 96, (2, 4)))
        greedy = model.generate(ids, max_new_tokens=6)
        beam1 = model.generate(ids, max_new_tokens=6, num_beams=1)
        np.testing.assert_array_equal(beam1.numpy(), greedy.numpy())
        beam4 = model.generate(ids, max_new_tokens=6, num_beams=4)
        assert beam4.shape == [2, 10]

        # beam-4's sequence log-prob must be >= greedy's (that's the
        # point of the search); verify by scoring both with the model
        def seq_logp(seq):
            seq_t = paddle.to_tensor(seq)
            logits = model(seq_t)
            lp = np.asarray(
                paddle.nn.functional.log_softmax(logits, -1).numpy())
            tot = np.zeros(seq.shape[0])
            for b in range(seq.shape[0]):
                for t in range(3, seq.shape[1] - 1):
                    tot[b] += lp[b, t, seq[b, t + 1]]
            return tot

        g = seq_logp(np.asarray(greedy.numpy()))
        b = seq_logp(np.asarray(beam4.numpy()))
        assert (b >= g - 1e-4).all(), (b, g)

    def test_beam_search_eos_freezes_finished(self):
        model = self._model()
        rng = np.random.RandomState(2)
        ids = paddle.to_tensor(rng.randint(0, 96, (1, 4)))
        out = model.generate(ids, max_new_tokens=8, num_beams=3,
                             eos_token_id=5)
        seq = np.asarray(out.numpy())[0, 4:]
        # after the first eos, the frozen beam only emits eos
        if (seq == 5).any():
            first = int(np.argmax(seq == 5))
            assert (seq[first:] == 5).all()

    def test_beam_rejects_sampling_mix(self):
        model = self._model()
        ids = paddle.to_tensor(np.zeros((1, 4), np.int64))
        with pytest.raises(ValueError, match="mutually exclusive"):
            model.generate(ids, max_new_tokens=4, num_beams=2, top_k=5)


def test_top_p_eager_path_and_zero_edge():
    """The eager fallback honors top_p, and top_p=0 degrades to greedy
    (keep-at-least-top-1 clamp), never uniform noise."""
    paddle.seed(0)
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position=24, dropout=0.0,
                    use_flash=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 64, (2, 4)))
    # compare within ONE execution path: jit and eager forwards can
    # diverge on near-tie logits (fusion changes rounding)
    greedy_eager = model.generate(ids, max_new_tokens=6, use_jit=False)
    eager = model.generate(ids, max_new_tokens=6, top_p=1e-9,
                           use_jit=False)
    np.testing.assert_array_equal(eager.numpy(), greedy_eager.numpy())
    greedy_jit = model.generate(ids, max_new_tokens=6)
    np.testing.assert_array_equal(
        model.generate(ids, max_new_tokens=6, top_p=0.0).numpy(),
        greedy_jit.numpy())
    out = model.generate(ids, max_new_tokens=6, top_p=0.8,
                         use_jit=False)
    assert out.shape == [2, 10]


class TestSeq2SeqTransformer:
    """models/transformer.py — the WMT seq2seq flagship family."""

    def _model(self):
        paddle.seed(0)
        from paddle_tpu.models.transformer import (
            TransformerConfig, TransformerModel,
        )

        cfg = TransformerConfig(src_vocab_size=64, tgt_vocab_size=64,
                                d_model=32, nhead=4, num_encoder_layers=2,
                                num_decoder_layers=2, dim_feedforward=64,
                                dropout=0.0, max_length=16, pad_id=0,
                                bos_id=1, eos_id=2)
        return TransformerModel(cfg)

    def test_teacher_forcing_trains(self):
        model = self._model()
        model.eval()  # dropout 0 anyway; deterministic
        rng = np.random.RandomState(0)
        src = paddle.to_tensor(rng.randint(3, 64, (4, 8)))
        tgt_in = paddle.to_tensor(rng.randint(3, 64, (4, 6)))
        labels = paddle.to_tensor(rng.randint(3, 64, (4, 6)))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        losses = []
        for _ in range(6):
            loss = model(src, tgt_in, labels=labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_padding_excluded_from_loss(self):
        model = self._model()
        model.eval()
        rng = np.random.RandomState(1)
        src = paddle.to_tensor(rng.randint(3, 64, (2, 8)))
        tgt_in = paddle.to_tensor(rng.randint(3, 64, (2, 6)))
        lab = rng.randint(3, 64, (2, 6))
        l_full = float(model(src, paddle.to_tensor(tgt_in),
                             labels=paddle.to_tensor(lab)))
        lab_pad = lab.copy()
        lab_pad[:, 3:] = 0  # pad_id: masked out of the mean
        l_pad = float(model(src, paddle.to_tensor(tgt_in),
                            labels=paddle.to_tensor(lab_pad)))
        assert l_full != l_pad  # the mask changed the objective

    def test_greedy_generate_with_cache(self):
        model = self._model()
        model.eval()
        rng = np.random.RandomState(2)
        src = paddle.to_tensor(rng.randint(3, 64, (3, 8)))
        out = model.generate(src, max_length=8)
        arr = np.asarray(out.numpy())
        assert arr.shape[0] == 3 and arr.shape[1] <= 8
        assert (arr[:, 0] == 1).all()  # starts at bos

    def test_cached_decode_matches_full_forward(self):
        """Incremental cache decode must equal the full (no-cache)
        decoder on the same prefix — the correctness contract of the
        Cache machinery."""
        model = self._model()
        model.eval()
        rng = np.random.RandomState(3)
        src = paddle.to_tensor(rng.randint(3, 64, (2, 8)))
        out = model.generate(src, max_length=6)
        ids = np.asarray(out.numpy())
        # full teacher-forcing pass over the generated prefix
        logits = model(src, paddle.to_tensor(ids.astype(np.int64)))
        full_next = np.argmax(np.asarray(logits.numpy()), -1)
        # every generated token (after bos) equals the full-forward
        # argmax at the previous position
        for t in range(1, ids.shape[1]):
            np.testing.assert_array_equal(ids[:, t], full_next[:, t - 1])


def test_seq2seq_guards_and_eos_freeze():
    from paddle_tpu.models.transformer import (
        TransformerConfig, TransformerModel,
    )

    with pytest.raises(ValueError, match="share_embedding"):
        TransformerConfig(src_vocab_size=64, tgt_vocab_size=32,
                          share_embedding=True)
    paddle.seed(0)
    cfg = TransformerConfig(src_vocab_size=32, tgt_vocab_size=32,
                            d_model=16, nhead=2, num_encoder_layers=1,
                            num_decoder_layers=1, dim_feedforward=32,
                            dropout=0.0, max_length=8, pad_id=0,
                            bos_id=1, eos_id=2)
    model = TransformerModel(cfg)
    model.eval()
    src = paddle.to_tensor(np.random.RandomState(0).randint(3, 32, (2, 6)))
    with pytest.raises(ValueError, match="max_length"):
        model.generate(src, max_length=99)
    with pytest.raises(ValueError, match="max_length"):
        model(paddle.to_tensor(np.zeros((1, 20), np.int64)),
              paddle.to_tensor(np.zeros((1, 4), np.int64)))
    out = np.asarray(model.generate(src, max_length=8).numpy())
    for b in range(out.shape[0]):  # post-eos tail is pad only
        row = out[b, 1:]
        if (row == 2).any():
            first = int(np.argmax(row == 2))
            assert (row[first + 1:] == 0).all()


def test_seq2seq_through_hapi_model_multi_input():
    """Model.train_batch with TWO inputs (src, tgt_in) — the reference's
    transformer-under-paddle.Model pattern exercises hapi's multi-input
    jitted step."""
    from paddle_tpu.models.transformer import (
        TransformerConfig, TransformerModel,
    )

    paddle.seed(0)
    cfg = TransformerConfig(src_vocab_size=48, tgt_vocab_size=48,
                            d_model=32, nhead=4, num_encoder_layers=1,
                            num_decoder_layers=1, dim_feedforward=64,
                            dropout=0.0, max_length=16)

    class WithLoss(nn.Layer):
        def __init__(self):
            super().__init__()
            self.m = TransformerModel(cfg)

        def forward(self, src, tgt_in):
            return self.m(src, tgt_in)

    class TokenCE(nn.Layer):
        def forward(self, logits, labels):
            import paddle_tpu.tensor as T

            return nn.functional.cross_entropy(
                T.reshape(logits, [-1, 48]), T.reshape(labels, [-1]))

    model = paddle.Model(WithLoss())
    model.prepare(paddle.optimizer.Adam(1e-3,
                                        parameters=model.parameters()),
                  TokenCE())
    rng = np.random.RandomState(0)
    src = paddle.to_tensor(rng.randint(3, 48, (16, 8)))
    tgt = paddle.to_tensor(rng.randint(3, 48, (16, 6)))
    lab = paddle.to_tensor(rng.randint(3, 48, (16, 6)))
    l1 = model.train_batch([src, tgt], [lab])[0]
    l2 = model.train_batch([src, tgt], [lab])[0]
    assert float(l2) < float(l1)
