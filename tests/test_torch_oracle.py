"""Numerics vs the torch CPU oracle — an independent reference
implementation (the numeric sweep's numpy formulas share our own
derivations; torch does not).

Covers the activation/loss/norm functions with subtle definitional
corners (approximate vs exact gelu, label smoothing, eps placement).
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _t(a):
    return paddle.to_tensor(a)


def _cmp(got, want, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(got.numpy(), np.float32),
                               want.detach().numpy(), rtol=rtol,
                               atol=atol)


_X = np.random.RandomState(0).randn(64).astype(np.float32) * 3


@pytest.mark.parametrize("ours,theirs,kw", [
    (F.relu, torch.nn.functional.relu, {}),
    (F.relu6, torch.nn.functional.relu6, {}),
    (F.silu, torch.nn.functional.silu, {}),
    (F.mish, torch.nn.functional.mish, {}),
    (F.softplus, torch.nn.functional.softplus, {}),
    (F.softsign, torch.nn.functional.softsign, {}),
    (F.tanhshrink, torch.nn.functional.tanhshrink, {}),
    (F.hardsigmoid, torch.nn.functional.hardsigmoid, {}),
    (F.hardswish, torch.nn.functional.hardswish, {}),
    (F.elu, torch.nn.functional.elu, {}),
    (F.celu, torch.nn.functional.celu, {}),
    (F.selu, torch.nn.functional.selu, {}),
    (F.log_sigmoid, torch.nn.functional.logsigmoid, {}),
], ids=lambda f: getattr(f, "__name__", str(f)))
def test_activations_vs_torch(ours, theirs, kw):
    _cmp(ours(_t(_X), **kw), theirs(torch.from_numpy(_X), **kw))


def test_gelu_both_modes_vs_torch():
    x = torch.from_numpy(_X)
    _cmp(F.gelu(_t(_X)), torch.nn.functional.gelu(x))
    _cmp(F.gelu(_t(_X), approximate=True),
         torch.nn.functional.gelu(x, approximate="tanh"))


def test_softmax_logsoftmax_vs_torch():
    a = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    _cmp(F.softmax(_t(a), axis=-1),
         torch.softmax(torch.from_numpy(a), -1))
    _cmp(F.log_softmax(_t(a), axis=0),
         torch.log_softmax(torch.from_numpy(a), 0))


def test_cross_entropy_label_smoothing_vs_torch():
    rng = np.random.RandomState(2)
    logits = rng.randn(16, 10).astype(np.float32)
    labels = rng.randint(0, 10, 16)
    got = F.cross_entropy(_t(logits), _t(labels.astype(np.int64)),
                          label_smoothing=0.1)
    want = torch.nn.functional.cross_entropy(
        torch.from_numpy(logits), torch.from_numpy(labels),
        label_smoothing=0.1)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_kl_bce_huber_vs_torch():
    rng = np.random.RandomState(3)
    p = rng.rand(32).astype(np.float32) * 0.98 + 0.01
    q = rng.rand(32).astype(np.float32) * 0.98 + 0.01
    got = F.kl_div(_t(np.log(p)), _t(q), reduction="mean")
    want = torch.nn.functional.kl_div(
        torch.from_numpy(np.log(p)), torch.from_numpy(q),
        reduction="mean")
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    got = F.binary_cross_entropy(_t(p), _t((q > 0.5).astype(np.float32)))
    want = torch.nn.functional.binary_cross_entropy(
        torch.from_numpy(p), torch.from_numpy((q > 0.5).astype(np.float32)))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    x = rng.randn(32).astype(np.float32)
    y = rng.randn(32).astype(np.float32)
    got = F.smooth_l1_loss(_t(x), _t(y))
    want = torch.nn.functional.smooth_l1_loss(torch.from_numpy(x),
                                              torch.from_numpy(y))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_layer_group_norm_vs_torch():
    rng = np.random.RandomState(4)
    x = rng.randn(4, 8, 6).astype(np.float32)
    _cmp(F.layer_norm(_t(x), (6,)),
         torch.nn.functional.layer_norm(torch.from_numpy(x), (6,)),
         rtol=1e-4, atol=1e-5)
    x4 = rng.randn(2, 8, 5, 5).astype(np.float32)
    got = paddle.nn.GroupNorm(4, 8)(_t(x4))
    want = torch.nn.functional.group_norm(torch.from_numpy(x4), 4)
    _cmp(got, want, rtol=1e-4, atol=1e-5)


def test_conv2d_vs_torch():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 3, 9, 9).astype(np.float32)
    w = rng.randn(6, 3, 3, 3).astype(np.float32)
    got = F.conv2d(_t(x), _t(w), stride=2, padding=1)
    want = torch.nn.functional.conv2d(torch.from_numpy(x),
                                      torch.from_numpy(w), stride=2,
                                      padding=1)
    _cmp(got, want, rtol=1e-4, atol=1e-4)


def test_multihead_attention_vs_torch():
    """Full MHA forward parity with torch (weights mapped from torch's
    packed in_proj into the separate q/k/v projections; paddle stores
    [in, out], torch [out, in])."""
    E, H, B, S = 16, 4, 2, 6
    torch.manual_seed(0)
    t_mha = torch.nn.MultiheadAttention(E, H, batch_first=True)
    p_mha = paddle.nn.MultiHeadAttention(E, H)
    _map_mha(p_mha, t_mha, E)  # helper defined below (shared mapping)

    rng = np.random.RandomState(6)
    q = rng.randn(B, S, E).astype(np.float32)
    kv = rng.randn(B, S, E).astype(np.float32)
    want, _ = t_mha(torch.from_numpy(q), torch.from_numpy(kv),
                    torch.from_numpy(kv), need_weights=False)
    got = p_mha(_t(q), _t(kv), _t(kv))
    _cmp(got, want, rtol=1e-4, atol=1e-5)

    # causal mask parity
    causal = torch.triu(torch.full((S, S), float("-inf")), 1)
    want2, _ = t_mha(torch.from_numpy(q), torch.from_numpy(kv),
                     torch.from_numpy(kv), attn_mask=causal,
                     need_weights=False)
    mask = np.triu(np.full((S, S), -np.inf, np.float32), 1)
    got2 = p_mha(_t(q), _t(kv), _t(kv),
                 attn_mask=_t(mask[None, None]))
    _cmp(got2, want2, rtol=1e-4, atol=1e-5)


def test_lstm_gru_cells_vs_torch():
    """Cell-level recurrence parity with torch (identical [4H,E] i,f,g,o
    and [3H,E] r,z,n layouts; GRU's reset gate applied inside the
    hidden-side term)."""
    E, H, B = 8, 12, 4
    rng = np.random.RandomState(7)

    torch.manual_seed(1)
    t_cell = torch.nn.LSTMCell(E, H)
    p_cell = paddle.nn.LSTMCell(E, H)
    p_cell.weight_ih.set_value(t_cell.weight_ih.detach().numpy())
    p_cell.weight_hh.set_value(t_cell.weight_hh.detach().numpy())
    p_cell.bias_ih.set_value(t_cell.bias_ih.detach().numpy())
    p_cell.bias_hh.set_value(t_cell.bias_hh.detach().numpy())
    x = rng.randn(B, E).astype(np.float32)
    h0 = rng.randn(B, H).astype(np.float32)
    c0 = rng.randn(B, H).astype(np.float32)
    th, tc = t_cell(torch.from_numpy(x),
                    (torch.from_numpy(h0), torch.from_numpy(c0)))
    _, (ph, pc) = p_cell(_t(x), (_t(h0), _t(c0)))
    _cmp(ph, th, rtol=1e-5, atol=1e-6)
    _cmp(pc, tc, rtol=1e-5, atol=1e-6)

    t_gru = torch.nn.GRUCell(E, H)
    p_gru = paddle.nn.GRUCell(E, H)
    p_gru.weight_ih.set_value(t_gru.weight_ih.detach().numpy())
    p_gru.weight_hh.set_value(t_gru.weight_hh.detach().numpy())
    p_gru.bias_ih.set_value(t_gru.bias_ih.detach().numpy())
    p_gru.bias_hh.set_value(t_gru.bias_hh.detach().numpy())
    tg = t_gru(torch.from_numpy(x), torch.from_numpy(h0))
    pg, _ = p_gru(_t(x), _t(h0))
    _cmp(pg, tg, rtol=1e-5, atol=1e-6)


def test_pooling_corners_vs_torch():
    """ceil_mode / padding / count_include_pad are where pooling
    implementations classically diverge."""
    rng = np.random.RandomState(8)
    x = rng.randn(2, 3, 7, 7).astype(np.float32)
    tx = torch.from_numpy(x)
    got = F.max_pool2d(_t(x), 3, stride=2, padding=1, ceil_mode=True)
    want = torch.nn.functional.max_pool2d(tx, 3, stride=2, padding=1,
                                          ceil_mode=True)
    _cmp(got, want)
    got = F.avg_pool2d(_t(x), 2, stride=2)
    want = torch.nn.functional.avg_pool2d(tx, 2, stride=2)
    _cmp(got, want, rtol=1e-5, atol=1e-6)
    got = F.adaptive_avg_pool2d(_t(x), (3, 5))
    want = torch.nn.functional.adaptive_avg_pool2d(tx, (3, 5))
    _cmp(got, want, rtol=1e-5, atol=1e-6)


def test_batchnorm_running_stats_vs_torch():
    """Train-mode BN: normalized output AND the running-stat update rule
    (torch and reference paddle share momentum semantics)."""
    C = 6
    torch.manual_seed(2)
    t_bn = torch.nn.BatchNorm2d(C, momentum=0.1)
    p_bn = paddle.nn.BatchNorm2D(C, momentum=0.9)  # paddle: 1 - torch's
    rng = np.random.RandomState(9)
    for step in range(3):
        x = rng.randn(4, C, 5, 5).astype(np.float32)
        want = t_bn(torch.from_numpy(x))
        got = p_bn(_t(x))
        _cmp(got, want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(p_bn._mean.numpy(),
                               t_bn.running_mean.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    # KNOWN paddle-vs-torch divergence: the reference PHI kernel updates
    # the running variance with the BIASED batch variance
    # (phi/kernels/cpu/batch_norm_kernel.cc: saved_variance /= N*sample)
    # while torch applies the Bessel correction. We follow the
    # reference; reconstruct its EMA by hand and assert against that.
    n = 4 * 5 * 5
    np.testing.assert_allclose(
        p_bn._variance.numpy(),
        # torch EMA of unbiased vars -> rebuild with biased vars: both
        # share the init term, the batch terms scale by (n-1)/n
        (t_bn.running_var.detach().numpy() - 0.9 ** 3)
        * (n - 1) / n + 0.9 ** 3,
        rtol=1e-4, atol=1e-5)


def test_embedding_padding_idx_vs_torch():
    V, D = 12, 6
    torch.manual_seed(3)
    t_emb = torch.nn.Embedding(V, D, padding_idx=0)
    p_emb = paddle.nn.Embedding(V, D, padding_idx=0)
    p_emb.weight.set_value(t_emb.weight.detach().numpy())
    idx = np.array([[0, 3, 5], [7, 0, 11]])
    _cmp(p_emb(_t(idx.astype(np.int64))),
         t_emb(torch.from_numpy(idx)))
    # padding row gets no gradient
    out = p_emb(_t(idx.astype(np.int64)))
    out.sum().backward()
    g = np.asarray(p_emb.weight.grad.numpy())
    np.testing.assert_array_equal(g[0], np.zeros(D, np.float32))


@pytest.mark.parametrize("which", ["sgd", "momentum", "adam", "adamw"])
def test_optimizer_trajectories_vs_torch(which):
    """10-step update trajectories on identical params/grads — bias
    correction, decoupled decay, and momentum accumulation semantics
    all have to line up for the end state to match."""
    rng = np.random.RandomState(10)
    w0 = rng.randn(5, 4).astype(np.float32)
    grads = [rng.randn(5, 4).astype(np.float32) for _ in range(10)]

    tw = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    pw = paddle.to_tensor(w0.copy())
    pw.stop_gradient = False

    mk = {
        "sgd": (lambda: torch.optim.SGD([tw], lr=0.1),
                lambda: paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=[pw])),
        "momentum": (lambda: torch.optim.SGD([tw], lr=0.05, momentum=0.9),
                     lambda: paddle.optimizer.Momentum(
                         learning_rate=0.05, momentum=0.9,
                         parameters=[pw])),
        "adam": (lambda: torch.optim.Adam([tw], lr=0.01),
                 lambda: paddle.optimizer.Adam(learning_rate=0.01,
                                               parameters=[pw])),
        "adamw": (lambda: torch.optim.AdamW([tw], lr=0.01,
                                            weight_decay=0.1),
                  lambda: paddle.optimizer.AdamW(learning_rate=0.01,
                                                 weight_decay=0.1,
                                                 parameters=[pw])),
        "rmsprop": (lambda: torch.optim.RMSprop([tw], lr=0.01, alpha=0.95,
                                                eps=1e-6),
                    lambda: paddle.optimizer.RMSProp(learning_rate=0.01,
                                                     rho=0.95,
                                                     epsilon=1e-6,
                                                     parameters=[pw])),
    }[which]
    t_opt, p_opt = mk[0](), mk[1]()

    from paddle_tpu.core.tensor import Tensor

    for g in grads:
        tw.grad = torch.from_numpy(g.copy())
        t_opt.step()
        pw._grad = Tensor(g.copy())
        p_opt.step()
        p_opt.clear_grad()
    np.testing.assert_allclose(pw.numpy(), tw.detach().numpy(),
                               rtol=2e-5, atol=2e-6)


def test_rmsprop_matches_reference_formula_not_torch():
    """KNOWN divergence: the reference PHI kernel computes
    g / sqrt(mean_square + eps) (rmsprop_kernel_impl.h:82 — eps INSIDE
    the sqrt); torch uses sqrt(v) + eps. We follow the reference; this
    test pins the formula against a hand-rolled trajectory."""
    rng = np.random.RandomState(11)
    w = rng.randn(5, 4).astype(np.float32)
    grads = [rng.randn(5, 4).astype(np.float32) for _ in range(6)]
    pw = paddle.to_tensor(w.copy())
    pw.stop_gradient = False
    opt = paddle.optimizer.RMSProp(learning_rate=0.01, rho=0.95,
                                   epsilon=1e-6, parameters=[pw])
    from paddle_tpu.core.tensor import Tensor

    ref_w, ms = w.copy(), np.zeros_like(w)
    for g in grads:
        pw._grad = Tensor(g.copy())
        opt.step()
        opt.clear_grad()
        ms = 0.95 * ms + 0.05 * g * g
        ref_w = ref_w - 0.01 * g / np.sqrt(ms + 1e-6)
    np.testing.assert_allclose(pw.numpy(), ref_w, rtol=2e-5, atol=2e-6)


def test_interpolate_modes_vs_torch():
    """bilinear/nearest up+downsampling incl. align_corners — the
    half-pixel vs corner-aligned grids are a classic divergence spot."""
    rng = np.random.RandomState(12)
    x = rng.randn(2, 3, 5, 7).astype(np.float32)
    tx = torch.from_numpy(x)
    for size, mode, ac in [((10, 14), "bilinear", False),
                           ((10, 14), "bilinear", True),
                           ((3, 4), "bilinear", False),
                           ((10, 14), "nearest", None)]:
        kw = {} if ac is None else {"align_corners": ac}
        got = F.interpolate(_t(x), size=size, mode=mode, **kw)
        want = torch.nn.functional.interpolate(tx, size=size, mode=mode,
                                               **kw)
        _cmp(got, want, rtol=1e-4, atol=1e-5)


def test_pixel_shuffle_unshuffle_vs_torch():
    rng = np.random.RandomState(13)
    x = rng.randn(2, 8, 3, 3).astype(np.float32)
    _cmp(F.pixel_shuffle(_t(x), 2),
         torch.nn.functional.pixel_shuffle(torch.from_numpy(x), 2))
    y = rng.randn(2, 2, 6, 6).astype(np.float32)
    _cmp(F.pixel_unshuffle(_t(y), 2),
         torch.nn.functional.pixel_unshuffle(torch.from_numpy(y), 2))


def test_grid_sample_vs_torch():
    rng = np.random.RandomState(14)
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    grid = (rng.rand(2, 4, 4, 2).astype(np.float32) * 2 - 1)
    got = F.grid_sample(_t(x), _t(grid), mode="bilinear",
                        padding_mode="zeros", align_corners=True)
    want = torch.nn.functional.grid_sample(
        torch.from_numpy(x), torch.from_numpy(grid), mode="bilinear",
        padding_mode="zeros", align_corners=True)
    _cmp(got, want, rtol=1e-4, atol=1e-5)


def test_interpolate_align_mode_1_asymmetric():
    """paddle's align_mode=1 (src = dst * scale, no half-pixel shift) —
    no torch equivalent; pinned against the hand-rolled formula
    (reference interpolate docs / bilinear_interp kernel)."""
    rng = np.random.RandomState(15)
    x = rng.randn(1, 1, 4, 6).astype(np.float32)
    o_h, o_w = 7, 9
    got = np.asarray(F.interpolate(_t(x), size=(o_h, o_w),
                                   mode="bilinear", align_corners=False,
                                   align_mode=1).numpy())[0, 0]

    def axis_interp(a, o, axis):
        s_in = a.shape[axis]
        idx = np.clip(np.arange(o) * (s_in / o), 0, s_in - 1)
        lo = np.floor(idx).astype(int)
        hi = np.minimum(lo + 1, s_in - 1)
        w = (idx - lo).astype(np.float32)
        sl = [slice(None)] * a.ndim
        sl_lo, sl_hi = list(sl), list(sl)
        sl_lo[axis] = lo
        sl_hi[axis] = hi
        shape = [1] * a.ndim
        shape[axis] = -1
        return a[tuple(sl_lo)] * (1 - w.reshape(shape)) + \
            a[tuple(sl_hi)] * w.reshape(shape)

    want = axis_interp(axis_interp(x[0, 0], o_h, 0), o_w, 1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # differs from the half-pixel (align_mode=0) result
    got0 = np.asarray(F.interpolate(_t(x), size=(o_h, o_w),
                                    mode="bilinear", align_corners=False,
                                    align_mode=0).numpy())[0, 0]
    assert np.abs(got - got0).max() > 1e-3


def test_pad_modes_vs_torch():
    rng = np.random.RandomState(16)
    x = rng.randn(2, 3, 5, 6).astype(np.float32)
    tx = torch.from_numpy(x)
    for mode, tmode in [("reflect", "reflect"), ("replicate", "replicate"),
                        ("circular", "circular")]:
        got = F.pad(_t(x), [1, 2, 2, 1], mode=mode)
        want = torch.nn.functional.pad(tx, (1, 2, 2, 1), mode=tmode)
        _cmp(got, want)
    got = F.pad(_t(x), [1, 1, 1, 1], mode="constant", value=3.5)
    want = torch.nn.functional.pad(tx, (1, 1, 1, 1), value=3.5)
    _cmp(got, want)


def test_dropout_modes_reference_semantics():
    """paddle's two dropout modes: upscale_in_train (default, inverted
    dropout — eval is identity) and downscale_in_infer (train keeps
    values unscaled, eval multiplies by (1-p))."""
    paddle.seed(0)
    x = np.full((512,), 2.0, np.float32)
    t = _t(x)
    # train, upscale: surviving values are x / (1 - p)
    out = F.dropout(t, p=0.25, training=True)
    vals = np.unique(np.round(np.asarray(out.numpy()), 5))
    assert set(vals.tolist()) <= {0.0, np.float32(2.0 / 0.75).round(5)}, vals
    # eval, upscale: identity
    np.testing.assert_array_equal(
        F.dropout(t, p=0.25, training=False).numpy(), x)
    # train, downscale: surviving values stay x
    out = F.dropout(t, p=0.25, training=True, mode="downscale_in_infer")
    vals = np.unique(np.asarray(out.numpy()))
    assert set(np.round(vals, 5).tolist()) <= {0.0, 2.0}, vals
    # eval, downscale: x * (1 - p)
    np.testing.assert_allclose(
        F.dropout(t, p=0.25, training=False,
                  mode="downscale_in_infer").numpy(),
        x * 0.75, rtol=1e-6)


def test_index_ops_vs_references():
    rng = np.random.RandomState(17)
    a = rng.randn(5, 4).astype(np.float32)
    t = _t(a)
    idx = np.array([3, 0, 3], np.int64)
    # index_select == numpy take
    np.testing.assert_array_equal(
        paddle.index_select(t, _t(idx), axis=0).numpy(), a[idx])
    # gather (paddle's axis-0 gather) == take
    np.testing.assert_array_equal(paddle.gather(t, _t(idx)).numpy(),
                                  a[idx])
    # masked_select flattens in row-major order like torch
    mask = a > 0
    np.testing.assert_array_equal(
        paddle.masked_select(t, _t(mask)).numpy(),
        torch.masked_select(torch.from_numpy(a),
                            torch.from_numpy(mask)).numpy())
    # take_along_axis == numpy
    tidx = rng.randint(0, 5, (2, 4))
    np.testing.assert_array_equal(
        paddle.take_along_axis(t, _t(tidx.astype(np.int64)), 0).numpy(),
        np.take_along_axis(a, tidx, 0))


def test_scatter_overwrite_and_add_semantics():
    """paddle.scatter(overwrite=True) keeps the LAST write per duplicate
    index (reference kernel order); overwrite=False accumulates."""
    x = _t(np.zeros((4, 2), np.float32))
    idx = _t(np.array([1, 1, 3], np.int64))
    upd = _t(np.array([[1, 1], [2, 2], [5, 5]], np.float32))
    got = paddle.scatter(x, idx, upd, overwrite=True).numpy()
    np.testing.assert_array_equal(got[1], [2, 2])   # last write wins
    np.testing.assert_array_equal(got[3], [5, 5])
    got2 = paddle.scatter(x, idx, upd, overwrite=False).numpy()
    np.testing.assert_array_equal(got2[1], [3, 3])  # accumulated
    # put_along_axis add-reduce matches torch scatter_add
    base = np.zeros((3, 3), np.float32)
    pidx = np.array([[0, 1, 2], [0, 1, 2]])
    vals = np.ones((2, 3), np.float32)
    got3 = paddle.put_along_axis(_t(base), _t(pidx.astype(np.int64)),
                                 _t(vals), 0, reduce="add").numpy()
    want3 = torch.zeros(3, 3).scatter_add(
        0, torch.from_numpy(pidx), torch.from_numpy(vals)).numpy()
    np.testing.assert_array_equal(got3, want3)


def test_stft_istft_vs_torch():
    """STFT frame/window/center semantics vs torch, and the
    istft(stft(x)) round trip."""
    rng = np.random.RandomState(18)
    x = rng.randn(2, 512).astype(np.float32)
    win = np.hanning(128).astype(np.float32)
    got = paddle.signal.stft(_t(x), n_fft=128, hop_length=64,
                             window=_t(win), center=True).numpy()
    want = torch.stft(torch.from_numpy(x), n_fft=128, hop_length=64,
                      window=torch.from_numpy(win), center=True,
                      return_complex=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    back = paddle.signal.istft(_t(got), n_fft=128, hop_length=64,
                               window=_t(win), center=True).numpy()
    tback = torch.istft(torch.from_numpy(want), n_fft=128, hop_length=64,
                        window=torch.from_numpy(win), center=True).numpy()
    np.testing.assert_allclose(back, tback, rtol=1e-4, atol=1e-4)
    # the round trip reconstructs the interior of the signal
    np.testing.assert_allclose(back[:, 64:-64], x[:, 64:back.shape[1]-64],
                               rtol=1e-3, atol=1e-3)


def test_distributions_vs_torch():
    """log_prob/entropy/kl parity against torch.distributions."""
    import paddle_tpu.distribution as D
    import torch.distributions as TD

    n1 = D.Normal(loc=1.5, scale=2.0)
    t1 = TD.Normal(1.5, 2.0)
    xs = np.linspace(-3, 5, 9, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(n1.log_prob(_t(xs)).numpy()),
        t1.log_prob(torch.from_numpy(xs)).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(np.asarray(n1.entropy().numpy())
                                     .ravel()[0]),
                               float(t1.entropy()), rtol=1e-5)
    n2, t2 = D.Normal(loc=0.0, scale=1.0), TD.Normal(0.0, 1.0)
    np.testing.assert_allclose(
        float(np.asarray(D.kl_divergence(n1, n2).numpy()).ravel()[0]),
        float(TD.kl_divergence(t1, t2)), rtol=1e-5)

    probs = np.array([0.2, 0.5, 0.3], np.float32)
    c = D.Categorical(_t(probs))
    tc = TD.Categorical(probs=torch.from_numpy(probs))
    k = np.array([0, 1, 2])
    np.testing.assert_allclose(
        np.asarray(c.log_prob(_t(k.astype(np.int64))).numpy()),
        tc.log_prob(torch.from_numpy(k)).numpy(), rtol=1e-5, atol=1e-6)

    b = D.Beta(_t(np.float32(2.0)), _t(np.float32(3.0)))
    tb = TD.Beta(2.0, 3.0)
    xb = np.array([0.1, 0.5, 0.9], np.float32)
    np.testing.assert_allclose(
        np.asarray(b.log_prob(_t(xb)).numpy()).ravel(),
        tb.log_prob(torch.from_numpy(xb)).numpy(), rtol=1e-5, atol=1e-5)


def test_ctc_loss_vs_torch():
    """ctc_loss takes UNSCALED logits (reference warpctc applies softmax
    internally — python/paddle/nn/functional/loss.py:1040); torch's takes
    log-probs, so the oracle feeds torch log_softmax(logits)."""
    rng = np.random.RandomState(7)
    T, B, C, S = 12, 3, 6, 5
    logits = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, (B, S)).astype(np.int32)  # blank=0 excluded
    in_len = np.array([12, 10, 8], np.int64)
    lab_len = np.array([5, 3, 2], np.int64)

    t_lp = torch.log_softmax(torch.from_numpy(logits), dim=-1)
    for reduction in ("none", "mean", "sum"):
        ours = F.ctc_loss(
            paddle.to_tensor(logits), paddle.to_tensor(labels),
            paddle.to_tensor(in_len), paddle.to_tensor(lab_len),
            blank=0, reduction=reduction)
        want = torch.nn.functional.ctc_loss(
            t_lp, torch.from_numpy(labels.astype(np.int64)),
            torch.from_numpy(in_len), torch.from_numpy(lab_len),
            blank=0, reduction=reduction, zero_infinity=False)
        np.testing.assert_allclose(
            np.asarray(ours.numpy()).ravel(), want.numpy().ravel(),
            rtol=1e-4, atol=1e-5, err_msg=f"reduction={reduction}")

    # repeated labels exercise the same_as_prev2 transition rule
    labels2 = np.array([[2, 2, 3, 3, 2]], np.int32)
    logits2 = rng.randn(T, 1, C).astype(np.float32)
    ours = F.ctc_loss(
        paddle.to_tensor(logits2), paddle.to_tensor(labels2),
        paddle.to_tensor(np.array([T], np.int64)),
        paddle.to_tensor(np.array([5], np.int64)), reduction="sum")
    want = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.from_numpy(logits2), dim=-1),
        torch.from_numpy(labels2.astype(np.int64)),
        torch.tensor([T]), torch.tensor([5]), blank=0, reduction="sum")
    np.testing.assert_allclose(float(ours), float(want), rtol=1e-4)


def _copy_rnn_weights(p_rnn, t_rnn, num_layers, bidirectional):
    """torch weight_ih_l{k}[_reverse] -> rnns[k].(rnn_fw|rnn_bw).cell."""
    for k in range(num_layers):
        wrappers = ([p_rnn.rnns[k].rnn_fw, p_rnn.rnns[k].rnn_bw]
                    if bidirectional else [p_rnn.rnns[k]])
        for d, wrap in enumerate(wrappers):
            sfx = "_reverse" if d == 1 else ""
            cell = wrap.cell
            cell.weight_ih.set_value(
                getattr(t_rnn, f"weight_ih_l{k}{sfx}").detach().numpy())
            cell.weight_hh.set_value(
                getattr(t_rnn, f"weight_hh_l{k}{sfx}").detach().numpy())
            cell.bias_ih.set_value(
                getattr(t_rnn, f"bias_ih_l{k}{sfx}").detach().numpy())
            cell.bias_hh.set_value(
                getattr(t_rnn, f"bias_hh_l{k}{sfx}").detach().numpy())


def test_lstm_layer_stacked_bidirectional_vs_torch():
    """Full-sequence 2-layer bidirectional LSTM: outputs and both final
    states must match torch, including the [num_layers*num_dirs, B, H]
    final-state packing order."""
    E, H, B, T, L = 6, 10, 3, 7, 2
    rng = np.random.RandomState(11)
    torch.manual_seed(3)
    t_rnn = torch.nn.LSTM(E, H, num_layers=L, bidirectional=True,
                          batch_first=True)
    p_rnn = paddle.nn.LSTM(E, H, num_layers=L, direction="bidirect")
    _copy_rnn_weights(p_rnn, t_rnn, L, True)

    x = rng.randn(B, T, E).astype(np.float32)
    h0 = rng.randn(2 * L, B, H).astype(np.float32)
    c0 = rng.randn(2 * L, B, H).astype(np.float32)
    t_out, (t_h, t_c) = t_rnn(torch.from_numpy(x),
                              (torch.from_numpy(h0), torch.from_numpy(c0)))
    p_out, (p_h, p_c) = p_rnn(_t(x), (_t(h0), _t(c0)))
    _cmp(p_out, t_out, rtol=1e-4, atol=1e-5)
    _cmp(p_h, t_h, rtol=1e-4, atol=1e-5)
    _cmp(p_c, t_c, rtol=1e-4, atol=1e-5)


def test_gru_layer_time_major_vs_torch():
    """GRU with time_major (torch batch_first=False) + default zero state."""
    E, H, B, T = 5, 8, 4, 6
    rng = np.random.RandomState(12)
    torch.manual_seed(4)
    t_rnn = torch.nn.GRU(E, H, num_layers=1, batch_first=False)
    p_rnn = paddle.nn.GRU(E, H, num_layers=1, time_major=True)
    _copy_rnn_weights(p_rnn, t_rnn, 1, False)
    x = rng.randn(T, B, E).astype(np.float32)
    t_out, t_h = t_rnn(torch.from_numpy(x))
    p_out, p_h = p_rnn(_t(x))
    _cmp(p_out, t_out, rtol=1e-4, atol=1e-5)
    _cmp(p_h, t_h, rtol=1e-4, atol=1e-5)


def test_lr_schedulers_vs_torch():
    """Schedule-value parity with torch for the schedulers both frameworks
    define with the same recurrence (Step/MultiStep/Exponential/
    CosineAnnealing). paddle steps the scheduler explicitly; torch steps
    an optimizer-bound one — values are compared per epoch."""
    import paddle_tpu.optimizer.lr as plr

    def torch_lrs(make, epochs):
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.SGD([p], lr=0.5)
        sch = make(opt)
        out = []
        for _ in range(epochs):
            out.append(opt.param_groups[0]["lr"])
            opt.step()
            sch.step()
        return out

    def paddle_lrs(sch, epochs):
        out = []
        for _ in range(epochs):
            out.append(float(sch()))
            sch.step()
        return out

    E = 12
    pairs = [
        (plr.StepDecay(0.5, step_size=3, gamma=0.4),
         lambda o: torch.optim.lr_scheduler.StepLR(o, 3, 0.4)),
        (plr.MultiStepDecay(0.5, milestones=[2, 5, 9], gamma=0.3),
         lambda o: torch.optim.lr_scheduler.MultiStepLR(o, [2, 5, 9], 0.3)),
        (plr.ExponentialDecay(0.5, gamma=0.9),
         lambda o: torch.optim.lr_scheduler.ExponentialLR(o, 0.9)),
        (plr.CosineAnnealingDecay(0.5, T_max=10, eta_min=0.01),
         lambda o: torch.optim.lr_scheduler.CosineAnnealingLR(
             o, 10, eta_min=0.01)),
    ]
    for p_sch, t_make in pairs:
        np.testing.assert_allclose(
            paddle_lrs(p_sch, E), torch_lrs(t_make, E), rtol=1e-6,
            err_msg=type(p_sch).__name__)


def test_ctc_loss_empty_target_and_norm_by_times():
    """lab_len==0 leaves only the all-blank path (torch oracle); the
    norm_by_times grad scaling divides d loss/d logits by T without
    changing the loss value."""
    rng = np.random.RandomState(13)
    T, B, C = 6, 2, 4
    logits = rng.randn(T, B, C).astype(np.float32)
    labels = np.zeros((B, 2), np.int32)
    in_len = np.array([6, 5], np.int64)
    lab_len = np.array([0, 0], np.int64)
    ours = F.ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                      paddle.to_tensor(in_len), paddle.to_tensor(lab_len),
                      reduction="none")
    want = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.from_numpy(logits), dim=-1),
        torch.from_numpy(labels.astype(np.int64)),
        torch.from_numpy(in_len), torch.from_numpy(lab_len),
        blank=0, reduction="none")
    np.testing.assert_allclose(np.asarray(ours.numpy()), want.numpy(),
                               rtol=1e-4, atol=1e-5)

    import jax
    import jax.numpy as jnp
    labels2 = rng.randint(1, C, (B, 3)).astype(np.int32)
    lab_len2 = np.array([3, 2], np.int64)

    def loss_sum(lg, norm):
        return jnp.sum(F.ctc_loss(
            paddle.to_tensor(lg), paddle.to_tensor(labels2),
            paddle.to_tensor(in_len), paddle.to_tensor(lab_len2),
            reduction="none", norm_by_times=norm)._value)

    base = F.ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels2),
                      paddle.to_tensor(in_len), paddle.to_tensor(lab_len2),
                      reduction="none", norm_by_times=False)
    normed = F.ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels2),
                        paddle.to_tensor(in_len), paddle.to_tensor(lab_len2),
                        reduction="none", norm_by_times=True)
    np.testing.assert_allclose(np.asarray(base.numpy()),
                               np.asarray(normed.numpy()), rtol=1e-6)
    g_plain = jax.grad(loss_sum)(jnp.asarray(logits), False)
    g_norm = jax.grad(loss_sum)(jnp.asarray(logits), True)
    np.testing.assert_allclose(np.asarray(g_norm),
                               np.asarray(g_plain) / in_len[None, :, None],
                               rtol=1e-5, atol=1e-7)


def _map_mha(p_mha, t_mha, E):
    w = t_mha.in_proj_weight.detach().numpy()
    b = t_mha.in_proj_bias.detach().numpy()
    for i, name in enumerate(["q_proj", "k_proj", "v_proj"]):
        lin = getattr(p_mha, name)
        lin.weight.set_value(w[i * E:(i + 1) * E].T.copy())
        lin.bias.set_value(b[i * E:(i + 1) * E].copy())
    p_mha.out_proj.weight.set_value(
        t_mha.out_proj.weight.detach().numpy().T.copy())
    p_mha.out_proj.bias.set_value(t_mha.out_proj.bias.detach().numpy())


def _map_linear(p_lin, t_lin):
    p_lin.weight.set_value(t_lin.weight.detach().numpy().T.copy())
    p_lin.bias.set_value(t_lin.bias.detach().numpy())


def _map_norm(p_n, t_n):
    p_n.weight.set_value(t_n.weight.detach().numpy())
    p_n.bias.set_value(t_n.bias.detach().numpy())


def test_transformer_encoder_decoder_vs_torch():
    """Whole nn.Transformer stack vs torch (2+2 layers, post-norm, relu,
    dropout 0): same residual/norm placement, same mask semantics, causal
    target mask through the decoder's self+cross attention."""
    E, H, FF, B, S, T = 16, 4, 32, 2, 7, 5
    torch.manual_seed(5)
    t_tr = torch.nn.Transformer(
        d_model=E, nhead=H, num_encoder_layers=2, num_decoder_layers=2,
        dim_feedforward=FF, dropout=0.0, batch_first=True,
        norm_first=False)
    p_tr = paddle.nn.Transformer(
        d_model=E, nhead=H, num_encoder_layers=2, num_decoder_layers=2,
        dim_feedforward=FF, dropout=0.0, normalize_before=False)

    for p_layer, t_layer in zip(p_tr.encoder.layers, t_tr.encoder.layers):
        _map_mha(p_layer.self_attn, t_layer.self_attn, E)
        _map_linear(p_layer.linear1, t_layer.linear1)
        _map_linear(p_layer.linear2, t_layer.linear2)
        _map_norm(p_layer.norm1, t_layer.norm1)
        _map_norm(p_layer.norm2, t_layer.norm2)
    for p_layer, t_layer in zip(p_tr.decoder.layers, t_tr.decoder.layers):
        _map_mha(p_layer.self_attn, t_layer.self_attn, E)
        _map_mha(p_layer.cross_attn, t_layer.multihead_attn, E)
        _map_linear(p_layer.linear1, t_layer.linear1)
        _map_linear(p_layer.linear2, t_layer.linear2)
        _map_norm(p_layer.norm1, t_layer.norm1)
        _map_norm(p_layer.norm2, t_layer.norm2)
        _map_norm(p_layer.norm3, t_layer.norm3)
    # both stacks apply a final LayerNorm unconditionally (reference
    # paddle nn/layer/transformer.py:1275 matches torch) — map the affine
    # to a non-trivial value so the final norm is actually exercised
    for t_norm, p_norm in ((t_tr.encoder.norm, p_tr.encoder.norm),
                           (t_tr.decoder.norm, p_tr.decoder.norm)):
        with torch.no_grad():
            t_norm.weight.mul_(1.3)
            t_norm.bias.add_(0.1)
        _map_norm(p_norm, t_norm)

    rng = np.random.RandomState(14)
    src = rng.randn(B, S, E).astype(np.float32)
    tgt = rng.randn(B, T, E).astype(np.float32)
    causal = torch.triu(torch.full((T, T), float("-inf")), 1)
    t_tr.eval()
    with torch.no_grad():
        want = t_tr(torch.from_numpy(src), torch.from_numpy(tgt),
                    tgt_mask=causal)
    p_tr.eval()
    mask = np.triu(np.full((T, T), -np.inf, np.float32), 1)
    got = p_tr(_t(src), _t(tgt), tgt_mask=_t(mask[None, None]))
    _cmp(got, want, rtol=1e-4, atol=1e-5)


def test_conv_transpose_vs_torch():
    """conv2d_transpose across stride/padding/output_padding/dilation/
    groups — the classic divergence corners; weight layout [Cin, Cout/g,
    kH, kW] matches torch."""
    rng = np.random.RandomState(15)
    cases = [
        dict(stride=2, padding=1, output_padding=1, dilation=1, groups=1),
        dict(stride=3, padding=2, output_padding=0, dilation=1, groups=1),
        dict(stride=2, padding=0, output_padding=0, dilation=2, groups=1),
        dict(stride=2, padding=1, output_padding=1, dilation=1, groups=2),
    ]
    for kw in cases:
        g = kw["groups"]
        x = rng.randn(2, 4, 9, 9).astype(np.float32)
        w = rng.randn(4, 6 // g, 3, 3).astype(np.float32)
        b = rng.randn(6).astype(np.float32)
        want = torch.nn.functional.conv_transpose2d(
            torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
            **kw)
        got = F.conv2d_transpose(_t(x), _t(w), _t(b), **kw)
        np.testing.assert_allclose(
            np.asarray(got.numpy()), want.numpy(), rtol=1e-3, atol=1e-4,
            err_msg=str(kw))

    # conv1d_transpose sanity at one non-trivial setting
    x1 = rng.randn(2, 3, 11).astype(np.float32)
    w1 = rng.randn(3, 5, 4).astype(np.float32)
    want1 = torch.nn.functional.conv_transpose1d(
        torch.from_numpy(x1), torch.from_numpy(w1), stride=2, padding=1,
        output_padding=1)
    got1 = F.conv1d_transpose(_t(x1), _t(w1), stride=2, padding=1,
                              output_padding=1)
    np.testing.assert_allclose(np.asarray(got1.numpy()), want1.numpy(),
                               rtol=1e-3, atol=1e-4)


def test_unfold_fold_pixelops_vs_torch():
    """unfold/fold patch extraction (kernel/stride/padding/dilation),
    pixel_shuffle/unshuffle, local_response_norm, glu."""
    rng = np.random.RandomState(16)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    tx = torch.from_numpy(x)
    got = F.unfold(_t(x), kernel_sizes=3, strides=2, paddings=1,
                   dilations=1)
    want = torch.nn.functional.unfold(tx, 3, dilation=1, padding=1,
                                      stride=2)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-5, atol=1e-6)

    cols = rng.randn(2, 3 * 3 * 3, 16).astype(np.float32)
    got = F.fold(_t(cols), output_sizes=(8, 8), kernel_sizes=3,
                 strides=2, paddings=1)
    want = torch.nn.functional.fold(torch.from_numpy(cols), (8, 8), 3,
                                    padding=1, stride=2)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-5, atol=1e-6)

    x2 = rng.randn(2, 8, 4, 4).astype(np.float32)
    got = F.pixel_shuffle(_t(x2), 2)
    want = torch.nn.functional.pixel_shuffle(torch.from_numpy(x2), 2)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-6)
    x3 = rng.randn(2, 2, 8, 8).astype(np.float32)
    got = F.pixel_unshuffle(_t(x3), 2)
    want = torch.nn.functional.pixel_unshuffle(torch.from_numpy(x3), 2)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-6)

    # the reference LRN implementation avg-pools x^2 (divides by size),
    # identical to torch at the same alpha — its docstring formula shows
    # a raw sum but the body does not
    x4 = rng.randn(2, 7, 6, 6).astype(np.float32) * 2
    got = F.local_response_norm(_t(x4), size=5, alpha=1e-3, beta=0.75, k=1.0)
    want = torch.nn.functional.local_response_norm(
        torch.from_numpy(x4), 5, alpha=1e-3, beta=0.75, k=1.0)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-4, atol=1e-6)

    x5 = rng.randn(4, 10).astype(np.float32)
    got = F.glu(_t(x5), axis=-1)
    want = torch.nn.functional.glu(torch.from_numpy(x5), -1)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_conv3d_and_normalize_vs_torch():
    rng = np.random.RandomState(17)
    x = rng.randn(2, 3, 5, 6, 6).astype(np.float32)
    w = rng.randn(4, 3, 3, 3, 3).astype(np.float32)
    got = F.conv3d(_t(x), _t(w), stride=(1, 2, 2), padding=1)
    want = torch.nn.functional.conv3d(torch.from_numpy(x),
                                      torch.from_numpy(w),
                                      stride=(1, 2, 2), padding=1)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-3, atol=1e-4)

    v = rng.randn(4, 8).astype(np.float32)
    got = F.normalize(_t(v), p=2, axis=1)
    want = torch.nn.functional.normalize(torch.from_numpy(v), p=2, dim=1)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-5, atol=1e-6)
    a = rng.randn(4, 8).astype(np.float32)
    b = rng.randn(4, 8).astype(np.float32)
    got = F.cosine_similarity(_t(a), _t(b), axis=1)
    want = torch.nn.functional.cosine_similarity(
        torch.from_numpy(a), torch.from_numpy(b), dim=1)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_affine_grid_channel_shuffle_unpool_vs_torch():
    rng = np.random.RandomState(18)
    theta = rng.randn(2, 2, 3).astype(np.float32) * 0.3
    for align in (False, True):
        got = F.affine_grid(_t(theta), [2, 3, 5, 7], align_corners=align)
        want = torch.nn.functional.affine_grid(
            torch.from_numpy(theta), [2, 3, 5, 7], align_corners=align)
        np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy(),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"align={align}")

    x = rng.randn(2, 8, 4, 4).astype(np.float32)
    got = F.channel_shuffle(_t(x), 4)
    want = torch.nn.functional.channel_shuffle(torch.from_numpy(x), 4)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy())

    # max_unpool round-trips pool indices
    xp = rng.randn(2, 3, 8, 8).astype(np.float32)
    t_out, t_idx = torch.nn.functional.max_pool2d(
        torch.from_numpy(xp), 2, stride=2, return_indices=True)
    p_out, p_idx = F.max_pool2d(_t(xp), 2, stride=2, return_mask=True)
    np.testing.assert_allclose(np.asarray(p_out.numpy()), t_out.numpy())
    got = F.max_unpool2d(p_out, p_idx, 2, stride=2)
    want = torch.nn.functional.max_unpool2d(t_out, t_idx, 2, stride=2)
    np.testing.assert_allclose(np.asarray(got.numpy()), want.numpy())


def test_distribution_transforms_vs_torch():
    """Transform forward/inverse/log_det_jacobian + TransformedDistribution
    log_prob vs torch.distributions."""
    import paddle_tpu.distribution as D
    import torch.distributions as TD
    import torch.distributions.transforms as TT

    rng = np.random.RandomState(19)
    x = rng.randn(6).astype(np.float32)
    u = (rng.rand(6).astype(np.float32) * 0.9 + 0.05)

    pairs = [
        (D.ExpTransform(), TT.ExpTransform(), x),
        (D.AffineTransform(_t(np.float32(1.5)), _t(np.float32(0.7))),
         TT.AffineTransform(1.5, 0.7), x),
        (D.SigmoidTransform(), TT.SigmoidTransform(), x),
        (D.TanhTransform(), TT.TanhTransform(), x * 0.5),
        (D.PowerTransform(_t(np.float32(2.0))), TT.PowerTransform(2.0),
         np.abs(x) + 0.1),
    ]
    for ours, theirs, inp in pairs:
        name = type(ours).__name__
        ti = torch.from_numpy(inp)
        fwd = np.asarray(ours.forward(_t(inp)).numpy())
        np.testing.assert_allclose(fwd, theirs(ti).numpy(), rtol=1e-4,
                                   atol=1e-5, err_msg=name)
        inv_in = fwd
        got_inv = np.asarray(ours.inverse(_t(inv_in)).numpy())
        np.testing.assert_allclose(got_inv, theirs.inv(
            torch.from_numpy(inv_in)).numpy(), rtol=1e-4, atol=1e-4,
            err_msg=name)
        got_ldj = np.asarray(
            ours.forward_log_det_jacobian(_t(inp)).numpy())
        want_ldj = theirs.log_abs_det_jacobian(
            ti, theirs(ti)).numpy()
        np.testing.assert_allclose(got_ldj, want_ldj, rtol=1e-4, atol=1e-5,
                                   err_msg=name + " ldj")

    # log-normal via TransformedDistribution(Normal, Exp)
    base = D.Normal(_t(np.float32(0.3)), _t(np.float32(1.2)))
    tbase = TD.Normal(0.3, 1.2)
    ours_td = D.TransformedDistribution(base, [D.ExpTransform()])
    theirs_td = TD.TransformedDistribution(tbase, [TT.ExpTransform()])
    v = np.abs(rng.randn(5)).astype(np.float32) + 0.2
    np.testing.assert_allclose(
        np.asarray(ours_td.log_prob(_t(v)).numpy()),
        theirs_td.log_prob(torch.from_numpy(v)).numpy(),
        rtol=1e-4, atol=1e-5)

    # stick-breaking: forward maps R^k -> simplex (k+1), round-trips
    sb = D.StickBreakingTransform()
    tsb = TT.StickBreakingTransform()
    z = rng.randn(4).astype(np.float32)
    got = np.asarray(sb.forward(_t(z)).numpy())
    want = tsb(torch.from_numpy(z)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    back = np.asarray(sb.inverse(_t(got)).numpy())
    np.testing.assert_allclose(back, z, rtol=1e-3, atol=1e-4)


def test_instance_and_3d_norms_vs_torch():
    rng = np.random.RandomState(21)
    # InstanceNorm 1d/2d/3d, affine
    for dims, shape in [(1, (2, 3, 9)), (2, (2, 3, 5, 6)),
                        (3, (2, 3, 4, 5, 6))]:
        x = rng.randn(*shape).astype(np.float32)
        ours_cls = getattr(paddle.nn, f"InstanceNorm{dims}D")(3)
        theirs_cls = getattr(torch.nn, f"InstanceNorm{dims}d")(
            3, affine=True)
        with torch.no_grad():
            theirs_cls.weight.mul_(1.4).add_(0.1)
            theirs_cls.bias.add_(0.2)
        ours_cls.scale.set_value(theirs_cls.weight.detach().numpy())
        ours_cls.bias.set_value(theirs_cls.bias.detach().numpy())
        got = np.asarray(ours_cls(_t(x)).numpy())
        want = theirs_cls(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                   err_msg=f"InstanceNorm{dims}D")

    # BatchNorm3D train-mode normalization + running stats
    x = rng.randn(2, 3, 4, 5, 6).astype(np.float32)
    p_bn = paddle.nn.BatchNorm3D(3, momentum=0.9)
    t_bn = torch.nn.BatchNorm3d(3, momentum=0.1)  # torch momentum = 1-p
    p_bn.train()
    t_bn.train()
    got = np.asarray(p_bn(_t(x)).numpy())
    want = t_bn(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(p_bn._mean.numpy()),
        t_bn.running_mean.numpy(), rtol=1e-4, atol=1e-5)
