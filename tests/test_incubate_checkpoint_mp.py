"""incubate.checkpoint.auto_checkpoint + incubate.multiprocessing
(round-3 verdict #6).

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:598,
python/paddle/incubate/multiprocessing/reductions.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate.checkpoint import auto_checkpoint as acp


@pytest.fixture(autouse=True)
def _detach():
    yield
    acp.detach()


def _model_opt():
    paddle.seed(0)
    model = nn.Linear(8, 8)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    return model, opt


def _train_one(model, opt, x):
    loss = (model(x) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss)


def test_train_epoch_range_kill_and_resume(tmp_path, monkeypatch):
    """Epochs completed before a kill are skipped on relaunch, with
    model AND optimizer state restored."""
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    import paddle_tpu.core.tensor as _ct

    count0 = _ct._tensor_count  # param names are counter-derived; a real
    # relaunch restarts the counter, so the simulated one must too
    model, opt = _model_opt()
    acp.attach(models=model, optimizers=opt)
    x = paddle.randn([4, 8])

    done = []
    for epoch in acp.train_epoch_range(5):
        _train_one(model, opt, x)
        done.append(epoch)
        if epoch == 2:
            break  # kill DURING epoch 2: its checkpoint never commits
    assert done == [0, 1, 2]

    # "relaunch": fresh objects, same job dir. Epochs 0-1 committed;
    # epoch 2's save never ran (crash-correct: a torn epoch re-runs).
    _ct._tensor_count = count0
    model2, opt2 = _model_opt()
    acp.attach(models=model2, optimizers=opt2)
    r = acp.train_epoch_range(5)
    resumed = []
    for epoch in r:
        _train_one(model2, opt2, x)
        resumed.append(epoch)
    assert resumed == [2, 3, 4]
    assert r.restored_from is not None
    # both paths are now 5 deterministic updates from the same init
    # (killed run's lost epoch-2 step re-ran), so end states match an
    # uninterrupted original trained 2 more epochs
    for _ in range(2):
        _train_one(model, opt, x)
    np.testing.assert_allclose(model2.weight.numpy(), model.weight.numpy(),
                               rtol=1e-6, atol=1e-7)
    for k, v in opt2.state_dict().items():
        if hasattr(v, "numpy"):
            np.testing.assert_allclose(v.numpy(),
                                       opt.state_dict()[k].numpy(),
                                       rtol=1e-6, atol=1e-7)


def test_train_epoch_range_fresh_run(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    model, opt = _model_opt()
    acp.attach(models=model, optimizers=opt)
    r = acp.train_epoch_range(3, name="fresh")
    assert list(r) == [0, 1, 2]
    assert r.restored_from is None
    # completed range: meta records the last epoch
    r2 = acp.train_epoch_range(3, name="fresh")
    assert list(r2) == []  # nothing left to do


def test_checker_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_JOB_ID", "job_42")
    c = acp.AutoCheckpointChecker()
    assert c.valid()
    assert c.job_id == "job_42"
    assert str(tmp_path) in c.get_range_checkpoint_path("r0")


def test_mp_tensor_pickle_round_trip_shm():
    """Tensors cross the ForkingPickler boundary via shared memory."""
    from multiprocessing.reduction import ForkingPickler
    import pickle

    from paddle_tpu.incubate import multiprocessing as pmp  # noqa: F401

    big = paddle.to_tensor(
        np.random.RandomState(0).randn(64, 64).astype(np.float32))
    buf = ForkingPickler.dumps(big)
    out = pickle.loads(buf)
    np.testing.assert_array_equal(out.numpy(), big.numpy())
    # small tensors take the inline path
    small = paddle.to_tensor(np.arange(4, dtype=np.float32))
    out2 = pickle.loads(ForkingPickler.dumps(small))
    np.testing.assert_array_equal(out2.numpy(), small.numpy())


def test_mp_tensor_through_queue():
    """A Tensor crosses a real process boundary through mp.Queue."""
    from _mp_child import child_echo

    from paddle_tpu.incubate import multiprocessing as pmp

    ctx = pmp.get_context("spawn")
    q_in, q_out = ctx.Queue(), ctx.Queue()
    p = ctx.Process(target=child_echo, args=(q_in, q_out))
    p.start()
    try:
        t = paddle.to_tensor(np.full((128, 128), 2.0, np.float32))
        q_in.put(t)
        assert q_out.get(timeout=120) == 2.0 * 128 * 128
    finally:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()


def test_namespace_surface():
    import paddle_tpu.incubate as inc
    import paddle_tpu.incubate.multiprocessing  # opt-in (reference parity)

    assert hasattr(inc.checkpoint, "auto_checkpoint")
    assert hasattr(inc.multiprocessing, "Queue")
    assert hasattr(inc.multiprocessing, "Process")


def test_restore_refuses_unattached(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    model, opt = _model_opt()
    acp.attach(models=model, optimizers=opt)
    x = paddle.randn([4, 8])
    for epoch in acp.train_epoch_range(3, name="guarded"):
        _train_one(model, opt, x)
        if epoch == 1:
            break
    acp.detach()
    with pytest.raises(RuntimeError, match="attach"):
        acp.train_epoch_range(3, name="guarded")


def test_download_multi_root_archive(tmp_path):
    import zipfile

    from paddle_tpu import utils

    zpath = tmp_path / "multi.zip"
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("weights.bin", "w")
        z.writestr("config.json", "{}")
    root = utils.download.get_path_from_url(
        f"file://{zpath}", root_dir=str(tmp_path / "c"))
    # dedicated dir, NOT the shared cache root
    assert os.path.basename(root) == "multi_unpacked"
    assert sorted(os.listdir(root)) == ["config.json", "weights.bin"]
    open(os.path.join(root, "config.json"), "w").write("edited")
    root2 = utils.download.get_path_from_url(
        f"file://{zpath}", root_dir=str(tmp_path / "c"))
    assert root2 == root
    assert open(os.path.join(root, "config.json")).read() == "edited"


def test_incubate_multiprocessing_is_opt_in():
    """Importing paddle_tpu must NOT register the global Tensor
    reduction (reference: incubate/__init__ imports only checkpoint)."""
    import subprocess
    import sys

    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import paddle_tpu\n"
        "from multiprocessing.reduction import ForkingPickler\n"
        "from paddle_tpu.core.tensor import Tensor\n"
        "assert Tensor not in ForkingPickler._extra_reducers, 'eager!'\n"
        "import paddle_tpu.incubate.multiprocessing\n"
        "assert Tensor in ForkingPickler._extra_reducers\n"
        "print('OPT-IN-OK')\n")
    out = subprocess.run([sys.executable, "-c", code], timeout=180,
                         capture_output=True, text=True)
    assert "OPT-IN-OK" in out.stdout, out.stderr[-500:]


def test_mp_bf16_and_parameter_round_trip():
    """bf16 (extension dtype) and Parameter (Tensor subclass) payloads
    survive the shm reduction."""
    import pickle

    from multiprocessing.reduction import ForkingPickler

    import paddle_tpu.incubate.multiprocessing  # noqa: F401
    from paddle_tpu import nn

    bf = paddle.cast(paddle.to_tensor(
        np.random.RandomState(0).randn(64, 64).astype(np.float32)),
        "bfloat16")
    out = pickle.loads(ForkingPickler.dumps(bf))
    assert "bfloat16" in str(out.dtype)
    np.testing.assert_array_equal(
        np.asarray(out.numpy(), np.float32),
        np.asarray(bf.numpy(), np.float32))
    paddle.seed(0)
    w = nn.Linear(64, 64).weight  # Parameter subclass, >4KB
    out2 = pickle.loads(ForkingPickler.dumps(w))
    np.testing.assert_array_equal(out2.numpy(), w.numpy())


def test_restore_refuses_count_mismatch(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    paddle.seed(0)
    m1, m2 = nn.Linear(4, 4), nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(
        learning_rate=0.1,
        parameters=list(m1.parameters()) + list(m2.parameters()))
    acp.attach(models=[m1, m2], optimizers=opt)
    x = paddle.randn([2, 4])
    for epoch in acp.train_epoch_range(3, name="pair"):
        loss = (m2(m1(x)) ** 2).mean()
        loss.backward(); opt.step(); opt.clear_grad()
        if epoch == 1:
            break
    acp.attach(models=[m1], optimizers=opt)  # partial re-attach
    with pytest.raises(RuntimeError, match="attach"):
        acp.train_epoch_range(3, name="pair")
