"""Static graph tests (reference: unittests test_executor_*, program tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


@pytest.fixture(autouse=True)
def _dynamic_after():
    yield
    paddle.disable_static()


def _build_regression():
    main = paddle.static.Program()
    startup = paddle.static.Program()
    with paddle.static.program_guard(main, startup):
        x = paddle.static.data("x", [-1, 4], "float32")
        y = paddle.static.data("y", [-1, 1], "float32")
        lin1 = nn.Linear(4, 8)
        lin2 = nn.Linear(8, 1)
        pred = lin2(nn.functional.relu(lin1(x)))
        loss = nn.functional.mse_loss(pred, y)
    return main, x, y, pred, loss


def test_program_capture_and_infer_run():
    paddle.enable_static()
    main, x, y, pred, loss = _build_regression()
    assert main.num_ops() > 0
    assert len(main.all_parameters()) == 4
    exe = paddle.static.Executor()
    xs = np.random.rand(16, 4).astype(np.float32)
    ys = np.random.rand(16, 1).astype(np.float32)
    pv, lv = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[pred, loss])
    assert pv.shape == (16, 1)
    assert lv.shape == ()


def test_static_training_converges():
    paddle.enable_static()
    paddle.seed(7)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [-1, 4], "float32")
        y = paddle.static.data("y", [-1, 1], "float32")
        pred = nn.Linear(4, 1)(x)
        loss = nn.functional.mse_loss(pred, y)
        paddle.optimizer.Adam(0.05).minimize(loss)
    exe = paddle.static.Executor()
    xs = np.random.rand(64, 4).astype(np.float32)
    w = np.random.rand(4, 1).astype(np.float32)
    ys = xs @ w
    first = None
    for i in range(150):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        first = first if first is not None else lv
    assert lv < first * 0.05
    assert lv < 1e-2


def test_dygraph_static_parity():
    """Same model, same weights: static Executor must match eager forward."""
    paddle.seed(3)
    w = np.random.rand(4, 3).astype(np.float32)
    b = np.random.rand(3).astype(np.float32)
    xs = np.random.rand(8, 4).astype(np.float32)

    lin_dy = nn.Linear(4, 3)
    lin_dy.weight.set_value(w)
    lin_dy.bias.set_value(b)
    eager_out = nn.functional.softmax(lin_dy(paddle.to_tensor(xs))).numpy()

    paddle.enable_static()
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [-1, 4], "float32")
        lin_st = nn.Linear(4, 3)
        lin_st.weight.set_value(w)
        lin_st.bias.set_value(b)
        out = nn.functional.softmax(lin_st(x))
    exe = paddle.static.Executor()
    (static_out,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(eager_out, static_out, rtol=1e-5, atol=1e-6)


def test_static_batch_size_respecialization():
    paddle.enable_static()
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [-1, 2], "float32")
        out = nn.Linear(2, 2)(x)
    exe = paddle.static.Executor()
    for bs in (4, 9, 1):
        (ov,) = exe.run(main, feed={"x": np.zeros((bs, 2), np.float32)},
                        fetch_list=[out])
        assert ov.shape == (bs, 2)


def test_static_nn_fc():
    paddle.enable_static()
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [-1, 6], "float32")
        out = paddle.static.nn.fc(x, 4, activation="relu")
    exe = paddle.static.Executor()
    (ov,) = exe.run(main, feed={"x": np.random.rand(3, 6).astype(np.float32)},
                    fetch_list=[out])
    assert ov.shape == (3, 4)
    assert (ov >= 0).all()


def test_static_save_load(tmp_path):
    paddle.enable_static()
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [-1, 3], "float32")
        out = nn.Linear(3, 2)(x)
    p = main.all_parameters()[0]
    orig = np.asarray(p._value).copy()
    path = str(tmp_path / "st")
    paddle.static.save(main, path)
    p.set_value(np.zeros_like(orig))
    paddle.static.load(main, path)
    np.testing.assert_allclose(np.asarray(p._value), orig)


def test_static_accuracy_is_traced_not_baked():
    """metric.accuracy must be a traced op: the numpy version baked the
    dummy-feed result into the static program (fetched garbage)."""
    import paddle_tpu.fluid as fluid

    paddle.enable_static()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            img = paddle.static.data("img", [16, 8], "float32")
            label = paddle.static.data("label", [16, 1], "int64")
            pred = fluid.layers.fc(img, size=4, activation="softmax")
            acc = fluid.layers.accuracy(input=pred, label=label)
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        x = rng.randn(16, 8).astype("float32")
        y = rng.randint(0, 4, (16, 1)).astype("int64")
        pv, av = exe.run(main, feed={"img": x, "label": y},
                         fetch_list=[pred, acc])
        manual = (np.argmax(np.asarray(pv), -1) == y[:, 0]).mean()
        assert float(np.asarray(av).ravel()[0]) == manual
        y2 = np.argmax(np.asarray(pv), -1)[:, None].astype("int64")
        _, av2 = exe.run(main, feed={"img": x, "label": y2},
                         fetch_list=[pred, acc])
        assert float(np.asarray(av2).ravel()[0]) == 1.0
    finally:
        paddle.disable_static()


def _ref_auc(scores, labels):
    order = np.argsort(-scores)
    y = labels[order]
    tp = np.cumsum(y); fp = np.cumsum(1 - y)
    tpr = np.concatenate([[0], tp / max(tp[-1], 1e-12)])
    fpr = np.concatenate([[0], fp / max(fp[-1], 1e-12)])
    trap = getattr(np, "trapezoid", None) or np.trapz
    return float(trap(tpr, fpr))


def test_static_auc_is_traced_not_baked():
    """static.auc must be a traced op (same bug class as accuracy: the
    numpy version baked the dummy-feed AUC into the program)."""
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            p = paddle.static.data("p", [64, 2], "float32")
            y = paddle.static.data("y", [64, 1], "int64")
            a, _, _ = paddle.static.auc(p, y, num_thresholds=8191)
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        scores = rng.rand(64).astype("float32")
        labels = (scores + rng.randn(64) * 0.3 > 0.5).astype("int64")
        pred = np.stack([1 - scores, scores], -1)
        (av,) = exe.run(main, feed={"p": pred, "y": labels[:, None]},
                        fetch_list=[a])
        ref = _ref_auc(scores, labels.astype(np.float64))
        np.testing.assert_allclose(float(np.asarray(av)), ref, atol=5e-3)
        # different feed MUST change the result (nothing baked)
        labels2 = 1 - labels
        (av2,) = exe.run(main, feed={"p": pred, "y": labels2[:, None]},
                         fetch_list=[a])
        assert abs(float(np.asarray(av2)) - float(np.asarray(av))) > 0.1
    finally:
        paddle.disable_static()
