"""Async input pipeline (io/prefetch.py) — ISSUE 15.

DevicePrefetcher semantics (order/values/exhaustion, error surfacing,
timeout, silent-producer-death degrade), the loss-bit-exact fit parity
the CI smoke gates, the DataLoader satellite fixes (workerless timeout,
worker-timeout fault + staging-ring recycle), and the sharded tier
(2-process per-host loading checksum-equal to single-host; dp-mesh
global assembly)."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader, DevicePrefetcher
from paddle_tpu.io import prefetch as prefetch_mod
from paddle_tpu.runtime import resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_prefetch_shard_child.py")


def _batches(n, shape=(4, 3)):
    rng = np.random.RandomState(0)
    return [(rng.rand(*shape).astype(np.float32), np.int64(i))
            for i in range(n)]


# ---------------------------------------------------------------------------
# DevicePrefetcher semantics

def test_prefetcher_order_values_exhaustion():
    src = _batches(7)
    with DevicePrefetcher(iter(src), depth=2) as pf:
        got = list(pf)
        assert len(got) == 7
        for (x, y), (rx, ry) in zip(got, src):
            np.testing.assert_array_equal(np.asarray(x), rx)
            assert int(np.asarray(y)) == int(ry)
        # exhausted stays exhausted
        with pytest.raises(StopIteration):
            next(pf)
    st = pf.stats()
    assert st["batches"] == 7 and not st["sync"]


def test_prefetcher_commits_leaves_to_device():
    import jax

    with DevicePrefetcher(iter(_batches(2)), depth=1) as pf:
        x, y = next(pf)
        assert isinstance(x, jax.Array) and isinstance(y, jax.Array)


def test_prefetcher_surfaces_source_exception():
    def src():
        yield _batches(1)[0]
        raise ValueError("boom in the dataset")

    pf = DevicePrefetcher(src(), depth=2)
    next(pf)
    with pytest.raises(ValueError, match="boom in the dataset"):
        next(pf)
    pf.close()


def test_prefetcher_timeout_raises_with_fault_event():
    def slow():
        time.sleep(30)
        yield None  # pragma: no cover

    before = resilience.fault_events()["data_worker_timeout"]
    pf = DevicePrefetcher(slow(), depth=1, timeout=0.2)
    with pytest.raises(TimeoutError):
        next(pf)
    assert resilience.fault_events()["data_worker_timeout"] == before + 1
    pf._stop.set()  # don't pay the slow generator on close
    pf.close()


def test_prefetcher_producer_death_degrades_to_sync():
    """A producer killed without a word (FaultInjector raising OUTSIDE
    the error capture) must leave a postmortem-visible fault event and
    a COMPLETED iteration via the synchronous path — never a wedged
    consumer."""
    src = _batches(5)
    before = resilience.fault_events()["data_producer_died"]
    with resilience.FaultInjector({"prefetch.producer": ("raise", 0)}):
        with DevicePrefetcher(iter(src), depth=2) as pf:
            got = list(pf)
    assert len(got) == 5  # died before staging anything: nothing lost
    assert resilience.fault_events()["data_producer_died"] == before + 1
    assert pf.stats()["sync"]
    assert any(k == "data_producer_died"
               for _, k, _ in resilience.fault_log(50))


def test_prefetcher_close_mid_iteration_unblocks_producer():
    src = _batches(50)
    pf = DevicePrefetcher(iter(src), depth=2)
    next(pf)
    pf.close()  # producer likely blocked on the full queue
    t = pf._thread
    if t is not None:
        t.join(timeout=5.0)
        assert not t.is_alive()


def test_fit_loss_bit_exact_and_data_wait_measured():
    def run(prefetch):
        paddle.seed(0)
        rng = np.random.RandomState(0)
        x = rng.rand(64, 4).astype(np.float32)
        y = (x @ rng.rand(4, 1).astype(np.float32)).astype(np.float32)
        net = nn.Linear(4, 1)
        m = paddle.Model(net)
        m.prepare(paddle.optimizer.Adam(0.05, parameters=net.parameters()),
                  nn.MSELoss())
        losses = []

        class _Rec(paddle.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                losses.append(logs["loss"])

        m.fit([x, y], epochs=2, batch_size=16, verbose=0, shuffle=False,
              callbacks=[_Rec()], prefetch=prefetch)
        return losses

    sync = run(False)
    pre = run(True)
    assert len(sync) == 8
    assert sync == pre  # bit-exact: same floats, not approx


def test_evaluate_prefetch_parity():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = rng.rand(32, 4).astype(np.float32)
    y = (x @ rng.rand(4, 1).astype(np.float32)).astype(np.float32)
    net = nn.Linear(4, 1)
    m = paddle.Model(net)
    m.prepare(loss=nn.MSELoss())
    a = m.evaluate([x, y], batch_size=8, verbose=0, prefetch=False)
    b = m.evaluate([x, y], batch_size=8, verbose=0, prefetch=True)
    assert a["loss"] == b["loss"]


def test_prefetch_stats_shape():
    st = prefetch_mod.prefetch_stats()
    for key in ("prefetchers", "depth", "batches", "stalls", "stall_s",
                "src_s", "h2d_s", "overlap_ratio", "producer_deaths"):
        assert key in st


def test_staging_direct_is_opt_in_and_probe_vetoed():
    # default: OFF everywhere — the np.array release barrier is the
    # only one that holds universally; =1 is a per-backend operator
    # assertion that block_until_ready truly barriers there
    assert prefetch_mod.staging_direct_ok() is False
    prev = prefetch_mod._direct[0]
    try:
        prefetch_mod._direct[0] = None
        os.environ["PADDLE_TPU_STAGING_DIRECT"] = "1"
        # even an explicit opt-in is VETOED here: the XLA CPU client
        # zero-copy ALIASES 64-byte-aligned host memory, so the direct
        # path would recycle the ring slot under live device data
        assert prefetch_mod._device_put_aliases_host() is True
        assert prefetch_mod.staging_direct_ok() is False
    finally:
        del os.environ["PADDLE_TPU_STAGING_DIRECT"]
        prefetch_mod._direct[0] = prev


def test_abandoned_prefetcher_thread_exits():
    """No close(), consumer just drops the iterator: the producer holds
    only a weak ref between batches, so GC collects the prefetcher and
    the thread exits instead of busy-polling the full queue forever."""
    import gc

    pf = DevicePrefetcher(iter(_batches(50)), depth=1)
    next(pf)
    t = pf._thread
    del pf
    gc.collect()
    deadline = time.time() + 5.0
    while t.is_alive() and time.time() < deadline:
        time.sleep(0.05)
    assert not t.is_alive(), "abandoned prefetcher leaked its producer"


# ---------------------------------------------------------------------------
# DataLoader satellites

class _SlowDataset(paddle.io.Dataset):
    def __init__(self, n=8, sleep_s=0.0):
        self.n = n
        self.sleep_s = sleep_s

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if self.sleep_s:
            time.sleep(self.sleep_s)
        return np.full((3,), float(i), np.float32), np.int64(i)


def test_iter_single_honors_timeout():
    before = resilience.fault_events()["data_worker_timeout"]
    loader = DataLoader(_SlowDataset(n=4, sleep_s=0.06), batch_size=2,
                        timeout=0.05)
    with pytest.raises(TimeoutError):
        list(loader)
    assert resilience.fault_events()["data_worker_timeout"] == before + 1
    # without a timeout the same loader drains fine
    loader2 = DataLoader(_SlowDataset(n=4, sleep_s=0.01), batch_size=2)
    assert len(list(loader2)) == 2


def test_iterable_and_no_autobatch_paths_honor_timeout():
    class SlowIt(paddle.io.IterableDataset):
        def __iter__(self):
            for i in range(6):
                time.sleep(0.04)
                yield np.float32(i)

    with pytest.raises(TimeoutError):
        list(DataLoader(SlowIt(), batch_size=2, timeout=0.05))
    with pytest.raises(TimeoutError):
        list(DataLoader(_SlowDataset(n=4, sleep_s=0.08), batch_size=None,
                        timeout=0.05))


def test_worker_timeout_fault_event_and_ring_recycled():
    """FaultInjector-delayed workers past `timeout=` must raise cleanly
    with the data_worker_timeout fault event, and every staging-ring
    slot must come back (no ring leak) so the loader survives
    re-iteration."""
    before = resilience.fault_events()["data_worker_timeout"]
    loader = DataLoader(_SlowDataset(n=16), batch_size=2, num_workers=2,
                        use_staging_pool=True, timeout=0.2)
    with resilience.FaultInjector({"data.worker_fetch": ("delay", 1.0)}):
        with pytest.raises(TimeoutError):
            list(loader)
    assert resilience.fault_events()["data_worker_timeout"] == before + 1
    # workers drain within their injected delay; then the ring must be
    # whole again: every slot acquirable (and released back)
    pool = loader._pool
    if pool is not None:
        deadline = time.time() + 5.0
        acquired = []
        while len(acquired) < pool.n_slots and time.time() < deadline:
            slot = pool.acquire_write(timeout_ms=100)
            if slot >= 0:
                acquired.append(slot)
        assert len(acquired) == pool.n_slots, \
            f"ring leaked: only {len(acquired)}/{pool.n_slots} came back"
        for s in acquired:
            pool.release(s)
    # and a clean pass over the same loader still works
    assert len(list(loader)) == 8


def test_worker_backpressure_no_busy_poll_completes():
    # regression guard for the plain cond.wait(): slow CONSUMER, fast
    # workers — backpressured workers must wake on the consumer's
    # notify and finish the epoch
    loader = DataLoader(_SlowDataset(n=24), batch_size=2, num_workers=3)
    seen = 0
    for _x, _y in loader:
        time.sleep(0.01)
        seen += 1
    assert seen == 12


# ---------------------------------------------------------------------------
# sharded tier

def _run_child(mode, extra_env):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PADDLE_TPU_DATA_PREFETCH": "1"})
    env.update(extra_env)
    p = subprocess.run([sys.executable, CHILD, mode], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=240)
    assert p.returncode == 0, p.stderr[-2000:]
    return json.loads(p.stdout.strip().splitlines()[-1])


def test_two_process_sharded_loading_matches_single_host():
    """ISSUE-15 acceptance: 2 processes, each loading ONLY its
    DistributedBatchSampler shard through a prefetcher, must together
    reproduce the single-host global batch stream — same order, same
    values, proven per-step by row digests."""
    from tests._prefetch_shard_child import (
        LOCAL_BATCH, N, _Det, row_digest,
    )
    from paddle_tpu.io.sampler import DistributedBatchSampler

    ranks = [_run_child("shard", {"PF_RANK": str(r), "PF_NRANKS": "2"})
             for r in range(2)]
    assert ranks[0]["rank"] == 0 and ranks[1]["rank"] == 1
    n_steps = len(ranks[0]["batches"])
    assert n_steps == len(ranks[1]["batches"]) == N // (2 * LOCAL_BATCH)

    # single-host reference: the SAME epoch-seeded shuffle, global batch
    sampler = DistributedBatchSampler(_Det(), batch_size=2 * LOCAL_BATCH,
                                      num_replicas=1, rank=0, shuffle=True)
    sampler.set_epoch(1)
    ds = _Det()
    for b, indices in enumerate(sampler):
        expect = []
        for idx in indices:
            x, y = ds[idx]
            expect.append(row_digest(x, y))
        # global row k came from rank k%2, local position k//2 — the
        # stride-sharded index space interleaves exactly this way
        got = [ranks[k % 2]["batches"][b][k // 2]
               for k in range(len(indices))]
        assert got == expect, f"global batch {b} diverged"


def test_mesh_sharded_global_assembly():
    """sharding='dp' commits batches as NamedSharding global arrays
    (2 forced CPU devices), value-identical to host batches."""
    out = _run_child("mesh", {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
    assert out["ok"] and out["sharded_leaves"] == 8
