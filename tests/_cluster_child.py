"""Child process for the 3-rank cluster resilience suite
(test_cluster_resilience.py).

One child = one cluster rank. Identity and store come from the
PADDLE_TPU_CLUSTER_* env vars the parent sets; checkpoints go to a
per-rank directory under a shared root (each rank's orbax manager owns
its own tree — the coordination layer, not orbax, is what keeps the
ranks agreeing).

Phases (argv[1]):

* ``train``  — tick cluster heartbeats under a quorum watchdog while
  saving + publishing checkpoints each step. The parent SIGKILLs rank 1
  mid-async-save via PADDLE_TPU_FAULT_INJECT (a big incompressible
  state keeps the background write in flight at the kill point, same
  trick as _resilience_child.py). Survivors keep ticking long enough to
  observe the dead peer, then print a JSON result line: their watchdog
  must have recorded `peer_stale`/`peer_dead` but must NOT have
  quorum-stalled for a single dead rank.
* ``restore`` — crash-restart: republish this rank's complete steps,
  agree on the cluster-wide restore step (leader computes + rendezvous,
  followers wait-and-read), restore it, and print the step + restored
  payload for the parent's divergence check.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.distributed import coordination  # noqa: E402
from paddle_tpu.distributed.elastic import ElasticManager  # noqa: E402
from paddle_tpu.io.checkpoint import (  # noqa: E402
    CheckpointManager, latest_common_complete_step,
)
from paddle_tpu.runtime.resilience import fault_events  # noqa: E402
from paddle_tpu.runtime import telemetry as _telemetry  # noqa: E402

PHASE = sys.argv[1]
CKPT_ROOT = sys.argv[2]
STEPS = int(sys.argv[3]) if len(sys.argv) > 3 else 4

ctx = coordination.cluster_context()
assert ctx is not None, "cluster env not set"
coordination.init_cluster_telemetry(ctx)
rank_dir = os.path.join(CKPT_ROOT, f"rank_{ctx.rank}")


def _state(step, big=False):
    # random f32 is incompressible: the async OCDBT write of ~64MB is
    # still in flight when the injector kills us at the post-queue site
    n = 4096 if big else 8
    rng = np.random.RandomState(step)
    return {"w": jnp.asarray(rng.randn(n, n).astype(np.float32)),
            "step": jnp.int32(step)}


def train():
    # stale threshold well above one step's worst-case save+publish wall
    # time (orbax on CPU can take seconds on a cold manager, more under
    # full-suite load): a healthy peer mid-save must not read as stale,
    # or TWO busy peers would quorum-stall the job the test proves
    # stays up
    em = ElasticManager(rank_dir, timeout=600.0, cluster=ctx,
                        peer_stale_after=8.0, peer_dead_after=14.0)
    em.start_watchdog(poll=0.25)
    mngr = CheckpointManager(rank_dir, max_to_keep=None, async_save=True)
    kill_step = int(os.environ.get("CLUSTER_CHILD_KILL_STEP", "-1"))
    for step in range(STEPS):
        big = step == kill_step
        mngr.save(step, _state(step, big=big), force=True)
        # (unreachable past this point at the kill step: the injector
        # SIGKILLs inside save() at checkpoint.async_started)
        mngr.wait()
        mngr.publish_complete(ctx.store, ctx.rank)
        _telemetry.publish_registry(ctx.store, ctx.rank)
        em.tick(step)
        time.sleep(0.3)
    # keep heartbeating past the dead peer's hard deadline so this
    # rank's monitor observes stale -> dead; a SINGLE dead rank must
    # degrade (peer events), never quorum-stall the survivors
    for extra in range(120):
        em.tick(STEPS + extra)
        time.sleep(0.3)
        if em.peers_down():
            break
    time.sleep(0.5)  # one more poll so peer_dead definitely recorded
    em.tick(STEPS + 121)
    em.stop()
    mngr.close()
    fe = fault_events()
    print("RESULT " + json.dumps({
        "rank": ctx.rank, "stalled": em.stalled,
        "stall_reason": em.stall_reason, "peers_down": em.peers_down(),
        "peer_stale": fe["peer_stale"], "peer_dead": fe["peer_dead"],
    }), flush=True)


def restore():
    mngr = CheckpointManager(rank_dir, max_to_keep=None, async_save=False)
    published_at = time.time()
    mngr.publish_complete(ctx.store, ctx.rank)
    if ctx.is_leader:
        # freshness-gated wait: the dead rank never republishes, so the
        # leader waits out the timeout (rendezvous_timeouts fault event)
        # and then intersects ALL publications — including the dead
        # rank's stale, conservative one
        step = latest_common_complete_step(
            ctx.store, expected_ranks=ctx.world_size, timeout=3.0,
            min_wall=published_at - 5.0)
        coordination.rendezvous(ctx.store, "restore_step", {"step": step},
                                leader=True)
    else:
        payload = coordination.rendezvous(
            ctx.store, "restore_step", timeout=15.0,
            min_wall=published_at - 5.0)
        step = (payload or {}).get("step")
        if step is None:  # degraded path: local intersection
            step = latest_common_complete_step(ctx.store, timeout=0.0,
                                               world_size=ctx.world_size)
    assert step is not None, "no common step to restore"
    restored = mngr.restore(step)
    mngr.close()
    print("RESULT " + json.dumps({
        "rank": ctx.rank, "step": int(step),
        "restored_step": int(np.asarray(restored["step"])),
        "w00": float(np.asarray(restored["w"])[0, 0]),
    }), flush=True)


if PHASE == "train":
    train()
elif PHASE == "restore":
    restore()
else:  # pragma: no cover
    raise SystemExit(f"unknown phase {PHASE}")
