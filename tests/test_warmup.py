"""Warm-start subsystem (runtime/warmup.py + dispatch/hapi/optimizer
wiring).

Covers the ISSUE acceptance: a second process with
``PADDLE_TPU_COMPILE_CACHE_DIR`` + manifest precompile performs ZERO
fresh XLA compiles for the recorded signatures (subprocess round trip);
a stale manifest (version / jax / framework mismatch) falls back to a
cold start with a ``stale_manifests`` fault event; a corrupt compile
cache entry is tolerated (fresh compile + ``compile_cache_errors``
event, correct numerics); and the compile observability surface —
``dispatch_stats()["compile"]`` keys, per-op compile seconds,
time-to-first-step, profiler.summary output.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.core import dispatch
from paddle_tpu.runtime import resilience, warmup


@pytest.fixture(autouse=True)
def _fresh():
    prev_warm = dispatch.set_warmup_count(1)
    dispatch.reset_dispatch_stats(clear_caches=True)
    warmup.reset_manifest_records()
    resilience.reset_fault_events()
    yield
    dispatch.set_warmup_count(prev_warm)
    dispatch.reset_dispatch_stats(clear_caches=True)
    warmup.reset_manifest_records()
    resilience.reset_fault_events()


def _t(arr, stop_gradient=True):
    return paddle.to_tensor(np.asarray(arr), stop_gradient=stop_gradient)


# ---- manifest record / serialize / precompile (in-process) ---------------

def test_compiled_ops_are_recorded_and_replayable():
    x = _t(np.ones((4, 8), np.float32))
    w = _t(np.ones((8, 4), np.float32))
    paddle.add(x, x)
    paddle.matmul(x, w, transpose_y=False)   # closure-captured statics
    paddle.sum(x, axis=1)                    # kwargs treedef + int static
    m = warmup.manifest()
    ops = [e for e in m["entries"] if e["kind"] == "op"]
    assert len(ops) >= 3
    assert all(e["replayable"] for e in ops), [
        (e["name"], e["impl"]) for e in ops if not e["replayable"]]
    assert m["version"] == warmup.MANIFEST_VERSION
    assert m["jax"] and m["paddle_tpu"]


def test_precompile_installs_warm_entries_zero_misses():
    """After a full cache reset, precompiling the recorded manifest must
    serve every recorded signature as a first-call hit — no misses, no
    retrace (the entries are AOT executables)."""
    x = _t(np.ones((4, 8), np.float32))
    w = _t(np.ones((8, 4), np.float32))

    def run():
        return [np.asarray(paddle.add(x, x)._value),
                np.asarray(paddle.matmul(x, w)._value),
                np.asarray(paddle.sum(x, axis=1)._value),
                np.asarray(F.softmax(x, axis=-1)._value)]

    cold = run()
    m = warmup.manifest()
    dispatch.reset_dispatch_stats(clear_caches=True)

    stats = warmup.precompile(m)
    assert stats["ops_precompiled"] >= 4 and not stats["stale"]
    warm = run()
    fwd = dispatch.dispatch_stats()["forward"]
    assert fwd["misses"] == 0, fwd
    assert fwd["hits"] >= 4
    for a, b in zip(cold, warm):
        np.testing.assert_allclose(a, b)


def test_precompile_skips_nonjittable_and_counts_skipped():
    m = {"version": warmup.MANIFEST_VERSION,
         **{k: v for k, v in warmup.manifest().items()
            if k in ("jax", "paddle_tpu")},
         "entries": [{"kind": "op", "name": "ghost", "replayable": False,
                      "impl": None, "tree": None, "leaves": None}]}
    stats = warmup.precompile(m)
    assert stats == {"ops_precompiled": 0, "ops_skipped": 1,
                     "programs_pending": 0, "traces_precompiled": 0,
                     "stale": False, "ops_unreplayable": 1}


def test_stale_manifest_falls_back_cold_with_fault_event(tmp_path):
    """Version / jax-version mismatch must degrade to a cold start and
    record a stale_manifests fault event — never raise."""
    p = tmp_path / "manifest.json"
    doc = warmup.manifest()
    doc["jax"] = "0.0.0-not-this-jax"
    p.write_text(json.dumps(doc))
    assert warmup.load_manifest(str(p)) is None
    stats = warmup.precompile(str(p))
    assert stats["stale"] and stats["ops_precompiled"] == 0
    doc2 = warmup.manifest()
    doc2["version"] = 999
    p.write_text(json.dumps(doc2))
    assert warmup.load_manifest(str(p)) is None
    # unreadable file: same contract
    p.write_text("{ not json")
    assert warmup.load_manifest(str(p)) is None
    assert resilience.fault_events()["stale_manifests"] >= 3


def test_unresolvable_op_entry_is_stale_not_fatal():
    x = _t(np.ones((4,), np.float32))
    paddle.exp(x)
    m = warmup.manifest()
    ops = [e for e in m["entries"] if e["kind"] == "op"]
    assert ops
    bad = json.loads(json.dumps(m))
    for e in bad["entries"]:
        if e.get("impl") and e["impl"].get("code"):
            e["impl"]["code"]["line"] = 999999  # source drifted
        elif e.get("impl"):
            e["impl"] = {"module": "paddle_tpu", "attr": "no_such_attr"}
    stats = warmup.precompile(bad)
    assert stats["ops_precompiled"] == 0
    assert resilience.fault_events()["stale_manifests"] >= 1


# ---- corrupt compile-cache entry tolerated --------------------------------

def test_corrupt_cache_entry_tolerated(tmp_path):
    """A corrupted on-disk cache file must degrade to a fresh compile
    with a compile_cache_errors fault event and correct numerics."""
    import jax

    cfg = warmup.configure_compile_cache(cache_dir=str(tmp_path / "cache"),
                                         min_compile_secs=0.0)
    assert cfg and cfg["cache_dir"] == str(tmp_path / "cache")
    try:
        x = _t(np.linspace(-1, 1, 32).astype(np.float32))
        ref = np.asarray(paddle.tanh(x)._value)
        cache_files = [f for f in os.listdir(cfg["cache_dir"])
                       if f.endswith("-cache")]
        assert cache_files, "no cache entries written"
        for f in cache_files:
            resilience.corrupt_file(os.path.join(cfg["cache_dir"], f))
        # drop in-memory executables so the next call re-reads the disk
        dispatch.reset_dispatch_stats(clear_caches=True)
        jax.clear_caches()
        out = np.asarray(paddle.tanh(x)._value)
        np.testing.assert_allclose(out, ref, rtol=1e-6)
        assert resilience.fault_events()["compile_cache_errors"] >= 1
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


# ---- whole-step programs --------------------------------------------------

def _tiny_model():
    paddle.seed(0)
    m = paddle.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                   nn.Linear(16, 4)))
    m.prepare(paddle.optimizer.Adam(parameters=m.parameters()),
              nn.CrossEntropyLoss())
    return m


def test_hapi_warm_start_from_recorded_manifest():
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16, 1)).astype(np.int64)
    m1 = _tiny_model()
    loss1 = m1.train_batch([x], [y])
    m1.eval_batch([x], [y])
    doc = warmup.manifest()
    names = {e["name"] for e in doc["entries"] if e["kind"] == "program"}
    assert {"hapi.train_step", "hapi.eval_step"} <= names

    m2 = _tiny_model()
    stats = m2.warm_start(doc)
    assert stats["train"] == 1 and stats["eval"] == 1
    loss2 = m2.train_batch([x], [y])
    np.testing.assert_allclose(loss1, loss2, rtol=1e-6)


def test_hapi_warm_start_stale_model_degrades():
    """A manifest recorded for a different architecture must degrade to
    a stale_manifests fault event, not an exception."""
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16, 1)).astype(np.int64)
    m1 = _tiny_model()
    m1.train_batch([x], [y])
    doc = warmup.manifest()

    paddle.seed(0)
    other = paddle.Model(nn.Sequential(nn.Linear(3, 5), nn.Linear(5, 2)))
    other.prepare(paddle.optimizer.Adam(parameters=other.parameters()),
                  nn.CrossEntropyLoss())
    stats = other.warm_start(doc)
    assert stats["train"] == 0
    assert resilience.fault_events()["stale_manifests"] >= 1


def test_optimizer_warm_start_self_derived():
    """warm_start with no manifest AOT-compiles the fused step from the
    live params; the first real step then reuses the built entry."""
    rng = np.random.RandomState(0)
    w = _t(rng.randn(8, 4).astype(np.float32), stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    assert opt.warm_start() >= 1
    assert len(opt._step_fn_cache) == 1
    loss = (w * w).sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert len(opt._step_fn_cache) == 1  # same entry, no rebuild


# ---- observability --------------------------------------------------------

def test_dispatch_stats_compile_section():
    x = _t(np.ones((4, 8), np.float32))
    paddle.add(x, x)
    comp = dispatch.dispatch_stats()["compile"]
    for k in ("fresh_compiles", "disk_cache_hits", "backend_compile_s",
              "compile_time_saved_s", "per_op_compile_s",
              "program_compile_s", "total_op_compile_s",
              "time_to_first_step_s", "manifest_records",
              "precompiled_ops", "precompiled_programs"):
        assert k in comp, k
    assert comp["per_op_compile_s"].get("add", 0) > 0
    assert comp["total_op_compile_s"] > 0
    assert "eager_op" in comp["time_to_first_step_s"]
    per_op = dispatch.dispatch_stats()["per_op"]["add"]
    assert per_op["compile_s"] > 0


def test_first_step_latch_and_reset():
    warmup.reset_first_step()
    assert warmup.time_to_first_step() == {}
    x = _t(np.ones((4,), np.float32))
    paddle.exp(x)
    t1 = warmup.time_to_first_step()["eager_op"]
    paddle.exp(x)
    assert warmup.time_to_first_step()["eager_op"] == t1  # latched


def test_profiler_summary_prints_compile_line(capsys):
    x = _t(np.ones((4, 8), np.float32))
    paddle.add(x, x)
    import paddle_tpu.profiler as prof

    prof.Profiler().summary()
    out = capsys.readouterr().out
    assert "compile:" in out and "fresh" in out
    assert "time-to-first-step" in out


def test_precompiled_entries_survive_into_next_manifest():
    """A warm process must carry the entries it precompiled forward into
    its own manifest — otherwise the exit-time save would shrink the
    manifest to only fresh compiles and warm-start would decay to cold
    within two generations."""
    x = _t(np.ones((4, 8), np.float32))
    paddle.add(x, x)
    paddle.tanh(x)
    doc_a = warmup.manifest()
    n_a = len(doc_a["entries"])
    assert n_a >= 2

    # simulate the next process: cold caches, empty recorder
    dispatch.reset_dispatch_stats(clear_caches=True)
    warmup.reset_manifest_records()
    assert len(warmup.manifest()["entries"]) == 0
    stats = warmup.precompile(doc_a)
    assert stats["ops_precompiled"] == n_a
    # the warm process re-runs the ops (all hits: no record_op fires)
    paddle.add(x, x)
    paddle.tanh(x)
    doc_b = warmup.manifest()
    assert len(doc_b["entries"]) == n_a  # nothing lost


def test_save_load_manifest_roundtrip(tmp_path):
    x = _t(np.ones((4,), np.float32))
    paddle.tanh(x)
    p = str(tmp_path / "m.json")
    assert warmup.save_manifest(p) == p
    doc = warmup.load_manifest(p)
    assert doc is not None
    assert any(e["kind"] == "op" for e in doc["entries"])


# ---- the acceptance round trip (two fresh processes) ----------------------

def test_warm_start_round_trip_zero_fresh_compiles(tmp_path):
    """ISSUE acceptance: process A records (shape manifest + persistent
    cache); process B precompiles the manifest and performs ZERO fresh
    XLA compiles for the whole workload, serving every recorded per-op
    signature without a single dispatch miss."""
    child = os.path.join(os.path.dirname(__file__), "_warmup_child.py")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PADDLE_TPU_COMPILE_CACHE_DIR": str(tmp_path / "cache"),
        "PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S": "0",
        "WARMUP_MANIFEST": str(tmp_path / "manifest.json"),
    })

    def run(mode):
        proc = subprocess.run([sys.executable, child, mode], env=env,
                              capture_output=True, text=True, timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    a = run("record")
    assert a["fresh_compiles"] > 0          # cold: XLA actually paid
    assert a["manifest_records"] > 0
    assert os.path.exists(env["WARMUP_MANIFEST"])

    b = run("replay")
    assert b["precompile"]["ops_precompiled"] > 0
    assert b["outs"] == a["outs"]           # numerically identical
    assert b["disk_cache_hits"] > 0         # served from the disk cache
    assert b["fresh_compiles"] == 0, b      # THE acceptance criterion
    assert b["forward_misses"] == 0, b      # every eager op pre-warmed
    assert b["time_to_first_step"]["eager_op"] <= \
        a["time_to_first_step"]["eager_op"] * 5  # sanity, not a perf gate
