"""Child process for the serving-engine acceptance round trip
(tests/test_serving.py / tools/serve_smoke.py).

Modes (argv[1]):
  record — cold server: N concurrent requests through
           admit -> prefill -> decode -> finish under continuous
           batching, then the SAME prompts sequentially (one-request
           engines) for the token-exactness check; saves the shape
           manifest; prints one JSON line of tokens + compile metrics +
           the histogram<->span reconciliation.
  replay — warm server: precompiles the manifest, runs the same
           concurrent workload, prints metrics. The parent asserts
           ZERO fresh XLA compiles (a server restart that recompiles
           is an outage).

Env (set by the parent): JAX_PLATFORMS=cpu,
PADDLE_TPU_COMPILE_CACHE_DIR, PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S=0,
SERVE_MANIFEST, SERVE_TRACE_DIR (optional: enables span tracing +
reconciliation fields), PADDLE_TPU_EAGER_FUSION (optional).
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from paddle_tpu.core import dispatch  # noqa: E402
from paddle_tpu.inference import (  # noqa: E402
    ServeConfig,
    ServingEngine,
    TinyServeModel,
)
from paddle_tpu.runtime import telemetry, tracing, warmup  # noqa: E402

mode = sys.argv[1]
manifest_path = os.environ["SERVE_MANIFEST"]
trace_dir = os.environ.get("SERVE_TRACE_DIR")
if trace_dir:
    tracing.configure(trace_dir)

PROMPTS = [[1, 2, 3, 4, 5], [7, 8], [3, 1, 4, 1, 5, 9], [11, 13]]
NEW_TOKENS = 4


def _mk_engine():
    model = TinyServeModel(vocab=32, dim=8, layers=2, heads=2, ffn=16,
                           seed=0)
    cfg = ServeConfig(max_running=3, token_budget=8, block_size=4,
                      num_blocks=16, max_blocks_per_seq=4)
    return ServingEngine(model, cfg)


pre = None
if mode == "replay":
    pre = warmup.precompile(manifest_path)

dispatch.set_warmup_count(1)
engine = _mk_engine()
batched = engine.generate(PROMPTS, max_new_tokens=NEW_TOKENS)

sequential = None
if mode == "record":
    sequential = []
    for p in PROMPTS:
        e = _mk_engine()
        sequential.append(e.generate([p], max_new_tokens=NEW_TOKENS)[0])
    warmup.save_manifest(manifest_path)

ds = dispatch.dispatch_stats()
comp = ds["compile"]
out = {
    "batched": batched,
    "sequential": sequential,
    "steps": engine.steps,
    "fresh_compiles": comp["fresh_compiles"],
    "disk_cache_hits": comp["disk_cache_hits"],
    "fused_misses": ds["fusion"]["fused"]["misses"],
    "recorded_ops": ds["fusion"]["recorded_ops"],
}
if pre is not None:
    out["precompile"] = pre
if trace_dir:
    st = tracing.span_stats()
    snap = telemetry.snapshot()

    def _hist(name):
        fam = snap.get(name) or {}
        series = fam.get("series") or [{}]
        return (float(series[0].get("sum", 0.0)),
                int(series[0].get("count", 0)))

    def _spans(name):
        v = st.get(("serve", name)) or {"total_s": 0.0, "count": 0}
        return float(v["total_s"]), int(v["count"])

    ok, report = tracing.reconcile_with_metrics()
    out["reconcile_ok"] = ok
    out["reconcile"] = {
        "request_span": _spans("request"),
        "request_hist": _hist("paddle_tpu_serve_request_seconds"),
        "ttft_span": _spans("ttft"),
        "ttft_hist": _hist("paddle_tpu_serve_ttft_seconds"),
        "serve_checks": {k: v for k, v in report.items()
                         if k.startswith("serve")},
    }
    tracing.close()
print(json.dumps(out))
