"""Multihost resilience: coordination store, quorum watchdog,
coordinated restore, rendezvous, cross-rank telemetry merge.

The acceptance scenario (ISSUE 7): a 3-subprocess CPU cluster over a
tmpdir store where one rank is SIGKILLed mid-async-save must end with
every surviving rank restored to the SAME verified checkpoint step, a
quorum watchdog that did NOT fire for the single dead rank, and a
host-0-merged fault log + Prometheus export carrying per-rank labeled
events for the kill.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed import coordination as C
from paddle_tpu.distributed.coordination import (
    ClusterContext, ClusterMonitor, DirectoryStore, publish_heartbeat,
    quorum_threshold, rendezvous,
)
from paddle_tpu.io.checkpoint import (
    latest_common_complete_step, publish_complete_steps,
)
from paddle_tpu.runtime import telemetry as T
from paddle_tpu.runtime.resilience import fault_events, reset_fault_events

HERE = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(HERE, "_cluster_child.py")


@pytest.fixture(autouse=True)
def _clean_faults():
    reset_fault_events()
    yield
    reset_fault_events()


# ---------------------------------------------------------------------------
# store

def test_directory_store_roundtrip(tmp_path):
    s = DirectoryStore(tmp_path)
    s.put("heartbeats/rank_0", {"rank": 0, "step": 3})
    assert s.get("heartbeats/rank_0") == {"rank": 0, "step": 3}
    s.put("heartbeats/rank_1", {"rank": 1, "step": 4}, fsync=False)
    assert sorted(s.list("heartbeats")) == [
        "heartbeats/rank_0", "heartbeats/rank_1"]
    s.delete("heartbeats/rank_0")
    assert s.get("heartbeats/rank_0") is None
    assert s.list("nowhere") == []


def test_directory_store_torn_file_reads_none(tmp_path):
    s = DirectoryStore(tmp_path)
    os.makedirs(tmp_path / "rendezvous", exist_ok=True)
    with open(tmp_path / "rendezvous" / "x.json", "w") as f:
        f.write('{"payload": {"a"')  # torn write
    assert s.get("rendezvous/x") is None  # poll contract, no raise


def test_directory_store_rejects_bad_keys(tmp_path):
    s = DirectoryStore(tmp_path)
    for bad in ("../escape", "a//b", "", "a/../b"):
        with pytest.raises(ValueError):
            s.put(bad, {})


# ---------------------------------------------------------------------------
# quorum watchdog

def _stale_beat(store, rank, age, step=0):
    store.put(f"heartbeats/rank_{rank}",
              {"rank": rank, "step": step, "wall": time.time() - age,
               "mono": 0.0}, fsync=False)


def test_quorum_threshold_never_one():
    assert quorum_threshold(2) == 2
    assert quorum_threshold(3) == 2
    assert quorum_threshold(8) == 4
    assert quorum_threshold(8, quorum=0.75) == 6
    assert quorum_threshold(100, quorum=0.01) == 2  # floor at 2


def test_single_slow_rank_degrades_not_aborts(tmp_path):
    s = DirectoryStore(tmp_path)
    publish_heartbeat(s, 0, 10)
    publish_heartbeat(s, 1, 10)
    _stale_beat(s, 2, age=100)
    m = ClusterMonitor(s, rank=0, world_size=3, stale_after=30,
                       dead_after=300)
    m.reset_grace(now=time.time() - 10_000)  # long-running monitor
    scan = m.poll()
    assert scan["stale"] == [2] and not scan["quorum_stalled"]
    assert fault_events()["peer_stale"] == 1
    m.poll()  # transition recorded ONCE, not per poll
    assert fault_events()["peer_stale"] == 1
    publish_heartbeat(s, 2, 11)  # recovers: next staleness is a new event
    m.poll()
    _stale_beat(s, 2, age=100)
    m.poll()
    assert fault_events()["peer_stale"] == 2


def test_quorum_of_stale_ranks_stalls(tmp_path):
    s = DirectoryStore(tmp_path)
    publish_heartbeat(s, 0, 5)
    _stale_beat(s, 1, age=100)
    _stale_beat(s, 2, age=100)
    m = ClusterMonitor(s, rank=0, world_size=3, stale_after=30,
                       dead_after=300)
    m.reset_grace(now=time.time() - 10_000)  # long-running monitor
    scan = m.poll()
    assert sorted(scan["stale"]) == [1, 2]
    assert scan["quorum_stalled"]


def test_dead_rank_declared_down_cluster_wide(tmp_path):
    s = DirectoryStore(tmp_path)
    publish_heartbeat(s, 0, 5)
    publish_heartbeat(s, 1, 5)
    _stale_beat(s, 2, age=1000, step=7)
    m0 = ClusterMonitor(s, rank=0, world_size=3, stale_after=30,
                        dead_after=300)
    m0.reset_grace(now=time.time() - 10_000)  # long-running monitor
    scan = m0.poll()
    assert scan["dead"] == [2] and scan["down"] == [2]
    assert fault_events()["peer_dead"] == 1
    # a PEER's monitor observes the declaration without re-declaring
    m1 = ClusterMonitor(s, rank=1, world_size=3, stale_after=3000,
                        dead_after=9000)
    m1.reset_grace(now=time.time() - 10_000)
    assert m1.poll()["down"] == [2]
    rec = s.get("down/rank_2")
    assert rec["declared_by"] == 0 and rec["last_step"] == 7


def test_recovered_rank_clears_down_declaration(tmp_path):
    s = DirectoryStore(tmp_path)
    publish_heartbeat(s, 0, 5)
    _stale_beat(s, 1, age=1000)
    m = ClusterMonitor(s, rank=0, world_size=2, stale_after=30,
                       dead_after=300)
    m.reset_grace(now=time.time() - 10_000)  # long-running monitor
    assert m.poll()["down"] == [1]
    # rank 1 comes back (restart into the same store, or a transient
    # stall that resolved): fresh heartbeats must clear the cluster-wide
    # declaration, or supervisors keying on peers_down() act on a
    # healthy rank forever
    publish_heartbeat(s, 1, 6)
    scan = m.poll()
    assert scan["down"] == [] and scan["fresh"] == [0, 1]
    assert s.get("down/rank_1") is None
    # ...and a LATER real death re-declares (transition state was reset)
    _stale_beat(s, 1, age=1000)
    assert m.poll()["down"] == [1]
    assert fault_events()["peer_dead"] == 2


def test_cold_start_never_quorum_stalls(tmp_path):
    # NOBODY has published yet (first-step compiles can far exceed
    # stale_after): every rank classifies stale once the grace expires,
    # but pure bring-up must not quorum-abort the job — each rank's
    # LOCAL watchdog guards a genuine pre-heartbeat hang
    s = DirectoryStore(tmp_path)
    m = ClusterMonitor(s, rank=0, world_size=3, stale_after=30,
                       dead_after=3000)
    scan = m.poll(now=time.time() + 120)  # grace long expired
    assert sorted(scan["stale"]) == [0, 1, 2]
    assert not scan["quorum_stalled"] and scan["published"] == 0
    # the FIRST heartbeat of THIS incarnation arms the quorum
    m.reset_grace(now=time.time() - 10_000)  # monitor now long-running
    publish_heartbeat(s, 0, 1)
    _stale_beat(s, 0, age=100)
    scan = m.poll(now=time.time() + 120)
    assert scan["quorum_stalled"]


def test_restart_into_reused_store_does_not_quorum_stall(tmp_path):
    # kill-and-resume into the same store dir: every heartbeat on disk
    # is the PREVIOUS incarnation's and stale. A fresh monitor must
    # grace those ranks like never-published ones — not classify them
    # instantly stale/dead and quorum-abort the restarted job before
    # anyone reaches a first tick
    s = DirectoryStore(tmp_path)
    for r in range(3):
        _stale_beat(s, r, age=500)
    m = ClusterMonitor(s, rank=0, world_size=3, stale_after=30,
                       dead_after=300)
    scan = m.poll()
    assert scan["fresh"] == [0, 1, 2]  # inside the new grace window
    assert not scan["quorum_stalled"] and scan["published"] == 0
    # this incarnation's first real heartbeat supersedes the old one
    publish_heartbeat(s, 1, 0)
    scan = m.poll()
    assert scan["published"] == 1


def test_down_ranks_outside_world_are_not_reported(tmp_path):
    # store dir reused by a SMALLER world: rank 3's old declaration is
    # not part of this job and nothing could ever clear it
    s = DirectoryStore(tmp_path)
    s.put("down/rank_3", {"rank": 3, "declared_by": 0,
                          "wall": time.time() - 100})
    publish_heartbeat(s, 0, 1)
    publish_heartbeat(s, 1, 1)
    publish_heartbeat(s, 2, 1)
    m = ClusterMonitor(s, rank=0, world_size=3, stale_after=30,
                       dead_after=300)
    assert m.poll()["down"] == []
    # ...and the direct reader every consumer (incl. ElasticManager
    # .peers_down()) goes through is filtered too
    assert m.down_ranks() == []


def test_never_published_rank_judged_from_monitor_start(tmp_path):
    # the PR-3 lesson, cluster edition: a rank that hangs before its
    # FIRST heartbeat must become visible once the start-grace expires
    s = DirectoryStore(tmp_path)
    publish_heartbeat(s, 0, 1)
    m = ClusterMonitor(s, rank=0, world_size=2, stale_after=30,
                       dead_after=300)
    assert m.poll()["stale"] == []          # inside the grace window
    _stale_beat(s, 0, age=-60)              # still fresh at the fake now
    scan = m.poll(now=time.time() + 60)     # grace expired
    assert scan["stale"] == [1]


# ---------------------------------------------------------------------------
# rendezvous

def test_rendezvous_leader_publishes_follower_reads(tmp_path):
    s = DirectoryStore(tmp_path)
    got = {}

    def follower():
        got["v"] = rendezvous(s, "manifest", timeout=10)

    t = threading.Thread(target=follower)
    t.start()
    time.sleep(0.1)
    assert rendezvous(s, "manifest", {"shapes": [1, 2]},
                      leader=True) == {"shapes": [1, 2]}
    t.join(timeout=10)
    assert got["v"] == {"shapes": [1, 2]}


def test_rendezvous_timeout_emits_fault_event_not_hang(tmp_path):
    s = DirectoryStore(tmp_path)
    t0 = time.monotonic()
    assert rendezvous(s, "never", timeout=0.3) is None
    assert time.monotonic() - t0 < 5.0
    assert fault_events()["rendezvous_timeouts"] == 1


def test_rendezvous_min_wall_ignores_previous_runs_doc(tmp_path):
    s = DirectoryStore(tmp_path)
    s.put("rendezvous/restore_step",
          {"payload": {"step": 99}, "wall": time.time() - 3600})
    assert rendezvous(s, "restore_step", timeout=0.3,
                      min_wall=time.time() - 60) is None
    assert fault_events()["rendezvous_timeouts"] == 1


# ---------------------------------------------------------------------------
# coordinated restore protocol (in-process; subprocess proof below)

def test_latest_common_complete_step_intersects(tmp_path):
    s = DirectoryStore(tmp_path)
    ck = tmp_path / "ck"
    for step in (0, 5, 10):
        os.makedirs(ck / str(step))
    assert publish_complete_steps(s, 0, str(ck)) == [0, 5, 10]
    s.put("ckpt/rank_1", {"rank": 1, "steps": [0, 5], "wall": time.time()})
    s.put("ckpt/rank_2", {"rank": 2, "steps": [0, 5, 10],
                          "wall": time.time()})
    assert latest_common_complete_step(s, expected_ranks=3, timeout=5) == 5
    # a missing publication degrades (fault event + intersect present)
    assert latest_common_complete_step(s, expected_ranks=4,
                                       timeout=0.3) == 5
    assert fault_events()["rendezvous_timeouts"] == 1


def test_latest_common_complete_step_empty_cases(tmp_path):
    s = DirectoryStore(tmp_path)
    assert latest_common_complete_step(s, timeout=0.0) is None
    s.put("ckpt/rank_0", {"rank": 0, "steps": [3], "wall": time.time()})
    s.put("ckpt/rank_1", {"rank": 1, "steps": [], "wall": time.time()})
    assert latest_common_complete_step(s, timeout=0.0) is None


# ---------------------------------------------------------------------------
# telemetry: publication, merge, pushgateway

def test_merge_cluster_rank_labels_and_histogram_aggregate(tmp_path):
    s = DirectoryStore(tmp_path)
    T.reset_metrics()
    T.counter("paddle_tpu_train_steps_total", "steps").inc(3)
    h = T.histogram("paddle_tpu_step_seconds", "step time")
    h.observe(0.01)
    h.observe(0.02)
    T.publish_registry(s, 0)
    T.publish_registry(s, 1)  # same registry published as a second rank
    out = T.merge_cluster(s)
    assert out["ranks"] == [0, 1]
    parsed = T.parse_prometheus_textfile(out["prom_path"])
    by_rank = {dict(k[1]).get("rank") for k in parsed}
    assert {"0", "1", "all"} <= by_rank
    # the rank="all" histogram aggregate sums both ranks' counts
    key = ("paddle_tpu_step_seconds_count", (("rank", "all"),))
    assert parsed[key] == 4.0
    T.reset_metrics()


def test_merge_cluster_fault_log_includes_event_stream_faults(tmp_path):
    s = DirectoryStore(tmp_path)
    # a rank that died after its last publication: its final fault only
    # exists in its per-record-flushed event stream
    ev_dir = tmp_path / "events" / "rank_2"
    os.makedirs(ev_dir)
    with open(ev_dir / "events.jsonl", "w") as f:
        f.write(json.dumps({"ts": 123.0, "kind": "fault", "rank": 2,
                            "fault": "injected_faults",
                            "detail": "checkpoint.async_started:kill"})
                + "\n")
    s.put("telemetry/rank_0",
          {"rank": 0, "metrics": {},
           "fault_log": [{"ts": 124.0, "fault": "peer_stale",
                          "detail": "rank 2"}]})
    out = T.merge_cluster(s)
    faults = out["faults"]
    assert [(f["rank"], f["fault"]) for f in faults] == [
        (2, "injected_faults"), (0, "peer_stale")]
    on_disk = [json.loads(line) for line in open(out["faults_path"])]
    assert on_disk == faults


def test_merge_cluster_never_double_counts_stream_faults(tmp_path):
    # record_fault stamps its own time.time() into the bounded log and
    # EventStream.emit stamps another microseconds later, so per-record
    # keys can never match the two copies up — a rank with an event
    # stream must contribute its faults from the stream ONLY
    s = DirectoryStore(tmp_path)
    ev_dir = tmp_path / "events" / "rank_0"
    os.makedirs(ev_dir)
    with open(ev_dir / "events.jsonl", "w") as f:
        f.write(json.dumps({"ts": 100.000009, "kind": "fault", "rank": 0,
                            "fault": "rollbacks", "detail": "x"}) + "\n")
    s.put("telemetry/rank_0",
          {"rank": 0, "metrics": {},
           "fault_log": [{"ts": 100.000001, "fault": "rollbacks",
                          "detail": "x"}]})
    out = T.merge_cluster(s)
    assert [(f["rank"], f["fault"], f["source"]) for f in out["faults"]] \
        == [(0, "rollbacks", "events")]


def test_merge_cluster_keeps_pre_stream_publication_faults(tmp_path):
    # a fault recorded BEFORE the event stream was configured (e.g. a
    # stale_manifests during warm-start, ahead of cluster bring-up)
    # exists only in the publication fault_log — the stream-supersedes
    # dedup must not swallow it
    s = DirectoryStore(tmp_path)
    ev_dir = tmp_path / "events" / "rank_0"
    os.makedirs(ev_dir)
    with open(ev_dir / "events.jsonl", "w") as f:
        f.write(json.dumps({"ts": 200.0, "kind": "train_begin",
                            "rank": 0}) + "\n")
        f.write(json.dumps({"ts": 201.000009, "kind": "fault", "rank": 0,
                            "fault": "peer_stale", "detail": "y"}) + "\n")
    s.put("telemetry/rank_0",
          {"rank": 0, "metrics": {},
           "fault_log": [
               {"ts": 150.0, "fault": "stale_manifests", "detail": "pre"},
               {"ts": 201.000001, "fault": "peer_stale", "detail": "y"}]})
    out = T.merge_cluster(s)
    got = [(f["fault"], f["source"]) for f in out["faults"]]
    assert got == [("stale_manifests", "publication"),
                   ("peer_stale", "events")], got


def test_push_prometheus_roundtrip_and_failure(tmp_path):
    import http.server

    T.reset_metrics()
    T.counter("paddle_tpu_train_steps_total", "steps").inc(7)
    got = {}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_PUT(self):
            got["path"] = self.path
            n = int(self.headers["Content-Length"])
            got["body"] = self.rfile.read(n).decode()
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        assert T.push_prometheus(f"127.0.0.1:{srv.server_port}",
                                 instance="rank3")
    finally:
        srv.shutdown()
    assert got["path"] == "/metrics/job/paddle_tpu/instance/rank3"
    assert "paddle_tpu_train_steps_total 7" in got["body"]
    # failure path: refused connection degrades to a fault event
    with pytest.warns(UserWarning, match="pushgateway"):
        assert T.push_prometheus("127.0.0.1:1", timeout=0.5) is False
    assert fault_events()["push_failures"] == 1
    T.reset_metrics()


def test_rendezvous_manifest_leader_follower(tmp_path):
    from paddle_tpu.runtime import warmup

    s = DirectoryStore(tmp_path)
    leader = ClusterContext(s, rank=0, world_size=2)
    follower = ClusterContext(s, rank=1, world_size=2)
    got = {}

    def wait():
        got["doc"] = warmup.rendezvous_manifest(follower, timeout=10)

    t = threading.Thread(target=wait)
    t.start()
    time.sleep(0.1)
    doc = warmup.rendezvous_manifest(leader)
    t.join(timeout=10)
    assert doc is not None and got["doc"] is not None
    assert got["doc"]["version"] == doc["version"]
    assert got["doc"]["jax"] == doc["jax"]


def test_rendezvous_manifest_version_mismatch_degrades(tmp_path):
    from paddle_tpu.runtime import warmup

    s = DirectoryStore(tmp_path)
    s.put("rendezvous/shape_manifest",
          {"payload": {"version": -1, "entries": []}, "wall": time.time()})
    follower = ClusterContext(s, rank=1, world_size=2)
    assert warmup.rendezvous_manifest(follower, timeout=1.0) is None
    assert fault_events()["stale_manifests"] == 1


# ---------------------------------------------------------------------------
# the acceptance scenario: 3 subprocess ranks, SIGKILL one mid-async-save

def _spawn_rank(rank, world, cluster_dir, ckpt_root, phase, steps=4,
                extra_env=None):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("PADDLE_TPU_FAULT_INJECT")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        # one CPU device per rank: the coordination layer needs no
        # backend collectives, and inheriting conftest's 8-virtual-device
        # XLA_FLAGS makes each child's saves slow enough to blow the
        # heartbeat staleness margins under full-suite load
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PADDLE_TPU_CLUSTER_DIR": str(cluster_dir),
        "PADDLE_TPU_CLUSTER_RANK": str(rank),
        "PADDLE_TPU_CLUSTER_WORLD": str(world),
    })
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, CHILD, phase, str(ckpt_root), str(steps)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)


def _result(out):
    for line in out.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(f"no RESULT line in:\n{out[-3000:]}")


@pytest.mark.slow  # ~50s: 5 subprocess jax imports + the dead-peer
#                    deadline wait. Excluded from the tier-1 870s
#                    budget run (ROADMAP wall-clock policy) but gated
#                    in CI: tools/ci_check.sh runs it explicitly.
def test_cluster_kill9_mid_async_save_survivors_agree(tmp_path):
    cluster_dir = tmp_path / "cluster"
    ckpt_root = tmp_path / "ckpts"
    kill_step = 2  # rank 1 dies inside save(step=2): its 3rd save call
    procs = {}
    for rank in range(3):
        extra = {}
        if rank == 1:
            extra = {"PADDLE_TPU_FAULT_INJECT":
                     f"checkpoint.async_started=kill:{kill_step + 1}",
                     "CLUSTER_CHILD_KILL_STEP": str(kill_step)}
        procs[rank] = _spawn_rank(rank, 3, cluster_dir, ckpt_root,
                                  "train", extra_env=extra)
    outs = {}
    try:
        for rank, p in procs.items():
            out, _ = p.communicate(timeout=240)
            outs[rank] = out.decode("utf-8", "replace")
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
    # rank 1 was SIGKILLed mid-async-save; survivors exited clean
    assert procs[1].returncode == -9, outs[1][-2000:]
    for rank in (0, 2):
        assert procs[rank].returncode == 0, \
            f"rank {rank}:\n{outs[rank][-3000:]}"
    r0, r2 = _result(outs[0]), _result(outs[2])
    # the quorum watchdog did NOT fire for the single dead rank...
    assert not r0["stalled"] and not r2["stalled"], (r0, r2)
    # ...but every survivor observed it degrade: stale, then declared
    # down cluster-wide
    for r in (r0, r2):
        assert r["peer_stale"] >= 1, r
        assert 1 in r["peers_down"], r
    # the torn step never entered rank 1's publication
    pub1 = DirectoryStore(cluster_dir).get("ckpt/rank_1")
    assert pub1 is not None and kill_step not in pub1["steps"], pub1

    # -- crash-restart: both survivors must restore the SAME step ------------
    procs = {rank: _spawn_rank(rank, 3, cluster_dir, ckpt_root, "restore")
             for rank in (0, 2)}
    routs = {}
    try:
        for rank, p in procs.items():
            out, _ = p.communicate(timeout=240)
            routs[rank] = out.decode("utf-8", "replace")
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
    for rank in (0, 2):
        assert procs[rank].returncode == 0, \
            f"rank {rank}:\n{routs[rank][-3000:]}"
    rr0, rr2 = _result(routs[0]), _result(routs[2])
    # same agreed step on every survivor — the max step ALL ranks
    # (including the dead one) verified complete, i.e. the step before
    # the kill — and identical restored payloads
    assert rr0["step"] == rr2["step"] == kill_step - 1, (rr0, rr2)
    assert rr0["restored_step"] == rr2["restored_step"] == kill_step - 1
    assert rr0["w00"] == rr2["w00"]

    # -- host-0 merge: one prom + one fault log for the whole job ------------
    store = DirectoryStore(cluster_dir)
    merged = T.merge_cluster(store)
    assert set(merged["ranks"]) == {0, 1, 2}
    parsed = T.parse_prometheus_textfile(merged["prom_path"])
    ranks_in_prom = {dict(k[1]).get("rank") for k in parsed}
    assert {"0", "1", "2"} <= ranks_in_prom, ranks_in_prom
    faults = merged["faults"]
    by_rank_kind = {(f["rank"], f["fault"]) for f in faults}
    # the kill itself, flushed by the dying rank's event stream in its
    # final instant
    assert (1, "injected_faults") in by_rank_kind, sorted(by_rank_kind)
    # the survivors' observation of the dead peer
    assert any(k == "peer_stale" and r in (0, 2)
               for r, k in by_rank_kind), sorted(by_rank_kind)
    assert any(k == "peer_dead" and r in (0, 2)
               for r, k in by_rank_kind), sorted(by_rank_kind)


def _event_line(detail, ts=1.0, kind="fault", pid=111, fault="rollbacks"):
    return json.dumps({"ts": ts, "kind": kind, "rank": 0, "pid": pid,
                       "fault": fault, "detail": detail}) + "\n"


def test_merge_cluster_tails_from_saved_offsets(tmp_path):
    """Each boundary reads O(new bytes): after a merge, already-consumed
    bytes are never parsed again. Proven by REWRITING a consumed line
    (the second one — the first line is the incarnation signature and
    changing it legitimately forces a reset) with an equally-long but
    different valid fault line: a re-read would surface the bogus
    fault; a tail cannot see it."""
    s = DirectoryStore(tmp_path)
    ev_dir = tmp_path / "events" / "rank_0"
    os.makedirs(ev_dir)
    path = ev_dir / "events.jsonl"
    first = _event_line("origA")
    second = _event_line("origB", ts=2.0)
    with open(path, "w") as f:
        f.write(first)
        f.write(second)
    out = T.merge_cluster(s)
    assert [f["detail"] for f in out["faults"]] == ["origA", "origB"]
    state = json.load(open(tmp_path / "merged" / "merge_state.json"))
    assert state["ranks"]["0"]["offset"] == os.path.getsize(path)

    bogus = _event_line("BOGUS", ts=2.0)  # same byte length as `second`
    assert len(bogus) == len(second)
    with open(path, "r+b") as f:
        f.seek(len(first))
        f.write(bogus.encode())           # overwrite a consumed line
        f.seek(0, os.SEEK_END)
        f.write(_event_line("origC", ts=3.0).encode())
    out = T.merge_cluster(s)
    details = [f["detail"] for f in out["faults"]]
    assert details == ["origA", "origB", "origC"], details  # no BOGUS
    state = json.load(open(tmp_path / "merged" / "merge_state.json"))
    assert state["ranks"]["0"]["offset"] == os.path.getsize(path)


def test_merge_cluster_offset_resets_on_rank_relaunch(tmp_path):
    """A relaunched rank that starts a FRESH (shorter) event file must
    reset the saved offset and be re-tailed from byte 0 — while the
    previous incarnation's accumulated faults survive in the merged
    log, without duplicates."""
    s = DirectoryStore(tmp_path)
    ev_dir = tmp_path / "events" / "rank_0"
    os.makedirs(ev_dir)
    path = ev_dir / "events.jsonl"
    with open(path, "w") as f:
        f.write(_event_line("inc1-a", ts=1.0, pid=111))
        f.write(_event_line("inc1-b", ts=2.0, pid=111))
    out = T.merge_cluster(s)
    assert len(out["faults"]) == 2
    old_offset = json.load(open(
        tmp_path / "merged" / "merge_state.json"))["ranks"]["0"]["offset"]

    # relaunch: a new incarnation replaces the file with a shorter one
    with open(path, "w") as f:
        f.write(_event_line("inc2-a", ts=3.0, pid=222))
    assert os.path.getsize(path) < old_offset
    out = T.merge_cluster(s)
    details = sorted(f["detail"] for f in out["faults"])
    assert details == ["inc1-a", "inc1-b", "inc2-a"], details
    state = json.load(open(tmp_path / "merged" / "merge_state.json"))
    assert state["ranks"]["0"]["offset"] == os.path.getsize(path)
    # both incarnations' stream starts are known (per-pid)
    assert set(state["ranks"]["0"]["starts"]) == {"111", "222"}

    # idempotence: a third merge with no new bytes changes nothing
    out = T.merge_cluster(s)
    assert sorted(f["detail"] for f in out["faults"]) == details


def test_merge_cluster_detects_relaunch_even_when_new_file_is_longer(
        tmp_path):
    """Incarnation change is detected by the head signature, not just
    file size: a relaunched rank whose fresh file grows PAST the old
    offset before the next merge must still be re-tailed from byte 0,
    or its earliest faults silently vanish."""
    s = DirectoryStore(tmp_path)
    ev_dir = tmp_path / "events" / "rank_0"
    os.makedirs(ev_dir)
    path = ev_dir / "events.jsonl"
    with open(path, "w") as f:
        f.write(_event_line("inc1-a", ts=1.0, pid=111))
    out = T.merge_cluster(s)
    assert [f["detail"] for f in out["faults"]] == ["inc1-a"]
    old_offset = json.load(open(
        tmp_path / "merged" / "merge_state.json"))["ranks"]["0"]["offset"]

    # fresh incarnation, LONGER than the consumed prefix of the old one
    with open(path, "w") as f:
        f.write(_event_line("inc2-a", ts=3.0, pid=222))
        f.write(_event_line("inc2-b", ts=4.0, pid=222))
        f.write(_event_line("inc2-c", ts=5.0, pid=222))
    assert os.path.getsize(path) > old_offset
    out = T.merge_cluster(s)
    details = sorted(f["detail"] for f in out["faults"])
    assert details == ["inc1-a", "inc2-a", "inc2-b", "inc2-c"], details
