"""Spawn-target helpers for test_incubate_checkpoint_mp (a spawned child
re-imports the target function's module, so it must live in a real file,
not the pytest module namespace)."""
import numpy as np


def child_echo(q_in, q_out):
    # a spawned child re-runs sitecustomize, which force-registers the
    # axon TPU plugin; first device use would hang on the tunnel unless
    # the child pins the platform the way conftest does for the parent
    import jax

    jax.config.update("jax_platforms", "cpu")
    t = q_in.get(timeout=30)
    q_out.put(float(np.asarray(t.numpy()).sum()))
