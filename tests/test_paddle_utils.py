"""paddle.utils parity: dlpack, download, unique_name, op_version,
install_check, image_util, legacy profiler facade (round-3 verdict #5).

Reference: python/paddle/utils/{dlpack,download,op_version}.py +
fluid/unique_name.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import utils


def test_dlpack_round_trip():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    cap = utils.dlpack.to_dlpack(x)
    y = utils.dlpack.from_dlpack(cap)
    np.testing.assert_array_equal(y.numpy(), x.numpy())


def test_dlpack_torch_interop():
    import torch

    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    y = utils.dlpack.from_dlpack(t)  # producer protocol, zero-copy on CPU
    np.testing.assert_array_equal(y.numpy(), t.numpy())
    back = torch.from_dlpack(utils.dlpack.to_dlpack(
        paddle.to_tensor(np.ones((2, 2), np.float32))))
    assert back.shape == (2, 2) and float(back.sum()) == 4.0


def test_download_file_url_and_zip(tmp_path):
    import zipfile

    src = tmp_path / "weights.npz"
    np.savez(src, w=np.ones(3))
    got = utils.download.get_path_from_url(f"file://{src}",
                                           root_dir=str(tmp_path / "cache"))
    assert os.path.exists(got)
    # zip archives decompress into the cache
    zpath = tmp_path / "model.zip"
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("model/weights.txt", "hello")
    got2 = utils.download.get_path_from_url(
        f"file://{zpath}", root_dir=str(tmp_path / "cache2"))
    assert os.path.isdir(got2)
    assert open(os.path.join(got2, "weights.txt")).read() == "hello"


def test_download_refuses_egress(tmp_path):
    with pytest.raises(RuntimeError, match="zero network egress"):
        utils.download.get_weights_path_from_url(
            "https://example.invalid/resnet50.pdparams")


def test_download_md5_mismatch(tmp_path):
    src = tmp_path / "f.bin"
    src.write_bytes(b"data")
    with pytest.raises(OSError, match="md5"):
        utils.download.get_path_from_url(
            f"file://{src}", root_dir=str(tmp_path / "c"), md5sum="0" * 32)


def test_unique_name_generate_switch_guard():
    un = utils.unique_name
    with un.guard():
        a, b = un.generate("fc"), un.generate("fc")
        c = un.generate("conv")
    assert a == "fc_0" and b == "fc_1" and c == "conv_0"
    with un.guard("scope_"):
        assert un.generate("fc").startswith("scope_fc_")
    with un.guard():  # fresh scope restarts numbering
        assert un.generate("fc") == "fc_0"


def test_op_version_checker():
    from paddle_tpu.utils.op_version import (
        OpLastCheckpointChecker, register_op_version,
    )

    register_op_version("test_op", "quant axis added", 1)
    checker = OpLastCheckpointChecker()
    assert checker.filter_updates("test_op", key="quant") \
        == ["quant axis added"]
    assert checker.filter_updates("missing_op") == []


def test_require_version():
    utils.require_version("0.0.1")
    utils.require_version("0.0.1", "99.0")
    with pytest.raises(Exception, match="older"):
        utils.require_version("99.0")
    with pytest.raises(Exception, match="newer"):
        utils.require_version("0.0.1", "0.0.2")
    with pytest.raises(TypeError):
        utils.require_version(1)


def test_run_check(capsys):
    utils.run_check()
    out = capsys.readouterr().out
    assert "installed successfully" in out


def test_image_util():
    from paddle_tpu.utils import image_util as iu

    img = np.arange(3 * 8 * 6, dtype=np.float32).reshape(3, 8, 6)
    r = iu.resize_image(img, 4)
    assert r.shape[0] == 3 and min(r.shape[1:]) == 4
    np.testing.assert_array_equal(iu.flip(img), img[:, :, ::-1])
    c = iu.crop_img(img, 4, test=True)
    assert c.shape == (3, 4, 4)
    flat = iu.preprocess_img(img, np.zeros((3, 4, 4)), 4, is_train=False)
    assert flat.shape == (48,)


def test_legacy_profiler_facade():
    opts = utils.ProfilerOptions({"state": "CPU"})
    assert opts["state"] == "CPU"
    assert opts.with_state("All")["state"] == "All"
    with pytest.raises(ValueError):
        opts["nope"]
    p = utils.Profiler(enabled=True)
    with p:
        (paddle.to_tensor(np.ones(4)) * 2).numpy()
        p.record_step()
    assert utils.get_profiler() is utils.get_profiler()


def test_op_version_type_filter():
    from paddle_tpu.utils.op_version import (
        OpLastCheckpointChecker, register_op_version,
    )

    register_op_version("typed_op", "new attr", 1, update_type="kNewAttr")
    register_op_version("typed_op", "bugfix", 2, update_type="kBugfix")
    checker = OpLastCheckpointChecker()
    assert checker.filter_updates("typed_op", type="kNewAttr") \
        == ["new attr"]
    assert len(checker.filter_updates("typed_op")) == 2


def test_run_check_preserves_static_mode():
    paddle.enable_static()
    try:
        utils.run_check()
        assert not paddle.in_dynamic_mode()
    finally:
        paddle.disable_static()


def test_download_skips_reextract(tmp_path):
    import zipfile

    zpath = tmp_path / "m.zip"
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("m/w.txt", "v1")
    root = utils.download.get_path_from_url(
        f"file://{zpath}", root_dir=str(tmp_path / "c"))
    marker = os.path.join(root, "w.txt")
    open(marker, "w").write("user-modified")
    root2 = utils.download.get_path_from_url(
        f"file://{zpath}", root_dir=str(tmp_path / "c"))
    assert root2 == root
    assert open(marker).read() == "user-modified"  # not re-extracted
