"""Test harness: CPU-hosted virtual 8-device mesh (SURVEY §4).

The image's sitecustomize imports jax and registers the axon TPU plugin at
interpreter start, so JAX_PLATFORMS in os.environ is already baked into
jax.config by the time conftest runs — override via jax.config.update before
any backend initializes.
"""
import os

# PADDLE_TPU_TEST_REAL_CHIP=1 leaves the live (axon TPU) backend in place
# for the @pytest.mark.tpu suite (`-m tpu`); everything else runs on the
# virtual 8-device CPU mesh. x64 stays off on the real chip — TPUs have
# no f64 and the tpu-marked checks are written for 32-bit.
_REAL_CHIP = os.environ.get("PADDLE_TPU_TEST_REAL_CHIP") == "1"

if not _REAL_CHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _REAL_CHIP:
    jax.config.update("jax_platforms", "cpu")
    # int64/float64 parity vs numpy references: tests opt in to x64 (the
    # library itself no longer enables it globally — round-2 verdict weak #3)
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: needs a real TPU chip "
        "(run with PADDLE_TPU_TEST_REAL_CHIP=1 -m tpu)")
    config.addinivalue_line(
        "markers", "slow: heavyweight file excluded from the tier-1 "
        "`-m 'not slow'` budget run (run explicitly with -m slow)")


@pytest.fixture(autouse=True)
def _seeded():
    import paddle_tpu as paddle

    paddle.seed(102)
    np.random.seed(102)
    yield


# ---------------------------------------------------------------------------
# capability probes

import functools  # noqa: E402


@functools.lru_cache(maxsize=1)
def _partial_auto_spmd_error():
    """None when this host's XLA can compile the partial-auto shard_map
    lowering the pipeline schedule uses (real TPU, or a jax/XLA with
    SPMD PartitionId support); else the error string. XLA CPU SPMD
    cannot compile the PartitionId instruction the partial-auto lowering
    emits, which hard-fails the test_pipeline_virtual /
    test_dist_dryrun cluster on CPU hosts — the probe converts those to
    skips-with-reason. Runs the smallest real failing computation (a
    2-chunk identity pipeline under jit) so it can never drift from
    what the tests actually exercise."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.distributed.pipeline import pipeline_forward

    devs = jax.devices()
    if len(devs) < 8:
        return None  # not the virtual-mesh config; let the tests speak

    mesh = Mesh(np.array(devs[:8]).reshape(4, 2), ("dp", "pp"))

    def stage_fn(p, h):
        return h + p

    try:
        out = jax.jit(lambda sp, xv: pipeline_forward(
            stage_fn, sp, xv, mesh=mesh))(
                jnp.zeros((2, 1), jnp.float32),
                jnp.ones((2, 1, 1), jnp.float32))
        np.asarray(out)
        return None
    except Exception as e:  # noqa: BLE001 — filtered by signature below
        msg = f"{type(e).__name__}: {str(e)[:200]}"
        # only the KNOWN platform gap converts to a skip; any other
        # probe failure (a real pipeline_forward regression) returns
        # None so the tests run and fail loudly instead of green-skipping
        if "PartitionId" in msg or "SPMD partitioning" in msg:
            return msg
        return None


@pytest.fixture
def require_partial_auto_spmd():
    """Skip (with the probed reason) on hosts whose XLA can't compile
    partial-auto shard_map programs (the PartitionId/XLA-CPU-SPMD gap,
    ROADMAP triage item)."""
    err = _partial_auto_spmd_error()
    if err is not None:
        pytest.skip("partial-auto shard_map unsupported on this host's "
                    "XLA backend (PartitionId/SPMD gap, likely TPU-only "
                    "until a jax upgrade): " + err)


# the smallest real cross-process computation: 2 processes, 1 CPU device
# each, jax.distributed rendezvous, then one jitted reduction whose
# input is sharded across BOTH processes — exactly the operation the
# multihost suite needs and exactly what some jaxlib CPU backends
# reject with "Multiprocess computations aren't implemented on the CPU
# backend".
_MP_PROBE_CHILD = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
pid, port = int(sys.argv[1]), sys.argv[2]
jax.distributed.initialize("127.0.0.1:" + port, 2, pid)
mesh = Mesh(np.array(jax.devices()), ("dp",))
x = jax.make_array_from_callback(
    (2,), NamedSharding(mesh, P("dp")),
    lambda idx: np.ones(1, np.float32))
out = jax.jit(lambda v: v.sum(),
              out_shardings=NamedSharding(mesh, P()))(x)
print("MP_PROBE_OK", float(jax.device_get(out)))
"""


@functools.lru_cache(maxsize=1)
def _cpu_multiprocess_error():
    """None when this host's jaxlib CPU backend can run computations
    spanning multiple processes; else the error signature. Only the
    KNOWN backend gap converts to a skip — any other probe failure
    returns None so the real tests run and fail loudly."""
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = str(s.getsockname()[1])
    s.close()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, "-c", _MP_PROBE_CHILD, str(pid), port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out.decode("utf-8", "replace"))
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        return None  # a hang is not the known gap: let the tests speak
    if all(p.returncode == 0 for p in procs):
        return None
    for out in outs:
        if "Multiprocess computations aren't implemented" in out:
            return ("jaxlib CPU backend: 'Multiprocess computations "
                    "aren't implemented on the CPU backend'")
    return None


@pytest.fixture
def require_multiprocess_cpu():
    """Skip (with the probed reason) on hosts whose jaxlib CPU backend
    cannot execute cross-process computations — the pre-existing
    test_multihost failure (ROADMAP triage item). The multihost
    COORDINATION layer (tests/test_cluster_resilience.py) does not
    need backend collectives and still runs everywhere."""
    err = _cpu_multiprocess_error()
    if err is not None:
        pytest.skip("cross-process computations unsupported on this "
                    "host's CPU backend (TPU/multi-host only until a "
                    "jaxlib upgrade): " + err)
