"""Test harness: CPU-hosted virtual 8-device mesh (SURVEY §4).

The image's sitecustomize imports jax and registers the axon TPU plugin at
interpreter start, so JAX_PLATFORMS in os.environ is already baked into
jax.config by the time conftest runs — override via jax.config.update before
any backend initializes.
"""
import os

# PADDLE_TPU_TEST_REAL_CHIP=1 leaves the live (axon TPU) backend in place
# for the @pytest.mark.tpu suite (`-m tpu`); everything else runs on the
# virtual 8-device CPU mesh. x64 stays off on the real chip — TPUs have
# no f64 and the tpu-marked checks are written for 32-bit.
_REAL_CHIP = os.environ.get("PADDLE_TPU_TEST_REAL_CHIP") == "1"

if not _REAL_CHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _REAL_CHIP:
    jax.config.update("jax_platforms", "cpu")
    # int64/float64 parity vs numpy references: tests opt in to x64 (the
    # library itself no longer enables it globally — round-2 verdict weak #3)
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: needs a real TPU chip "
        "(run with PADDLE_TPU_TEST_REAL_CHIP=1 -m tpu)")


@pytest.fixture(autouse=True)
def _seeded():
    import paddle_tpu as paddle

    paddle.seed(102)
    np.random.seed(102)
    yield
