"""Pipeline parallel, MoE, sequence-parallel ring attention, elastic
(SURVEY §4 test_distributed_*: PP output parity, MoE dispatch)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn

N = 8


@pytest.fixture(autouse=True)
def _clean_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


class TestPipeline:
    def _setup(self, S=4, M=8, mb=2, d=16):
        from paddle_tpu.distributed.pipeline import (
            microbatch, stack_stage_params)

        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        rng = np.random.RandomState(0)
        stage_params = [
            {"w": jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.3),
             "b": jnp.asarray(rng.randn(d).astype(np.float32) * 0.1)}
            for _ in range(S)]

        def stage_fn(p, h):
            return jnp.tanh(h @ p["w"] + p["b"])

        x = rng.randn(M * mb, d).astype(np.float32)
        return (mesh, stage_params, stack_stage_params(stage_params),
                stage_fn, x, microbatch(jnp.asarray(x), M))

    def test_forward_parity_vs_sequential(self):
        from paddle_tpu.distributed.pipeline import pipeline_forward

        mesh, plist, stacked, stage_fn, x, mbs = self._setup()
        out = jax.jit(lambda sp, m: pipeline_forward(
            stage_fn, sp, m, mesh=mesh))(stacked, mbs)
        h = jnp.asarray(x)
        for p in plist:
            h = stage_fn(p, h)
        np.testing.assert_allclose(np.asarray(out).reshape(x.shape),
                                   np.asarray(h), rtol=1e-5, atol=1e-6)

    def test_grad_parity_vs_sequential(self):
        from paddle_tpu.distributed.pipeline import pipeline_forward

        mesh, plist, stacked, stage_fn, x, mbs = self._setup()

        def loss_pp(sp):
            return (pipeline_forward(stage_fn, sp, mbs, mesh=mesh) ** 2).mean()

        def loss_seq(ps):
            h = jnp.asarray(x)
            for p in ps:
                h = stage_fn(p, h)
            return (h ** 2).mean()

        g_pp = jax.jit(jax.grad(loss_pp))(stacked)
        g_seq = jax.grad(loss_seq)(plist)
        for i in range(len(plist)):
            np.testing.assert_allclose(np.asarray(g_pp["w"][i]),
                                       np.asarray(g_seq[i]["w"]),
                                       rtol=1e-4, atol=1e-6)

    def test_pipeline_layer_bridge_parity(self):
        """PipelineLayer.stacked_trunk_params + trunk_stage_fn drive the
        jitted schedule and match sequential forward."""
        from paddle_tpu.distributed.pipeline import (
            LayerDesc, PipelineLayer, microbatch, pipeline_forward)

        paddle.seed(9)
        pl = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 16, 16) for _ in range(8)],
            num_stages=4)
        mesh = Mesh(np.array(jax.devices()[:4]), ("pp",))
        stacked = pl.stacked_trunk_params()
        fn = pl.trunk_stage_fn()
        x = paddle.randn([8, 16])
        out = jax.jit(lambda sp, m: pipeline_forward(
            fn, sp, m, mesh=mesh))(stacked, microbatch(x._value, 4))
        np.testing.assert_allclose(np.asarray(out).reshape(8, 16),
                                   np.asarray(pl(x)._value),
                                   rtol=1e-5, atol=1e-6)

    def test_pipeline_layer_heterogeneous_trunk_rejected(self):
        from paddle_tpu.distributed.pipeline import LayerDesc, PipelineLayer

        pl = PipelineLayer(layers=[LayerDesc(nn.Linear, 16, 16),
                                   LayerDesc(nn.Linear, 16, 8)],
                           num_stages=2)
        with pytest.raises(ValueError, match="homogeneous"):
            pl.stacked_trunk_params()

    def test_pipeline_layer_segmentation(self):
        from paddle_tpu.distributed.pipeline import LayerDesc, PipelineLayer

        pl = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8) for _ in range(6)],
            num_stages=3)
        assert pl.num_stages == 3
        assert [len(pl.get_stage_layers(s)) for s in range(3)] == [2, 2, 2]
        y = pl(paddle.randn([2, 8]))
        assert tuple(y.shape) == (2, 8)


class TestMoE:
    def test_moe_layer_shapes_and_grads(self):
        paddle.seed(5)
        layer = dist.MoELayer(d_model=8, d_hidden=16, num_experts=4,
                              top_k=2, capacity_factor=2.0)
        x = paddle.randn([2, 6, 8])
        y = layer(x)
        assert tuple(y.shape) == (2, 6, 8)
        loss = (y * y).mean() + layer.aux_loss
        loss.backward()
        assert layer.w1.grad is not None
        assert layer.gate_weight.grad is not None

    def test_gating_matches_loop_reference(self):
        from paddle_tpu.distributed.moe import top_k_gating

        T, E, C, K = 12, 4, 8, 2
        rng = np.random.RandomState(0)
        logits = rng.randn(T, E).astype(np.float32)
        combine, dispatch, aux = top_k_gating(jnp.asarray(logits), K, C)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        fill = np.zeros(E, int)
        ref = np.zeros((T, E, C), np.float32)
        for k in range(K):
            p = probs.copy()
            for kk in range(k):
                for t in range(T):
                    p[t, np.argsort(-probs[t])[kk]] = 0
            for t in range(T):
                e = int(np.argmax(p[t]))
                if fill[e] < C:
                    ref[t, e, fill[e]] = p[t, e]
                fill[e] += 1
        np.testing.assert_allclose(np.asarray(combine), ref, rtol=1e-5,
                                   atol=1e-6)

    def test_expert_sharding_on_mesh(self):
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "ep"))
        dist.set_mesh(mesh)
        layer = dist.MoELayer(d_model=8, d_hidden=16, num_experts=4,
                              top_k=1)
        assert layer.w1._value.sharding.spec[0] == "ep"


class TestRingAttention:
    def _ref(self, q, k, v, causal):
        s = q.shape[2]
        sc = 1.0 / np.sqrt(q.shape[-1])
        logits = np.einsum("bhqd,bhkd->bhqk", q, k) * sc
        if causal:
            logits = np.where(np.tril(np.ones((s, s), bool)), logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_parity_vs_full_attention(self, causal):
        mesh = Mesh(np.array(jax.devices()), ("sp",))
        rng = np.random.RandomState(0)
        b, h, s, d = 2, 4, 64, 16
        q, k, v = (rng.randn(b, h, s, d).astype(np.float32)
                   for _ in range(3))
        out = dist.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh=mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out),
                                   self._ref(q, k, v, causal),
                                   rtol=2e-4, atol=2e-5)

    def test_differentiable_and_tape(self):
        mesh = Mesh(np.array(jax.devices()), ("sp",))
        dist.set_mesh(mesh)
        q = paddle.randn([1, 2, 32, 8])
        q.stop_gradient = False
        k, v = paddle.randn([1, 2, 32, 8]), paddle.randn([1, 2, 32, 8])
        out = dist.ring_attention(q, k, v, causal=True)
        out.sum().backward()
        assert q.grad is not None
        assert np.isfinite(np.asarray(q.grad._value)).all()


class TestElastic:
    def test_kill_and_resume(self, tmp_path):
        from paddle_tpu.distributed.elastic import (
            ElasticManager, latest_checkpoint)

        ckpt = str(tmp_path / "ck")
        saved = {}

        def save_fn(step):
            import os

            d = f"{ckpt}/{step}"
            os.makedirs(d, exist_ok=True)
            saved[step] = True

        em = ElasticManager(ckpt, timeout=0.2, save_interval=2,
                            save_fn=save_fn)
        # "train" 5 steps, saving at 2 and 4, then die
        for step in range(5):
            em.tick(step)
        assert latest_checkpoint(ckpt) == 4

        # resume in a fresh manager
        em2 = ElasticManager(ckpt, timeout=0.2)
        restored = {}
        start = em2.resume(lambda s: restored.update(step=s))
        assert start == 5 and restored["step"] == 4

    def test_watchdog_detects_stall(self, tmp_path):
        import time

        from paddle_tpu.distributed.elastic import ElasticManager

        em = ElasticManager(str(tmp_path / "ck"), timeout=0.05)
        em.tick(0)
        hit = []
        em.start_watchdog(on_stall=lambda hb: hit.append(hb), poll=0.05)
        time.sleep(0.5)
        em.stop()
        assert em.stalled and hit and hit[0]["step"] == 0


class TestPipelineHeterogeneous:
    """Round-2 verdict weak #4: heterogeneous trunks through the jitted
    schedule (padded per-stage param vectors + lax.switch branches)."""

    def _build(self, S=4, d=16):
        from paddle_tpu.distributed.pipeline import PipelineLayer

        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        dist.set_mesh(mesh)
        paddle.seed(11)
        widths = [24, 40, 8, 16][:S]  # different per-stage architectures
        stages, probes = [], []
        for w in widths:
            lin1, lin2 = nn.Linear(d, w), nn.Linear(w, d)
            probes.append(lin1)
            stages.append(nn.Sequential(lin1, nn.Tanh(), lin2))
        pl = PipelineLayer(layers=stages, num_stages=S)
        assert not pl.is_homogeneous()
        return pl, probes, mesh

    def test_het_forward_parity_and_grads(self):
        pl, probes, _ = self._build()
        x = paddle.randn([8, 16])
        out_pp = pl.forward_pipelined(x, num_micro=4)
        out_seq = pl(x)
        np.testing.assert_allclose(np.asarray(out_pp.numpy()),
                                   np.asarray(out_seq.numpy()),
                                   rtol=1e-5, atol=1e-6)
        loss = (out_pp ** 2).mean()
        loss.backward()
        for lin in probes:
            assert lin.weight.grad is not None
            assert float(np.abs(np.asarray(lin.weight.grad.numpy())).sum()) > 0

    def test_het_remat_parity(self):
        pl, probes, _ = self._build()
        pl._recompute_interval = 1  # checkpoint each stage branch
        x = paddle.randn([8, 16])
        out_remat = pl.forward_pipelined(x, num_micro=4)
        out_seq = pl(x)
        np.testing.assert_allclose(np.asarray(out_remat.numpy()),
                                   np.asarray(out_seq.numpy()),
                                   rtol=1e-5, atol=1e-6)
        (out_remat ** 2).mean().backward()
        assert probes[0].weight.grad is not None


def test_pipeline_dropout_varies_across_steps():
    """The jit-cached schedule must not bake dropout masks in as
    trace-time constants (fresh key threaded per call)."""
    from paddle_tpu.distributed.pipeline import PipelineLayer

    mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
    dist.set_mesh(mesh)
    paddle.seed(5)
    stages = [nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5))
              for _ in range(2)]
    pl = PipelineLayer(layers=stages, num_stages=2)
    pl.train()
    x = paddle.ones([4, 8])
    out1 = np.asarray(pl.forward_pipelined(x, num_micro=2).numpy())
    out2 = np.asarray(pl.forward_pipelined(x, num_micro=2).numpy())
    assert not np.allclose(out1, out2), "dropout mask reused across steps"
    # and microbatches within one step see different masks: with the same
    # row fed to every microbatch, identical masks would duplicate rows
    assert not np.allclose(out1[:2], out1[2:]), \
        "dropout mask reused across microbatches"
    pl.eval()
    e1 = np.asarray(pl.forward_pipelined(x, num_micro=2).numpy())
    e2 = np.asarray(pl.forward_pipelined(x, num_micro=2).numpy())
    np.testing.assert_allclose(e1, e2)


class TestMetaParallelNamespace:
    """fleet.meta_parallel import path (reference meta_parallel/__init__)."""

    def test_imports_and_wrappers(self):
        from paddle_tpu.distributed.fleet import meta_parallel as mp

        for n in ("VocabParallelEmbedding", "ColumnParallelLinear",
                  "RowParallelLinear", "ParallelCrossEntropy",
                  "LayerDesc", "SharedLayerDesc", "PipelineLayer",
                  "TensorParallel", "PipelineParallel",
                  "ShardingParallel", "get_rng_state_tracker"):
            assert hasattr(mp, n), n
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu import nn

        paddle.seed(0)
        wrapped = mp.TensorParallel(nn.Linear(4, 4), hcg=None,
                                    strategy=None)
        x = paddle.randn([2, 4])
        assert wrapped(x).shape == [2, 4]
        assert len(list(wrapped.parameters())) == 2

    def test_shared_layer_desc_ties_weights(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet import meta_parallel as mp

        paddle.seed(0)
        reg = {}
        d1 = mp.SharedLayerDesc("embed", nn.Embedding, 16, 8)
        d2 = mp.SharedLayerDesc(
            "embed", nn.Embedding, 16, 8,
            forward_func=lambda l, x: x @ paddle.transpose(l.weight,
                                                           [1, 0]))
        a = d1.build_layer(shared_registry=reg)
        b = d2.build_layer(shared_registry=reg)
        assert a.weight is b.weight  # tied: one Parameter object
        out = b(paddle.randn([2, 8]))  # forward_func: tied LM head
        assert out.shape == [2, 16]
        # a separate construction scope shares nothing
        c = d1.build_layer(shared_registry={})
        assert c.weight is not a.weight

    def test_rng_state_tracker(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.distributed.fleet import meta_parallel as mp

        t = mp.RNGStatesTracker()
        t.add("mp", 1234)
        with t.rng_state("mp"):
            a = paddle.randn([4]).numpy()
        with t.rng_state("mp"):
            b = paddle.randn([4]).numpy()
        assert not np.array_equal(a, b)  # stream advances per scope
        import pytest

        with pytest.raises(ValueError):
            t.add("mp", 99)
        with pytest.raises(ValueError):
            t.rng_state("missing").__enter__()

    def test_meta_optimizers(self):
        import numpy as np

        import paddle_tpu as paddle
        import pytest
        from paddle_tpu import nn
        from paddle_tpu.distributed.fleet import meta_optimizers as mo

        paddle.seed(0)
        lin = nn.Linear(8, 8)
        opt = mo.GradientMergeOptimizer(
            paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=lin.parameters()),
            k_steps=2)
        w0 = np.asarray(lin.weight.numpy()).copy()
        x = paddle.randn([4, 8])
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        np.testing.assert_array_equal(lin.weight.numpy(), w0)  # merged
        assert mo.LambOptimizer is paddle.optimizer.Lamb
        with pytest.raises(AttributeError, match="strategy.recompute"):
            mo.RecomputeOptimizer
        assert not hasattr(mo, "AMPOptimizer")  # probes degrade


class TestRingAttentionLongContext:
    """Long-context is first-class: exact parity and finite grads at a
    sequence length where ring attention actually earns its keep
    (seq 2048 over the full 8-way sp ring; per-device shard 256)."""

    def test_parity_seq_2048(self):
        mesh = Mesh(np.array(jax.devices()), ("sp",))
        rng = np.random.RandomState(0)
        b, h, s, d = 1, 2, 2048, 16
        q, k, v = (rng.randn(b, h, s, d).astype(np.float32)
                   for _ in range(3))
        out = np.asarray(dist.ring_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh=mesh,
            causal=True))
        sc = 1.0 / np.sqrt(d)
        logits = np.einsum("bhqd,bhkd->bhqk", q, k) * sc
        logits = np.where(np.tril(np.ones((s, s), bool)), logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(out, ref, rtol=3e-4, atol=3e-5)

    def test_grads_finite_seq_2048(self):
        mesh = Mesh(np.array(jax.devices()), ("sp",))
        dist.set_mesh(mesh)
        try:
            paddle.seed(0)
            q = paddle.randn([1, 2, 2048, 16])
            q.stop_gradient = False
            k = paddle.randn([1, 2, 2048, 16])
            v = paddle.randn([1, 2, 2048, 16])
            out = dist.ring_attention(q, k, v, causal=True)
            out.mean().backward()
            g = np.asarray(q.grad._value)
            assert np.isfinite(g).all() and np.abs(g).max() > 0
        finally:
            dist.set_mesh(None)
