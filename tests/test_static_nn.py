"""paddle.static.nn control flow + layer builders.

Reference: python/paddle/static/nn/control_flow.py (cond, case,
switch_case, while_loop) and static/nn/common.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


class TestControlFlow:
    def test_cond(self):
        x = paddle.to_tensor(3.0)
        out = snn.cond(x < 5.0, lambda: x * 2, lambda: x - 1)
        assert float(out) == 6.0
        out = snn.cond(x > 5.0, lambda: x * 2, lambda: x - 1)
        assert float(out) == 2.0

    def test_cond_multi_output(self):
        x = paddle.to_tensor(2.0)
        a, b = snn.cond(x < 5.0, lambda: (x + 1, x + 2),
                        lambda: (x - 1, x - 2))
        assert (float(a), float(b)) == (3.0, 4.0)

    def test_cond_under_jit(self):
        import jax
        import jax.numpy as jnp

        def f(v):
            t = paddle.to_tensor(v)
            return snn.cond(t < 0, lambda: -t, lambda: t)._value

        g = jax.jit(f)
        assert float(g(jnp.float32(-3.0))) == 3.0
        assert float(g(jnp.float32(4.0))) == 4.0

    def test_case_first_match_wins(self):
        x = paddle.to_tensor(1.0)
        out = snn.case([(x < 2, lambda: paddle.to_tensor(10.0)),
                        (x < 3, lambda: paddle.to_tensor(20.0))],
                       default=lambda: paddle.to_tensor(30.0))
        assert float(out) == 10.0
        x2 = paddle.to_tensor(2.5)
        out = snn.case([(x2 < 2, lambda: paddle.to_tensor(10.0)),
                        (x2 < 3, lambda: paddle.to_tensor(20.0))],
                       default=lambda: paddle.to_tensor(30.0))
        assert float(out) == 20.0
        x3 = paddle.to_tensor(9.0)
        out = snn.case([(x3 < 2, lambda: paddle.to_tensor(10.0)),
                        (x3 < 3, lambda: paddle.to_tensor(20.0))],
                       default=lambda: paddle.to_tensor(30.0))
        assert float(out) == 30.0

    def test_switch_case(self):
        idx = paddle.to_tensor(np.int32(1))
        out = snn.switch_case(idx, {
            0: lambda: paddle.to_tensor(0.0),
            1: lambda: paddle.to_tensor(11.0),
            7: lambda: paddle.to_tensor(77.0)},
            default=lambda: paddle.to_tensor(-1.0))
        assert float(out) == 11.0
        out = snn.switch_case(paddle.to_tensor(np.int32(5)), {
            0: lambda: paddle.to_tensor(0.0),
            1: lambda: paddle.to_tensor(11.0)},
            default=lambda: paddle.to_tensor(-1.0))
        assert float(out) == -1.0

    def test_while_loop(self):
        i = paddle.to_tensor(np.int64(0))
        s = paddle.to_tensor(0.0)
        i_out, s_out = snn.while_loop(
            lambda i, s: i < 10,
            lambda i, s: [i + 1, s + paddle.cast(i, "float32")],
            [i, s])
        assert int(i_out) == 10
        assert float(s_out) == 45.0

    def test_while_loop_under_jit(self):
        """while_loop compiles as lax.while_loop inside one XLA program."""
        import jax
        import jax.numpy as jnp

        def f(x0):
            with paddle.no_grad():
                _, out = snn.while_loop(
                    lambda i, v: i < 3,
                    lambda i, v: [i + 1, v * 2.0],
                    [paddle.to_tensor(np.int32(0)), paddle.to_tensor(x0)])
            return out._value

        assert float(jax.jit(f)(jnp.float32(1.5))) == 12.0  # 1.5 * 2^3


class TestLayerBuilders:
    def test_layer_norm_group_norm(self):
        paddle.seed(0)
        x = paddle.randn([2, 6, 4, 4])
        out = snn.layer_norm(x, begin_norm_axis=1)
        np.testing.assert_allclose(out.numpy().mean((1, 2, 3)), 0.0,
                                   atol=1e-5)
        out = snn.group_norm(x, groups=3)
        assert out.shape == [2, 6, 4, 4]

    def test_conv_transpose_and_3d(self):
        paddle.seed(1)
        x = paddle.randn([1, 3, 8, 8])
        out = snn.conv2d_transpose(x, 5, filter_size=2, stride=2)
        assert out.shape == [1, 5, 16, 16]
        v = paddle.randn([1, 2, 4, 8, 8])
        out = snn.conv3d(v, 4, filter_size=3, padding=1)
        assert out.shape == [1, 4, 4, 8, 8]
        out = snn.conv3d_transpose(v, 4, filter_size=2, stride=2)
        assert out.shape == [1, 4, 8, 16, 16]

    def test_bilinear_prelu_rowconv(self):
        paddle.seed(2)
        x = paddle.randn([4, 5])
        y = paddle.randn([4, 7])
        out = snn.bilinear_tensor_product(x, y, size=3)
        assert out.shape == [4, 3]
        img = paddle.randn([2, 3, 4, 4])
        assert snn.prelu(img, "channel").shape == [2, 3, 4, 4]
        seq = paddle.to_tensor(np.ones((2, 5, 3), np.float32))
        out = snn.row_conv(seq, future_context_size=2)
        # interior steps see full context: sum of 3 taps * 0.1 each
        np.testing.assert_allclose(out.numpy()[:, 0], 0.3, rtol=1e-5)

    def test_spectral_norm(self):
        paddle.seed(5)
        w = paddle.randn([6, 4])
        out = snn.spectral_norm(w, power_iters=20)
        s = np.linalg.svd(out.numpy(), compute_uv=False)[0]
        np.testing.assert_allclose(s, 1.0, rtol=1e-3)

    def test_conv_transpose_from_output_size(self):
        x = paddle.randn([1, 3, 8, 8])
        out = snn.conv2d_transpose(x, 5, output_size=[16, 16], stride=2)
        assert out.shape == [1, 5, 16, 16]

    def test_param_creation_in_branch_raises(self):
        x = paddle.to_tensor(np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="control-flow branch"):
            snn.cond(x.sum() > 0, lambda: snn.fc(x.reshape([1, 4]), 3),
                     lambda: x)

    def test_py_func_scalar_output(self):
        x = paddle.to_tensor(np.arange(4, dtype=np.float32))
        op = paddle.utils.register_custom_op(
            "host_mean", lambda a: np.float32(np.mean(a)),
            infer_shape=lambda a: ((), "float32"))
        assert float(op(x)._value) == 1.5

    def test_py_func(self):
        def host_sq(a):
            return np.asarray(a) ** 2

        x = paddle.to_tensor(np.arange(4, dtype=np.float32))
        out_spec = paddle.to_tensor(np.zeros(4, np.float32))
        out = snn.py_func(host_sq, x, out_spec)
        np.testing.assert_allclose(out.numpy(), [0, 1, 4, 9])

    def test_static_program_with_cond(self):
        """Control flow records into a static Program and replays."""
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            with paddle.static.program_guard(main):
                x = paddle.static.data("x", [4], "float32")
                y = snn.cond(x.sum() > 0, lambda: x * 2.0, lambda: x - 1.0)
            exe = paddle.static.Executor()
            (pos,) = exe.run(main, feed={"x": np.ones(4, np.float32)},
                             fetch_list=[y])
            np.testing.assert_allclose(pos, 2.0)
            (neg,) = exe.run(main, feed={"x": -np.ones(4, np.float32)},
                             fetch_list=[y])
            np.testing.assert_allclose(neg, -2.0)
        finally:
            paddle.disable_static()
