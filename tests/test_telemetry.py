"""Unified telemetry subsystem (runtime/telemetry.py): registry
semantics, exporter round-trips, event-stream rotation and crash
survival, per-op run-time attribution, and the TelemetryCallback's
reconciliation with the runtime's authoritative snapshots."""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core import dispatch
from paddle_tpu.runtime import telemetry as T
from paddle_tpu.runtime.resilience import fault_events, record_fault

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture
def tdir(tmp_path):
    """Fresh registry + event stream in a temp dir; restores the
    process-global telemetry state afterwards (other test files rely on
    emit() being a configured-elsewhere no-op)."""
    T.reset_metrics()
    prev_dir = T.telemetry_dir()
    d = str(tmp_path / "telemetry")
    T.configure(d)
    yield d
    stream = T.event_stream()
    if stream is not None:
        stream.close()
    T._stream = None
    T._config["dir"] = prev_dir
    T.reset_metrics()


# ---------------------------------------------------------------------------
# registry semantics

def test_counter_labels_and_values():
    T.reset_metrics()
    c = T.counter("t_requests_total", "reqs", ("route",))
    c.labels(route="a").inc()
    c.labels(route="a").inc(4)
    c.labels(route="b").inc()
    snap = T.snapshot()["t_requests_total"]
    by = {s["labels"]["route"]: s["value"] for s in snap["series"]}
    assert by == {"a": 5, "b": 1}
    assert snap["type"] == "counter"


def test_registration_idempotent_and_type_clash():
    T.reset_metrics()
    a = T.counter("t_same", "x")
    assert T.counter("t_same") is a
    with pytest.raises(ValueError):
        T.gauge("t_same")
    g = T.gauge("t_g")
    g.set(2.0)
    g.inc()
    g.dec(0.5)
    assert T.snapshot()["t_g"]["series"][0]["value"] == 2.5
    # mismatched re-declarations clash HERE, not at observe time
    with pytest.raises(ValueError):
        T.counter("t_same", labelnames=("op",))
    h = T.histogram("t_same_h", buckets=(0.1, 1.0))
    with pytest.raises(ValueError):
        T.histogram("t_same_h", buckets=(0.5, 2.0))
    assert T.histogram("t_same_h", buckets=(1.0, 0.1)) is h  # order-free


def test_histogram_buckets_and_merge():
    T.reset_metrics()
    h = T.histogram("t_lat_seconds", "lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.004, 0.05, 0.5, 7.0):
        h.observe(v)
    s = T.snapshot()["t_lat_seconds"]["series"][0]
    assert s["bucket_counts"] == [2, 1, 1, 1]  # last is the +Inf tail
    assert s["count"] == 5
    assert abs(s["sum"] - 7.559) < 1e-9
    merged = T.merge_histograms([s, s])
    assert merged["bucket_counts"] == [4, 2, 2, 2]
    assert merged["count"] == 10
    with pytest.raises(ValueError):
        T.merge_histograms([s, {"bucket_counts": [0], "sum": 0, "count": 0}])


def test_concurrent_increments():
    T.reset_metrics()
    c = T.counter("t_conc_total", "", ("k",))
    h = T.histogram("t_conc_seconds", "")

    def work():
        for _ in range(1000):
            c.labels(k="x").inc()
            h.observe(0.01)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert T.snapshot()["t_conc_total"]["series"][0]["value"] == 8000
    assert T.snapshot()["t_conc_seconds"]["series"][0]["count"] == 8000


def test_kill_switch_makes_mutations_noop(tdir):
    c = T.counter("t_kill_total")
    c.inc(3)
    prev = T.set_enabled(False)
    try:
        assert not T.enabled()
        c.inc(100)
        T.gauge("t_kill_g").set(9)
        T.emit("train_step", step=1)
        assert T.op_sample_every() == 0  # dispatch sampling keys off this
    finally:
        T.set_enabled(prev)
    assert T.snapshot()["t_kill_total"]["series"][0]["value"] == 3
    assert T.snapshot()["t_kill_g"]["series"][0]["value"] == 0.0
    assert T.read_events() == []


# ---------------------------------------------------------------------------
# exporters

def test_prometheus_round_trip(tdir):
    c = T.counter("t_rt_total", "with help", ("op",))
    c.labels(op='we"ird\\nm').inc(2)
    T.gauge("t_rt_gauge").set(-1.5)
    h = T.histogram("t_rt_seconds", "", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    path = T.write_prometheus()
    assert path == os.path.join(tdir, "metrics.prom")
    parsed = T.parse_prometheus_textfile(path)
    assert parsed[("t_rt_total", (("op", 'we"ird\\nm'),))] == 2.0
    assert parsed[("t_rt_gauge", ())] == -1.5
    # histogram exposition: cumulative buckets + sum + count
    assert parsed[("t_rt_seconds_bucket", (("le", "0.1"),))] == 1.0
    assert parsed[("t_rt_seconds_bucket", (("le", "1.0"),))] == 2.0
    assert parsed[("t_rt_seconds_bucket", (("le", "+Inf"),))] == 2.0
    assert parsed[("t_rt_seconds_count", ())] == 2.0
    assert abs(parsed[("t_rt_seconds_sum", ())] - 0.55) < 1e-9


def test_prometheus_nonfinite_values_export(tdir):
    # a NaN loss is exactly the state worth exporting (the bad-step
    # scenario): the writer must not crash on it
    T.gauge("t_nan").set(float("nan"))
    T.gauge("t_inf").set(float("inf"))
    parsed = T.parse_prometheus_textfile(T.write_prometheus())
    assert np.isnan(parsed[("t_nan", ())])
    assert parsed[("t_inf", ())] == float("inf")


def test_labels_typo_raises():
    T.reset_metrics()
    h = T.histogram("t_strict_seconds", "", ("op",))
    with pytest.raises(ValueError):
        h.labels(opname="matmul")  # typo must not aggregate under "None"
    with pytest.raises(ValueError):
        h.labels(op="x", extra="y")


def test_kill_switch_rearms_dispatch_sampling():
    prev_rate = dispatch.set_op_sample_every(7)
    try:
        T.set_enabled(False)
        assert dispatch.dispatch_stats()["op_sample_every"] == 0
        T.set_enabled(True)
        assert dispatch.dispatch_stats()["op_sample_every"] == \
            T.op_sample_env_rate()
    finally:
        T.set_enabled(True)
        dispatch.set_op_sample_every(prev_rate)


def test_export_failure_never_kills_fit(tdir, monkeypatch):
    def boom(*a, **kw):
        raise OSError("disk full")

    monkeypatch.setattr(T, "write_prometheus", boom)
    with pytest.warns(UserWarning, match="export failed"):
        cb = _tiny_fit(tdir, export_every=2)
    assert cb.global_step == 8  # the run outlived its observability


def test_snapshot_jsonl_append(tdir):
    T.counter("t_snap_total").inc(7)
    p1 = T.append_snapshot_jsonl(extra={"step": 1})
    T.counter("t_snap_total").inc()
    T.append_snapshot_jsonl(extra={"step": 2})
    lines = [json.loads(line) for line in open(p1)]
    assert len(lines) == 2
    assert lines[0]["step"] == 1 and "ts" in lines[0] and "mono" in lines[0]
    vals = [rec["metrics"]["t_snap_total"]["series"][0]["value"]
            for rec in lines]
    assert vals == [7, 8]


def test_event_stream_fields_and_rotation(tmp_path):
    path = str(tmp_path / "ev" / "events.jsonl")
    s = T.EventStream(path, max_bytes=300, max_files=3)
    for i in range(60):
        s.emit("tick", i=i)
    s.close()
    assert s.emitted == 60
    # bounded: exactly max_files generations on disk
    files = [path] + [f"{path}.{i}" for i in (1, 2)]
    assert all(os.path.exists(f) for f in files)
    assert not os.path.exists(f"{path}.3")
    back = T.read_events(path)
    idx = [e["i"] for e in back]
    assert idx == sorted(idx) and idx[-1] == 59  # oldest-first, tail kept
    ev = back[-1]
    assert ev["kind"] == "tick" and "ts" in ev and "mono" in ev
    assert ev["host"] and ev["pid"] == os.getpid()


def test_failed_reconfigure_keeps_old_stream_live(tdir):
    T.emit("tick", i=1)
    with pytest.raises(OSError):
        T.configure("/proc/definitely/unwritable/dir")
    T.emit("tick", i=2)  # the old stream must still be the live one
    assert T.telemetry_dir() == tdir
    assert [e["i"] for e in T.read_events()] == [1, 2]


def test_reconfigure_same_dir_updates_rotation_bounds(tdir):
    T.configure(tdir, max_bytes=65536, max_files=2)
    s = T.event_stream()
    assert s.max_bytes == 65536 and s.max_files == 2


def test_unwritable_log_dir_degrades_with_warning(tdir):
    with pytest.warns(UserWarning, match="cannot write"):
        cb = _tiny_fit("/proc/nope/telemetry", export_every=100)
    assert cb.global_step == 8  # fit survived; registry-only collection


def test_scalars_sink_flushes_per_write(tmp_path):
    sink = T.ScalarsSink(str(tmp_path / "vdl"))
    sink.write(1, {"loss": 0.5})
    sink.write(2, {"loss": 0.25})
    # readable BEFORE close: per-write flush is the kill -9 contract
    lines = [json.loads(line) for line in open(sink.path)]
    assert lines == [{"loss": 0.5, "global_step": 1},
                     {"loss": 0.25, "global_step": 2}]
    sink.close()


# ---------------------------------------------------------------------------
# runtime bridge + dispatch attribution

def test_sync_runtime_metrics_reconciles(tdir):
    record_fault("rollbacks", "test fixture")
    ds = T.sync_runtime_metrics()
    parsed = T.parse_prometheus_textfile(T.write_prometheus())
    for which in ("forward", "backward"):
        for key, mname in (("hits", "paddle_tpu_dispatch_cache_hits_total"),
                           ("misses",
                            "paddle_tpu_dispatch_cache_misses_total")):
            assert parsed[(mname, (("cache", which),))] == ds[which][key]
    for kind, n in fault_events().items():
        assert parsed[("paddle_tpu_fault_events_total",
                       (("fault", kind),))] == n
    # the structured event for the fault is on the stream too
    faults = [e for e in T.read_events() if e["kind"] == "fault"]
    assert any(e["fault"] == "rollbacks" for e in faults)


def test_op_run_time_sampling(tdir):
    prev_rate = dispatch.set_op_sample_every(1)  # sample every execution
    prev_warm = dispatch.set_warmup_count(1)
    try:
        x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
        for _ in range(4):
            paddle.tanh(x)
        per = dispatch.dispatch_stats()["per_op"].get("tanh")
        assert per and per["run_samples"] >= 3  # first call is the compile
        assert per["run_s"] > 0.0
        snap = T.snapshot().get("paddle_tpu_op_run_seconds")
        assert snap is not None
        tanh = [s for s in snap["series"] if s["labels"].get("op") == "tanh"]
        assert tanh and tanh[0]["count"] == per["run_samples"]
    finally:
        dispatch.set_op_sample_every(prev_rate)
        dispatch.set_warmup_count(prev_warm)


def test_sampling_disabled_costs_nothing(tdir):
    prev_rate = dispatch.set_op_sample_every(0)
    prev_warm = dispatch.set_warmup_count(1)
    try:
        x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
        for _ in range(4):
            paddle.exp(x)
        per = dispatch.dispatch_stats()["per_op"].get("exp")
        assert per is None or per["run_samples"] == 0
        snap = T.snapshot().get("paddle_tpu_op_run_seconds")
        assert snap is None or not any(
            s["labels"].get("op") == "exp" for s in snap["series"])
    finally:
        dispatch.set_op_sample_every(prev_rate)
        dispatch.set_warmup_count(prev_warm)


# ---------------------------------------------------------------------------
# hapi integration

def _tiny_fit(tdir, **cb_kw):
    from paddle_tpu.hapi.callbacks import TelemetryCallback

    paddle.seed(0)
    x = np.random.rand(64, 4).astype(np.float32)
    y = (x @ np.random.rand(4, 1).astype(np.float32)).astype(np.float32)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    cb = TelemetryCallback(log_dir=tdir, **cb_kw)
    model.fit([x, y], epochs=2, batch_size=16, verbose=0, callbacks=[cb])
    return cb


def test_telemetry_callback_fit_reconciles(tdir):
    cb = _tiny_fit(tdir, export_every=3)
    assert cb.global_step == 8  # 2 epochs x 4 batches
    # prometheus textfile written and reconciling EXACTLY with the
    # authoritative snapshots — the subsystem's acceptance property
    parsed = T.parse_prometheus_textfile(os.path.join(tdir, "metrics.prom"))
    ds = dispatch.dispatch_stats()
    assert parsed[("paddle_tpu_dispatch_cache_hits_total",
                   (("cache", "forward"),))] == ds["forward"]["hits"]
    assert parsed[("paddle_tpu_dispatch_cache_misses_total",
                   (("cache", "forward"),))] == ds["forward"]["misses"]
    for kind, n in fault_events().items():
        assert parsed[("paddle_tpu_fault_events_total",
                       (("fault", kind),))] == n
    assert parsed[("paddle_tpu_train_steps_total", ())] == 8
    assert parsed[("paddle_tpu_step_seconds_count", ())] == 8
    # per-step structured events with both clocks + host tags
    steps = [e for e in T.read_events() if e["kind"] == "train_step"]
    assert len(steps) == 8
    assert steps[-1]["step"] == 8 and steps[-1]["loss"] is not None
    assert all("mono" in e and "host" in e for e in steps)
    kinds = {e["kind"] for e in T.read_events()}
    assert {"train_begin", "train_end"} <= kinds
    # per-step scalars (TensorBoard-consumable), one line per batch
    scalars = [json.loads(line)
               for line in open(os.path.join(tdir, "scalars.jsonl"))]
    assert [r["global_step"] for r in scalars] == list(range(1, 9))
    assert all("loss" in r and "step_s" in r for r in scalars)


def test_telemetry_callback_inert_when_disabled(tdir):
    prev = T.set_enabled(False)
    try:
        cb = _tiny_fit(tdir, export_every=3)
    finally:
        T.set_enabled(prev)
    assert not cb._active
    assert not os.path.exists(os.path.join(tdir, "metrics.prom"))
    assert not os.path.exists(os.path.join(tdir, "scalars.jsonl"))


def test_visualdl_writes_per_batch(tmp_path):
    from paddle_tpu.hapi.callbacks import VisualDL

    vdl = VisualDL(log_dir=str(tmp_path / "vdl"))
    vdl.on_train_begin()
    vdl.on_train_batch_end(0, {"loss": 1.0, "step": 0})
    vdl.on_train_batch_end(1, {"loss": 0.5, "step": 1, "skipme": "str"})
    # the whole point of the fix: records are durable BEFORE
    # on_train_end — a kill -9 mid-run keeps every completed batch
    lines = [json.loads(line)
             for line in open(tmp_path / "vdl" / "scalars.jsonl")]
    assert len(lines) == 2
    assert lines[1] == {"loss": 0.5, "step": 1, "global_step": 2}
    vdl.on_train_end()


# ---------------------------------------------------------------------------
# crash survival + schema

def test_kill9_child_stream_survives(tmp_path):
    from paddle_tpu.testing.faults import faults_env

    child_dir = str(tmp_path / "crash")
    env = faults_env({"telemetry.child": ("kill", 25)})
    env.update({"TELEMETRY_CHILD_DIR": child_dir, "JAX_PLATFORMS": "cpu"})
    p = subprocess.run(
        [sys.executable, os.path.join(HERE, "_telemetry_child.py")],
        env=env, capture_output=True, text=True, timeout=120)
    assert p.returncode == -9, (p.returncode, p.stderr)
    events = T.read_events(os.path.join(child_dir, "events.jsonl"))
    steps = [e["step"] for e in events if e["kind"] == "train_step"]
    # every event emitted before the SIGKILL is durable (per-record
    # flush); the injector fired right after the 25th
    assert steps == list(range(1, 26))
    # the injection itself is on the stream too (record_fault emits)
    assert any(e["kind"] == "fault" and e["fault"] == "injected_faults"
               for e in events)


def test_schema_matches_checked_in_file():
    path = os.path.join(os.path.dirname(HERE), "tools",
                        "telemetry_schema.json")
    with open(path) as f:
        frozen = json.load(f)
    live = T.schema()
    assert live == frozen, (
        "metric/event schema drifted from tools/telemetry_schema.json — "
        "dashboards key on these names; if the rename is deliberate, "
        "regenerate with `python tools/telemetry_smoke.py --emit-schema`")


def test_schema_covers_registered_metrics(tdir):
    # everything sync + the callback register must be IN the schema —
    # an unlisted metric would dodge the rename gate
    _tiny_fit(tdir, export_every=100)
    T.sync_runtime_metrics()
    names = set(T.schema()["metrics"])
    unknown = set(T.snapshot()) - names
    assert not unknown, f"metrics missing from telemetry.SCHEMA: {unknown}"
