"""incubate fused ops/optimizers + onnx export (VERDICT items 9/10 tail).

Reference: python/paddle/incubate/nn/functional/fused_transformer.py,
incubate/optimizer/lookahead.py, incubate/tensor/math.py,
python/paddle/onnx/export.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_fused_feedforward_matches_unfused():
    paddle.seed(1)
    b, s, d, ff = 2, 6, 16, 32
    x = paddle.randn([b, s, d])
    w1 = paddle.randn([d, ff]) * 0.1
    w2 = paddle.randn([ff, d]) * 0.1
    b1 = paddle.zeros([ff])
    b2 = paddle.zeros([d])
    g = paddle.ones([d])
    z = paddle.zeros([d])
    out = paddle.incubate.nn.functional.fused_feedforward(
        x, w1, w2, linear1_bias=b1, linear2_bias=b2,
        ln1_scale=g, ln1_bias=z, ln2_scale=g, ln2_bias=z,
        dropout1_rate=0.0, dropout2_rate=0.0, pre_layer_norm=True,
        training=False)
    ref = x + nn.functional.linear(
        nn.functional.relu(nn.functional.linear(
            nn.functional.layer_norm(x, d, weight=g, bias=z), w1, b1)),
        w2, b2)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5,
                               atol=1e-6)


def test_fused_mha_matches_reference_math():
    paddle.seed(2)
    b, s, e, h = 2, 5, 16, 4
    d = e // h
    x = paddle.randn([b, s, e])
    qkv_w = paddle.randn([3, h, d, e]) * 0.1
    qkv_b = paddle.zeros([3, h, d])
    lin_w = paddle.randn([e, e]) * 0.1
    lin_b = paddle.zeros([e])
    g, z = paddle.ones([e]), paddle.zeros([e])
    out = paddle.incubate.nn.functional.fused_multi_head_attention(
        x, qkv_w, lin_w, pre_layer_norm=False, ln_scale=g, ln_bias=z,
        qkv_bias=qkv_b, linear_bias=lin_b, dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False)
    # unfused reference math
    qw = qkv_w.numpy().reshape(3 * e, e).T
    qkv = x.numpy() @ qw
    q, k, v = [a.reshape(b, s, h, d).transpose(0, 2, 1, 3)
               for a in np.split(qkv, 3, axis=-1)]
    att = q @ k.transpose(0, 1, 3, 2) / np.sqrt(d)
    att = np.exp(att - att.max(-1, keepdims=True))
    att /= att.sum(-1, keepdims=True)
    ctx = (att @ v).transpose(0, 2, 1, 3).reshape(b, s, e)
    proj = ctx @ lin_w.numpy()
    res = x.numpy() + proj
    mu = res.mean(-1, keepdims=True)
    var = res.var(-1, keepdims=True)
    expect = (res - mu) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)


def test_fused_layers_train():
    paddle.seed(3)
    layer = paddle.incubate.nn.FusedTransformerEncoderLayer(
        d_model=16, nhead=4, dim_feedforward=32, dropout_rate=0.0)
    x = paddle.randn([2, 6, 16])
    out = layer(x)
    assert out.shape == [2, 6, 16]
    loss = (out ** 2).mean()
    loss.backward()
    # pre_ln params are (correctly) unused with normalize_before=False;
    # everything that participated must carry a grad
    named = dict(layer.named_parameters())
    unused = {n for n in named if "pre_ln" in n or "ln1" in n}
    for n, p in named.items():
        if n not in unused and not p.stop_gradient:
            assert p.grad is not None, n


def test_softmax_mask_fuse():
    paddle.seed(4)
    x = paddle.randn([2, 4, 8, 8])
    mask = paddle.zeros([2, 1, 8, 8])
    out = paddle.incubate.softmax_mask_fuse(x, mask)
    expect = nn.functional.softmax(x, axis=-1)
    np.testing.assert_allclose(out.numpy(), expect.numpy(), rtol=1e-5)

    tri = paddle.incubate.softmax_mask_fuse_upper_triangle(x)
    got = tri.numpy()
    # strictly-upper entries masked out -> zero probability
    assert np.allclose(np.triu(got[0, 0], 1), 0.0)
    np.testing.assert_allclose(got.sum(-1), 1.0, rtol=1e-5)


def test_segment_ops():
    data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]],
                                     np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1, 1]))
    np.testing.assert_allclose(paddle.incubate.segment_sum(data, ids).numpy(),
                               [[4., 6.], [12., 14.]])
    np.testing.assert_allclose(paddle.incubate.segment_mean(data, ids).numpy(),
                               [[2., 3.], [6., 7.]])
    np.testing.assert_allclose(paddle.incubate.segment_max(data, ids).numpy(),
                               [[3., 4.], [7., 8.]])
    np.testing.assert_allclose(paddle.incubate.segment_min(data, ids).numpy(),
                               [[1., 2.], [5., 6.]])


def test_segment_sum_grad():
    data = paddle.to_tensor(np.ones((4, 2), np.float32))
    data.stop_gradient = False
    ids = paddle.to_tensor(np.array([0, 0, 1, 1]))
    out = paddle.incubate.segment_sum(data, ids)
    out.sum().backward()
    np.testing.assert_allclose(data.grad.numpy(), 1.0)


def test_graph_send_recv():
    x = paddle.to_tensor(np.array([[0., 2., 3.], [1., 4., 5.], [2., 6., 7.]],
                                  np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2, 0]))
    dst = paddle.to_tensor(np.array([1, 2, 1, 0]))
    out = paddle.incubate.graph_send_recv(x, src, dst, pool_type="sum")
    expect = np.zeros((3, 3), np.float32)
    expect[1] = x.numpy()[0] + x.numpy()[2]
    expect[2] = x.numpy()[1]
    expect[0] = x.numpy()[0]
    np.testing.assert_allclose(out.numpy(), expect)
    out_max = paddle.incubate.graph_send_recv(x, src, dst, pool_type="max")
    np.testing.assert_allclose(out_max.numpy()[1],
                               np.maximum(x.numpy()[0], x.numpy()[2]))


def test_graph_sampling_ops():
    # CSC graph: node 0 <- {1, 2}, node 1 <- {0}, node 2 <- {0, 1}
    row = paddle.to_tensor(np.array([1, 2, 0, 0, 1]))
    colptr = paddle.to_tensor(np.array([0, 2, 3, 5]))
    nodes = paddle.to_tensor(np.array([0, 2]))
    neigh, cnt = paddle.incubate.graph_sample_neighbors(row, colptr, nodes)
    np.testing.assert_array_equal(np.asarray(cnt._value), [2, 2])
    np.testing.assert_array_equal(np.asarray(neigh._value), [1, 2, 0, 1])
    # bounded sampling
    n2, c2 = paddle.incubate.graph_sample_neighbors(row, colptr, nodes,
                                                    sample_size=1)
    np.testing.assert_array_equal(np.asarray(c2._value), [1, 1])
    # reindex: seeds [0, 2] + neighbors [1, 2, 0, 1]
    src, dst, out_nodes = paddle.incubate.graph_reindex(nodes, neigh, cnt)
    nv = np.asarray(out_nodes._value)
    np.testing.assert_array_equal(nv[:2], [0, 2])  # seeds first
    np.testing.assert_array_equal(np.asarray(dst._value), [0, 0, 1, 1])
    # local src ids map back to the original neighbor ids
    np.testing.assert_array_equal(nv[np.asarray(src._value)],
                                  np.asarray(neigh._value))
    # khop: two hops of size 1
    es, ed, idx, _ = paddle.incubate.graph_khop_sampler(
        row, colptr, nodes, [1, 1])
    assert np.asarray(es._value).shape == (4,)


def test_lookahead():
    paddle.seed(5)
    lin = nn.Linear(4, 4)
    inner = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=lin.parameters())
    opt = paddle.incubate.LookAhead(inner, alpha=0.5, k=2)
    x = paddle.randn([8, 4])
    w0 = lin.weight.numpy().copy()
    fast = None
    for i in range(2):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        if i == 1:
            # fast weight just before the k-th sync
            pass
        opt.step()
        if i == 0:
            fast = lin.weight.numpy().copy()
        opt.clear_grad()
    # after k=2 steps: w = w0 + alpha*(fast2 - w0); fast2 moved beyond fast1
    w = lin.weight.numpy()
    assert not np.allclose(w, w0)
    # slow weights lie between initial and fast trajectory
    assert np.linalg.norm(w - w0) < np.linalg.norm(fast - w0) * 2


def test_model_average():
    paddle.seed(6)
    lin = nn.Linear(2, 2)
    # rate=1.0: window == num_updates, so after the first fold every
    # subsequent value stays in sum_1 -> average covers ALL steps
    # (reference recurrence, average_accumulates_op.h)
    ma = paddle.incubate.ModelAverage(1.0, parameters=lin.parameters(),
                                      min_average_window=1,
                                      max_average_window=10)
    vals = []
    for i in range(4):
        lin.weight._value = lin.weight._value + 1.0
        vals.append(lin.weight.numpy().copy())
        ma.step()
    live = lin.weight.numpy().copy()
    with ma.apply():
        np.testing.assert_allclose(lin.weight.numpy(),
                                   np.mean(vals, axis=0), rtol=1e-6)
    np.testing.assert_allclose(lin.weight.numpy(), live)  # restored

    # tiny trailing window (rate -> 0 keeps only the newest fold)
    ma2 = paddle.incubate.ModelAverage(0.5, parameters=lin.parameters(),
                                       min_average_window=1,
                                       max_average_window=10)
    for i in range(4):
        lin.weight._value = lin.weight._value + 1.0
        ma2.step()
    # folds at steps 1, 2, and 4 leave sum_3 = w3 + w4 over old_num=2
    with ma2.apply():
        np.testing.assert_allclose(lin.weight.numpy(), live + 3.5, rtol=1e-6)


def test_onnx_export_roundtrip(tmp_path):
    paddle.seed(7)
    lin = nn.Linear(4, 3)
    lin.eval()
    path = str(tmp_path / "model")
    out_path = paddle.onnx.export(
        lin, path, input_spec=[paddle.static.InputSpec([2, 4], "float32")])
    assert out_path.endswith(".onnx.stablehlo")
    import json
    import os

    assert os.path.exists(path + ".onnx.json")
    manifest = json.load(open(path + ".onnx.json"))
    assert manifest["format"] == "stablehlo"
    x = paddle.randn([2, 4])
    loaded = paddle.onnx.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), lin(x).numpy(), rtol=1e-5)
