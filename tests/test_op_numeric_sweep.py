"""OpTest-style numeric sweep (reference:
python/paddle/fluid/tests/unittests/op_test.py — fwd vs numpy reference +
grad vs numeric differentiation, one table entry per op config).

Forward: paddle op output == numpy reference (f64 under the test x64 opt-in).
Grad: for a random fixed cotangent c, loss = sum(op(x)*c); the tape gradient
g must satisfy the directional-derivative identity
    g . d  ==  (loss(x + eps d) - loss(x - eps d)) / (2 eps)
for a random direction d — the same check op_test.py's get_numeric_gradient
performs elementwise, collapsed to one dot product per input.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class Case:
    def __init__(self, name, fn, ref, inputs, kwargs=None, rtol=1e-5,
                 atol=1e-7, grad=None, grad_tol=5e-3, eps=1e-5):
        self.name = name
        self.fn = fn
        self.ref = ref
        self.inputs = inputs          # list of np arrays (f64 for grad acc)
        self.kwargs = kwargs or {}
        self.rtol = rtol
        self.atol = atol
        # default: grad-check every float-input float-output op
        self.grad = grad if grad is not None else all(
            a.dtype.kind == "f" for a in inputs)
        self.grad_tol = grad_tol
        self.eps = eps

    def __repr__(self):
        return self.name


R = np.random.RandomState


def _arr(seed, *shape, lo=-1.0, hi=1.0, dtype=np.float64):
    a = R(seed).uniform(lo, hi, shape).astype(dtype)
    return a


def _pos(seed, *shape, lo=0.2, hi=2.0):
    return _arr(seed, *shape, lo=lo, hi=hi)


def _ints(seed, *shape, lo=0, hi=10):
    return R(seed).randint(lo, hi, shape).astype(np.int64)


def _P(name):
    return getattr(paddle, name)


def _F(name):
    return getattr(nn.functional, name)


def _erf(x):
    from scipy import special

    return special.erf(x)


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


CASES = []


def C(*a, **kw):
    CASES.append(Case(*a, **kw))


# ---- unary math ----------------------------------------------------------
_X = _arr(0, 3, 4)
for name, ref, inp in [
    ("abs", np.abs, _arr(1, 3, 4, lo=0.1, hi=2.0)),
    ("neg", np.negative, _X),
    ("exp", np.exp, _X),
    ("expm1", np.expm1, _X),
    ("log", np.log, _pos(2, 3, 4)),
    ("log2", np.log2, _pos(3, 3, 4)),
    ("log10", np.log10, _pos(4, 3, 4)),
    ("log1p", np.log1p, _pos(5, 3, 4)),
    ("sqrt", np.sqrt, _pos(6, 3, 4)),
    ("rsqrt", lambda x: 1 / np.sqrt(x), _pos(7, 3, 4)),
    ("square", np.square, _X),
    ("sin", np.sin, _X),
    ("cos", np.cos, _X),
    ("tan", np.tan, _arr(8, 3, 4, lo=-1.0, hi=1.0)),
    ("asin", np.arcsin, _arr(9, 3, 4, lo=-0.9, hi=0.9)),
    ("acos", np.arccos, _arr(10, 3, 4, lo=-0.9, hi=0.9)),
    ("atan", np.arctan, _X),
    ("sinh", np.sinh, _X),
    ("cosh", np.cosh, _X),
    ("tanh", np.tanh, _X),
    ("asinh", np.arcsinh, _X),
    ("acosh", np.arccosh, _pos(11, 3, 4, lo=1.2, hi=3.0)),
    ("atanh", np.arctanh, _arr(12, 3, 4, lo=-0.9, hi=0.9)),
    ("ceil", np.ceil, _arr(13, 3, 4, lo=0.6, hi=3.4)),
    ("floor", np.floor, _arr(14, 3, 4, lo=0.6, hi=3.4)),
    ("round", np.round, _arr(15, 3, 4, lo=0.6, hi=3.4)),
    ("trunc", np.trunc, _arr(16, 3, 4, lo=0.6, hi=3.4)),
    ("sign", np.sign, _arr(17, 3, 4, lo=0.2, hi=2.0)),
    ("reciprocal", np.reciprocal, _pos(18, 3, 4)),
    ("erf", _erf, _X),
    ("digamma", None, None),  # placeholder removed below
]:
    if ref is None:
        continue
    grad = name not in ("ceil", "floor", "round", "trunc", "sign")
    C(name, _P(name), ref, [inp], grad=grad)

C("logit", _P("logit"), lambda x: np.log(x / (1 - x)),
  [_arr(19, 3, 4, lo=0.2, hi=0.8)])

# ---- binary math ---------------------------------------------------------
_A, _B = _arr(20, 3, 4), _arr(21, 3, 4, lo=0.3, hi=1.5)
for name, ref, a, b in [
    ("add", np.add, _A, _B),
    ("subtract", np.subtract, _A, _B),
    ("multiply", np.multiply, _A, _B),
    ("divide", np.divide, _A, _B),
    ("maximum", np.maximum, _A, _B),
    ("minimum", np.minimum, _A, _B),
    ("pow", np.power, _pos(22, 3, 4), _arr(23, 3, 4, lo=0.5, hi=2.0)),
    ("atan2", np.arctan2, _A, _B),
    ("fmax", np.fmax, _A, _B),
    ("fmin", np.fmin, _A, _B),
    ("hypot", np.hypot, _pos(24, 3, 4), _pos(25, 3, 4)),
    ("logaddexp", np.logaddexp, _A, _B),
    ("nextafter", np.nextafter, _A, _B),
    ("copysign", np.copysign, _A, _B),
    ("heaviside", np.heaviside, _arr(26, 3, 4, lo=0.1), _B),
]:
    grad = name not in ("nextafter", "copysign", "heaviside")
    C(name, _P(name), ref, [a, b], grad=grad)

C("mod_float", _P("mod"), np.mod, [_pos(27, 3, 4), _pos(28, 3, 4)],
  grad=False)
C("floor_divide", _P("floor_divide"), np.floor_divide,
  [_ints(29, 3, 4, lo=1, hi=20), _ints(30, 3, 4, lo=1, hi=5)])
C("remainder_int", _P("remainder"), np.remainder,
  [_ints(31, 3, 4, lo=0, hi=20), _ints(32, 3, 4, lo=1, hi=5)])
C("broadcast_add", _P("add"), np.add, [_arr(33, 3, 1), _arr(34, 1, 4)])

# ---- reductions ----------------------------------------------------------
_RX = _arr(40, 3, 4, 5)
for name, ref in [
    ("sum", np.sum), ("mean", np.mean), ("max", np.max), ("min", np.min),
    ("prod", np.prod),
]:
    # max/min grads are well-defined off ties (random continuous inputs)
    grad = True
    C(f"{name}_all", _P(name), ref, [_RX], grad=grad)
    C(f"{name}_axis", _P(name), lambda x, _r=ref: _r(x, axis=1), [_RX],
      kwargs={"axis": 1}, grad=grad)
    C(f"{name}_keepdim", _P(name),
      lambda x, _r=ref: _r(x, axis=2, keepdims=True), [_RX],
      kwargs={"axis": 2, "keepdim": True}, grad=grad)
C("logsumexp", _P("logsumexp"),
  lambda x: np.log(np.exp(x).sum(-1)), [_arr(41, 3, 4)],
  kwargs={"axis": -1})
C("amax", _P("amax"), lambda x: np.max(x, axis=0), [_RX],
  kwargs={"axis": 0}, grad=False)
C("amin", _P("amin"), lambda x: np.min(x, axis=0), [_RX],
  kwargs={"axis": 0}, grad=False)
C("all", _P("all"), lambda x: np.all(x, axis=1),
  [R(42).rand(3, 4) > 0.3], kwargs={"axis": 1})
C("any", _P("any"), lambda x: np.any(x, axis=1),
  [R(43).rand(3, 4) > 0.7], kwargs={"axis": 1})
C("count_nonzero", _P("count_nonzero"),
  lambda x: np.count_nonzero(x), [np.asarray(R(44).rand(3, 4) > 0.5,
                                             np.float64)], grad=False)

# ---- stat ---------------------------------------------------------------
C("std", _P("std"), lambda x: np.std(x, ddof=1), [_RX])
C("var", _P("var"), lambda x: np.var(x, ddof=1), [_RX])
C("median", _P("median"), np.median, [_arr(45, 3, 5)], grad=False)
C("nanmean", _P("nanmean"), np.nanmean, [_arr(46, 3, 4)])
C("nansum", _P("nansum"), np.nansum, [_arr(47, 3, 4)])
C("quantile", _P("quantile"), lambda x: np.quantile(x, 0.25),
  [_arr(48, 20)], kwargs={"q": 0.25}, grad=False)
C("kthvalue", _P("kthvalue"),
  lambda x: np.partition(x, 2, axis=-1)[..., 2], [_arr(49, 3, 7)],
  kwargs={"k": 3}, grad=False)

# ---- logic / compare -----------------------------------------------------
for name, ref in [
    ("equal", np.equal), ("not_equal", np.not_equal),
    ("greater_than", np.greater), ("greater_equal", np.greater_equal),
    ("less_than", np.less), ("less_equal", np.less_equal),
]:
    C(name, _P(name), ref, [_ints(50, 3, 4, hi=3), _ints(51, 3, 4, hi=3)])
C("logical_and", _P("logical_and"), np.logical_and,
  [R(52).rand(3, 4) > 0.5, R(53).rand(3, 4) > 0.5])
C("logical_or", _P("logical_or"), np.logical_or,
  [R(54).rand(3, 4) > 0.5, R(55).rand(3, 4) > 0.5])
C("logical_xor", _P("logical_xor"), np.logical_xor,
  [R(56).rand(3, 4) > 0.5, R(57).rand(3, 4) > 0.5])
C("logical_not", _P("logical_not"), np.logical_not,
  [R(58).rand(3, 4) > 0.5])
C("isnan", _P("isnan"), np.isnan,
  [np.array([1.0, np.nan, np.inf, -1.0])], grad=False)
C("isinf", _P("isinf"), np.isinf,
  [np.array([1.0, np.nan, np.inf, -1.0])], grad=False)
C("isfinite", _P("isfinite"), np.isfinite,
  [np.array([1.0, np.nan, np.inf, -1.0])], grad=False)
C("isclose", _P("isclose"), np.isclose,
  [np.array([1.0, 2.0, 3.0]), np.array([1.0, 2.0001, 4.0])], grad=False)

# ---- bitwise -------------------------------------------------------------
C("bitwise_and", _P("bitwise_and"), np.bitwise_and,
  [_ints(60, 3, 4, hi=16), _ints(61, 3, 4, hi=16)])
C("bitwise_or", _P("bitwise_or"), np.bitwise_or,
  [_ints(62, 3, 4, hi=16), _ints(63, 3, 4, hi=16)])
C("bitwise_xor", _P("bitwise_xor"), np.bitwise_xor,
  [_ints(64, 3, 4, hi=16), _ints(65, 3, 4, hi=16)])
C("bitwise_not", _P("bitwise_not"), np.bitwise_not,
  [_ints(66, 3, 4, hi=16)])

# ---- manipulation --------------------------------------------------------
_M = _arr(70, 2, 3, 4)
C("reshape", _P("reshape"), lambda x: x.reshape(3, 8), [_M],
  kwargs={"shape": [3, 8]})
C("reshape_infer", _P("reshape"), lambda x: x.reshape(4, -1), [_M],
  kwargs={"shape": [4, -1]})
C("transpose", _P("transpose"), lambda x: x.transpose(2, 0, 1), [_M],
  kwargs={"perm": [2, 0, 1]})
C("squeeze", _P("squeeze"), lambda x: x.squeeze(1), [_arr(71, 3, 1, 4)],
  kwargs={"axis": 1})
C("unsqueeze", _P("unsqueeze"), lambda x: x[:, None], [_arr(72, 3, 4)],
  kwargs={"axis": 1})
C("flatten", _P("flatten"), lambda x: x.reshape(2, -1), [_M],
  kwargs={"start_axis": 1, "stop_axis": 2})
C("concat", lambda a, b: paddle.concat([a, b], axis=1),
  lambda a, b: np.concatenate([a, b], axis=1),
  [_arr(73, 2, 3), _arr(74, 2, 5)])
C("stack", lambda a, b: paddle.stack([a, b], axis=0),
  lambda a, b: np.stack([a, b]), [_arr(75, 3, 4), _arr(76, 3, 4)])
C("split", lambda x: paddle.split(x, 2, axis=1)[1],
  lambda x: np.split(x, 2, axis=1)[1], [_arr(77, 3, 8)])
C("chunk", lambda x: paddle.chunk(x, 4, axis=0)[2],
  lambda x: np.split(x, 4, axis=0)[2], [_arr(78, 8, 3)])
C("tile", _P("tile"), lambda x: np.tile(x, (2, 3)), [_arr(79, 2, 3)],
  kwargs={"repeat_times": [2, 3]})
C("expand", _P("expand"), lambda x: np.broadcast_to(x, (4, 3, 2)),
  [_arr(80, 3, 2)], kwargs={"shape": [4, 3, 2]})
C("flip", _P("flip"), lambda x: np.flip(x, 1), [_M], kwargs={"axis": 1})
C("roll", _P("roll"), lambda x: np.roll(x, 2, 1), [_arr(81, 3, 5)],
  kwargs={"shifts": 2, "axis": 1})
C("repeat_interleave", _P("repeat_interleave"),
  lambda x: np.repeat(x, 3, axis=1), [_arr(82, 2, 3)],
  kwargs={"repeats": 3, "axis": 1})
C("broadcast_to", _P("broadcast_to"),
  lambda x: np.broadcast_to(x, (5, 2, 3)), [_arr(83, 2, 3)],
  kwargs={"shape": [5, 2, 3]})
C("rot90", _P("rot90"), lambda x: np.rot90(x, 1, (0, 1)), [_arr(84, 3, 4)])
C("moveaxis", _P("moveaxis"), lambda x: np.moveaxis(x, 0, 2), [_M],
  kwargs={"source": 0, "destination": 2})
C("as_strided_T", _P("t"), lambda x: x.T, [_arr(85, 3, 5)])
C("pad_spatial", lambda x: paddle.nn.functional.pad(
    x, [1, 2], value=0.5, data_format="NCL"),
  lambda x: np.pad(x, [(0, 0), (0, 0), (1, 2)], constant_values=0.5),
  [_arr(86, 2, 3, 4)])
C("pad_fullrank", lambda x: paddle.nn.functional.pad(x, [1, 0, 0, 2]),
  lambda x: np.pad(x, [(1, 0), (0, 2)]), [_arr(86, 2, 3)])
C("gather", lambda x: paddle.gather(x, paddle.to_tensor(
    np.array([2, 0, 1])), axis=0),
  lambda x: x[[2, 0, 1]], [_arr(87, 4, 3)])
C("index_select", lambda x: paddle.index_select(
    x, paddle.to_tensor(np.array([0, 2])), axis=1),
  lambda x: x[:, [0, 2]], [_arr(88, 3, 4)])
C("masked_select", lambda x: paddle.masked_select(
    x, paddle.to_tensor(np.asarray(
        [[True, False, True], [False, True, False]]))),
  lambda x: x[np.array([[True, False, True], [False, True, False]])],
  [_arr(89, 2, 3)], grad=False)
C("take_along_axis", lambda x: paddle.take_along_axis(
    x, paddle.to_tensor(_ints(90, 3, 2, hi=4)), axis=1),
  lambda x: np.take_along_axis(x, _ints(90, 3, 2, hi=4), axis=1),
  [_arr(91, 3, 4)])
C("diag", _P("diag"), np.diag, [_arr(92, 4)])
C("diagonal", _P("diagonal"), lambda x: np.diagonal(x, 0, 0, 1),
  [_arr(93, 3, 3)])
C("tril", _P("tril"), np.tril, [_arr(94, 4, 4)])
C("triu", _P("triu"), np.triu, [_arr(95, 4, 4)])
C("unbind", lambda x: paddle.unbind(x, axis=0)[1], lambda x: x[1],
  [_arr(96, 3, 4)])
C("where", lambda c, a, b: paddle.where(c, a, b), np.where,
  [R(97).rand(3, 4) > 0.5, _arr(98, 3, 4), _arr(99, 3, 4)])
C("clip", _P("clip"), lambda x: np.clip(x, -0.5, 0.5), [_X],
  kwargs={"min": -0.5, "max": 0.5})
C("cumsum", _P("cumsum"), lambda x: np.cumsum(x, 1), [_arr(100, 3, 4)],
  kwargs={"axis": 1})
C("cumprod", _P("cumprod"), lambda x: np.cumprod(x, 0),
  [_pos(101, 3, 4)], kwargs={"dim": 0})
C("cummax", _P("cummax"),
  lambda x: np.maximum.accumulate(x, 1), [_arr(102, 3, 4)],
  kwargs={"axis": 1}, grad=False)
C("cummin", _P("cummin"),
  lambda x: np.minimum.accumulate(x, 1), [_arr(103, 3, 4)],
  kwargs={"axis": 1}, grad=False)

# ---- search / sort -------------------------------------------------------
C("argmax", _P("argmax"), lambda x: np.argmax(x, 1), [_arr(110, 3, 5)],
  kwargs={"axis": 1}, grad=False)
C("argmin", _P("argmin"), lambda x: np.argmin(x, 1), [_arr(111, 3, 5)],
  kwargs={"axis": 1}, grad=False)
C("argsort", _P("argsort"), lambda x: np.argsort(x, 1, kind="stable"),
  [_arr(112, 3, 5)], kwargs={"axis": 1}, grad=False)
C("sort", _P("sort"), lambda x: np.sort(x, 1), [_arr(113, 3, 5)],
  kwargs={"axis": 1})
C("topk", lambda x: paddle.topk(x, 3)[0],
  lambda x: np.sort(x, -1)[..., ::-1][..., :3], [_arr(114, 2, 6)])
C("searchsorted", lambda s, v: paddle.searchsorted(s, v),
  lambda s, v: np.searchsorted(s, v),
  [np.sort(_arr(115, 8)), _arr(116, 5)], grad=False)
C("nonzero", lambda x: paddle.nonzero(x),
  lambda x: np.stack(np.nonzero(x), -1),
  [np.asarray(R(117).rand(3, 4) > 0.5, np.float64)], grad=False)
C("unique_sorted", lambda x: paddle.unique(x),
  lambda x: np.unique(x), [_ints(118, 12, hi=5)], grad=False)
C("index_sample", lambda x: paddle.index_sample(
    x, paddle.to_tensor(_ints(119, 3, 2, hi=4))),
  lambda x: np.take_along_axis(x, _ints(119, 3, 2, hi=4), axis=1),
  [_arr(120, 3, 4)])

# ---- linalg --------------------------------------------------------------
C("matmul", _P("matmul"), np.matmul, [_arr(130, 3, 4), _arr(131, 4, 5)])
C("matmul_bcast", _P("matmul"), np.matmul,
  [_arr(132, 2, 3, 4), _arr(133, 4, 5)])
C("dot", _P("dot"), lambda a, b: (a * b).sum(-1),
  [_arr(134, 5), _arr(135, 5)])
C("inner", _P("inner"), np.inner, [_arr(136, 3, 4), _arr(137, 2, 4)])
C("outer", _P("outer"), np.outer, [_arr(138, 3), _arr(139, 4)])
C("norm_fro", _P("norm"), lambda x: np.linalg.norm(x), [_arr(140, 3, 4)])
C("norm_1", lambda x: paddle.norm(x, p=1, axis=1),
  lambda x: np.abs(x).sum(1), [_arr(141, 3, 4, lo=0.2, hi=1.0)])
C("trace", _P("trace"), np.trace, [_arr(142, 4, 4)])
C("cholesky", _P("cholesky"),
  lambda x: np.linalg.cholesky(x),
  [np.eye(3) * 2 + 0.3 * (_arr(143, 3, 3) + _arr(143, 3, 3).T)],
  grad=False)
C("inverse", _P("inverse"), np.linalg.inv,
  [np.eye(3) * 2 + 0.1 * _arr(144, 3, 3)], grad=False)
C("matrix_power", lambda x: paddle.linalg.matrix_power(x, 3),
  lambda x: np.linalg.matrix_power(x, 3), [_arr(145, 3, 3)], grad=False)
C("solve", _P("linalg").solve if hasattr(_P("linalg"), "solve")
  else None, np.linalg.solve,
  [np.eye(3) * 2 + 0.1 * _arr(146, 3, 3), _arr(147, 3, 2)], grad=False)
C("cross", _P("cross"), lambda a, b: np.cross(a, b),
  [_arr(148, 4, 3), _arr(149, 4, 3)])
C("bmm", _P("bmm"), np.matmul, [_arr(150, 2, 3, 4), _arr(151, 2, 4, 5)])
C("mv", _P("mv"), np.matmul, [_arr(152, 3, 4), _arr(153, 4)])
C("kron", _P("kron"), np.kron, [_arr(154, 2, 2), _arr(155, 3, 2)])
C("einsum_ij", lambda a, b: paddle.einsum("ij,jk->ik", a, b),
  lambda a, b: a @ b, [_arr(156, 3, 4), _arr(157, 4, 2)])

# ---- creation ------------------------------------------------------------
C("zeros", lambda: paddle.zeros([3, 4]), lambda: np.zeros((3, 4)), [],
  grad=False)
C("ones", lambda: paddle.ones([2, 5]), lambda: np.ones((2, 5)), [],
  grad=False)
C("full", lambda: paddle.full([2, 3], 7.5),
  lambda: np.full((2, 3), 7.5), [], grad=False)
C("arange", lambda: paddle.arange(2, 14, 3),
  lambda: np.arange(2, 14, 3), [], grad=False)
C("linspace", lambda: paddle.linspace(0, 1, 7),
  lambda: np.linspace(0, 1, 7), [], grad=False)
C("eye", lambda: paddle.eye(4, 3), lambda: np.eye(4, 3), [], grad=False)
C("full_like", _P("full_like"), lambda x: np.full_like(x, 2.0),
  [_arr(160, 2, 3)], kwargs={"fill_value": 2.0}, grad=False)
C("zeros_like", _P("zeros_like"), np.zeros_like, [_arr(161, 2, 3)],
  grad=False)
C("ones_like", _P("ones_like"), np.ones_like, [_arr(162, 2, 3)],
  grad=False)
C("tril_indices", lambda: paddle.tril_indices(3, 3, 0),
  lambda: np.stack(np.tril_indices(3, 0, 3)), [], grad=False)
C("meshgrid", lambda a, b: paddle.meshgrid(a, b)[0],
  lambda a, b: np.meshgrid(a, b, indexing="ij")[0],
  [_arr(163, 3), _arr(164, 4)], grad=False)
C("diagflat", _P("diagflat"), np.diagflat, [_arr(165, 3)], grad=False)

# ---- activations (nn.functional) ----------------------------------------
_AX = _arr(170, 3, 5)
C("relu", _F("relu"), lambda x: np.maximum(x, 0),
  [_arr(171, 3, 5, lo=0.05, hi=1.0) * np.where(
      R(172).rand(3, 5) > 0.5, 1, -1)])
C("relu6", _F("relu6"), lambda x: np.clip(x, 0, 6), [_AX * 4])
C("leaky_relu", _F("leaky_relu"),
  lambda x: np.where(x > 0, x, 0.01 * x), [_AX])
C("elu", _F("elu"), lambda x: np.where(x > 0, x, np.expm1(x)), [_AX])
C("selu", _F("selu"),
  lambda x: 1.0507009873554805 * np.where(
      x > 0, x, 1.6732632423543772 * np.expm1(x)), [_AX])
C("celu", _F("celu"), lambda x: np.where(x > 0, x, np.expm1(x)), [_AX])
C("gelu_exact", _F("gelu"), lambda x: 0.5 * x * (1 + _erf(x / np.sqrt(2))),
  [_AX])
C("gelu_tanh", lambda x: nn.functional.gelu(x, approximate=True),
  lambda x: 0.5 * x * (1 + np.tanh(
      np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3))), [_AX])
C("sigmoid", _F("sigmoid"), _sigmoid, [_AX])
C("hardsigmoid", _F("hardsigmoid"),
  lambda x: np.clip(x / 6 + 0.5, 0, 1), [_AX * 4])
C("hardswish", _F("hardswish"),
  lambda x: x * np.clip(x + 3, 0, 6) / 6, [_AX * 2])
C("hardtanh", _F("hardtanh"), lambda x: np.clip(x, -1, 1), [_AX * 2])
C("softplus", _F("softplus"), lambda x: np.log1p(np.exp(x)), [_AX])
C("softsign", _F("softsign"), lambda x: x / (1 + np.abs(x)), [_AX])
C("silu", _F("silu"), lambda x: x * _sigmoid(x), [_AX])
C("mish", _F("mish"),
  lambda x: x * np.tanh(np.log1p(np.exp(x))), [_AX])
C("swish", _F("swish"), lambda x: x * _sigmoid(x), [_AX])
C("tanhshrink", _F("tanhshrink"), lambda x: x - np.tanh(x), [_AX])
C("softshrink", _F("softshrink"),
  lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0)),
  [_AX * 2])
C("hardshrink", _F("hardshrink"),
  lambda x: np.where(np.abs(x) > 0.5, x, 0), [_AX * 2])
C("softmax", _F("softmax"), _softmax, [_AX])
C("log_softmax", _F("log_softmax"),
  lambda x: np.log(_softmax(x)), [_AX])
C("log_sigmoid", _F("log_sigmoid"),
  lambda x: -np.log1p(np.exp(-x)), [_AX])
C("thresholded_relu", _F("thresholded_relu"),
  lambda x: np.where(x > 1.0, x, 0), [_AX * 3])
C("prelu", lambda x: nn.functional.prelu(
    x, paddle.to_tensor(np.asarray([0.2]))),
  lambda x: np.where(x > 0, x, 0.2 * x), [_AX])

# ---- losses vs hand formulas ---------------------------------------------
_LOGITS = _arr(180, 4, 6)
_ONEHOT = np.eye(6)[_ints(181, 4, hi=6)]
C("mse_loss", lambda a, b: nn.functional.mse_loss(a, b),
  lambda a, b: np.mean((a - b) ** 2), [_arr(182, 4, 3), _arr(183, 4, 3)])
C("l1_loss", lambda a, b: nn.functional.l1_loss(a, b),
  lambda a, b: np.mean(np.abs(a - b)),
  [_arr(184, 4, 3), _arr(184, 4, 3) + _pos(185, 4, 3)])
C("smooth_l1", lambda a, b: nn.functional.smooth_l1_loss(a, b),
  lambda a, b: np.mean(np.where(np.abs(a - b) < 1,
                                0.5 * (a - b) ** 2,
                                np.abs(a - b) - 0.5)),
  [_arr(186, 4, 3) * 3, _arr(187, 4, 3)])
C("bce_with_logits",
  lambda x, y: nn.functional.binary_cross_entropy_with_logits(x, y),
  lambda x, y: np.mean(np.maximum(x, 0) - x * y + np.log1p(
      np.exp(-np.abs(x)))),
  [_LOGITS, np.asarray(R(188).rand(4, 6) > 0.5, np.float64)])
C("kl_div", lambda x, y: nn.functional.kl_div(x, y, reduction="mean"),
  lambda x, y: np.mean(y * (np.log(y) - x)),
  [np.log(_softmax(_arr(189, 4, 6))), _softmax(_arr(190, 4, 6))])
C("cross_entropy_soft",
  lambda x, y: nn.functional.cross_entropy(x, y, soft_label=True),
  lambda x, y: np.mean(-(y * np.log(_softmax(x))).sum(-1)),
  [_LOGITS, _ONEHOT])

# ---- misc tensor methods -------------------------------------------------
C("lerp", _P("lerp"), lambda a, b, w: a + w * (b - a),
  [_arr(191, 3, 4), _arr(192, 3, 4), _pos(193, 3, 4, lo=0.1, hi=0.9)])
C("addmm", lambda i, a, b: paddle.addmm(i, a, b, beta=0.5, alpha=2.0),
  lambda i, a, b: 0.5 * i + 2.0 * (a @ b),
  [_arr(194, 3, 5), _arr(195, 3, 4), _arr(196, 4, 5)])
C("diff", _P("diff"), lambda x: np.diff(x, axis=-1), [_arr(198, 3, 5)])
C("sgn_float", _P("sgn"), np.sign, [_arr(199, 3, 4, lo=0.2, hi=1.0)],
  grad=False)
C("frac", _P("frac"), lambda x: x - np.trunc(x), [_arr(200, 3, 4) * 3])
C("nan_to_num", _P("nan_to_num"), np.nan_to_num,
  [np.array([1.0, np.nan, np.inf, -np.inf])], grad=False)
C("angle_real", _P("angle"), np.angle,
  [_arr(201, 3, 4, lo=0.2, hi=1.0)], grad=False)
C("conj_real", _P("conj"), np.conj, [_arr(202, 3, 4)])
C("real_of_complex", _P("real"), np.real,
  [_arr(203, 3, 4) + 1j * _arr(204, 3, 4)], grad=False)
C("imag_of_complex", _P("imag"), np.imag,
  [_arr(205, 3, 4) + 1j * _arr(206, 3, 4)], grad=False)

CASES = [c for c in CASES if c.fn is not None]


def _np(x):
    if hasattr(x, "numpy"):
        return np.asarray(x.numpy())
    return np.asarray(x)


def _first(out):
    if isinstance(out, (list, tuple)):
        return out[0]
    return out


@pytest.mark.parametrize("case", CASES, ids=repr)
def test_forward(case):
    args = [paddle.to_tensor(a) for a in case.inputs]
    got = _np(_first(case.fn(*args, **case.kwargs)))
    want = np.asarray(case.ref(*case.inputs))
    assert got.shape == tuple(want.shape), (got.shape, want.shape)
    if want.dtype.kind in "fc":
        np.testing.assert_allclose(got, want, rtol=case.rtol,
                                   atol=case.atol, err_msg=case.name)
    else:
        np.testing.assert_array_equal(got, want, err_msg=case.name)


GRAD_CASES = [c for c in CASES if c.grad and c.inputs]


@pytest.mark.parametrize("case", GRAD_CASES, ids=repr)
def test_grad(case):
    rng = R(1234)
    out0 = case.ref(*case.inputs)
    cot = rng.randn(*np.asarray(out0).shape)

    def loss_np(*arrays):
        return float((np.asarray(case.ref(*arrays)) * cot).sum())

    # analytic grads via the tape
    ts = [paddle.to_tensor(a) for a in case.inputs]
    for t in ts:
        t.stop_gradient = False
    out = _first(case.fn(*ts, **case.kwargs))
    loss = (out * paddle.to_tensor(cot)).sum()
    loss.backward()

    # directional derivative check per differentiable input
    eps = case.eps
    for i, a in enumerate(case.inputs):
        if a.dtype.kind != "f":
            continue
        d = rng.randn(*a.shape)
        plus = [x.copy() for x in case.inputs]
        minus = [x.copy() for x in case.inputs]
        plus[i] = a + eps * d
        minus[i] = a - eps * d
        numeric = (loss_np(*plus) - loss_np(*minus)) / (2 * eps)
        g = ts[i].grad
        assert g is not None, f"{case.name}: input {i} got no grad"
        analytic = float((_np(g) * d).sum())
        denom = max(abs(numeric), abs(analytic), 1.0)
        assert abs(numeric - analytic) / denom < case.grad_tol, (
            f"{case.name} input {i}: analytic {analytic} vs numeric "
            f"{numeric}")


# ---- pooling / norm / interpolate (appended batch 2) ---------------------
def _maxpool2d_np(x, k, s):
    n, c, h, w = x.shape
    oh, ow = (h - k) // s + 1, (w - k) // s + 1
    out = np.full((n, c, oh, ow), -np.inf)
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = x[:, :, i * s:i * s + k,
                                j * s:j * s + k].max((2, 3))
    return out


def _avgpool2d_np(x, k, s):
    n, c, h, w = x.shape
    oh, ow = (h - k) // s + 1, (w - k) // s + 1
    out = np.zeros((n, c, oh, ow))
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = x[:, :, i * s:i * s + k,
                                j * s:j * s + k].mean((2, 3))
    return out


def _layer_norm_np(x, w, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * w + b


_P2 = _arr(210, 2, 3, 8, 8)
C("max_pool2d", lambda x: nn.functional.max_pool2d(x, 2, 2),
  lambda x: _maxpool2d_np(x, 2, 2), [_P2])
C("avg_pool2d", lambda x: nn.functional.avg_pool2d(x, 2, 2),
  lambda x: _avgpool2d_np(x, 2, 2), [_P2])
C("max_pool2d_k3s1", lambda x: nn.functional.max_pool2d(x, 3, 1),
  lambda x: _maxpool2d_np(x, 3, 1), [_arr(211, 2, 2, 6, 6)])
C("adaptive_avg_pool2d_1",
  lambda x: nn.functional.adaptive_avg_pool2d(x, 1),
  lambda x: x.mean((2, 3), keepdims=True), [_P2])
C("layer_norm",
  lambda x, w, b: nn.functional.layer_norm(x, 6, w, b),
  _layer_norm_np, [_arr(212, 4, 6), _pos(213, 6), _arr(214, 6)],
  rtol=1e-4, atol=1e-6)
C("normalize_l2", lambda x: nn.functional.normalize(x),
  lambda x: x / np.sqrt((x ** 2).sum(-1, keepdims=True)).clip(1e-12),
  [_arr(215, 3, 5)])
C("interp_nearest_2x",
  lambda x: nn.functional.interpolate(x, scale_factor=2, mode="nearest"),
  lambda x: x.repeat(2, axis=2).repeat(2, axis=3), [_arr(216, 2, 2, 3, 3)])
C("pixel_shuffle", lambda x: nn.functional.pixel_shuffle(x, 2),
  lambda x: x.reshape(1, 2, 2, 2, 3, 3).transpose(0, 1, 4, 2, 5, 3)
  .reshape(1, 2, 6, 6), [_arr(217, 1, 8, 3, 3)])
C("one_hot", lambda x: nn.functional.one_hot(x, 5),
  lambda x: np.eye(5)[x], [_ints(218, 6, hi=5)], grad=False)
C("embedding", lambda ids: nn.functional.embedding(
    ids, paddle.to_tensor(_arr(219, 10, 4))),
  lambda ids: _arr(219, 10, 4)[ids], [_ints(220, 3, 5, hi=10)],
  grad=False)
C("cosine_similarity",
  lambda a, b: nn.functional.cosine_similarity(a, b),
  lambda a, b: (a * b).sum(-1) / (
      np.sqrt((a ** 2).sum(-1)) * np.sqrt((b ** 2).sum(-1))),
  [_arr(221, 3, 6), _arr(222, 3, 6)])
C("pairwise_distance",
  lambda a, b: nn.PairwiseDistance()(a, b),
  lambda a, b: np.sqrt(((a - b) ** 2).sum(-1)),
  [_arr(223, 3, 6), _arr(224, 3, 6)])
C("glu", lambda x: nn.functional.glu(x),
  lambda x: x[..., :3] * _sigmoid(x[..., 3:]), [_arr(225, 4, 6)])
C("dropout_eval", lambda x: nn.functional.dropout(x, 0.5, training=False),
  lambda x: x, [_arr(226, 3, 4)])


# ---- bf16 smoke: the dtype the MXU actually runs ------------------------
_BF16_OPS = ["exp", "log", "sqrt", "tanh", "sigmoid", "erf", "sin", "cos",
             "abs", "square", "rsqrt", "log1p"]


@pytest.mark.parametrize("name", _BF16_OPS)
def test_bf16_forward(name):
    """Key unary ops stay finite and near-f32 in bf16 (TPU hot dtype)."""
    import jax.numpy as jnp

    x32 = _pos(777, 4, 8, lo=0.3, hi=1.7).astype(np.float32)
    fn = _P(name) if hasattr(paddle, name) else _F(name)
    t_bf16 = paddle.to_tensor(jnp.asarray(x32, jnp.bfloat16))
    t_f32 = paddle.to_tensor(x32)
    out_bf = np.asarray(fn(t_bf16).numpy(), np.float32)
    out_f32 = np.asarray(fn(t_f32).numpy(), np.float32)
    assert np.isfinite(out_bf).all()
    np.testing.assert_allclose(out_bf, out_f32, rtol=2e-2, atol=2e-2)


def test_bf16_matmul_accumulates_f32():
    """bf16 matmul with preferred f32 accumulation keeps large-K sums
    accurate (MXU behavior contract)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(5)
    a = rng.randn(8, 2048).astype(np.float32)
    b = rng.randn(2048, 8).astype(np.float32)
    a_bf = jnp.asarray(a, jnp.bfloat16)
    b_bf = jnp.asarray(b, jnp.bfloat16)
    got = np.asarray(
        (paddle.to_tensor(a_bf) @ paddle.to_tensor(b_bf)).numpy(),
        np.float32)
    # reference: the SAME rounded inputs accumulated exactly — isolates
    # accumulation error from the unavoidable bf16 input rounding
    want = np.asarray(a_bf, np.float64) @ np.asarray(b_bf, np.float64)
    rel = np.abs(got - want) / (np.abs(want) + 1.0)
    assert rel.max() < 0.02, rel.max()


# ---- fft family vs numpy -------------------------------------------------
_CX = _arr(300, 3, 8) + 1j * _arr(301, 3, 8)
_RX2 = _arr(302, 3, 8)


def _fft_cases():
    cases = [
        ("fft", lambda x: paddle.fft.fft(x), np.fft.fft, _CX),
        ("ifft", lambda x: paddle.fft.ifft(x), np.fft.ifft, _CX),
        ("rfft", lambda x: paddle.fft.rfft(x), np.fft.rfft, _RX2),
        ("irfft", lambda x: paddle.fft.irfft(x),
         lambda x: np.fft.irfft(x), _CX[:, :5]),
        ("fft2", lambda x: paddle.fft.fft2(x), np.fft.fft2,
         _arr(303, 4, 4) + 1j * _arr(304, 4, 4)),
        ("fftshift", lambda x: paddle.fft.fftshift(x), np.fft.fftshift,
         _RX2),
        ("ifftshift", lambda x: paddle.fft.ifftshift(x), np.fft.ifftshift,
         _RX2),
        ("hfft", lambda x: paddle.fft.hfft(x), np.fft.hfft, _CX[:, :5]),
        ("fftfreq", lambda: paddle.fft.fftfreq(8, 0.5),
         lambda: np.fft.fftfreq(8, 0.5), None),
        ("rfftfreq", lambda: paddle.fft.rfftfreq(8, 0.5),
         lambda: np.fft.rfftfreq(8, 0.5), None),
    ]
    return cases


@pytest.mark.parametrize("case", _fft_cases(), ids=lambda c: c[0])
def test_fft_forward(case):
    name, fn, ref, inp = case
    if inp is None:
        got = np.asarray(fn().numpy())
        want = ref()
    else:
        got = np.asarray(fn(paddle.to_tensor(inp)).numpy())
        want = ref(inp)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-8,
                               err_msg=name)


# ---- linalg decompositions vs numpy -------------------------------------
def test_linalg_svd_qr_eigh():
    rng = np.random.RandomState(310)
    a = rng.randn(4, 3)
    u, s, vh = (np.asarray(t.numpy()) for t in
                paddle.linalg.svd(paddle.to_tensor(a), full_matrices=False))
    np.testing.assert_allclose(u @ np.diag(s) @ vh, a, atol=1e-8)
    q, r = (np.asarray(t.numpy()) for t in
            paddle.linalg.qr(paddle.to_tensor(a)))
    np.testing.assert_allclose(q @ r, a, atol=1e-8)
    sym = a.T @ a
    w, v = (np.asarray(t.numpy()) for t in
            paddle.linalg.eigh(paddle.to_tensor(sym)))
    np.testing.assert_allclose(v @ np.diag(w) @ v.T, sym, atol=1e-7)


def test_linalg_lstsq_det_slogdet():
    rng = np.random.RandomState(311)
    a = rng.randn(5, 3)
    b = rng.randn(5, 2)
    sol = paddle.linalg.lstsq(paddle.to_tensor(a), paddle.to_tensor(b))
    x = np.asarray(sol[0].numpy())
    np.testing.assert_allclose(x, np.linalg.lstsq(a, b, rcond=None)[0],
                               atol=1e-7)
    m = np.eye(3) * 2 + 0.1 * rng.randn(3, 3)
    det = float(paddle.linalg.det(paddle.to_tensor(m)).numpy())
    np.testing.assert_allclose(det, np.linalg.det(m), rtol=1e-6)
    sign, logd = np.linalg.slogdet(m)
    sarr = np.asarray(
        paddle.linalg.slogdet(paddle.to_tensor(m)).numpy()).reshape(-1)
    np.testing.assert_allclose(sarr[0], sign, rtol=1e-6)
    np.testing.assert_allclose(sarr[1], logd, rtol=1e-6)
