"""Legacy sequence ops over the padded-dense + lengths carrier
(reference: fluid/layers/sequence_lod.py — see static/sequence_ops.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


@pytest.fixture
def seq():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(3, 5, 4).astype(np.float32))
    lengths = paddle.to_tensor(np.array([5, 3, 1], np.int64))
    return x, lengths, rng


class TestSequencePool:
    def test_pool_types(self, seq):
        x, ln, _ = seq
        xn = np.asarray(x.numpy())
        lnn = np.asarray(ln.numpy())
        out_sum = np.asarray(snn.sequence_pool(x, "sum", lengths=ln).numpy())
        for i, l in enumerate(lnn):
            np.testing.assert_allclose(out_sum[i], xn[i, :l].sum(0),
                                       rtol=1e-5)
        out_avg = np.asarray(snn.sequence_pool(x, "average",
                                               lengths=ln).numpy())
        np.testing.assert_allclose(out_avg[1], xn[1, :3].mean(0), rtol=1e-5)
        out_max = np.asarray(snn.sequence_pool(x, "max", lengths=ln).numpy())
        np.testing.assert_allclose(out_max[2], xn[2, :1].max(0), rtol=1e-5)
        out_last = np.asarray(snn.sequence_last_step(x, lengths=ln).numpy())
        np.testing.assert_allclose(out_last[1], xn[1, 2], rtol=1e-6)
        out_first = np.asarray(snn.sequence_first_step(x).numpy())
        np.testing.assert_allclose(out_first, xn[:, 0], rtol=1e-6)

    def test_softmax_masks_padding(self, seq):
        x, ln, _ = seq
        out = np.asarray(snn.sequence_softmax(x, lengths=ln).numpy())
        np.testing.assert_allclose(out.sum(1), np.ones((3, 4)), rtol=1e-5)
        assert (out[2, 1:] == 0).all()  # beyond length -> zero prob

    def test_reverse_respects_lengths(self, seq):
        x, ln, _ = seq
        xn = np.asarray(x.numpy())
        out = np.asarray(snn.sequence_reverse(x, lengths=ln).numpy())
        np.testing.assert_allclose(out[1, :3], xn[1, :3][::-1], rtol=1e-6)
        np.testing.assert_allclose(out[1, 3:], xn[1, 3:], rtol=1e-6)


class TestPadUnpad:
    def test_round_trip(self):
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(2, 4, 3).astype(np.float32))
        padded, lengths = snn.sequence_pad(x, 0.0, maxlen=6)
        assert padded.shape == [2, 6, 3]
        flat = snn.sequence_unpad(padded,
                                  paddle.to_tensor(np.array([4, 2])))
        assert flat.shape == [6, 3]
        np.testing.assert_allclose(np.asarray(flat.numpy())[:4],
                                   np.asarray(x.numpy())[0], rtol=1e-6)


class TestMiscOps:
    def test_sequence_conv_shape(self, seq):
        x, _, _ = seq
        paddle.seed(0)
        out = snn.sequence_conv(x, num_filters=8, filter_size=3)
        assert out.shape == [3, 5, 8]

    def test_crf_decoding(self):
        rng = np.random.RandomState(2)
        emis = paddle.to_tensor(rng.randn(2, 6, 4).astype(np.float32))
        trans = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
        path = snn.crf_decoding(emis, transition=trans)
        arr = np.asarray(path.numpy())
        assert arr.shape == (2, 6)
        assert ((arr >= 0) & (arr < 4)).all()

    def test_nce_loss(self):
        rng = np.random.RandomState(3)
        h = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 50, (8, 1)))
        w = paddle.to_tensor(rng.randn(50, 16).astype(np.float32) * 0.1)
        h.stop_gradient = False
        w.stop_gradient = False
        loss = snn.nce(h, y, 50, num_neg_samples=5, weight=w)
        assert loss.shape == [8, 1]
        loss.sum().backward()
        assert h.grad is not None and w.grad is not None

    def test_sparse_embedding(self):
        paddle.seed(0)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 0]], np.int64))
        out = snn.sparse_embedding(ids, size=[10, 6])
        assert out.shape == [2, 2, 6]

    def test_prior_box(self):
        fmap = paddle.randn([1, 8, 4, 4])
        img = paddle.randn([1, 3, 64, 64])
        boxes, var = snn.prior_box(fmap, img, min_sizes=[16.0],
                                   aspect_ratios=[1.0, 2.0], flip=True,
                                   clip=True)
        assert boxes.shape == [4, 4, 3, 4]
        b = np.asarray(boxes.numpy())
        assert (b >= 0).all() and (b <= 1).all()

    def test_sequence_enumerate(self):
        x = paddle.to_tensor(np.arange(10).reshape(2, 5))
        out = np.asarray(snn.sequence_enumerate(x, 2).numpy())
        assert out.shape[0] == 2


class TestReviewRegressions:
    def test_pool_zero_length_pad_value(self):
        x = paddle.to_tensor(np.ones((2, 3, 2), np.float32))
        ln = paddle.to_tensor(np.array([3, 0], np.int64))
        out = np.asarray(snn.sequence_pool(x, "max", pad_value=-1.0,
                                           lengths=ln).numpy())
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out[1], -1.0)

    def test_crf_start_stop_folded(self):
        rng = np.random.RandomState(5)
        emis = paddle.to_tensor(rng.randn(1, 4, 3).astype(np.float32))
        base = rng.randn(3, 3).astype(np.float32)
        # huge start weight for tag 2 must force the first tag
        full = np.concatenate([np.array([[-10, -10, 50]], np.float32),
                               np.zeros((1, 3), np.float32), base])
        path = np.asarray(snn.crf_decoding(
            emis, transition=paddle.to_tensor(full)).numpy())
        assert path[0, 0] == 2

    def test_multi_box_head_layout(self):
        paddle.seed(0)
        fmaps = [paddle.randn([2, 8, 4, 4]), paddle.randn([2, 8, 2, 2])]
        img = paddle.randn([2, 3, 64, 64])
        locs, confs, boxes, var = snn.multi_box_head(
            fmaps, img, base_size=64, num_classes=5,
            aspect_ratios=[[1.0, 2.0], [1.0, 2.0]],
            min_sizes=[[16.0], [32.0]], flip=True, steps=[[16], [32]])
        n_total = boxes.shape[0]
        assert locs.shape == [2, n_total, 4]
        assert confs.shape == [2, n_total, 5]
        assert var.shape == [n_total, 4]

    def test_nce_seeded_stream(self):
        from paddle_tpu.static import sequence_ops as sops

        rng = np.random.RandomState(6)
        h = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 20, (4, 1)))
        w = paddle.to_tensor(rng.randn(20, 8).astype(np.float32))
        sops._nce_counters.clear()
        run1 = [np.asarray(snn.nce(h, y, 20, weight=w, seed=7).numpy())
                for _ in range(2)]
        sops._nce_counters.clear()
        run2 = [np.asarray(snn.nce(h, y, 20, weight=w, seed=7).numpy())
                for _ in range(2)]
        # reproducible stream across runs...
        np.testing.assert_array_equal(run1[0], run2[0])
        np.testing.assert_array_equal(run1[1], run2[1])
        # ...but fresh negatives per step within a run
        assert not np.array_equal(run1[0], run1[1])
        dist = np.ones(20) / 20
        l3 = snn.nce(h, y, 20, weight=w, sampler="custom_dist",
                     custom_dist=dist, seed=7)
        assert l3.shape == [4, 1]

    def test_viterbi_lengths_mask_padding(self):
        """Padded emissions must not change the decoded prefix."""
        from paddle_tpu.text import viterbi_decode

        rng = np.random.RandomState(8)
        emis_a = rng.randn(1, 5, 3).astype(np.float32)
        emis_b = emis_a.copy()
        emis_b[:, 3:] = 100.0 * rng.randn(1, 2, 3)  # wild padding values
        trans = rng.randn(3, 3).astype(np.float32)
        ln = paddle.to_tensor(np.array([3], np.int64))
        _, p_a = viterbi_decode(paddle.to_tensor(emis_a),
                                paddle.to_tensor(trans), lengths=ln)
        _, p_b = viterbi_decode(paddle.to_tensor(emis_b),
                                paddle.to_tensor(trans), lengths=ln)
        np.testing.assert_array_equal(np.asarray(p_a.numpy())[:, :3],
                                      np.asarray(p_b.numpy())[:, :3])
