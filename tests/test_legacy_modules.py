"""Legacy compat modules: paddle.reader decorators, paddle.dataset
reader creators, paddle.cost_model (reference: python/paddle/reader/,
dataset/, cost_model/)."""
import numpy as np

import paddle_tpu as paddle


class TestReader:
    def test_map_shuffle_firstn_buffered_cache(self):
        r = lambda: iter(range(10))
        doubled = paddle.reader.map_readers(lambda x: x * 2, r)
        assert list(doubled()) == [x * 2 for x in range(10)]
        assert sorted(paddle.reader.shuffle(r, 4)()) == list(range(10))
        assert list(paddle.reader.firstn(r, 3)()) == [0, 1, 2]
        assert list(paddle.reader.buffered(r, 2)()) == list(range(10))
        c = paddle.reader.cache(r)
        assert list(c()) == list(range(10)) == list(c())

    def test_chain_compose(self):
        a = lambda: iter([1, 2])
        b = lambda: iter([3, 4])
        assert list(paddle.reader.chain(a, b)()) == [1, 2, 3, 4]
        assert list(paddle.reader.compose(a, b)()) == [(1, 3), (2, 4)]

    def test_xmap_ordered(self):
        r = lambda: iter(range(20))
        out = list(paddle.reader.xmap_readers(
            lambda x: x + 100, r, process_num=4, buffer_size=8,
            order=True)())
        assert out == [x + 100 for x in range(20)]

    def test_xmap_unordered_complete(self):
        r = lambda: iter(range(20))
        out = sorted(paddle.reader.xmap_readers(
            lambda x: x * 3, r, process_num=3, buffer_size=4)())
        assert out == [x * 3 for x in range(20)]


class TestDataset:
    def test_uci_housing_reader(self):
        reader = paddle.dataset.uci_housing.train()
        x, y = next(reader())
        assert x.shape == (13,) and y.shape == (1,)

    def test_imdb_reader_and_dict(self):
        d = paddle.dataset.imdb.word_dict()
        assert "<unk>" in d
        doc, label = next(paddle.dataset.imdb.train(d)())
        assert label.shape == (1,)

    def test_mnist_reader(self):
        img, label = next(paddle.dataset.mnist.train()())
        assert np.prod(np.asarray(img).shape) in (784, 28 * 28)

    def test_download_disabled(self):
        import pytest

        with pytest.raises(RuntimeError):
            paddle.dataset.common.download("http://x", "m", "0")


class TestCostModel:
    def test_static_op_time_and_profile(self):
        cm = paddle.cost_model.CostModel()
        try:
            t = cm.get_static_op_time("matmul")
            assert t["op_time"] > 0 and "config" in t
            # memoized
            assert cm.get_static_op_time("matmul") == t
            # no measurement recipe -> empty dict (reference contract)
            assert cm.get_static_op_time("no_such_op") == {}
            data = cm.static_cost_data()
            relu = next(d for d in data if d["op"] == "relu")
            assert relu["paddle_gpu_time"] > 0
            startup, main = cm.build_program()
            cost = cm.profile_measure(startup, main)
            assert cost["time"] > 0
        finally:
            # build_program flips global static mode (reference does too)
            paddle.disable_static()


def test_compose_alignment_contract():
    """check_alignment=True raises on misaligned readers; False silently
    truncates (reference decorator.py:293)."""
    import pytest

    a = lambda: iter([1, 2, 3])
    b = lambda: iter([4, 5])
    with pytest.raises(paddle.reader.ComposeNotAligned):
        list(paddle.reader.compose(a, b)())
    assert list(paddle.reader.compose(a, b, check_alignment=False)()) == \
        [(1, 4), (2, 5)]


def test_dataset_args_respected():
    import pytest

    # n -> window size
    sample = next(paddle.dataset.imikolov.train(None, n=3)())
    assert len(sample) == 3
    # foreign dict -> loud error, not silent divergence
    with pytest.raises(NotImplementedError):
        next(paddle.dataset.imdb.train({"bogus": 0})())
    # cycle=True wraps around
    import itertools

    r = paddle.dataset.cifar.train10(cycle=True)
    n_base = sum(1 for _ in paddle.dataset.cifar.train10()())
    got = list(itertools.islice(r(), n_base + 3))
    assert len(got) == n_base + 3


def test_reader_exceptions_propagate():
    """A raising mapper/reader must not deadlock the consumer (round-3
    review regression)."""
    import pytest

    def bad_reader():
        yield 1
        raise ValueError("source died")

    with pytest.raises(ValueError, match="source died"):
        list(paddle.reader.buffered(bad_reader, 2)())

    def bad_mapper(x):
        raise RuntimeError("mapper died")

    with pytest.raises(RuntimeError, match="mapper died"):
        list(paddle.reader.xmap_readers(bad_mapper, lambda: iter(range(4)),
                                        2, 2)())


def test_profile_measure_has_flops():
    cm = paddle.cost_model.CostModel()
    try:
        startup, main = cm.build_program()
        cost = cm.profile_measure(startup, main)
        assert cost["time"] > 0
        assert cost.get("flops", 0) > 0, cost
    finally:
        paddle.disable_static()


class TestDistributedUtils:
    def test_cluster_topology(self):
        from paddle_tpu.distributed import utils as dutils

        eps = [["127.0.0.1:6170", "127.0.0.1:6171"],
               ["10.0.0.2:6170", "10.0.0.2:6171"]]
        cluster, pod = dutils.get_cluster(
            ["127.0.0.1", "10.0.0.2"], "127.0.0.1", eps, [0, 1])
        assert cluster.trainers_nranks() == 4
        assert pod.rank == 0 and len(pod.trainers) == 2
        assert cluster.trainers_endpoints()[2] == "10.0.0.2:6170"
        assert pod.trainers[0].gpus == [0]
        assert cluster.get_pod_by_id(1).addr == "10.0.0.2"
        ports = dutils.find_free_ports(3)
        assert len(ports) == 3

    def test_start_and_watch_local_trainers(self, tmp_path):
        from paddle_tpu.distributed import utils as dutils

        script = tmp_path / "worker.py"
        script.write_text(
            "import os\n"
            "print('rank', os.environ['PADDLE_TRAINER_ID'])\n")
        cluster, pod = dutils.get_cluster(
            ["127.0.0.1"], "127.0.0.1",
            [["127.0.0.1:6180", "127.0.0.1:6181"]], [0, 1])
        procs = dutils.start_local_trainers(
            cluster, pod, str(script), [], log_dir=str(tmp_path / "logs"))
        import time

        deadline = time.time() + 30
        alive = procs
        while alive and time.time() < deadline:
            alive = dutils.watch_local_trainers(procs, 2)
            time.sleep(0.2)
        assert not alive
        logs = sorted((tmp_path / "logs").glob("workerlog.*"))
        assert len(logs) == 2


class TestMoEHelpers:
    def test_number_count_and_assign_pos(self):
        import paddle_tpu.distributed.models.moe as moe_utils

        ids = paddle.to_tensor(np.array([1, 0, 2, 1, 1], np.int64))
        counts = moe_utils._number_count(ids, 4)
        np.testing.assert_array_equal(np.asarray(counts.numpy()),
                                      [1, 3, 1, 0])
        cum = paddle.to_tensor(np.cumsum(np.asarray(counts.numpy())))
        pos = moe_utils._assign_pos(ids, cum)
        sorted_ids = np.asarray(ids.numpy())[np.asarray(pos.numpy())]
        assert (np.diff(sorted_ids) >= 0).all()
        # capacity-clipped layout: only cum[-1] slots survive, overflow
        # tokens of each expert dropped
        clipped = np.array([1, 2, 1, 0])  # expert 1 capped at 2 (was 3)
        cum_c = paddle.to_tensor(np.cumsum(clipped))
        pos_c = np.asarray(moe_utils._assign_pos(ids, cum_c).numpy())
        assert pos_c.shape == (4,)
        ids_np = np.asarray(ids.numpy())
        assert (ids_np[pos_c] == np.array([1, 0, 1, 2])[
            np.argsort(np.array([1, 0, 1, 2]), kind="stable")]).all() or             sorted(ids_np[pos_c].tolist()) == [0, 1, 1, 2]

    def test_limit_and_prune(self):
        import paddle_tpu.distributed.models.moe as moe_utils

        ec = paddle.to_tensor(np.array([3, 5, 2, 0], np.int64))  # 2 workers x 2 experts
        cap = paddle.to_tensor(np.array([4, 4], np.int64))
        out = np.asarray(moe_utils._limit_by_capacity(ec, cap, 2).numpy())
        assert out.sum() <= 8
        assert (out <= np.array([3, 4, 2, 0])).all()

        gates = paddle.to_tensor(np.array([0, 0, 0, 1], np.int64))
        ec2 = paddle.to_tensor(np.array([2, 2], np.int64))
        pruned = np.asarray(moe_utils._prune_gate_by_capacity(
            gates, ec2, 2, 1).numpy())
        np.testing.assert_array_equal(pruned, [0, 0, -1, 1])

    def test_random_routing(self):
        import paddle_tpu.distributed.models.moe as moe_utils

        idx = paddle.to_tensor(np.array([[0, 1], [2, 3]], np.int64))
        val = paddle.to_tensor(np.array([[0.9, 0.6], [0.8, 0.1]],
                                        np.float32))
        prob = paddle.to_tensor(np.array([0.5, 0.9], np.float32))
        out = np.asarray(moe_utils._random_routing(idx, val, prob).numpy())
        np.testing.assert_array_equal(out, [[0, 1], [2, -1]])


def test_paddle_compat():
    from paddle_tpu import compat

    assert compat.to_text(b"abc") == "abc"
    assert compat.to_text(["a", b"b", True]) == ["a", "b", True]
    assert compat.to_bytes("abc") == b"abc"
    assert compat.round(2.5) == 3.0
    assert compat.round(-2.5) == -3.0
    assert compat.floor_division(7, 2) == 3
    assert compat.get_exception_message(ValueError("boom")) == "boom"


def test_c_ops_dispatch():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import _C_ops

    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
    np.testing.assert_array_equal(_C_ops.relu(x).numpy(), [0.0, 2.0])
    np.testing.assert_allclose(_C_ops.final_state_tanh(x).numpy(),
                               np.tanh([-1.0, 2.0]), rtol=1e-6)
    assert float(_C_ops.mean(x)) == 0.5
    import pytest

    with pytest.raises(AttributeError, match="no matching op"):
        _C_ops.definitely_not_an_op
