"""hapi Model tests (reference: unittests test_model.py) + metrics."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_lenet_fit_reaches_loss_threshold():
    paddle.seed(0)
    model = paddle.Model(LeNet())
    model.prepare(paddle.optimizer.Adam(0.001,
                                        parameters=model.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    train = MNIST(mode="train")
    model.fit(train, epochs=2, batch_size=64, verbose=0)
    res = model.evaluate(MNIST(mode="test"), batch_size=64, verbose=0)
    assert res["loss"] < 0.5
    assert res["acc"] > 0.9


def test_fit_with_numpy_arrays_and_predict():
    paddle.seed(0)
    x = np.random.rand(128, 4).astype(np.float32)
    w = np.random.rand(4, 1).astype(np.float32)
    y = (x @ w).astype(np.float32)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    model.fit([x, y], epochs=60, batch_size=32, verbose=0)
    res = model.evaluate([x, y], batch_size=64, verbose=0)
    assert res["loss"] < 1e-2
    preds = model.predict([x], batch_size=64, stack_outputs=True)
    assert preds[0].shape == (128, 1)
    np.testing.assert_allclose(preds[0], y, atol=0.3)


def test_train_eval_batch_api():
    net = nn.Linear(3, 2)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    x = np.random.rand(8, 3).astype(np.float32)
    y = np.random.randint(0, 2, (8,))
    loss1, m1 = model.train_batch([x], [y])
    loss2, m2 = model.train_batch([x], [y])
    assert loss2[0] < loss1[0] + 1.0  # training progresses / no blowup
    eloss, em = model.eval_batch([x], [y])
    assert isinstance(eloss[0], float)


def test_bn_buffers_update_in_jitted_fit():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.ReLU(),
                        nn.Linear(8, 2))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.05, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    x = np.random.rand(64, 4).astype(np.float32) + 3.0  # mean ~3.5
    y = np.random.randint(0, 2, (64,))
    bn = net[1]
    mean_before = bn._mean.numpy().copy()
    model.fit([x, y], epochs=3, batch_size=16, verbose=0)
    mean_after = bn._mean.numpy()
    assert not np.allclose(mean_before, mean_after)
    assert np.all(np.isfinite(mean_after))


def test_callbacks_early_stopping_and_checkpoint(tmp_path):
    paddle.seed(0)
    x = np.random.rand(64, 4).astype(np.float32)
    y = np.random.randint(0, 2, (64,))
    net = nn.Linear(4, 2)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.0, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    es = paddle.callbacks.EarlyStopping(monitor="loss", patience=1,
                                        save_best_model=False, verbose=0)
    model.fit([x, y], eval_data=[x, y], epochs=10, batch_size=32, verbose=0,
              callbacks=[es])
    assert model.stop_training  # lr=0 -> no improvement -> stopped early
    save_dir = str(tmp_path / "ckpts")
    model2 = paddle.Model(nn.Linear(4, 2))
    model2.prepare(paddle.optimizer.SGD(0.1,
                                        parameters=model2.parameters()),
                   nn.CrossEntropyLoss())
    model2.fit([x, y], epochs=2, batch_size=32, verbose=0, save_dir=save_dir)
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "1.pdparams"))


def test_model_save_load_roundtrip(tmp_path):
    net = LeNet()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.001, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    x = np.random.rand(8, 1, 28, 28).astype(np.float32)
    y = np.random.randint(0, 10, (8,))
    model.train_batch([x], [y])
    path = str(tmp_path / "model")
    model.save(path)
    m2 = paddle.Model(LeNet())
    m2.prepare(paddle.optimizer.Adam(0.001, parameters=m2.parameters()),
               nn.CrossEntropyLoss(), Accuracy())
    m2.load(path)
    p1 = model.predict_batch([x])[0].numpy()
    p2 = m2.predict_batch([x])[0].numpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_summary():
    model = paddle.Model(LeNet())
    info = model.summary((1, 1, 28, 28))
    assert info["total_params"] == 61610  # LeNet param count


def test_accuracy_metric():
    acc = Accuracy(topk=(1, 2))
    pred = paddle.to_tensor(np.array([[0.1, 0.9, 0.0], [0.8, 0.05, 0.15]],
                                     np.float32))
    label = paddle.to_tensor(np.array([[1], [2]]))
    c = acc.compute(pred, label)
    acc.update(c)
    top1, top2 = acc.accumulate()
    assert top1 == pytest.approx(0.5)
    assert top2 == pytest.approx(1.0)
    assert acc.name() == ["acc_top1", "acc_top2"]


def test_precision_recall_auc():
    prec = Precision()
    rec = Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.6], np.float32)
    labels = np.array([1, 0, 1, 1])
    prec.update(preds, labels)
    rec.update(preds, labels)
    assert prec.accumulate() == pytest.approx(2 / 3)
    assert rec.accumulate() == pytest.approx(2 / 3)
    auc = Auc()
    probs = np.stack([1 - preds, preds], -1)
    auc.update(probs, labels)
    assert 0.0 <= auc.accumulate() <= 1.0


def test_lr_scheduler_steps_per_epoch_in_fit():
    x = np.random.rand(32, 2).astype(np.float32)
    y = np.random.randint(0, 2, (32,))
    net = nn.Linear(2, 2)
    sch = paddle.optimizer.lr.StepDecay(0.1, step_size=1, gamma=0.5)
    opt = paddle.optimizer.SGD(sch, parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, nn.CrossEntropyLoss())
    model.fit([x, y], epochs=3, batch_size=16, verbose=0)
    assert opt.get_lr() == pytest.approx(0.1 * 0.5 ** 3)
