"""Jit-cached eager op dispatch (core/dispatch.py).

Covers the contract: hit/miss keying across shapes/dtypes/statics,
frozen-closure snapshot semantics, no_grad vs grad paths, AMP-enabled
keying, the shape-churn retrace guard, the PADDLE_TPU_EAGER_JIT=0
bypass, and the headline acceptance: a 100-iteration small-MLP eager
train loop serves ≥99% of op calls from the cache after warmup.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core import dispatch
from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor


@pytest.fixture(autouse=True)
def _fresh_cache():
    """Cold cache + compile-on-first-sighting so hits/misses are exact."""
    prev_warm = dispatch.set_warmup_count(1)
    prev_on = dispatch.set_eager_jit(True)
    dispatch.reset_dispatch_stats(clear_caches=True)
    yield
    dispatch.set_warmup_count(prev_warm)
    dispatch.set_eager_jit(prev_on)
    dispatch.reset_dispatch_stats(clear_caches=True)


def _fwd():
    return dispatch.dispatch_stats()["forward"]


def _t(arr, stop_gradient=True):
    return paddle.to_tensor(np.asarray(arr), stop_gradient=stop_gradient)


# ---- keying ---------------------------------------------------------------

def test_stable_shape_hits():
    x = _t(np.ones((4, 8), np.float32))
    y = _t(np.ones((4, 8), np.float32))
    for _ in range(5):
        z = paddle.add(x, y)
    s = _fwd()
    assert s["misses"] == 1 and s["hits"] == 4
    np.testing.assert_allclose(np.asarray(z._value), 2.0)


def test_shape_and_dtype_miss():
    a32 = _t(np.ones((4, 8), np.float32))
    paddle.add(a32, a32)                       # miss: first sighting
    paddle.add(a32, a32)                       # hit
    b = _t(np.ones((2, 8), np.float32))
    paddle.add(b, b)                           # miss: new shape
    c = _t(np.ones((4, 8), np.float64))
    paddle.add(c, c)                           # miss: new dtype
    s = _fwd()
    assert s["misses"] == 3 and s["hits"] == 1


def test_static_args_key_by_value():
    x = _t(np.arange(12, dtype=np.float32).reshape(3, 4))
    r0 = paddle.sum(x, axis=0)                 # miss
    r0b = paddle.sum(x, axis=0)                # hit (same static)
    r1 = paddle.sum(x, axis=1)                 # miss (different static)
    s = _fwd()
    assert s["misses"] == 2 and s["hits"] == 1
    np.testing.assert_allclose(np.asarray(r0._value),
                               np.asarray(r0b._value))
    assert list(r1.shape) == [3]


def test_cross_type_statics_do_not_collide():
    """Python hashes 2 == 2.0 == True, but the baked programs differ:
    pow(int32, 2) stays int32 while pow(int32, 2.0) promotes. The key
    must type-tag numeric statics."""
    x = _t(np.arange(4, dtype=np.int32))
    a = paddle.pow(x, 2.0)
    b = paddle.pow(x, 2)
    assert "int32" in str(b.dtype), (a.dtype, b.dtype)
    np.testing.assert_allclose(np.asarray(b._value), [0, 1, 4, 9])
    # ±0.0 hash equal too but 1/v differs
    y = _t(np.float32([1.0]))
    import jax.numpy as jnp

    def scl(v, s):
        return 1.0 / (v * s + jnp.float32(0))

    pos = apply(scl, _t(np.float32([0.0])), 0.0)
    neg = apply(scl, _t(np.float32([0.0])), -0.0)
    assert np.asarray(pos._value)[0] > 0 > np.asarray(neg._value)[0]
    del y


def test_weak_type_in_key():
    """A weak-typed scalar operand must not collide with a strong one:
    promotion differs, so the emitted programs differ."""
    import jax.numpy as jnp

    x = _t(np.ones((4,), np.float32))
    weak = Tensor(jnp.asarray(1.0))            # weak-typed f32 scalar
    strong = Tensor(jnp.ones((), jnp.float32))
    assert weak._value.weak_type and not strong._value.weak_type
    paddle.add(x, weak)
    paddle.add(x, strong)
    assert _fwd()["misses"] == 2


def test_unhashable_static_leaf_reaches_fn_intact():
    """A slice passed as an op ARG is a tree leaf: the key stores its
    hashable encoding, but the compiled program must close over the real
    slice object."""
    x = _t(np.arange(10, dtype=np.float32))

    def take(v, sl):
        return v[sl]

    a = apply(take, x, slice(2, 5))            # miss (compiles)
    b = apply(take, x, slice(2, 5))            # hit
    c = apply(take, x, slice(1, 3))            # different static -> miss
    np.testing.assert_allclose(np.asarray(a._value), [2, 3, 4])
    np.testing.assert_allclose(np.asarray(b._value), [2, 3, 4])
    np.testing.assert_allclose(np.asarray(c._value), [1, 2])
    s = _fwd()
    assert s["misses"] == 2 and s["hits"] == 1


# ---- closure snapshot -----------------------------------------------------

def test_closure_rebinding_frozen_snapshot():
    x = _t(np.ones((3,), np.float32))
    scale = 2.0

    def op(v):
        return v * scale

    a = apply(op, x)
    scale = 5.0
    b = apply(op, x)          # new cell value -> new key, fresh program
    c = apply(op, x)          # hit on the scale=5.0 entry
    np.testing.assert_allclose(np.asarray(a._value), 2.0)
    np.testing.assert_allclose(np.asarray(b._value), 5.0)
    np.testing.assert_allclose(np.asarray(c._value), 5.0)
    s = _fwd()
    assert s["misses"] == 2 and s["hits"] == 1


def test_captured_array_never_cached():
    """A closure over a live array (dropout's PRNG key pattern) must
    bypass the cache — caching would freeze the captured value."""
    x = _t(np.ones((8,), np.float32))
    import jax.numpy as jnp

    seen = []
    for i in range(3):
        k = jnp.full((8,), float(i), jnp.float32)

        def op(v):
            return v + k

        seen.append(float(np.asarray(apply(op, x)._value)[0]))
    assert seen == [1.0, 2.0, 3.0]
    s = _fwd()
    assert s["hits"] == 0 and s["misses"] == 0 and s["unkeyable"] == 3


def test_dropout_randomness_survives():
    x = _t(np.ones((1000,), np.float32))
    m1 = np.asarray(F.dropout(x, p=0.5)._value)
    m2 = np.asarray(F.dropout(x, p=0.5)._value)
    assert not np.array_equal(m1, m2)


# ---- grad paths -----------------------------------------------------------

def test_no_grad_and_grad_share_entries_and_agree():
    xv = np.linspace(-1, 1, 12).astype(np.float32).reshape(3, 4)
    with paddle.no_grad():
        y_ng = paddle.tanh(_t(xv))             # miss
    x = _t(xv, stop_gradient=False)
    y_g = paddle.tanh(x)                       # hit: same forward program
    s = _fwd()
    assert s["misses"] == 1 and s["hits"] == 1
    np.testing.assert_allclose(np.asarray(y_ng._value),
                               np.asarray(y_g._value))
    y_g.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               1.0 - np.tanh(xv) ** 2, rtol=1e-6)


def test_backward_cache_shares_infrastructure():
    x = _t(np.ones((4,), np.float32), stop_gradient=False)
    for _ in range(3):
        y = (x * x).sum()
        y.backward()
    bwd = dispatch.dispatch_stats()["backward"]
    assert bwd["misses"] >= 1 and bwd["hits"] >= bwd["misses"]


# ---- AMP ------------------------------------------------------------------

def test_amp_cast_is_part_of_the_key():
    a = _t(np.ones((8, 8), np.float32))
    b = _t(np.ones((8, 8), np.float32))
    paddle.matmul(a, b)                        # f32 program
    with paddle.amp.auto_cast():
        out = paddle.matmul(a, b)              # white-list -> bf16 program
    assert "bfloat16" in str(out.dtype)
    s = _fwd()
    # the two matmuls cannot share an entry (different post-cast avals)
    assert s["misses"] == 2
    with paddle.amp.auto_cast():
        paddle.matmul(a, b)                    # hit on the bf16 entry
    assert _fwd()["hits"] == 1


# ---- retrace guard --------------------------------------------------------

def test_retrace_guard_warns_on_shape_churn():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for n in range(3, 20):                 # new shape every call
            paddle.exp(_t(np.ones((n,), np.float32)))
    msgs = [str(x.message) for x in w
            if "missed the jit cache" in str(x.message)]
    assert len(msgs) == 1                      # warns once, not per call
    assert "exp" in msgs[0]
    per_op = dispatch.dispatch_stats()["per_op"]["exp"]
    assert per_op["retraces"] > 0


def test_stable_shapes_do_not_warn():
    x = _t(np.ones((4,), np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in range(30):
            paddle.exp(x)
    assert not [x for x in w if "missed the jit cache" in str(x.message)]


# ---- escape hatch ---------------------------------------------------------

def _mlp_step(x, y, w1, b1, w2, b2):
    h = paddle.nn.functional.relu(paddle.matmul(x, w1) + b1)
    p = paddle.matmul(h, w2) + b2
    loss = ((p - y) * (p - y)).mean()
    loss.backward()
    grads = [np.asarray(t.grad._value) for t in (w1, b1, w2, b2)]
    for t in (w1, b1, w2, b2):
        t.clear_grad()
    return float(np.asarray(loss._value)), grads


def test_eager_jit_off_bypass_equivalence():
    rng = np.random.RandomState(0)
    xv = rng.randn(8, 16).astype(np.float32)
    yv = rng.randn(8, 4).astype(np.float32)
    ws = [rng.randn(16, 32).astype(np.float32) * 0.1,
          np.zeros(32, np.float32),
          rng.randn(32, 4).astype(np.float32) * 0.1,
          np.zeros(4, np.float32)]

    def run():
        params = [_t(w.copy(), stop_gradient=False) for w in ws]
        return _mlp_step(_t(xv), _t(yv), *params)

    loss_on, grads_on = run()
    assert _fwd()["misses"] > 0               # the cache actually engaged

    dispatch.set_eager_jit(False)
    dispatch.reset_dispatch_stats()
    loss_off, grads_off = run()
    s = _fwd()
    assert s["misses"] == 0 and s["hits"] == 0 and s["bypasses"] > 0

    np.testing.assert_allclose(loss_on, loss_off, rtol=1e-5)
    for g_on, g_off in zip(grads_on, grads_off):
        np.testing.assert_allclose(g_on, g_off, rtol=1e-5, atol=1e-6)


def test_env_escape_hatch_reaches_module_flag(monkeypatch):
    """PADDLE_TPU_EAGER_JIT=0 must produce a disabled dispatch layer on
    import (checked against the module's own env parser)."""
    monkeypatch.setenv("PADDLE_TPU_EAGER_JIT", "0")
    assert dispatch._env_flag("PADDLE_TPU_EAGER_JIT", "1") is False
    monkeypatch.setenv("PADDLE_TPU_EAGER_JIT", "1")
    assert dispatch._env_flag("PADDLE_TPU_EAGER_JIT", "1") is True


# ---- non_jittable opt-out -------------------------------------------------

def test_non_jittable_opt_out():
    @dispatch.non_jittable
    def host_op(v):
        return v * 2.0

    x = _t(np.ones((4,), np.float32))
    for _ in range(3):
        out = apply(host_op, x)
    s = _fwd()
    assert s["hits"] == 0 and s["misses"] == 0 and s["bypasses"] >= 3
    np.testing.assert_allclose(np.asarray(out._value), 2.0)


def test_learned_fallback_on_unjittable_op():
    """An op that traces to a host-side conversion fails under jit,
    succeeds eagerly, and is learned as non-jittable — no retry loop."""
    def hostish(v):
        return v * float(v.sum())              # float() on a tracer raises

    x = _t(np.ones((4,), np.float32))
    out1 = apply(hostish, x)
    out2 = apply(hostish, x)
    np.testing.assert_allclose(np.asarray(out1._value), 4.0)
    np.testing.assert_allclose(np.asarray(out2._value), 4.0)
    s = _fwd()
    assert s["fallbacks"] == 1 and s["bypasses"] >= 1


def test_row_iteration_never_compiles_per_index():
    """Scalar-int indexing is iteration-shaped (Tensor.__iter__,
    dataset[i]): it must bypass the cache — one compiled program per
    distinct index would thrash the LRU every epoch."""
    t = _t(np.arange(40, dtype=np.float32).reshape(10, 4))
    for _ in range(3):                     # three epochs of row iteration
        rows = [np.asarray(r._value) for r in t]
    s = _fwd()
    assert s["misses"] == 0 and s["size"] == 0, s
    assert s["bypasses"] >= 30
    np.testing.assert_allclose(rows[3], [12, 13, 14, 15])
    t[2:5]                                 # slice indexing still caches
    assert _fwd()["misses"] == 1


def test_stateful_callable_object_never_cached():
    """A callable OBJECT keys by identity while its attributes can
    mutate — it must bypass the cache (stale-bake hazard)."""
    class Scaler:
        def __init__(self, s):
            self.s = s

        def __call__(self, v):
            return v * self.s

    sc = Scaler(2.0)
    x = _t(np.ones((4,), np.float32))
    a = apply(sc, x)
    sc.s = 5.0
    b = apply(sc, x)
    np.testing.assert_allclose(np.asarray(a._value), 2.0)
    np.testing.assert_allclose(np.asarray(b._value), 5.0)
    s = _fwd()
    assert s["hits"] == 0 and s["misses"] == 0 and s["unkeyable"] == 2


def test_kwonly_defaults_distinguish_function_statics():
    """Two same-code functions differing only in keyword-only defaults
    must not collide when passed as static args."""
    def make(a):
        def act(v, *, s=a):
            return v * s
        return act

    def op(x, actfn):
        return actfn(x)

    x = _t(np.ones((4,), np.float32))
    r2 = apply(op, x, make(2.0))
    r5 = apply(op, x, make(5.0))
    np.testing.assert_allclose(np.asarray(r2._value), 2.0)
    np.testing.assert_allclose(np.asarray(r5._value), 5.0)
    assert _fwd()["misses"] == 2


def test_genuine_errors_still_raise():
    a = _t(np.ones((3, 4), np.float32))
    b = _t(np.ones((5, 6), np.float32))
    with pytest.raises(Exception):
        paddle.matmul(a, b)


# ---- acceptance: hot-loop hit rate ---------------------------------------

def test_mlp_train_loop_hit_rate_after_warmup():
    """ISSUE acceptance: ≥99% of eager op calls served from cache over a
    100-iteration small-MLP train loop after warmup, per
    dispatch_stats()."""
    rng = np.random.RandomState(7)
    x = _t(rng.randn(16, 8).astype(np.float32))
    y = _t(rng.randn(16, 2).astype(np.float32))
    params = [
        _t(rng.randn(8, 16).astype(np.float32) * 0.1, stop_gradient=False),
        _t(np.zeros(16, np.float32), stop_gradient=False),
        _t(rng.randn(16, 2).astype(np.float32) * 0.1, stop_gradient=False),
        _t(np.zeros(2, np.float32), stop_gradient=False),
    ]
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=params)

    def step():
        h = F.relu(paddle.matmul(x, params[0]) + params[1])
        p = paddle.matmul(h, params[2]) + params[3]
        loss = ((p - y) * (p - y)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    for _ in range(3):                         # warmup: compile everything
        step()
    dispatch.reset_dispatch_stats()            # counters only, keep cache
    for _ in range(100):
        loss = step()
    s = dispatch.dispatch_stats()
    fwd = s["forward"]
    assert fwd["hits"] + fwd["misses"] > 0
    assert fwd["hit_rate"] >= 0.99, f"forward stats: {fwd}"
    assert s["backward"]["hit_rate"] >= 0.99, f"backward: {s['backward']}"
    # nothing on the hot loop should be silently eager
    assert fwd["unkeyable"] == 0 and fwd["fallbacks"] == 0
    assert np.isfinite(float(np.asarray(loss._value)))


def test_reset_counters_holds_the_cache_lock():
    """Regression (threadlint CL001): get()/put() bump hits/misses under
    the cache lock; an unlocked reset_counters could interleave with an
    in-flight increment and resurrect pre-reset counts. The reset must
    take the same lock."""
    cache = dispatch.JitCache("probe", 8)
    acquired = []

    class _ProbeLock:
        def __enter__(self):
            acquired.append(True)

        def __exit__(self, *exc):
            return False

    cache._lock = _ProbeLock()
    cache.reset_counters()
    assert acquired, ("JitCache.reset_counters must zero the counters "
                      "under the cache lock")
    assert cache.hits == cache.misses == cache.evictions == 0
