"""Child process for the tracing crash + cluster-merge tests
(test_tracing.py).

``kill`` mode: configures the tracer at TRACING_CHILD_DIR with a small
flush bound, emits numbered spans with a fault point after each one;
the parent arms ``PADDLE_TPU_FAULT_INJECT=tracing.child=kill:N`` so the
process dies by SIGKILL (no atexit, no terminator) right after the Nth
span. The parent then proves the trace survived minus at most the
unflushed tail — the bounded-buffer durability contract.

``rank`` mode: one cluster rank — tags telemetry with its rank, traces
into the shared ``<store>/traces`` dir, emits spans across several
categories (compute, checkpoint, coord via a rendezvous round trip),
publishes its registry, and flushes. The parent runs the host-0 merge
and asserts the merged cluster timeline carries both ranks' spans.
"""
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

mode = sys.argv[1] if len(sys.argv) > 1 else "kill"

if mode == "kill":
    os.environ["PADDLE_TPU_TRACE_FLUSH_EVERY"] = "4"
    from paddle_tpu.runtime import tracing  # noqa: E402
    from paddle_tpu.testing.faults import fault_point  # noqa: E402

    tracing.configure(os.environ["TRACING_CHILD_DIR"])
    i = 0
    while i < 10_000:  # bounded: a mis-armed injector must not spin forever
        i += 1
        tracing.emit_span(f"work{i}", "test", time.time(), 0.001, i=i)
        fault_point("tracing.child")  # parent arms kill -9 on the Nth call
    print("child exited without being killed", file=sys.stderr)
    sys.exit(3)

elif mode == "rank":
    from paddle_tpu.distributed import coordination  # noqa: E402
    from paddle_tpu.runtime import telemetry, tracing  # noqa: E402

    ctx = coordination.cluster_context()
    assert ctx is not None, "cluster env not set"
    coordination.init_cluster_telemetry(ctx)
    tracing.configure(os.path.join(ctx.store.root, "traces"))
    with tracing.span("work", "compute", rank=ctx.rank):
        time.sleep(0.01)
    tracing.emit_span("save", "checkpoint", time.time() - 0.002, 0.002,
                      step=1)
    if ctx.is_leader:
        coordination.rendezvous(ctx.store, "trace_tok", {"t": 1},
                                leader=True)
    else:
        tok = coordination.rendezvous(ctx.store, "trace_tok", timeout=30.0)
        assert tok == {"t": 1}, tok
    telemetry.publish_registry(ctx.store, ctx.rank)
    tracing.flush()
    print(f"RANK_OK {ctx.rank}", flush=True)

else:
    raise SystemExit(f"unknown mode {mode!r}")
