"""Autograd tape tests (reference: unittests test_imperative_*.py,
test_grad.py, test_double_grad.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(a, sg=False):
    return paddle.to_tensor(a, stop_gradient=sg)


def test_backward_simple():
    x = t([1.0, 2.0, 3.0])
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_chain_and_branching():
    x = t([[1.0, 2.0], [3.0, 4.0]])
    a = x * 2
    b = x + 1
    y = (a * b).sum()
    y.backward()
    # d/dx [2x(x+1)] = 4x + 2
    np.testing.assert_allclose(x.grad.numpy(), 4 * x.numpy() + 2)


def test_grad_accumulation_and_clear():
    x = t([1.0, 2.0])
    x.sum().backward()
    x.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])
    x.clear_grad()
    assert x.grad is None


def test_no_grad_context_and_decorator():
    x = t([1.0])
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient

    @paddle.no_grad()
    def f(v):
        return v * 3

    assert f(x).stop_gradient
    y2 = x * 2
    assert not y2.stop_gradient


def test_stop_gradient_blocks():
    x = t([1.0, 2.0])
    y = t([3.0, 4.0], sg=True)
    out = (x * y).sum()
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), y.numpy())
    assert y.grad is None


def test_detach():
    x = t([2.0])
    y = x * 3
    z = y.detach() * 2
    assert z.stop_gradient
    (y * 1.0).backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0])


def test_paddle_grad():
    x = t([3.0])
    y = x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [6.0])
    assert x.grad is None  # paddle.grad must not touch .grad


def test_double_grad():
    x = t(2.0)
    y = x ** 4
    (g1,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g1.numpy(), 32.0)  # 4x^3
    (g2,) = paddle.grad(g1, x, create_graph=True)
    np.testing.assert_allclose(g2.numpy(), 48.0)  # 12x^2
    (g3,) = paddle.grad(g2, x)
    np.testing.assert_allclose(g3.numpy(), 48.0)  # 24x


def test_retain_graph():
    x = t([1.0, 2.0])
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 8.0])
    z = (x * x).sum()
    z.backward()
    with pytest.raises(RuntimeError):
        z.backward()


def test_backward_with_grad_tensor():
    x = t([[1.0, 2.0]])
    y = x * 3
    y.backward(paddle.to_tensor([[1.0, 10.0]]))
    np.testing.assert_allclose(x.grad.numpy(), [[3.0, 30.0]])


def test_multi_output_op_grad():
    x = t(np.arange(6, dtype=np.float32).reshape(2, 3))
    a, b, c = paddle.split(x, 3, axis=1)
    (a.sum() + 2 * b.sum() + 3 * c.sum()).backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 2, 3], [1, 2, 3]])
    vals, idx = paddle.topk(x, 2, axis=1)
    vals.sum().backward()
    # topk picks columns 2,1 per row
    assert x.grad is not None


def test_matmul_grad_vs_jax():
    import jax

    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 2).astype(np.float32)
    xa, xb = t(a), t(b)
    paddle.matmul(xa, xb).sum().backward()
    ga = jax.grad(lambda u: (u @ b).sum())(a)
    gb = jax.grad(lambda u: (a @ u).sum())(b)
    np.testing.assert_allclose(xa.grad.numpy(), ga, rtol=1e-5)
    np.testing.assert_allclose(xb.grad.numpy(), gb, rtol=1e-5)


def test_getitem_grad():
    x = t(np.ones((4, 4), np.float32))
    y = x[1:3, :2].sum()
    y.backward()
    ex = np.zeros((4, 4))
    ex[1:3, :2] = 1
    np.testing.assert_allclose(x.grad.numpy(), ex)


def test_broadcast_grad():
    x = t(np.ones((3, 1), np.float32))
    y = t(np.ones((1, 4), np.float32))
    (x + y).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((3, 1), 4.0))
    np.testing.assert_allclose(y.grad.numpy(), np.full((1, 4), 3.0))


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor
            return gy * 3 * x.detach() * x.detach()

    x = t(2.0)
    y = Cube.apply(x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 12.0)


def test_deep_graph():
    x = t(1.0)
    y = x
    for _ in range(300):
        y = y * 1.01
    y.backward()
    assert x.grad is not None


def test_register_hook_leaf_and_intermediate():
    """Tensor.register_hook fires with the final gradient and can
    replace it; handles are removable (reference Tensor.register_hook)."""
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.to_tensor(np.ones(3, np.float32))
    x.stop_gradient = False
    seen = []
    h = x.register_hook(lambda g: seen.append(np.asarray(g.numpy())) or
                        g * 2.0)
    (x * 3.0).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], 3.0)          # pre-hook grad
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), 6.0)  # doubled

    # removable
    x.clear_grad()
    h.remove()
    (x * 3.0).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), 3.0)
    assert len(seen) == 1

    # intermediate tensor: hook sees d loss/d y, replacement propagates
    x.clear_grad()
    y = x * 4.0
    got = []
    y.register_hook(lambda g: got.append(np.asarray(g.numpy())) or g * 0.5)
    (y * 2.0).sum().backward()
    np.testing.assert_allclose(got[0], 2.0)
    # dx = d/dx (x*4) * (hooked dy) = 4 * (2*0.5) = 4
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), 4.0)

    # observation-only hook (returns None) leaves the gradient alone
    x.clear_grad()
    z = x * 5.0
    z.register_hook(lambda g: None)
    z.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), 5.0)


def test_register_hook_reference_contract_corners():
    """Leaf hooks fire ONCE on the accumulated per-pass gradient (not per
    contribution); paddle.grad sees hooked gradients; removed handles
    never delete later registrations."""
    import numpy as np

    import paddle_tpu as paddle

    # one firing on the pass-final sum: (x*2).sum() + (x*3).sum()
    x = paddle.to_tensor(np.ones(2, np.float32))
    x.stop_gradient = False
    seen = []
    x.register_hook(lambda g: seen.append(np.asarray(g.numpy())) or
                    paddle.clip(g, max=2.5))
    ((x * 2.0).sum() + (x * 3.0).sum()).backward()
    assert len(seen) == 1, seen
    np.testing.assert_allclose(seen[0], 5.0)   # accumulated, pre-hook
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), 2.5)  # clipped

    # paddle.grad consumes hooked intermediate gradients
    x2 = paddle.to_tensor(np.float32(1.0))
    x2.stop_gradient = False
    y = x2 * 4.0
    y.register_hook(lambda g: g * 0.5)
    (gy,) = paddle.grad((y * 2.0).sum(), [y])
    np.testing.assert_allclose(float(gy), 1.0)  # 2.0 halved by the hook

    # handle removal never affects later registrations
    t = paddle.to_tensor(np.ones(1, np.float32))
    t.stop_gradient = False
    calls = []
    h1 = t.register_hook(lambda g: calls.append("a") or None)
    h2 = t.register_hook(lambda g: calls.append("b") or None)
    h2.remove()
    t.register_hook(lambda g: calls.append("c") or None)
    h2.remove()  # idempotent; must NOT delete the "c" hook
    (t * 1.0).sum().backward()
    assert calls == ["a", "c"], calls


def test_unused_sibling_output_reports_none():
    """A requested intermediate on a multi-output node whose out_idx got
    NO gradient must report unused (None / allow_unused error), not a
    synthesized zeros tensor (round-4 advisor finding)."""
    x = t(np.arange(6, dtype=np.float32).reshape(2, 3))
    a, b, c = paddle.split(x, 3, axis=1)
    loss = a.sum()  # only output 0 carries gradient
    (g_b,) = paddle.grad(loss, [b], retain_graph=True, allow_unused=True)
    assert g_b is None
    with pytest.raises(RuntimeError):
        paddle.grad(loss, [b], retain_graph=True, allow_unused=False)
    # the used sibling still gets its real gradient
    (g_a,) = paddle.grad(loss, [a], allow_unused=False)
    np.testing.assert_allclose(g_a.numpy(), np.ones((2, 1), np.float32))


def test_closure_cells_frozen_at_forward_time():
    """The deferred pullback recomputes the forward at backward() time;
    a captured variable rebound in between must NOT change the gradient
    (cells are snapshotted at apply() time — round-4 advisor finding)."""
    from paddle_tpu.core.autograd import apply

    x = t(np.float32(3.0))
    scale = 2.0

    def f(v):
        return v * scale

    y = apply(f, x)
    scale = 5.0  # rebinding after the forward must be invisible
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2.0)

    # the create_graph path (node.closed) shares the same freeze
    x2 = t(np.float32(3.0))
    scale2 = 2.0

    def f2(v):
        return v * scale2

    y2 = apply(f2, x2)
    scale2 = 5.0
    (g2,) = paddle.grad(y2, [x2], create_graph=True)
    np.testing.assert_allclose(g2.numpy(), 2.0)
