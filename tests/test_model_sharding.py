"""Model-level sharding annotations are live (VERDICT round-1 weak #2).

Installs a Mesh and proves the GPT/BERT `annotate` calls produce real
sharding constraints in the compiled step, and that the dp-sharded train
step computes the same loss as the unsharded one.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM


@pytest.fixture
def mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    m = Mesh(devs, ("dp", "tp"))
    dist.set_mesh(m)
    yield m
    dist.set_mesh(None)


def _tiny_gpt():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position=64, dropout=0.0,
                    use_flash=False)
    return GPTForCausalLM(cfg), cfg


def _loss_fn(model):
    def f(pv, ids, labels):
        with paddle.no_grad():
            out, _ = model.functional_call(
                {k: Tensor(v) for k, v in pv.items()},
                Tensor(ids), None, Tensor(labels))
        loss = out[0] if isinstance(out, (list, tuple)) else out
        return loss._value.astype(jnp.float32)

    return f


def test_annotate_emits_sharding_constraints(mesh):
    model, cfg = _tiny_gpt()
    params = {k: p._value for k, p in model.named_parameters()}
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
    lowered = jax.jit(_loss_fn(model)).lower(params, ids, labels)
    text = lowered.as_text()
    # with_sharding_constraint lowers to @Sharding custom calls — if the
    # model's annotate() calls were dead (no mesh seen), none would exist
    assert "sharding_constraint" in text or "@Sharding" in text, \
        "model annotate() produced no constraints"


def test_dp_sharded_step_matches_unsharded(mesh):
    model, cfg = _tiny_gpt()
    params = {k: p._value for k, p in model.named_parameters()}
    rng = np.random.RandomState(1)
    ids_np = rng.randint(0, cfg.vocab_size, (8, 16))
    labels_np = rng.randint(0, cfg.vocab_size, (8, 16))

    loss_fn = _loss_fn(model)

    def train_step(pv, ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(pv, ids, labels)
        new_p = jax.tree_util.tree_map(lambda v, g: v - 0.1 * g, pv, grads)
        return loss, new_p

    # unsharded reference
    dist.set_mesh(None)
    loss_ref, p_ref = jax.jit(train_step)(
        params, jnp.asarray(ids_np), jnp.asarray(labels_np))

    # dp-sharded batch on the mesh
    dist.set_mesh(mesh)
    ids = jax.device_put(jnp.asarray(ids_np),
                         NamedSharding(mesh, P("dp", None)))
    labels = jax.device_put(jnp.asarray(labels_np),
                            NamedSharding(mesh, P("dp", None)))
    loss_sh, p_sh = jax.jit(train_step)(params, ids, labels)

    np.testing.assert_allclose(float(loss_sh), float(loss_ref), rtol=2e-5)
    w_ref = jax.tree_util.tree_leaves(p_ref)[0]
    w_sh = jax.tree_util.tree_leaves(p_sh)[0]
    np.testing.assert_allclose(np.asarray(w_sh), np.asarray(w_ref),
                               rtol=2e-4, atol=1e-5)


def test_bert_annotations_live(mesh):
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM

    paddle.seed(0)
    cfg = BertConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128, max_position=64,
                     dropout=0.0, attention_dropout=0.0)
    model = BertForMaskedLM(cfg)
    params = {k: p._value for k, p in model.named_parameters()}
    rng = np.random.RandomState(2)
    ids = jnp.asarray(rng.randint(0, 128, (8, 16)))

    def fwd(pv, ids):
        with paddle.no_grad():
            out, _ = model.functional_call(
                {k: Tensor(v) for k, v in pv.items()}, Tensor(ids),
                None, None, None)
        first = out[0] if isinstance(out, (list, tuple)) else out
        return first._value

    text = jax.jit(fwd).lower(params, ids).as_text()
    assert "sharding_constraint" in text or "@Sharding" in text
