"""Child process for the telemetry kill -9 crash test
(test_telemetry.py).

Configures the event stream at TELEMETRY_CHILD_DIR, then emits
``train_step`` events in a loop with a fault point after each one; the
parent arms ``PADDLE_TPU_FAULT_INJECT=telemetry.child=kill:N`` so the
process dies by SIGKILL (no atexit, no flush-on-close) right after the
Nth event. The parent then proves the stream survived: every event
emitted before the kill is on disk, because the stream flushes per
record — the exact property the old VisualDL buffering lacked.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.runtime import telemetry  # noqa: E402
from paddle_tpu.testing.faults import fault_point  # noqa: E402

telemetry.configure(os.environ["TELEMETRY_CHILD_DIR"])
step = 0
while step < 10_000:  # bounded: a mis-armed injector must not spin forever
    step += 1
    telemetry.emit("train_step", step=step)
    fault_point("telemetry.child")  # parent arms kill -9 on the Nth call
print("child exited without being killed", file=sys.stderr)
sys.exit(3)
