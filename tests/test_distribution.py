"""paddle.distribution tests vs scipy ground truth.

Mirrors the reference's test strategy (SURVEY §4):
test_distribution_{normal,uniform,categorical,beta,dirichlet,multinomial}
validate log_prob/entropy/kl against scipy.stats closed forms.
"""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu.distribution import (
    AffineTransform, Beta, Categorical, ChainTransform, Dirichlet,
    ExpTransform, Independent, Multinomial, Normal, SigmoidTransform,
    StickBreakingTransform, TanhTransform, TransformedDistribution, Uniform,
    kl_divergence, register_kl,
)


def npv(t):
    return np.asarray(t._value)


class TestNormal:
    def test_log_prob_entropy(self):
        loc, scale = np.array([0.0, 1.5]), np.array([1.0, 2.5])
        d = Normal(loc, scale)
        x = np.array([0.3, -1.2])
        np.testing.assert_allclose(npv(d.log_prob(paddle.to_tensor(x))),
                                   st.norm(loc, scale).logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(npv(d.entropy()),
                                   st.norm(loc, scale).entropy(), rtol=1e-5)
        np.testing.assert_allclose(npv(d.cdf(paddle.to_tensor(x))),
                                   st.norm(loc, scale).cdf(x), rtol=1e-5)

    def test_sample_moments(self):
        d = Normal(2.0, 3.0)
        s = npv(d.sample((20000,)))
        assert abs(s.mean() - 2.0) < 0.1
        assert abs(s.std() - 3.0) < 0.1

    def test_kl(self):
        p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
        expect = (np.log(2.0) + (1.0 + 1.0) / (2 * 4.0) - 0.5)
        np.testing.assert_allclose(npv(kl_divergence(p, q)), expect,
                                   rtol=1e-5)

    def test_rsample_grad(self):
        import jax

        def f(loc):
            paddle.seed(7)
            d = Normal(loc, 1.0)
            return d.rsample((4,))._value.mean()

        g = jax.grad(f)(0.5)
        np.testing.assert_allclose(float(g), 1.0, rtol=1e-5)


class TestUniform:
    def test_log_prob_entropy(self):
        d = Uniform(1.0, 3.0)
        x = np.array([1.5, 2.9, 0.5])
        got = npv(d.log_prob(paddle.to_tensor(x)))
        np.testing.assert_allclose(got[:2],
                                   st.uniform(1.0, 2.0).logpdf(x[:2]),
                                   rtol=1e-5)
        assert got[2] == -np.inf
        np.testing.assert_allclose(npv(d.entropy()), np.log(2.0), rtol=1e-5)

    def test_kl(self):
        np.testing.assert_allclose(
            npv(kl_divergence(Uniform(0.0, 1.0), Uniform(-1.0, 2.0))),
            np.log(3.0), rtol=1e-5)
        assert npv(kl_divergence(Uniform(0.0, 3.0),
                                 Uniform(1.0, 2.0))) == np.inf


class TestCategorical:
    def test_entropy_kl_probs(self):
        # reference semantics: logits are unnormalized probabilities
        logits = np.array([1.0, 2.0, 3.0])
        d = Categorical(logits)
        p = logits / logits.sum()
        np.testing.assert_allclose(npv(d.entropy()), st.entropy(p), rtol=1e-5)
        q = Categorical(np.array([3.0, 2.0, 1.0]))
        np.testing.assert_allclose(npv(d.kl_divergence(q)),
                                   st.entropy(p, np.array([3., 2., 1.]) / 6),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            npv(d.probs(paddle.to_tensor(np.array([0, 2])))),
            p[[0, 2]], rtol=1e-5)

    def test_sample_frequencies(self):
        d = Categorical(np.array([1.0, 1.0, 2.0]))
        s = npv(d.sample((8000,)))
        freq = np.bincount(s, minlength=3) / 8000
        np.testing.assert_allclose(freq, [0.25, 0.25, 0.5], atol=0.03)

    def test_batched(self):
        logits = np.array([[1.0, 1.0], [1.0, 3.0]])
        d = Categorical(logits)
        lp = npv(d.log_prob(paddle.to_tensor(np.array([0, 1]))))
        np.testing.assert_allclose(lp, np.log([0.5, 0.75]), rtol=1e-5)
        assert npv(d.sample((5,))).shape == (5, 2)

    def test_batched_scores_own_samples(self):
        d = Categorical(np.array([[1.0, 1.0], [1.0, 3.0]]))
        s = d.sample((5,))
        lp = npv(d.log_prob(s))
        assert lp.shape == (5, 2)
        assert np.all(lp <= 0)


class TestBeta:
    def test_log_prob_entropy_moments(self):
        a, b = 2.0, 5.0
        d = Beta(a, b)
        x = np.array([0.1, 0.5, 0.9])
        np.testing.assert_allclose(npv(d.log_prob(paddle.to_tensor(x))),
                                   st.beta(a, b).logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(npv(d.entropy()), st.beta(a, b).entropy(),
                                   rtol=1e-5)
        np.testing.assert_allclose(npv(d.mean), st.beta(a, b).mean(),
                                   rtol=1e-5)
        np.testing.assert_allclose(npv(d.variance), st.beta(a, b).var(),
                                   rtol=1e-5)

    def test_expfamily_entropy_matches_closed_form(self):
        from paddle_tpu.distribution.exponential_family import (
            ExponentialFamily,
        )

        d = Beta(np.array([2.0, 3.0]), np.array([5.0, 0.5]))
        bregman = npv(ExponentialFamily.entropy(d))
        closed = st.beta([2.0, 3.0], [5.0, 0.5]).entropy()
        np.testing.assert_allclose(bregman, closed, rtol=1e-4)
        # shared scalar param broadcast across a batched one
        d2 = Beta(2.0, np.array([1.0, 2.0, 3.0]))
        np.testing.assert_allclose(npv(ExponentialFamily.entropy(d2)),
                                   st.beta(2.0, [1.0, 2.0, 3.0]).entropy(),
                                   rtol=1e-4)
        # event-axis params reduce to batch rank (scalar here)
        d3 = Dirichlet(np.array([2.0, 3.0, 4.0]))
        np.testing.assert_allclose(
            npv(ExponentialFamily.entropy(d3)),
            st.dirichlet([2.0, 3.0, 4.0]).entropy(), rtol=1e-4)

    def test_kl_vs_scipy_mc(self):
        p, q = Beta(2.0, 3.0), Beta(4.0, 2.0)
        xs = np.linspace(1e-4, 1 - 1e-4, 20001)
        pdf = st.beta(2.0, 3.0).pdf(xs)
        integrand = pdf * (st.beta(2.0, 3.0).logpdf(xs)
                           - st.beta(4.0, 2.0).logpdf(xs))
        expect = np.trapz(integrand, xs)
        np.testing.assert_allclose(npv(kl_divergence(p, q)), expect,
                                   rtol=1e-3)

    def test_sample(self):
        d = Beta(2.0, 5.0)
        s = npv(d.sample((20000,)))
        assert abs(s.mean() - 2 / 7) < 0.02


class TestDirichlet:
    def test_log_prob_entropy(self):
        conc = np.array([2.0, 3.0, 4.0])
        d = Dirichlet(conc)
        x = np.array([0.2, 0.3, 0.5])
        np.testing.assert_allclose(npv(d.log_prob(paddle.to_tensor(x))),
                                   st.dirichlet(conc).logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(npv(d.entropy()),
                                   st.dirichlet(conc).entropy(), rtol=1e-5)
        np.testing.assert_allclose(npv(d.mean), st.dirichlet(conc).mean(),
                                   rtol=1e-5)

    def test_kl(self):
        p = Dirichlet(np.array([2.0, 3.0, 4.0]))
        q = Dirichlet(np.array([1.0, 1.0, 1.0]))
        # closed form via expfamily Bregman; cross-check with digamma formula
        from scipy.special import digamma, gammaln

        a, b = np.array([2.0, 3.0, 4.0]), np.ones(3)
        a0 = a.sum()
        expect = (gammaln(a0) - gammaln(a).sum()
                  - gammaln(b.sum()) + gammaln(b).sum()
                  + ((a - b) * (digamma(a) - digamma(a0))).sum())
        np.testing.assert_allclose(npv(kl_divergence(p, q)), expect,
                                   rtol=1e-5)

    def test_sample_shape(self):
        d = Dirichlet(np.array([[1.0, 2.0], [3.0, 4.0]]))
        s = npv(d.sample((5,)))
        assert s.shape == (5, 2, 2)
        np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)


class TestMultinomial:
    def test_log_prob(self):
        d = Multinomial(10, np.array([0.2, 0.3, 0.5]))
        x = np.array([2.0, 3.0, 5.0])
        np.testing.assert_allclose(
            npv(d.log_prob(paddle.to_tensor(x))),
            st.multinomial(10, [0.2, 0.3, 0.5]).logpmf(x), rtol=1e-5)

    def test_entropy(self):
        d = Multinomial(10, np.array([0.2, 0.3, 0.5]))
        np.testing.assert_allclose(
            npv(d.entropy()),
            st.multinomial(10, [0.2, 0.3, 0.5]).entropy(), rtol=1e-4)

    def test_entropy_zero_prob_category(self):
        d = Multinomial(10, np.array([0.5, 0.5, 0.0]))
        np.testing.assert_allclose(
            npv(d.entropy()),
            st.multinomial(10, [0.5, 0.5]).entropy(), rtol=1e-4)

    def test_sample(self):
        d = Multinomial(20, np.array([0.25, 0.75]))
        s = npv(d.sample((3000,)))
        assert s.shape == (3000, 2)
        np.testing.assert_allclose(s.sum(-1), 20.0)
        assert abs(s[:, 1].mean() - 15.0) < 0.2


class TestIndependent:
    def test_log_prob_reduces(self):
        base = Normal(np.zeros((3, 4)), np.ones((3, 4)))
        d = Independent(base, 1)
        assert d.batch_shape == (3,)
        assert d.event_shape == (4,)
        x = np.random.RandomState(0).randn(3, 4)
        np.testing.assert_allclose(
            npv(d.log_prob(paddle.to_tensor(x))),
            st.norm(0, 1).logpdf(x).sum(-1), rtol=1e-5)


class TestTransforms:
    def test_affine_roundtrip_logdet(self):
        t = AffineTransform(2.0, 3.0)
        x = np.array([0.5, -1.0])
        y = npv(t.forward(paddle.to_tensor(x)))
        np.testing.assert_allclose(y, 2.0 + 3.0 * x, rtol=1e-6)
        np.testing.assert_allclose(npv(t.inverse(paddle.to_tensor(y))), x,
                                   rtol=1e-6)
        np.testing.assert_allclose(
            npv(t.forward_log_det_jacobian(paddle.to_tensor(x))),
            np.log(3.0), rtol=1e-6)

    def test_exp_sigmoid_tanh(self):
        for t, ref_fwd in [(ExpTransform(), np.exp),
                           (SigmoidTransform(),
                            lambda v: 1 / (1 + np.exp(-v))),
                           (TanhTransform(), np.tanh)]:
            x = np.array([0.3, -0.7])
            y = npv(t.forward(paddle.to_tensor(x)))
            np.testing.assert_allclose(y, ref_fwd(x), rtol=1e-5)
            np.testing.assert_allclose(npv(t.inverse(paddle.to_tensor(y))),
                                       x, rtol=1e-4)
            # log-det matches numerical dy/dx
            eps = 1e-4
            num = (ref_fwd(x + eps) - ref_fwd(x - eps)) / (2 * eps)
            np.testing.assert_allclose(
                npv(t.forward_log_det_jacobian(paddle.to_tensor(x))),
                np.log(np.abs(num)), atol=1e-4)

    def test_stickbreaking(self):
        t = StickBreakingTransform()
        x = np.array([0.2, -0.5, 1.0])
        y = npv(t.forward(paddle.to_tensor(x)))
        assert y.shape == (4,)
        np.testing.assert_allclose(y.sum(), 1.0, rtol=1e-5)
        np.testing.assert_allclose(npv(t.inverse(paddle.to_tensor(y))), x,
                                   rtol=1e-4)

    def test_lognormal_via_transformed(self):
        d = TransformedDistribution(Normal(0.3, 0.8), [ExpTransform()])
        x = np.array([0.5, 1.5, 3.0])
        np.testing.assert_allclose(
            npv(d.log_prob(paddle.to_tensor(x))),
            st.lognorm(s=0.8, scale=np.exp(0.3)).logpdf(x), rtol=1e-5)
        s = npv(d.sample((30000,)))
        np.testing.assert_allclose(s.mean(),
                                   st.lognorm(s=0.8,
                                              scale=np.exp(0.3)).mean(),
                                   rtol=0.05)

    def test_call_coerces_raw_values(self):
        t = ExpTransform()
        np.testing.assert_allclose(npv(t(np.array([0.0, 1.0]))),
                                   [1.0, np.e], rtol=1e-6)
        d = t(Normal(0.0, 1.0))
        assert isinstance(d, TransformedDistribution)
        chained = t(AffineTransform(0.0, 2.0))
        np.testing.assert_allclose(npv(chained(np.array([1.0]))),
                                   np.exp(2.0), rtol=1e-6)

    def test_chain(self):
        t = ChainTransform([AffineTransform(1.0, 2.0), ExpTransform()])
        x = np.array([0.1, 0.4])
        y = npv(t.forward(paddle.to_tensor(x)))
        np.testing.assert_allclose(y, np.exp(1 + 2 * x), rtol=1e-5)
        ld = npv(t.forward_log_det_jacobian(paddle.to_tensor(x)))
        np.testing.assert_allclose(ld, np.log(2.0) + (1 + 2 * x), rtol=1e-5)


class TestRegisterKL:
    def test_custom_dispatch(self):
        class MyNormal(Normal):
            pass

        calls = []

        @register_kl(MyNormal, Normal)
        def _kl(p, q):  # noqa: ARG001
            calls.append(1)
            return paddle.to_tensor(0.0)

        kl_divergence(MyNormal(0.0, 1.0), Normal(0.0, 1.0))
        assert calls  # most-derived match picked over (Normal, Normal)
