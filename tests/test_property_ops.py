"""Property-based op semantics vs numpy (hypothesis; derandomized so CI
is deterministic). Complements the table-driven numeric sweep with
randomized shapes/broadcasting/axis combinations — the input space where
hand-picked cases miss edge geometry.
"""
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed in this image")
from hypothesis import given, settings, strategies as st  # noqa: E402

import paddle_tpu as paddle  # noqa: E402

_SET = settings(max_examples=40, deadline=None, derandomize=True)


def _shapes_broadcastable(draw):
    """A pair of shapes that numpy-broadcast together."""
    base = draw(st.lists(st.integers(1, 4), min_size=1, max_size=3))
    a, b = list(base), list(base)
    for i in range(len(base)):
        which = draw(st.integers(0, 2))
        if which == 0:
            a[i] = 1
        elif which == 1:
            b[i] = 1
    cut = draw(st.integers(0, len(base) - 1))
    return tuple(a), tuple(b[cut:]) if draw(st.booleans()) else tuple(b)


shapes_pair = st.composite(_shapes_broadcastable)()


@_SET
@given(shapes_pair, st.sampled_from(["add", "subtract", "multiply",
                                     "maximum", "minimum"]))
def test_binary_broadcast_matches_numpy(shapes, opname):
    sa, sb = shapes
    rng = np.random.RandomState(hash((sa, sb, opname)) % (2 ** 31))
    a = rng.randn(*sa).astype(np.float32)
    b = rng.randn(*sb).astype(np.float32)
    got = getattr(paddle, opname)(paddle.to_tensor(a),
                                  paddle.to_tensor(b)).numpy()
    want = getattr(np, opname)(a, b)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert got.shape == want.shape


@_SET
@given(st.lists(st.integers(1, 5), min_size=1, max_size=4),
       st.sampled_from(["sum", "mean", "max", "min", "prod"]),
       st.booleans(), st.data())
def test_reductions_match_numpy(shape, red, keepdim, data):
    rng = np.random.RandomState(hash((tuple(shape), red)) % (2 ** 31))
    a = rng.randn(*shape).astype(np.float32)
    axis = data.draw(st.one_of(
        st.none(), st.integers(-len(shape), len(shape) - 1)))
    got = getattr(paddle, red)(paddle.to_tensor(a), axis=axis,
                               keepdim=keepdim).numpy()
    want = getattr(np, red if red != "prod" else "prod")(
        a, axis=axis, keepdims=keepdim)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@_SET
@given(st.lists(st.integers(1, 6), min_size=2, max_size=4), st.data())
def test_reshape_transpose_roundtrip(shape, data):
    rng = np.random.RandomState(hash(tuple(shape)) % (2 ** 31))
    a = rng.randn(*shape).astype(np.float32)
    perm = data.draw(st.permutations(range(len(shape))))
    t = paddle.transpose(paddle.to_tensor(a), list(perm))
    np.testing.assert_array_equal(t.numpy(), np.transpose(a, perm))
    # inverse permutation restores the original
    inv = [0] * len(perm)
    for i, p in enumerate(perm):
        inv[p] = i
    back = paddle.transpose(t, inv)
    np.testing.assert_array_equal(back.numpy(), a)
    flat = paddle.reshape(back, [-1])
    np.testing.assert_array_equal(flat.numpy(), a.reshape(-1))


@_SET
@given(st.integers(1, 4), st.integers(1, 5), st.integers(1, 5),
       st.integers(1, 5))
def test_matmul_matches_numpy(b, m, k, n):
    rng = np.random.RandomState(hash((b, m, k, n)) % (2 ** 31))
    x = rng.randn(b, m, k).astype(np.float32)
    y = rng.randn(b, k, n).astype(np.float32)
    got = paddle.matmul(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
    np.testing.assert_allclose(got, x @ y, rtol=1e-5, atol=1e-5)


@_SET
@given(st.lists(st.integers(1, 5), min_size=1, max_size=3), st.data())
def test_concat_split_roundtrip(shape, data):
    axis = data.draw(st.integers(0, len(shape) - 1))
    parts = data.draw(st.integers(1, 3))
    rng = np.random.RandomState(hash((tuple(shape), axis, parts))
                                % (2 ** 31))
    arrs = [rng.randn(*shape).astype(np.float32) for _ in range(parts)]
    cat = paddle.concat([paddle.to_tensor(a) for a in arrs], axis=axis)
    np.testing.assert_array_equal(cat.numpy(),
                                  np.concatenate(arrs, axis=axis))
    back = paddle.split(cat, parts, axis=axis)
    for got, want in zip(back, arrs):
        np.testing.assert_array_equal(got.numpy(), want)


@_SET
@given(st.lists(st.integers(1, 5), min_size=1, max_size=3))
def test_grad_of_sum_is_ones(shape):
    rng = np.random.RandomState(hash(tuple(shape)) % (2 ** 31))
    x = paddle.to_tensor(rng.randn(*shape).astype(np.float32))
    x.stop_gradient = False
    (x * x).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-6)


@_SET
@given(st.sampled_from(["ij,jk->ik", "bij,bjk->bik", "ij->ji", "ii->i",
                        "ij,ij->", "bij->b", "ij,kj->ik",
                        "abc,cd,de->abe"]))
def test_einsum_matches_numpy(eq):
    rng = np.random.RandomState(hash(eq) % (2 ** 31))
    ins = eq.split("->")[0].split(",")
    dims = {}
    arrs = []
    for term in ins:
        shape = []
        for ch in term:
            dims.setdefault(ch, rng.randint(2, 5))
            shape.append(dims[ch])
        arrs.append(rng.randn(*shape).astype(np.float32))
    got = paddle.einsum(eq, *[paddle.to_tensor(a) for a in arrs]).numpy()
    np.testing.assert_allclose(got, np.einsum(eq, *arrs), rtol=1e-5,
                               atol=1e-5)


@_SET
@given(st.lists(st.integers(1, 6), min_size=1, max_size=3), st.data())
def test_sort_argsort_topk_consistency(shape, data):
    axis = data.draw(st.integers(-len(shape), len(shape) - 1))
    desc = data.draw(st.booleans())
    rng = np.random.RandomState(hash((tuple(shape), axis, desc))
                                % (2 ** 31))
    a = rng.randn(*shape).astype(np.float32)
    t = paddle.to_tensor(a)
    s = paddle.sort(t, axis=axis, descending=desc).numpy()
    idx = paddle.argsort(t, axis=axis, descending=desc).numpy()
    ref = np.sort(a, axis=axis)
    if desc:
        ref = np.flip(ref, axis=axis)
    np.testing.assert_array_equal(s, ref)
    # argsort gathers back to the sorted values
    np.testing.assert_array_equal(
        np.take_along_axis(a, idx.astype(np.int64), axis=axis), s)
    k = data.draw(st.integers(1, shape[axis]))
    vals, vidx = paddle.topk(t, k, axis=axis)
    np.testing.assert_array_equal(
        np.take_along_axis(a, vidx.numpy().astype(np.int64), axis=axis),
        vals.numpy())


@_SET
@given(st.lists(st.integers(1, 5), min_size=1, max_size=3), st.data())
def test_cumsum_cumprod_match_numpy(shape, data):
    axis = data.draw(st.integers(0, len(shape) - 1))
    rng = np.random.RandomState(hash(tuple(shape)) % (2 ** 31))
    a = rng.randn(*shape).astype(np.float32)
    np.testing.assert_allclose(
        paddle.cumsum(paddle.to_tensor(a), axis=axis).numpy(),
        np.cumsum(a, axis=axis), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        paddle.cumprod(paddle.to_tensor(a), dim=axis).numpy(),
        np.cumprod(a, axis=axis), rtol=1e-4, atol=1e-5)
