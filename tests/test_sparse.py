"""paddle.sparse tests (reference: python/paddle/sparse + phi sparse kernels)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_sparse_coo_create_roundtrip():
    indices = [[0, 1, 2], [1, 2, 0]]
    values = [1.0, 2.0, 3.0]
    s = paddle.sparse.sparse_coo_tensor(indices, values, shape=[3, 3])
    dense = np.zeros((3, 3), np.float32)
    dense[0, 1], dense[1, 2], dense[2, 0] = 1, 2, 3
    np.testing.assert_allclose(s.to_dense().numpy(), dense)
    assert s.nnz() == 3
    np.testing.assert_allclose(np.asarray(s.indices()._value), indices)
    np.testing.assert_allclose(np.asarray(s.values()._value), values)
    assert s.is_sparse_coo() and not s.is_sparse_csr()


def test_sparse_coo_infer_shape():
    s = paddle.sparse.sparse_coo_tensor([[0, 2], [1, 3]], [5.0, 7.0])
    assert s.shape == [3, 4]


def test_sparse_csr_create_roundtrip():
    crows = [0, 2, 3, 5]
    cols = [1, 3, 2, 0, 1]
    values = [1.0, 2.0, 3.0, 4.0, 5.0]
    s = paddle.sparse.sparse_csr_tensor(crows, cols, values, [3, 4])
    dense = np.zeros((3, 4), np.float32)
    dense[0, 1], dense[0, 3], dense[1, 2] = 1, 2, 3
    dense[2, 0], dense[2, 1] = 4, 5
    np.testing.assert_allclose(s.to_dense().numpy(), dense)
    np.testing.assert_allclose(np.asarray(s.crows()._value), crows)
    assert s.is_sparse_csr()


def test_dense_to_sparse_and_back():
    x = paddle.to_tensor(np.array([[0.0, 1.0], [2.0, 0.0]], np.float32))
    s = x.to_sparse_coo(2)
    assert s.nnz() == 2
    np.testing.assert_allclose(s.to_dense().numpy(), x.numpy())
    csr = s.to_sparse_csr()
    np.testing.assert_allclose(csr.to_dense().numpy(), x.numpy())
    back = csr.to_sparse_coo(2)
    np.testing.assert_allclose(back.to_dense().numpy(), x.numpy())


def test_sparse_relu():
    x = paddle.to_tensor(np.array([[0.0, -1.0], [2.0, -3.0]], np.float32))
    s = paddle.sparse.relu(x.to_sparse_coo(2))
    np.testing.assert_allclose(s.to_dense().numpy(),
                               np.maximum(x.numpy(), 0))
    layer = paddle.sparse.ReLU()
    s2 = layer(x.to_sparse_coo(2))
    np.testing.assert_allclose(s2.to_dense().numpy(),
                               np.maximum(x.numpy(), 0))


def test_sparse_matmul():
    rng = np.random.RandomState(0)
    dense = rng.randn(8, 6).astype(np.float32)
    dense[dense < 0.5] = 0.0  # ~70% sparse
    y = rng.randn(6, 4).astype(np.float32)
    s = paddle.to_tensor(dense).to_sparse_coo(2)
    out = paddle.sparse.matmul(s, paddle.to_tensor(y))
    np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5)
    # CSR path
    out2 = paddle.sparse.matmul(
        paddle.to_tensor(dense).to_sparse_coo(2).to_sparse_csr(),
        paddle.to_tensor(y))
    np.testing.assert_allclose(out2.numpy(), dense @ y, rtol=1e-5)


def test_masked_matmul():
    rng = np.random.RandomState(1)
    x = rng.randn(5, 3).astype(np.float32)
    y = rng.randn(3, 5).astype(np.float32)
    mask_dense = (rng.rand(5, 5) < 0.4).astype(np.float32)
    mask = paddle.to_tensor(mask_dense).to_sparse_coo(2)
    out = paddle.sparse.masked_matmul(paddle.to_tensor(x),
                                      paddle.to_tensor(y), mask)
    expect = (x @ y) * mask_dense
    np.testing.assert_allclose(out.to_dense().numpy(), expect, rtol=1e-5)


def test_sparse_matmul_grad():
    """Sparse values participate in jax autodiff (BCOO is a pytree)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import sparse as jsparse

    dense = np.array([[1.0, 0.0], [0.0, 2.0]], np.float32)
    bcoo = jsparse.BCOO.fromdense(jnp.asarray(dense))
    y = jnp.ones((2, 2), jnp.float32)

    def loss(data):
        m = jsparse.BCOO((data, bcoo.indices), shape=bcoo.shape)
        return (m @ y).sum()

    g = jax.grad(loss)(bcoo.data)
    np.testing.assert_allclose(np.asarray(g), [2.0, 2.0])
