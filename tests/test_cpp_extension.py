"""csrc staging pool + utils.cpp_extension (VERDICT item 8).

Reference: python/paddle/utils/cpp_extension/cpp_extension.py:736 (JIT load),
fluid/operators/reader/buffered_reader.cc (staging buffers).
"""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.runtime.staging import StagingPool, staging_lib


@pytest.fixture(scope="module")
def lib():
    return staging_lib()  # compiles csrc/staging_pool.cpp once


def test_load_is_cached(lib):
    import time

    t0 = time.perf_counter()
    again = staging_lib()
    assert time.perf_counter() - t0 < 0.5  # content-hash cache hit
    assert again is lib


def test_ring_roundtrip(lib):
    pool = StagingPool(n_slots=2, slot_bytes=1 << 16)
    a = np.arange(100, dtype=np.float32).reshape(10, 10)
    b = np.arange(7, dtype=np.int64)
    slot, meta = pool.stage([a, b])
    got = pool.acquire_read()
    assert got == slot
    va, vb = pool.view_arrays(got, meta)
    np.testing.assert_array_equal(va, a)
    np.testing.assert_array_equal(vb, b)
    pool.release(got)
    pool.close()


def test_ring_fifo_and_blocking(lib):
    pool = StagingPool(n_slots=2, slot_bytes=4096)
    s0, m0 = pool.stage([np.full(4, 0.0)])
    s1, m1 = pool.stage([np.full(4, 1.0)])
    # pool exhausted: non-blocking write acquisition times out
    assert pool.acquire_write(timeout_ms=50) == -1
    # consumer sees FIFO order
    r = pool.acquire_read()
    assert r == s0
    np.testing.assert_array_equal(pool.view_arrays(r, m0)[0], 0.0)
    pool.release(r)
    # a slot freed unblocks the producer
    assert pool.acquire_write(timeout_ms=1000) == s0
    pool.close()


def test_oversize_batch_rejected(lib):
    pool = StagingPool(n_slots=1, slot_bytes=64)
    slot = pool.acquire_write()
    with pytest.raises(ValueError):
        pool.write_arrays(slot, [np.zeros(1024, np.float32)])
    pool.close()


def test_parallel_producers(lib):
    pool = StagingPool(n_slots=4, slot_bytes=1 << 20)
    results = {}
    lock = threading.Lock()

    def produce(i):
        arr = np.full(1000, float(i), np.float32)
        staged = pool.stage([arr])
        with lock:
            results[staged[0]] = (i, staged[1])

    threads = [threading.Thread(target=produce, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seen = set()
    for _ in range(4):
        slot = pool.acquire_read()
        i, meta = results[slot]
        np.testing.assert_array_equal(pool.view_arrays(slot, meta)[0],
                                      float(i))
        seen.add(i)
        pool.release(slot)
    assert seen == {0, 1, 2, 3}
    pool.close()


class _ArrayDataset(paddle.io.Dataset):
    def __init__(self, n=64):
        self.x = np.random.RandomState(0).randn(n, 3, 8, 8).astype(np.float32)
        self.y = np.arange(n, dtype=np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def test_dataloader_staging_parity():
    ds = _ArrayDataset()
    plain = paddle.io.DataLoader(ds, batch_size=8, num_workers=2)
    staged = paddle.io.DataLoader(ds, batch_size=8, num_workers=2,
                                  use_staging_pool=True)
    got_plain = [(x.numpy(), y.numpy()) for x, y in plain]
    got_staged = [(x.numpy(), y.numpy()) for x, y in staged]
    assert len(got_plain) == len(got_staged) == 8
    for (xp, yp), (xs, ys) in zip(got_plain, got_staged):
        np.testing.assert_array_equal(xp, xs)
        np.testing.assert_array_equal(yp, ys)
    assert staged._pool is not None  # the staging path actually engaged


def test_dataloader_staging_reiteration():
    ds = _ArrayDataset(32)
    loader = paddle.io.DataLoader(ds, batch_size=8, num_workers=2,
                                  use_staging_pool=True)
    for _ in range(3):  # slots must recycle across epochs
        assert sum(1 for _ in loader) == 4
    # early break must not leak slots
    it = iter(loader)
    next(it)
    del it
    assert sum(1 for _ in loader) == 4


def test_dataloader_staging_unstageable_falls_back():
    """A non-numpy component (Tensor label) must fall back to the normal
    collate — not get silently dropped by the None pytree hole."""

    class MixedDataset(paddle.io.Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return (np.full((4,), float(i), np.float32),
                    paddle.to_tensor(np.int64(i)))

    loader = paddle.io.DataLoader(MixedDataset(), batch_size=4,
                                  num_workers=2, use_staging_pool=True)
    batches = list(loader)
    assert len(batches) == 4
    for x, y in batches:
        assert y is not None
        np.testing.assert_array_equal(x.numpy()[:, 0], y.numpy())


def test_cpp_extension_compile_error(tmp_path):
    bad = tmp_path / "bad.cpp"
    bad.write_text("this is not C++")
    from paddle_tpu.utils.cpp_extension import load

    with pytest.raises(RuntimeError, match="failed"):
        load("bad_ext", [str(bad)], build_directory=str(tmp_path))
