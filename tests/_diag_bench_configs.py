"""Fake CONFIGS for the diagnostics deadline-kill acceptance
(BENCH_CONFIGS_MODULE): one config that produces real dispatch traffic
and then wedges forever — the shape of every rc=124 bench round the
flight recorder exists for. The campaign child running it must be
SIGTERMable at any point and leave a postmortem bundle."""
import time


def _hang():
    import numpy as np

    import paddle_tpu as paddle

    t = paddle.to_tensor(np.ones((8, 8), "float32"))
    for _ in range(4):
        float(paddle.tanh(paddle.matmul(t, t)).sum())
    while True:  # the wedge a per-config deadline exists to kill
        paddle.tanh(paddle.matmul(t, t)).sum()
        time.sleep(0.05)


CONFIGS = {"hang": (_hang, {}, 60)}
