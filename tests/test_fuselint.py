"""fuselint: static fusion-barrier analyzer on the shared staticlib
core, plus the runtime cross-reference machinery.

Locks the ISSUE-11 acceptance surface:
  * fixture detections for all 7 rules (FL001–FL007);
  * precision controls that must NOT fire (shape/dtype/ndim reads —
    LazyArray serves them eagerly, host-container truthiness, the
    sanctioned fusion.lazy_* routes, eager-only non-loop code, waived
    sites);
  * the CLI exit-code contract and baseline freshness of the shipped
    tree;
  * SARIF output round-trips for all three linters;
  * the unified tools/staticcheck.py entry point;
  * the --verify-runtime cross-reference (unit-level, no subprocess);
  * the staticlib-growth regression: tracelint AND threadlint still
    analyze the tree to BYTE-IDENTICAL baselines.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.fuselint import analyzer  # noqa: E402
from tools.staticlib import baseline as slib_baseline  # noqa: E402


# ---------------------------------------------------------------------------
# fixture code exercising every rule

FIXTURE = textwrap.dedent('''
    import numpy as np
    import jax.numpy as jnp
    import logging
    import paddle
    from paddle import tensor as T
    from paddle_tpu.core.fusion import lazy_add
    from paddle_tpu.core import dispatch

    log = logging.getLogger(__name__)


    def train_loop(data, model):
        for batch in data:
            loss = paddle.mean(model(batch))
            loss.backward()
            host = float(loss)             # FL001: per-step flush
            arr = loss.numpy()             # FL001: per-step flush
            print(loss)                    # FL005: per-step print
            log.info("loss %s", loss)      # FL005: per-step log
            msg = f"step loss {loss}"      # FL005: per-step f-string
            if loss > 0:                   # FL002: bool() on a tensor
                pass
            # precision controls: LazyArray serves these eagerly
            n = loss.shape
            d = loss.dtype
            k = loss.ndim
            if len(loss.shape) > 1:        # control: sanitized, clean
                pass
            waived = float(loss)  # fuselint: ok[FL001] reviewed sync
        return host, arr, msg, n, d, k, waived


    def eager_only(x):
        y = paddle.tanh(x)
        return float(y)                    # control: not in a loop


    def host_counter_loop(items):
        total = 0
        for it in items:
            total += 1                     # control: host ints only
            if total > 3:                  # control: host branch
                break
        return total


    @dispatch.non_jittable
    def value_dependent_op(v):             # FL003: declared barrier
        return v


    def traced_region(fn, x):
        with dispatch.suspend():           # FL004: mandatory flush
            return fn(x)


    def waived_region(fn, x):
        with dispatch.suspend():  # fuselint: ok[FL004] reviewed boundary
            return fn(x)


    def run_backward(nodes, cot):
        for node in nodes:
            cot = jnp.maximum(cot, 0)      # FL006: raw jnp on cotangent
            cot = cot + 1                  # FL006: bare add escape
            cot = lazy_add(cot, 1)         # control: sanctioned route
        return cot


    def huge_unrolled(x):
        for i in range(300):               # FL007: 300 ops >= max cap
            x = paddle.tanh(x)
        return x


    def short_loop(x):
        for i in range(4):                 # control: tiny trace
            x = paddle.tanh(x)
        return x
''')

MANIFEST_FIXTURE = textwrap.dedent('''
    MANIFEST_VERSION = 1
    UNJITTABLE = {
        ("fixture_fuse.py", "value_dependent_op", 999):
            "TL001 host-materialize",
    }
''')


@pytest.fixture(scope="module")
def fixture_findings(tmp_path_factory):
    d = tmp_path_factory.mktemp("fuselint_fixture")
    p = d / "fixture_fuse.py"
    p.write_text(FIXTURE)
    mp = d / "_manifest_fixture.py"
    mp.write_text(MANIFEST_FIXTURE)
    findings, errors = analyzer.analyze_paths([str(p)],
                                              manifest_path=str(mp))
    assert not errors
    return findings


def _hits(findings, rule, where=""):
    return [f for f in findings
            if f.rule == rule and where in f.func and not f.suppressed]


# -- detections (all 7 rules) -------------------------------------------------

def test_all_seven_rules_detect_on_fixture(fixture_findings):
    rules = {f.rule for f in fixture_findings if not f.suppressed}
    assert {"host-materialize-in-loop", "data-dependent-branch",
            "known-demotion-barrier", "suspend-region-entry",
            "per-step-side-effect", "backward-path-escape",
            "trace-length-hazard"} <= rules, rules


def test_fl001_host_materialize_in_loop(fixture_findings):
    hits = _hits(fixture_findings, "host-materialize-in-loop",
                 "train_loop")
    syms = {f.symbol for f in hits}
    assert "float:loss" in syms and ".numpy" in syms, syms
    assert all(f.severity == "error" for f in hits)


def test_fl002_data_dependent_branch(fixture_findings):
    hits = _hits(fixture_findings, "data-dependent-branch", "train_loop")
    assert hits and hits[0].symbol == "if:loss"


def test_fl003_known_demotion_barrier(fixture_findings):
    hits = _hits(fixture_findings, "known-demotion-barrier")
    syms = {f.symbol for f in hits}
    # both halves: the @non_jittable decoration AND the manifest entry
    assert "non_jittable:value_dependent_op" in syms, syms
    assert "manifest:value_dependent_op" in syms, syms


def test_fl004_suspend_region_entry(fixture_findings):
    hits = _hits(fixture_findings, "suspend-region-entry",
                 "traced_region")
    assert hits and hits[0].symbol.startswith("suspend:")


def test_fl005_per_step_side_effect(fixture_findings):
    hits = _hits(fixture_findings, "per-step-side-effect", "train_loop")
    syms = {f.symbol for f in hits}
    assert "print:loss" in syms, syms
    assert "log:loss" in syms, syms
    assert "fstr:loss" in syms, syms


def test_fl006_backward_path_escape(fixture_findings):
    hits = _hits(fixture_findings, "backward-path-escape",
                 "run_backward")
    syms = {f.symbol for f in hits}
    assert "escape:jnp.maximum" in syms, syms
    assert "add:cot" in syms, syms


def test_fl007_trace_length_hazard(fixture_findings):
    hits = _hits(fixture_findings, "trace-length-hazard",
                 "huge_unrolled")
    assert hits and hits[0].symbol == "ops~300"
    assert hits[0].confidence == "definite"


# -- precision controls -------------------------------------------------------

def test_shape_dtype_ndim_reads_are_clean(fixture_findings):
    """The FL002 precision contract: LazyArray serves shape/dtype/ndim
    (and len() over them) from memoized avals with no flush — none of
    those reads may produce a finding."""
    for f in fixture_findings:
        assert not any(tok in f.symbol for tok in ("n", "d", "k")
                       if f.symbol in (f"if:{tok}",)), f.symbol
    branch_hits = _hits(fixture_findings, "data-dependent-branch",
                        "train_loop")
    assert {f.symbol for f in branch_hits} == {"if:loss"}


def test_eager_only_non_loop_code_is_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if "eager_only" in f.func and not f.suppressed]


def test_host_only_loop_is_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if "host_counter_loop" in f.func and not f.suppressed]


def test_sanctioned_lazy_route_is_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if f.rule == "backward-path-escape"
                and "lazy_add" in f.symbol]


def test_short_loop_below_cap_is_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if "short_loop" in f.func and not f.suppressed]


def test_waived_sites_are_suppressed_not_new(fixture_findings):
    waived = [f for f in fixture_findings
              if "waived_region" in f.func or (
                  "train_loop" in f.func and f.line and f.suppressed)]
    assert any(f.rule == "suspend-region-entry" and f.suppressed
               for f in fixture_findings if "waived_region" in f.func)
    assert any(f.rule == "host-materialize-in-loop" and f.suppressed
               for f in fixture_findings if "train_loop" in f.func)
    assert waived


def test_fingerprints_are_line_number_free(tmp_path):
    (tmp_path / "a.py").write_text(FIXTURE)
    (tmp_path / "b.py").write_text("# unrelated leading comment\n"
                                   + FIXTURE)
    fa, _ = analyzer.analyze_paths([str(tmp_path / "a.py")])
    fb, _ = analyzer.analyze_paths([str(tmp_path / "b.py")])
    fp_a = sorted(f.fingerprint().split("|", 2)[2] for f in fa)
    fp_b = sorted(f.fingerprint().split("|", 2)[2] for f in fb)
    assert fp_a == fp_b


def test_machinery_modules_are_exempt():
    """core/fusion.py and core/dispatch.py ARE the flush protocol; their
    internal concrete()/materialize calls must never self-flag."""
    for suffix in ("fusion.py", "dispatch.py"):
        path = os.path.join(REPO_ROOT, "paddle_tpu", "core", suffix)
        findings, _ = analyzer.analyze_paths([path])
        assert not findings, [(f.rule, f.line) for f in findings]


# -- the shipped tree ---------------------------------------------------------

def test_shipped_baseline_is_fresh():
    """The checked-in baseline matches what the analyzer produces today
    (no stale entries, no unbaselined findings)."""
    findings, errors = analyzer.analyze_paths(
        [os.path.join(REPO_ROOT, "paddle_tpu")])
    assert not errors
    bl = slib_baseline.load_baseline(
        os.path.join(REPO_ROOT, "tools", "fuselint", "baseline.json"))
    new, baselined, _sup, _info, stale = slib_baseline.partition(
        findings, bl)
    assert not new, [(f.path, f.rule, f.symbol) for f in new]
    assert not stale, stale


def test_step_path_barriers_are_reviewed():
    """The ISSUE-11 triage contract: every barrier in the default
    train-step path (optimizer concretize boundary, eager-backward
    fallback, the hapi suspend) carries a reviewed inline waiver."""
    opt = os.path.join(REPO_ROOT, "paddle_tpu", "optimizer",
                       "optimizer.py")
    findings, _ = analyzer.analyze_paths([opt])
    # the concretize boundary lives in _step_impl since the span-traced
    # step() wrapper landed (PR 12) — the reviewed waivers moved with it
    step = [f for f in findings if f.func == "Optimizer._step_impl"
            and f.rule == "host-materialize-in-loop"]
    assert step and all(f.suppressed for f in step), [
        (f.line, f.suppressed) for f in step]
    ag = os.path.join(REPO_ROOT, "paddle_tpu", "core", "autograd.py")
    findings, _ = analyzer.analyze_paths([ag])
    assert all(f.suppressed for f in findings
               if f.rule in ("host-materialize-in-loop",
                             "known-demotion-barrier")), [
        (f.line, f.rule) for f in findings if not f.suppressed]


# -- CLI contract -------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.fuselint", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)


def test_cli_clean_tree_exits_zero():
    r = _run_cli("paddle_tpu", "--fail-stale")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_synthetic_violation_fails(tmp_path):
    pkg = tmp_path / "synthpkg"
    pkg.mkdir()
    (pkg / "hot.py").write_text(textwrap.dedent('''
        import paddle


        def loop(data, model):
            for batch in data:
                loss = paddle.mean(model(batch))
                print(float(loss))
    '''))
    r = _run_cli(str(pkg))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "FL001" in r.stdout


def test_cli_write_baseline_then_clean(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "hot.py").write_text(textwrap.dedent('''
        import paddle


        def loop(data, model):
            for batch in data:
                loss = paddle.mean(model(batch))
                print(float(loss))
    '''))
    bl = tmp_path / "baseline.json"
    assert _run_cli(str(pkg), "--baseline", str(bl)).returncode == 1
    assert _run_cli(str(pkg), "--baseline", str(bl),
                    "--write-baseline").returncode == 0
    r = _run_cli(str(pkg), "--baseline", str(bl))
    assert r.returncode == 0, r.stdout
    assert "baselined" in r.stdout
    # fixing the debt leaves a stale entry: --fail-stale gates on it
    (pkg / "hot.py").write_text("def loop():\n    return 0\n")
    assert _run_cli(str(pkg), "--baseline", str(bl)).returncode == 0
    r = _run_cli(str(pkg), "--baseline", str(bl), "--fail-stale")
    assert r.returncode == 1
    assert "stale" in r.stdout


# -- SARIF (shared staticlib exporter, all three linters) ---------------------

def _assert_sarif_shape(doc, tool, want_rules):
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == tool
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert want_rules <= rule_ids, rule_ids
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert res["partialFingerprints"]["staticlibFingerprint/v1"]
    return run


def test_sarif_round_trip_fuselint(tmp_path, fixture_findings):
    """The SARIF report reproduces the analyzer's findings: every
    non-info finding appears once, waived findings carry inSource
    suppressions, and fingerprints survive the round trip."""
    d = tmp_path / "fx"
    d.mkdir()
    (d / "fixture_fuse.py").write_text(FIXTURE)
    out = tmp_path / "fuselint.sarif"
    r = _run_cli(str(d), "--no-baseline", "--sarif", str(out))
    assert r.returncode == 1  # new findings on the fixture
    doc = json.loads(out.read_text())
    run = _assert_sarif_shape(doc, "fuselint",
                              {"FL001", "FL002", "FL005"})
    sarif_fps = {res["partialFingerprints"]["staticlibFingerprint/v1"]
                 for res in run["results"]}
    live, _ = analyzer.analyze_paths([str(d)])  # same root as the CLI
    assert {f.fingerprint() for f in live} == sarif_fps
    suppressed = [res for res in run["results"]
                  if res.get("suppressions")]
    assert suppressed and all(
        s["suppressions"][0]["kind"] == "inSource" for s in suppressed)


def test_sarif_output_tracelint_and_threadlint(tmp_path):
    for tool, rule in (("tracelint", "TL001"), ("threadlint", "CL001")):
        out = tmp_path / f"{tool}.sarif"
        r = subprocess.run(
            [sys.executable, "-m", f"tools.{tool}", "paddle_tpu",
             "--sarif", str(out)],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(out.read_text())
        _assert_sarif_shape(doc, tool, {rule})


# -- staticcheck unified entry point ------------------------------------------

def test_staticcheck_runs_all_tools_clean(tmp_path):
    out = tmp_path / "combined.json"
    r = subprocess.run(
        [sys.executable, "tools/staticcheck.py", "paddle_tpu",
         "--json", str(out)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["staticcheck"]["clean"] is True
    assert set(doc["staticcheck"]["ran"]) == {
        "tracelint", "threadlint", "fuselint", "distlint", "schema"}
    for tool in ("tracelint", "threadlint", "fuselint", "distlint"):
        assert doc["tools"][tool]["summary"]["new"] == 0
        assert doc["tools"][tool]["exit_code"] == 0
    assert doc["tools"]["schema"]["problems"] == []


def test_staticcheck_fails_on_violation(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(textwrap.dedent('''
        import threading
        import paddle

        _state = {"n": 0}


        def _worker():
            _state["n"] += 1


        def read():
            return _state["n"]


        def launch():
            threading.Thread(target=_worker).start()


        def loop(data, model):
            for batch in data:
                print(float(paddle.mean(model(batch))))
    '''))
    r = subprocess.run(
        [sys.executable, "tools/staticcheck.py", str(pkg)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=600)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "threadlint" in r.stderr and "fuselint" in r.stderr


# -- verify-runtime cross-reference (unit level) ------------------------------

def test_cross_reference_confirms_and_reports_gaps(fixture_findings):
    from tools.fuselint.verify import cross_reference

    f = next(f for f in fixture_findings
             if f.rule == "host-materialize-in-loop"
             and f.symbol == "float:loss")
    flush_sites = {
        "materialize": {
            # exactly at the static finding's line: the closest match
            f"{f.path}:{f.line}": 5,
            # an in-tree site far from every finding: a recall gap
            f"{f.path}:9999": 2,
            # a driver-script site: external, never a gap
            "my_train.py:33": 1,
        },
    }
    rep = cross_reference(fixture_findings, flush_sites,
                          roots=(f.path,))
    confirmed_fps = {c["fingerprint"] for c in rep["confirmed"]}
    assert f.fingerprint() in confirmed_fps
    assert len(rep["runtime_only"]) == 1
    assert rep["runtime_only"][0]["site"].endswith(":9999")
    assert len(rep["external_sites"]) == 1
    assert rep["external_sites"][0]["site"] == "my_train.py:33"
    # static_only counts FINDINGS whose fingerprint was not confirmed
    # (the float:loss fingerprint covers both the live and the waived
    # occurrence, so count by fingerprint membership, not by entry)
    assert rep["static_only"] == sum(
        1 for x in fixture_findings
        if x.fingerprint() not in confirmed_fps)


# -- staticlib growth regressions ---------------------------------------------

def test_tracelint_baseline_byte_identical():
    from tools.tracelint import analyzer as t_analyzer
    from tools.tracelint import baseline as t_baseline

    findings, errors = t_analyzer.analyze_paths(
        [os.path.join(REPO_ROOT, "paddle_tpu")])
    assert not errors
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "baseline.json")
        t_baseline.write_baseline(out, findings)
        with open(out, "rb") as f1, open(
                os.path.join(REPO_ROOT, "tools", "tracelint",
                             "baseline.json"), "rb") as f2:
            assert f1.read() == f2.read()


def test_threadlint_baseline_byte_identical():
    from tools.threadlint import analyzer as c_analyzer
    from tools.threadlint.__main__ import _COMMENT

    findings, errors = c_analyzer.analyze_paths(
        [os.path.join(REPO_ROOT, "paddle_tpu")])
    assert not errors
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "baseline.json")
        slib_baseline.write_baseline(out, findings, _COMMENT)
        with open(out, "rb") as f1, open(
                os.path.join(REPO_ROOT, "tools", "threadlint",
                             "baseline.json"), "rb") as f2:
            assert f1.read() == f2.read()


def test_all_three_tools_share_the_staticlib_finding_record():
    from tools.fuselint.analyzer import Finding as FlFinding
    from tools.staticlib.findings import Finding as Base
    from tools.threadlint.analyzer import Finding as ClFinding
    from tools.tracelint.analyzer import Finding as TlFinding

    for cls in (TlFinding, ClFinding, FlFinding):
        assert issubclass(cls, Base)
    assert len({id(TlFinding.RULES), id(ClFinding.RULES),
                id(FlFinding.RULES)}) == 3


def test_loop_context_tracking():
    """The staticlib growth this PR shipped: ScopeIndex.enclosing_loops
    and const_range."""
    import ast

    from tools.staticlib.astnav import ScopeIndex, const_range

    tree = ast.parse(textwrap.dedent('''
        def f(xs):
            a = 1
            for x in xs:
                b = 2
                while True:
                    c = 3
            d = [y for y in xs]

            def nested():
                e = 4
    '''))
    scopes = ScopeIndex(tree)
    by_name = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and isinstance(
                n.targets[0], ast.Name):
            by_name[n.targets[0].id] = n
    assert scopes.loop_depth(by_name["a"]) == 0
    assert scopes.loop_depth(by_name["b"]) == 1
    assert scopes.loop_depth(by_name["c"]) == 2
    # a nested def's body is NOT in its definer's loops
    assert scopes.loop_depth(by_name["e"]) == 0
    rng = ast.parse("range(300)", mode="eval").body
    assert const_range(rng) == 300
    assert const_range(ast.parse("range(2, 12)", mode="eval").body) == 10
    assert const_range(ast.parse("range(0, 10, 3)",
                                 mode="eval").body) == 4
    assert const_range(ast.parse("range(n)", mode="eval").body) is None
