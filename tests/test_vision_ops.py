"""paddle.vision.ops tests (reference: python/paddle/vision/ops.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops


def test_nms_basic():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    kept = ops.nms(paddle.to_tensor(boxes), 0.5,
                   scores=paddle.to_tensor(scores))
    np.testing.assert_array_equal(np.asarray(kept._value), [0, 2])


def test_nms_categories_topk():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [0, 0, 10, 10]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.95], np.float32)
    cats = np.array([0, 0, 1])
    kept = ops.nms(paddle.to_tensor(boxes), 0.5,
                   scores=paddle.to_tensor(scores),
                   category_idxs=paddle.to_tensor(cats), categories=[0, 1],
                   top_k=2)
    # per-category: cat0 keeps box0 (suppresses box1), cat1 keeps box2;
    # global score order -> [2, 0]
    np.testing.assert_array_equal(np.asarray(kept._value), [2, 0])


def test_roi_align_uniform_feature():
    """On a constant feature map every bin averages to that constant."""
    x = paddle.to_tensor(np.full((1, 3, 16, 16), 7.0, np.float32))
    boxes = paddle.to_tensor(np.array([[2.0, 2.0, 10.0, 10.0]], np.float32))
    out = ops.roi_align(x, boxes, paddle.to_tensor(np.array([1])), 4)
    assert out.shape == [1, 3, 4, 4]
    np.testing.assert_allclose(out.numpy(), 7.0, rtol=1e-6)


def test_roi_align_linear_gradient_field():
    """Bilinear sampling of f(y,x)=x is exact: bin centers reproduce x."""
    W = 16
    grid = np.broadcast_to(np.arange(W, dtype=np.float32), (W, W))
    x = paddle.to_tensor(grid[None, None])
    boxes = paddle.to_tensor(np.array([[4.0, 4.0, 12.0, 12.0]], np.float32))
    out = ops.roi_align(x, boxes, paddle.to_tensor(np.array([1])), 2,
                        sampling_ratio=2, aligned=False)
    # roi [4,12): bins of width 4, sample points at x=4+{1,3} and 8+{1,3}
    np.testing.assert_allclose(out.numpy()[0, 0, 0], [6.0, 10.0], rtol=1e-5)


def test_roi_pool_max():
    feat = np.zeros((1, 1, 8, 8), np.float32)
    feat[0, 0, 2, 2] = 5.0
    feat[0, 0, 6, 6] = 9.0
    x = paddle.to_tensor(feat)
    boxes = paddle.to_tensor(np.array([[0.0, 0.0, 7.0, 7.0]], np.float32))
    out = ops.roi_pool(x, boxes, paddle.to_tensor(np.array([1])), 2)
    np.testing.assert_allclose(out.numpy()[0, 0], [[5.0, 0.0], [0.0, 9.0]])


def test_psroi_pool_position_sensitive():
    ph = pw = 2
    co = 2
    # reference layout: channel (c*ph + i)*pw + j
    feat = np.stack([np.full((8, 8), float(i)) for i in range(co * ph * pw)])
    x = paddle.to_tensor(feat[None].astype(np.float32))
    boxes = paddle.to_tensor(np.array([[0.0, 0.0, 8.0, 8.0]], np.float32))
    out = ops.psroi_pool(x, boxes, paddle.to_tensor(np.array([1])), 2)
    # output channel c, bin (i,j) reads input channel (c*ph+i)*pw+j
    np.testing.assert_allclose(out.numpy()[0, 0], [[0.0, 1.0], [2.0, 3.0]])
    np.testing.assert_allclose(out.numpy()[0, 1], [[4.0, 5.0], [6.0, 7.0]])


def test_deform_conv2d_zero_offset_matches_conv():
    """Zero offsets + ones mask reduce deformable conv to plain conv."""
    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 10, 10).astype(np.float32)
    w = rng.randn(6, 4, 3, 3).astype(np.float32)
    offset = np.zeros((2, 2 * 1 * 9, 8, 8), np.float32)
    out = ops.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(offset),
                            paddle.to_tensor(w))
    ref = paddle.nn.functional.conv2d(paddle.to_tensor(x),
                                      paddle.to_tensor(w))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_deform_conv2d_mask_and_grad():
    paddle.seed(1)
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(1, 2, 6, 6).astype(np.float32))
    x.stop_gradient = False
    w = paddle.to_tensor(rng.randn(3, 2, 3, 3).astype(np.float32) * 0.1)
    w.stop_gradient = False
    offset = paddle.to_tensor(
        rng.randn(1, 18, 4, 4).astype(np.float32) * 0.1)
    offset.stop_gradient = False
    mask = paddle.to_tensor(
        np.full((1, 9, 4, 4), 0.5, np.float32))
    out = ops.deform_conv2d(x, offset, w, mask=mask)
    assert out.shape == [1, 3, 4, 4]
    out.sum().backward()
    assert x.grad is not None and w.grad is not None \
        and offset.grad is not None
    # half mask == half output
    out2 = ops.deform_conv2d(x, offset, w,
                             mask=paddle.to_tensor(
                                 np.ones((1, 9, 4, 4), np.float32)))
    np.testing.assert_allclose(out.numpy() * 2, out2.numpy(), rtol=1e-4)


def test_deform_conv2d_layer():
    layer = ops.DeformConv2D(4, 8, 3, padding=1)
    x = paddle.randn([2, 4, 8, 8])
    offset = paddle.zeros([2, 18, 8, 8])
    out = layer(x, offset)
    assert out.shape == [2, 8, 8, 8]


def test_yolo_box_decode():
    np.random.seed(0)
    na, cls, H = 2, 3, 4
    x = np.zeros((1, na * (5 + cls), H, H), np.float32)
    boxes, scores = ops.yolo_box(
        paddle.to_tensor(x),
        paddle.to_tensor(np.array([[128, 128]], np.int32)),
        anchors=[10, 13, 16, 30], class_num=cls, conf_thresh=0.4,
        downsample_ratio=32)
    assert boxes.shape == [1, na * H * H, 4]
    assert scores.shape == [1, na * H * H, cls]
    # zero logits: sigmoid=0.5 > thresh; center of cell(0,0) at 0.5/4
    b0 = boxes.numpy()[0, 0]
    assert abs((b0[0] + b0[2]) / 2 - 128 * 0.5 / 4) < 1e-3
    # w = exp(0)*anchor_w/input_w = 10/128 (relative) -> 10 px
    assert abs((b0[2] - b0[0]) - 10.0) < 1e-3


def test_yolo_box_conf_thresh_zeroes():
    x = np.full((1, 1 * 5, 2, 2), -10.0, np.float32)  # conf ~ 0
    boxes, scores = ops.yolo_box(
        paddle.to_tensor(x), paddle.to_tensor(np.array([[64, 64]])),
        anchors=[10, 13], class_num=0, conf_thresh=0.5,
        downsample_ratio=32)
    np.testing.assert_allclose(boxes.numpy(), 0.0)


def test_yolo_loss_runs_and_grads():
    np.random.seed(2)
    na, cls, H = 3, 5, 8
    x = paddle.to_tensor(
        np.random.randn(2, na * (5 + cls), H, H).astype(np.float32) * 0.1)
    x.stop_gradient = False
    gt_box = paddle.to_tensor(np.array(
        [[[0.5, 0.5, 0.3, 0.4], [0.2, 0.2, 0.1, 0.1]],
         [[0.7, 0.3, 0.2, 0.2], [0.0, 0.0, 0.0, 0.0]]], np.float32))
    gt_label = paddle.to_tensor(np.array([[1, 2], [3, 0]]))
    loss = ops.yolo_loss(x, gt_box, gt_label,
                         anchors=[10, 13, 16, 30, 33, 23],
                         anchor_mask=[0, 1, 2], class_num=cls,
                         ignore_thresh=0.7, downsample_ratio=32)
    assert loss.shape == [2]
    assert np.all(np.isfinite(loss.numpy())) and np.all(loss.numpy() > 0)


def test_read_file_decode_jpeg(tmp_path):
    from PIL import Image

    img = Image.fromarray(
        (np.random.RandomState(0).rand(10, 12, 3) * 255).astype(np.uint8))
    p = tmp_path / "t.jpg"
    img.save(p)
    raw = ops.read_file(str(p))
    assert raw._value.dtype == np.uint8
    decoded = ops.decode_jpeg(raw)
    assert decoded.shape == [3, 10, 12]


def test_transforms_functional_namespace():
    """paddle.vision.transforms.functional import path (reference
    functional.py) — the form pipelines import as F."""
    import paddle_tpu.vision.transforms.functional as F

    img = (np.random.RandomState(0).rand(12, 10, 3) * 255).astype(np.uint8)
    t = F.to_tensor(img)
    assert t.shape == (3, 12, 10) and 0.0 <= t.min() and t.max() <= 1.0
    assert F.to_tensor(img, data_format="HWC").shape == (12, 10, 3)
    np.testing.assert_array_equal(F.hflip(t), t[:, :, ::-1])
    assert min(np.asarray(F.resize(t, 6)).shape[1:]) == 6
    c = F.crop(t, 2, 2, 4, 4)
    assert np.asarray(c).shape[-2:] == (4, 4)
    s = F.adjust_saturation(t, 0.0)  # factor 0 -> pure grayscale
    g = np.asarray(s)
    np.testing.assert_allclose(g[0], g[1], atol=1e-6)
    n = F.normalize(t, [0.5, 0.5, 0.5], [0.5, 0.5, 0.5])
    assert np.asarray(n).min() < 0


def test_fleet_dataset_and_framework_dtype_paths():
    from paddle_tpu.distributed.fleet import dataset as fds
    from paddle_tpu.framework import get_default_dtype, set_default_dtype

    assert hasattr(fds, "InMemoryDataset")
    assert hasattr(fds, "QueueDataset")
    assert get_default_dtype() == "float32"
    set_default_dtype("float32")


def test_conv_norm_activation_block():
    """Reference ConvNormActivation: same-padding default, bias only
    when norm_layer is None, Sequential structure."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import ConvNormActivation

    blk = ConvNormActivation(3, 8, kernel_size=5, stride=2, dilation=1)
    x = paddle.to_tensor(np.random.RandomState(0).randn(
        2, 3, 16, 16).astype(np.float32))
    y = blk(x)
    assert tuple(y.shape) == (2, 8, 8, 8)       # same-padding halves HW
    assert blk[0].bias is None                   # norm present -> no bias
    assert type(blk[1]).__name__ == "BatchNorm2D"
    assert type(blk[2]).__name__ == "ReLU"

    blk2 = ConvNormActivation(3, 8, norm_layer=None, activation_layer=None)
    assert blk2[0].bias is not None
    assert len(blk2) == 1
