"""distlint: static cross-rank divergence analyzer on the shared
staticlib core, plus the runtime collective-schedule reconciliation.

Locks the ISSUE-17 acceptance surface:
  * fixture detections for all 7 rules (DL001–DL007);
  * precision controls that must NOT fire (rank-gated branches with a
    MATCHING collective on both sides, mesh-bound axis names, seeded
    generators, broadcast-of-host-local — the sanctioned replication
    route, a barrier completing the collective before a store wait);
  * inline waivers, line-free fingerprints, the machinery exemption
    (distributed/collective.py IS the protocol);
  * the CLI exit-code contract and the freshness of the shipped
    (empty) baseline;
  * SARIF round-trip and the distlint baseline regenerating
    byte-identically;
  * the --verify-runtime cross-reference over the SITE INVENTORY
    (unit-level, no subprocess);
  * the runtime half: the collective-schedule recorder (digest,
    positional window marks, kill switch, dispatch-stats parity),
    heartbeat publication through ElasticManager.tick, and
    ClusterMonitor's divergence scan (fault + latch + bundle);
  * the rollback/resume divergence fix: cluster mode routes BOTH
    through the host-0 common-step agreement;
  * staticcheck's telemetry schema-consistency pass.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.distlint import analyzer  # noqa: E402
from tools.staticlib import baseline as slib_baseline  # noqa: E402


# ---------------------------------------------------------------------------
# fixture code exercising every rule

FIXTURE = textwrap.dedent('''
    import time
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh
    import paddle_tpu.distributed as dist
    from paddle_tpu.core import fusion
    from paddle_tpu.distributed import coordination
    from paddle_tpu.distributed.elastic import latest_checkpoint
    from paddle_tpu.runtime import telemetry

    MESH = Mesh((), ("dp",))


    def gated_sync(x):
        rank = dist.get_rank()
        if rank == 0:
            dist.all_reduce(x)             # DL001: only rank 0 enters
        return x


    def paired_sync(x, rank):
        if rank == 0:
            dist.all_reduce(x)             # control: matched on both
        else:
            dist.all_reduce(x)             # branches -> no deadlock
        return x


    def waived_gate(x):
        if dist.get_rank() == 0:
            dist.all_reduce(x)  # distlint: ok[DL001] fixture-reviewed
        return x


    def staged_sync(x, t0):
        if time.time() - t0 > 5:           # DL002: host-tainted test,
            dist.all_reduce(x)             # different sequences
        else:
            dist.broadcast(x, src=0)
        return x


    def noisy_sync(x):
        noise = np.random.rand(4)
        dist.all_reduce(noise)             # DL003: unseeded operand
        return noise


    def local_resume(restore_fn, ckpt_dir):
        step = latest_checkpoint(ckpt_dir)
        return restore_fn(step)            # DL003: rank-local restore


    def seeded_sync(x):
        rng = np.random.default_rng(1234)
        vals = rng.normal(size=4)
        dist.all_reduce(vals)              # control: seeded = replicated
        return vals


    def replicate_seed(x):
        seed = np.random.rand(1)
        dist.broadcast(seed, src=0)        # control: broadcast IS the fix
        return seed


    def bound_axis_reduce(x):
        return lax.psum(x, "dp")           # control: bound by MESH


    def unbound_axis_reduce(x):
        return lax.psum(x, "model")        # DL004: no binding anywhere


    def sync_then_wait(store, x):
        dist.all_reduce(x)
        store.rendezvous("agree")          # DL005: wait under in-flight
        return x


    def sync_complete_then_wait(store, x):
        dist.all_reduce(x)
        dist.barrier()
        store.rendezvous("agree")          # control: collective done
        return x


    def publish(store):
        telemetry.merge_cluster(store)     # DL006: no rank gate


    def publish_gated(store, rank):
        if rank == 0:
            telemetry.merge_cluster(store)  # control: gated


    def publish_guard(store, rank):
        if rank != 0:
            return
        telemetry.merge_cluster(store)     # control: guard clause


    def elect(store):
        coordination.rendezvous(store, "k", {"v": 1}, leader=True)  # DL006


    def fused_region(x):
        with fusion.suspend():
            dist.all_reduce(x)             # DL007: schedule skew
        return x
''')


@pytest.fixture(scope="module")
def fixture_result(tmp_path_factory):
    d = tmp_path_factory.mktemp("distlint_fixture")
    p = d / "fixture_dist.py"
    p.write_text(FIXTURE)
    sites = []
    findings, errors = analyzer.analyze_paths([str(p)], sites=sites)
    assert not errors
    return findings, sites


@pytest.fixture(scope="module")
def fixture_findings(fixture_result):
    return fixture_result[0]


def _hits(findings, rule, where=""):
    return [f for f in findings
            if f.rule == rule and where in f.func and not f.suppressed]


# -- detections (all 7 rules) -------------------------------------------------

def test_all_seven_rules_detect_on_fixture(fixture_findings):
    rules = {f.rule for f in fixture_findings if not f.suppressed}
    assert {"rank-conditional-collective", "divergent-collective-schedule",
            "host-local-value-divergence", "unbound-axis-name",
            "coordination-wait-under-collective", "ungated-leader-write",
            "collective-in-suspend-region"} <= rules, rules


def test_dl001_rank_conditional_collective(fixture_findings):
    hits = _hits(fixture_findings, "rank-conditional-collective",
                 "gated_sync")
    assert hits and hits[0].symbol == "gated:all_reduce"
    assert hits[0].severity == "error"
    assert hits[0].confidence == "definite"


def test_dl002_divergent_schedule(fixture_findings):
    hits = _hits(fixture_findings, "divergent-collective-schedule",
                 "staged_sync")
    assert hits and hits[0].symbol == "schedule:all_reduce!=broadcast"
    assert "time.time" in hits[0].message


def test_dl003_host_local_divergence(fixture_findings):
    syms = {f.symbol for f in _hits(fixture_findings,
                                    "host-local-value-divergence")}
    # both sink families: the collective operand AND the restore decision
    assert "hostlocal:all_reduce:noise" in syms, syms
    assert "hostlocal:restore_fn:step" in syms, syms


def test_dl004_unbound_axis_name(fixture_findings):
    hits = _hits(fixture_findings, "unbound-axis-name")
    assert {f.symbol for f in hits} == {"axis:model"}, hits


def test_dl005_coordination_wait_under_collective(fixture_findings):
    hits = _hits(fixture_findings, "coordination-wait-under-collective",
                 "sync_then_wait")
    assert hits and hits[0].symbol == "coordwait:rendezvous<-all_reduce"
    assert hits[0].severity == "error"


def test_dl006_ungated_leader_write(fixture_findings):
    syms = {f.symbol for f in _hits(fixture_findings,
                                    "ungated-leader-write")}
    # both shapes: the merge-artifact write AND the leader rendezvous
    assert "leaderwrite:merge_cluster" in syms, syms
    assert "leaderwrite:rendezvous" in syms, syms


def test_dl007_collective_in_suspend_region(fixture_findings):
    hits = _hits(fixture_findings, "collective-in-suspend-region",
                 "fused_region")
    assert hits and hits[0].symbol == "suspend:all_reduce"


# -- precision controls -------------------------------------------------------

def test_matched_branches_are_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if "paired_sync" in f.func and not f.suppressed]


def test_seeded_generator_is_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if "seeded_sync" in f.func and not f.suppressed]


def test_broadcast_of_host_local_is_clean(fixture_findings):
    """broadcast/scatter are asymmetric BY DESIGN: feeding a host-local
    value into broadcast from the source rank is the sanctioned way to
    replicate it — the fix route must never re-flag."""
    assert not [f for f in fixture_findings
                if "replicate_seed" in f.func and not f.suppressed]


def test_bound_axis_name_is_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if f.func == "bound_axis_reduce" and not f.suppressed]


def test_completed_collective_before_wait_is_clean(fixture_findings):
    assert not [f for f in fixture_findings
                if "sync_complete_then_wait" in f.func
                and not f.suppressed]


def test_rank_gated_leader_writes_are_clean(fixture_findings):
    for fn in ("publish_gated", "publish_guard"):
        assert not [f for f in fixture_findings
                    if fn in f.func and not f.suppressed], fn


def test_waived_site_is_suppressed_not_new(fixture_findings):
    waived = [f for f in fixture_findings if "waived_gate" in f.func]
    assert waived and all(f.suppressed for f in waived)
    assert waived[0].rule == "rank-conditional-collective"


def test_fingerprints_are_line_number_free(tmp_path):
    (tmp_path / "a.py").write_text(FIXTURE)
    (tmp_path / "b.py").write_text("# unrelated leading comment\n"
                                   + FIXTURE)
    fa, _ = analyzer.analyze_paths([str(tmp_path / "a.py")])
    fb, _ = analyzer.analyze_paths([str(tmp_path / "b.py")])
    fp_a = sorted(f.fingerprint().split("|", 2)[2] for f in fa)
    fp_b = sorted(f.fingerprint().split("|", 2)[2] for f in fb)
    assert fp_a == fp_b


def test_site_inventory_collected(fixture_result):
    _, sites = fixture_result
    ops = {s["op"] for s in sites}
    assert {"all_reduce", "broadcast", "psum", "barrier"} <= ops, ops
    for s in sites:
        assert s["end_line"] >= s["line"] >= 1
        assert s["path"].endswith("fixture_dist.py")


def test_machinery_module_is_exempt_but_inventoried():
    """distributed/collective.py IS the protocol implementation: its
    rank-asymmetric eager bodies must never self-flag, but its public
    op spans must enter the site inventory (the runtime recorder's
    fallback attribution target)."""
    path = os.path.join(REPO_ROOT, "paddle_tpu", "distributed",
                        "collective.py")
    sites = []
    findings, errors = analyzer.analyze_paths([path], sites=sites)
    assert not errors
    assert not findings, [(f.rule, f.line) for f in findings]
    assert {"all_reduce", "broadcast", "all_gather"} <= {
        s["op"] for s in sites}


# -- the shipped tree ---------------------------------------------------------

@pytest.fixture(scope="module")
def tree_findings():
    """One analysis of the shipped package, shared by the tree-level
    tests (each in-process pass costs ~1s of suite wall-clock)."""
    findings, errors = analyzer.analyze_paths(
        [os.path.join(REPO_ROOT, "paddle_tpu")])
    assert not errors
    return findings


def test_shipped_baseline_is_fresh_and_empty(tree_findings):
    """ISSUE-17 triage: the shipped baseline is EMPTY — the one true
    positive (rank-local resume) was fixed, reviewed degrade paths
    carry inline waivers — and it matches today's analyzer output."""
    findings = tree_findings
    bl_path = os.path.join(REPO_ROOT, "tools", "distlint",
                           "baseline.json")
    bl = slib_baseline.load_baseline(bl_path)
    new, baselined, _sup, _info, stale = slib_baseline.partition(
        findings, bl)
    assert not new, [(f.path, f.rule, f.symbol) for f in new]
    assert not stale, stale
    assert not baselined  # empty baseline: nothing to be baselined BY
    assert json.load(open(bl_path))["fingerprints"] == {}


def test_elastic_degrade_paths_carry_reviewed_waivers(tree_findings):
    """The resume/rollback agreement's rank-local degrade paths (store
    down, single-process mode) are intentional — every DL003 in
    elastic.py must be waived, none baselined."""
    dl003 = [f for f in tree_findings
             if f.rule == "host-local-value-divergence"
             and f.path.endswith("distributed/elastic.py")]
    assert dl003 and all(f.suppressed for f in dl003), [
        (f.line, f.suppressed) for f in dl003]


def test_distlint_baseline_byte_identical(tree_findings):
    from tools.distlint.__main__ import _COMMENT

    findings = tree_findings
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "baseline.json")
        slib_baseline.write_baseline(out, findings, _COMMENT)
        with open(out, "rb") as f1, open(
                os.path.join(REPO_ROOT, "tools", "distlint",
                             "baseline.json"), "rb") as f2:
            assert f1.read() == f2.read()


# -- CLI contract -------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.distlint", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)


def test_cli_clean_tree_exits_zero():
    r = _run_cli("paddle_tpu", "--fail-stale")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_synthetic_violation_fails(tmp_path):
    pkg = tmp_path / "synthpkg"
    pkg.mkdir()
    (pkg / "hot.py").write_text(textwrap.dedent('''
        import paddle_tpu.distributed as dist


        def sync(x):
            if dist.get_rank() == 0:
                dist.all_reduce(x)
    '''))
    r = _run_cli(str(pkg))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "DL001" in r.stdout


def test_cli_write_baseline_then_clean(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "hot.py").write_text(textwrap.dedent('''
        import paddle_tpu.distributed as dist


        def sync(x):
            if dist.get_rank() == 0:
                dist.all_reduce(x)
    '''))
    bl = tmp_path / "baseline.json"
    assert _run_cli(str(pkg), "--baseline", str(bl)).returncode == 1
    assert _run_cli(str(pkg), "--baseline", str(bl),
                    "--write-baseline").returncode == 0
    r = _run_cli(str(pkg), "--baseline", str(bl))
    assert r.returncode == 0, r.stdout
    assert "baselined" in r.stdout
    # fixing the debt leaves a stale entry: --fail-stale gates on it
    (pkg / "hot.py").write_text("def sync(x):\n    return x\n")
    assert _run_cli(str(pkg), "--baseline", str(bl)).returncode == 0
    r = _run_cli(str(pkg), "--baseline", str(bl), "--fail-stale")
    assert r.returncode == 1
    assert "stale" in r.stdout


# -- SARIF --------------------------------------------------------------------

def test_sarif_round_trip(tmp_path):
    d = tmp_path / "fx"
    d.mkdir()
    (d / "fixture_dist.py").write_text(FIXTURE)
    out = tmp_path / "distlint.sarif"
    r = _run_cli(str(d), "--no-baseline", "--sarif", str(out))
    assert r.returncode == 1  # new findings on the fixture
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "distlint"
    rule_ids = {rr["id"] for rr in run["tool"]["driver"]["rules"]}
    assert {"DL001", "DL002", "DL003", "DL004", "DL005", "DL006",
            "DL007"} <= rule_ids
    sarif_fps = {res["partialFingerprints"]["staticlibFingerprint/v1"]
                 for res in run["results"]}
    live, _ = analyzer.analyze_paths([str(d)])
    assert {f.fingerprint() for f in live} == sarif_fps
    suppressed = [res for res in run["results"]
                  if res.get("suppressions")]
    assert suppressed and all(
        s["suppressions"][0]["kind"] == "inSource" for s in suppressed)


# -- verify-runtime cross-reference (unit level) ------------------------------

def test_cross_reference_confirms_and_reports_gaps(fixture_result):
    from tools.distlint.verify import cross_reference

    _, sites = fixture_result
    anchor = next(s for s in sites if s["op"] == "all_reduce")
    recorded = {
        # exactly inside the anchor's span: confirmed
        f"{anchor['path']}:{anchor['line']}": 7,
        # an in-tree site far from every inventory entry: a recall gap
        f"{anchor['path']}:9999": 2,
        # a driver-script site: external, never a gap
        "my_train.py:33": 1,
        # the recorder's bounded-table overflow key: external too
        "<overflow>": 4,
    }
    # roots must name the fixture tree the inventory paths live under
    root = anchor["path"].split("/")[0]
    rep = cross_reference(sites, recorded, roots=(root,))
    confirmed = {(c["path"], c["line"], c["op"])
                 for c in rep["confirmed"]}
    assert (anchor["path"], anchor["line"], "all_reduce") in confirmed
    assert len(rep["runtime_only"]) == 1
    assert rep["runtime_only"][0]["site"].endswith(":9999")
    assert {r["site"] for r in rep["external_sites"]} == {
        "my_train.py:33", "<overflow>"}
    assert rep["static_only"] == len(sites) - len(rep["confirmed"])


# -- the runtime half: collective-schedule recorder ---------------------------

@pytest.fixture
def recorder():
    from paddle_tpu.runtime import collective_schedule as cs

    cs.reset()
    yield cs
    cs.reset()


def _replay(cs, ops):
    cs.reset()
    for op in ops:
        cs.note(op, "", (4,), "float32")
    stats = cs.schedule_stats()
    cs.reset()
    return stats


def test_recorder_counts_marks_and_tail(recorder):
    cs = recorder
    for i in range(cs.MARK_WINDOW * 2):
        cs.note("all_reduce", "", (8,), "float32")
    s = cs.schedule_stats()
    assert s["enabled"] is True
    assert s["seq"] == 2 * cs.MARK_WINDOW
    assert [m[0] for m in s["marks"]] == [cs.MARK_WINDOW,
                                          2 * cs.MARK_WINDOW]
    assert s["marks"][-1][1] == s["fingerprint"]
    assert s["per_op"] == {"all_reduce": 2 * cs.MARK_WINDOW}
    assert len(s["recent"]) == 8  # bounded tail
    hb = cs.heartbeat_payload()["csched"]
    assert hb["seq"] == s["seq"] and hb["fp"] == s["fingerprint"]
    assert hb["marks"] == s["marks"]


def test_recorder_digest_is_schedule_sensitive(recorder):
    """Two ranks with the same schedule share every mark; a single
    divergent entry forks every mark from its window on — the
    positional-comparability property the monitor's scan rides."""
    cs = recorder
    w = cs.MARK_WINDOW
    a = _replay(cs, ["all_reduce"] * (2 * w))
    b = _replay(cs, ["all_reduce"] * w + ["broadcast"]
                + ["all_reduce"] * (w - 1))
    same = _replay(cs, ["all_reduce"] * (2 * w))
    assert a["fingerprint"] == same["fingerprint"]
    assert a["marks"] == same["marks"]
    # identical prefix: the first mark agrees; fork at entry w+1: the
    # second mark (and the head fingerprint) disagree
    assert a["marks"][0] == b["marks"][0]
    assert a["marks"][1] != b["marks"][1]
    assert a["fingerprint"] != b["fingerprint"]


def test_recorder_aval_and_axis_feed_the_digest(recorder):
    cs = recorder
    a = _replay(cs, ["all_reduce"])
    cs.reset()
    cs.note("all_reduce", "", (8,), "float32")
    b = cs.schedule_stats()
    cs.reset()
    cs.note("all_reduce", "dp", (4,), "float32")
    c = cs.schedule_stats()
    assert len({a["fingerprint"], b["fingerprint"],
                c["fingerprint"]}) == 3


def test_recorder_kill_switch(recorder, monkeypatch):
    cs = recorder
    monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_SCHEDULE", "0")
    assert cs.enabled() is False
    cs.note("all_reduce", "", (8,), "float32")
    s = cs.schedule_stats()
    assert s["seq"] == 0 and s["marks"] == [] and s["recent"] == []
    assert cs.heartbeat_payload() == {}


def test_heartbeat_payload_empty_before_first_collective(recorder):
    assert recorder.heartbeat_payload() == {}


def test_dispatch_stats_parity_with_recorder_killed(monkeypatch):
    """PADDLE_TPU_COLLECTIVE_SCHEDULE=0 removes the schedule CONTENT
    but never the dispatch-stats shape: every other key survives."""
    from paddle_tpu.core import dispatch

    base = dispatch.dispatch_stats()
    assert "collectives" in base
    monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_SCHEDULE", "0")
    killed = dispatch.dispatch_stats()
    assert killed["collectives"]["enabled"] is False
    assert set(killed) == set(base)


def test_statusz_payload_carries_collectives(recorder):
    from paddle_tpu.runtime import diagnostics

    recorder.note("all_reduce", "", (8,), "float32")
    payload = diagnostics._statusz_payload()
    assert payload["collectives"]["seq"] == 1


# -- heartbeat publication + monitor divergence scan --------------------------

def test_tick_publishes_schedule_fingerprint(tmp_path, recorder):
    from paddle_tpu.distributed.coordination import (
        DirectoryStore, ClusterContext, read_heartbeats,
    )
    from paddle_tpu.distributed.elastic import ElasticManager

    recorder.note("all_reduce", "", (8,), "float32")
    store = DirectoryStore(str(tmp_path / "store"))
    ctx = ClusterContext(store, rank=0, world_size=1)
    em = ElasticManager(str(tmp_path / "ckpt"), timeout=9999,
                        cluster=ctx)
    assert em.tick(1)
    hb = read_heartbeats(store)[0]
    assert hb["csched"]["seq"] == 1
    assert hb["csched"]["fp"]
    assert hb["csched"]["tail"][0][1] == "all_reduce"


def test_tick_without_recorder_publishes_no_csched(tmp_path, recorder,
                                                   monkeypatch):
    from paddle_tpu.distributed.coordination import (
        DirectoryStore, ClusterContext, read_heartbeats,
    )
    from paddle_tpu.distributed.elastic import ElasticManager

    monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_SCHEDULE", "0")
    store = DirectoryStore(str(tmp_path / "store"))
    ctx = ClusterContext(store, rank=0, world_size=1)
    em = ElasticManager(str(tmp_path / "ckpt"), timeout=9999,
                        cluster=ctx)
    assert em.tick(1)
    assert "csched" not in read_heartbeats(store)[0]


def test_monitor_sched_points_tolerates_malformed_marks():
    from paddle_tpu.distributed.coordination import ClusterMonitor

    pts = ClusterMonitor._sched_points(
        {"seq": 20, "fp": "head",
         "marks": [[16, "m16"], ["junk"], None, [32]]})
    assert pts == {16: "m16", 20: "head"}
    assert ClusterMonitor._sched_points({}) == {}


def test_monitor_flags_schedule_divergence(tmp_path, monkeypatch,
                                           recorder):
    """The divergence protocol at unit level: a common marked seq with
    differing digests raises collective_divergence ONCE per pair (the
    scan keeps reporting the pair), with the two-sided diff in the
    fault detail and the postmortem bundle."""
    from paddle_tpu.distributed.coordination import (
        ClusterMonitor, DirectoryStore,
    )
    from paddle_tpu.runtime import diagnostics, resilience

    cs = recorder
    w = cs.MARK_WINDOW
    a = _replay(cs, ["all_reduce"] * (2 * w))
    b = _replay(cs, ["all_reduce"] * w + ["broadcast"]
                + ["all_reduce"] * (w - 1))

    def csched(stats):
        return {"seq": stats["seq"], "fp": stats["fingerprint"],
                "marks": stats["marks"],
                "tail": stats["recent"]}

    monkeypatch.setenv("PADDLE_TPU_DIAGNOSTICS_DIR",
                       str(tmp_path / "diag"))
    mon = ClusterMonitor(DirectoryStore(str(tmp_path / "store")),
                         rank=0, world_size=2, stale_after=30.0,
                         dead_after=60.0)
    live = {0: {"csched": csched(a)}, 1: {"csched": csched(b)}}
    before = resilience.fault_events().get("collective_divergence", 0)
    # identical schedules: no divergence, no fault
    assert mon._scan_schedules(
        {0: {"csched": csched(a)}, 1: {"csched": csched(a)}}) == []
    # fork at entry w+1: first divergent common point is the 2nd mark
    assert mon._scan_schedules(live) == [[0, 1, 2 * w]]
    after = resilience.fault_events().get("collective_divergence", 0)
    assert after == before + 1
    # latched: the pair keeps reporting, the fault fires once
    assert mon._scan_schedules(live) == [[0, 1, 2 * w]]
    assert resilience.fault_events().get(
        "collective_divergence", 0) == after
    # the two-sided diff survives into the postmortem bundle
    bundle = diagnostics.read_bundle(diagnostics.last_bundle_path())
    assert bundle["reason"] == "collective_divergence"
    diff = bundle["extra"]["collective_divergence"]
    assert diff["ranks"] == [0, 1]
    assert diff["first_divergent_seq"] == 2 * w
    assert set(diff["fp"]) == {"0", "1"}
    assert diff["fp"]["0"] != diff["fp"]["1"]


def test_monitor_poll_scan_includes_schedule_divergence(tmp_path):
    """poll()'s scan dict carries the (empty) schedule_divergence list
    even with no peers — the /statusz and smoke consumers key on it."""
    from paddle_tpu.distributed.coordination import (
        ClusterMonitor, DirectoryStore, publish_heartbeat,
    )

    store = DirectoryStore(str(tmp_path))
    publish_heartbeat(store, 0, 1)
    mon = ClusterMonitor(store, rank=0, world_size=1,
                         stale_after=30.0, dead_after=60.0)
    scan = mon.poll()
    assert scan["schedule_divergence"] == []


# -- rollback/resume agreement (ROADMAP item 3 divergence gap) ----------------

def _complete_steps_dir(tmp_path, steps):
    d = str(tmp_path / "ckpt")
    os.makedirs(d, exist_ok=True)
    for s in steps:
        os.makedirs(os.path.join(d, str(s)), exist_ok=True)
    return d


def test_cluster_resume_uses_common_step_not_local_newest(tmp_path):
    """Rank 0 holds steps {2,3,4}, the peer publication only {2,3}:
    cluster resume must agree on 3 — restoring the rank-local newest 4
    is exactly the divergence distlint DL003 flags."""
    import time as _time

    from paddle_tpu.distributed.coordination import (
        ClusterContext, DirectoryStore,
    )
    from paddle_tpu.distributed.elastic import ElasticManager

    d = _complete_steps_dir(tmp_path, [2, 3, 4])
    store = DirectoryStore(str(tmp_path / "store"))
    store.put("ckpt/rank_1", {"rank": 1, "steps": [2, 3],
                              "wall": _time.time()})
    ctx = ClusterContext(store, rank=0, world_size=2)
    em = ElasticManager(d, timeout=9999, cluster=ctx)
    seen = []
    assert em.resume(seen.append) == 4  # continue AFTER the agreed 3
    assert seen == [3]


def test_agreed_rollback_step_intersects_publications(tmp_path):
    import time as _time

    from paddle_tpu.distributed.coordination import (
        ClusterContext, DirectoryStore,
    )
    from paddle_tpu.distributed.elastic import agreed_rollback_step

    d = _complete_steps_dir(tmp_path, [2, 3, 4])
    store = DirectoryStore(str(tmp_path / "store"))
    store.put("ckpt/rank_1", {"rank": 1, "steps": [2, 3],
                              "wall": _time.time()})
    ctx = ClusterContext(store, rank=0, world_size=2)
    assert agreed_rollback_step(ctx, d, bad_step=7,
                                rendezvous_timeout=2.0) == 3


def test_single_process_resume_unchanged(tmp_path):
    """No cluster: resume keeps the rank-local contract (the reviewed
    waiver in elastic.py documents it)."""
    from paddle_tpu.distributed.elastic import ElasticManager

    d = _complete_steps_dir(tmp_path, [2, 5])
    em = ElasticManager(d, timeout=9999)
    seen = []
    assert em.resume(seen.append) == 6
    assert seen == [5]


# -- staticcheck: telemetry schema consistency --------------------------------

def test_schema_consistency_clean_on_tree():
    from tools.staticcheck import schema_consistency

    rc, report = schema_consistency(
        [os.path.join(REPO_ROOT, "paddle_tpu")])
    assert rc == 0, report["problems"]
    assert report["problems"] == []
    assert report["declared"]["fault_kinds"] == \
        report["used"]["fault_kinds"]


def test_schema_consistency_flags_undeclared_kind(tmp_path):
    from tools.staticcheck import schema_consistency

    (tmp_path / "m.py").write_text(textwrap.dedent('''
        from paddle_tpu.runtime.resilience import record_fault


        def f():
            record_fault("totally_new_kind", "detail")
    '''))
    rc, report = schema_consistency([str(tmp_path)])
    assert rc == 1
    assert any("totally_new_kind" in p and "not declared" in p
               for p in report["problems"])


def test_schema_consistency_sees_aliased_and_counter_literals(tmp_path):
    """The scanner's two blind-spot fixes: `_record_fault` aliases and
    `counter=` keyword literals both count as uses."""
    from tools.staticcheck import _kind_literals

    (tmp_path / "m.py").write_text(textwrap.dedent('''
        def f(_record_fault, retry):
            _record_fault("aliased_kind", "x")
            retry(lambda: 0, counter="kw_kind")
    '''))
    faults, _events = _kind_literals([str(tmp_path)])
    assert {"aliased_kind", "kw_kind"} <= set(faults)


def test_declared_fault_kinds_match_schema_file():
    from paddle_tpu.runtime.resilience import _EVENT_KINDS

    with open(os.path.join(REPO_ROOT, "tools",
                           "telemetry_schema.json")) as f:
        schema = json.load(f)
    assert sorted(_EVENT_KINDS) == schema["fault_kinds"]
    assert "collective_divergence" in schema["fault_kinds"]
