#!/usr/bin/env python
"""Trace-fusion warm-start smoke (tools/ci_check.sh): two fresh
processes sharing a persistent compile-cache dir + shape manifest
prove the fused-trace round trip on CPU in a few seconds.

Pass A (record): runs a small fused train loop (fwd + backward +
cotangent accumulation + SGD) with ``PADDLE_TPU_EAGER_FUSION`` live,
flushing one fused XLA program per step; saves the shape manifest,
which now carries replayable fused-trace entries.

Pass B (replay): precompiles the manifest — `fusion.precompile_trace`
AOT-rebuilds each trace's node chain and installs the compiled fused
program under its reconstructed fingerprint — then runs the same
workload and must report:

* ``traces_precompiled >= 1`` (the manifest carried the traces),
* ``fused_misses == 0``       (every flush was a cache hit),
* ``fresh_compiles == 0``     (every XLA executable came from disk),
* ``disk_cache_hits > 0``     (the disk cache actually served them),
* losses identical to pass A  (deferred execution changed nothing).

The child workload lives in tests/_fusion_child.py (shared with
tests/test_fusion.py's acceptance test).

Usage: python tools/fusion_smoke.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_fusion_child.py")


def _run_pass(mode, env):
    proc = subprocess.run([sys.executable, CHILD, mode], env=env, cwd=REPO,
                          capture_output=True, timeout=240)
    if proc.returncode != 0:
        print(proc.stderr.decode()[-2000:], file=sys.stderr)
        raise SystemExit(f"fusion_smoke: {mode} child failed "
                         f"(rc={proc.returncode})")
    return json.loads(proc.stdout.decode().strip().splitlines()[-1])


def main():
    with tempfile.TemporaryDirectory(prefix="fusion_smoke_") as td:
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            PADDLE_TPU_COMPILE_CACHE_DIR=os.path.join(td, "cache"),
            PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S="0",
            FUSION_MANIFEST=os.path.join(td, "manifest.json"),
        )
        env.pop("PADDLE_TPU_SHAPE_MANIFEST", None)
        cold = _run_pass("record", env)
        warm = _run_pass("replay", env)

    problems = []
    if cold["recorded_ops"] <= 0:
        problems.append(f"pass A recorded no ops: {cold}")
    if warm.get("precompile", {}).get("traces_precompiled", 0) < 1:
        problems.append(f"pass B precompiled no traces: "
                        f"{warm.get('precompile')}")
    if warm["fused_misses"] != 0:
        problems.append(f"pass B fused-cache misses: "
                        f"{warm['fused_misses']} (want 0)")
    if warm["fresh_compiles"] != 0:
        problems.append(f"pass B fresh XLA compiles: "
                        f"{warm['fresh_compiles']} (want 0)")
    if warm["disk_cache_hits"] <= 0:
        problems.append("pass B loaded nothing from the disk cache")
    if any(abs(a - b) > 1e-6 for a, b in zip(cold["losses"],
                                             warm["losses"])):
        problems.append(f"losses diverged: {cold['losses']} vs "
                        f"{warm['losses']}")
    if problems:
        for p in problems:
            print(f"fusion_smoke: FAIL: {p}", file=sys.stderr)
        raise SystemExit(1)
    print(f"fusion_smoke: OK (pass A: {cold['recorded_ops']} ops recorded, "
          f"{cold['fused_misses']} fused compiles; pass B: "
          f"{warm['fused_hits']} fused-cache hits, 0 misses, 0 fresh "
          f"compiles, {warm['disk_cache_hits']} disk loads)")


if __name__ == "__main__":
    main()
