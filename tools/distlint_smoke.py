#!/usr/bin/env python
"""Cross-rank collective-divergence smoke (tools/ci_check.sh).

The runtime half of tools/distlint, proven end to end on a 2-process
CPU cluster over a tmpdir store: rank 1 carries an injected
rank-conditional collective (``PADDLE_TPU_FAULT_INJECT`` fires an
``InjectedFault`` at a fault point rank 0 sails past; the except arm
issues an extra ``broadcast`` — exactly the DL001 bug shape distlint
flags statically), so the two ranks' collective schedules fork from
the first step. Each rank publishes its rolling schedule fingerprint
through the heartbeat path (``ElasticManager.tick``) and polls a
``ClusterMonitor``; the smoke asserts

* the monitor on BOTH ranks flags ``collective_divergence`` well
  before the dead-peer deadline (the whole point: name the fork in
  seconds, not after a wedge timeout);
* the recorded fault's detail carries BOTH ranks' schedule tails, and
  survives into the host-0 merged cluster fault log;
* the postmortem bundle each detecting rank dumps carries the same
  two-sided schedule diff.

Usage: python tools/distlint_smoke.py           (run the smoke)
       python tools/distlint_smoke.py --child   (internal: one rank)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STALE_AFTER = 30.0
DEAD_AFTER = 60.0
# fixed step count, NOT stop-on-detect: a rank that stopped publishing
# the instant IT detected would freeze its schedule before the first
# window mark (MARK_WINDOW=16) and could deny its peer a common
# comparison point — both ranks run the full loop so both must detect
STEPS = 64


def _child():
    sys.path.insert(0, REPO)
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import coordination
    from paddle_tpu.distributed.elastic import ElasticManager
    from paddle_tpu.runtime import diagnostics, telemetry
    from paddle_tpu.runtime.resilience import (
        InjectedFault, fault_events, fault_point,
    )

    ctx = coordination.cluster_context()
    assert ctx is not None
    coordination.init_cluster_telemetry(ctx)
    em = ElasticManager(tempfile.mkdtemp(), timeout=600.0, cluster=ctx,
                        peer_stale_after=STALE_AFTER,
                        peer_dead_after=DEAD_AFTER)
    # polled deterministically in the step loop (the background
    # watchdog would also get there, but the smoke wants exact timing)
    monitor = coordination.ClusterMonitor(
        ctx.store, rank=ctx.rank, world_size=ctx.world_size,
        stale_after=STALE_AFTER, dead_after=DEAD_AFTER)

    dist.init_process_group()
    x = paddle.to_tensor(np.ones(8, np.float32))
    extra = paddle.to_tensor(np.ones(4, np.float32))
    t0 = time.monotonic()
    detected = None
    for step in range(STEPS):
        dist.all_reduce(x)
        try:
            fault_point("distlint_smoke.divergence", step=step)
        except InjectedFault:
            # the injected rank-conditional collective: only the rank
            # whose env carries the fault spec takes this arm — the
            # DL001 shape, live
            dist.broadcast(extra, src=0)
        em.tick(step)
        scan = monitor.poll()
        if detected is None and scan.get("schedule_divergence"):
            detected = (step, time.monotonic() - t0,
                        scan["schedule_divergence"])
        time.sleep(0.02)
    telemetry.publish_registry(ctx.store, ctx.rank)
    if detected is None:
        print(f"NO_DIVERGENCE rank={ctx.rank}", flush=True)
        sys.exit(1)
    step, elapsed, pairs = detected
    assert elapsed < DEAD_AFTER, \
        f"divergence after the dead-peer deadline ({elapsed:.1f}s)"
    assert fault_events().get("collective_divergence", 0) >= 1
    print(f"DIVERGENCE_DETECTED rank={ctx.rank} step={step} "
          f"elapsed={elapsed:.2f} pairs={pairs} "
          f"bundle={diagnostics.last_bundle_path()}", flush=True)


def _env(cluster_dir, rank, world, diag_dir, inject):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PADDLE_TPU_CLUSTER_DIR": cluster_dir,
                "PADDLE_TPU_CLUSTER_RANK": str(rank),
                "PADDLE_TPU_CLUSTER_WORLD": str(world),
                "PADDLE_TPU_DIAGNOSTICS_DIR": diag_dir,
                "PADDLE_TPU_COLLECTIVE_SCHEDULE": "1"})
    if inject:
        env["PADDLE_TPU_FAULT_INJECT"] = "distlint_smoke.divergence=raise"
    else:
        env.pop("PADDLE_TPU_FAULT_INJECT", None)
    return env


def main():
    if "--child" in sys.argv:
        _child()
        return

    sys.path.insert(0, REPO)
    root = tempfile.mkdtemp(prefix="paddle_tpu_distlint_smoke_")
    cluster_dir = os.path.join(root, "store")
    diag_dirs = [os.path.join(root, f"diag_rank{r}") for r in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_env(cluster_dir, rank, 2, diag_dirs[rank], inject=rank == 1))
        for rank in range(2)]
    outs = []
    for rank, p in enumerate(procs):
        out, _ = p.communicate(timeout=180)
        out = out.decode("utf-8", "replace")
        outs.append(out)
        assert p.returncode == 0, f"rank {rank} rc={p.returncode}:\n{out}"
        assert f"DIVERGENCE_DETECTED rank={rank}" in out, out
    print("smoke: both ranks flagged collective_divergence before the "
          "dead-peer deadline OK")

    from paddle_tpu.distributed.coordination import DirectoryStore
    from paddle_tpu.runtime import telemetry

    store = DirectoryStore(cluster_dir)
    merged = telemetry.merge_cluster(store)
    div = [f for f in merged["faults"]
           if f["fault"] == "collective_divergence"]
    assert div, f"no collective_divergence in merged faults: " \
        f"{[f['fault'] for f in merged['faults']]}"
    # the detail carries the two-sided schedule diff: both ranks' tails
    detail = div[0].get("detail") or ""
    diff = json.loads(detail[detail.index("{"):])
    assert set(diff["tail"]) == {"0", "1"}, diff
    ops = {op for tail in diff["tail"].values()
           for (_, op, _a, _v, _s) in tail}
    assert "all_reduce" in ops, ops
    assert "broadcast" in ops, ops  # the injected divergent branch
    print("smoke: merged cluster fault log carries both ranks' "
          "schedule tails OK")

    for rank, out in enumerate(outs):
        bundle_path = out.split("bundle=")[-1].strip().splitlines()[0]
        assert bundle_path and bundle_path != "None", \
            f"rank {rank} dumped no bundle:\n{out}"
        with open(bundle_path) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "collective_divergence", bundle["reason"]
        bdiff = bundle["extra"]["collective_divergence"]
        assert set(bdiff["tail"]) == {"0", "1"}, bdiff
        assert bdiff["first_divergent_seq"] >= 1
    print("smoke: postmortem bundles carry the two-sided schedule "
          "diff OK")
    print("distlint_smoke: OK")


if __name__ == "__main__":
    main()
