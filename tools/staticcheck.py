#!/usr/bin/env python
"""One entry point for every static analyzer: tracelint + threadlint +
fuselint + distlint in one command — one report grammar, one combined
JSON, one exit code, with every tool's CI freshness gate engaged.

    python tools/staticcheck.py [roots...] [options]

Runs, in order:

* **tracelint**  — jit-safety over the op surface, WITH the manifest
  freshness gate (``--check-manifest``: a stale checked-in unjittable
  manifest fails);
* **threadlint** — concurrency/race analysis, with the baseline
  freshness gate (``--fail-stale``);
* **fuselint**   — fusion-barrier analysis, same freshness gate;
* **distlint**   — cross-rank divergence / collective-deadlock
  analysis, same freshness gate;
* the **telemetry schema-consistency** pass — every
  ``record_fault("<kind>")`` / ``emit("<kind>")`` literal in the tree
  must name a kind declared in ``tools/telemetry_schema.json``, and
  every declared kind must be used by at least one in-tree literal
  (both directions: an undeclared kind is invisible to dashboards, a
  dead declaration is vocabulary nothing can produce).

Each tool prints its usual human report under a banner; the combined
JSON report (``--json``) nests each tool's machine-readable report
under its name plus a ``staticcheck`` summary block. ``--sarif-dir``
writes one SARIF file per tool (<dir>/<tool>.sarif) for code-scanning
upload.

Exit grammar (the strictest of all passes, uniformly): 0 — every tool
clean (baselined-only); 1 — any new finding, parse error, stale
baseline entry, stale manifest, or schema inconsistency; 2 — usage
error.
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.distlint import __main__ as distlint_main  # noqa: E402
from tools.fuselint import __main__ as fuselint_main  # noqa: E402
from tools.threadlint import __main__ as threadlint_main  # noqa: E402
from tools.tracelint import __main__ as tracelint_main  # noqa: E402

TOOLS = ("tracelint", "threadlint", "fuselint", "distlint")

SCHEMA_PATH = os.path.join(REPO, "tools", "telemetry_schema.json")


def build_parser():
    p = argparse.ArgumentParser(
        prog="python tools/staticcheck.py",
        description="run all static analyzers (tracelint + threadlint "
                    "+ fuselint + distlint) and the telemetry schema-"
                    "consistency pass with their CI freshness gates")
    p.add_argument("roots", nargs="*", default=["paddle_tpu"],
                   help="package dirs to analyze (default: paddle_tpu)")
    p.add_argument("--json", metavar="PATH",
                   help="write the combined machine-readable report")
    p.add_argument("--sarif-dir", metavar="DIR",
                   help="write one SARIF report per tool here")
    p.add_argument("--skip", action="append", default=[],
                   choices=list(TOOLS) + ["schema"], metavar="TOOL",
                   help="skip one tool (repeatable)")
    p.add_argument("--verify-runtime", action="store_true",
                   help="also run fuselint's runtime flush-site and "
                        "distlint's collective-schedule cross-"
                        "references (one pass per tool does both the "
                        "gate and the verify)")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="itemize baselined/waived findings too")
    return p


def _tool_argv(tool, args, json_path):
    argv = list(args.roots) + ["--json", json_path]
    if tool == "tracelint":
        # manifest freshness IS tracelint's staleness gate; the
        # baseline gate is implicit in its exit code. Roots without a
        # core/ dir (fixture trees) have no manifest to check.
        if any(os.path.isdir(os.path.join(r, "core"))
               for r in args.roots):
            argv.append("--check-manifest")
    else:
        argv.append("--fail-stale")
    if tool in ("fuselint", "distlint") and args.verify_runtime:
        argv.append("--verify-runtime")
    if args.sarif_dir:
        argv += ["--sarif", os.path.join(args.sarif_dir, f"{tool}.sarif")]
    if args.verbose:
        argv.append("-v")
    return argv


def _kind_literals(roots):
    """(fault kinds, event kinds) used as literals anywhere under the
    roots: first string argument of any ``*record_fault(...)`` call,
    any ``counter="..."`` keyword (the checkpoint retry helpers thread
    it into record_fault), and the first string argument of any
    ``emit(...)`` call. Unparseable files are skipped — the lint tools
    already gate on parse errors."""
    faults, events = {}, {}
    for root in roots:
        for dirpath, dirs, files in os.walk(root):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", "node_modules")]
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    with open(path, encoding="utf-8") as f:
                        tree = ast.parse(f.read())
                except (OSError, SyntaxError, ValueError):
                    continue
                for n in ast.walk(tree):
                    if not isinstance(n, ast.Call):
                        continue
                    f0 = n.func
                    name = (f0.id if isinstance(f0, ast.Name)
                            else f0.attr if isinstance(f0, ast.Attribute)
                            else "")
                    lit = (n.args[0].value if n.args
                           and isinstance(n.args[0], ast.Constant)
                           and isinstance(n.args[0].value, str) else None)
                    if name.endswith("record_fault") and lit is not None:
                        faults.setdefault(lit, set()).add(path)
                    elif name == "emit" and lit is not None:
                        events.setdefault(lit, set()).add(path)
                    for kw in n.keywords:
                        if kw.arg == "counter" and \
                                isinstance(kw.value, ast.Constant) and \
                                isinstance(kw.value.value, str):
                            faults.setdefault(kw.value.value,
                                              set()).add(path)
    return faults, events


def schema_consistency(roots):
    """Both-direction vocabulary check against
    tools/telemetry_schema.json. Returns (exit_code, report)."""
    try:
        with open(SCHEMA_PATH, encoding="utf-8") as f:
            schema = json.load(f)
    except (OSError, ValueError) as e:
        print(f"schema-consistency: cannot read {SCHEMA_PATH}: {e}",
              file=sys.stderr)
        return 1, {"error": str(e)}
    declared_faults = set(schema.get("fault_kinds") or [])
    declared_events = set(schema.get("events") or [])
    used_faults, used_events = _kind_literals(roots)
    problems = []
    for kind in sorted(set(used_faults) - declared_faults):
        where = sorted(used_faults[kind])[0]
        problems.append(
            f"fault kind `{kind}` (used in {where}) is not declared — "
            "add it to resilience._EVENT_KINDS and regenerate the "
            "schema (tools/telemetry_smoke.py --emit-schema)")
    for kind in sorted(declared_faults - set(used_faults)):
        problems.append(
            f"fault kind `{kind}` is declared but no in-tree "
            "record_fault()/counter= literal uses it — dead vocabulary "
            "(remove it, or the producer regressed)")
    for kind in sorted(set(used_events) - declared_events):
        where = sorted(used_events[kind])[0]
        problems.append(
            f"event kind `{kind}` (emitted in {where}) is not declared "
            "— add it to telemetry.EVENT_KINDS and regenerate the "
            "schema")
    for kind in sorted(declared_events - set(used_events)):
        problems.append(
            f"event kind `{kind}` is declared but no in-tree emit() "
            "literal produces it — dead vocabulary (remove it, or the "
            "producer regressed)")
    report = {
        "declared": {"fault_kinds": len(declared_faults),
                     "events": len(declared_events)},
        "used": {"fault_kinds": len(used_faults),
                 "events": len(used_events)},
        "problems": problems,
    }
    if problems:
        for p in problems:
            print(f"schema-consistency: {p}", file=sys.stderr)
        return 1, report
    print(f"schema-consistency: OK ({len(used_faults)} fault kinds, "
          f"{len(used_events)} event kinds, both directions)")
    return 0, report


def main(argv=None):
    args = build_parser().parse_args(argv)
    for r in args.roots:
        if not os.path.exists(r):
            print(f"staticcheck: no such path: {r}", file=sys.stderr)
            return 2
    if args.sarif_dir:
        os.makedirs(args.sarif_dir, exist_ok=True)
    mains = {"tracelint": tracelint_main.main,
             "threadlint": threadlint_main.main,
             "fuselint": fuselint_main.main,
             "distlint": distlint_main.main}
    combined = {"version": 1, "tools": {}, "staticcheck": {}}
    failed = []
    for tool in TOOLS:
        if tool in args.skip:
            continue
        print(f"== staticcheck: {tool} ==")
        fd, json_path = tempfile.mkstemp(prefix=f"staticcheck_{tool}_",
                                         suffix=".json")
        os.close(fd)
        try:
            rc = mains[tool](_tool_argv(tool, args, json_path))
            try:
                with open(json_path, "r", encoding="utf-8") as f:
                    combined["tools"][tool] = json.load(f)
            except (OSError, ValueError):
                combined["tools"][tool] = None
        finally:
            os.unlink(json_path)
        combined["tools"].setdefault(tool, None)
        if combined["tools"][tool] is not None:
            combined["tools"][tool]["exit_code"] = rc
        if rc == 2:
            return 2
        if rc != 0:
            failed.append(tool)
        print()
    if "schema" not in args.skip:
        print("== staticcheck: telemetry schema consistency ==")
        src, sreport = schema_consistency(args.roots)
        combined["tools"]["schema"] = sreport
        if src != 0:
            failed.append("schema")
        print()
    ran = [t for t in TOOLS if t not in args.skip]
    if "schema" not in args.skip:
        ran.append("schema")
    combined["staticcheck"] = {
        "ran": ran,
        "failed": failed,
        "clean": not failed,
    }
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(combined, f, indent=1)
            f.write("\n")
    if failed:
        print(f"staticcheck: FAIL ({', '.join(failed)})",
              file=sys.stderr)
        return 1
    print("staticcheck: OK (" + ", ".join(ran) + ")")
    return 0


if __name__ == "__main__":
    sys.exit(main())
