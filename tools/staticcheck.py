#!/usr/bin/env python
"""One entry point for every static analyzer: tracelint + threadlint +
fuselint in one command — one report grammar, one combined JSON, one
exit code, with every tool's CI freshness gate engaged.

    python tools/staticcheck.py [roots...] [options]

Runs, in order:

* **tracelint**  — jit-safety over the op surface, WITH the manifest
  freshness gate (``--check-manifest``: a stale checked-in unjittable
  manifest fails);
* **threadlint** — concurrency/race analysis, with the baseline
  freshness gate (``--fail-stale``);
* **fuselint**   — fusion-barrier analysis, same freshness gate.

Each tool prints its usual human report under a banner; the combined
JSON report (``--json``) nests each tool's machine-readable report
under its name plus a ``staticcheck`` summary block. ``--sarif-dir``
writes one SARIF file per tool (<dir>/<tool>.sarif) for code-scanning
upload.

Exit grammar (the strictest of the three, uniformly): 0 — every tool
clean (baselined-only); 1 — any new finding, parse error, stale
baseline entry, or stale manifest; 2 — usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.fuselint import __main__ as fuselint_main  # noqa: E402
from tools.threadlint import __main__ as threadlint_main  # noqa: E402
from tools.tracelint import __main__ as tracelint_main  # noqa: E402

TOOLS = ("tracelint", "threadlint", "fuselint")


def build_parser():
    p = argparse.ArgumentParser(
        prog="python tools/staticcheck.py",
        description="run all static analyzers (tracelint + threadlint "
                    "+ fuselint) with their CI freshness gates")
    p.add_argument("roots", nargs="*", default=["paddle_tpu"],
                   help="package dirs to analyze (default: paddle_tpu)")
    p.add_argument("--json", metavar="PATH",
                   help="write the combined machine-readable report")
    p.add_argument("--sarif-dir", metavar="DIR",
                   help="write one SARIF report per tool here")
    p.add_argument("--skip", action="append", default=[],
                   choices=list(TOOLS), metavar="TOOL",
                   help="skip one tool (repeatable)")
    p.add_argument("--verify-runtime", action="store_true",
                   help="also run fuselint's runtime flush-site "
                        "cross-reference (one fuselint pass does both "
                        "the gate and the verify)")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="itemize baselined/waived findings too")
    return p


def _tool_argv(tool, args, json_path):
    argv = list(args.roots) + ["--json", json_path]
    if tool == "tracelint":
        # manifest freshness IS tracelint's staleness gate; the
        # baseline gate is implicit in its exit code. Roots without a
        # core/ dir (fixture trees) have no manifest to check.
        if any(os.path.isdir(os.path.join(r, "core"))
               for r in args.roots):
            argv.append("--check-manifest")
    else:
        argv.append("--fail-stale")
    if tool == "fuselint" and args.verify_runtime:
        argv.append("--verify-runtime")
    if args.sarif_dir:
        argv += ["--sarif", os.path.join(args.sarif_dir, f"{tool}.sarif")]
    if args.verbose:
        argv.append("-v")
    return argv


def main(argv=None):
    args = build_parser().parse_args(argv)
    for r in args.roots:
        if not os.path.exists(r):
            print(f"staticcheck: no such path: {r}", file=sys.stderr)
            return 2
    if args.sarif_dir:
        os.makedirs(args.sarif_dir, exist_ok=True)
    mains = {"tracelint": tracelint_main.main,
             "threadlint": threadlint_main.main,
             "fuselint": fuselint_main.main}
    combined = {"version": 1, "tools": {}, "staticcheck": {}}
    failed = []
    for tool in TOOLS:
        if tool in args.skip:
            continue
        print(f"== staticcheck: {tool} ==")
        fd, json_path = tempfile.mkstemp(prefix=f"staticcheck_{tool}_",
                                         suffix=".json")
        os.close(fd)
        try:
            rc = mains[tool](_tool_argv(tool, args, json_path))
            try:
                with open(json_path, "r", encoding="utf-8") as f:
                    combined["tools"][tool] = json.load(f)
            except (OSError, ValueError):
                combined["tools"][tool] = None
        finally:
            os.unlink(json_path)
        combined["tools"].setdefault(tool, None)
        if combined["tools"][tool] is not None:
            combined["tools"][tool]["exit_code"] = rc
        if rc == 2:
            return 2
        if rc != 0:
            failed.append(tool)
        print()
    combined["staticcheck"] = {
        "ran": [t for t in TOOLS if t not in args.skip],
        "failed": failed,
        "clean": not failed,
    }
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(combined, f, indent=1)
            f.write("\n")
    if failed:
        print(f"staticcheck: FAIL ({', '.join(failed)})",
              file=sys.stderr)
        return 1
    print("staticcheck: OK (" +
          ", ".join(t for t in TOOLS if t not in args.skip) + ")")
    return 0


if __name__ == "__main__":
    sys.exit(main())
