"""AST cross-rank divergence analysis over the paddle_tpu distributed
storey (`distributed/`, `optimizer/`, `io/checkpoint.py`).

The SPMD contract the collective layer runs on is simple and brutal:
every rank issues the SAME collectives in the SAME order with
REPLICATED operands. The three sibling analyzers audit one process;
distlint audits that cross-process contract, file-locally and
approximately, without ever importing the code it inspects:

* **rank taint** — a name-level "this value differs per rank" marker
  seeded from rank/process-index reads (`get_rank()`, `.rank`,
  `axis_index(...)`) and propagated through assignments. A collective
  under a rank-tainted branch with no matching collective on the
  sibling branch is the classic deadlock (DL001); two branches that
  both issue collectives but in different sequences (compared one
  call-graph level deep) are a schedule divergence (DL002).
* **host-local taint** — tools/staticlib NameTaint re-seeded with a
  host-local source vocabulary (wall-clock, pid, hostname, unseeded
  generators, rank-local disk scans). Where it reaches a symmetric
  collective operand, a sharded init, a restore decision, or a trace
  fingerprint, ranks compute different values where SPMD assumes one
  (DL003). Seeded generators and agreement/broadcast results are
  sanitizers — the fix routes must never re-flag.
* **schedule structure** — axis-name literals not bound by any mesh
  declaration in the analyzed tree (DL004), coordination-store waits
  reachable while a collective is in flight on the same source-order
  path (DL005), leader-only artifact writes with no rank gate (DL006),
  and collectives inside fusion-suspend regions (DL007).

The analyzer also emits a **collective-site inventory** — every
collective call site plus the public implementation spans in
distributed/collective.py — which --verify-runtime (verify.py)
cross-references against the schedule sites the runtime recorder
(paddle_tpu/runtime/collective_schedule.py) actually observed.

Residual false positives are absorbed by reviewed inline waivers
(`# distlint: ok[rule]`) and the checked fingerprint baseline, exactly
like the three sibling analyzers — never by weakening detection.
"""
from __future__ import annotations

import ast
import os

from ..staticlib import findings as _findings
from ..staticlib.astnav import (
    ScopeIndex, dotted, func_params,
    iter_py_files as _iter_py_files, relpath as _relpath,
    runtime_first_line,
)
from ..staticlib.callgraph import CallGraph
from ..staticlib.taint import NameTaint, body_nodes as _taint_body_nodes
from ..staticlib.waivers import suppressed as _waiver_suppressed
from .rules import RULES

__all__ = ["Finding", "analyze_file", "analyze_paths", "iter_py_files",
           "COLLECTIVE_OPS"]

SKIP_DIRS = {"__pycache__", ".git", "libs", "include"}
TOOL = "distlint"

# the collective layer itself: its rank-asymmetric eager bodies and
# dynamic axis plumbing ARE the implementation of the protocol, not
# clients of it (absolute-path suffix so single-file analysis of
# collective.py is exempt too, while a fixture named collective.py
# is not)
MACHINERY_SUFFIXES = ("paddle_tpu/distributed/collective.py",)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# ---------------------------------------------------------------------------
# collective vocabulary

# paddle-style process-group collectives + jax per-axis collectives
_PADDLE_COLLECTIVES = {
    "all_reduce", "all_gather", "all_gather_object", "broadcast",
    "reduce", "scatter", "reduce_scatter", "alltoall",
    "alltoall_single", "send", "recv", "isend", "irecv", "barrier",
    "p2p_permute",
}
_JAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_to_all", "psum_scatter", "axis_index",
}
COLLECTIVE_OPS = _PADDLE_COLLECTIVES | _JAX_COLLECTIVES
# names too generic to trust bare: require a collective-looking head
# or a from-import out of the collective layer
_AMBIGUOUS_OPS = {"all_gather", "broadcast", "reduce", "scatter",
                  "send", "recv", "axis_index"}
_COLLECTIVE_HEADS = {"dist", "distributed", "collective", "collectives",
                     "lax", "jax"}
_NONCOLLECTIVE_HEADS = {"np", "numpy", "jnp", "functools", "itertools",
                        "operator", "socket", "sock", "conn", "pickle",
                        "struct", "queue"}
# axis_index reads a rank, it does not rendezvous — it taints (rank
# vocabulary below) but is not itself a schedule entry
_NON_SCHEDULE_OPS = {"axis_index"}

# collectives whose SEMANTICS assume replicated operands: host-local
# taint flowing in is silent divergence. broadcast/scatter/send are
# asymmetric by design — feeding a host-local value into broadcast
# from the source rank is the sanctioned way to REPLICATE it.
_SYMMETRIC_OPS = COLLECTIVE_OPS - {
    "broadcast", "scatter", "send", "isend", "recv", "irecv",
    "barrier", "axis_index",
}

# ---------------------------------------------------------------------------
# rank taint vocabulary (DL001/DL002/DL006)

RANK_CALLS = {"get_rank", "process_index", "axis_index", "local_rank",
              "node_rank", "cluster_rank", "get_world_rank", "rank",
              "is_leader", "_is_leader"}
RANK_ATTRS = {"rank", "local_rank", "node_rank", "process_index",
              "is_leader", "leader"}
RANK_PARAM_NAMES = {"rank", "local_rank", "rank_id", "process_index",
                    "node_rank", "src_rank", "leader"}

# ---------------------------------------------------------------------------
# host-local taint vocabulary (DL002 test taint + DL003)

# calls whose result differs per host/process by construction
HOST_SOURCE_TAILS = {"time", "time_ns", "monotonic", "monotonic_ns",
                     "perf_counter", "perf_counter_ns", "getpid",
                     "gethostname", "getfqdn", "uname", "urandom",
                     "uuid1", "uuid4"}
# rank-local disk scans: each rank sees its own retention window — a
# restore decision made from one diverges past what peers still hold
LOCAL_DISK_TAILS = {"latest_checkpoint", "latest_complete_step"}
# seedable generator constructors: WITH arguments the stream is
# replicated (the seeded-generator precision contract); argless they
# pull OS entropy and every rank gets a different stream
_SEEDABLE_CTORS = {"RandomState", "default_rng", "Generator", "PRNGKey",
                   "key"}
# results that are replicated/agreed no matter what flowed in — the
# fix routes distlint recommends, so they must never re-flag
HOST_SANITIZERS = {"broadcast", "all_reduce", "all_gather",
                   "rendezvous", "latest_common_complete_step",
                   "isinstance", "hasattr", "callable", "type", "len"}
HOST_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type"}

# DL003 non-collective sinks
RESTORE_SINKS = {"restore", "restore_fn", "load_checkpoint",
                 "discard_after", "set_state_dict"}
FINGERPRINT_SINKS = {"fingerprint", "trace_fingerprint", "cache_key"}
INIT_SINKS = {"device_put", "with_sharding_constraint", "shard"}

# ---------------------------------------------------------------------------
# DL004 vocabulary

MESH_DECLS = {"Mesh", "AbstractMesh", "make_mesh", "world_mesh",
              "create_device_mesh", "mesh_axes"}
AXIS_USERS = {"psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
              "all_to_all", "psum_scatter", "axis_index", "shard_map"}
SPEC_CTORS = {"PartitionSpec", "P", "NamedSharding"}
AXIS_KWARGS = {"axis_name", "axis_names", "axis"}

# ---------------------------------------------------------------------------
# DL005 vocabulary

COLLECTIVE_WAITS = {"wait", "block_until_ready", "synchronize"}
COORD_WAITS = {"rendezvous", "latest_common_complete_step",
               "wait_for_peers", "poll_until", "wait_rendezvous"}

# ---------------------------------------------------------------------------
# DL006 vocabulary

LEADER_WRITES = {"merge_cluster", "merge_traces", "publish_registry",
                 "write_manifest", "merge_telemetry"}


# ---------------------------------------------------------------------------
# model

class Finding(_findings.Finding):
    """distlint finding: the shared record bound to the DL catalog."""

    RULES = RULES


# ---------------------------------------------------------------------------
# collective-call classification

def _collective_op(call, imported_collectives=frozenset()):
    """The collective op name a call issues, or None."""
    d = dotted(call.func)
    if not d:
        return None
    tail = d[-1]
    if tail not in COLLECTIVE_OPS:
        return None
    if d[0] in _NONCOLLECTIVE_HEADS:
        return None
    if tail in _AMBIGUOUS_OPS:
        if len(d) == 1:
            return tail if tail in imported_collectives else None
        if d[0] not in _COLLECTIVE_HEADS:
            return None
    return tail


def _str_constants(node):
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value


def module_axis_bindings(tree):
    """Axis names BOUND somewhere in a module: string literals inside a
    mesh-declaration call, plus string defaults of axis_name(s)
    parameters (the `def world_mesh(axis_name="dp")` shape)."""
    bound = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call):
            d = dotted(n.func)
            if d and d[-1] in MESH_DECLS:
                bound.update(_str_constants(n))
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = n.args
            pos = list(a.posonlyargs) + list(a.args)
            for p, dflt in zip(pos[len(pos) - len(a.defaults):],
                               a.defaults):
                if p.arg in AXIS_KWARGS:
                    bound.update(_str_constants(dflt))
            for p, dflt in zip(a.kwonlyargs, a.kw_defaults):
                if p.arg in AXIS_KWARGS and dflt is not None:
                    bound.update(_str_constants(dflt))
    return bound


# ---------------------------------------------------------------------------
# per-function analysis

class _FnChecker:
    def __init__(self, module, fnode):
        self.m = module
        self.fnode = fnode
        self.scopes = module.scopes
        self.qual = module.scopes.qualname(fnode)
        self.func_name = (fnode.name if not isinstance(fnode, ast.Lambda)
                          else "<lambda>")
        self.func_line = runtime_first_line(fnode)

        # host-local taint: re-seed NameTaint from the source
        # vocabulary (its default seeds — no-default params — model
        # "traced array", the wrong property here)
        self.host = NameTaint(fnode, static_attrs=HOST_STATIC_ATTRS,
                              sanitizer_calls=HOST_SANITIZERS)
        seeds = set()
        for n in _taint_body_nodes(fnode):
            tgts = None
            if isinstance(n, ast.Assign):
                tgts, val = n.targets, n.value
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign,
                                ast.NamedExpr)):
                tgts, val = [n.target], getattr(n, "value", None)
            if tgts and val is not None and self._has_host_source(val):
                for t in tgts:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name):
                            seeds.add(nm.id)
        self.host.tainted = seeds
        self.host.propagate()

        self.rank_names = self._collect_rank_names()

    # -- host-local sources -------------------------------------------------
    @staticmethod
    def _is_host_source(call):
        d = dotted(call.func)
        if not d:
            return False
        tail = d[-1]
        if tail in _SEEDABLE_CTORS:
            # seeded = replicated stream; argless = OS entropy per rank
            return not call.args and not call.keywords
        if tail in HOST_SOURCE_TAILS or tail in LOCAL_DISK_TAILS:
            return True
        # module-level random.* / np.random.* draws share one unseeded
        # process-global stream (a bound generator `rng.x()` has a Name
        # head that is only tainted if its ctor was argless)
        return "random" in d[:-1] and tail not in _SEEDABLE_CTORS

    def _has_host_source(self, expr):
        return any(isinstance(n, ast.Call) and self._is_host_source(n)
                   for n in ast.walk(expr))

    def _host_tainted(self, expr):
        return self.host.expr_tainted(expr) or self._has_host_source(expr)

    def _host_evidence(self, expr):
        names = self.host.taint_names(expr)
        if names:
            return names
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and self._is_host_source(n):
                d = dotted(n.func)
                return [".".join(d)]
        return ["<expr>"]

    # -- rank taint ---------------------------------------------------------
    def _collect_rank_names(self):
        names = {p for p in func_params(self.fnode)[0]
                 if p in RANK_PARAM_NAMES}
        for _ in range(3):
            changed = False
            for n in _taint_body_nodes(self.fnode):
                tgts = None
                if isinstance(n, ast.Assign):
                    tgts, val = n.targets, n.value
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign,
                                    ast.NamedExpr)):
                    tgts, val = [n.target], getattr(n, "value", None)
                if not tgts or val is None or \
                        not self._rank_expr(val, names):
                    continue
                for t in tgts:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name) \
                                and nm.id not in names:
                            names.add(nm.id)
                            changed = True
            if not changed:
                break
        return names

    def _rank_expr(self, expr, names=None):
        names = self.rank_names if names is None else names
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and d[-1] in RANK_CALLS:
                    return True
            elif isinstance(n, ast.Attribute) and n.attr in RANK_ATTRS:
                return True
            elif isinstance(n, ast.Name) and n.id in names:
                return True
        return False

    def _rank_describe(self, expr):
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and d[-1] in RANK_CALLS:
                    return ".".join(d) + "()"
            if isinstance(n, ast.Attribute) and n.attr in RANK_ATTRS:
                return "." + n.attr
            if isinstance(n, ast.Name) and n.id in self.rank_names:
                return n.id
        return "<rank>"

    # -- plumbing -----------------------------------------------------------
    def _body(self):
        """Own-body nodes only: nested defs/lambdas get their own
        checker (taint propagation still sees the full body)."""
        yield from CallGraph.body_nodes(self.fnode)

    def _op(self, call):
        return _collective_op(call, self.m.imported_collectives)

    def report(self, rule, node, message, symbol, confidence,
               context="spmd"):
        self.m.findings.append(Finding(
            rule=rule, path=self.m.relpath, line=node.lineno,
            col=node.col_offset, func=self.qual,
            func_name=self.func_name, func_line=self.func_line,
            message=message, symbol=symbol,
            severity=RULES[rule].severity, confidence=confidence,
            context=context))

    # -- collective sequences (DL001/DL002) ---------------------------------
    def _branch_seq(self, stmts, depth=1):
        """[(op, report node)] for a branch, in source order, expanding
        locally-resolvable calls one call-graph level deep (the call
        SITE stays the report anchor — the divergence is introduced by
        the branch, not by the helper)."""
        seq = []

        def walk_stmt(node):
            if isinstance(node, _FUNC_NODES):
                return
            if isinstance(node, ast.Call):
                op = self._op(node)
                if op is not None and op not in _NON_SCHEDULE_OPS:
                    seq.append((op, node))
                elif depth > 0:
                    callee = self.m.graph.resolve_call(node)
                    fn = self.m.graph.functions.get(callee) \
                        if callee else None
                    if fn is not None:
                        for n2 in CallGraph.body_nodes(fn):
                            if isinstance(n2, ast.Call):
                                op2 = self._op(n2)
                                if op2 is not None and \
                                        op2 not in _NON_SCHEDULE_OPS:
                                    seq.append((op2, node))
            for child in ast.iter_child_nodes(node):
                walk_stmt(child)

        for st in stmts:
            walk_stmt(st)
        return seq

    def _check_rank_branches(self):
        for n in self._body():
            if isinstance(n, ast.If):
                rank_test = self._rank_expr(n.test)
                host_test = self._host_tainted(n.test)
                if not (rank_test or host_test):
                    continue
                body_seq = self._branch_seq(n.body)
                else_seq = self._branch_seq(n.orelse)
                if not body_seq and not else_seq:
                    continue
                gate = (self._rank_describe(n.test) if rank_test
                        else ", ".join(self._host_evidence(n.test)))
                fired = self._dl001(n, body_seq, else_seq, gate,
                                    rank_test)
                if not fired and body_seq and else_seq and \
                        [op for op, _ in body_seq] != \
                        [op for op, _ in else_seq]:
                    self._dl002(n, body_seq, else_seq, gate)
            elif isinstance(n, (ast.While, ast.IfExp)):
                if not self._rank_expr(n.test):
                    continue
                kind = "while" if isinstance(n, ast.While) else "ternary"
                roots = ([n.body, n.orelse] if isinstance(n, ast.While)
                         else [[n.body], [n.orelse]])
                for branch in roots:
                    for op, site in self._branch_seq(branch):
                        self.report(
                            "rank-conditional-collective", site,
                            f"`{op}` under a rank-dependent `{kind}` "
                            f"({self._rank_describe(n.test)}) — ranks "
                            "that never take this path never enter the "
                            "collective, wedging the ranks that do "
                            "until the dead-peer deadline",
                            f"gated:{op}", "definite",
                            context="deadlock")

    def _dl001(self, ifnode, body_seq, else_seq, gate, rank_test):
        """Collectives present on one branch with no matching op on the
        sibling. Returns True when anything fired (suppresses the
        coarser DL002 for the same If)."""
        if not rank_test:
            # a host-tainted (non-rank) test still diverges schedules,
            # but the per-op pairing argument needs rank semantics;
            # leave those to DL002's sequence comparison
            return False
        fired = False
        for seq, other, where in ((body_seq, else_seq, "taken"),
                                  (else_seq, body_seq, "else")):
            other_ops = {op for op, _ in other}
            for op, site in seq:
                if op in other_ops:
                    continue
                peer = ("the other branch issues no collective"
                        if not other_ops else
                        "the other branch issues "
                        + "/".join(sorted(other_ops)))
                self.report(
                    "rank-conditional-collective", site,
                    f"`{op}` only on the {where} branch of a "
                    f"rank-dependent condition ({gate}); {peer} — "
                    "ranks on the other side never enter this "
                    "collective and the job wedges until the "
                    "dead-peer deadline; issue the collective on "
                    "every rank (gate the PAYLOAD, not the call), "
                    "or waive if every rank provably takes the "
                    "same side",
                    f"gated:{op}", "definite", context="deadlock")
                fired = True
        return fired

    def _dl002(self, ifnode, body_seq, else_seq, gate):
        bs = "/".join(op for op, _ in body_seq[:4])
        es = "/".join(op for op, _ in else_seq[:4])
        self.report(
            "divergent-collective-schedule", ifnode,
            f"branches of a condition tainted by a non-replicated "
            f"value ({gate}) issue different collective sequences "
            f"([{bs}] vs [{es}]) — ranks taking different sides post "
            "mismatched schedules and deadlock or exchange mis-paired "
            "tensors; make the schedule branch-invariant or decide "
            "the branch from an agreed (broadcast/rendezvous) value",
            f"schedule:{bs}!={es}", "possible", context="divergence")

    # -- DL003 --------------------------------------------------------------
    def _check_host_local_sinks(self):
        for n in self._body():
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            op = self._op(n)
            sink = None
            if op is not None and op in _SYMMETRIC_OPS:
                sink = f"collective `{op}`"
            elif d and d[-1] in RESTORE_SINKS:
                sink = f"restore decision `{d[-1]}`"
            elif d and d[-1] in FINGERPRINT_SINKS:
                sink = f"trace fingerprint `{d[-1]}`"
            elif d and d[-1] in INIT_SINKS:
                sink = f"sharded init `{d[-1]}`"
            if sink is None:
                continue
            hot = []
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                if self._host_tainted(a):
                    hot.extend(self._host_evidence(a))
            if not hot:
                continue
            hot = sorted(set(hot))
            self.report(
                "host-local-value-divergence", n,
                f"host-local value ({', '.join(hot)}) flows into "
                f"{sink} — each rank computes its own copy where SPMD "
                "assumes a replicated one, diverging silently; seed "
                "the generator, broadcast from one rank, or decide "
                "from an agreed (rendezvous) value",
                f"hostlocal:{(op or d[-1])}:{','.join(hot)[:60]}",
                "possible", context="divergence")

    # -- DL005 --------------------------------------------------------------
    def _check_coord_wait(self):
        calls = sorted(
            (n for n in self._body() if isinstance(n, ast.Call)),
            key=lambda n: (n.lineno, n.col_offset))
        in_flight = None  # (op, node) of the pending collective
        for n in calls:
            d = dotted(n.func)
            op = self._op(n)
            if op == "barrier" or (d and d[-1] in COLLECTIVE_WAITS):
                in_flight = None
                continue
            if op is not None and op not in _NON_SCHEDULE_OPS:
                in_flight = (op, n)
                continue
            if d and d[-1] in COORD_WAITS and in_flight is not None:
                pend, _site = in_flight
                self.report(
                    "coordination-wait-under-collective", n,
                    f"blocking store wait `{d[-1]}` reachable while "
                    f"`{pend}` (line {_site.lineno}) is still in "
                    "flight on this path — the store wait holds this "
                    "rank out of the collective its peers are blocked "
                    "in: neither the store timeout nor the collective "
                    "watchdog names the real cycle; complete (wait/"
                    "barrier) the collective first, or reorder the "
                    "store wait ahead of it",
                    f"coordwait:{d[-1]}<-{pend}", "possible",
                    context="coordination")

    # -- DL006 --------------------------------------------------------------
    def _rank_gated(self, node):
        cur = self.scopes.parent.get(node)
        while cur is not None and not isinstance(cur, _FUNC_NODES):
            if isinstance(cur, ast.If) and self._rank_expr(cur.test):
                return True
            cur = self.scopes.parent.get(cur)
        # guard-clause shape: `if rank != 0: return` earlier in the body
        body = ([] if isinstance(self.fnode, ast.Lambda)
                else self.fnode.body)
        for st in body:
            if st.lineno >= node.lineno:
                break
            if isinstance(st, ast.If) and self._rank_expr(st.test) and \
                    any(isinstance(s, (ast.Return, ast.Raise))
                        for s in st.body):
                return True
        return False

    def _check_leader_writes(self):
        for n in self._body():
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            is_write = bool(d) and d[-1] in LEADER_WRITES
            is_leader_rdv = (
                bool(d) and d[-1] == "rendezvous"
                and any(kw.arg == "leader"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in n.keywords))
            if not (is_write or is_leader_rdv):
                continue
            if self._rank_gated(n):
                continue
            what = (f"`{d[-1]}`" if is_write
                    else "`rendezvous(leader=True)`")
            self.report(
                "ungated-leader-write", n,
                f"leader-only artifact write {what} with no enclosing "
                "rank/leader gate — every rank races the same store "
                "key and the merged artifact is corrupted (or N "
                "leaders are elected); gate on rank 0/is_leader, or "
                "waive if the caller guarantees single-rank entry",
                f"leaderwrite:{d[-1]}", "possible", context="leader")

    # -- DL007 --------------------------------------------------------------
    def _check_suspend_regions(self):
        for n in self._body():
            if not isinstance(n, (ast.With, ast.AsyncWith)):
                continue
            if not any(
                    isinstance(item.context_expr, ast.Call)
                    and (dotted(item.context_expr.func) or ("",))[-1]
                    == "suspend"
                    for item in n.items):
                continue
            for sub in ast.walk(n):
                if isinstance(sub, _FUNC_NODES):
                    continue
                if isinstance(sub, ast.Call):
                    op = self._op(sub)
                    if op is None or op in _NON_SCHEDULE_OPS:
                        continue
                    self.report(
                        "collective-in-suspend-region", sub,
                        f"`{op}` inside a fusion suspend()/eager-"
                        "fallback region — peers still recording "
                        "their fused trace reach this collective at a "
                        "different schedule position, skewing the "
                        "cross-rank schedule across the fusion kill "
                        "switch; flush (barrier) before entering the "
                        "region, or move the collective outside it",
                        f"suspend:{op}", "possible", context="suspend")

    # -- sites --------------------------------------------------------------
    def collect_sites(self):
        end = getattr(self.fnode, "end_lineno", self.func_line)
        for n in self._body():
            if isinstance(n, ast.Call):
                op = self._op(n)
                if op is not None and op not in _NON_SCHEDULE_OPS:
                    self.m.sites.append({
                        "path": self.m.relpath, "line": n.lineno,
                        "op": op, "func": self.qual,
                        "func_line": self.func_line, "end_line": end,
                    })

    def run(self):
        self._check_rank_branches()     # DL001 + DL002
        self._check_host_local_sinks()  # DL003
        self._check_coord_wait()        # DL005
        self._check_leader_writes()     # DL006
        self._check_suspend_regions()   # DL007


# ---------------------------------------------------------------------------
# per-module driver

class ModuleDistAnalysis:
    def __init__(self, path, root_parent, bound_axes=None):
        self.path = path
        self.relpath = _relpath(path, root_parent)
        self.is_machinery = os.path.abspath(path).replace(
            os.sep, "/").endswith(MACHINERY_SUFFIXES)
        with open(path, "r", encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=path)
        self.scopes = ScopeIndex(self.tree)
        self.graph = CallGraph(self.tree, self.scopes)
        self.imported_collectives = self._imported_collectives()
        # axis names bound by THIS module, or the tree-wide union the
        # driver collected in its first pass
        self.bound_axes = (bound_axes if bound_axes is not None
                           else module_axis_bindings(self.tree))
        self.findings = []
        self.sites = []

    def _imported_collectives(self):
        out = set()
        for n in ast.walk(self.tree):
            if isinstance(n, ast.ImportFrom) and n.module and any(
                    k in n.module for k in ("collective", "distributed",
                                            "communication")):
                for a in n.names:
                    name = a.asname or a.name
                    if name in COLLECTIVE_OPS:
                        out.add(name)
        return out

    def run(self):
        for qual, fnode in self.graph.functions.items():
            checker = _FnChecker(self, fnode)
            checker.collect_sites()
            if not self.is_machinery:
                checker.run()
        if not self.is_machinery:
            self._check_axis_names()  # DL004
        else:
            self._machinery_impl_sites()
        for f in self.findings:
            f.suppressed = _waiver_suppressed(self.lines, f.line, f.rule,
                                              TOOL, RULES)
        self.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return self.findings

    # -- DL004 --------------------------------------------------------------
    def _check_axis_names(self):
        seen = set()
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            if not d:
                continue
            names = []
            if d[-1] in SPEC_CTORS:
                names = [s for s in _str_constants(n)]
            elif d[-1] in AXIS_USERS:
                for a in n.args[1:]:
                    if isinstance(a, ast.Constant) and \
                            isinstance(a.value, str):
                        names.append(a.value)
                for kw in n.keywords:
                    if kw.arg in AXIS_KWARGS:
                        names.extend(_str_constants(kw.value))
            for name in names:
                if name in self.bound_axes or name in seen or not name:
                    continue
                seen.add(name)
                scope = self.scopes.scope_chain(n)
                fnode = next((s for s in scope
                              if isinstance(s, _FUNC_NODES)), None)
                qual = self.scopes.qualname(fnode) if fnode else ""
                fname = ("" if fnode is None else
                         (fnode.name
                          if not isinstance(fnode, ast.Lambda)
                          else "<lambda>"))
                self.findings.append(Finding(
                    rule="unbound-axis-name", path=self.relpath,
                    line=n.lineno, col=n.col_offset, func=qual,
                    func_name=fname,
                    func_line=(runtime_first_line(fnode)
                               if fnode else n.lineno),
                    message=f"axis name '{name}' used in "
                            f"`{'.'.join(d)}` is not bound by any "
                            "mesh/axis declaration in the analyzed "
                            "tree — the name resolves only against "
                            "the device mesh installed at run time; "
                            "an unbound name is a latent NameError on "
                            "the multi-host path (declare the mesh "
                            "axis, or thread the name from one)",
                    symbol=f"axis:{name}",
                    severity=RULES["unbound-axis-name"].severity,
                    confidence="possible", context="axis"))

    # -- machinery implementation spans (site inventory only) ---------------
    def _machinery_impl_sites(self):
        """Public collective implementations in the machinery module:
        the spans runtime schedule sites fall back to when the caller
        is outside the tree (a driver script calling dist.all_reduce
        directly attributes to the implementation, not the driver)."""
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name in COLLECTIVE_OPS:
                self.sites.append({
                    "path": self.relpath, "line": stmt.lineno,
                    "op": stmt.name, "func": stmt.name,
                    "func_line": runtime_first_line(stmt),
                    "end_line": getattr(stmt, "end_lineno", stmt.lineno),
                })


# ---------------------------------------------------------------------------
# tree driver

def iter_py_files(root):
    """The analysis scope: fixture trees and single files analyze
    everything; the real package (recognized by its distributed/ dir)
    narrows to the distributed storey — distributed/, optimizer/, and
    io/checkpoint.py — the surfaces where the SPMD contract lives."""
    if os.path.isdir(os.path.join(root, "distributed")):
        for sub in ("distributed", "optimizer"):
            d = os.path.join(root, sub)
            if os.path.isdir(d):
                yield from _iter_py_files(d, skip_dirs=SKIP_DIRS)
        ck = os.path.join(root, "io", "checkpoint.py")
        if os.path.isfile(ck):
            yield ck
    else:
        yield from _iter_py_files(root, skip_dirs=SKIP_DIRS)


def analyze_paths(roots, sites=None):
    """Analyze every in-scope .py under each root. Returns (findings,
    errors); errors are (path, message) for unparseable files. Axis
    bindings (DL004) are collected tree-wide in a first pass — a mesh
    declared in env.py binds the axis names sharding helpers use.
    When `sites` is a list, the collective-site inventory (for
    --verify-runtime) is appended to it."""
    parsed = []   # (path, root_parent, tree or None, error)
    bound = set()
    for root in roots:
        root = os.path.normpath(root)
        root_parent = os.path.dirname(os.path.abspath(root))
        for path in iter_py_files(root):
            rel = _relpath(path, root_parent)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                parsed.append((path, root_parent,
                               (rel, f"{type(e).__name__}: {e}")))
                continue
            bound |= module_axis_bindings(tree)
            parsed.append((path, root_parent, None))
    findings, errors = [], []
    for entry in parsed:
        path, root_parent, err = entry
        if err is not None:
            errors.append(err)
            continue
        try:
            ma = ModuleDistAnalysis(path, root_parent, bound_axes=bound)
            findings.extend(ma.run())
            if sites is not None:
                sites.extend(ma.sites)
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append((_relpath(path, root_parent),
                           f"{type(e).__name__}: {e}"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if sites is not None:
        sites.sort(key=lambda s: (s["path"], s["line"], s["op"]))
    return findings, errors


def analyze_file(path):
    return analyze_paths([path])
