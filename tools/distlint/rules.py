"""Rule catalog for distlint.

Each rule names one class of cross-rank hazard — a code shape that can
make SPMD ranks disagree about which collectives to issue (deadlock),
feed host-local values into replicated math (silent divergence), or
wedge the coordination layer against the collective layer. The catalog
is data, not behavior — detection lives in analyzer.py — and the Rule
dataclass/severity vocabulary is shared with tracelint, threadlint and
fuselint via tools/staticlib.

Severity:
  error    — a proven deadlock/divergence shape; fix or waive.
  warning  — likely hazard; depends on which ranks run which paths.
  info     — hygiene note / intentional-asymmetry audit; never gates
             CI by severity alone.
"""
from __future__ import annotations

from ..staticlib.rules import Rule, ruleset

RULES, BY_ID, get = ruleset([
    Rule("DL001", "rank-conditional-collective", "error", False,
         "collective call under rank/process-index-dependent control "
         "flow with no matching collective on the other branch — the "
         "classic `if rank == 0: all_reduce(...)` shape: the gated "
         "ranks enter the collective, the rest never do, and the job "
         "wedges until the watchdog's dead_after deadline"),
    Rule("DL002", "divergent-collective-schedule", "error", False,
         "the branches of a condition tainted by a non-replicated "
         "value issue DIFFERENT collective sequences (compared one "
         "call-graph level deep) — ranks taking different branches "
         "post mismatched schedules and deadlock or exchange "
         "mis-paired tensors"),
    Rule("DL003", "host-local-value-divergence", "warning", False,
         "unseeded host randomness / wall-clock / pid / hostname / "
         "rank-local disk state flowing into a collective operand, a "
         "sharded parameter init, or a restore decision — each rank "
         "computes a different value where SPMD assumes a replicated "
         "one, diverging silently instead of crashing"),
    Rule("DL004", "unbound-axis-name", "warning", False,
         "axis-name string used in psum/shard_map/NamedSharding/"
         "PartitionSpec with no enclosing mesh or axis binding in the "
         "module — the name resolves (or fails) only at run time on "
         "the device mesh actually installed; an unbound name is a "
         "latent NameError on the multi-host path"),
    Rule("DL005", "coordination-wait-under-collective", "error", False,
         "blocking coordination-store wait (rendezvous / agreement "
         "poll) reachable while a collective is still in flight on "
         "the same path — the store wait holds the rank out of the "
         "collective its peers are blocked in: a cross-subsystem "
         "deadlock neither layer's timeout names correctly"),
    Rule("DL006", "ungated-leader-write", "warning", False,
         "host-0-only artifact write (cluster merge, agreement "
         "publication, leader rendezvous payload) with no enclosing "
         "rank/leader gate — every rank racing the same store key "
         "corrupts the merged artifact or elects N leaders"),
    Rule("DL007", "collective-in-suspend-region", "warning", False,
         "collective issued inside a fusion.suspend()/eager-fallback "
         "region — peers still recording their fused trace reach the "
         "collective at a different schedule position, skewing the "
         "cross-rank schedule across the fusion kill switch"),
])

__all__ = ["Rule", "RULES", "BY_ID", "get"]
