"""distlint — static cross-rank divergence & collective-deadlock
analysis for the paddle_tpu distributed layer.

Fourth analyzer on the shared tools/staticlib core (after tracelint's
jit-safety pass, threadlint's concurrency pass, and fuselint's
fusion-barrier pass). Where those three audit a single process,
distlint audits the SPMD contract ACROSS processes: every rank must
issue the same collectives in the same order with replicated operands,
or the job deadlocks (mismatched schedules), silently diverges
(host-local values in replicated math), or wedges the coordination
layer against the collective layer. The catalog covers rank-gated
collectives, divergent per-branch collective schedules, host-local
taint reaching collective operands, unbound mesh axis names,
store-waits issued under an in-flight collective, ungated leader-only
writes, and collectives inside fusion-suspend regions.

The runtime half mirrors fuselint's static<->runtime loop: the
collective layer records a bounded per-rank schedule
(dispatch_stats()["collectives"]), each rank publishes a rolling
schedule fingerprint over the CoordinationStore heartbeat path, and
ClusterMonitor names a mismatch as a `collective_divergence` fault in
seconds instead of a dead-peer timeout. --verify-runtime
cross-references the static collective-site inventory against the
schedule sites the runtime actually recorded.
"""
