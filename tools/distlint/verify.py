"""--verify-runtime: close the loop between distlint's static
collective-site inventory and the runtime schedule recorder.

A child process (fresh interpreter, two forced host devices) runs a
small eager collective workload — all_reduce / broadcast / all_gather /
barrier over the default group — and prints
``dispatch_stats()["collectives"]`` including the ``sites`` table
(``file:line`` -> count) the schedule recorder attributed each issued
collective to. The parent then cross-references against the STATIC
SITE INVENTORY (every collective call site the analyzer classified,
plus the machinery implementation spans in
``distributed/collective.py``) — the inventory, not the findings,
because a clean tree has zero findings but its collective sites must
still be the ones the runtime observes:

* **confirmed** — inventory entries a runtime-recorded collective
  actually attributed to (same file, line within the entry's span plus
  a small window): the static pass sees the sites the runtime runs.
* **static-only** — inventory entries never observed in this workload:
  precision feedback (most are simply paths the tiny workload never
  runs).
* **runtime-only** — recorded sites inside the analyzed roots with no
  inventory entry covering them: recall feedback — a collective shape
  the classifier misses. Sites outside the roots (the driver script
  itself) are reported separately, not counted as gaps.

Exit contract: 0 when at least one static site cross-references a
runtime-recorded collective AND there are no recall gaps; 1 otherwise
— CI can gate on the static pass staying anchored to what the
schedule recorder attributes.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

# line slack when matching a static site to a runtime attribution: the
# recorder reports the caller frame's CURRENT line, which for a
# multi-line call can sit a few lines below the expression's anchor
MATCH_WINDOW = 5

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_child():
    """The eager collective workload (executed in a fresh interpreter
    via --verify-child). Prints one JSON line: the schedule recorder's
    stats after a few rounds of collectives over the default group."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.core import dispatch

    dist.init_process_group()
    x = paddle.to_tensor(np.ones(8, np.float32))
    for _ in range(3):
        dist.all_reduce(x)
        dist.broadcast(x, src=0)
        gathered = []
        dist.all_gather(gathered, x)
        # gloo_barrier is the one IN-TREE caller this driver exercises:
        # the recorder attributes its barrier to distributed/__init__.py,
        # a site the analyzer's call inventory must cover
        dist.gloo_barrier()
    stats = dispatch.dispatch_stats()["collectives"]
    print(json.dumps({
        "seq": stats["seq"],
        "fingerprint": stats["fingerprint"],
        "per_op": stats["per_op"],
        "sites": stats["sites"],
    }))


def _spawn_child(timeout=300):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if "--xla_force_host_platform_device_count" not in \
            env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=2"
                            ).strip()
    env["PADDLE_TPU_COLLECTIVE_SCHEDULE"] = "1"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.distlint", "--verify-child"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"distlint --verify-runtime: child failed rc="
            f"{proc.returncode}\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _parse_site(site):
    """('paddle_tpu/x/y.py', 123) or None for unknown/overflow keys."""
    path, _, line = site.rpartition(":")
    if not path or not line.isdigit():
        return None
    return path, int(line)


def cross_reference(sites, recorded, roots=("paddle_tpu",)):
    """Correlate the static site inventory with the schedule recorder's
    attributions. Returns a report dict (see module docstring).

    Path frames differ by construction — inventory paths are relative
    to each analyzed root's PARENT, runtime sites are repo-relative —
    so a runtime site is "in tree" when a root name appears as one of
    its path components, and files match by SUFFIX (the longer of the
    two ends with the other)."""
    by_path = {}
    for s in sites:
        by_path.setdefault(s["path"], []).append(s)
    root_parts = {r.rstrip("/").rsplit("/", 1)[-1] for r in roots}

    def _same_file(inv_path, site_path):
        return site_path.endswith("/" + inv_path) or \
            inv_path.endswith("/" + site_path) or inv_path == site_path

    def _covers(entry, line):
        return (entry["line"] - MATCH_WINDOW <= line
                <= entry.get("end_line", entry["line"]) + MATCH_WINDOW)

    def _key(entry):
        return f"{entry['path']}:{entry['line']}:{entry['op']}"

    confirmed = {}        # inventory key -> (entry, [site records])
    runtime_only = []
    external = []
    for site, count in (recorded or {}).items():
        parsed = _parse_site(site)
        rec = {"site": site, "count": count}
        if parsed is None:
            external.append(rec)
            continue
        path, line = parsed
        if not root_parts & set(path.split("/")[:-1] + [path]):
            external.append(rec)
            continue
        near = [s for sp, ss in by_path.items()
                if _same_file(sp, path)
                for s in ss if _covers(s, line)]
        if near:
            best = min(near, key=lambda s: abs(s["line"] - line))
            confirmed.setdefault(_key(best), [best, []])[1].append(rec)
        else:
            runtime_only.append(rec)
    confirmed_keys = set(confirmed)
    static_only = [s for s in sites if _key(s) not in confirmed_keys]
    return {
        "confirmed": [
            {"path": s["path"], "line": s["line"], "op": s["op"],
             "func": s["func"], "sites": recs}
            for _, (s, recs) in sorted(confirmed.items())],
        "static_only": len(static_only),
        "static_only_sites": sorted(_key(s) for s in static_only),
        "runtime_only": runtime_only,
        "external_sites": external,
    }


def run_verify(sites, json_path=None, roots=("paddle_tpu",)):
    """Drive the child, cross-reference, print the report. Returns the
    process exit code (0 = anchored: >= 1 confirmed site and no recall
    gaps). `roots` must be the roots the inventory was collected over —
    recorded sites outside them are external, not recall gaps."""
    stats = _spawn_child()
    report = cross_reference(sites, stats.get("sites"),
                             roots=tuple(roots))
    report["child"] = {"seq": stats["seq"],
                       "fingerprint": stats["fingerprint"],
                       "per_op": stats["per_op"]}
    n_conf = len(report["confirmed"])
    print(f"distlint --verify-runtime: {n_conf} static collective "
          "site(s) confirmed by the runtime schedule recorder")
    for c in report["confirmed"]:
        recs = ", ".join(f"{r['site']} (x{r['count']})"
                         for r in c["sites"])
        print(f"  {c['op']} {c['path']}:{c['line']} in "
              f"`{c['func']}` <- {recs}")
    print(f"  precision: {report['static_only']} inventory site(s) not "
          "observed in this workload (unexercised paths expected for "
          "the small collective loop)")
    if report["runtime_only"]:
        print(f"  RECALL GAP: {len(report['runtime_only'])} recorded "
              "collective site(s) in the analyzed tree with no "
              "inventory entry covering them:")
        for r in report["runtime_only"]:
            print(f"    {r['site']} (x{r['count']})")
    if report["external_sites"]:
        ext = ", ".join(f"{r['site']} (x{r['count']})"
                        for r in report["external_sites"])
        print(f"  external (driver-script) sites: {ext}")
    if json_path:
        from ..staticlib.report import write_json

        write_json(json_path, report)
    if n_conf == 0:
        print("distlint --verify-runtime: FAIL — no static collective "
              "site cross-references a runtime-recorded collective; "
              "the static inventory has come unanchored from the "
              "schedule recorder's attribution", file=sys.stderr)
        return 1
    if report["runtime_only"]:
        print("distlint --verify-runtime: FAIL — recorded collective "
              "sites above have no static inventory coverage (a "
              "classifier recall gap); extend the collective "
              "vocabulary or attribute the site", file=sys.stderr)
        return 1
    return 0
