"""CLI: python -m tools.distlint <roots...> [options].

Exit codes: 0 clean (or baselined-only), 1 new findings, parse errors,
(with --fail-stale) stale baseline entries, or a failed
--verify-runtime cross-reference, 2 usage error.
"""
from __future__ import annotations

import argparse
import os
import sys

from ..staticlib.baseline import load_baseline, partition, write_baseline
from ..staticlib.report import (
    human_report, json_report, write_json, write_sarif,
)
from .analyzer import analyze_paths
from .rules import RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

_COMMENT = ("distlint suppression baseline — regenerate with "
            "`python -m tools.distlint paddle_tpu "
            "--write-baseline` after reviewing that every new "
            "finding is a rank-role divergence the protocol "
            "intends, not a collective-schedule regression.")


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m tools.distlint",
        description="static cross-rank divergence and collective-"
                    "deadlock analyzer for the paddle_tpu distributed "
                    "layer (see docs/DISTLINT.md)")
    p.add_argument("roots", nargs="*", default=["paddle_tpu"],
                   help="package dirs or files to analyze (paddle_tpu)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help=f"baseline file (default {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding as new (ignore baseline)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "and exit 0")
    p.add_argument("--json", metavar="PATH",
                   help="also write the machine-readable report here")
    p.add_argument("--sarif", metavar="PATH",
                   help="also write a SARIF 2.1.0 report here (CI "
                        "code-scanning annotations)")
    p.add_argument("--fail-stale", action="store_true",
                   help="exit nonzero on stale baseline entries too "
                        "(CI freshness gate)")
    p.add_argument("--verify-runtime", action="store_true",
                   help="additionally run a small eager collective "
                        "workload in a child process and cross-"
                        "reference the static collective-site "
                        "inventory against the runtime schedule "
                        "recorder's site attribution "
                        "(dispatch_stats()['collectives']['sites'])")
    p.add_argument("--verify-json", metavar="PATH",
                   help="write the --verify-runtime report here")
    p.add_argument("--verify-child", action="store_true",
                   help=argparse.SUPPRESS)  # internal: the workload
    p.add_argument("-v", "--verbose", action="store_true",
                   help="itemize baselined/waived/info findings too")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.verify_child:
        from .verify import run_child

        run_child()
        return 0
    for r in args.roots:
        if not os.path.exists(r):
            print(f"distlint: no such path: {r}", file=sys.stderr)
            return 2

    # the site inventory (every collective call/impl site, finding or
    # not) feeds --verify-runtime: a CLEAN tree has zero findings but
    # must still cross-reference its sites against the recorder
    sites = []
    findings, errors = analyze_paths(args.roots, sites=sites)

    if args.write_baseline:
        if errors:
            # a baseline written while files are unparseable silently
            # drops their debt; the next clean run would gate on it
            for p, m in errors:
                print(f"{p}: PARSE ERROR — {m}", file=sys.stderr)
            print("distlint: refusing to write a baseline while files "
                  "fail to parse", file=sys.stderr)
            return 1
        counts = write_baseline(args.baseline, findings, _COMMENT)
        print(f"distlint: baseline written to {args.baseline} "
              f"({sum(counts.values())} findings, "
              f"{len(counts)} distinct fingerprints)")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, baselined, suppressed, info, stale = partition(findings, baseline)

    print(human_report(new, baselined, suppressed, info, stale, errors,
                       tool="distlint", rules=RULES,
                       verbose=args.verbose))
    if args.json:
        write_json(args.json, json_report(new, baselined, suppressed, info,
                                          stale, errors, rules=RULES))
    if args.sarif:
        write_sarif(args.sarif, new, baselined, suppressed, info, errors,
                    tool="distlint", rules=RULES)
    rc = 0
    if new or errors:
        rc = 1
    elif args.fail_stale and stale:
        print("distlint: stale baseline entries above — the debt was "
              "fixed; shrink the baseline with --write-baseline",
              file=sys.stderr)
        rc = 1
    if args.verify_runtime:
        from .verify import run_verify

        # sites carry paths relative to each root's PARENT — pass the
        # same normalized names so in-tree/external classification
        # matches the analysis
        roots = [os.path.basename(os.path.normpath(r))
                 for r in args.roots]
        vrc = run_verify(sites, json_path=args.verify_json,
                         roots=roots)
        rc = rc or vrc
    return rc


if __name__ == "__main__":
    sys.exit(main())
