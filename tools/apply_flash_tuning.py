"""Fold bench.py flash_tiling sweep results into the kernel's tuning
table (paddle_tpu/ops/pallas/flash_tuning.json), which the dispatch
wrapper consults via `tuned_blocks` — round-5 verdict #4: flash block
defaults chosen from measured data.

Usage: python tools/apply_flash_tuning.py [result.json ...]
Defaults to .bench_state*/flash_tiling.json under the repo root. Keys
parsed: tiling_s{seq}_q{bq}_k{bk}_ms (smaller is better, per seq).
Refuses to write from a small-config sweep (tiling measured at toy
shapes would mis-tune real ones).
"""
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "paddle_tpu", "ops", "pallas",
                   "flash_tuning.json")
KEY = re.compile(r"tiling_s(\d+)_q(\d+)_k(\d+)_ms$")


def main(paths):
    if not paths:
        paths = sorted(glob.glob(os.path.join(REPO, ".bench_state*",
                                              "flash_tiling.json")))
    best = {}  # seq -> (ms, bq, bk)
    device_kind = None
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"skip {path}: {e}", file=sys.stderr)
            continue
        if data.get("flash_tiling_small"):
            print(f"skip {path}: small-config sweep (toy shapes would "
                  "mis-tune real ones)", file=sys.stderr)
            continue
        probe = os.path.join(os.path.dirname(path), "probe.json")
        try:
            with open(probe) as f:
                device_kind = json.load(f).get("device_kind", device_kind)
        except (OSError, ValueError):
            pass
        for k, v in data.items():
            m = KEY.match(k)
            if not m or not isinstance(v, (int, float)):
                continue
            seq, bq, bk = (int(x) for x in m.groups())
            if seq not in best or v < best[seq][0]:
                best[seq] = (float(v), bq, bk)
    if not best:
        print("no full-size tiling measurements found; nothing written")
        return 1
    doc = {
        "device_kind": device_kind,
        "tilings": [{"seq": s, "block_q": b[1], "block_k": b[2],
                     "ms": round(b[0], 3)}
                    for s, b in sorted(best.items())],
    }
    tmp = OUT + ".tmp"  # atomic: a concurrent reader must never see a
    with open(tmp, "w") as f:  # truncated table (it would cache [])
        json.dump(doc, f, indent=2)
    os.replace(tmp, OUT)
    print(f"wrote {OUT}: {doc['tilings']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
