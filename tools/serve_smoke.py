#!/usr/bin/env python
"""Serving-engine acceptance smoke (tools/ci_check.sh): two fresh
processes prove the ISSUE-13 end-to-end criteria on CPU in seconds.

Pass A (record, cold server): 4 concurrent requests flow through
admit -> prefill -> decode -> finish under continuous batching over the
paged KV cache, then the SAME prompts run sequentially through
one-request engines. Asserts:

* token-exact outputs: batched continuous-batching generation ==
  sequential one-request-at-a-time generation, request by request;
* exact histogram<->span reconciliation: the serve/request and
  serve/ttft span sums equal the
  ``paddle_tpu_serve_request_seconds`` / ``_ttft_seconds`` histogram
  sums (same-measurement emission), via
  ``tracing.reconcile_with_metrics``.

Pass B (replay, warm server): a second process precompiles the shape
manifest pass A saved and serves the same workload. Asserts:

* ``fresh_compiles == 0`` — a server restart performs ZERO fresh XLA
  compiles (every executable comes from the persistent disk cache);
* ``disk_cache_hits > 0`` — the cache actually served them;
* tokens identical to pass A.

The child workload lives in tests/_serve_child.py (shared with
tests/test_serving.py).

Usage: python tools/serve_smoke.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_serve_child.py")


def _run_pass(mode, env):
    proc = subprocess.run([sys.executable, CHILD, mode], env=env, cwd=REPO,
                          capture_output=True, timeout=300)
    if proc.returncode != 0:
        print(proc.stderr.decode()[-2000:], file=sys.stderr)
        raise SystemExit(f"serve_smoke: {mode} child failed "
                         f"(rc={proc.returncode})")
    return json.loads(proc.stdout.decode().strip().splitlines()[-1])


def main():
    with tempfile.TemporaryDirectory(prefix="serve_smoke_") as td:
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            PADDLE_TPU_COMPILE_CACHE_DIR=os.path.join(td, "cache"),
            PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S="0",
            SERVE_MANIFEST=os.path.join(td, "manifest.json"),
            SERVE_TRACE_DIR=os.path.join(td, "trace"),
        )
        env.pop("PADDLE_TPU_SHAPE_MANIFEST", None)
        cold = _run_pass("record", env)
        warm = _run_pass("replay", env)

    problems = []
    if cold["batched"] != cold["sequential"]:
        problems.append(
            "continuous batching is not token-exact vs sequential: "
            f"{cold['batched']} vs {cold['sequential']}")
    if not cold.get("reconcile_ok"):
        problems.append(
            f"span<->metric reconciliation failed: {cold.get('reconcile')}")
    rec = cold.get("reconcile") or {}
    for which in ("request", "ttft"):
        sp, hi = rec.get(f"{which}_span"), rec.get(f"{which}_hist")
        if not sp or not hi or sp[1] == 0:
            problems.append(f"no serve/{which} spans were recorded")
        elif sp[1] != hi[1] or abs(sp[0] - hi[0]) > 1e-6:
            problems.append(
                f"serve/{which} spans != histogram: {sp} vs {hi}")
    if warm.get("precompile", {}).get("ops_precompiled", 0) < 1:
        problems.append(f"pass B precompiled no ops: "
                        f"{warm.get('precompile')}")
    if warm["fresh_compiles"] != 0:
        problems.append(f"pass B fresh XLA compiles: "
                        f"{warm['fresh_compiles']} (want 0)")
    if warm["disk_cache_hits"] <= 0:
        problems.append("pass B loaded nothing from the disk cache")
    if warm["batched"] != cold["batched"]:
        problems.append(f"warm tokens diverged: {warm['batched']} vs "
                        f"{cold['batched']}")
    if problems:
        for p in problems:
            print(f"serve_smoke: FAIL: {p}", file=sys.stderr)
        raise SystemExit(1)
    print(f"serve_smoke: OK (pass A: {len(cold['batched'])} concurrent "
          f"requests token-exact vs sequential in {cold['steps']} steps, "
          "spans==histograms; pass B: 0 fresh compiles, "
          f"{warm['disk_cache_hits']} disk loads, "
          f"{warm['precompile']['ops_precompiled']} ops precompiled)")


if __name__ == "__main__":
    main()
