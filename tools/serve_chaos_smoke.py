#!/usr/bin/env python
"""Serving robustness acceptance smoke (tools/ci_check.sh): the ISSUE-18
overload + chaos + drain + crash-recovery contracts, proven end-to-end
on CPU in four stages of fresh subprocesses (tests/_chaos_child.py).

Stage 1 — overload (4x sustainable arrival rate through tools/loadgen):
  * the engine SHEDS: OverloadedError submissions > 0, `overloaded`
    outcome counter > 0, serve_sheds fault events > 0;
  * queue depth never exceeds the admission bound (memory stays
    bounded at ANY arrival rate);
  * admitted-request TTFT p99 stays bounded (queue-wait cap + service,
    with CPU slack);
  * the run exits clean — no wedge (the server-published
    oldest-queued-age stays below the wedge threshold);
  * ISSUE 20: /requestz parses under scrape WHILE the storm runs; the
    last-1m TTFT window moves during the storm (count grows, p99
    shifts — the lifetime histogram alone could not show this); every
    shed request carries a full sampled trace (access record with
    ``sampled`` + `serve/request/*` detail spans); access-log
    aggregates reconcile EXACTLY with the outcome counters and
    latency/TTFT histograms (tracing.reconcile_with_metrics).

Stage 2 — chaos degradation contracts (FaultInjector):
  * serve.step delay: deadline-burdened requests evict
    (request_deadline faults), patient requests still complete;
  * serve.kv_alloc raising on EVERY allocation: the loop starves
    promptly (no spin, no crash) and serves normally once the
    injector lifts.

Stage 3 — SIGTERM graceful drain:
  * child exits rc=-SIGTERM (supervisor semantics preserved);
  * a `sigterm_drain` postmortem bundle lands, carrying the drain
    report (completed/shed counts) in its extra.

Stage 4 — SIGKILL mid-decode + journal recovery:
  * baseline child serves the workload uninterrupted, saves the shape
    manifest;
  * kill child (same workload + request journal) is SIGKILLed
    mid-decode by PADDLE_TPU_FAULT_INJECT=serve.step=kill:N;
  * recover child warm-starts, re-admits the journal's unfinished
    tail, and its (pre-crash completed) U (post-restart) outputs are
    TOKEN-EXACT vs baseline — with ZERO fresh XLA compiles.

Usage: python tools/serve_chaos_smoke.py
"""
from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_chaos_child.py")

KILL_STEP = 9  # mid-decode: after some requests finish, before all
#              (the workload finishes req-0..2 by step 8, all by 13)


def _run(mode, env, timeout=300, expect_rc=0):
    proc = subprocess.run([sys.executable, CHILD, mode], env=env,
                          cwd=REPO, capture_output=True, timeout=timeout)
    if expect_rc is not None and proc.returncode != expect_rc:
        print(proc.stderr.decode()[-3000:], file=sys.stderr)
        raise SystemExit(f"serve_chaos_smoke: {mode} child rc="
                         f"{proc.returncode} (want {expect_rc})")
    if expect_rc == 0:
        return json.loads(proc.stdout.decode().strip().splitlines()[-1])
    return proc


def _base_env(td):
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PADDLE_TPU_COMPILE_CACHE_DIR=os.path.join(td, "cache"),
        PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S="0",
        SERVE_MANIFEST=os.path.join(td, "manifest.json"),
    )
    for k in ("PADDLE_TPU_SHAPE_MANIFEST", "PADDLE_TPU_FAULT_INJECT",
              "PADDLE_TPU_DIAGNOSTICS_DIR", "PADDLE_TPU_SERVE_JOURNAL",
              "CHAOS_JOURNAL", "PADDLE_TPU_TRACE", "PADDLE_TPU_STATUSZ",
              "PADDLE_TPU_SERVE_ACCESS_LOG"):
        env.pop(k, None)
    return env


def _stage_overload(td, problems):
    env = _base_env(td)
    # tracer live so the child's sampled detail spans + reconcile run
    env["PADDLE_TPU_TRACE"] = os.path.join(td, "trace")
    doc = _run("overload", env)
    rep, outcomes = doc["report"], doc["outcomes"]
    if rep["wedged"]:
        problems.append(f"overload: engine WEDGED at 4x rate: {rep}")
    if rep["shed"] + rep["evicted_by_reason"].get("queue_timeout", 0) <= 0:
        problems.append(f"overload: nothing shed at 4x rate: {rep}")
    if outcomes.get("overloaded", 0) <= 0:
        problems.append(f"overload: no `overloaded` outcomes: {outcomes}")
    if doc["serve_sheds"] <= 0:
        problems.append("overload: no serve_sheds fault events")
    if rep["max_queue_depth"] > doc["max_queued"]:
        problems.append(f"overload: queue depth {rep['max_queue_depth']} "
                        f"exceeded bound {doc['max_queued']}")
    # bounded TTFT for ADMITTED work: queue-wait cap (2s) + service
    # time, with generous CPU scheduling slack — the contract is
    # "bounded", not "fast"
    if rep["ttft_p99_s"] is not None and rep["ttft_p99_s"] > 20.0:
        problems.append(f"overload: TTFT p99 {rep['ttft_p99_s']:.1f}s "
                        "is unbounded-looking (> 20s)")
    if rep["completed"] <= 0:
        problems.append("overload: nothing completed under overload")
    # -- ISSUE 20: request-scoped observability under fire ----------------
    rz = doc["requestz"]
    if rz["parsed"] <= 0:
        problems.append(f"overload: /requestz never parsed under scrape "
                        f"during the storm: {rz}")
    w0, w1 = doc["w1_before"], doc["w1_after"]
    if w1["ttft_count"] <= w0["ttft_count"]:
        problems.append(f"overload: last-1m TTFT window did not move "
                        f"during the storm: {w0} -> {w1}")
    if not doc["reconcile_ok"]:
        problems.append(f"overload: access-log aggregates failed to "
                        f"reconcile with metrics: {doc['reconcile_bad']}")
    if doc["shed_records"] <= 0:
        problems.append("overload: no shed access records in the ring")
    elif doc["shed_records_sampled"] != doc["shed_records"]:
        problems.append(f"overload: {doc['shed_records_sampled']} of "
                        f"{doc['shed_records']} shed records tail-"
                        "sampled (want ALL)")
    if doc["detail_spans"].get("request/queue", 0) <= 0:
        problems.append(f"overload: sampled requests emitted no "
                        f"request/* detail spans: {doc['detail_spans']}")
    return (f"shed {rep['shed']}+{rep['evicted_by_reason'].get('queue_timeout', 0)} "
            f"of {rep['submitted']} at {doc['rate_rps']:.0f} rps "
            f"(~4x {doc['sustainable_rps']:.0f}), depth<="
            f"{rep['max_queue_depth']}, ttft_p99="
            f"{0 if rep['ttft_p99_s'] is None else rep['ttft_p99_s']:.2f}s; "
            f"requestz {rz['parsed']}/{rz['scrapes']} scrapes parsed, "
            f"1m ttft_count {w0['ttft_count']}->{w1['ttft_count']}, "
            f"reconcile ok, {doc['shed_records']} shed records all "
            f"sampled")


def _stage_chaos(td, problems):
    doc = _run("chaos", _base_env(td))
    p1, p2 = doc["phase1"], doc["phase2"]
    if sorted(p1["completed"]) != sorted(p1["patient"]):
        problems.append(f"chaos/delay: patient requests did not (all) "
                        f"complete: {p1}")
    if p1["deadline_faults"] < len(p1["impatient"]):
        problems.append(f"chaos/delay: expected >= "
                        f"{len(p1['impatient'])} request_deadline "
                        f"faults, got {p1['deadline_faults']}")
    if p2["starved_completed"] != 0:
        problems.append(f"chaos/kv: completions during total KV "
                        f"starvation: {p2}")
    if p2["starve_wall_s"] > 30.0:
        problems.append(f"chaos/kv: starved loop took "
                        f"{p2['starve_wall_s']:.1f}s to yield (spin?)")
    if p2["completed"] < 1 or len(doc["post_recovery_tokens"] or []) != 3:
        problems.append(f"chaos/kv: engine did not serve normally "
                        f"after the injector lifted: {p2}, "
                        f"post={doc['post_recovery_tokens']}")
    return (f"delay: {len(p1['completed'])} patient ok / "
            f"{p1['deadline_faults']} deadline faults; kv: starved "
            f"clean in {p2['starve_wall_s']:.2f}s, recovered "
            f"{p2['completed']} post-injector")


def _stage_drain(td, problems):
    env = _base_env(td)
    diag = os.path.join(td, "diag")
    env["PADDLE_TPU_DIAGNOSTICS_DIR"] = diag
    proc = subprocess.Popen([sys.executable, CHILD, "drain"], env=env,
                            cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    try:
        line = proc.stdout.readline().decode().strip()
        if line != "READY":
            proc.kill()
            raise SystemExit(f"serve_chaos_smoke: drain child said "
                             f"{line!r}, not READY")
        time.sleep(0.5)  # let it get mid-flight
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    if rc != -signal.SIGTERM:
        problems.append(f"drain: rc={rc}, want {-signal.SIGTERM} "
                        "(default SIGTERM semantics must survive the "
                        "graceful drain)")
    bundles = glob.glob(os.path.join(diag, "postmortem-*sigterm_drain*"))
    report = None
    if not bundles:
        problems.append(f"drain: no sigterm_drain bundle in {diag}: "
                        f"{os.listdir(diag) if os.path.isdir(diag) else 'missing dir'}")
    else:
        with open(bundles[0]) as f:
            doc = json.load(f)
        report = (doc.get("extra") or {}).get("drain")
        if not report:
            problems.append(f"drain: bundle carries no drain report: "
                            f"{sorted(doc)}")
    return f"rc={rc}, bundle drain report: {report}"


def _stage_recovery(td, problems):
    env = _base_env(td)
    journal = os.path.join(td, "journal.jsonl")
    base = _run("baseline", env)

    env_kill = dict(env)
    env_kill["CHAOS_JOURNAL"] = journal
    env_kill["PADDLE_TPU_FAULT_INJECT"] = f"serve.step=kill:{KILL_STEP}"
    proc = _run("kill", env_kill, expect_rc=None)
    if proc.returncode != -signal.SIGKILL:
        problems.append(f"recovery: kill child rc={proc.returncode}, "
                        f"want {-signal.SIGKILL} (the injected SIGKILL "
                        "must land mid-decode)")

    env_rec = dict(env)
    env_rec["CHAOS_JOURNAL"] = journal
    rec = _run("recover", env_rec)
    merged = dict(rec["recovered_completed"])
    merged.update(rec["post_outputs"])
    want = base["outputs"]
    if rec["fresh_compiles"] != 0:
        problems.append(f"recovery: {rec['fresh_compiles']} fresh XLA "
                        "compiles on the restarted process (want 0)")
    if rec["disk_cache_hits"] <= 0:
        problems.append("recovery: restarted process loaded nothing "
                        "from the compile cache")
    if not rec["resumed"]:
        problems.append("recovery: nothing resumed from the journal — "
                        f"the SIGKILL landed too late? ({rec})")
    if len(rec["recovered_completed"]) + len(rec["skipped"]) == 0 \
            and KILL_STEP > 4:
        # not fatal by itself, but worth failing loudly: the kill step
        # is tuned so SOME request finishes pre-crash
        problems.append("recovery: no pre-crash completions in the "
                        "journal — KILL_STEP needs retuning")
    if merged != want:
        problems.append("recovery: recovered outputs are NOT token-"
                        f"exact vs uninterrupted: {merged} vs {want}")
    return (f"{len(rec['recovered_completed'])} pre-crash + "
            f"{len(rec['post_outputs'])} resumed = {len(merged)} "
            f"requests token-exact, 0 fresh compiles "
            f"({rec['disk_cache_hits']} disk loads)")


def main():
    problems = []
    notes = {}
    with tempfile.TemporaryDirectory(prefix="serve_chaos_") as td:
        notes["overload"] = _stage_overload(td, problems)
        notes["chaos"] = _stage_chaos(td, problems)
        notes["drain"] = _stage_drain(td, problems)
        notes["recovery"] = _stage_recovery(td, problems)
    if problems:
        for p in problems:
            print(f"serve_chaos_smoke: FAIL: {p}", file=sys.stderr)
        raise SystemExit(1)
    for stage, note in notes.items():
        print(f"serve_chaos_smoke: {stage}: {note}")
    print("serve_chaos_smoke: OK")


if __name__ == "__main__":
    main()
