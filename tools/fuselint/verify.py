"""--verify-runtime: close the loop between fuselint's static findings
and the flush-site attribution the runtime records.

A child process (fresh interpreter, ``PADDLE_TPU_EAGER_FUSION=1``) runs
the bench MLP train step — the same small fwd+bwd+SGD loop bench.py's
``eager_fusion`` config measures — and prints
``dispatch_stats()["fusion"]`` including the ``flush_sites`` table
(reason -> {file:line -> count}). The parent then cross-references:

* **confirmed** — static findings whose site a runtime flush actually
  attributed to (same file, within a small line window): the static
  pass is predicting real barriers.
* **static-only** — findings never observed flushing in this workload:
  precision feedback (most are simply paths the tiny MLP never runs;
  a static-only finding ON the exercised step path is a likely false
  positive).
* **runtime-only** — flush sites inside the analyzed roots with no
  static finding nearby: recall feedback — a barrier shape the rule
  catalog misses. Sites outside the roots (the driver script itself)
  are reported separately, not counted as gaps.

Exit contract: 0 when at least one static finding cross-references a
runtime flush site AND there are no recall gaps; 1 otherwise — CI can
gate on the static pass staying anchored to runtime truth.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

# line slack when matching a static finding to a runtime site: the
# runtime attributes to the statement that touched the FIRST pending
# placeholder (often one line below the statement the finding anchors)
MATCH_WINDOW = 5

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_child():
    """The bench-MLP train step under fusion (executed in a fresh
    interpreter via --verify-child). Prints one JSON line: the fusion
    stats snapshot after a short training loop whose per-step loss
    read is the only HOST sync the driver itself performs."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.core import dispatch, fusion

    dispatch.set_warmup_count(1)
    if not fusion.fusion_enabled():
        fusion.set_fusion(True)
    rng = np.random.RandomState(0)
    prng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(32, 64).astype(np.float32))
    y = paddle.to_tensor(rng.randn(32, 8).astype(np.float32))
    params = [
        paddle.to_tensor(prng.randn(64, 128).astype(np.float32) * 0.1,
                         stop_gradient=False),
        paddle.to_tensor(np.zeros(128, np.float32), stop_gradient=False),
        paddle.to_tensor(prng.randn(128, 8).astype(np.float32) * 0.1,
                         stop_gradient=False),
        paddle.to_tensor(np.zeros(8, np.float32), stop_gradient=False),
    ]
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=params)
    losses = []
    for _ in range(8):
        h = F.relu(paddle.matmul(x, params[0]) + params[1])
        p = paddle.matmul(h, params[2]) + params[3]
        loss = ((p - y) * (p - y)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._value)))
    fs = dispatch.dispatch_stats()["fusion"]
    print(json.dumps({
        "flushes": fs["flushes"],
        "flush_sites": fs["flush_sites"],
        "recorded_ops": fs["recorded_ops"],
        "losses": losses,
    }))


def _spawn_child(timeout=300):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PADDLE_TPU_EAGER_FUSION"] = "1"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.fuselint", "--verify-child"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"fuselint --verify-runtime: child failed rc="
            f"{proc.returncode}\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _parse_site(site):
    """('paddle_tpu/x/y.py', 123) or None for unknown/overflow keys."""
    path, _, line = site.rpartition(":")
    if not path or not line.isdigit():
        return None
    return path, int(line)


def cross_reference(findings, flush_sites, roots=("paddle_tpu",)):
    """Correlate static findings with runtime-attributed flush sites.
    Returns a report dict (see module docstring for the categories).
    ALL findings participate — a waived or baselined finding is still
    an intentional barrier the runtime should be observed hitting.

    Path frames differ by construction — finding paths are relative to
    each analyzed root's PARENT, runtime sites are repo-relative — so a
    site is "in tree" when a root name appears as one of its path
    components, and a site file matches a finding file by SUFFIX (the
    longer of the two ends with the other)."""
    by_path = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    root_parts = {r.rstrip("/").rsplit("/", 1)[-1] for r in roots}

    def _same_file(find_path, site_path):
        return site_path.endswith("/" + find_path) or \
            find_path.endswith("/" + site_path) or find_path == site_path

    confirmed = {}        # fingerprint -> (finding, [site records])
    runtime_only = []
    external = []
    for reason, sites in (flush_sites or {}).items():
        for site, count in sites.items():
            parsed = _parse_site(site)
            rec = {"reason": reason, "site": site, "count": count}
            if parsed is None:
                external.append(rec)
                continue
            path, line = parsed
            if not root_parts & set(path.split("/")[:-1] + [path]):
                external.append(rec)
                continue
            near = [f for fp, fs in by_path.items()
                    if _same_file(fp, path)
                    for f in fs if abs(f.line - line) <= MATCH_WINDOW]
            if near:
                best = min(near, key=lambda f: abs(f.line - line))
                confirmed.setdefault(
                    best.fingerprint(), [best, []])[1].append(rec)
            else:
                runtime_only.append(rec)
    confirmed_fps = set(confirmed)
    static_only = [f for f in findings
                   if f.fingerprint() not in confirmed_fps]
    return {
        "confirmed": [
            {"fingerprint": fp, "path": f.path, "line": f.line,
             "rule": f.rule, "rule_id": f.rule_id, "func": f.func,
             "sites": recs}
            for fp, (f, recs) in sorted(confirmed.items())],
        "static_only": len(static_only),
        "static_only_fingerprints": sorted(
            f.fingerprint() for f in static_only),
        "runtime_only": runtime_only,
        "external_sites": external,
    }


def run_verify(findings, json_path=None, roots=("paddle_tpu",)):
    """Drive the child, cross-reference, print the report. Returns the
    process exit code (0 = anchored: >= 1 confirmed finding and no
    recall gaps). `roots` must be the roots the findings were analyzed
    over — sites outside them are external, not recall gaps."""
    stats = _spawn_child()
    report = cross_reference(findings, stats.get("flush_sites"),
                             roots=tuple(roots))
    report["child"] = {"flushes": stats["flushes"],
                       "recorded_ops": stats["recorded_ops"]}
    n_conf = len(report["confirmed"])
    print(f"fuselint --verify-runtime: {n_conf} static finding(s) "
          "confirmed by runtime flush attribution")
    for c in report["confirmed"]:
        sites = ", ".join(f"{r['site']} ({r['reason']} x{r['count']})"
                          for r in c["sites"])
        print(f"  {c['rule_id']} {c['path']}:{c['line']} in "
              f"`{c['func']}` <- {sites}")
    print(f"  precision: {report['static_only']} finding(s) not "
          "observed flushing in this workload (unexercised paths "
          "expected for the small MLP)")
    if report["runtime_only"]:
        print(f"  RECALL GAP: {len(report['runtime_only'])} runtime "
              "flush site(s) in the analyzed tree with no static "
              "finding nearby:")
        for r in report["runtime_only"]:
            print(f"    {r['site']} ({r['reason']} x{r['count']})")
    if report["external_sites"]:
        ext = ", ".join(f"{r['site']} ({r['reason']})"
                        for r in report["external_sites"])
        print(f"  external (driver-script) sites: {ext}")
    if json_path:
        from ..staticlib.report import write_json

        write_json(json_path, report)
    if n_conf == 0:
        print("fuselint --verify-runtime: FAIL — no static finding "
              "cross-references a runtime flush site; the static pass "
              "has come unanchored from the runtime's attribution",
              file=sys.stderr)
        return 1
    if report["runtime_only"]:
        print("fuselint --verify-runtime: FAIL — runtime flush sites "
              "above have no static coverage (a rule-catalog recall "
              "gap); extend the rules or attribute the site",
              file=sys.stderr)
        return 1
    return 0
