"""AST fusion-barrier analysis over the paddle_tpu eager caller surface.

Where tracelint audits what happens INSIDE an op body that may reach
`jax.jit`, fuselint audits the code AROUND the dispatch layer — the
eager caller paths (train loops, optimizer steps, the backward tape,
metric/callback plumbing) that consume tensor values while the
trace-fusion engine (core/fusion.py) is trying to accumulate them into
one fused program. Every host materialization, data-dependent branch,
unjittable-op sighting, suspend() region, per-step side effect, and
trace-length hazard is a **fusion barrier**: the pending trace flushes
there, and the fused program shrinks back toward per-op dispatch.

**Potential laziness** is a name-level taint (tools/staticlib/taint.py
bound to the fusion sanitizer vocabulary): positional parameters
without defaults are assumed potentially lazy, plus names assigned
from tensor-producing calls (`paddle.*`/`T.*`/`F.*`, `apply()`,
`to_tensor`, `._value`/`.grad` reads). Shape/dtype/ndim/len reads
sanitize — LazyArray serves them from memoized avals without a flush,
so they must never flag (the FL002 precision contract).

**Evidence grading** keeps precision: a finding fires only when the
function itself treats the value as a tensor (fed to paddle/T/F ops,
`._value`/`.grad` access, `.backward()`/array-method calls, or
assigned from a tensor producer). Residual false positives are
absorbed by reviewed inline waivers (`# fuselint: ok[rule]`) and the
checked fingerprint baseline, exactly like the two sibling analyzers —
never by weakening detection.

The pass is file-local and approximate and must never import the code
it analyzes. The one cross-file input is the CHECKED-IN static
unjittable manifest (core/_unjittable_manifest.py), read as AST data
(ast.literal_eval, no import): ops tracelint proved trace-unsafe are
reported as FL003 barriers at their definition site, where they will
bite fusion — not rediscovered per-process at runtime.
"""
from __future__ import annotations

import ast
import os

from ..staticlib import findings as _findings
from ..staticlib.astnav import (
    ScopeIndex, const_range, dotted, func_params,
    iter_py_files as _iter_py_files, relpath as _relpath,
    runtime_first_line,
)
from ..staticlib.callgraph import CallGraph
from ..staticlib.taint import NameTaint
from ..staticlib.waivers import suppressed as _waiver_suppressed
from .rules import RULES

__all__ = ["Finding", "analyze_file", "analyze_paths", "iter_py_files",
           "load_unjittable_manifest", "DEFAULT_MAX_OPS"]

SKIP_DIRS = {"__pycache__", ".git", "libs", "include"}
TOOL = "fuselint"

# the deferred-execution machinery itself: its concrete()/materialize
# calls ARE the implementation of the flush protocol, not clients of it
# (matched against the ABSOLUTE path so single-file analysis of
# core/fusion.py is exempt too, while a fixture named fusion.py is not)
MACHINERY_SUFFIXES = ("paddle_tpu/core/fusion.py",
                      "paddle_tpu/core/dispatch.py")

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# ---------------------------------------------------------------------------
# fusion sanitizer vocabulary

# attribute reads LazyArray serves eagerly from its memoized aval —
# these stay eager under fusion by construction and must NEVER flag
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "name",
                "itemsize", "nbytes", "stop_gradient", "trainable",
                "place", "is_leaf", "persistable", "type"}
# calls whose result is host-static (no flush to compute)
SANITIZER_CALLS = {"len", "isinstance", "issubclass", "type", "id",
                   "hasattr", "callable", "getattr", "issubdtype",
                   "result_type", "finfo", "iinfo", "aval_of",
                   "enumerate", "zip", "range", "sorted", "reversed",
                   # host container constructors: membership/truthiness
                   # on their result is host work, never a flush
                   "set", "frozenset", "dict",
                   # the sanctioned deferred/concretize routes: their
                   # RESULT is handled; routing through them is the fix
                   # fuselint recommends, so it must not re-flag
                   "lazy_add", "lazy_astype", "record_call", "concrete",
                   "_concrete", "_raw",
                   # pytree structure work is host-side bookkeeping
                   "tree_flatten", "tree_unflatten", "tree_map",
                   "tree_leaves", "tree_structure", "flatten_up_to"}
# scalar coercions: each is a materialize (flush) on a lazy operand
COERCIONS = {"float", "int", "bool", "complex"}
HOST_METHODS = {"numpy", "item", "tolist"}
NP_HOST_FUNCS = {"asarray", "array", "asanyarray", "ascontiguousarray"}
EXPLICIT_CONCRETIZE = {"concrete", "_concrete"}

# tensor-producing surfaces: a name bound from one of these is
# potentially lazy even when no tainted value flowed in
TENSOR_HEADS = {"paddle", "T", "F"}
TENSOR_PRODUCERS = {"to_tensor", "Tensor", "apply", "_apply", "run_op"}
TENSOR_ATTRS = {"_value", "grad", "_grad"}
ARRAY_METHODS = {"astype", "reshape", "sum", "mean", "transpose", "ravel",
                 "squeeze", "flatten", "min", "max", "dot", "backward",
                 "clip", "detach", "cast", "numpy", "item", "tolist",
                 "clear_grad", "cumsum", "prod", "abs", "norm"}

# FL005 side-effect surfaces
LOG_HEADS = {"logging", "logger", "log", "warnings"}
LOG_METHODS = {"debug", "info", "warning", "warn", "error", "critical",
               "exception"}
STRINGIFY = {"str", "format", "repr"}

# FL006 backward-path entry names (reachability seeds + name patterns)
BACKWARD_SEEDS = {"run_backward", "backward", "grad"}
BACKWARD_NAME_HINTS = ("pullback", "_add_cot", "_accum_leaf", "_eager",
                      "bwd_fn", "vjp_call")
RAW_ARRAY_HEADS = {"jnp", "np", "numpy", "jax", "lax"}

DEFAULT_MAX_OPS = 256


def _max_ops_threshold():
    try:
        return max(2, int(os.environ.get("PADDLE_TPU_FUSION_MAX_OPS",
                                         str(DEFAULT_MAX_OPS))))
    except ValueError:
        return DEFAULT_MAX_OPS


# ---------------------------------------------------------------------------
# model

class Finding(_findings.Finding):
    """fuselint finding: the shared record bound to the FL catalog."""

    RULES = RULES


# ---------------------------------------------------------------------------
# checked-in unjittable manifest, read as data (never imported)

def load_unjittable_manifest(path):
    """(path suffix, co_name, co_firstlineno) -> reason from the
    generated manifest module, parsed as AST data. Missing/stale file
    degrades to {} — FL003's manifest half just goes silent."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError, UnicodeDecodeError):
        return {}
    version = None
    table = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for t in stmt.targets:
            if not isinstance(t, ast.Name):
                continue
            try:
                if t.id == "MANIFEST_VERSION":
                    version = ast.literal_eval(stmt.value)
                elif t.id == "UNJITTABLE":
                    table = ast.literal_eval(stmt.value)
            except (ValueError, SyntaxError):
                return {}
    if version != 1 or not isinstance(table, dict):
        return {}
    return table


# ---------------------------------------------------------------------------
# per-function barrier analysis

class _FnChecker:
    def __init__(self, module, fnode):
        self.m = module
        self.fnode = fnode
        self.scopes = module.scopes
        self.qual = module.scopes.qualname(fnode)
        self.func_name = (fnode.name if not isinstance(fnode, ast.Lambda)
                          else "<lambda>")
        self.func_line = runtime_first_line(fnode)

        self.taint = NameTaint(fnode, static_attrs=STATIC_ATTRS,
                               sanitizer_calls=SANITIZER_CALLS,
                               coercions=COERCIONS,
                               host_methods=HOST_METHODS)
        # re-seed from scratch: the receiver objects (self/cls) are
        # never themselves lazy arrays, and the constructor's propagate
        # already spread their taint (`x = self._table.get(k)`) — reset
        # to the param seeds minus self/cls, add names bound from
        # tensor-producing expressions, and re-propagate once
        seeds = set(func_params(fnode)[1]) - {"self", "cls"}
        for n in self._body():
            if isinstance(n, ast.Assign) and self._produces_tensor(n.value):
                for t in n.targets:
                    seeds.update(self._target_roots(t))
        self.taint.tainted = seeds
        self.taint.propagate()
        self.taint.tainted -= {"self", "cls"}
        self.evidence = self._collect_evidence()

    @staticmethod
    def _target_roots(t):
        """The name(s) an assignment target BINDS — plain names and
        tuple/list element names; for container-element stores
        (`d[k] = v`, `obj.a = v`) the ROOT container only, never the
        subscript-index names (`k` is not made a tensor by being a key
        under one)."""
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            out = []
            for e in t.elts:
                out.extend(_FnChecker._target_roots(e))
            return out
        root = t
        while isinstance(root, (ast.Attribute, ast.Subscript,
                                ast.Starred)):
            root = root.value
        return [root.id] if isinstance(root, ast.Name) else []

    def _body(self):
        """Own-body nodes only: nested defs/lambdas are separate graph
        functions and get their own checker — scanning them here too
        would double-report every finding (taint propagation still sees
        the full body via NameTaint's own iteration)."""
        yield from CallGraph.body_nodes(self.fnode)

    # -- tensor-ness --------------------------------------------------------
    def _produces_tensor(self, expr):
        if isinstance(expr, ast.Call):
            d = dotted(expr.func)
            if d and (d[0] in TENSOR_HEADS or d[-1] in TENSOR_PRODUCERS):
                return True
        if isinstance(expr, ast.Attribute) and expr.attr in TENSOR_ATTRS:
            return True
        return False

    def _collect_evidence(self):
        """Names this function itself treats as tensors."""
        ev = set()
        for n in self._body():
            if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name):
                if n.attr in TENSOR_ATTRS or n.attr in ARRAY_METHODS:
                    ev.add(n.value.id)
            elif isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and (d[0] in TENSOR_HEADS
                          or d[-1] in TENSOR_PRODUCERS):
                    for a in list(n.args) + [kw.value for kw in n.keywords]:
                        for nm in ast.walk(a):
                            if isinstance(nm, ast.Name):
                                ev.add(nm.id)
            elif isinstance(n, ast.Assign) and \
                    self._produces_tensor(n.value):
                for t in n.targets:
                    ev.update(self._target_roots(t))
        return ev

    def _hot(self, expr):
        """Taint + evidence: the bar a finding must clear."""
        if not self.taint.expr_tainted(expr):
            return None
        names = self.taint.taint_names(expr)
        if any(nm in self.evidence for nm in names):
            return names
        # expression-level evidence without a named carrier
        # (float(x.sum()) — the receiver method IS the evidence)
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and (
                    n.attr in TENSOR_ATTRS or n.attr in ARRAY_METHODS):
                return names or ["<expr>"]
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and (d[0] in TENSOR_HEADS or d[-1] in TENSOR_PRODUCERS):
                    return names or ["<expr>"]
        return None

    def _in_loop(self, node):
        return bool(self.scopes.enclosing_loops(node))

    # -- reporting ----------------------------------------------------------
    def report(self, rule, node, message, symbol, confidence,
               context="step-path"):
        self.m.findings.append(Finding(
            rule=rule, path=self.m.relpath, line=node.lineno,
            col=node.col_offset, func=self.qual, func_name=self.func_name,
            func_line=self.func_line, message=message, symbol=symbol,
            severity=RULES[rule].severity, confidence=confidence,
            context=context))

    # -- FL001 / FL005 (loop-scoped) + FL002 --------------------------------
    def run(self):
        for n in self._body():
            if isinstance(n, ast.Call):
                self._check_call(n)
            elif isinstance(n, ast.If):
                self._check_branch(n, n.test, "if")
            elif isinstance(n, ast.While):
                self._check_branch(n, n.test, "while")
            elif isinstance(n, ast.IfExp):
                self._check_branch(n, n.test, "ternary")
            elif isinstance(n, ast.Assert):
                self._check_branch(n, n.test, "assert")
            elif isinstance(n, ast.JoinedStr):
                self._check_fstring(n)

    def _check_call(self, n):
        d = dotted(n.func)
        in_loop = self._in_loop(n)
        # FL001: scalar coercions on a lazy value, per iteration
        if d and len(d) == 1 and d[0] in COERCIONS and n.args and in_loop:
            names = self._hot(n.args[0])
            if names:
                self.report(
                    "host-materialize-in-loop", n,
                    f"{d[0]}() on a potentially-lazy tensor value "
                    f"({', '.join(names)}) inside a loop — every "
                    "iteration flushes the pending fused trace here; "
                    "hoist the read out of the loop, batch it, or "
                    "waive if the per-step sync is the contract "
                    "(loss logging)",
                    f"{d[0]}:{','.join(names)}", "definite")
                return
        # FL001: .numpy()/.item()/.tolist() per iteration
        if isinstance(n.func, ast.Attribute) and \
                n.func.attr in HOST_METHODS and in_loop:
            base = n.func.value
            if self._hot(base) or (
                    isinstance(base, ast.Attribute)
                    and base.attr in TENSOR_ATTRS):
                self.report(
                    "host-materialize-in-loop", n,
                    f".{n.func.attr}() inside a loop forces a host "
                    "transfer — a per-iteration flush point for the "
                    "fused trace",
                    f".{n.func.attr}", "definite")
                return
        # FL001: np.asarray & friends on a lazy value, per iteration
        if d and len(d) >= 2 and d[0] in ("np", "numpy") and \
                d[-1] in NP_HOST_FUNCS and in_loop:
            hot = [nm for a in n.args for nm in (self._hot(a) or ())]
            if hot:
                self.report(
                    "host-materialize-in-loop", n,
                    f"{'.'.join(d)} materializes a potentially-lazy "
                    f"value ({', '.join(hot)}) on host every iteration",
                    ".".join(d), "definite")
                return
        # FL001: explicit concretize route — deliberate by definition,
        # but each site is a flush boundary the audit must see (and the
        # anchor --verify-runtime cross-references against)
        if d and d[-1] in EXPLICIT_CONCRETIZE and n.args and in_loop:
            self.report(
                "host-materialize-in-loop", n,
                f"{'.'.join(d)}() — explicit concretize inside a loop: "
                "a deliberate flush boundary; keep it reviewed (waive "
                "or baseline) so the fused-program extent stays an "
                "intentional choice",
                "concrete", "possible", context="explicit-materialize")
            return
        # FL004: suspend-region entry (machinery modules never reach
        # here — run() gates the whole per-function pass)
        if d and d[-1] == "suspend":
            head = d[-2] if len(d) > 1 else ""
            self.report(
                "suspend-region-entry", n,
                f"{'.'.join(d)}() — entering a suspend region flushes "
                "the pending trace and records nothing until exit (a "
                "mandatory fusion boundary); confirm it is intentional "
                "with `# fuselint: ok[FL004]` after review",
                f"suspend:{head or 'suspend'}".rstrip(":"),
                "definite", context="suspend")
            return
        # FL005: side effects on tensor values per iteration
        if in_loop:
            self._check_side_effect(n, d)

    def _check_side_effect(self, n, d):
        is_print = d == ("print",)
        is_log = bool(d) and (d[0] in LOG_HEADS
                              or (len(d) > 1 and d[-1] in LOG_METHODS))
        is_str = bool(d) and len(d) == 1 and d[0] in STRINGIFY
        if not (is_print or is_log or is_str):
            return
        hot = []
        for a in list(n.args) + [kw.value for kw in n.keywords]:
            hot.extend(self._hot(a) or ())
        if not hot:
            return
        kind = "print" if is_print else ("log" if is_log else d[0])
        self.report(
            "per-step-side-effect", n,
            f"{kind}() of a potentially-lazy tensor value "
            f"({', '.join(sorted(set(hot)))}) inside a loop — "
            "stringification materializes, flushing the fused trace "
            "every iteration; log a host scalar captured outside the "
            "loop, throttle to every-N steps, or waive",
            f"{kind}:{','.join(sorted(set(hot)))}", "definite")

    def _check_fstring(self, n):
        if not self._in_loop(n):
            return
        hot = []
        for v in n.values:
            if isinstance(v, ast.FormattedValue):
                hot.extend(self._hot(v.value) or ())
        if hot:
            self.report(
                "per-step-side-effect", n,
                "f-string interpolates a potentially-lazy tensor value "
                f"({', '.join(sorted(set(hot)))}) inside a loop — each "
                "format materializes and flushes the fused trace",
                f"fstr:{','.join(sorted(set(hot)))}", "definite")

    def _check_branch(self, node, test, kind):
        names = self._hot(test)
        if not names:
            return
        self.report(
            "data-dependent-branch", node,
            f"`{kind}` on a potentially-lazy tensor value "
            f"({', '.join(names)}) — __bool__ concretizes, flushing "
            "the pending trace; compare host scalars, use jnp.where, "
            "or waive if the branch is a deliberate sync point",
            f"{kind}:{','.join(names)}", "definite",
            context="control-flow")


# ---------------------------------------------------------------------------
# per-module driver

class ModuleFusionAnalysis:
    def __init__(self, path, root_parent, manifest=None):
        self.path = path
        self.relpath = _relpath(path, root_parent)
        self.is_machinery = os.path.abspath(path).replace(
            os.sep, "/").endswith(MACHINERY_SUFFIXES)
        with open(path, "r", encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=path)
        self.scopes = ScopeIndex(self.tree)
        self.graph = CallGraph(self.tree, self.scopes)
        self.manifest = manifest or {}
        self.findings = []

    def run(self):
        if not self.is_machinery:
            for qual, fnode in self.graph.functions.items():
                _FnChecker(self, fnode).run()
            self._check_manifest_barriers()        # FL003 (manifest half)
            self._check_non_jittable_barriers()    # FL003 (decorator half)
            self._check_backward_escapes()         # FL006
            self._check_trace_length()             # FL007
        for f in self.findings:
            f.suppressed = _waiver_suppressed(self.lines, f.line, f.rule,
                                              TOOL, RULES)
        self.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return self.findings

    # -- FL003 --------------------------------------------------------------
    def _check_manifest_barriers(self):
        if not self.manifest:
            return
        rp = self.relpath
        # manifest keys are paddle_tpu/-anchored suffixes; relpath is
        # root_parent-relative, so a direct suffix match is exact
        for (suffix, co_name, lineno), reason in sorted(
                self.manifest.items()):
            if not rp.endswith(suffix):
                continue
            self.findings.append(Finding(
                rule="known-demotion-barrier", path=rp, line=lineno,
                col=0, func=co_name, func_name=co_name, func_line=lineno,
                message=f"`{co_name}` is in the static unjittable "
                        f"manifest ({reason}) — under fusion every "
                        "sighting is a forced flush point; make the op "
                        "trace-safe to lift the barrier, or accept it "
                        "(baseline) as a known fusion boundary",
                symbol=f"manifest:{co_name}",
                severity=RULES["known-demotion-barrier"].severity,
                confidence="definite", context="manifest"))

    def _check_non_jittable_barriers(self):
        for n in ast.walk(self.tree):
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in n.decorator_list:
                dd = dotted(dec)
                if dd and dd[-1] == "non_jittable":
                    qual = self.scopes.qualname(n)
                    self.findings.append(Finding(
                        rule="known-demotion-barrier", path=self.relpath,
                        line=n.lineno, col=n.col_offset, func=qual,
                        func_name=n.name,
                        func_line=runtime_first_line(n),
                        message=f"@non_jittable op `{n.name}` — a "
                                "declared trace-unsafe op is a forced "
                                "flush point under fusion; every call "
                                "site on a step path cuts the fused "
                                "program here",
                        symbol=f"non_jittable:{n.name}",
                        severity=RULES["known-demotion-barrier"].severity,
                        confidence="definite", context="non-jittable"))

    # -- FL006 --------------------------------------------------------------
    def _backward_quals(self):
        seeds = [q for q in self.graph.functions
                 if q.rsplit(".", 1)[-1] in BACKWARD_SEEDS]
        reach = self.graph.reachable(seeds)
        for q in self.graph.functions:
            last = q.rsplit(".", 1)[-1]
            if any(h in last for h in BACKWARD_NAME_HINTS) or \
                    any(h in q for h in ("pullback",)):
                reach.add(q)
        return reach

    def _check_backward_escapes(self):
        # only modules that participate in the lazy protocol carry the
        # backward tape (importing/naming fusion.lazy_* is the marker);
        # elsewhere a jnp call in a `backward` helper is ordinary eager
        if "lazy_" not in self.src and "record_call" not in self.src:
            return
        for qual in sorted(self._backward_quals()):
            fnode = self.graph.functions.get(qual)
            if fnode is None:
                continue
            checker = _FnChecker(self, fnode)
            for n in CallGraph.body_nodes(fnode):
                if isinstance(n, ast.Call):
                    d = dotted(n.func)
                    if not d or d[0] not in RAW_ARRAY_HEADS:
                        continue
                    if d[-1] in SANITIZER_CALLS or len(d) > 1 and \
                            d[1] == "tree_util":
                        continue
                    # taint alone suffices here: backward-path
                    # functions are pre-qualified by reachability, and
                    # cotangents are raw arrays (no Tensor-evidence
                    # surface to observe)
                    hot = [nm for a in n.args
                           if checker.taint.expr_tainted(a)
                           for nm in (checker.taint.taint_names(a)
                                      or ["<expr>"])]
                    if not hot:
                        continue
                    checker.report(
                        "backward-path-escape", n,
                        f"{'.'.join(d)} on a potentially-lazy cotangent "
                        f"({', '.join(sorted(set(hot)))}) inside the "
                        "backward tape path — __jax_array__ "
                        "materializes it, flushing the fused "
                        "fwd+bwd program mid-backward; route through "
                        "fusion.lazy_*/record_call, or concrete() "
                        "deliberately",
                        f"escape:{'.'.join(d)}", "definite",
                        context="backward")
                elif isinstance(n, ast.BinOp) and \
                        isinstance(n.op, ast.Add):
                    t = checker.taint
                    lhot = t.taint_names(n.left) \
                        if t.expr_tainted(n.left) else []
                    rhot = t.taint_names(n.right) \
                        if t.expr_tainted(n.right) else []
                    if not (lhot or rhot):
                        continue
                    names = sorted(set(lhot + rhot))
                    checker.report(
                        "backward-path-escape", n,
                        "bare `+` on a potentially-lazy cotangent "
                        f"({', '.join(names)}) in the backward tape "
                        "path — a concrete-left + lazy-right add "
                        "materializes the lazy side and flushes "
                        "mid-backward; use fusion.lazy_add",
                        f"add:{','.join(names)}", "possible",
                        context="backward")

    # -- FL007 --------------------------------------------------------------
    def _loop_op_estimate(self, loop, checker):
        """Per-iteration recorded-op estimate of a loop body: tensor-op
        calls + tainted binops, nested statically-known ranges
        multiplied in."""
        def est_stmts(stmts):
            total = 0
            for st in stmts:
                total += est_node(st)
            return total

        def est_node(node):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                inner = est_stmts(node.body) + est_stmts(node.orelse)
                trip = const_range(node.iter)
                return inner * (trip if trip is not None else 1)
            if isinstance(node, ast.While):
                return est_stmts(node.body) + est_stmts(node.orelse)
            if isinstance(node, _FUNC_NODES):
                return 0
            total = 0
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d and (d[0] in TENSOR_HEADS
                          or d[-1] in TENSOR_PRODUCERS):
                    total += 1
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ARRAY_METHODS and \
                        checker._hot(node.func.value):
                    total += 1
            elif isinstance(node, ast.BinOp):
                if checker._hot(node.left) or checker._hot(node.right):
                    total += 1
            for ch in ast.iter_child_nodes(node):
                total += est_node(ch)
            return total

        return est_stmts(loop.body) + est_stmts(loop.orelse)

    def _check_trace_length(self):
        threshold = _max_ops_threshold()
        for qual, fnode in self.graph.functions.items():
            checker = None
            for n in CallGraph.body_nodes(fnode):
                if not isinstance(n, (ast.For, ast.While)):
                    continue
                if self.scopes.enclosing_loops(n):
                    continue  # count outermost loops once (nested are
                    #           folded into the parent's estimate)
                if checker is None:
                    checker = _FnChecker(self, fnode)
                per_iter = self._loop_op_estimate(n, checker)
                if per_iter == 0:
                    continue
                trip = const_range(n.iter) if isinstance(n, ast.For) \
                    else None
                total = per_iter * trip if trip is not None else per_iter
                if total < threshold:
                    continue
                via = (f"{per_iter} ops/iter x {trip} iterations"
                       if trip is not None and trip > 1
                       else f"{per_iter} ops in one iteration")
                checker.report(
                    "trace-length-hazard", n,
                    f"static estimate ~{total} recorded ops for this "
                    f"loop ({via}) reaches PADDLE_TPU_FUSION_MAX_OPS "
                    f"({threshold}) — the trace will hit the max_len "
                    "safety valve and flush at an arbitrary op "
                    "boundary mid-loop; add an explicit flush/"
                    "materialize point per step, or raise the cap",
                    f"ops~{total}",
                    "definite" if trip is not None else "possible",
                    context="trace-length")


# ---------------------------------------------------------------------------
# tree driver

def iter_py_files(root):
    yield from _iter_py_files(root, skip_dirs=SKIP_DIRS)


def _find_manifest(roots):
    for root in roots:
        cand = os.path.join(root, "core", "_unjittable_manifest.py")
        if os.path.isfile(cand):
            return cand
    return None


def analyze_paths(roots, manifest_path=None):
    """Analyze every .py under each root. Returns (findings, errors):
    errors are (path, message) for unparseable files. The unjittable
    manifest is auto-discovered at <root>/core/_unjittable_manifest.py
    unless an explicit path is given."""
    if manifest_path is None:
        manifest_path = _find_manifest(roots)
    manifest = load_unjittable_manifest(manifest_path) \
        if manifest_path else {}
    findings, errors = [], []
    for root in roots:
        root = os.path.normpath(root)
        root_parent = os.path.dirname(os.path.abspath(root))
        for path in iter_py_files(root):
            rel = _relpath(path, root_parent)
            if rel.endswith("core/_unjittable_manifest.py"):
                continue  # generated data, not analyzed code
            try:
                ma = ModuleFusionAnalysis(path, root_parent,
                                          manifest=manifest)
                findings.extend(ma.run())
            except (SyntaxError, UnicodeDecodeError) as e:
                errors.append((rel, f"{type(e).__name__}: {e}"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


def analyze_file(path, manifest_path=None):
    return analyze_paths([path], manifest_path=manifest_path)
