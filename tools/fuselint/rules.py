"""Rule catalog for fuselint.

Each rule names one class of fusion barrier — a code shape that forces
the deferred-execution trace (core/fusion.py) to flush, cutting the
fused program short. The catalog is data, not behavior — detection
lives in analyzer.py — and the Rule dataclass/severity vocabulary is
shared with tracelint and threadlint via tools/staticlib.

Severity:
  error    — a proven per-step flush on a hot path; fix or waive.
  warning  — likely barrier; depends on which paths run under fusion.
  info     — hygiene note / intentional-boundary audit; never gates CI
             by severity alone (FL004 gates via the baseline like any
             warning — see RULES below).
"""
from __future__ import annotations

from ..staticlib.rules import Rule, ruleset

RULES, BY_ID, get = ruleset([
    Rule("FL001", "host-materialize-in-loop", "error", False,
         "host materialization of a potentially-lazy tensor value "
         "inside a loop body (float()/int()/bool(), .numpy()/.item()/"
         ".tolist(), np.asarray) — a per-step flush that caps the "
         "fused program at the loop granularity"),
    Rule("FL002", "data-dependent-branch", "warning", False,
         "Python if/while/assert on a tensor value in eager caller "
         "code — __bool__ concretizes, flushing the pending trace "
         "(shape/dtype/ndim reads stay eager via LazyArray's memoized "
         "avals and never flag)"),
    Rule("FL003", "known-demotion-barrier", "warning", False,
         "op statically known to demote at runtime (the tracelint "
         "unjittable manifest or an explicit @non_jittable marking) — "
         "every sighting under fusion is a forced flush point; "
         "reported at the op's definition so the barrier is visible "
         "where it will bite, not rediscovered per-process"),
    Rule("FL004", "suspend-region-entry", "warning", False,
         "dispatch.suspend()/fusion.suspend() region entry — a "
         "mandatory flush boundary by contract; every entry must be "
         "intentional and carry a reviewed inline waiver "
         "(`# fuselint: ok[FL004]`) or live in the baseline"),
    Rule("FL005", "per-step-side-effect", "warning", False,
         "Python side effect on a tensor value inside a loop body "
         "(print/logging/str-format of a traced value) — each "
         "stringification materializes and flushes per step"),
    Rule("FL006", "backward-path-escape", "error", False,
         "flush-forcing call inside the backward tape path: a raw "
         "jnp/np/jax call (or bare `+`) on a potentially-lazy "
         "cotangent that escapes the fusion.lazy_*/record_call/"
         "concrete() protocol — a mid-backward flush cuts the fused "
         "fwd+bwd program in half"),
    Rule("FL007", "trace-length-hazard", "warning", False,
         "static op-count estimate of a loop body (times any "
         "statically-known trip count) reaches "
         "PADDLE_TPU_FUSION_MAX_OPS — the trace will hit the max_len "
         "safety valve and flush mid-loop at a nondeterministic "
         "boundary; raise the cap or add an explicit flush point"),
])

__all__ = ["Rule", "RULES", "BY_ID", "get"]
