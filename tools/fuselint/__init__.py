"""fuselint — static fusion-barrier analysis for the paddle_tpu
deferred-execution (trace-fusion) engine.

Third analyzer on the shared tools/staticlib core (after tracelint's
jit-safety pass and threadlint's concurrency pass). Where tracelint
audits what happens INSIDE an op body handed to jax.jit, fuselint
audits the EAGER CALLER code around the dispatch layer: every host
materialization, data-dependent Python branch, unjittable op sighting,
suspend() region, per-step side effect, and trace-length hazard is a
FUSION BARRIER — a point where the lazy trace core/fusion.py is
accumulating must flush, shrinking the fused program back toward
per-op dispatch. Making deferred execution THE execution engine
(ROADMAP item 2) is gated on knowing where and why traces break;
fuselint moves that discovery to lint time, and its --verify-runtime
mode closes the loop against the flush-site attribution the runtime
records (dispatch_stats()["fusion"]["flush_sites"]).
"""
