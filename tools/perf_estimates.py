"""Generate docs/PERF_ESTIMATES.md — compile-time + XLA cost-analysis
tables for the BASELINE configs, measured on the CPU backend.

These are ESTIMATES, not benchmark numbers (round-5 verdict #9): the
XLA-optimized program's FLOPs/bytes are backend-sensitive, and nothing
here times execution. Their purpose is to make the first real chip
grant pure measurement time: model sizes, per-step work, and arithmetic
intensity are already pinned; the chip only needs to supply seconds.

Run from the repo root: `python tools/perf_estimates.py`
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

V5E_PEAK_BF16 = 197e12      # dense bf16 FLOP/s, public spec
V5E_HBM_GBS = 819e9         # HBM bandwidth, public spec


def _cost(compiled):
    try:
        c = compiled.cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0]
        return c or {}
    except Exception:  # noqa: BLE001
        return {}


def _row(name, lower_fn):
    t0 = time.perf_counter()
    lowered = lower_fn()
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0
    c = _cost(compiled)
    flops = float(c.get("flops", 0.0))
    bytes_acc = float(c.get("bytes accessed", 0.0))
    return {
        "config": name,
        "lower_s": round(t_lower, 1),
        "compile_s": round(t_compile, 1),
        "gflops_per_step": round(flops / 1e9, 1),
        "gbytes_per_step": round(bytes_acc / 1e9, 2),
        "arith_intensity": round(flops / bytes_acc, 1) if bytes_acc else None,
        # time bounds on v5e at peak: compute-bound vs bandwidth-bound
        "v5e_compute_bound_ms": round(flops / V5E_PEAK_BF16 * 1e3, 2),
        "v5e_bw_bound_ms": round(bytes_acc / V5E_HBM_GBS * 1e3, 2),
    }


def bert_step(batch=32, seq=128):
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM

    paddle.seed(0)
    cfg = BertConfig(dropout=0.0, attention_dropout=0.0)
    model = BertForMaskedLM(cfg)
    paddle.amp.decorate(model, level="O2")
    model.eval()
    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    meta = opt.param_meta({k: p for k, p in model.named_parameters()
                           if not p.stop_gradient})
    states = opt.functional_init_states(params)

    def step(pv, st, ids, labels):
        def loss_of(p):
            with paddle.no_grad():
                out, _ = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()},
                    Tensor(ids), None, None, Tensor(labels))
            loss = out[0] if isinstance(out, (list, tuple)) else out
            return loss._value.astype(jnp.float32)
        loss, grads = jax.value_and_grad(loss_of)(pv)
        new_p, new_s = opt.functional_update(pv, grads, st,
                                             jnp.float32(1e-4), meta=meta)
        return new_p, new_s, loss

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    return (jax.jit(step, donate_argnums=(0, 1))
            .lower(params, states, ids, labels),
            n_params, batch * seq)


def gpt_step(batch=8, seq=512):
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_position=seq, dropout=0.0)
    model = GPTForCausalLM(cfg)
    paddle.amp.decorate(model, level="O2")
    model.eval()
    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    meta = opt.param_meta({k: p for k, p in model.named_parameters()
                           if not p.stop_gradient})
    states = opt.functional_init_states(params)

    def step(pv, st, ids, labels):
        def loss_of(p):
            with paddle.no_grad():
                out = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()},
                    Tensor(ids), None, Tensor(labels))[0]
            loss = out[0] if isinstance(out, (list, tuple)) else out
            return loss._value.astype(jnp.float32)
        loss, grads = jax.value_and_grad(loss_of)(pv)
        new_p, new_s = opt.functional_update(pv, grads, st,
                                             jnp.float32(1e-4), meta=meta)
        return new_p, new_s, loss

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    return (jax.jit(step, donate_argnums=(0, 1))
            .lower(params, states, ids, labels),
            n_params, batch * seq)


def resnet50_fwdbwd(batch=64):
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=100)
    model.eval()
    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}

    def step(pv, x, y):
        def loss_of(p):
            with paddle.no_grad():
                logits, _ = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()}, Tensor(x))
            from paddle_tpu import nn
            return nn.functional.cross_entropy(
                logits, Tensor(y))._value.astype(jnp.float32)
        loss, grads = jax.value_and_grad(loss_of)(pv)
        return grads, loss

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, 3, 224, 224).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 100, batch).astype(np.int64))
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    return jax.jit(step).lower(params, x, y), n_params, batch


def lenet_step(batch=256):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}

    def step(pv, x, y):
        def loss_of(p):
            with paddle.no_grad():
                logits, _ = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()}, Tensor(x))
            return nn.functional.cross_entropy(
                logits, Tensor(y))._value.astype(jnp.float32)
        loss, grads = jax.value_and_grad(loss_of)(pv)
        return grads, loss

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, 1, 28, 28).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, (batch,)).astype(np.int64))
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    return jax.jit(step).lower(params, x, y), n_params, batch


def main():
    rows = []
    extras = {}
    for name, builder, unit in [
        ("LeNet Model.fit step (b256)", lenet_step, "imgs"),
        ("ResNet50 fwd+bwd (b64, f32)", resnet50_fwdbwd, "imgs"),
        ("BERT-base MLM AMP-O2 step (b32 s128)", bert_step, "tokens"),
        ("GPT-2 small AMP-O2 step (b8 s512)", gpt_step, "tokens"),
    ]:
        lowered, n_params, units_per_step = (None, None, None)
        t_build = time.perf_counter()
        lowered, n_params, units_per_step = builder()
        t_build = time.perf_counter() - t_build
        row = _row(name, lambda: lowered)
        row["lower_s"] = round(t_build, 1)  # build+trace+lower together
        row["params_m"] = round(n_params / 1e6, 1)
        row["units_per_step"] = units_per_step
        row["unit"] = unit
        # throughput the cost model implies on v5e if the step runs at
        # the max of the two bounds (idealized; real MFU will be lower)
        bound_ms = max(row["v5e_compute_bound_ms"], row["v5e_bw_bound_ms"])
        if bound_ms:
            row["v5e_roofline_per_sec"] = round(
                units_per_step / (bound_ms / 1e3))
        rows.append(row)
        print(json.dumps(row), flush=True)

    md = [
        "# PERF ESTIMATES (no chip required) — round-5 contingency",
        "",
        "**These are NOT measured benchmark numbers.** They are XLA",
        "cost-analysis properties of the compiled train-step programs,",
        "generated on the CPU backend (`tools/perf_estimates.py`),",
        "plus public v5e peak specs (197 Tbf16FLOP/s, 819 GB/s HBM).",
        "The roofline column is the throughput implied if the step ran",
        "exactly at the binding bound — an upper bound, not a claim.",
        "Purpose: when the chip grant arrives, all model/work numbers",
        "are pre-pinned and the grant is spent purely on timing",
        "(bench.py measures; BENCH_rNN.json records).",
        "",
        "| config | params (M) | GFLOP/step | GB/step | FLOP:byte | "
        "compute-bound ms | bw-bound ms | roofline/s | compile s (CPU) |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        md.append(
            f"| {r['config']} | {r['params_m']} | {r['gflops_per_step']} | "
            f"{r['gbytes_per_step']} | {r['arith_intensity']} | "
            f"{r['v5e_compute_bound_ms']} | {r['v5e_bw_bound_ms']} | "
            f"{r.get('v5e_roofline_per_sec', '-')} {r['unit']} | "
            f"{r['compile_s']} |")
    md += [
        "",
        "Notes:",
        "- FLOPs/bytes come from `compiled.cost_analysis()` of the whole",
        "  donated train step (fwd+bwd+optimizer) on the CPU backend; the",
        "  TPU-optimized program may fuse differently. Cross-check against",
        "  the analytic `6*params*tokens` estimate is recorded by bench.py",
        "  (`*_flops_xla_vs_analytic`).",
        "- The Pallas flash kernel cannot appear in CPU lowerings (dispatch",
        "  requires the tpu backend); `bench_bert` records",
        "  `bert_flash_in_hlo` from the on-chip lowering as engagement",
        "  proof. Multi-chip collective evidence (all-reduce /",
        "  collective-permute / all-to-all in the 8-device HLO) is pinned",
        "  by `__graft_entry__.dryrun_multichip` (MULTICHIP_r0N.json).",
        "- 8-chip GPT hybrid (dp*tp*pp) per-chip cost scales the GPT row",
        "  by ~1/8 compute with collective overhead on top; the dryrun",
        "  compiles and executes the sharded program on the virtual mesh.",
        "",
    ]
    with open(os.path.join(REPO, "docs", "PERF_ESTIMATES.md"), "w") as f:
        f.write("\n".join(md))
    print("wrote docs/PERF_ESTIMATES.md")


if __name__ == "__main__":
    main()
