#!/usr/bin/env bash
# One-command static gate: staticcheck (tracelint + threadlint +
# fuselint + distlint with their freshness gates, plus the telemetry
# schema-consistency pass) + the fuselint/distlint runtime
# cross-references + import health, plus the fast resilience/warm-start/
# fusion-parity/telemetry/multihost/divergence smokes and the cluster
# crash acceptance (~4 min total) — run before pushing; CI runs the
# same line.
#
#   ./tools/ci_check.sh
#
# Exit non-zero on: new (non-baselined) tracelint findings, a stale
# checked-in unjittable manifest, or any paddle_tpu submodule that
# fails to import on CPU.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== staticcheck (tracelint + threadlint + fuselint + distlint + runtime anchors) =="
# one command runs every static analyzer with its freshness gate:
# tracelint (jit-safety + stale-manifest check), threadlint
# (concurrency + stale-baseline check), fuselint (fusion barriers +
# stale-baseline check), distlint (cross-rank divergence + stale-
# baseline check), and the telemetry schema-consistency pass (every
# record_fault/emit kind literal declared, every declared kind used) —
# new findings, parse errors, or stale debt in any tool fail here.
# --verify-runtime rides on each tool's SINGLE pass: fuselint's child
# runs the bench MLP train step under fusion and the static findings
# must cross-reference the runtime flush-site attribution; distlint's
# child issues eager collectives and the static collective-site
# inventory must cross-reference the runtime schedule recorder
# (>= 1 confirmed, no uncovered in-tree sites, per tool)
JAX_PLATFORMS=cpu python tools/staticcheck.py paddle_tpu --verify-runtime

echo "== import health (every submodule imports on CPU) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_import_health.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== resilience (fast fault-injection paths) =="
# everything but the subprocess crash-consistency test (that one spawns
# a fresh interpreter and SIGKILLs it mid-save; tier-1 runs it)
JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
    -k "not kill9_mid_async" \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== runtime-learned demotions vs the static unjittable manifest =="
# a demotion the dispatch layer learns at runtime is a tracelint rule
# gap — fails with the op names and a manifest-regenerate hint
JAX_PLATFORMS=cpu python tools/check_runtime_demotions.py

echo "== warm-start smoke (persistent compile cache + shape manifest) =="
# two subprocesses share a temp cache dir: the second must load from
# disk (hits > 0) and perform ZERO fresh XLA compiles
JAX_PLATFORMS=cpu python tools/warmstart_smoke.py

echo "== fusion smoke (trace-fusion warm-start round trip) =="
# two subprocesses share a compile cache + shape manifest: the second
# must AOT-replay the recorded fused traces (fused-cache misses == 0)
# with ZERO fresh XLA compiles and disk hits > 0
JAX_PLATFORMS=cpu python tools/fusion_smoke.py

echo "== serve smoke (continuous batching + warm restart + reconciliation) =="
# two subprocesses prove the ISSUE-13 serving acceptance: pass A runs
# 4 concurrent requests under continuous batching over the paged KV
# cache and must be TOKEN-EXACT vs sequential one-request-at-a-time
# generation with serve/ span sums equal to the request/ttft latency
# histograms; pass B warm-starts from pass A's manifest and must
# perform ZERO fresh XLA compiles
JAX_PLATFORMS=cpu python tools/serve_smoke.py

echo "== serve chaos smoke (overload shed + fault chaos + drain + crash recovery) =="
# four fresh-subprocess stages prove the ISSUE-18 robustness
# acceptance: 4x-sustainable open-loop traffic must SHED (overloaded
# outcomes + serve_sheds faults) with bounded queue depth and TTFT and
# no wedge; injected serve.step delay / serve.kv_alloc failures must
# degrade per contract (deadline evictions only / prompt starvation
# then full recovery); SIGTERM must drain gracefully (rc=-15 + a
# sigterm_drain postmortem bundle carrying the drain report); and a
# SIGKILL mid-decode must journal-recover TOKEN-EXACT vs an
# uninterrupted run with ZERO fresh XLA compiles
JAX_PLATFORMS=cpu python tools/serve_chaos_smoke.py

echo "== loadgen record/replay round trip (trace-driven replay fidelity) =="
# ISSUE 20: a short recorded run's serve_access log replayed through
# --replay must reproduce the recorded arrival offsets and request mix
# EXACTLY (--verify-replay fails the run otherwise)
REPLAY_DIR=$(mktemp -d)
trap 'rm -rf "$REPLAY_DIR"' EXIT
JAX_PLATFORMS=cpu python tools/loadgen.py --rate 30 --duration 1 \
    --seed 3 --max-queued 16 --record "$REPLAY_DIR/rec.jsonl" \
    > "$REPLAY_DIR/record_report.json"
JAX_PLATFORMS=cpu python tools/loadgen.py --replay "$REPLAY_DIR/rec.jsonl" \
    --verify-replay --seed 3 --max-queued 16 \
    > "$REPLAY_DIR/replay_report.json"
python - "$REPLAY_DIR" <<'PYEOF'
import json, sys
d = json.load(open(sys.argv[1] + "/replay_report.json"))
assert d["replay"]["fidelity_ok"], d["replay"]
assert d["replay"]["count"] == d["offered"], (d["replay"], d["offered"])
print("record/replay: %d requests, fidelity ok, skew max %ss"
      % (d["replay"]["count"], d["replay"]["arrival_skew_max_s"]))
PYEOF

echo "== multihost smoke (coordination store + quorum + merge) =="
# 2-process CPU cluster over a tmpdir store: heartbeat + rendezvous
# round trip, host-0 merged prom/fault-log carrying both rank labels,
# and a quorum-stall watchdog that must exit NONZERO once every rank
# goes silent
JAX_PLATFORMS=cpu python tools/multihost_smoke.py

echo "== distlint smoke (cross-rank collective-divergence detection) =="
# 2-process CPU cluster over a tmpdir store: rank 1 carries an injected
# rank-conditional collective (the DL001 bug shape, live); BOTH ranks'
# monitors must flag collective_divergence well before the dead-peer
# deadline, the merged host-0 fault log must carry both ranks' schedule
# tails, and each rank's postmortem bundle must hold the two-sided
# schedule diff
JAX_PLATFORMS=cpu python tools/distlint_smoke.py

echo "== cluster crash-consistency acceptance (3-rank SIGKILL) =="
# the PR-6 acceptance proof (slow-marked out of the tier-1 budget run):
# rank 1 SIGKILLed mid-async-save; survivors must not quorum-stall,
# must restore the SAME common step, and the host-0 merge must carry
# all three ranks' labels incl. the dying rank's final fault (~50s)
JAX_PLATFORMS=cpu python -m pytest \
    "tests/test_cluster_resilience.py::test_cluster_kill9_mid_async_save_survivors_agree" \
    -q -m slow -p no:cacheprovider -p no:xdist -p no:randomly

echo "== fusion parity slice (model families under PADDLE_TPU_EAGER_FUSION=1) =="
# ROADMAP item 2's flip-the-default gate grows here: the eager-path
# slice now covers EVERY model family — transformer/gpt generate +
# autograd + op math + fusion + amp (the original slice) plus vision
# ops, rnn/layer sweeps, and quantization — and must pass with
# deferred execution ON. Parity gaps get a skip-with-reason in the
# test and an entry in ROADMAP — never a silent drop from this list.
JAX_PLATFORMS=cpu PADDLE_TPU_EAGER_FUSION=1 python -m pytest \
    tests/test_transformer_models.py tests/test_autograd.py \
    tests/test_ops_math.py tests/test_fusion.py tests/test_amp.py \
    tests/test_vision_ops.py tests/test_nn_layers.py \
    tests/test_layer_sweep.py tests/test_quantization.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

echo "== telemetry smoke (event stream + prom export + schema gate) =="
# a tiny fit must produce an event stream, a Prometheus textfile whose
# counters reconcile exactly with dispatch_stats()/fault_events(), and
# the metric/event schema must match the checked-in telemetry_schema.json
JAX_PLATFORMS=cpu python tools/telemetry_smoke.py

echo "== diagnostics smoke (flight recorder + bundles + statusz) =="
# the crash-and-hang layer: a watchdog stall must dump a postmortem
# bundle (all-thread stacks + dispatch/fusion stats + contiguous
# flight-recorder tail), /statusz + /metrics must serve well-formed
# live data DURING a real fit, and a bench campaign child killed at
# its per-config deadline must leave a bundle the orchestrator
# ingests into the round payload (evidence instead of rc=124)
JAX_PLATFORMS=cpu python tools/diagnostics_smoke.py

echo "== data smoke (async input pipeline: parity + 2x data-wait cut) =="
# three subprocesses over one compile cache prove the ISSUE-15 gates:
# the DevicePrefetcher path is loss-BIT-exact vs synchronous input,
# cuts measured data-wait >= 2x on a data-bound fit, reconciles the
# new io/h2d spans with paddle_tpu_h2d_seconds exactly, introduces
# ZERO new fusion flush sites, and the warm second process still
# performs zero fresh XLA compiles with the prefetcher on
JAX_PLATFORMS=cpu python tools/data_smoke.py

echo "== trace smoke (span timeline + reconciliation + cluster merge) =="
# a tiny fit under PADDLE_TPU_TRACE must emit a Perfetto-loadable
# Chrome trace whose per-phase span sums reconcile with
# dispatch_stats()/the telemetry histograms, and a 2-process cluster
# fit must merge into ONE cluster timeline carrying dispatch/fusion/
# checkpoint/coordination spans from BOTH ranks
JAX_PLATFORMS=cpu python tools/trace_smoke.py

echo "ci_check: OK"
