#!/usr/bin/env bash
# One-command static gate: tracelint + manifest freshness + import
# health. Fast (no test suite, ~seconds) — run it locally before
# pushing; CI runs the same line.
#
#   ./tools/ci_check.sh
#
# Exit non-zero on: new (non-baselined) tracelint findings, a stale
# checked-in unjittable manifest, or any paddle_tpu submodule that
# fails to import on CPU.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tracelint (jit-safety static analysis + manifest freshness) =="
# one invocation does both: reports/gates on new findings AND fails if
# the checked-in unjittable manifest is stale
JAX_PLATFORMS=cpu python -m tools.tracelint paddle_tpu --check-manifest

echo "== import health (every submodule imports on CPU) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_import_health.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== resilience (fast fault-injection paths) =="
# everything but the subprocess crash-consistency test (that one spawns
# a fresh interpreter and SIGKILLs it mid-save; tier-1 runs it)
JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
    -k "not kill9_mid_async" \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== runtime-learned demotions vs the static unjittable manifest =="
# a demotion the dispatch layer learns at runtime is a tracelint rule
# gap — fails with the op names and a manifest-regenerate hint
JAX_PLATFORMS=cpu python tools/check_runtime_demotions.py

echo "== warm-start smoke (persistent compile cache + shape manifest) =="
# two subprocesses share a temp cache dir: the second must load from
# disk (hits > 0) and perform ZERO fresh XLA compiles
JAX_PLATFORMS=cpu python tools/warmstart_smoke.py

echo "== telemetry smoke (event stream + prom export + schema gate) =="
# a tiny fit must produce an event stream, a Prometheus textfile whose
# counters reconcile exactly with dispatch_stats()/fault_events(), and
# the metric/event schema must match the checked-in telemetry_schema.json
JAX_PLATFORMS=cpu python tools/telemetry_smoke.py

echo "ci_check: OK"
