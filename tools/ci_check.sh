#!/usr/bin/env bash
# One-command static gate: tracelint + manifest freshness + import
# health, plus the fast resilience/warm-start/telemetry/multihost
# smokes and the cluster crash acceptance (~3 min total) — run before
# pushing; CI runs the same line.
#
#   ./tools/ci_check.sh
#
# Exit non-zero on: new (non-baselined) tracelint findings, a stale
# checked-in unjittable manifest, or any paddle_tpu submodule that
# fails to import on CPU.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tracelint (jit-safety static analysis + manifest freshness) =="
# one invocation does both: reports/gates on new findings AND fails if
# the checked-in unjittable manifest is stale
JAX_PLATFORMS=cpu python -m tools.tracelint paddle_tpu --check-manifest

echo "== threadlint (static concurrency analysis + baseline freshness) =="
# gates on new concurrency findings AND (--fail-stale) on fixed debt
# still sitting in the checked-in baseline — both directions must stay
# fresh, exactly like the tracelint/manifest pair above
JAX_PLATFORMS=cpu python -m tools.threadlint paddle_tpu --fail-stale

echo "== import health (every submodule imports on CPU) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_import_health.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== resilience (fast fault-injection paths) =="
# everything but the subprocess crash-consistency test (that one spawns
# a fresh interpreter and SIGKILLs it mid-save; tier-1 runs it)
JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
    -k "not kill9_mid_async" \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== runtime-learned demotions vs the static unjittable manifest =="
# a demotion the dispatch layer learns at runtime is a tracelint rule
# gap — fails with the op names and a manifest-regenerate hint
JAX_PLATFORMS=cpu python tools/check_runtime_demotions.py

echo "== warm-start smoke (persistent compile cache + shape manifest) =="
# two subprocesses share a temp cache dir: the second must load from
# disk (hits > 0) and perform ZERO fresh XLA compiles
JAX_PLATFORMS=cpu python tools/warmstart_smoke.py

echo "== fusion smoke (trace-fusion warm-start round trip) =="
# two subprocesses share a compile cache + shape manifest: the second
# must AOT-replay the recorded fused traces (fused-cache misses == 0)
# with ZERO fresh XLA compiles and disk hits > 0
JAX_PLATFORMS=cpu python tools/fusion_smoke.py

echo "== multihost smoke (coordination store + quorum + merge) =="
# 2-process CPU cluster over a tmpdir store: heartbeat + rendezvous
# round trip, host-0 merged prom/fault-log carrying both rank labels,
# and a quorum-stall watchdog that must exit NONZERO once every rank
# goes silent
JAX_PLATFORMS=cpu python tools/multihost_smoke.py

echo "== cluster crash-consistency acceptance (3-rank SIGKILL) =="
# the PR-6 acceptance proof (slow-marked out of the tier-1 budget run):
# rank 1 SIGKILLed mid-async-save; survivors must not quorum-stall,
# must restore the SAME common step, and the host-0 merge must carry
# all three ranks' labels incl. the dying rank's final fault (~50s)
JAX_PLATFORMS=cpu python -m pytest \
    "tests/test_cluster_resilience.py::test_cluster_kill9_mid_async_save_survivors_agree" \
    -q -m slow -p no:cacheprovider -p no:xdist -p no:randomly

echo "== telemetry smoke (event stream + prom export + schema gate) =="
# a tiny fit must produce an event stream, a Prometheus textfile whose
# counters reconcile exactly with dispatch_stats()/fault_events(), and
# the metric/event schema must match the checked-in telemetry_schema.json
JAX_PLATFORMS=cpu python tools/telemetry_smoke.py

echo "ci_check: OK"
