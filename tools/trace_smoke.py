#!/usr/bin/env python
"""Span-tracing smoke (tools/ci_check.sh).

Two stages, both over fresh subprocesses the way an operator would run
them:

**Single-process fit.** A tiny `Model.fit` under ``PADDLE_TPU_TRACE``
(plus TelemetryCallback + ResilienceCallback) must produce a trace
file that

* validates as Chrome Trace Event Format JSON (loads in Perfetto);
* carries spans from every instrumented storey — dispatch (compile +
  sampled runs), fusion flushes (tagged with the PR-11 reason+site),
  step/data/compute phases, checkpoint saves;
* RECONCILES with the metrics: per-phase span sums must agree with
  ``dispatch_stats()`` / the telemetry histograms
  (`tracing.reconcile_with_metrics`), asserted inside the child where
  the authoritative snapshots live.

**2-process cluster fit.** Two ranks run `Model.fit` with
ResilienceCallback in cluster mode over a tmpdir store, tracing into
the shared ``<store>/traces`` dir. The host-0 merge
(`telemetry.merge_cluster`, driven by the leader's train end) must
produce ONE merged cluster timeline carrying spans from BOTH ranks —
dispatch, fusion flush (reason+site), checkpoint, and coordination
lanes — which is the acceptance criterion for the span-tracing PR.

Usage: python tools/trace_smoke.py            (run both stages)
       python tools/trace_smoke.py --child    (internal: single fit)
       python tools/trace_smoke.py --rank N   (internal: cluster rank)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny_fit(ckpt_dir, cluster=False):
    """The shared workload: eager warm-up ops (dispatch compile + run
    spans), a fusion window (flush spans with reason+site), then a
    small fit with telemetry + resilience callbacks (step/data/
    checkpoint/coord spans)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.core import dispatch, fusion

    dispatch.set_warmup_count(1)
    dispatch.set_op_sample_every(1)
    paddle.seed(0)
    rng = np.random.RandomState(0)
    t = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
    for _ in range(4):
        paddle.tanh(paddle.matmul(t, t)).sum()
    fusion.set_fusion(True)
    for _ in range(3):
        float(paddle.tanh(paddle.matmul(t, t)).sum())
    fusion.set_fusion(False)
    x = rng.rand(64, 4).astype(np.float32)
    y = (x @ rng.rand(4, 1).astype(np.float32)).astype(np.float32)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    cbs = [paddle.callbacks.TelemetryCallback(export_every=3),
           paddle.callbacks.ResilienceCallback(
               ckpt_dir, save_interval=4, async_save=False,
               rendezvous_timeout=20.0 if cluster else 5.0)]
    model.fit([x, y], epochs=2, batch_size=16, verbose=0, callbacks=cbs)
    return 8  # train steps


def _child():
    sys.path.insert(0, REPO)
    from paddle_tpu.runtime import tracing

    steps = _tiny_fit(os.path.join(os.environ["TRACE_SMOKE_DIR"], "ckpt"))
    tracing.flush()
    ok, report = tracing.reconcile_with_metrics()
    print(json.dumps({
        "trace_path": tracing.trace_path(),
        "steps": steps,
        "reconcile_ok": ok,
        "reconcile": report,
    }))
    if not ok:
        raise SystemExit(f"trace_smoke: span/metric reconciliation failed: "
                         f"{report}")


def _cluster_rank():
    sys.path.insert(0, REPO)
    from paddle_tpu.distributed import coordination
    from paddle_tpu.runtime import tracing

    ctx = coordination.cluster_context()
    assert ctx is not None, "cluster env not set"
    ckpt = os.path.join(os.environ["TRACE_SMOKE_DIR"], f"ckpt_{ctx.rank}")
    _tiny_fit(ckpt, cluster=True)
    tracing.flush()
    print(f"RANK_OK rank={ctx.rank} trace={tracing.trace_path()}",
          flush=True)


def _required_cats(events, where):
    cats = {e.get("cat") for e in events if e.get("ph") == "X"}
    for need in ("dispatch", "fusion", "step", "data", "checkpoint"):
        if need not in cats:
            raise SystemExit(
                f"trace_smoke: no {need!r} spans in {where} (cats: "
                f"{sorted(c for c in cats if c)})")
    flushes = [e for e in events
               if e.get("cat") == "fusion" and e.get("name") == "flush"]
    if not flushes:
        raise SystemExit(f"trace_smoke: no fusion flush spans in {where}")
    for f in flushes:
        args = f.get("args") or {}
        if "reason" not in args or "site" not in args:
            raise SystemExit(
                f"trace_smoke: flush span missing reason/site tags: {f}")


def run_single():
    tmp = tempfile.mkdtemp(prefix="trace_smoke_")
    trace_dir = os.path.join(tmp, "trace")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PADDLE_TPU_TRACE": trace_dir,
                "PADDLE_TPU_TELEMETRY_DIR": os.path.join(tmp, "telemetry"),
                "PADDLE_TPU_TELEMETRY": "1", "TRACE_SMOKE_DIR": tmp})
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    if p.returncode != 0:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(f"trace_smoke: child failed rc={p.returncode}")
    truth = json.loads(p.stdout.strip().splitlines()[-1])

    sys.path.insert(0, REPO)
    from paddle_tpu.runtime import tracing

    events = tracing.validate_trace(truth["trace_path"])
    _required_cats(events, "the single-process trace")
    n_steps = sum(1 for e in events
                  if e.get("cat") == "step" and e.get("name") == "train_step")
    if n_steps != truth["steps"]:
        raise SystemExit(f"trace_smoke: {n_steps} train_step spans for "
                         f"{truth['steps']} steps")
    if not truth["reconcile_ok"]:
        raise SystemExit("trace_smoke: child reported reconciliation "
                         f"failure: {truth['reconcile']}")
    checked = [k for k, v in truth["reconcile"].items() if not v["skipped"]]
    for need in ("dispatch_run", "step", "data_wait", "checkpoint_save"):
        if need not in checked:
            raise SystemExit(
                f"trace_smoke: reconciliation never exercised {need!r} "
                f"(checked: {checked}) — nothing real reconciled")
    print(f"trace_smoke: single-process OK ({len(events)} events, "
          f"{n_steps} step spans, reconciled: {', '.join(checked)})")


def run_cluster():
    tmp = tempfile.mkdtemp(prefix="trace_smoke_cluster_")
    store = os.path.join(tmp, "store")
    trace_dir = os.path.join(store, "traces")
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PADDLE_TPU_TRACE": trace_dir,
            "PADDLE_TPU_CLUSTER_DIR": store,
            "PADDLE_TPU_CLUSTER_RANK": str(rank),
            "PADDLE_TPU_CLUSTER_WORLD": "2",
            "PADDLE_TPU_TELEMETRY": "1",
            "TRACE_SMOKE_DIR": tmp,
            # coordination needs no collectives; one device keeps the
            # children light (the PR-6 budget lesson)
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--rank", str(rank)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            p.kill()
            raise SystemExit(f"trace_smoke: cluster rank {rank} timed out")
        outs.append((out, err))
        if p.returncode != 0:
            print(out)
            print(err, file=sys.stderr)
            raise SystemExit(
                f"trace_smoke: cluster rank {rank} failed rc={p.returncode}")

    sys.path.insert(0, REPO)
    from paddle_tpu.runtime import tracing

    # per-rank trace files exist (distinct, pid-keyed names)
    files = [fn for fn in sorted(os.listdir(trace_dir))
             if fn.startswith(tracing.TRACE_BASENAME_PREFIX)]
    if len(files) < 2:
        raise SystemExit(f"trace_smoke: expected 2 per-rank trace files, "
                         f"found {files}")
    # ONE merged cluster timeline, produced by the leader's train-end
    # merge, carrying both ranks' spans
    merged = os.path.join(store, "merged", "cluster_trace.json")
    if not os.path.exists(merged):
        raise SystemExit("trace_smoke: no merged cluster timeline at "
                         f"{merged}")
    events = tracing.read_trace(merged, strict=True)
    _required_cats(events, "the merged cluster timeline")
    for need_cat in ("coord",):
        if not any(e.get("cat") == need_cat for e in events):
            raise SystemExit(
                f"trace_smoke: merged timeline has no {need_cat!r} spans")
    by_rank = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        by_rank.setdefault(e.get("pid"), set()).add(e.get("cat"))
    for rank in (0, 1):
        if rank not in by_rank:
            raise SystemExit(
                f"trace_smoke: merged timeline carries no spans from rank "
                f"{rank} (pids: {sorted(by_rank)})")
        for need in ("dispatch", "fusion", "checkpoint", "coord"):
            if need not in by_rank[rank]:
                raise SystemExit(
                    f"trace_smoke: rank {rank} contributed no {need!r} "
                    f"spans to the merged timeline ({sorted(by_rank[rank])})")
    print(f"trace_smoke: cluster OK ({len(files)} rank files, "
          f"{len(events)} merged events, ranks {sorted(by_rank)})")


if __name__ == "__main__":
    args = sys.argv[1:]
    if args[:1] == ["--child"]:
        _child()
    elif args[:1] == ["--rank"]:
        _cluster_rank()
    else:
        run_single()
        run_cluster()
        print("trace_smoke: OK")
