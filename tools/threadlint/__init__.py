"""threadlint — static concurrency/race analysis for the paddle_tpu
threaded runtime.

The runtime spine is concurrent on the host side: elastic watchdog
threads, background cluster merges, orbax async checkpoint commits,
data-pipeline workers, atexit manifest saves. The dominant live-bug
class across the PR-6 review rounds was unguarded shared state,
check-then-act races, and background-vs-synchronous path collisions.
threadlint moves that class to lint time: a stdlib-`ast` pass
(on the shared `tools/staticlib/` analysis core tracelint also runs
on) discovers every thread entry point itself, walks the module-local
call graph, models held locks, and classifies hazards per rules.py:

  CL001 unguarded-shared-mutation   CL005 non-atomic-shared-write
  CL002 lock-order-inversion        CL006 shutdown-ordering
  CL003 blocking-under-lock         CL007 check-then-act
  CL004 thread-before-fork

Usage:
    python -m tools.threadlint paddle_tpu
    python -m tools.threadlint paddle_tpu -v
    python -m tools.threadlint paddle_tpu --json /tmp/threadlint.json
    python -m tools.threadlint paddle_tpu --write-baseline

CI gates via tools/ci_check.sh exactly like tracelint: exit 0 on the
baselined tree, nonzero on any new finding (and, with --fail-stale,
on fixed-but-unpruned baseline debt). Reviewed-safe sites carry inline
`# threadlint: ok[rule] reason` waivers. See docs/THREADLINT.md.
"""
from ..staticlib.baseline import load_baseline, partition  # noqa: F401
from .analyzer import Finding, analyze_file, analyze_paths  # noqa: F401
from .rules import RULES  # noqa: F401

__all__ = ["Finding", "analyze_file", "analyze_paths", "load_baseline",
           "partition", "RULES", "main"]

__version__ = "1.0"


def main(argv=None):
    from .__main__ import main as _main
    return _main(argv)
