"""CLI: python -m tools.threadlint <roots...> [options].

Exit codes: 0 clean (or baselined-only), 1 new findings, parse errors,
or (with --fail-stale) stale baseline entries, 2 usage error.
"""
from __future__ import annotations

import argparse
import os
import sys

from ..staticlib.baseline import load_baseline, partition, write_baseline
from ..staticlib.report import human_report, json_report, write_json
from .analyzer import analyze_paths
from .rules import RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

_COMMENT = ("threadlint suppression baseline — regenerate with "
            "`python -m tools.threadlint paddle_tpu "
            "--write-baseline` after reviewing that every new "
            "finding is intended debt, not a regression.")


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m tools.threadlint",
        description="static concurrency/race analyzer for the "
                    "paddle_tpu threaded runtime "
                    "(see docs/THREADLINT.md)")
    p.add_argument("roots", nargs="+",
                   help="package dirs or files to analyze (paddle_tpu)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help=f"baseline file (default {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding as new (ignore baseline)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "and exit 0")
    p.add_argument("--json", metavar="PATH",
                   help="also write the machine-readable report here")
    p.add_argument("--fail-stale", action="store_true",
                   help="exit nonzero on stale baseline entries too "
                        "(CI freshness gate: fixed debt must be pruned "
                        "with --write-baseline)")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="itemize baselined/waived/info findings too")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    for r in args.roots:
        if not os.path.exists(r):
            print(f"threadlint: no such path: {r}", file=sys.stderr)
            return 2

    findings, errors = analyze_paths(args.roots)

    if args.write_baseline:
        if errors:
            # a baseline written while files are unparseable silently
            # drops their debt; the next clean run would gate on it
            for p, m in errors:
                print(f"{p}: PARSE ERROR — {m}", file=sys.stderr)
            print("threadlint: refusing to write a baseline while files "
                  "fail to parse", file=sys.stderr)
            return 1
        counts = write_baseline(args.baseline, findings, _COMMENT)
        print(f"threadlint: baseline written to {args.baseline} "
              f"({sum(counts.values())} findings, "
              f"{len(counts)} distinct fingerprints)")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, baselined, suppressed, info, stale = partition(findings, baseline)

    print(human_report(new, baselined, suppressed, info, stale, errors,
                       tool="threadlint", rules=RULES,
                       verbose=args.verbose))
    if args.json:
        write_json(args.json, json_report(new, baselined, suppressed, info,
                                          stale, errors, rules=RULES))
    if new or errors:
        return 1
    if args.fail_stale and stale:
        print("threadlint: stale baseline entries above — the debt was "
              "fixed; shrink the baseline with --write-baseline",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
