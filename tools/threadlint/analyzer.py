"""Static concurrency analysis over the paddle_tpu threaded runtime.

The runtime is genuinely concurrent on the host side: elastic watchdog
threads, background cluster merges, async checkpoint commits, data
pipeline workers, atexit manifest saves. The live-bug class the PR-6
review rounds kept finding is exactly unguarded shared state,
check-then-act races, and background-vs-synchronous path collisions —
so, like tracelint did for trace hygiene, this pass moves those
discoveries to lint time.

**Thread-entry discovery** is automatic: ``threading.Thread(target=f)``
(and ``Timer``, ``multiprocessing.Process``), ``executor.submit(f)``,
``_thread.start_new_thread(f)``, plus registered ``atexit`` and
``signal`` handlers — each resolved target is the root of a *context*.
Everything reachable from an entry (module-local call graph,
tools/staticlib/callgraph.py) runs on that context; everything
reachable from functions nothing local calls (public API) runs on the
implicit *sync* context.

**Shared state** is a module global, a class/instance attribute
(``self.x`` / ``cls.x``), or a closure cell shared between a function
and a nested thread target, that is either

  * accessed from two or more distinct contexts (at least one of them
    a thread-entry context), or
  * accessed under a held lock somewhere (the guard itself is the
    author's declaration that the state is shared).

**Lock modeling**: ``threading.Lock/RLock/Condition/Semaphore`` objects
bound to module globals, class attributes, or function locals are
tracked through ``with`` blocks and ``.acquire()``/``.release()``
pairs; a private function whose every local call site holds lock L is
treated as executing under L (caller-held fixpoint), so a helper
factored out of a locked region does not false-positive.

The pass is file-local and approximate, exactly like tracelint: it
never imports the code it inspects, and residual false positives are
absorbed by reviewed inline waivers (`# threadlint: ok[rule]`) and the
checked fingerprint baseline rather than by weakening detection.
"""
from __future__ import annotations

import ast
import os
import re

from ..staticlib import findings as _findings
from ..staticlib.astnav import (
    ScopeIndex, dotted, iter_py_files as _iter_py_files,
    relpath as _relpath, runtime_first_line,
)
from ..staticlib.callgraph import CallGraph
from ..staticlib.waivers import suppressed as _waiver_suppressed
from .rules import RULES

__all__ = ["Finding", "analyze_file", "analyze_paths", "iter_py_files"]

SKIP_DIRS = {"__pycache__", ".git", "libs", "include"}
TOOL = "threadlint"

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
REENTRANT_FACTORIES = {"RLock"}
# list/dict/set-style in-place mutation (threading.Event.set / queue.put
# are deliberately absent: those primitives are internally synchronized)
MUTATING_METHODS = {"append", "extend", "insert", "remove", "pop", "clear",
                    "update", "setdefault", "add", "discard", "popitem",
                    "sort", "reverse"}
SPAWN_CALLS = {
    ("subprocess", "Popen"), ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("os", "fork"), ("os", "forkpty"), ("os", "posix_spawn"),
    ("os", "spawnv"), ("os", "spawnl"), ("os", "system"),
    ("multiprocessing", "Process"),
}
BLOCKING_NET_HEADS = {"requests", "urllib", "socket"}
FILE_IO_METHODS = {"read", "readline", "readlines", "write", "writelines",
                   "flush"}
QUEUEISH_NAME = re.compile(r"(^|_)(q|queue|jobs?|tasks?|work|in_q|out_q)"
                           r"(_|$)", re.IGNORECASE)
SHARED_PATH_HINT = re.compile(
    r"store|heartbeat|telemetr|merged|cluster|ckpt|checkpoint|manifest"
    r"|\.prom|events|baseline", re.IGNORECASE)
DUNDER_INIT = {"__init__", "__new__", "__del__", "__init_subclass__",
               "__set_name__"}


# ---------------------------------------------------------------------------
# model

class Finding(_findings.Finding):
    """threadlint finding: the shared record bound to the CL catalog."""

    RULES = RULES


class Entry:
    """One discovered thread-entry point."""

    __slots__ = ("kind", "target", "node", "daemon", "label")

    def __init__(self, kind, target, node, daemon=False):
        self.kind = kind        # thread|timer|submit|atexit|signal|...
        self.target = target    # resolved qualname or None
        self.node = node        # the registering/constructing Call node
        self.daemon = daemon
        self.label = f"{kind}:{target or '?'}"


def _const_str(node):
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


def _kwarg(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _has_timeout(call):
    if _kwarg(call, "timeout") is not None:
        return True
    return bool(call.args)


# ---------------------------------------------------------------------------
# per-module analysis

class ModuleConcurrencyAnalysis:
    def __init__(self, path, root_parent):
        self.path = path
        self.relpath = _relpath(path, root_parent)
        with open(path, "r", encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=path)
        self.scopes = ScopeIndex(self.tree)
        self.graph = CallGraph(self.tree, self.scopes)
        self.findings = []

        self._collect_locks()
        self._collect_function_locals()
        self._discover_entries()
        self._compute_contexts()
        self._walk_held()
        self._effective_held_fixpoint()
        self._collect_accesses()

    # -- reporting ----------------------------------------------------------
    def report(self, rule, node, func_qual, message, symbol, confidence,
               context):
        fnode = self.graph.functions.get(func_qual)
        if fnode is not None:
            func_name = getattr(fnode, "name", "<lambda>")
            func_line = runtime_first_line(fnode)
        else:
            func_name, func_line = "<module>", 1
        self.findings.append(Finding(
            rule=rule, path=self.relpath, line=node.lineno,
            col=node.col_offset, func=func_qual or "<module>",
            func_name=func_name, func_line=func_line, message=message,
            symbol=symbol, severity=RULES[rule].severity,
            confidence=confidence, context=context))

    # -- locks --------------------------------------------------------------
    def _is_lock_factory(self, call):
        d = dotted(call.func) if isinstance(call, ast.Call) else None
        return d is not None and d[-1] in LOCK_FACTORIES

    def _collect_locks(self):
        """Lock objects bound to module globals, class attributes, or
        function locals. Also records which are reentrant."""
        self.lock_globals = {}      # name -> lock id
        self.lock_attrs = {}        # (class, attr) -> lock id
        self.lock_attr_names = {}   # attr -> set of classes defining it
        self.local_locks = {}       # (func qual, name) -> lock id
        self.reentrant = set()      # lock ids from RLock()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign) or \
                    not self._is_lock_factory(node.value):
                continue
            d = dotted(node.value.func)
            rlock = d[-1] in REENTRANT_FACTORIES
            for t in node.targets:
                lid = None
                if isinstance(t, ast.Name):
                    chain = self.scopes.scope_chain(node)
                    fns = [s for s in chain if isinstance(s, _FUNC_NODES)]
                    cls = self.scopes.enclosing_class(node)
                    if fns and not (cls is not None
                                    and chain and chain[0] is cls):
                        # a function-local lock (dataloader's `cond`)
                        q = self.scopes.qualname(fns[0])
                        lid = f"l:{q}.{t.id}"
                        self.local_locks[(q, t.id)] = lid
                    elif cls is not None and chain and chain[0] is cls:
                        # class-body assignment: a class-level lock
                        lid = f"a:{cls.name}.{t.id}"
                        self.lock_attrs[(cls.name, t.id)] = lid
                        self.lock_attr_names.setdefault(
                            t.id, set()).add(cls.name)
                    else:
                        lid = f"g:{t.id}"
                        self.lock_globals[t.id] = lid
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in ("self", "cls"):
                    cls = self.scopes.enclosing_class(node)
                    cname = cls.name if cls is not None else "?"
                    lid = f"a:{cname}.{t.attr}"
                    self.lock_attrs[(cname, t.attr)] = lid
                    self.lock_attr_names.setdefault(t.attr, set()).add(cname)
                if lid and rlock:
                    self.reentrant.add(lid)

    def _resolve_lock(self, expr, from_node):
        """Lock id for an expression used in `with`/`.acquire()`, or
        None when it isn't a recognizable lock."""
        if isinstance(expr, ast.Name):
            fid = self._enclosing_fn_quals(from_node)
            for q in fid:
                lid = self.local_locks.get((q, expr.id))
                if lid:
                    return lid
            return self.lock_globals.get(expr.id)
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            classes = self.lock_attr_names.get(attr)
            if not classes:
                return None
            if isinstance(expr.value, ast.Name):
                recv = expr.value.id
                if recv in ("self", "cls"):
                    cls = self.scopes.enclosing_class(from_node)
                    if cls is not None and cls.name in classes:
                        return f"a:{cls.name}.{attr}"
                elif recv in classes:
                    return f"a:{recv}.{attr}"
            if len(classes) == 1:
                return f"a:{next(iter(classes))}.{attr}"
            return f"a:*.{attr}"
        return None

    def _enclosing_fn_quals(self, node):
        return [self.scopes.qualname(s)
                for s in self.scopes.scope_chain(node)
                if isinstance(s, _FUNC_NODES)]

    # -- function locals ----------------------------------------------------
    def _collect_function_locals(self):
        self.fn_locals = {}     # qual -> set of local names
        self.fn_globals = {}    # qual -> names declared `global`
        self.fn_nonlocals = {}  # qual -> names declared `nonlocal`
        for qual, fnode in self.graph.functions.items():
            loc, gl, nl = set(), set(), set()
            if not isinstance(fnode, ast.Lambda):
                for a in (list(fnode.args.posonlyargs) +
                          list(fnode.args.args) + list(fnode.args.kwonlyargs)):
                    loc.add(a.arg)
                if fnode.args.vararg:
                    loc.add(fnode.args.vararg.arg)
                if fnode.args.kwarg:
                    loc.add(fnode.args.kwarg.arg)
            for n in CallGraph.body_nodes(fnode):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    loc.add(n.id)
                elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    loc.add(n.name)
                elif isinstance(n, ast.comprehension):
                    for t in ast.walk(n.target):
                        if isinstance(t, ast.Name):
                            loc.add(t.id)
                elif isinstance(n, ast.Global):
                    gl.update(n.names)
                elif isinstance(n, ast.Nonlocal):
                    nl.update(n.names)
            loc -= gl
            loc -= nl
            self.fn_locals[qual] = loc
            self.fn_globals[qual] = gl
            self.fn_nonlocals[qual] = nl
        # mutable module globals: module-level Assign targets + anything
        # declared `global` somewhere (imports/classes/defs excluded)
        self.module_globals = set()
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.module_globals.add(t.id)
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)) and \
                    isinstance(stmt.target, ast.Name):
                self.module_globals.add(stmt.target.id)
        for gl in self.fn_globals.values():
            self.module_globals.update(gl)

    # -- thread-entry discovery ---------------------------------------------
    def _discover_entries(self):
        self.entries = []
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            target = kind = None
            if d and d[-1] in ("Thread", "Process") and \
                    (len(d) == 1 or d[0] in ("threading",
                                             "multiprocessing")):
                kind = "thread"
                target = _kwarg(n, "target") or (
                    n.args[1] if len(n.args) > 1 else None)
            elif d and d[-1] == "Timer" and \
                    (len(d) == 1 or d[0] == "threading"):
                kind = "timer"
                target = _kwarg(n, "function") or (
                    n.args[1] if len(n.args) > 1 else None)
            elif isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "submit" and n.args:
                kind = "submit"
                target = n.args[0]
            elif d == ("atexit", "register") and n.args:
                kind = "atexit"
                target = n.args[0]
            elif d == ("signal", "signal") and len(n.args) > 1:
                kind = "signal"
                target = n.args[1]
            elif d and d[-1] == "start_new_thread" and n.args:
                kind = "thread"
                target = n.args[0]
            if kind is None or target is None:
                continue
            daemon_kw = _kwarg(n, "daemon")
            daemon = isinstance(daemon_kw, ast.Constant) and \
                daemon_kw.value is True
            qual = self.graph.resolve_target(target, n)
            self.entries.append(Entry(kind, qual, n, daemon))

    # -- contexts -----------------------------------------------------------
    def _compute_contexts(self):
        """contexts[qual] = set of context labels the function can run
        on. Thread contexts come from entry reachability; the implicit
        "sync" context flows from functions nothing local calls (the
        public API) that are not themselves entry targets."""
        entry_targets = {e.target for e in self.entries if e.target}
        self.entry_reach = {}
        for e in self.entries:
            if e.target:
                self.entry_reach.setdefault(
                    e.label, self.graph.reachable([e.target]))
        sync_seeds = [q for q in self.graph.functions
                      if not self.graph.callers(q)
                      and q not in entry_targets]
        self.sync_reach = self.graph.reachable(sync_seeds)
        self.contexts = {}
        for q in self.graph.functions:
            ctxs = {label for label, reach in self.entry_reach.items()
                    if q in reach}
            if q in self.sync_reach:
                ctxs.add("sync")
            self.contexts[q] = ctxs

    # -- held-lock walk -----------------------------------------------------
    def _walk_held(self):
        """held[qual] = {id(node): (held lock tuple)} for every node of
        the function's own body, plus direct lock-order edges."""
        self.held = {}
        self.order_edges = []   # (A, B, site node, func qual)
        self.acquires = {}      # qual -> set of lock ids acquired directly
        for qual, fnode in self.graph.functions.items():
            table = {}
            acq = set()

            def mark(node, held):
                if id(node) in table:
                    return
                table[id(node)] = held
                for ch in ast.iter_child_nodes(node):
                    if isinstance(ch, _FUNC_NODES):
                        table[id(ch)] = held
                        continue
                    mark(ch, held)

            def enter(lid, held, site):
                if lid in held and lid not in self.reentrant:
                    self.report(
                        "lock-order-inversion", site, qual,
                        f"lock `{lid}` re-acquired while already held — "
                        "a non-reentrant Lock self-deadlocks here",
                        f"reacquire:{lid}", "definite", "lock-order")
                for a in held:
                    if a != lid:
                        self.order_edges.append((a, lid, site, qual))
                acq.add(lid)

            def do_stmts(body, held):
                held = list(held)
                for st in body:
                    do_stmt(st, held)

            def do_stmt(st, held):
                hf = tuple(held)
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    inner = list(held)
                    for item in st.items:
                        mark(item.context_expr, hf)
                        if item.optional_vars is not None:
                            mark(item.optional_vars, hf)
                        lid = self._resolve_lock(item.context_expr, st)
                        if lid is not None:
                            enter(lid, inner, st)
                            inner.append(lid)
                    table[id(st)] = hf
                    do_stmts(st.body, inner)
                    return
                if isinstance(st, (ast.If, ast.While)):
                    mark(st.test, hf)
                    table[id(st)] = hf
                    do_stmts(st.body, held)
                    do_stmts(st.orelse, held)
                    return
                if isinstance(st, (ast.For, ast.AsyncFor)):
                    mark(st.target, hf)
                    mark(st.iter, hf)
                    table[id(st)] = hf
                    do_stmts(st.body, held)
                    do_stmts(st.orelse, held)
                    return
                if isinstance(st, ast.Try):
                    table[id(st)] = hf
                    do_stmts(st.body, held)
                    for h in st.handlers:
                        table[id(h)] = hf
                        if h.type is not None:
                            mark(h.type, hf)
                        do_stmts(h.body, held)
                    do_stmts(st.orelse, held)
                    do_stmts(st.finalbody, held)
                    return
                # manual acquire()/release() at statement granularity:
                # held for the REMAINDER of this block
                if isinstance(st, ast.Expr) and \
                        isinstance(st.value, ast.Call) and \
                        isinstance(st.value.func, ast.Attribute):
                    call = st.value
                    if call.func.attr == "acquire":
                        lid = self._resolve_lock(call.func.value, st)
                        if lid is not None:
                            mark(st, hf)
                            enter(lid, held, st)
                            held.append(lid)
                            return
                    elif call.func.attr == "release":
                        lid = self._resolve_lock(call.func.value, st)
                        mark(st, hf)
                        if lid is not None and lid in held:
                            held.remove(lid)
                        return
                mark(st, hf)

            if isinstance(fnode, ast.Lambda):
                mark(fnode.body, ())
            else:
                do_stmts(fnode.body, ())
            self.held[qual] = table
            self.acquires[qual] = acq

    def _effective_held_fixpoint(self):
        """Locks a function provably ALWAYS runs under: the intersection
        over its local call sites of (site-held ∪ caller's effective
        held). Only private-named helpers inherit — a public function
        is callable from outside the module with nothing held."""
        self.eff = {q: frozenset() for q in self.graph.functions}
        entry_targets = {e.target for e in self.entries if e.target}

        def inheritable(q):
            last = q.rsplit(".", 1)[-1]
            return (last.startswith("_") and last not in DUNDER_INIT
                    and q not in entry_targets
                    and self.graph.callers(q))

        for _ in range(4):
            changed = False
            for q in self.graph.functions:
                if not inheritable(q):
                    continue
                sets = []
                for caller, call_node in self.graph.callers(q):
                    site = self.held.get(caller, {}).get(
                        id(call_node), ())
                    sets.append(frozenset(site) | self.eff.get(
                        caller, frozenset()))
                new = frozenset.intersection(*sets) if sets else frozenset()
                if new != self.eff[q]:
                    self.eff[q] = new
                    changed = True
            if not changed:
                break

    def _held_at(self, qual, node):
        return frozenset(self.held.get(qual, {}).get(id(node), ())) | \
            self.eff.get(qual, frozenset())

    # -- shared-state access table -------------------------------------------
    def _owner_of_free_name(self, qual, name):
        """The qualname of the nearest enclosing function that binds
        `name` as a local (closure cell owner), or None."""
        fnode = self.graph.functions.get(qual)
        if fnode is None:
            return None
        for s in self.scopes.scope_chain(fnode):
            if isinstance(s, _FUNC_NODES):
                oq = self.scopes.qualname(s)
                if name in self.fn_locals.get(oq, ()):
                    return oq
        return None

    def _var_for_name(self, qual, name):
        """Shared-var key for a bare name access in `qual`, or None for
        plain locals."""
        if name in self.fn_locals.get(qual, ()):
            # the OWNER's accesses to a local that a nested function
            # captures are the sync side of a closure-shared cell
            if (qual, name) in self.escaping:
                return ("c", qual, name)
            return None
        if name in self.fn_globals.get(qual, ()) or (
                name in self.module_globals):
            return ("g", name)
        owner = self._owner_of_free_name(qual, name)
        if owner is not None:
            return ("c", owner, name)
        return None

    def _class_for_receiver(self, qual, recv_name):
        if recv_name in ("self", "cls"):
            fnode = self.graph.functions.get(qual)
            cls = self.scopes.enclosing_class(fnode) if fnode is not None \
                else None
            return cls.name if cls is not None else None
        if recv_name in self.graph.classes:
            return recv_name
        return None

    def _collect_accesses(self):
        """accesses[var] = {"reads": [(qual, node, held)],
                            "writes": [(qual, node, held)]}"""
        # escape pre-pass: (owner qual, name) for every local some
        # nested function references free — the closure cells that can
        # be shared between a function and its thread targets
        self.escaping = set()
        for qual, fnode in self.graph.functions.items():
            for n in CallGraph.body_nodes(fnode):
                if isinstance(n, ast.Name) and \
                        n.id not in self.fn_locals.get(qual, ()) and \
                        n.id not in self.module_globals:
                    owner = self._owner_of_free_name(qual, n.id)
                    if owner is not None:
                        self.escaping.add((owner, n.id))
        self.accesses = {}

        def rec(var, kind, qual, node):
            if var is None:
                return
            slot = self.accesses.setdefault(
                var, {"reads": [], "writes": []})
            slot[kind].append((qual, node, self._held_at(qual, node)))

        for qual, fnode in self.graph.functions.items():
            for n in CallGraph.body_nodes(fnode):
                if isinstance(n, ast.Name):
                    if isinstance(n.ctx, ast.Store):
                        # a bare-name store is a shared mutation only
                        # for declared globals/nonlocals (otherwise it
                        # just binds a local)
                        if n.id in self.fn_globals.get(qual, ()):
                            rec(("g", n.id), "writes", qual, n)
                        elif n.id in self.fn_nonlocals.get(qual, ()):
                            owner = self._owner_of_free_name(qual, n.id)
                            if owner:
                                rec(("c", owner, n.id), "writes", qual, n)
                    elif isinstance(n.ctx, ast.Load):
                        rec(self._var_for_name(qual, n.id), "reads",
                            qual, n)
                elif isinstance(n, ast.Attribute) and \
                        isinstance(n.value, ast.Name):
                    cname = self._class_for_receiver(qual, n.value.id)
                    if cname is not None:
                        kind = ("writes"
                                if isinstance(n.ctx, (ast.Store, ast.Del))
                                else "reads")
                        rec(("a", cname, n.attr), kind, qual, n)
                elif isinstance(n, ast.Subscript):
                    if not isinstance(n.ctx, (ast.Store, ast.Del)):
                        continue
                    # container-element store: the ROOT is mutated
                    root = n.value
                    while isinstance(root, (ast.Attribute, ast.Subscript)):
                        if isinstance(root, ast.Attribute) and \
                                isinstance(root.value, ast.Name):
                            cname = self._class_for_receiver(
                                qual, root.value.id)
                            if cname is not None:
                                rec(("a", cname, root.attr), "writes",
                                    qual, n)
                                root = None
                                break
                        root = root.value
                    if isinstance(root, ast.Name):
                        rec(self._var_for_name(qual, root.id),
                            "writes", qual, n)
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in MUTATING_METHODS:
                    recv = n.func.value
                    if isinstance(recv, ast.Name):
                        rec(self._var_for_name(qual, recv.id),
                            "writes", qual, n)
                    elif isinstance(recv, ast.Attribute) and \
                            isinstance(recv.value, ast.Name):
                        cname = self._class_for_receiver(
                            qual, recv.value.id)
                        if cname is not None:
                            rec(("a", cname, recv.attr), "writes", qual, n)
                elif isinstance(n, ast.AugAssign):
                    t = n.target
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name):
                        cname = self._class_for_receiver(qual, t.value.id)
                        if cname is not None:
                            rec(("a", cname, t.attr), "writes", qual, n)
                            rec(("a", cname, t.attr), "reads", qual, n)

    # -- shared-ness --------------------------------------------------------
    def _var_name(self, var):
        if var[0] == "g":
            return var[1]
        if var[0] == "a":
            return f"{var[1]}.{var[2]}"
        return f"{var[1]}.<local {var[2]}>"

    def _var_contexts(self, var):
        slot = self.accesses[var]
        ctxs = set()
        for kind in ("reads", "writes"):
            for qual, _n, _h in slot[kind]:
                ctxs.update(self.contexts.get(qual, ()))
        return ctxs

    def _var_lock_assoc(self, var):
        slot = self.accesses[var]
        return any(h for kind in ("reads", "writes")
                   for _q, _n, h in slot[kind])

    def _shared_vars(self):
        """Vars that matter: multi-context with a thread context, or
        lock-associated. Returns {var: (contexts, lock_assoc)}."""
        out = {}
        for var, slot in self.accesses.items():
            if not slot["writes"]:
                continue
            ctxs = self._var_contexts(var)
            lock_assoc = self._var_lock_assoc(var)
            multi = len(ctxs) >= 2 and any(c != "sync" for c in ctxs)
            if multi or lock_assoc:
                out[var] = (ctxs, lock_assoc)
        return out

    def _is_init_write(self, var, qual):
        """Constructor writes happen before the object is visible to a
        second thread — never a race."""
        if var[0] != "a":
            return qual.rsplit(".", 1)[-1] in DUNDER_INIT
        last = qual.rsplit(".", 1)[-1]
        return last in DUNDER_INIT and f".{var[1]}." in f".{qual}."

    # -- rules --------------------------------------------------------------
    def run(self):
        shared = self._shared_vars()
        claimed = self._check_check_then_act(shared)     # CL007 first
        self._check_unguarded_mutation(shared, claimed)  # CL001 defers
        self._check_lock_order()                         # CL002
        self._check_blocking_under_lock()                # CL003
        self._check_thread_before_fork()                 # CL004
        self._check_nonatomic_shared_write()             # CL005
        self._check_shutdown_ordering()                  # CL006
        for f in self.findings:
            f.suppressed = _waiver_suppressed(self.lines, f.line, f.rule,
                                              TOOL, RULES)
        return self.findings

    # CL007 ------------------------------------------------------------------
    def _test_reads_var(self, test, var, qual):
        for n in ast.walk(test):
            if var[0] == "g" and isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Load) and n.id == var[1] and \
                    self._var_for_name(qual, n.id) == var:
                return True
            if var[0] == "c" and isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Load) and n.id == var[2] and \
                    self._var_for_name(qual, n.id) == var:
                return True
            if var[0] == "a" and isinstance(n, ast.Attribute) and \
                    n.attr == var[2] and isinstance(n.value, ast.Name) and \
                    self._class_for_receiver(qual, n.value.id) == var[1]:
                return True
        return False

    def _check_check_then_act(self, shared):
        claimed = set()
        for var, (ctxs, lock_assoc) in shared.items():
            writes = self.accesses[var]["writes"]
            by_fn = {}
            for qual, node, held in writes:
                if not held and not self._is_init_write(var, qual):
                    by_fn.setdefault(qual, []).append(node)
            if not by_fn:
                continue
            for qual, wnodes in by_fn.items():
                fnode = self.graph.functions[qual]
                for n in CallGraph.body_nodes(fnode):
                    if not isinstance(n, ast.If):
                        continue
                    if self._held_at(qual, n):
                        continue
                    if not self._test_reads_var(n.test, var, qual):
                        continue
                    # any unguarded write AT OR AFTER the check is the
                    # act half: lazy init writes inside the branch, a
                    # tick-style monotonicity guard writes later in the
                    # same function — both are the same race
                    acts = [w for w in wnodes if w.lineno >= n.lineno]
                    if not acts:
                        continue
                    name = self._var_name(var)
                    self.report(
                        "check-then-act", n, qual,
                        f"`{name}` is tested here and mutated at line "
                        f"{acts[0].lineno} with no lock held across the "
                        "check and the act — another thread can change "
                        "it in between (shared across: "
                        f"{', '.join(sorted(ctxs))})",
                        f"toctou:{name}",
                        "definite" if lock_assoc else "possible",
                        "check-then-act")
                    claimed.add((qual, var))
                    break  # one finding per (function, var)
        return claimed

    # CL001 ------------------------------------------------------------------
    def _check_unguarded_mutation(self, shared, claimed):
        for var, (ctxs, lock_assoc) in shared.items():
            name = self._var_name(var)
            seen_fns = set()
            for qual, node, held in self.accesses[var]["writes"]:
                if held:
                    continue
                if self._is_init_write(var, qual):
                    continue
                if (qual, var) in claimed:
                    continue
                if not lock_assoc and "sync" in self.contexts.get(
                        qual, ()) and len(
                        self.contexts.get(qual, ())) == 1 and \
                        len(ctxs - {"sync"}) == 0:
                    continue  # purely-sync var (shouldn't reach here)
                if (qual, var) in seen_fns:
                    continue  # one finding per (function, var)
                seen_fns.add((qual, var))
                if lock_assoc:
                    why = ("other accesses to it hold a lock — this "
                           "write bypasses that discipline")
                    conf = "definite"
                else:
                    why = (f"it is reachable from "
                           f"{', '.join(sorted(ctxs))} with no lock "
                           "anywhere")
                    conf = "possible"
                self.report(
                    "unguarded-shared-mutation", node, qual,
                    f"`{name}` is shared mutable state but this write "
                    f"holds no lock: {why}; guard it, or waive with "
                    f"`# threadlint: ok[CL001]` if a happens-before "
                    "edge (GIL-atomic publish, queue handoff, "
                    "single-writer contract) makes it safe",
                    f"mut:{name}", conf, "shared-state")

    # CL002 ------------------------------------------------------------------
    def _check_lock_order(self):
        # direct edges + one level through the call graph: a call made
        # while holding A into a function that acquires B
        edges = {}
        for a, b, site, qual in self.order_edges:
            edges.setdefault((a, b), (site, qual))
        acq_closure = {}

        def closure(q):
            if q in acq_closure:
                return acq_closure[q]
            acq_closure[q] = set()  # cycle guard
            out = set(self.acquires.get(q, ()))
            for _site, callee in self.graph.callees(q):
                out |= closure(callee)
            acq_closure[q] = out
            return out

        for qual in self.graph.functions:
            for call_node, callee in self.graph.callees(qual):
                held = self._held_at(qual, call_node)
                if not held:
                    continue
                for b in closure(callee):
                    for a in held:
                        if a != b:
                            edges.setdefault((a, b), (call_node, qual))
        # pairwise inversion: A->B and B->A both observed
        reported = set()
        for (a, b), (site, qual) in sorted(
                edges.items(), key=lambda kv: (kv[1][0].lineno, kv[0])):
            if (b, a) in edges and (b, a) not in reported:
                reported.add((a, b))
                self.report(
                    "lock-order-inversion", site, qual,
                    f"lock `{b}` is acquired while holding `{a}` here, "
                    f"but elsewhere `{a}` is acquired while holding "
                    f"`{b}` — two threads taking the two paths "
                    "deadlock (ABBA)",
                    f"order:{a}->{b}", "definite", "lock-order")

    # CL003 ------------------------------------------------------------------
    def _blocking_call(self, n, held, qual):
        """(label, confidence) when call `n` can block, else None."""
        d = dotted(n.func)
        if d == ("time", "sleep"):
            return "time.sleep", "definite"
        if d and d[0] == "subprocess" and d[-1] in (
                "run", "call", "check_call", "check_output"):
            return ".".join(d), "definite"
        if d and d[0] == "os" and d[-1] in ("waitpid", "system"):
            return ".".join(d), "definite"
        if d and d[0] in BLOCKING_NET_HEADS and len(d) > 1:
            return ".".join(d), "definite"
        if isinstance(n.func, ast.Attribute):
            attr = n.func.attr
            recv = n.func.value
            if attr in ("join", "communicate") and not _has_timeout(n):
                return f".{attr}", "definite"
            if attr == "wait" and not _has_timeout(n):
                # Condition.wait on the HELD condition releases it —
                # that is the idiom, not a hazard
                lid = self._resolve_lock(recv, n)
                if lid is None or lid not in held:
                    return ".wait", "definite"
                return None
            if attr == "get" and not n.args and not n.keywords:
                return ".get", "definite"
            if attr == "put" and isinstance(recv, ast.Name) and \
                    QUEUEISH_NAME.search(recv.id) and not _has_timeout(n):
                return ".put", "definite"
            if attr in FILE_IO_METHODS:
                return f".{attr}", "possible"
        if d and len(d) == 1 and d[0] == "open":
            return "open", "possible"
        if d and d[0] == "json" and d[-1] in ("dump", "load"):
            return ".".join(d), "possible"
        if d and d[0] == "os" and d[-1] == "fsync":
            return "os.fsync", "possible"
        return None

    def _check_blocking_under_lock(self):
        for qual, fnode in self.graph.functions.items():
            seen = set()
            for n in CallGraph.body_nodes(fnode):
                if not isinstance(n, ast.Call):
                    continue
                held = self._held_at(qual, n)
                if not held:
                    continue
                hit = self._blocking_call(n, held, qual)
                if hit is None:
                    continue
                label, conf = hit
                if (label,) in seen:
                    continue  # one finding per call shape per function
                seen.add((label,))
                locks = ", ".join(sorted(held))
                self.report(
                    "blocking-under-lock", n, qual,
                    f"{label} while holding `{locks}` — every thread "
                    "contending on the lock stalls for the duration; "
                    "move the blocking work outside the critical "
                    "section or waive if the serialization is the "
                    "contract",
                    f"block:{label}", conf, "blocking")

    # CL004 ------------------------------------------------------------------
    def _check_thread_before_fork(self):
        thread_ctor_lines = {}
        for e in self.entries:
            if e.kind in ("thread", "timer"):
                chain = self._enclosing_fn_quals(e.node)
                q = chain[0] if chain else "<module>"
                thread_ctor_lines.setdefault(q, []).append(e.node.lineno)
        for qual, starts in thread_ctor_lines.items():
            fnode = self.graph.functions.get(qual)
            nodes = (CallGraph.body_nodes(fnode) if fnode is not None
                     else ast.walk(self.tree))
            first = min(starts)
            for n in nodes:
                if not isinstance(n, ast.Call) or n.lineno <= first:
                    continue
                d = dotted(n.func)
                if d in SPAWN_CALLS or (
                        d and d[-1] == "fork" and d[0] == "os"):
                    self.report(
                        "thread-before-fork", n, qual,
                        f"{'.'.join(d)} after a thread was started at "
                        f"line {first} on the same path — the forked "
                        "child inherits locked locks and torn state "
                        "from threads that do not survive the fork",
                        f"spawn:{'.'.join(d)}", "possible", "fork")

    # CL005 ------------------------------------------------------------------
    def _module_has_atomic_helpers(self):
        return ("atomic_write_json" in self.src
                or "os.replace" in self.src)

    def _fn_has_atomic_pattern(self, qual):
        fnode = self.graph.functions.get(qual)
        if fnode is None:
            return False
        for n in CallGraph.body_nodes(fnode):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and ((d[0] == "os" and d[-1] in ("replace", "rename"))
                          or d[-1] == "atomic_write_json"):
                    return True
        return False

    def _check_nonatomic_shared_write(self):
        participates = self._module_has_atomic_helpers()
        for qual, fnode in self.graph.functions.items():
            for n in CallGraph.body_nodes(fnode):
                if not (isinstance(n, ast.Call) and
                        dotted(n.func) == ("open",)):
                    continue
                mode = _const_str(_kwarg(n, "mode")) or (
                    _const_str(n.args[1]) if len(n.args) > 1 else None)
                if mode is None or not any(c in mode for c in "wx"):
                    continue
                try:
                    path_src = ast.get_source_segment(
                        self.src, n.args[0]) or ""
                except Exception:  # pragma: no cover — degenerate node
                    path_src = ""
                hinted = bool(SHARED_PATH_HINT.search(path_src))
                if not (participates or hinted):
                    continue
                if self._fn_has_atomic_pattern(qual):
                    continue  # tmp-file + os.replace: the atomic idiom
                self.report(
                    "non-atomic-shared-write", n, qual,
                    f"open({path_src or '...'}, {mode!r}) truncates in "
                    "place — a concurrent reader (another rank, a "
                    "scraper, the merge thread) sees an empty or torn "
                    "file; write to a tmp path and os.replace(), or "
                    "use atomic_write_json",
                    "open-w", "definite" if hinted and participates
                    else "possible", "shared-path")

    # CL006 ------------------------------------------------------------------
    def _reach_does_file_io(self, target):
        """WRITE I/O only: a daemon thread reading a file at exit is
        harmless; a torn write is the hazard."""
        for q in self.graph.reachable([target]):
            fnode = self.graph.functions.get(q)
            if fnode is None:
                continue
            for n in CallGraph.body_nodes(fnode):
                if isinstance(n, ast.Call):
                    d = dotted(n.func)
                    if d == ("open",):
                        mode = _const_str(_kwarg(n, "mode")) or (
                            _const_str(n.args[1])
                            if len(n.args) > 1 else None)
                        if mode and any(c in mode for c in "wax+"):
                            return True
                        continue
                    if (d and d[0] == "json" and d[-1] == "dump") or (
                            d and d[0] == "os"
                            and d[-1] in ("replace", "rename")):
                        return True
                    if isinstance(n.func, ast.Attribute) and \
                            n.func.attr in ("write", "writelines"):
                        return True
        return False

    def _check_shutdown_ordering(self):
        daemon_lock_use = set()
        for e in self.entries:
            if e.daemon and e.target:
                for q in self.graph.reachable([e.target]):
                    daemon_lock_use |= self.acquires.get(q, set())
                    daemon_lock_use |= self.eff.get(q, frozenset())
        for e in self.entries:
            if e.daemon and e.target and self._reach_does_file_io(e.target):
                chain = self._enclosing_fn_quals(e.node)
                q = chain[0] if chain else "<module>"
                self.report(
                    "shutdown-ordering", e.node, q,
                    f"daemon thread `{e.target}` performs file I/O — "
                    "at interpreter exit daemon threads are killed "
                    "abruptly, tearing in-flight writes; join it on "
                    "shutdown or make every write atomic "
                    "(tmp + os.replace)",
                    f"daemon-io:{e.target}", "possible", "shutdown")
            if e.kind == "atexit" and e.target:
                hazards = []
                for q in self.graph.reachable([e.target]):
                    fnode = self.graph.functions.get(q)
                    if fnode is None:
                        continue
                    for n in CallGraph.body_nodes(fnode):
                        if isinstance(n, ast.Call) and \
                                isinstance(n.func, ast.Attribute) and \
                                n.func.attr == "join" and \
                                not _has_timeout(n):
                            hazards.append("joins a thread with no "
                                           "timeout")
                    overlap = (self.acquires.get(q, set())
                               & daemon_lock_use)
                    if overlap:
                        hazards.append(
                            "takes lock(s) "
                            f"{', '.join(sorted(overlap))} that daemon "
                            "threads also hold")
                if hazards:
                    chain = self._enclosing_fn_quals(e.node)
                    q = chain[0] if chain else "<module>"
                    self.report(
                        "shutdown-ordering", e.node, q,
                        f"atexit handler `{e.target}` "
                        f"{'; '.join(sorted(set(hazards)))} — at exit "
                        "daemon threads may be frozen mid-hold, so "
                        "this handler can deadlock shutdown",
                        f"atexit:{e.target}", "possible", "shutdown")


# ---------------------------------------------------------------------------
# tree driver

def iter_py_files(root):
    yield from _iter_py_files(root, skip_dirs=SKIP_DIRS)


def analyze_paths(roots):
    """Analyze every .py under each root. Returns (findings, errors):
    errors are (path, message) for unparseable files."""
    findings, errors = [], []
    for root in roots:
        root = os.path.normpath(root)
        root_parent = os.path.dirname(os.path.abspath(root))
        for path in iter_py_files(root):
            rel = _relpath(path, root_parent)
            try:
                ma = ModuleConcurrencyAnalysis(path, root_parent)
                findings.extend(ma.run())
            except (SyntaxError, UnicodeDecodeError) as e:
                errors.append((rel, f"{type(e).__name__}: {e}"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


def analyze_file(path):
    return analyze_paths([path])
