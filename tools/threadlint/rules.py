"""Rule catalog for threadlint.

Each rule names one class of host-side concurrency hazard in the
threaded runtime (elastic watchdogs, background merges, async
checkpoint saves, data-pipeline workers, atexit/signal handlers). The
catalog is data, not behavior — detection lives in analyzer.py — and
the Rule dataclass/severity vocabulary is shared with tracelint via
tools/staticlib.

Severity:
  error    — a proven race/deadlock shape; fix or waive with a review.
  warning  — likely hazard; depends on which paths actually run
             concurrently.
  info     — hygiene note; never gates CI.
"""
from __future__ import annotations

from ..staticlib.rules import Rule, ruleset

RULES, BY_ID, get = ruleset([
    Rule("CL001", "unguarded-shared-mutation", "error", False,
         "shared mutable state (module global / instance attribute "
         "reachable from two or more thread-entry call paths, or "
         "guarded by a lock elsewhere) mutated without holding its "
         "guarding lock"),
    Rule("CL002", "lock-order-inversion", "error", False,
         "two named locks acquired in opposite orders on different "
         "paths (or a non-reentrant lock re-acquired while held) — "
         "the classic ABBA deadlock"),
    Rule("CL003", "blocking-under-lock", "warning", False,
         "blocking call while holding a lock (time.sleep, join()/"
         "wait() without timeout, queue put/get, subprocess waits, "
         "network, file I/O) — every other thread contending on the "
         "lock stalls behind it"),
    Rule("CL004", "thread-before-fork", "warning", False,
         "a thread is started before a fork/subprocess spawn on the "
         "same code path — the child inherits locked locks and "
         "half-initialized state from threads that do not survive "
         "the fork"),
    Rule("CL005", "non-atomic-shared-write", "warning", False,
         "open(path, 'w')-style truncating write to a coordination-"
         "store/telemetry shared path — concurrent readers see torn "
         "files; route through the atomic-rename helpers "
         "(atomic_write_json / tmp + os.replace)"),
    Rule("CL006", "shutdown-ordering", "warning", False,
         "daemon-thread/atexit shutdown-ordering hazard: a daemon "
         "thread doing file I/O is killed mid-write at interpreter "
         "exit, and an atexit handler that joins threads or takes a "
         "lock a daemon thread may hold can deadlock shutdown"),
    Rule("CL007", "check-then-act", "warning", False,
         "check-then-act (TOCTOU) on shared state: a flag/attribute "
         "is tested and then mutated without a lock held across both "
         "halves — the state can change between the check and the "
         "act"),
])

__all__ = ["Rule", "RULES", "BY_ID", "get"]
