#!/usr/bin/env python
"""Diagnostics smoke (tools/ci_check.sh): the crash-and-hang layer
proven over fresh subprocesses, the way a dying bench child or a
wedged trainer would actually exercise it.

Three stages:

**Stall-triggered dump.** A child arms an ElasticManager watchdog with
a sub-second timeout and never ticks; the ``no_heartbeat`` stall must
write a postmortem bundle containing all-thread stacks, live
``dispatch_stats()`` (incl. the fusion section), and a contiguous
flight-recorder tail.

**Statusz round trip.** A child runs a real ``Model.fit`` with
``PADDLE_TPU_STATUSZ=0`` (ephemeral port, loopback); the parent
discovers the bound port from the diagnostics dir and scrapes
``/statusz`` + ``/metrics`` + ``/flightrecorder`` DURING the fit —
every response must be well-formed and must eventually show live data
(dispatch hits, step histogram counts).

**Deadline-kill acceptance.** A bench campaign-runner child
(``bench.py --campaign-config``, fake CONFIGS with a config that
wedges after real dispatch traffic) is SIGTERMed exactly the way the
orchestrator's per-config deadline kills it. The child must die with
rc = -SIGTERM *and leave a bundle* whose stacks/dispatch+fusion
stats/flight tail are all present, and the orchestrator-side
ingestion (`bench._collect_child_diagnostics`) must surface
``<name>_bundle_path`` + ``<name>_flight_tail`` into the round
payload — the ISSUE-14 acceptance criterion: a deadline-killed config
leaves evidence instead of ``rc=124``.

Usage: python tools/diagnostics_smoke.py           (run all stages)
       python tools/diagnostics_smoke.py --fit-child  (internal)
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.join(REPO, "tests")


def _fail(msg):
    print(f"diagnostics_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _read_bundle(diag_dir, reason_contains):
    names = sorted(n for n in os.listdir(diag_dir)
                   if n.startswith("postmortem-") and n.endswith(".json"))
    if not names:
        _fail(f"no bundle in {diag_dir}")
    path = os.path.join(diag_dir, names[-1])
    if os.path.getsize(path) > 1024 * 1024:
        _fail(f"bundle over the default size bound: {path}")
    with open(path) as f:
        b = json.load(f)
    if reason_contains not in b.get("reason", ""):
        _fail(f"bundle reason {b.get('reason')!r} lacks "
              f"{reason_contains!r}")
    if not b.get("stacks"):
        _fail("bundle has no all-thread stacks")
    ds = b.get("dispatch")
    if not ds or ds["forward"]["hits"] < 1:
        _fail("bundle dispatch_stats missing or has no traffic")
    if "fusion" not in ds:
        _fail("bundle dispatch_stats lacks the fusion section")
    tail = (b.get("flight_recorder") or {}).get("tail") or []
    if not tail:
        _fail("bundle has no flight-recorder tail")
    seqs = [r["seq"] for r in tail]
    if seqs != list(range(seqs[0], seqs[0] + len(seqs))):
        _fail(f"flight tail not contiguous: {seqs[:10]}...")
    return path, b


# ---------------------------------------------------------------------------
# stage 1: stall-triggered dump

def stage_stall(tmp):
    diag = os.path.join(tmp, "stall")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_DIAGNOSTICS_DIR=diag,
               PADDLE_TPU_FLIGHT_FLUSH_EVERY="1")
    proc = subprocess.run(
        [sys.executable, os.path.join(TESTS, "_diagnostics_child.py"),
         "stall"],
        env=env, cwd=REPO, capture_output=True, timeout=180)
    if proc.returncode != 0:
        _fail("stall child rc="
              f"{proc.returncode}: {proc.stderr.decode()[-800:]}")
    path, b = _read_bundle(diag, "watchdog_stall")
    if b["extra"]["reason"] != "no_heartbeat":
        _fail(f"unexpected stall reason {b['extra']}")
    print(f"  stall dump OK: {os.path.basename(path)} "
          f"({len(b['stacks'])} threads, "
          f"{len(b['flight_recorder']['tail'])} flight records)")


# ---------------------------------------------------------------------------
# stage 2: statusz round trip during a real fit

def _fit_child():
    sys.path.insert(0, REPO)
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.runtime import diagnostics

    assert diagnostics.statusz_address() is not None, \
        "PADDLE_TPU_STATUSZ must have started the server at import"
    rng = np.random.RandomState(0)
    x = rng.rand(256, 4).astype(np.float32)
    y = (x @ rng.rand(4, 1).astype(np.float32)).astype(np.float32)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    cbs = [paddle.callbacks.TelemetryCallback(export_every=4)]
    model.fit([x, y], epochs=6, batch_size=16, verbose=0, callbacks=cbs)
    # hold the server up until the parent finishes scraping
    stop = os.path.join(diagnostics.diagnostics_dir(), "stop")
    with open(os.path.join(diagnostics.diagnostics_dir(), "fit_done"),
              "w") as f:
        f.write("1")
    deadline = time.time() + 60
    while not os.path.exists(stop) and time.time() < deadline:
        time.sleep(0.1)
    return 0


def _get(addr, route, timeout=5):
    with urllib.request.urlopen(f"http://{addr}{route}",
                                timeout=timeout) as r:
        return r.read()


def stage_statusz(tmp):
    diag = os.path.join(tmp, "statusz")
    os.makedirs(diag, exist_ok=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_DIAGNOSTICS_DIR=diag,
               PADDLE_TPU_STATUSZ="0")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--fit-child"],
        env=env, cwd=REPO, stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE)
    addr = None
    try:
        deadline = time.time() + 120
        while addr is None and time.time() < deadline:
            ports = [n for n in (os.listdir(diag) if os.path.isdir(diag)
                                 else [])
                     if n.startswith("statusz-") and n.endswith(".port")]
            if ports:
                with open(os.path.join(diag, ports[0])) as f:
                    addr = f.read().strip()
                break
            if proc.poll() is not None:
                _fail("fit child died before statusz: "
                      + proc.stderr.read().decode()[-800:])
            time.sleep(0.1)
        if addr is None:
            _fail("statusz port file never appeared")
        # scrape DURING the fit until live data shows. Every response
        # that ARRIVES must be well-formed JSON (a half-updated
        # registry must never produce a torn document — json.loads
        # raising fails the smoke); connection-level noise while the
        # child is inside a first-step XLA compile is retried, bounded
        live = False
        scrapes = 0
        conn_errors = 0
        while time.time() < deadline:
            try:
                doc = json.loads(_get(addr, "/statusz"))
                json.loads(_get(addr, "/flightrecorder?n=10"))
                metrics = _get(addr, "/metrics").decode()
            except (ConnectionError, OSError):
                conn_errors += 1
                if conn_errors > 20:
                    _fail("statusz unreachable 20 times in a row")
                time.sleep(0.3)
                continue
            conn_errors = 0
            scrapes += 1
            tel = ((doc.get("summary") or {}).get("telemetry") or {})
            if tel.get("step_count", 0) >= 1 and \
                    "paddle_tpu_step_seconds" in metrics and \
                    doc["flight_recorder"]["recorded"] >= 1:
                live = True
                if os.path.exists(os.path.join(diag, "fit_done")):
                    break
            if proc.poll() is not None and \
                    os.path.exists(os.path.join(diag, "fit_done")):
                break
            time.sleep(0.2)
        if not live:
            _fail("statusz never served live fit data")
        stacks = json.loads(_get(addr, "/stacks"))
        if not stacks.get("stacks"):
            _fail("/stacks empty")
    finally:
        with open(os.path.join(diag, "stop"), "w") as f:
            f.write("1")
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
        proc.stderr.close()
    if proc.returncode != 0:
        _fail(f"fit child rc={proc.returncode}")
    print(f"  statusz OK: {scrapes} live scrapes of "
          f"/statusz+/metrics+/flightrecorder at {addr}")


# ---------------------------------------------------------------------------
# stage 3: deadline-killed campaign child leaves evidence

def stage_deadline_kill(tmp):
    out_dir = os.path.join(tmp, "bench_state")
    os.makedirs(out_dir, exist_ok=True)
    diag = os.path.join(out_dir, "diagnostics", "hang")
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FORCE_CPU="1",
               BENCH_CONFIGS_MODULE="_diag_bench_configs",
               PYTHONPATH=TESTS + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               PADDLE_TPU_DIAGNOSTICS_DIR=diag,  # as the orchestrator sets
               PADDLE_TPU_FLIGHT_FLUSH_EVERY="1")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--campaign-config", "hang", "--out-dir", out_dir,
         "--deadline-ts", str(time.time() + 600)],
        env=env, cwd=REPO, stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE)
    try:
        marker = os.path.join(out_dir, "hang.started")
        deadline = time.time() + 120
        while not os.path.exists(marker):
            if proc.poll() is not None:
                _fail("campaign child died before .started: "
                      + proc.stderr.read().decode()[-800:])
            if time.time() > deadline:
                _fail("campaign child never wrote .started")
            time.sleep(0.1)
        time.sleep(1.0)  # let the wedge loop record a few flight rows
        # the orchestrator's per-config deadline action, verbatim
        proc.terminate()
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stderr.close()
    if proc.returncode != -signal.SIGTERM:
        _fail(f"expected rc={-signal.SIGTERM}, got {proc.returncode}")
    path, b = _read_bundle(diag, "signal_SIGTERM")
    # the orchestrator-side ingestion: payload keys for the round
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    details = {}
    bench._collect_child_diagnostics(diag, "hang", details)
    if details.get("hang_bundle_path") != path:
        _fail(f"ingestion missed the bundle: {details}")
    if not details.get("hang_flight_tail"):
        _fail("ingestion missed the flight tail")
    print(f"  deadline kill OK: bundle {os.path.basename(path)} "
          f"+ {len(details['hang_flight_tail'])}-record flight tail "
          "ingested into the round payload")


def main():
    import tempfile

    with tempfile.TemporaryDirectory(prefix="diag_smoke_") as tmp:
        print("diagnostics_smoke: stage 1 — stall-triggered dump")
        stage_stall(tmp)
        print("diagnostics_smoke: stage 2 — statusz round trip")
        stage_statusz(tmp)
        print("diagnostics_smoke: stage 3 — deadline-killed campaign "
              "child")
        stage_deadline_kill(tmp)
    print("diagnostics_smoke: OK")


if __name__ == "__main__":
    if "--fit-child" in sys.argv:
        sys.exit(_fit_child())
    main()
