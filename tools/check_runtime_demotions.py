#!/usr/bin/env python
"""CI gate (tools/ci_check.sh): no runtime-learned unjittable demotions
on a representative eager workload.

The dispatch layer demotes an op to permanent eager execution when its
jit probe fails at runtime — paying one failed XLA compile first. Every
such demotion in library code is a gap in tracelint's static analysis:
the op should either be fixed, decorated ``@non_jittable``, or proven
unsafe by a rule so the static unjittable manifest preloads it for
free. This script sweeps the common eager op surface and fails if
``dispatch_stats()["unjittable"]["runtime_learned"]`` is non-zero,
naming the ops.

Usage: JAX_PLATFORMS=cpu python tools/check_runtime_demotions.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _workload():
    """Representative slice of the eager op surface: math, reductions,
    shaping, indexing, activations, norm layers, a small train loop —
    the ops a dygraph user hits, each dispatched enough times to pass
    the warm gate and compile."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn
    from paddle_tpu.core import dispatch

    dispatch.set_warmup_count(1)
    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    idx = paddle.to_tensor(np.arange(8, dtype=np.int64))

    for _ in range(2):
        paddle.add(x, y)
        paddle.multiply(x, y)
        paddle.matmul(x, y, transpose_y=True)
        paddle.sum(x, axis=1)
        paddle.mean(x)
        paddle.max(x, axis=0)
        paddle.reshape(x, [4, 32])
        paddle.transpose(x, [1, 0])
        paddle.concat([x, y], axis=0)
        paddle.stack([x, y])
        paddle.split(x, 2, axis=0)
        paddle.squeeze(paddle.unsqueeze(x, 0), 0)
        paddle.gather(x, idx)
        x[2:5]
        x[:, 3]
        F.relu(x)
        F.softmax(x, axis=-1)
        F.gelu(x)
        paddle.tanh(x)
        paddle.exp(x)
        paddle.clip(x, -1.0, 1.0)
        F.dropout(x, p=0.5)  # bypass (PRNG capture), never a demotion
        paddle.where(x > 0, x, y)
        paddle.cast(x, "bfloat16")

    # norm layers carry buffers + training-mode branches
    bn = nn.BatchNorm1D(16)
    ln = nn.LayerNorm(16)
    for _ in range(2):
        bn(x)
        ln(x)

    # eager train loop: backward pullbacks + fused optimizer step
    w = paddle.to_tensor(rng.randn(16, 4).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=[w, b])
    for _ in range(3):
        out = F.relu(paddle.matmul(x, w) + b)
        loss = (out * out).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()

    return dispatch.dispatch_stats()


def main():
    stats = _workload()
    uj = stats["unjittable"]
    learned = uj["runtime_learned"]
    print(f"unjittable: {uj['total']} total "
          f"({uj['manifest_preloaded']} manifest-preloaded, "
          f"{uj['decorated']} decorated, {learned} runtime-learned)")
    if learned:
        names = uj.get("runtime_learned_ops") or ["<name lost to reset>"]
        print(
            "check_runtime_demotions: FAIL — the dispatch layer learned "
            f"{learned} unjittable op(s) at runtime that tracelint's "
            f"static analysis missed: {', '.join(names)}.\n"
            "Each paid a failed XLA compile probe. Fix the op, decorate "
            "it @non_jittable, or extend the rule and regenerate the "
            "static manifest:\n"
            "    python -m tools.tracelint paddle_tpu --emit-manifest",
            file=sys.stderr)
        raise SystemExit(1)
    print("check_runtime_demotions: OK (no runtime-learned demotions)")


if __name__ == "__main__":
    main()
